"""Fleet-wide request tracing and metrics federation (the observability
plane's cross-process half).

Bottom-up: trace contexts + Lamport clock (pure units), the bounded flight
recorder (ring eviction, anomaly pinning, atomic chrome-trace dumps), the
merge/export path (schema-checked chrome JSON), context propagation across
the RPC frame, and the gateway surfaces — ``/v1/requests/{rid}/trace``,
the federated ``/metrics`` page, and the ``/healthz`` fleet rollup — first
against in-process replicas, then against a thread-hosted WorkerServer
fleet where one member is SIGKILL-shaped mid-scrape (RPC listener gone,
lease intact) and the scrape must skip it, not wedge."""
import json
import os
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu import observability as obs
from paddle_tpu.observability import flight
from paddle_tpu.observability.registry import REGISTRY


@pytest.fixture()
def recorder():
    """Flight recorder on, empty, default-sized; restored afterwards."""
    flight.enable()
    flight.reset()
    flight.configure(ring_size=4096)
    yield
    flight.disable()
    flight.reset()
    flight.configure(ring_size=4096)


def _assert_valid_chrome_trace(doc):
    """Minimal chrome://tracing schema check: every event names a phase the
    viewer understands, samples reference a pid announced by a preceding
    ``process_name`` metadata event, and complete events carry durations.
    Returns {pid: process label}."""
    assert isinstance(doc, dict) and isinstance(doc["traceEvents"], list)
    pids = {}
    for ev in doc["traceEvents"]:
        assert isinstance(ev["name"], str) and ev["name"]
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int)
        if ev["ph"] == "M":
            assert ev["name"] == "process_name"
            pids[ev["pid"]] = ev["args"]["name"]
            continue
        assert ev["ph"] in ("X", "i"), ev
        assert ev["pid"] in pids, "sample before its process_name metadata"
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0
        if ev["ph"] == "X":
            assert ev["dur"] >= 0
        else:
            assert ev["s"] == "t"
    return pids


# ---------------------------------------------------- contexts + Lamport clock

class TestTraceContext:
    def test_mint_adopts_supplied_id(self):
        assert flight.mint("req-abc").trace_id == "req-abc"
        a, b = flight.mint(), flight.mint()
        assert a.trace_id != b.trace_id
        assert b.clock > a.clock

    def test_use_context_scopes_ambient(self):
        ctx = flight.mint("scoped")
        assert flight.current() is None
        with flight.use_context(ctx):
            assert flight.current() is ctx
            with flight.use_context(None):      # None is a passthrough
                assert flight.current() is ctx
        assert flight.current() is None

    def test_wire_round_trip_is_causally_monotone(self, recorder):
        ctx = flight.mint("wire-rt")
        with flight.use_context(ctx):
            wire = flight.wire_context()
        assert wire[0] == "wire-rt"
        adopted = flight.adopt_wire(wire)
        assert adopted.trace_id == "wire-rt"
        assert adopted.clock > wire[1]          # receive happens-after send
        assert flight.adopt_wire(None) is None

    def test_disabled_wire_is_none(self):
        flight.disable()
        with flight.use_context(flight.mint()):
            assert flight.wire_context() is None

    def test_context_pickles(self, recorder):
        import pickle
        ctx = flight.mint("pkl")
        clone = pickle.loads(pickle.dumps(ctx))
        assert (clone.trace_id, clone.clock) == (ctx.trace_id, ctx.clock)

    def test_hostile_client_id_is_sanitized(self):
        # the gateway adopts X-Request-ID verbatim as the trace id, and
        # trace ids become dump FILENAMES: path syntax must never survive
        evil = "../../etc/cron.d/evil"
        tid = flight.mint(evil).trace_id
        assert "/" not in tid and "\\" not in tid and ".." not in tid
        # hashing is stable, so retries of the same hostile id correlate
        assert flight.mint(evil).trace_id == tid
        # distinct hostile ids stay distinct
        assert flight.mint("../../other").trace_id != tid
        # conforming ids pass through untouched; overlong ones are hashed
        assert flight.mint("req_A.1-b").trace_id == "req_A.1-b"
        assert flight.mint("x" * 200).trace_id != "x" * 200


# ----------------------------------------------------------- flight recorder

class TestFlightRecorder:
    def test_disabled_record_is_noop(self):
        flight.disable()
        flight.record("phase", trace_id="off")
        flight.enable()
        try:
            assert flight.events_for("off") == []
        finally:
            flight.disable()

    def test_untraced_record_is_noop(self, recorder):
        flight.record("phase")                  # no trace_id, no ambient ctx
        assert flight.snapshot_events() == []

    def test_ring_eviction_bounds_memory(self, recorder):
        flight.configure(ring_size=16)
        for i in range(200):
            flight.record("p", rid=i, trace_id=f"t{i}")
        events = flight.snapshot_events()
        assert len(events) == 16
        # the survivors are the NEWEST 16, in causal order
        assert [e["trace_id"] for e in events] == [
            f"t{i}" for i in range(184, 200)]
        assert flight.events_for("t0") == []    # evicted

    def test_pin_survives_eviction_and_registers_reason(self, recorder):
        flight.configure(ring_size=8)
        with flight.use_context(flight.mint("victim")):
            flight.record("queued", rid=42)
            flight.record("prefill", rid=42, dur=0.01)
        assert flight.pin_rid(42, "stuck_step")
        for i in range(100):                    # churn the whole ring
            flight.record("noise", trace_id=f"n{i}")
        phases = [e["phase"] for e in flight.events_for("victim")]
        assert phases == ["queued", "prefill", "pinned"]
        assert flight.pinned() == {"victim": "stuck_step"}
        # pinned events also ride along in the full-ring snapshot (RPC pull)
        assert any(e["trace_id"] == "victim"
                   for e in flight.snapshot_events())

    def test_pin_unknown_rid_is_false(self, recorder):
        assert not flight.pin_rid(999999, "whatever")
        assert flight.pinned() == {}

    def test_pinned_store_is_bounded(self, recorder):
        # replica churn pins every resumed request: the anomaly store must
        # evict like the ring does, not grow for the life of the process
        last = flight._PINNED_MAX + 9
        for i in range(last + 1):
            flight.record("queued", trace_id=f"anom{i}", rid=i)
            assert flight.pin(f"anom{i}", "stuck_step")
        pins = flight.pinned()
        assert len(pins) == flight._PINNED_MAX
        assert "anom0" not in pins             # oldest pins fell out
        assert f"anom{last}" in pins
        # re-pinning a resident trace updates in place — no eviction
        assert flight.pin(f"anom{last}", "again")
        assert len(flight.pinned()) == flight._PINNED_MAX
        assert flight.pinned()[f"anom{last}"] == "again"

    def test_hostile_pin_cannot_escape_dump_dir(self, recorder, tmp_path,
                                                monkeypatch):
        dumps = tmp_path / "dumps"
        monkeypatch.setenv("PADDLE_TPU_TRACE_DUMP_DIR", str(dumps))
        ctx = flight.mint("../../escape")      # hostile X-Request-ID shape
        with flight.use_context(ctx):
            flight.record("queued", rid=1)
        assert flight.pin(ctx.trace_id, "quarantine")
        # the dump landed INSIDE the configured dir, nowhere else
        assert sorted(p.name for p in dumps.iterdir()) == [
            f"trace-{ctx.trace_id}.json"]
        assert not (tmp_path / "escape").exists()
        # defense in depth: the write site refuses a raw unsanitized id
        with pytest.raises(OSError):
            flight.dump_trace("../../escape", [], out_dir=str(dumps))
        with pytest.raises(OSError):
            flight.dump_trace("a/b", [], out_dir=str(dumps))

    def test_pin_dumps_valid_chrome_trace(self, recorder, tmp_path,
                                          monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_TRACE_DUMP_DIR", str(tmp_path))
        with flight.use_context(flight.mint("anomaly1")):
            flight.record("queued", rid=7)
            flight.record("decode", rid=7, dur=0.002, block=3)
        assert flight.pin("anomaly1", "quarantine")
        path = tmp_path / "trace-anomaly1.json"
        assert path.exists()
        assert not list(tmp_path.glob("*.tmp.*")), "torn dump left behind"
        doc = json.loads(path.read_text())
        _assert_valid_chrome_trace(doc)
        assert doc["metadata"] == {"trace_id": "anomaly1",
                                   "pin_reason": "quarantine"}
        names = [e["name"] for e in doc["traceEvents"] if e["ph"] != "M"]
        assert names == ["queued", "decode", "pinned"]

    def test_chaos_artifact_dump_hook(self, recorder, tmp_path,
                                      monkeypatch):
        """The conftest post-mortem hook: a failed chaos test leaves a
        metrics snapshot and every pinned trace in the artifacts dir."""
        from tests.conftest import _dump_chaos_artifacts
        monkeypatch.setenv("PADDLE_TPU_CHAOS_ARTIFACTS", str(tmp_path))
        with flight.use_context(flight.mint("chaosart")):
            flight.record("queued", rid=1)
        flight.pin("chaosart", "stuck_step")
        _dump_chaos_artifacts("tests/test_x.py::TestY::test_z[leg-11]")
        files = sorted(p.name for p in tmp_path.iterdir())
        assert "trace-chaosart.json" in files
        metrics = [f for f in files if f.startswith("metrics-")]
        assert len(metrics) == 1 and metrics[0].endswith(".json")
        json.loads((tmp_path / metrics[0]).read_text())  # valid JSON
        doc = json.loads((tmp_path / "trace-chaosart.json").read_text())
        _assert_valid_chrome_trace(doc)
        assert doc["metadata"]["pin_reason"] == "stuck_step"

    def test_trace_for_rid(self, recorder):
        flight.record("queued", rid=5, trace_id="lookup")
        assert flight.trace_for_rid(5) == "lookup"
        assert flight.trace_for_rid(6) is None


# ------------------------------------------------------------- merge / export

class TestMergeExport:
    def test_merge_dedups_and_orders_causally(self):
        a = [{"trace_id": "t", "phase": "p1", "lamport": 1, "pid": 1,
              "proc": "gw", "ts": 10.0},
             {"trace_id": "t", "phase": "p3", "lamport": 5, "pid": 1,
              "proc": "gw", "ts": 30.0}]
        b = [{"trace_id": "t", "phase": "p2", "lamport": 3, "pid": 2,
              "proc": "w0", "ts": 1.0},       # skewed wall clock: ts lies
             dict(a[1])]                       # duplicate via pinned copy
        merged = flight.merge_events(a, b, None)
        assert [e["phase"] for e in merged] == ["p1", "p2", "p3"]
        assert len(merged) == 3                # dedup by (lamport, pid, proc)

    def test_chrome_trace_schema_and_rebase(self, recorder):
        flight.set_proc_label("procA")
        flight.record("instant", trace_id="ct", rid=3)
        flight.record("span", trace_id="ct", rid=3, dur=0.5)
        doc = flight.chrome_trace(flight.events_for("ct"))
        pids = _assert_valid_chrome_trace(doc)
        assert list(pids.values()) == ["procA"]
        span = next(e for e in doc["traceEvents"] if e["name"] == "span")
        inst = next(e for e in doc["traceEvents"] if e["name"] == "instant")
        assert span["ph"] == "X" and span["dur"] == pytest.approx(5e5)
        # complete events draw from their start: recorded ts is the END of
        # the measured work, so the renderer rebases by dur
        assert span["ts"] < inst["ts"]
        assert inst["ph"] == "i"
        assert inst["tid"] == 3                # rid becomes the chrome tid

    def test_merged_multiproc_trace_round_trips_json(self, recorder):
        def in_thread(label, phase):
            def run():
                flight.set_proc_label(label)
                with flight.use_context(flight.mint("multi")):
                    flight.record(phase, rid=1)
            t = threading.Thread(target=run)
            t.start()
            t.join()
        in_thread("gateway", "queued")
        in_thread("worker:w0", "prefill")
        doc = flight.chrome_trace(flight.events_for("multi"))
        doc = json.loads(json.dumps(doc))      # must be pure-JSON types
        pids = _assert_valid_chrome_trace(doc)
        assert sorted(pids.values()) == ["gateway", "worker:w0"]


# ------------------------------------------------- RPC context propagation

class TestRpcPropagation:
    def test_ctx_crosses_the_frame_and_clock_folds_back(self, recorder):
        from paddle_tpu.inference.frontend.rpc import RpcClient, RpcServer

        def handler(op, kw):
            flight.set_proc_label("srv")
            flight.record("remote_work", rid=kw["rid"])
            return "ok"

        srv = RpcServer(handler)
        srv.start()
        try:
            c = RpcClient(srv.host, srv.port)
            with flight.use_context(flight.mint("rpc-trace")):
                flight.set_proc_label("cli")
                flight.record("send", rid=9)
                assert c.call("work", rid=9,
                              ctx=flight.wire_context()) == "ok"
                flight.record("after", rid=9)
            c.close()
        finally:
            srv.close()
        events = flight.events_for("rpc-trace")
        assert [e["phase"] for e in events] == ["send", "remote_work",
                                                "after"]
        lamports = [e["lamport"] for e in events]
        assert lamports == sorted(lamports)    # causal chain is monotone
        assert events[1]["proc"] == "srv"      # recorded server-side
        # the reply folded the server's clock back into the client's, so
        # "after" happens-after the remote work despite no shared wall clock
        assert lamports[2] > lamports[1]

    def test_ctx_none_leaves_remote_untraced(self, recorder):
        from paddle_tpu.inference.frontend.rpc import RpcClient, RpcServer
        seen = []
        srv = RpcServer(lambda op, kw: seen.append(flight.current()))
        srv.start()
        try:
            c = RpcClient(srv.host, srv.port)
            c.call("work", ctx=None)
            c.close()
        finally:
            srv.close()
        assert seen == [None]


# ------------------------------------- gateway surfaces (in-process replicas)

def _tiny_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _engine(model, **kw):
    from paddle_tpu.inference.serving import LLMEngine
    kw.setdefault("max_batch", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    return LLMEngine(model, **kw)


def _post(url, body, headers=None):
    req = urllib.request.Request(
        f"{url}/v1/completions", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=60) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


def _get(url, path):
    with urllib.request.urlopen(f"{url}{path}", timeout=30) as resp:
        return resp.status, json.loads(resp.read())


class TestGatewayObservability:
    @pytest.fixture()
    def served(self, model, recorder):
        from paddle_tpu.inference.frontend import ReplicaSet, start_gateway
        obs.enable()
        rs = ReplicaSet([_engine(model) for _ in range(2)])
        gw = start_gateway(rs)
        yield gw, rs
        gw.close()
        rs.close()
        obs.disable()
        obs.reset()

    def test_client_request_id_becomes_the_trace(self, served):
        gw, _ = served
        status, headers, body = _post(
            gw.url, {"prompt": [1, 2, 3, 4, 5], "max_tokens": 4},
            headers={"X-Request-ID": "clienttrace01"})
        assert status == 200
        assert headers["X-Request-ID"] == "clienttrace01"
        assert body["request_id"] == "clienttrace01"
        assert len(body["tokens"]) == 4

        code, doc = _get(gw.url, "/v1/requests/clienttrace01/trace")
        assert code == 200
        pids = _assert_valid_chrome_trace(doc)
        # ISSUE acceptance: one merged trace spanning >= 2 recorder
        # processes, every event under the one trace id, causally ordered
        assert "gateway" in pids.values()
        assert any(p.startswith("replica:") for p in pids.values())
        samples = [e for e in doc["traceEvents"] if e["ph"] != "M"]
        assert all(e["args"]["trace_id"] == "clienttrace01"
                   for e in samples)
        lamports = [e["args"]["lamport"] for e in samples]
        assert lamports == sorted(lamports)
        phases = [e["name"] for e in samples]
        for must in ("gateway_accept", "queued", "routed", "prefill",
                     "first_token", "terminal", "gateway_done"):
            assert must in phases, (must, phases)
        assert phases.index("queued") < phases.index("first_token")
        assert phases.index("first_token") < phases.index("terminal")

    def test_minted_request_id_echoes_back(self, served):
        gw, _ = served
        _, headers, body = _post(
            gw.url, {"prompt": [2, 3, 4], "max_tokens": 2})
        rid = body["request_id"]
        assert headers["X-Request-ID"] == rid and len(rid) == 16
        code, doc = _get(gw.url, f"/v1/requests/{rid}/trace")
        assert code == 200 and doc["traceEvents"]

    def test_keepalive_never_echoes_a_stale_request_id(self, served):
        """handler instances persist across requests on one HTTP/1.1
        socket: a follow-up GET, or a POST that 400s before minting, must
        not inherit the previous POST's X-Request-ID."""
        import http.client
        gw, _ = served
        conn = http.client.HTTPConnection(gw.addr, gw.port, timeout=60)
        try:
            conn.request("POST", "/v1/completions",
                         body=json.dumps({"prompt": [1, 2, 3],
                                          "max_tokens": 2}).encode(),
                         headers={"Content-Type": "application/json",
                                  "X-Request-ID": "staleid01"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("X-Request-ID") == "staleid01"
            resp.read()
            # same socket: the health probe owns no request id
            conn.request("GET", "/healthz")
            resp = conn.getresponse()
            assert resp.status == 200
            assert resp.getheader("X-Request-ID") is None
            resp.read()
            # same socket: a 400 before mint carries no id either
            conn.request("POST", "/v1/completions", body=b"{}",
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 400
            assert resp.getheader("X-Request-ID") is None
            resp.read()
        finally:
            conn.close()

    def test_unknown_trace_is_404(self, served):
        gw, _ = served
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(gw.url, "/v1/requests/nosuchtrace/trace")
        assert ei.value.code == 404

    def test_healthz_carries_fleet_rollup(self, served):
        gw, rs = served
        code, health = _get(gw.url, "/healthz")
        assert code == 200
        fleet = health["fleet"]
        assert fleet["replicas"] == 2 and fleet["alive"] == 2
        assert fleet["draining"] == 0
        assert fleet["free_pages"] > 0         # summed across members
        assert fleet["active_slots"] == 0

    def test_metrics_page_is_valid_exposition(self, served):
        gw, _ = served
        _post(gw.url, {"prompt": [1, 2, 3], "max_tokens": 2})
        with urllib.request.urlopen(f"{gw.url}/metrics", timeout=30) as r:
            assert r.status == 200
            text = r.read().decode()
        from tests.test_observability import _assert_valid_exposition
        typed, _ = _assert_valid_exposition(text)
        assert "frontend_requests_total" in typed


# ------------------------- remote-worker federation + mid-scrape member death

class TestFleetFederation:
    @pytest.fixture()
    def fleet(self, model, recorder, monkeypatch):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.inference.frontend.fleet import FleetReplicaSet
        from paddle_tpu.inference.frontend.worker import WorkerServer
        monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
        obs.enable()
        master = TCPStore(is_master=True, timeout=20)
        workers = {}
        for name in ("w0", "w1"):
            w = WorkerServer(name, _engine(model),
                             TCPStore(port=master.port, timeout=20),
                             group="obsfed", ttl=60.0)
            w.start(heartbeat=False)
            workers[name] = w
        fs = FleetReplicaSet(TCPStore(port=master.port, timeout=20),
                             group="obsfed", ttl=60.0)
        fs.sync()
        yield fs, workers
        fs.close()
        for w in workers.values():
            w.close(drain=False)
        obs.disable()
        obs.reset()

    def _errors(self):
        snap = obs.snapshot(prefix="frontend_federation_errors_total")
        fam = snap.get("frontend_federation_errors_total", {"series": []})
        return {s["labels"]["replica"]: s["value"] for s in fam["series"]}

    def _skipped(self):
        snap = obs.snapshot(prefix="frontend_federation_skipped")
        fam = snap.get("frontend_federation_skipped", {"series": []})
        return sum(s["value"] for s in fam["series"])

    def test_metrics_federate_and_survive_member_death(self, fleet):
        from paddle_tpu.inference.frontend import start_gateway
        from tests.test_observability import _assert_valid_exposition
        fs, workers = fleet
        assert {r.name for r in fs.alive_replicas()} == {"w0", "w1"}
        gw = start_gateway(fs)
        try:
            with urllib.request.urlopen(f"{gw.url}/metrics",
                                        timeout=30) as r:
                assert r.status == 200
                text = r.read().decode()
            _assert_valid_exposition(text)
            # both members answered the scrape: their series carry their name
            assert 'replica="w0"' in text and 'replica="w1"' in text
            assert self._errors() == {}

            # SIGKILL shape: w1's RPC listener and step loop vanish, its
            # lease does not — the next scrape must skip it, not wedge
            w = workers.pop("w1")
            w.rpc.close()
            w.replica.close()
            with urllib.request.urlopen(f"{gw.url}/metrics",
                                        timeout=30) as r:
                assert r.status == 200
                text = r.read().decode()
            _assert_valid_exposition(text)
            assert 'replica="w0"' in text
            assert self._errors().get("w1", 0) >= 1
            assert ('frontend_federation_errors_total{replica="w1"}'
                    in text)
            # the failure marked w1 dead: further scrapes SKIP it without
            # re-counting (the counter's rate must mean "new failures",
            # not "a dead member still lingers in the set") — the skip
            # shows up in the gauge instead
            after_death = self._errors()["w1"]
            for _ in range(2):
                with urllib.request.urlopen(f"{gw.url}/metrics",
                                            timeout=30) as r:
                    text = r.read().decode()
            assert self._errors()["w1"] == after_death
            assert self._skipped() == 1
            assert "frontend_federation_skipped 1" in text
        finally:
            gw.close()

    def test_trace_pull_merges_worker_events(self, fleet):
        fs, workers = fleet
        with flight.use_context(flight.mint("fedtrace01")):
            h = fs.submit(list(range(1, 13)), max_new_tokens=3,
                          do_sample=False)
        toks = list(fs.stream(h))
        assert len(toks) == 3
        events = fs.trace_events_fleet("fedtrace01")
        phases = [e["phase"] for e in events]
        for must in ("routed", "queued", "prefill", "terminal"):
            assert must in phases, (must, phases)
        lamports = [e["lamport"] for e in events]
        assert lamports == sorted(lamports)
        # the engine-side spans were recorded under the worker's label
        worker_procs = {e["proc"] for e in events
                        if e["phase"] in ("queued", "prefill", "terminal")}
        assert worker_procs <= {"worker:w0", "worker:w1",
                                "replica:w0", "replica:w1"}
