"""vision model zoo additions + vision.ops (nms/roi_align/roi_pool)."""
import numpy as np
import pytest

import paddle_tpu as paddle

rng = np.random.RandomState(0)


class TestModelZoo:
    def test_vgg16_params_and_forward(self):
        from paddle_tpu.vision.models import vgg16
        m = vgg16(num_classes=10)
        m.eval()
        n = sum(p.size for p in m.parameters())
        assert n == 134_301_514  # canonical vgg16 @ 10 classes
        x = paddle.to_tensor(rng.rand(1, 3, 64, 64).astype(np.float32))
        assert m(x).shape == [1, 10]

    def test_mobilenet_v2_params_and_train_step(self):
        from paddle_tpu.vision.models import mobilenet_v2
        m = mobilenet_v2(num_classes=10)
        n = sum(p.size for p in m.parameters())
        assert n == 2_236_682  # canonical mobilenet_v2 @ 10 classes
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=m.parameters())
        x = paddle.to_tensor(rng.rand(2, 3, 32, 32).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 10, (2,)))
        loss = paddle.nn.CrossEntropyLoss()(m(x), y)
        loss.backward()
        opt.step()
        assert np.isfinite(float(loss))

    def test_alexnet_forward(self):
        from paddle_tpu.vision.models import alexnet
        m = alexnet(num_classes=10)
        m.eval()
        assert sum(p.size for p in m.parameters()) == 57_044_810
        x = paddle.to_tensor(rng.rand(1, 3, 64, 64).astype(np.float32))
        assert m(x).shape == [1, 10]


class TestModelZoo3:
    """extra2 families — exact canonical (torch) parameter counts @ 1000
    classes, plus forward shape on the fast ones."""

    def test_small_families_counts_and_forward(self):
        from paddle_tpu.vision.models import (squeezenet1_1,
                                              shufflenet_v2_x1_0,
                                              mobilenet_v3_small)
        x = paddle.to_tensor(rng.rand(1, 3, 64, 64).astype(np.float32))
        for fn, count in [(squeezenet1_1, 1_235_496),
                          (shufflenet_v2_x1_0, 2_278_604),
                          (mobilenet_v3_small, 2_542_856)]:
            m = fn()
            m.eval()
            assert sum(p.size for p in m.parameters()) == count, fn.__name__
            assert m(x).shape == [1, 1000], fn.__name__

    def test_mobilenet_v1_count_and_forward(self):
        from paddle_tpu.vision.models import mobilenet_v1
        m = mobilenet_v1()
        m.eval()
        assert sum(p.size for p in m.parameters()) == 4_231_976
        x = paddle.to_tensor(rng.rand(1, 3, 64, 64).astype(np.float32))
        assert m(x).shape == [1, 1000]

    def test_densenet121_count_and_forward(self):
        from paddle_tpu.vision.models import densenet121
        m = densenet121()
        m.eval()
        assert sum(p.size for p in m.parameters()) == 7_978_856
        x = paddle.to_tensor(rng.rand(1, 3, 64, 64).astype(np.float32))
        assert m(x).shape == [1, 1000]

    def test_resnet_variants_counts_and_forward(self):
        from paddle_tpu.vision.models import resnext50_32x4d, wide_resnet50_2
        m = resnext50_32x4d()
        m.eval()
        assert sum(p.size for p in m.parameters()) == 25_028_904
        x = paddle.to_tensor(rng.rand(1, 3, 64, 64).astype(np.float32))
        assert m(x).shape == [1, 1000]
        w = wide_resnet50_2()
        assert sum(p.size for p in w.parameters()) == 68_883_240

    def test_googlenet_aux_heads_and_inception_count(self):
        from paddle_tpu.vision.models import googlenet, inception_v3
        g = googlenet(num_classes=10)
        assert sum(p.size for p in g.parameters()) == 13_004_888 - \
            (1000 - 10) * (1024 + 1024 + 1024 + 3)  # three heads @ 10 classes
        g.train()
        x = paddle.to_tensor(rng.rand(1, 3, 64, 64).astype(np.float32))
        out, a1, a2 = g(x)
        assert out.shape == [1, 10] and a1.shape == [1, 10] and a2.shape == [1, 10]
        g.eval()
        assert g(x).shape == [1, 10]
        i = inception_v3()
        assert sum(p.size for p in i.parameters()) == 23_834_568


class TestVisionOps:
    def test_nms_matches_greedy_reference(self):
        from paddle_tpu.vision.ops import nms
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30],
                          [21, 21, 29, 29], [50, 50, 60, 60]], np.float32)
        scores = np.array([0.9, 0.8, 0.7, 0.95, 0.5], np.float32)
        kept = nms(paddle.to_tensor(boxes), iou_threshold=0.5,
                   scores=paddle.to_tensor(scores)).numpy()
        # greedy: 3 (0.95) suppresses 2; 0 (0.9) suppresses 1; 4 stays
        assert kept.tolist() == [3, 0, 4]

    def test_nms_category_aware(self):
        from paddle_tpu.vision.ops import nms
        boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11]], np.float32)
        scores = np.array([0.9, 0.8], np.float32)
        cats = np.array([0, 1])
        kept = nms(paddle.to_tensor(boxes), iou_threshold=0.3,
                   scores=paddle.to_tensor(scores),
                   category_idxs=paddle.to_tensor(cats),
                   categories=[0, 1]).numpy()
        assert sorted(kept.tolist()) == [0, 1]  # different cats never suppress

    def test_box_iou(self):
        from paddle_tpu.vision.ops import box_iou
        a = np.array([[0, 0, 10, 10]], np.float32)
        b = np.array([[0, 0, 10, 10], [5, 5, 15, 15], [20, 20, 30, 30]],
                     np.float32)
        iou = box_iou(paddle.to_tensor(a), paddle.to_tensor(b)).numpy()
        np.testing.assert_allclose(iou[0], [1.0, 25 / 175, 0.0], atol=1e-5)

    def test_roi_align_constant_field(self):
        from paddle_tpu.vision.ops import roi_align
        # constant feature map -> every pooled value equals the constant
        x = paddle.to_tensor(np.full((1, 2, 16, 16), 3.0, np.float32))
        boxes = paddle.to_tensor(np.array([[2, 2, 10, 10]], np.float32))
        out = roi_align(x, boxes, paddle.to_tensor(np.array([1])), 4,
                        spatial_scale=1.0)
        assert out.shape == [1, 2, 4, 4]
        np.testing.assert_allclose(out.numpy(), 3.0, rtol=1e-6)

    def test_roi_align_gradient_flows(self):
        from paddle_tpu.vision.ops import roi_align
        x = paddle.to_tensor(rng.rand(1, 2, 8, 8).astype(np.float32),
                             stop_gradient=False)
        boxes = paddle.to_tensor(np.array([[1, 1, 6, 6]], np.float32))
        roi_align(x, boxes, paddle.to_tensor(np.array([1])),
                  2).sum().backward()
        assert x.grad is not None and float(np.abs(x.grad.numpy()).sum()) > 0

    def test_roi_pool_takes_max(self):
        from paddle_tpu.vision.ops import roi_pool
        feat = np.zeros((1, 1, 8, 8), np.float32)
        feat[0, 0, 3, 3] = 7.0
        out = roi_pool(paddle.to_tensor(feat),
                       paddle.to_tensor(np.array([[0, 0, 7, 7]], np.float32)),
                       paddle.to_tensor(np.array([1])), 1)
        assert float(out.numpy().max()) == 7.0

    def test_multi_image_roi_assignment(self):
        from paddle_tpu.vision.ops import roi_align
        x = np.zeros((2, 1, 8, 8), np.float32)
        x[0] = 1.0
        x[1] = 5.0
        boxes = np.array([[0, 0, 7, 7], [0, 0, 7, 7]], np.float32)
        out = roi_align(paddle.to_tensor(x), paddle.to_tensor(boxes),
                        paddle.to_tensor(np.array([1, 1])), 2)
        np.testing.assert_allclose(out.numpy()[0], 1.0, rtol=1e-5)
        np.testing.assert_allclose(out.numpy()[1], 5.0, rtol=1e-5)
