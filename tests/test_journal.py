"""Durable front door (ISSUE 15): write-ahead request journal, idempotent
submission, client-resumable SSE, and gateway crash recovery.

Layers under test, bottom-up:

- journal units: record CRC round trip, segment rotation, compaction
  retention, torn-tail truncation recovery (garbage at the active
  segment's tail is skipped, earlier records survive), reopen-never-
  appends-to-old-segments discipline, and the ``journal.append`` /
  ``journal.fsync`` fault points;
- durable plane: ACCEPTED journals (fsync path) before submit returns —
  a failed append is a failed submit with nothing running on the fleet;
  a fully-detached pre-terminal stream is cancelled only after the grace
  TTL;
- durable gateway: replayed ``Idempotency-Key`` submits serve the
  journaled stream without re-running (engine admission count unchanged),
  ``Last-Event-ID`` reconnects splice journal replay onto the live stream
  byte-identically (offsets × seeds × prefix-cache on/off), healthz
  carries journal depth + recovery state, and submits during recovery
  shed 503 + Retry-After;
- crash chaos: "kill -9" the gateway mid-stream (HTTP serving and pumps
  stopped dead, no terminal journaled, journal left as the crash left
  it), restart a fresh gateway + fresh engines on the same journal dir,
  reconnect with ``Last-Event-ID`` — the client's concatenated stream is
  byte-identical to an uninterrupted run, zero duplicate and zero missing
  events, greedy and fixed-seed, prefix cache on and off.  The
  real-process variant (actual subprocess, actual SIGKILL) is slow-marked
  and excluded from tier-1.
"""
import http.client
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

import pytest

import paddle_tpu.observability as obs
from paddle_tpu.inference.engine.request import RequestStatus
from paddle_tpu.inference.frontend import (DurableRequestPlane,
                                           RequestJournal, ReplicaSet,
                                           http_completion, start_gateway)
from paddle_tpu.testing import FAULTS, Always, FailNth


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _tiny_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _engine(model, **kw):
    from paddle_tpu.inference.serving import LLMEngine
    kw.setdefault("max_batch", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefix_cache", True)
    return LLMEngine(model, **kw)


def _run(model, prompt, max_new, seed=None, cache=True):
    """Reference: one fresh engine, one request, all tokens out."""
    eng = _engine(model, prefix_cache=cache)
    kw = {"max_new_tokens": max_new, "do_sample": seed is not None}
    if seed is not None:
        kw["seed"] = seed
    rid = eng.add_request(list(prompt), **kw)
    eng.run_until_done()
    return list(eng.result(rid))


PROMPT = list(range(1, 17))                  # 16 tokens = 2 full pages


def _durable_gateway(model, tmp_path, n=2, cache=True, **gw_kw):
    rs = ReplicaSet([_engine(model, prefix_cache=cache) for _ in range(n)],
                    requeue=True)
    gw_kw.setdefault("journal_fsync", "never")    # tests: page cache is fine
    gw = start_gateway(rs, journal_dir=str(tmp_path / "journal"), **gw_kw)
    _wait_recovered(gw)
    return rs, gw


def _wait_recovered(gw, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        h = json.loads(urllib.request.urlopen(gw.url + "/healthz",
                                              timeout=10).read())
        if not h["journal"]["recovering"]:
            return h
        time.sleep(0.05)
    raise TimeoutError("gateway never finished recovery")


def _admissions(rs):
    """Total requests the fleet's engines ever saw — terminal, active, or
    queued — the number the idempotency acceptance criterion pins."""
    total = 0
    for r in rs.replicas:
        h = r.health()
        total += h["finished"] + h["active_slots"] + h["waiting"]
    return total


def _sse_read(resp, want=None):
    """Consume an SSE response; returns ``(tokens, last_id, status)``.
    ``want`` stops reading after that many tokens (mid-stream disconnect
    is the caller closing the connection afterwards)."""
    tokens, last_id, status = [], None, None
    for raw in resp:
        line = raw.decode("utf-8").strip()
        if line.startswith("id: "):
            last_id = int(line[len("id: "):])
        elif line.startswith("data: "):
            payload = line[len("data: "):]
            if payload == "[DONE]":
                break
            evt = json.loads(payload)
            if "token" in evt:
                tokens.append(evt["token"])
                if want is not None and len(tokens) >= want:
                    break
            else:
                status = evt.get("status")
    return tokens, last_id, status


def _stream_request(gw, prompt, max_tokens, key, last_id=None, seed=None):
    """Open one streaming completion over raw http.client (so the caller
    can stop mid-stream); returns ``(conn, resp)``."""
    conn = http.client.HTTPConnection(gw.addr, gw.port, timeout=60)
    body = {"prompt": list(prompt), "max_tokens": int(max_tokens),
            "stream": True}
    if seed is not None:
        body.update(do_sample=True, seed=seed)
    headers = {"Content-Type": "application/json", "Idempotency-Key": key}
    if last_id is not None:
        headers["Last-Event-ID"] = str(last_id)
    conn.request("POST", "/v1/completions", body=json.dumps(body),
                 headers=headers)
    return conn, conn.getresponse()


def _kill_gateway(gw):
    """kill -9 facsimile: HTTP serving and journal pumps stop dead, no
    terminal records land, the journal directory is left exactly as the
    crash left it (the OS would close the fd; buffered lines were already
    flushed per append, same as a real kill)."""
    gw._httpd.shutdown()
    gw._httpd.server_close()
    gw.plane._closed = True


# ------------------------------------------------------------ journal units

class TestJournalUnits:
    def test_record_roundtrip(self, tmp_path):
        with RequestJournal(tmp_path, fsync="never") as j:
            j.append_accepted("k", [1, 2, 3], {"max_new_tokens": 4,
                                               "seed": 7})
            j.append_tokens("k", 0, [10, 11])
            j.append_tokens("k", 2, [12])
            j.append_terminal("k", RequestStatus.FINISHED)
            state, counts = j.replay()
        req = state["k"]
        assert req.prompt == [1, 2, 3]
        assert req.kw == {"max_new_tokens": 4, "seed": 7}
        assert req.tokens == [10, 11, 12]
        assert req.status is RequestStatus.FINISHED
        assert counts == {"accepted": 1, "tokens": 2, "terminal": 1,
                          "result": 0, "torn": 0}

    def test_duplicate_token_records_replay_once(self, tmp_path):
        # compaction racing a crash can leave the same batch twice; the
        # seq field makes the second application a no-op
        with RequestJournal(tmp_path, fsync="never") as j:
            j.append_accepted("k", [1], {})
            j.append_tokens("k", 0, [10, 11])
            j.append_tokens("k", 0, [10, 11])
            j.append_tokens("k", 1, [11, 12])
            state, _ = j.replay()
        assert state["k"].tokens == [10, 11, 12]

    def test_rotation_bounds_segments_and_replay_spans_them(self, tmp_path):
        with RequestJournal(tmp_path, segment_bytes=128,
                            fsync="never") as j:
            for i in range(10):
                j.append_accepted(f"k{i}", [i], {})
            stats = j.stats()
            state, _ = j.replay()
        assert stats["segments"] > 1
        assert len(state) == 10

    def test_torn_tail_is_skipped_not_fatal(self, tmp_path):
        j = RequestJournal(tmp_path, fsync="never")
        j.append_accepted("k1", [1], {})
        j.append_tokens("k1", 0, [10])
        j.close()
        segs = sorted(p for p in os.listdir(tmp_path)
                      if p.endswith(".jsonl"))
        with open(tmp_path / segs[-1], "ab") as fh:
            fh.write(b'{"c":123,"k":"T","key":"k1","s":1,"t"')  # torn write
        with RequestJournal(tmp_path, fsync="never") as j2:
            state, counts = j2.replay()
        assert counts["torn"] == 1
        assert state["k1"].tokens == [10]        # pre-tear records survive

    def test_corrupt_record_ends_its_segment_only(self, tmp_path):
        j = RequestJournal(tmp_path, fsync="never")
        j.append_accepted("old", [1], {})
        j._rotate()                              # seal segment 0
        j.append_accepted("newer", [2], {})
        j.close()
        segs = sorted(p for p in os.listdir(tmp_path)
                      if p.endswith(".jsonl"))
        # corrupt segment 0 entirely; segment 1 must still replay
        with open(tmp_path / segs[0], "wb") as fh:
            fh.write(b"\x00garbage\n")
        with RequestJournal(tmp_path, fsync="never") as j2:
            state, counts = j2.replay()
        assert "newer" in state and "old" not in state
        assert counts["torn"] == 1

    def test_reopen_never_appends_to_preexisting_segment(self, tmp_path):
        j = RequestJournal(tmp_path, fsync="never")
        j.append_accepted("k", [1], {})
        j.close()
        j2 = RequestJournal(tmp_path, fsync="never")
        j2.append_accepted("k2", [2], {})
        j2.close()
        segs = [p for p in os.listdir(tmp_path) if p.endswith(".jsonl")]
        assert len(segs) >= 2                    # fresh segment per open

    def test_compaction_folds_terminals_and_bounds_retention(self, tmp_path):
        with RequestJournal(tmp_path, fsync="never",
                            keep_terminal=2) as j:
            for i in range(5):
                j.append_accepted(f"k{i}", [i], {"max_new_tokens": 2})
                j.append_tokens(f"k{i}", 0, [i, i + 1])
                j.append_terminal(f"k{i}", RequestStatus.FINISHED)
            j.append_accepted("live", [9], {"max_new_tokens": 8})
            j.append_tokens("live", 0, [90])
            dropped = j.compact()
            state, counts = j.replay()
        assert dropped == 3
        # newest keep_terminal=2 terminals survive as RESULT records
        assert set(state) == {"k3", "k4", "live"}
        assert counts["result"] == 2
        assert state["k4"].tokens == [4, 5]
        assert state["k4"].status is RequestStatus.FINISHED
        # the non-terminal request keeps everything recovery needs
        assert state["live"].prompt == [9]
        assert state["live"].kw == {"max_new_tokens": 8}
        assert state["live"].tokens == [90]
        assert state["live"].status is None

    def test_append_fault_point(self, tmp_path):
        from paddle_tpu.testing.faults import InjectedFault
        with RequestJournal(tmp_path, fsync="never") as j:
            FAULTS.install("journal.append", FailNth(2))
            j.append_accepted("k", [1], {})
            with pytest.raises(InjectedFault):
                j.append_tokens("k", 0, [10])
            FAULTS.reset()
            # the failed record never landed; the journal still appends
            j.append_tokens("k", 0, [10])
            state, _ = j.replay()
        assert state["k"].tokens == [10]

    def test_fsync_fault_fails_critical_appends_only(self, tmp_path):
        from paddle_tpu.testing.faults import InjectedFault
        with RequestJournal(tmp_path, fsync="critical") as j:
            FAULTS.install("journal.fsync", Always())
            j.append_tokens("k", 0, [1])         # non-critical: flush only
            with pytest.raises(InjectedFault):
                j.append_accepted("k2", [1], {})  # critical: fsync path


# ----------------------------------------------------------- durable plane

class TestDurablePlane:
    def test_accepted_journals_before_ack(self, model, tmp_path):
        rs = ReplicaSet([_engine(model)], requeue=True)
        try:
            plane = DurableRequestPlane(rs, str(tmp_path / "j"),
                                        fsync="never")
            req, created = plane.submit("key1", PROMPT,
                                        {"max_new_tokens": 2,
                                         "do_sample": False})
            assert created
            state, _ = plane.journal.replay()
            assert state["key1"].prompt == PROMPT   # durable at ack time
            req.wait_terminal(timeout=60)
            plane.close()
        finally:
            rs.close()

    def test_failed_append_fails_submit_and_runs_nothing(self, model,
                                                         tmp_path):
        from paddle_tpu.testing.faults import InjectedFault
        rs = ReplicaSet([_engine(model)], requeue=True)
        try:
            plane = DurableRequestPlane(rs, str(tmp_path / "j"),
                                        fsync="never")
            # pace the engine so the cancel races nothing
            FAULTS.install("serving.slow_step", Always(), delay=0.05)
            FAULTS.install("journal.append", Always())
            with pytest.raises(InjectedFault):
                plane.submit("key1", PROMPT, {"max_new_tokens": 40})
            FAULTS.reset()
            assert plane.get("key1") is None
            state, _ = plane.journal.replay()
            assert "key1" not in state
            # the already-routed request was cancelled, not left decoding
            deadline = time.monotonic() + 15
            while (time.monotonic() < deadline
                   and rs.replicas[0].health()["cancels"] == 0):
                time.sleep(0.05)
            assert rs.replicas[0].health()["cancels"] == 1
            plane.close()
        finally:
            FAULTS.reset()
            rs.close()

    def test_detach_ttl_cancels_orphaned_request(self, model, tmp_path):
        rs = ReplicaSet([_engine(model)], requeue=True)
        try:
            plane = DurableRequestPlane(rs, str(tmp_path / "j"),
                                        fsync="never", detach_ttl=0.2)
            FAULTS.install("serving.slow_step", Always(), delay=0.05)
            req, _ = plane.submit("orphan", PROMPT,
                                  {"max_new_tokens": 40})
            # nobody ever attaches: the grace TTL must reap it
            _, status = req.wait_terminal(timeout=30)
            assert status is RequestStatus.CANCELLED
            state, _ = plane.journal.replay()
            assert state["orphan"].status is RequestStatus.CANCELLED
            plane.close()
        finally:
            FAULTS.reset()
            rs.close()

    def test_attached_stream_is_not_reaped(self, model, tmp_path):
        rs = ReplicaSet([_engine(model)], requeue=True)
        try:
            plane = DurableRequestPlane(rs, str(tmp_path / "j"),
                                        fsync="never", detach_ttl=0.1)
            FAULTS.install("serving.slow_step", Always(), delay=0.03)
            req, _ = plane.submit("held", PROMPT, {"max_new_tokens": 8,
                                                   "do_sample": False})
            plane.attach(req)
            try:
                got = [t for _s, t in req.events()]
            finally:
                plane.detach(req)
            assert req.status is not RequestStatus.CANCELLED
            assert len(got) == 8
            plane.close()
        finally:
            FAULTS.reset()
            rs.close()


# ------------------------------------------- idempotent submission (HTTP)

class TestIdempotency:
    def test_replayed_key_serves_journal_without_rerun(self, model,
                                                       tmp_path):
        ref = _run(model, PROMPT, 6)
        rs, gw = _durable_gateway(model, tmp_path)
        try:
            first = http_completion(gw.url, PROMPT, max_tokens=6,
                                    stream=True,
                                    headers={"Idempotency-Key": "idem"})
            assert first["tokens"] == ref
            admitted = _admissions(rs)
            # stream and non-stream replays: same tokens, no new admission
            again = http_completion(gw.url, PROMPT, max_tokens=6,
                                    stream=True,
                                    headers={"Idempotency-Key": "idem"})
            blocking = http_completion(gw.url, PROMPT, max_tokens=6,
                                       headers={"Idempotency-Key": "idem"})
            assert again["tokens"] == ref
            assert blocking["tokens"] == ref
            assert blocking["idempotency_key"] == "idem"
            assert _admissions(rs) == admitted
        finally:
            gw.close()
            rs.close()

    def test_generated_key_is_echoed_for_streams(self, model, tmp_path):
        rs, gw = _durable_gateway(model, tmp_path)
        try:
            conn, resp = _stream_request(gw, PROMPT, 2, key="echoed")
            assert resp.getheader("Idempotency-Key") == "echoed"
            _sse_read(resp)
            conn.close()
        finally:
            gw.close()
            rs.close()

    def test_sse_events_carry_monotonic_ids(self, model, tmp_path):
        rs, gw = _durable_gateway(model, tmp_path)
        try:
            out = http_completion(gw.url, PROMPT, max_tokens=5, stream=True,
                                  headers={"Idempotency-Key": "ids"})
            assert out["last_id"] == 4          # ids 0..4, one per token
            assert out["tokens"] == _run(model, PROMPT, 5)
        finally:
            gw.close()
            rs.close()


# -------------------------------------------- Last-Event-ID splice parity

class TestReattachSplice:
    """A client that disconnects mid-stream and reconnects with
    Last-Event-ID gets journal replay spliced onto the live stream —
    the concatenation is byte-identical to the uninterrupted run, at
    every offset, greedy and fixed-seed, prefix cache on and off."""

    @pytest.mark.parametrize("cache", [True, False],
                             ids=["prefix-cache", "no-cache"])
    def test_reattach_parity_sweep(self, model, tmp_path, cache):
        for seed in (None, 7):
            ref = _run(model, PROMPT, 8, seed=seed, cache=cache)
            for offset in (1, 3):
                d = tmp_path / f"s{seed}-o{offset}"
                d.mkdir()
                rs, gw = _durable_gateway(model, d, cache=cache)
                try:
                    key = f"re-{seed}-{offset}"
                    FAULTS.install("serving.slow_step", Always(),
                                   delay=0.05)
                    conn, resp = _stream_request(gw, PROMPT, 8, key=key,
                                                 seed=seed)
                    head, last_id, _ = _sse_read(resp, want=offset)
                    conn.close()                 # vanish mid-stream
                    FAULTS.reset()
                    assert last_id == offset - 1
                    conn2, resp2 = _stream_request(gw, PROMPT, 8, key=key,
                                                   last_id=last_id,
                                                   seed=seed)
                    tail, _, status = _sse_read(resp2)
                    conn2.close()
                    assert status in ("finished", "eos")
                    assert head + tail == ref, (
                        f"seed={seed} offset={offset} cache={cache}: "
                        f"spliced stream diverged")
                finally:
                    FAULTS.reset()
                    gw.close()
                    rs.close()

    def test_reattach_ticks_metric_and_detach_preserves_request(
            self, model, tmp_path):
        obs.enable()
        try:
            rs, gw = _durable_gateway(model, tmp_path, detach_ttl=30.0)
            try:
                FAULTS.install("serving.slow_step", Always(), delay=0.05)
                conn, resp = _stream_request(gw, PROMPT, 8, key="met")
                _head, last_id, _ = _sse_read(resp, want=2)
                conn.close()
                FAULTS.reset()
                # the disconnect DETACHED (grace TTL pending), the pump
                # kept decoding: the reconnect must find it undamaged
                conn2, resp2 = _stream_request(gw, PROMPT, 8, key="met",
                                               last_id=last_id)
                tail, _, status = _sse_read(resp2)
                conn2.close()
                assert status != "cancelled"
                assert len(tail) == 6
                text = obs.render_prometheus()
                assert "stream_reattach_total 1" in text
            finally:
                FAULTS.reset()
                gw.close()
                rs.close()
        finally:
            obs.disable()
            obs.reset()


# ------------------------------------------------- gateway crash recovery

class TestGatewayCrashRecovery:
    """The acceptance chaos test: kill -9 the gateway mid-stream, restart
    against the same journal dir, reconnect with Last-Event-ID — the
    concatenated stream is byte-identical, no duplicate or missing
    events, and idempotent re-submits do not re-execute."""

    @pytest.mark.parametrize("cache", [True, False],
                             ids=["prefix-cache", "no-cache"])
    @pytest.mark.parametrize("seed", [None, 7], ids=["greedy", "seeded"])
    def test_kill9_restart_reconnect_byte_identical(self, model, tmp_path,
                                                    seed, cache):
        obs.enable()
        try:
            ref = _run(model, PROMPT, 8, seed=seed, cache=cache)
            rs, gw = _durable_gateway(model, tmp_path, cache=cache)
            key = "crash"
            try:
                FAULTS.install("serving.slow_step", Always(), delay=0.1)
                conn, resp = _stream_request(gw, PROMPT, 8, key=key,
                                             seed=seed)
                head, last_id, _ = _sse_read(resp, want=3)
                _kill_gateway(gw)                # mid-stream, no goodbye
                conn.close()
            finally:
                FAULTS.reset()
                rs.close()
            assert head == ref[:3]

            # fresh gateway, fresh engines, same journal dir
            rs2, gw2 = _durable_gateway(model, tmp_path, cache=cache)
            try:
                conn2, resp2 = _stream_request(gw2, PROMPT, 8, key=key,
                                               last_id=last_id, seed=seed)
                tail, _, status = _sse_read(resp2)
                conn2.close()
                assert status in ("finished", "eos")
                assert head + tail == ref, (
                    f"seed={seed} cache={cache}: stream across gateway "
                    f"death diverged")
                # recovery admitted the resumed request exactly once; the
                # reconnect replayed from the journal, it did not re-run
                assert _admissions(rs2) == 1
                text = obs.render_prometheus()
                assert "gateway_recoveries_total 1" in text
                assert 'journal_replayed_total{kind="accepted"} 1' in text
                assert 'kind="tokens"' in text
            finally:
                gw2.close()
                rs2.close()
        finally:
            obs.disable()
            obs.reset()

    def test_terminal_requests_recover_as_replay_only(self, model,
                                                      tmp_path):
        ref = _run(model, PROMPT, 4)
        rs, gw = _durable_gateway(model, tmp_path)
        try:
            done = http_completion(gw.url, PROMPT, max_tokens=4,
                                   stream=True,
                                   headers={"Idempotency-Key": "done"})
            assert done["tokens"] == ref
            _kill_gateway(gw)
        finally:
            rs.close()
        rs2, gw2 = _durable_gateway(model, tmp_path)
        try:
            admitted = _admissions(rs2)
            replay = http_completion(gw2.url, PROMPT, max_tokens=4,
                                     headers={"Idempotency-Key": "done"})
            assert replay["tokens"] == ref
            assert replay["status"] in ("finished", "eos")
            assert _admissions(rs2) == admitted   # replay-only, no re-run
        finally:
            gw2.close()
            rs2.close()

    def test_recover_fault_fails_request_durably(self, model, tmp_path):
        rs, gw = _durable_gateway(model, tmp_path)
        try:
            FAULTS.install("serving.slow_step", Always(), delay=0.1)
            conn, resp = _stream_request(gw, PROMPT, 8, key="doomed")
            _sse_read(resp, want=1)
            _kill_gateway(gw)
            conn.close()
        finally:
            FAULTS.reset()
            rs.close()
        FAULTS.install("gateway.recover", Always())
        rs2, gw2 = _durable_gateway(model, tmp_path)
        try:
            FAULTS.reset()
            out = http_completion(gw2.url, PROMPT, max_tokens=8,
                                  stream=True,
                                  headers={"Idempotency-Key": "doomed"})
            assert out["status"] == "failed"
            # the failure is journaled: a THIRD gateway serves it replay-
            # only instead of re-driving a poisoned request forever
            state, _ = gw2.plane.journal.replay()
            assert state["doomed"].status is RequestStatus.FAILED
        finally:
            gw2.close()
            rs2.close()

    def test_healthz_journal_state_and_recovery_shed(self, model,
                                                     tmp_path):
        rs, gw = _durable_gateway(model, tmp_path)
        try:
            h = json.loads(urllib.request.urlopen(gw.url + "/healthz",
                                                  timeout=10).read())
            assert h["journal"]["depth"] == 0
            assert h["journal"]["recovering"] is False
            assert "segments" in h["journal"]
            assert set(h) == {"r0", "r1", "journal", "fleet"}
            # while recovery owns the fleet, submits shed with Retry-After
            gw.plane.recovering = True
            conn = http.client.HTTPConnection(gw.addr, gw.port, timeout=10)
            conn.request("POST", "/v1/completions",
                         body=json.dumps({"prompt": PROMPT,
                                          "max_tokens": 2}),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            body = json.loads(resp.read())
            assert resp.status == 503
            assert resp.getheader("Retry-After") == "1"
            assert body["recovering"] is True
            conn.close()
            gw.plane.recovering = False
        finally:
            gw.close()
            rs.close()


# ------------------------------------------------- real processes (slow tier)

@pytest.mark.slow
class TestRealKillNine:
    def test_sigkill_gateway_subprocess(self, tmp_path):
        """A real gateway process, a real SIGKILL, the same journal dir."""
        child = os.path.join(os.path.dirname(__file__), "_gateway_child.py")
        repo = os.path.dirname(os.path.dirname(child))
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               # script-by-path puts tests/ on sys.path, not the repo root
               "PYTHONPATH": repo + os.pathsep
               + os.environ.get("PYTHONPATH", "")}
        journal_dir = str(tmp_path / "journal")
        ref = _run(_tiny_model(), PROMPT, 8)

        def spawn():
            p = subprocess.Popen(
                [sys.executable, child, journal_dir, "--slow-step", "0.2"],
                env=env, cwd=os.path.dirname(os.path.dirname(child)),
                stdout=subprocess.PIPE, text=True)
            line = p.stdout.readline().strip()   # "READY <port>"
            assert line.startswith("READY "), f"child said {line!r}"
            return p, int(line.split()[1])

        p1, port = spawn()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", port, timeout=60)
            conn.request("POST", "/v1/completions",
                         body=json.dumps({"prompt": PROMPT, "max_tokens": 8,
                                          "stream": True}),
                         headers={"Content-Type": "application/json",
                                  "Idempotency-Key": "real"})
            head, last_id, _ = _sse_read(conn.getresponse(), want=3)
            os.kill(p1.pid, signal.SIGKILL)
            p1.wait(timeout=30)
            conn.close()
            assert head == ref[:3]

            p2, port2 = spawn()
            try:
                deadline = time.monotonic() + 60
                while time.monotonic() < deadline:
                    h = json.loads(urllib.request.urlopen(
                        f"http://127.0.0.1:{port2}/healthz",
                        timeout=10).read())
                    if not h["journal"]["recovering"]:
                        break
                    time.sleep(0.2)
                conn2 = http.client.HTTPConnection("127.0.0.1", port2,
                                                   timeout=120)
                conn2.request(
                    "POST", "/v1/completions",
                    body=json.dumps({"prompt": PROMPT, "max_tokens": 8,
                                     "stream": True}),
                    headers={"Content-Type": "application/json",
                             "Idempotency-Key": "real",
                             "Last-Event-ID": str(last_id)})
                tail, _, status = _sse_read(conn2.getresponse())
                conn2.close()
                assert status in ("finished", "eos")
                assert head + tail == ref
            finally:
                p2.terminate()
                p2.wait(timeout=30)
        finally:
            for p in (p1,):
                if p.poll() is None:
                    p.kill()
