"""Self-contained ONNX export (VERDICT r4 missing #5 / row #91): models
export to real .onnx protobuf files whose graphs re-execute (via the in-tree
numpy runner) to the same numbers as the framework forward."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.onnx import export
from paddle_tpu.onnx import _proto as P
from paddle_tpu.onnx import _runner


def _roundtrip(layer, inputs, tmp_path, atol=1e-5):
    path = export(layer, str(tmp_path / "m"), input_spec=inputs)
    blob = open(path, "rb").read()
    feeds = {f"x{i}": np.asarray(t._data) for i, t in enumerate(inputs)}
    got = _runner.run(blob, feeds)
    ref = layer(*inputs)
    refs = ref if isinstance(ref, (tuple, list)) else [ref]
    for i, r in enumerate(refs):
        np.testing.assert_allclose(got[f"y{i}"], np.asarray(r._data),
                                   atol=atol, rtol=1e-4)
    return blob


class TestOnnxExport:
    def test_linear_relu_stack(self, tmp_path):
        paddle.seed(0)
        m = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                          nn.Linear(16, 4), nn.Sigmoid())
        x = paddle.to_tensor(np.random.RandomState(0).rand(
            5, 8).astype(np.float32))
        blob = _roundtrip(m, [x], tmp_path)
        # structural: a real ModelProto with IR version, opset and our graph
        mf = P.decode(blob)
        assert int(mf[1][0]) == 8                       # ir_version
        opset = P.decode(mf[8][0])
        assert int(opset[2][0]) == 17
        gf = P.decode(mf[7][0])
        ops = [P.decode(n)[4][0].decode() for n in gf[1]]
        assert "MatMul" in ops and "Sigmoid" in ops

    def test_layernorm_gelu_mlp(self, tmp_path):
        paddle.seed(1)
        m = nn.Sequential(nn.Linear(6, 12), nn.LayerNorm(12), nn.GELU(),
                          nn.Linear(12, 3))
        x = paddle.to_tensor(np.random.RandomState(1).rand(
            4, 6).astype(np.float32))
        _roundtrip(m, [x], tmp_path, atol=1e-4)

    def test_functional_callable_and_multi_output(self, tmp_path):
        def f(a, b):
            s = a + b.exp()
            return s.tanh(), (s * 2.0).mean()

        x = paddle.to_tensor(np.random.RandomState(2).rand(
            3, 4).astype(np.float32))
        y = paddle.to_tensor(np.random.RandomState(3).rand(
            3, 4).astype(np.float32))
        path = export(f, str(tmp_path / "fn"), input_spec=[x, y])
        got = _runner.run(open(path, "rb").read(),
                          {"x0": np.asarray(x._data),
                           "x1": np.asarray(y._data)})
        np.testing.assert_allclose(
            got["y0"], np.tanh(np.asarray(x._data) + np.exp(np.asarray(y._data))),
            atol=1e-5, rtol=1e-5)
        np.testing.assert_allclose(
            got["y1"],
            ((np.asarray(x._data) + np.exp(np.asarray(y._data))) * 2).mean(),
            atol=1e-5, rtol=1e-5)

    def test_unsupported_primitive_raises_with_name(self, tmp_path):
        def f(a):
            return paddle.ops.cumsum(a)   # cumsum is outside the subset

        x = paddle.to_tensor(np.ones((3,), np.float32))
        with pytest.raises(NotImplementedError, match="cumsum"):
            export(f, str(tmp_path / "bad"), input_spec=[x])

    def test_input_spec_objects(self, tmp_path):
        paddle.seed(2)
        m = nn.Linear(4, 2)

        class Spec:
            shape = [None, 4]
            dtype = "float32"

        path = export(m, str(tmp_path / "spec"), input_spec=[Spec()])
        got = _runner.run(open(path, "rb").read(),
                          {"x0": np.zeros((1, 4), np.float32)})
        ref = m(paddle.to_tensor(np.zeros((1, 4), np.float32)))
        np.testing.assert_allclose(got["y0"], np.asarray(ref._data),
                                   atol=1e-6)


class TestBf16Export:
    def test_bf16_initializers_decode(self, tmp_path):
        """bf16 models export and their initializers decode in-tree (the
        runner's dtype table covers BFLOAT16)."""
        import ml_dtypes
        paddle.seed(3)
        m = nn.Linear(4, 3)
        m.to(dtype="bfloat16")
        x = paddle.to_tensor(
            np.zeros((2, 4), np.float32)).astype("bfloat16")
        path = export(m, str(tmp_path / "bf16"), input_spec=[x])
        mf = P.decode(open(path, "rb").read())
        gf = P.decode(mf[7][0])
        decoded = [P.decode_tensor(t) for t in gf.get(5, [])]
        assert any(arr.dtype == np.dtype(ml_dtypes.bfloat16)
                   for _, arr in decoded)
