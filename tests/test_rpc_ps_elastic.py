"""paddle.distributed.rpc / parameter server / elastic manager."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest


def _run(code, timeout=180):
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=timeout,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    return r


class TestRpc:
    def test_self_rpc_and_remote_exception(self):
        code = """
import operator
from paddle_tpu.distributed import rpc

rpc.init_rpc("w0", rank=0, world_size=1, master_endpoint="127.0.0.1:0")
assert rpc.rpc_sync("w0", operator.add, args=(2, 3)) == 5
fut = rpc.rpc_async("w0", operator.mul, args=(4, 5))
assert fut.wait() == 20
info = rpc.get_current_worker_info()
assert info.name == "w0" and info.rank == 0
assert [w.name for w in rpc.get_all_worker_infos()] == ["w0"]
try:
    rpc.rpc_sync("w0", operator.truediv, args=(1, 0))
    raise SystemExit("no remote exception")
except ZeroDivisionError:
    pass
rpc.shutdown()
print("RPC_OK")
"""
        r = _run(code)
        assert "RPC_OK" in r.stdout, r.stderr[-2000:]

    def test_two_process_rpc(self, tmp_path):
        # real usage: all ranks know the master endpoint up front
        import socket as _s
        srv = _s.socket()
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        srv.close()
        master = f"""
import operator
from paddle_tpu.distributed import rpc
rpc.init_rpc("master", rank=0, world_size=2,
             master_endpoint="127.0.0.1:{port}")
assert rpc.rpc_sync("worker", operator.add, args=(10, 20)) == 30
rpc.shutdown()
print("MASTER_OK")
"""
        worker = f"""
from paddle_tpu.distributed import rpc
rpc.init_rpc("worker", rank=1, world_size=2,
             master_endpoint="127.0.0.1:{port}")
rpc.shutdown()
print("WORKER_OK")
"""
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        pm = subprocess.Popen([sys.executable, "-c", master],
                              stdout=subprocess.PIPE, text=True, env=env)
        pw = subprocess.Popen([sys.executable, "-c", worker],
                              stdout=subprocess.PIPE, text=True, env=env)
        om, _ = pm.communicate(timeout=180)
        ow, _ = pw.communicate(timeout=180)
        assert "MASTER_OK" in om and "WORKER_OK" in ow


class TestParameterServer:
    def test_pull_push_sharded(self):
        code = """
import numpy as np
from paddle_tpu.distributed import rpc, ps

rpc.init_rpc("trainer", rank=0, world_size=1,
             master_endpoint="127.0.0.1:0")
client = ps.PsClient(["trainer"])   # 1-server world: PS colocated
client.create_table("emb", rows=64, dim=8, initializer="zeros", lr=0.5)
rows = np.array([3, 10, 3])
vals = client.pull("emb", rows)
assert vals.shape == (3, 8) and (vals == 0).all()
g = np.ones((3, 8), np.float32)
client.push("emb", rows, g)         # duplicate row 3 accumulates
after = client.pull("emb", np.array([3, 10, 5]))
np.testing.assert_allclose(after[0], -1.0)   # 2 grads * lr 0.5
np.testing.assert_allclose(after[1], -0.5)
np.testing.assert_allclose(after[2], 0.0)
stats = client.stats("emb")
assert stats[0]["shape"] == [64, 8]
rpc.shutdown()
print("PS_OK")
"""
        r = _run(code)
        assert "PS_OK" in r.stdout, r.stderr[-2000:]


class TestElastic:
    def test_membership_lifecycle(self):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)
        m0 = ElasticManager(rank=0, np_min=2, np_max=2, timeout=1.0,
                            heartbeat_interval=0.2, job_id="t1")
        assert m0.watch(world_hint=2) == ElasticStatus.HOLD  # nobody yet
        m0.start()
        assert m0.watch(world_hint=2) == ElasticStatus.HOLD  # 1 < np_min
        m1 = ElasticManager(rank=1, store=m0.store, np_min=2, np_max=2,
                            timeout=1.0, heartbeat_interval=0.2, job_id="t1")
        m1.start()
        assert m0.watch(world_hint=2) == ElasticStatus.COMPLETED
        assert m0.alive_ranks(world_hint=2) == [0, 1]
        # rank 1 dies -> heartbeats stop -> RESTART decision
        m1.stop()
        time.sleep(1.5)
        assert m0.watch(world_hint=2) == ElasticStatus.RESTART
        # rank 1 rejoins -> COMPLETED again
        m1b = ElasticManager(rank=1, store=m0.store, np_min=2, np_max=2,
                             timeout=1.0, heartbeat_interval=0.2,
                             job_id="t1")
        m1b.start()
        assert m0.watch(world_hint=2) == ElasticStatus.COMPLETED
        m1b.stop()
        m0.stop()

    def test_finished_rank_is_not_a_fault(self):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)
        m0 = ElasticManager(rank=0, np_min=1, np_max=2, timeout=1.0,
                            heartbeat_interval=0.2, job_id="t2")
        m0.start()
        m1 = ElasticManager(rank=1, store=m0.store, np_min=1, np_max=2,
                            timeout=1.0, heartbeat_interval=0.2, job_id="t2")
        m1.start()
        assert m0.watch(world_hint=2) == ElasticStatus.COMPLETED
        # rank 1 completes CLEANLY: no restart storm
        m1.mark_finished()
        m1.stop()
        time.sleep(1.5)
        assert m0.watch(world_hint=2) == ElasticStatus.COMPLETED
        m0.stop()

    def test_launch_elastic_restart(self, tmp_path):
        # worker fails on first attempt, succeeds on second (restart loop)
        marker = tmp_path / "tried"
        script = tmp_path / "train.py"
        script.write_text(f"""
import os, sys, pathlib
m = pathlib.Path({str(marker)!r})
if not m.exists():
    m.write_text("1")
    sys.exit(3)
print("second attempt ok")
""")
        from paddle_tpu.distributed.launch.main import launch
        rc = launch(["--nproc_per_node=1", "--max_restarts=2", "--backend=cpu",
                     f"--log_dir={tmp_path}/log", str(script)])
        assert rc == 0
        log = (tmp_path / "log" / "workerlog.0").read_bytes().decode()
        assert "second attempt ok" in log
