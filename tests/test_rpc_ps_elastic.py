"""paddle.distributed.rpc / parameter server / elastic manager."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest


def _run(code, timeout=180):
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=timeout,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    return r


class TestRpc:
    def test_self_rpc_and_remote_exception(self):
        code = """
import operator
from paddle_tpu.distributed import rpc

rpc.init_rpc("w0", rank=0, world_size=1, master_endpoint="127.0.0.1:0")
assert rpc.rpc_sync("w0", operator.add, args=(2, 3)) == 5
fut = rpc.rpc_async("w0", operator.mul, args=(4, 5))
assert fut.wait() == 20
info = rpc.get_current_worker_info()
assert info.name == "w0" and info.rank == 0
assert [w.name for w in rpc.get_all_worker_infos()] == ["w0"]
try:
    rpc.rpc_sync("w0", operator.truediv, args=(1, 0))
    raise SystemExit("no remote exception")
except ZeroDivisionError:
    pass
rpc.shutdown()
print("RPC_OK")
"""
        r = _run(code)
        assert "RPC_OK" in r.stdout, r.stderr[-2000:]

    def test_two_process_rpc(self, tmp_path):
        # real usage: all ranks know the master endpoint up front
        import socket as _s
        srv = _s.socket()
        srv.bind(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        srv.close()
        master = f"""
import operator
from paddle_tpu.distributed import rpc
rpc.init_rpc("master", rank=0, world_size=2,
             master_endpoint="127.0.0.1:{port}")
assert rpc.rpc_sync("worker", operator.add, args=(10, 20)) == 30
rpc.shutdown()
print("MASTER_OK")
"""
        worker = f"""
from paddle_tpu.distributed import rpc
rpc.init_rpc("worker", rank=1, world_size=2,
             master_endpoint="127.0.0.1:{port}")
rpc.shutdown()
print("WORKER_OK")
"""
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        pm = subprocess.Popen([sys.executable, "-c", master],
                              stdout=subprocess.PIPE, text=True, env=env)
        pw = subprocess.Popen([sys.executable, "-c", worker],
                              stdout=subprocess.PIPE, text=True, env=env)
        om, _ = pm.communicate(timeout=180)
        ow, _ = pw.communicate(timeout=180)
        assert "MASTER_OK" in om and "WORKER_OK" in ow


class TestParameterServer:
    def test_pull_push_sharded(self):
        code = """
import numpy as np
from paddle_tpu.distributed import rpc, ps

rpc.init_rpc("trainer", rank=0, world_size=1,
             master_endpoint="127.0.0.1:0")
client = ps.PsClient(["trainer"])   # 1-server world: PS colocated
client.create_table("emb", rows=64, dim=8, initializer="zeros", lr=0.5)
rows = np.array([3, 10, 3])
vals = client.pull("emb", rows)
assert vals.shape == (3, 8) and (vals == 0).all()
g = np.ones((3, 8), np.float32)
client.push("emb", rows, g)         # duplicate row 3 accumulates
after = client.pull("emb", np.array([3, 10, 5]))
np.testing.assert_allclose(after[0], -1.0)   # 2 grads * lr 0.5
np.testing.assert_allclose(after[1], -0.5)
np.testing.assert_allclose(after[2], 0.0)
stats = client.stats("emb")
assert stats[0]["shape"] == [64, 8]
rpc.shutdown()
print("PS_OK")
"""
        r = _run(code)
        assert "PS_OK" in r.stdout, r.stderr[-2000:]


class TestElastic:
    def test_membership_lifecycle(self):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)
        m0 = ElasticManager(rank=0, np_min=2, np_max=2, timeout=1.0,
                            heartbeat_interval=0.2, job_id="t1")
        assert m0.watch(world_hint=2) == ElasticStatus.HOLD  # nobody yet
        m0.start()
        assert m0.watch(world_hint=2) == ElasticStatus.HOLD  # 1 < np_min
        m1 = ElasticManager(rank=1, store=m0.store, np_min=2, np_max=2,
                            timeout=1.0, heartbeat_interval=0.2, job_id="t1")
        m1.start()
        assert m0.watch(world_hint=2) == ElasticStatus.COMPLETED
        assert m0.alive_ranks(world_hint=2) == [0, 1]
        # rank 1 dies -> heartbeats stop -> RESTART decision
        m1.stop()
        time.sleep(1.5)
        assert m0.watch(world_hint=2) == ElasticStatus.RESTART
        # rank 1 rejoins -> COMPLETED again
        m1b = ElasticManager(rank=1, store=m0.store, np_min=2, np_max=2,
                             timeout=1.0, heartbeat_interval=0.2,
                             job_id="t1")
        m1b.start()
        assert m0.watch(world_hint=2) == ElasticStatus.COMPLETED
        m1b.stop()
        m0.stop()

    def test_finished_rank_is_not_a_fault(self):
        from paddle_tpu.distributed.fleet.elastic import (ElasticManager,
                                                          ElasticStatus)
        m0 = ElasticManager(rank=0, np_min=1, np_max=2, timeout=1.0,
                            heartbeat_interval=0.2, job_id="t2")
        m0.start()
        m1 = ElasticManager(rank=1, store=m0.store, np_min=1, np_max=2,
                            timeout=1.0, heartbeat_interval=0.2, job_id="t2")
        m1.start()
        assert m0.watch(world_hint=2) == ElasticStatus.COMPLETED
        # rank 1 completes CLEANLY: no restart storm
        m1.mark_finished()
        m1.stop()
        time.sleep(1.5)
        assert m0.watch(world_hint=2) == ElasticStatus.COMPLETED
        m0.stop()

    def test_launch_elastic_restart(self, tmp_path):
        # worker fails on first attempt, succeeds on second (restart loop)
        marker = tmp_path / "tried"
        script = tmp_path / "train.py"
        script.write_text(f"""
import os, sys, pathlib
m = pathlib.Path({str(marker)!r})
if not m.exists():
    m.write_text("1")
    sys.exit(3)
print("second attempt ok")
""")
        from paddle_tpu.distributed.launch.main import launch
        rc = launch(["--nproc_per_node=1", "--max_restarts=2", "--backend=cpu",
                     f"--log_dir={tmp_path}/log", str(script)])
        assert rc == 0
        log = (tmp_path / "log" / "workerlog.0").read_bytes().decode()
        assert "second attempt ok" in log


class TestSparsePs:
    """Host-resident sparse PS (VERDICT r2 #6): hash tables with a bounded
    resident pool + sqlite spill, server-side optimizer, kill/restart from
    checkpoint, and a device-integrated embedding that trains."""

    @staticmethod
    def _start(tmp_path, n=2):
        import socket as sk
        from paddle_tpu.distributed.ps_sparse import (start_server_process,
                                                      SparsePsClient)
        ports = []
        for _ in range(n):
            with sk.socket() as s:
                s.bind(("127.0.0.1", 0))
                ports.append(s.getsockname()[1])
        procs = [start_server_process(p, str(tmp_path / f"srv{i}"))
                 for i, p in enumerate(ports)]
        client = SparsePsClient([f"127.0.0.1:{p}" for p in ports])
        return client, procs, ports

    def test_budget_eviction_roundtrip(self, tmp_path):
        import numpy as np
        client, procs, _ = self._start(tmp_path)
        try:
            cap = 64
            client.create_table("emb", dim=8, capacity_rows_per_server=cap,
                                lr=0.5, initializer="zeros")
            total_ids = np.arange(400, dtype=np.int64)
            # push a known gradient to every id (walks far past capacity)
            for chunk in np.array_split(total_ids, 8):
                g = np.full((len(chunk), 8), 1.0, np.float32)
                client.push("emb", chunk, g)
            stats = client.stats()
            for st in stats:
                assert st["emb"]["resident"] <= cap
            spilled = sum(st["emb"]["spilled"] for st in stats)
            resident = sum(st["emb"]["resident"] for st in stats)
            assert spilled + resident == 400
            assert spilled >= 400 - 2 * cap  # table >> per-server budget
            # every row round-trips through the spill with the update applied
            rows = client.pull("emb", total_ids)
            np.testing.assert_allclose(rows, -0.5, atol=1e-6)
        finally:
            client.shutdown()
            for p in procs:
                p.wait(timeout=10)

    def test_kill_restart_resumes_from_checkpoint(self, tmp_path):
        import numpy as np
        import os, signal, time
        from paddle_tpu.distributed.ps_sparse import start_server_process
        client, procs, ports = self._start(tmp_path)
        try:
            client.create_table("emb", dim=4, capacity_rows_per_server=16,
                                lr=1.0, initializer="zeros")
            ids = np.arange(40, dtype=np.int64)
            client.push("emb", ids, np.full((40, 4), 2.0, np.float32))
            before = client.pull("emb", ids)
            ck = tmp_path / "ckpt"
            client.save(str(ck))
            # hard-kill server 0, restart on the same port + data dir
            procs[0].send_signal(signal.SIGKILL)
            procs[0].wait(timeout=10)
            procs[0] = start_server_process(ports[0], str(tmp_path / "srv0"))
            # recreate shard + load checkpoint (client reconnects on retry)
            client.create_table("emb", dim=4, capacity_rows_per_server=16,
                                lr=1.0, initializer="zeros")
            client.load("emb", str(ck))
            after = client.pull("emb", ids)
            np.testing.assert_allclose(after, before)
        finally:
            client.shutdown()
            for p in procs:
                p.wait(timeout=10)

    def test_ps_embedding_trains(self, tmp_path):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.distributed.ps_sparse import PsEmbedding
        client, procs, _ = self._start(tmp_path)
        try:
            pt.seed(0)
            emb = PsEmbedding(client, "tok", dim=8, lr=0.3,
                              capacity_rows_per_server=128)
            head = pt.nn.Linear(8, 1)
            opt = pt.optimizer.SGD(learning_rate=0.1,
                                   parameters=head.parameters())
            rng = np.random.RandomState(0)
            ids = pt.to_tensor(rng.randint(0, 1000, (16, 3)).astype(np.int64))
            target = pt.to_tensor(rng.rand(16, 1).astype(np.float32))
            losses = []
            for _ in range(25):
                h = emb(ids).mean(axis=1)          # [16, 8]
                loss = ((head(h) - target) ** 2).mean()
                loss.backward()                     # hook pushes row grads
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            assert losses[-1] < losses[0] * 0.5, losses[::6]
        finally:
            client.shutdown()
            for p in procs:
                p.wait(timeout=10)


class TestFleetPsMode:
    """VERDICT r3 #3: fleet.init(role_maker) must branch the runtime on the
    role purely from the PaddleCloud env contract (reference
    fleet/fleet.py:220-226): SERVER processes serve their ps_sparse shard,
    TRAINER processes get a connected client, and PsEmbedding trains."""

    SERVER = """
import paddle_tpu.distributed.fleet as fleet_mod
from paddle_tpu.distributed.fleet import PaddleCloudRoleMaker
fleet = fleet_mod.Fleet()
rm = PaddleCloudRoleMaker(is_collective=False)
fleet.init(role_maker=rm)
assert fleet.is_server() and not fleet.is_worker()
fleet.run_server()           # blocks until a trainer sends shutdown
print("SERVER_DONE")
"""

    TRAINER = """
import os, time
import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed.fleet as fleet_mod
from paddle_tpu.distributed.fleet import PaddleCloudRoleMaker
from paddle_tpu.distributed.ps_sparse import PsEmbedding

fleet = fleet_mod.Fleet()
rm = PaddleCloudRoleMaker(is_collective=False)
fleet.init(role_maker=rm)
assert fleet.is_worker() and not fleet.is_server()
client = fleet.ps_client()

emb = PsEmbedding(client, "feat", dim=8, lr=2.0,
                  capacity_rows_per_server=64)
rid = int(os.environ["PADDLE_TRAINER_ID"])
rng = np.random.RandomState(rid)
target = paddle.to_tensor(np.ones((4, 8), np.float32))
first = last = None
for step in range(60):
    ids = paddle.to_tensor(rng.randint(0, 10, (4,)).astype(np.int64))
    out = emb(ids)
    loss = ((out - target) ** 2).mean()
    loss.backward()
    v = float(np.asarray(loss._data, np.float32))
    first = v if first is None else first
    last = v
assert last < 0.5 * first, (first, last)
done = os.environ["PS_DONE_DIR"] + f"/trainer_{rid}.done"
open(done, "w").write("ok")
if rid == 0:   # shut servers down once every trainer has finished
    import glob
    n = int(os.environ["PADDLE_TRAINERS_NUM"])
    deadline = time.time() + 60
    while len(glob.glob(os.environ["PS_DONE_DIR"] + "/trainer_*.done")) < n:
        assert time.time() < deadline, "peers never finished"
        time.sleep(0.1)
    client.shutdown()
fleet.stop_worker()
print("TRAINER_OK", first, last)
"""

    def test_fleet_ps_bringup_from_env(self, tmp_path):
        import socket as _s
        ports = []
        socks = []
        for _ in range(2):
            s = _s.socket()
            s.bind(("127.0.0.1", 0))
            ports.append(s.getsockname()[1])
            socks.append(s)
        for s in socks:
            s.close()
        servers_list = ",".join(f"127.0.0.1:{p}" for p in ports)
        base_env = {**os.environ, "JAX_PLATFORMS": "cpu",
                    "PADDLE_PSERVERS_IP_PORT_LIST": servers_list,
                    "PADDLE_PS_DATA_DIR": str(tmp_path / "data"),
                    "PS_DONE_DIR": str(tmp_path)}
        procs = []
        for i, p in enumerate(ports):
            env = {**base_env, "TRAINING_ROLE": "PSERVER",
                   "POD_IP": "127.0.0.1", "PADDLE_PORT": str(p)}
            procs.append(subprocess.Popen(
                [sys.executable, "-c", self.SERVER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        trainers = []
        for i in range(2):
            env = {**base_env, "TRAINING_ROLE": "TRAINER",
                   "PADDLE_TRAINER_ID": str(i), "PADDLE_TRAINERS_NUM": "2"}
            trainers.append(subprocess.Popen(
                [sys.executable, "-c", self.TRAINER], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True))
        outs = [t.communicate(timeout=180) for t in trainers]
        for t, (out, err) in zip(trainers, outs):
            assert t.returncode == 0 and "TRAINER_OK" in out, err[-2000:]
        souts = [p.communicate(timeout=60) for p in procs]
        for p, (out, err) in zip(procs, souts):
            assert p.returncode == 0 and "SERVER_DONE" in out, err[-2000:]

    def test_unwired_strategy_flags_raise(self):
        import pytest
        from paddle_tpu.distributed.fleet import DistributedStrategy
        s = DistributedStrategy()
        for flag in ("amp", "recompute", "tensor_parallel",
                     "find_unused_parameters"):
            assert getattr(s, flag) is False
            with pytest.raises(NotImplementedError):
                setattr(s, flag, True)
            setattr(s, flag, False)   # explicit False stays allowed
        s.gradient_merge = True       # wired flags still settable
        s.gradient_merge_configs = {"k_steps": 2}
