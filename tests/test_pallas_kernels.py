"""Pallas kernel numerics (interpret mode on CPU; compiled path covered by
bench/verify on the real chip)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu.ops.pallas.flash_attention import flash_attention_bshd
from paddle_tpu.nn.functional.attention import _sdpa_ref

rng = np.random.RandomState(0)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("D", [64, 128])
def test_flash_forward_matches_reference(causal, D):
    B, S, H = 1, 256, 2
    q = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
    out = flash_attention_bshd(q, k, v, causal=causal)
    ref = _sdpa_ref(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_gradients_match_reference(causal):
    B, S, H, D = 1, 256, 1, 128
    q = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
    v = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))

    def loss_fl(q, k, v):
        return jnp.sum(flash_attention_bshd(q, k, v, causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_sdpa_ref(q, k, v, causal=causal) ** 2)

    g1 = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_flash_gqa():
    B, S, H, D = 1, 128, 4, 64
    q = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
    kv = jnp.asarray(rng.rand(B, S, 1, D).astype(np.float32))
    out = flash_attention_bshd(q, kv, kv, causal=True)
    ref = _sdpa_ref(q, kv, kv, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_bf16():
    B, S, H, D = 1, 128, 2, 64
    q = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32)).astype(jnp.bfloat16)
    out = flash_attention_bshd(q, q, q, causal=True)
    ref = _sdpa_ref(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref, np.float32),
                               atol=2e-2)


def test_flash_gqa_gradients_match_reference():
    """GQA backward: dk/dv must sum over the query-head group."""
    B, S, H, Hk, D = 1, 256, 4, 2, 64
    q = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.rand(B, S, Hk, D).astype(np.float32))
    v = jnp.asarray(rng.rand(B, S, Hk, D).astype(np.float32))

    def loss_fl(q, k, v):
        return jnp.sum(flash_attention_bshd(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_sdpa_ref(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_flash_gqa_never_materializes_repeated_kv():
    """VERDICT r1 weak#4: GQA must index kv-head in the kernel, not jnp.repeat.
    No intermediate in the traced program may have the repeated-KV shape."""
    B, Sq, Sk, H, Hk, D = 2, 128, 256, 8, 2, 64
    q = jnp.zeros((B, Sq, H, D), jnp.float32)
    k = jnp.zeros((B, Sk, Hk, D), jnp.float32)
    v = jnp.zeros((B, Sk, Hk, D), jnp.float32)

    def fwd_bwd(q, k, v):
        return jnp.sum(flash_attention_bshd(q, k, v, causal=False) ** 2)

    jaxpr = jax.make_jaxpr(jax.grad(fwd_bwd, argnums=(0, 1, 2)))(q, k, v)
    repeated = {(B * H, Sk, D), (B, Sk, H, D)}

    def scan(jp):
        for eqn in jp.eqns:
            for var in eqn.outvars:
                shape = tuple(getattr(var.aval, "shape", ()))
                assert shape not in repeated, (
                    f"materialized repeated KV {shape} via {eqn.primitive}")
            for sub in eqn.params.values():
                if hasattr(sub, "eqns"):
                    scan(sub)
                elif hasattr(sub, "jaxpr") and hasattr(sub.jaxpr, "eqns"):
                    scan(sub.jaxpr)

    scan(jaxpr.jaxpr)


def test_flash_rejects_non_divisible_seq():
    """A sequence not divisible by the block size must error loudly, never
    silently truncate (round-1 hazard: nq = Sq // BQ dropped the tail)."""
    q = jnp.zeros((1, 100, 2, 64), jnp.float32)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention_bshd(q, q, q)


def test_supported_predicate():
    from paddle_tpu.ops.pallas.flash_attention import supported
    assert supported((1, 256, 8, 64))
    assert supported((1, 256, 8, 128), (1, 256, 8, 128))
    assert not supported((1, 100, 8, 128))      # r1 precedence bug: was True
    assert not supported((1, 256, 8, 100))
    assert not supported((1, 256, 8, 64), (1, 100, 8, 64))
    assert not supported((1, 256, 8, 64), (1, 256, 3, 64))  # 8 % 3 != 0


def test_layout_direct_bshd_path_matches_reference():
    """FLAGS_flash_layout_direct engages the [B,S,H,D] lane-sliced kernels;
    numerics must match the default [B*H,S,D] path (fwd + grads)."""
    import paddle_tpu as pt
    rng = np.random.RandomState(7)
    B, S, H, D = 2, 128, 4, 64
    q = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(B, S, H, D).astype(np.float32) * 0.3)

    def loss(qq, kk, vv):
        return jnp.sum(flash_attention_bshd(qq, kk, vv, causal=True)
                       .astype(jnp.float32) ** 2)

    o_ref = flash_attention_bshd(q, k, v, causal=True)
    g_ref = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    pt.set_flags({"FLAGS_flash_layout_direct": True})
    try:
        from paddle_tpu.ops.pallas.flash_attention import _bshd_config
        assert _bshd_config(B, S, S, H, D, q.dtype) is not None
        o_new = flash_attention_bshd(q, k, v, causal=True)
        g_new = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        pt.set_flags({"FLAGS_flash_layout_direct": False})
    np.testing.assert_allclose(np.asarray(o_new), np.asarray(o_ref),
                               rtol=1e-4, atol=1e-4)
    for a, b in zip(g_new, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-3)


def test_flash_llama3_geometry_fwd_bwd():
    """The r5 bench's north-star head shape: head_dim=128 + GQA 4:1 (the MXU
    contraction-filling configuration) — forward and gradients vs reference,
    in one test so the llama3_shaped_pretrain bench path is pre-validated
    off-chip."""
    B, S, H, KVH, D = 1, 128, 8, 2, 128
    q = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
    k = jnp.asarray(rng.rand(B, S, KVH, D).astype(np.float32))
    v = jnp.asarray(rng.rand(B, S, KVH, D).astype(np.float32))
    out = flash_attention_bshd(q, k, v, causal=True)
    ref = _sdpa_ref(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def loss_fl(q, k, v):
        return jnp.sum(flash_attention_bshd(q, k, v, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(_sdpa_ref(q, k, v, causal=True) ** 2)

    g1 = jax.grad(loss_fl, argnums=(0, 1, 2))(q, k, v)
    g2 = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3)


def test_llama3_shaped_train_step_scans():
    """Layer-scaled version of the bench's Llama-3-shaped config (head_dim
    128, GQA 4:1, SwiGLU, tied vocab) through jit.scan_steps — the exact
    code path _llama_child drives on chip, pre-validated off-chip."""
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    paddle.seed(0)
    cfg = LlamaConfig(vocab_size=997, hidden_size=512, intermediate_size=896,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=1, max_position_embeddings=128,
                      tie_word_embeddings=True)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters(),
                                 grad_clip=nn.ClipGradByGlobalNorm(1.0))

    def train_step(x, y):
        _, loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.scan_steps(train_step)
    r = np.random.RandomState(0)

    def data(k):
        ids = r.randint(0, cfg.vocab_size, (k, 2, 65)).astype(np.int32)
        return (paddle.to_tensor(ids[:, :, :-1]),
                paddle.to_tensor(ids[:, :, 1:]))

    losses = []
    for _ in range(3):                 # spy x2 + compiled scan
        out = step(*data(2))
        losses.extend(np.asarray(out._data, np.float32).tolist())
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]      # it actually trains
