"""The examples/ scripts are the user's first contact — they must run."""
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = sorted(f for f in os.listdir(os.path.join(ROOT, "examples"))
                  if f.endswith(".py"))
# full training/serving loops in a fresh interpreter (~15s each): slow tier;
# export_onnx stays in tier-1 as the fast end-to-end canary
_SLOW = {"serve_llama.py", "sharded_train.py", "train_gpt2.py"}


@pytest.mark.parametrize(
    "script",
    [pytest.param(s, marks=pytest.mark.slow) if s in _SLOW else s
     for s in EXAMPLES])
def test_example_runs(script):
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=8"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    r = subprocess.run([sys.executable, os.path.join("examples", script)],
                       cwd=ROOT, env=env, capture_output=True, text=True,
                       timeout=420)
    assert r.returncode == 0, f"{script}:\n{r.stderr[-2000:]}"
