"""sparse.nn tests (reference test analog: test/legacy_test/test_sparse_conv_op.py,
test_sparse_pooling_op.py, test_sparse_norm_op.py — dense-equivalence + grads)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu as pt
import paddle_tpu.sparse as sparse


def _rand_sparse(rng, shape, density=0.2, channels=4):
    """Random [N, *spatial, C] COO tensor with given site density."""
    nd = len(shape) - 2
    n = shape[0]
    spatial = shape[1:1 + nd]
    mask = rng.rand(n, *spatial) < density
    idx = np.stack(np.nonzero(mask), axis=0)          # [1+nd, nnz]
    vals = rng.randn(idx.shape[1], channels).astype(np.float32)
    x = sparse.sparse_coo_tensor(idx, pt.to_tensor(vals), shape,
                                 stop_gradient=False)
    dense = np.zeros(shape, np.float32)
    dense[tuple(idx)] = vals
    return x, dense


def _dense_conv(dense, w, stride, padding, nd):
    """Reference dense conv via lax (NDHWC x [*k, Cin, Cout])."""
    dn = jax.lax.conv_dimension_numbers(
        dense.shape, w.shape,
        ("NDHWC", "DHWIO", "NDHWC") if nd == 3 else ("NHWC", "HWIO", "NHWC"))
    return np.asarray(jax.lax.conv_general_dilated(
        dense, w, (stride,) * nd, [(padding, padding)] * nd,
        dimension_numbers=dn))


@pytest.mark.parametrize("nd", [2, 3])
def test_conv_matches_dense(nd):
    rng = np.random.RandomState(0)
    shape = (2,) + (6,) * nd + (4,)
    x, dense = _rand_sparse(rng, shape)
    cout = 5
    w = rng.randn(*((3,) * nd), 4, cout).astype(np.float32) * 0.3
    f = sparse.nn.functional.conv3d if nd == 3 else sparse.nn.functional.conv2d
    out = f(x, pt.to_tensor(w), stride=1, padding=1)
    ref = _dense_conv(dense, w, 1, 1, nd)
    got = np.asarray(out.to_dense().numpy())
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-4)


def test_subm_conv3d_matches_dense_at_sites():
    rng = np.random.RandomState(1)
    shape = (2, 5, 6, 7, 3)
    x, dense = _rand_sparse(rng, shape, channels=3)
    w = rng.randn(3, 3, 3, 3, 4).astype(np.float32) * 0.3
    out = sparse.nn.functional.subm_conv3d(x, pt.to_tensor(w), padding=1)
    # subm: output sites == input sites; values equal dense conv there
    assert out.nnz() == x.nnz()
    ref = _dense_conv(dense, w, 1, 1, 3)
    idx = np.asarray(x.indices().numpy())
    got = np.asarray(out.to_dense().numpy())
    np.testing.assert_allclose(got[tuple(idx)], ref[tuple(idx)],
                               rtol=1e-4, atol=1e-4)
    # everything off the active set stays empty
    mask = np.zeros(shape[:4], bool)
    mask[tuple(idx)] = True
    assert np.all(got[~mask] == 0)


def test_sparse_conv_grads_flow_to_weight_and_values():
    rng = np.random.RandomState(2)
    shape = (1, 4, 4, 4, 2)
    x, dense = _rand_sparse(rng, shape, density=0.3, channels=2)
    conv = sparse.nn.SubmConv3D(2, 3, 3, padding=1)
    out = conv(x)
    loss = (out.values() ** 2).sum()
    loss.backward()
    g = conv.weight.grad
    assert g is not None and float(np.abs(np.asarray(g._data)).sum()) > 0
    gx = x.values().grad
    assert gx is not None and gx.shape == list(x.values().shape)
    # finite-difference check one weight element
    w0 = np.asarray(conv.weight._data).copy()
    eps = 1e-3
    def loss_at(wval):
        conv.weight.set_value(pt.to_tensor(wval))
        return float((conv(x).values() ** 2).sum())
    w1 = w0.copy(); w1[0, 0, 0, 0, 0] += eps
    w2 = w0.copy(); w2[0, 0, 0, 0, 0] -= eps
    fd = (loss_at(w1) - loss_at(w2)) / (2 * eps)
    np.testing.assert_allclose(float(np.asarray(g._data)[0, 0, 0, 0, 0]), fd,
                               rtol=2e-2, atol=1e-2)


def test_max_pool3d_matches_dense():
    rng = np.random.RandomState(3)
    shape = (2, 4, 4, 4, 3)
    x, dense = _rand_sparse(rng, shape, density=0.4, channels=3)
    out = sparse.nn.functional.max_pool3d(x, kernel_size=2, stride=2)
    got = np.asarray(out.to_dense().numpy())
    # dense max pool over ONLY the active sites (empty sites don't contribute)
    big = np.where(np.any(dense != 0, axis=-1, keepdims=True) |
                   (dense != 0), dense, -np.inf)
    N, D, H, W, C = shape
    ref = big.reshape(N, D // 2, 2, H // 2, 2, W // 2, 2, C).max((2, 4, 6))
    mask = np.isfinite(ref)
    np.testing.assert_allclose(got[mask], ref[mask], rtol=1e-5)
    assert np.all(got[~mask] == 0)


def test_batchnorm_and_activations():
    rng = np.random.RandomState(4)
    shape = (2, 4, 4, 4, 6)
    x, _ = _rand_sparse(rng, shape, channels=6)
    bn = sparse.nn.BatchNorm(6)
    out = bn(x)
    v = np.asarray(out.values().numpy())
    np.testing.assert_allclose(v.mean(0), 0, atol=1e-4)
    np.testing.assert_allclose(v.std(0), 1, atol=1e-2)
    r = sparse.nn.ReLU()(out)
    assert np.all(np.asarray(r.values().numpy()) >= 0)
    r6 = sparse.nn.ReLU6()(out)
    assert np.all(np.asarray(r6.values().numpy()) <= 6)
    lr = sparse.nn.LeakyReLU(0.1)(out)
    neg = v < 0
    np.testing.assert_allclose(np.asarray(lr.values().numpy())[neg],
                               v[neg] * 0.1, rtol=1e-5)


def test_sparse_net_trains():
    """VERDICT r2 done-criterion: a small sparse conv net trains on CPU."""
    rng = np.random.RandomState(5)
    pt.seed(0)

    class Net(pt.nn.Layer):
        def __init__(self):
            super().__init__()
            self.c1 = sparse.nn.SubmConv3D(2, 8, 3, padding=1)
            self.bn = sparse.nn.BatchNorm(8)
            self.act = sparse.nn.ReLU()
            self.c2 = sparse.nn.SubmConv3D(8, 4, 3, padding=1)
            self.head = pt.nn.Linear(4, 1)

        def forward(self, x):
            h = self.act(self.bn(self.c1(x)))
            h = self.c2(h)
            pooled = h.values().mean(axis=0)     # global mean over sites
            return self.head(pooled)

    net = Net()
    opt = pt.optimizer.Adam(learning_rate=0.01,
                            parameters=net.parameters())
    shape = (1, 4, 4, 4, 2)
    x, _ = _rand_sparse(rng, shape, density=0.4, channels=2)
    target = pt.to_tensor(np.array([0.7], np.float32))
    losses = []
    for _ in range(30):
        y = net(x)
        loss = ((y - target) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.1, losses[::10]


def test_sparse_softmax_and_attention():
    rng = np.random.RandomState(6)
    # csr softmax rows sum to 1
    dense = (rng.rand(4, 6) * (rng.rand(4, 6) < 0.5)).astype(np.float32)
    idx = np.stack(np.nonzero(dense), 0)
    coo = sparse.sparse_coo_tensor(idx, dense[tuple(idx)], dense.shape)
    sm = sparse.nn.functional.softmax(coo.to_sparse_csr())
    v = np.asarray(sm.values().numpy())
    crows = np.asarray(sm.crows().numpy())
    for r in range(4):
        seg = v[crows[r]:crows[r + 1]]
        if len(seg):
            np.testing.assert_allclose(seg.sum(), 1.0, rtol=1e-5)
    # sparse-mask attention == dense attention when the mask is causal-full
    B, H, S, D = 1, 2, 4, 8
    q = pt.to_tensor(rng.randn(B, H, S, D).astype(np.float32))
    k = pt.to_tensor(rng.randn(B, H, S, D).astype(np.float32))
    vv = pt.to_tensor(rng.randn(B, H, S, D).astype(np.float32))
    tri = np.tril(np.ones((S, S), np.float32))
    full = np.broadcast_to(tri, (B * H, S, S))
    idx3 = np.stack(np.nonzero(full), 0)
    mask = sparse.sparse_coo_tensor(idx3, full[tuple(idx3)],
                                    full.shape).to_sparse_csr()
    out = sparse.nn.functional.attention(q, k, vv, mask)
    qa, ka, va = (np.asarray(t.numpy()) for t in (q, k, vv))
    s = np.einsum("bhid,bhjd->bhij", qa, ka) / np.sqrt(D)
    s = np.where(tri > 0, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bhij,bhjd->bhid", p, va)
    np.testing.assert_allclose(np.asarray(out.numpy()), ref, rtol=1e-4,
                               atol=1e-5)


def test_sparse_softmax_keeps_gradient():
    """COO->CSR->COO conversions must not detach the tape: softmax between
    sparse layers trains."""
    rng = np.random.RandomState(8)
    dense = (rng.rand(4, 6) * (rng.rand(4, 6) < 0.6)).astype(np.float32)
    idx = np.stack(np.nonzero(dense), 0)
    vals = pt.to_tensor(dense[tuple(idx)])
    vals.stop_gradient = False
    coo = sparse.sparse_coo_tensor(idx, vals, dense.shape,
                                   stop_gradient=False)
    out = sparse.nn.functional.softmax(coo)
    (out.values() ** 2).sum().backward()
    assert vals.grad is not None
    assert float(np.abs(np.asarray(vals.grad._data)).sum()) > 0
