"""Top-level API parity surface (reference python/paddle/__init__.py
__all__ — 434 names, all present; this exercises the round-2 additions)."""
import re

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def test_reference_top_level_all_covered():
    import os
    ref = "/root/reference/python/paddle/__init__.py"
    if not os.path.exists(ref):
        pytest.skip("reference checkout not present")
    src = open(ref).read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    names = re.findall(r"'([A-Za-z0-9_]+)'", m.group(1))
    missing = [n for n in names if not hasattr(paddle, n)]
    assert not missing, f"missing top-level names: {missing}"


def test_constants_and_dtype_info():
    assert paddle.pi == np.pi and paddle.inf == float("inf")
    assert paddle.newaxis is None and np.isnan(paddle.nan)
    fi = paddle.finfo(paddle.bfloat16)
    assert fi.bits == 16 and fi.max > 3e38
    assert paddle.iinfo("int32").max == 2 ** 31 - 1
    assert paddle.dtype("float32") == paddle.dtype(np.float32)
    assert (paddle.dtype("float32") == object()) is False   # no TypeError


def test_stack_variants_and_cartesian():
    a = paddle.to_tensor(np.ones((2, 3), np.float32))
    b = paddle.to_tensor(np.zeros((2, 3), np.float32))
    assert paddle.hstack([a, b]).shape == [2, 6]
    assert paddle.vstack([a, b]).shape == [4, 3]
    assert paddle.row_stack([a, b]).shape == [4, 3]
    assert paddle.dstack([a, b]).shape == [2, 3, 2]
    c = paddle.column_stack([paddle.to_tensor(np.ones(4, np.float32)),
                             paddle.to_tensor(np.zeros(4, np.float32))])
    assert c.shape == [4, 2]
    cp = paddle.cartesian_prod([paddle.to_tensor(np.arange(3)),
                                paddle.to_tensor(np.arange(2))])
    assert cp.shape == [6, 2]
    single = paddle.cartesian_prod([paddle.to_tensor(np.arange(3))])
    assert single.shape == [3]        # 1-D for a single input (reference)


def test_module_level_inplace_forms():
    t = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
    r = paddle.abs_(t)
    assert r is t
    np.testing.assert_allclose(np.asarray(t._data), [1.0, 2.0])
    paddle.tanh_(t)
    np.testing.assert_allclose(np.asarray(t._data), np.tanh([1.0, 2.0]),
                               rtol=1e-6)


def test_dlpack_roundtrip():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = paddle.from_dlpack(paddle.to_dlpack(x))
    np.testing.assert_array_equal(np.asarray(y._data), np.asarray(x._data))
    z = paddle.from_dlpack(np.arange(4).reshape(2, 2))   # __dlpack__ object
    assert z.shape == [2, 2]


def test_shape_numel_tolist_crop_positive():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert np.asarray(paddle.shape(x)._data).tolist() == [2, 3]
    assert int(np.asarray(paddle.numel(x)._data)) == 6
    assert paddle.tolist(x) == [[0.0, 1.0, 2.0], [3.0, 4.0, 5.0]]
    c = paddle.crop(x, shape=[1, 2], offsets=[1, 1])
    np.testing.assert_allclose(np.asarray(c._data), [[4.0, 5.0]])
    p = paddle.positive(x)
    np.testing.assert_array_equal(np.asarray(p._data), np.asarray(x._data))
    with pytest.raises(TypeError):
        paddle.positive(paddle.to_tensor(np.array([True])))


def test_standard_gamma_statistics():
    paddle.seed(0)
    g = paddle.standard_gamma(paddle.to_tensor(np.full((2000,), 2.0, np.float32)))
    arr = np.asarray(g._data)
    assert (arr > 0).all() and abs(arr.mean() - 2.0) < 0.15


def test_batch_decorator_and_misc():
    def reader():
        yield from range(7)
    assert list(paddle.batch(reader, 3)()) == [[0, 1, 2], [3, 4, 5], [6]]
    assert list(paddle.batch(reader, 3, drop_last=True)()) == [[0, 1, 2], [3, 4, 5]]
    paddle.check_shape([2, -1, None])
    with pytest.raises(ValueError):
        paddle.check_shape([2, -3])
    with paddle.LazyGuard():
        lin = nn.Linear(4, 4, weight_attr=paddle.ParamAttr(name="w0"))
    assert lin.weight.name == "w0"
    place = paddle.CUDAPlace(0)       # resolves to the default accelerator
    assert place.device is not None
    with pytest.raises(TypeError):
        paddle.pstring()
