"""Lease-based membership over TCPStore: CAS/delete store primitives, the
register/renew/expire/release lifecycle, epoch bumps across restarts, the
heartbeat thread, fault injection, and the membership metric families.

Everything runs against the pure-Python store server (the native daemon is
once-per-process; its protocol parity is covered in test_native_store.py)
with an injectable clock, so every expiry in here is a clock assignment,
never a sleep."""
import pickle
import threading
import time

import pytest

from paddle_tpu.core.retry import RetryPolicy
from paddle_tpu.distributed.membership import (EXPIRE, JOIN, LEAVE,
                                               LeaseLostError,
                                               MembershipService)
from paddle_tpu.distributed.store import StoreKeyDeleted, TCPStore
from paddle_tpu.testing import FAULTS, Always, FailNth


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture()
def store(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
    master = TCPStore(is_master=True, timeout=20)
    yield master


class _Clock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t


def _service(store, clock, group="g", ttl=2.0, attempts=2):
    return MembershipService(
        store, group=group, ttl=ttl, clock=clock,
        retry_policy=RetryPolicy(max_attempts=attempts, base_delay=0.0,
                                 max_delay=0.0))


# ---------------------------------------------------- store lease primitives

class TestStorePrimitives:
    def test_cas_expect_absent_then_token_swap(self, store):
        ok, cur = store.compare_and_set("k", None, {"v": 1})
        assert ok and pickle.loads(cur) == {"v": 1}
        raw = store.get_raw("k")
        ok, _ = store.compare_and_set("k", b"not-the-token", {"v": 2})
        assert not ok and store.get("k") == {"v": 1}
        ok, _ = store.compare_and_set("k", raw, {"v": 2})
        assert ok and store.get("k") == {"v": 2}

    def test_cas_expect_absent_fails_on_existing(self, store):
        store.set("k", 1)
        ok, cur = store.compare_and_set("k", None, 2)
        assert not ok and pickle.loads(cur) == 1

    def test_cas_rejects_non_bytes_expected(self, store):
        with pytest.raises(TypeError):
            store.compare_and_set("k", {"v": 1}, {"v": 2})
        with pytest.raises(ValueError):
            store.compare_and_set("k", b"", {"v": 2})

    def test_cas_loop_under_contention(self, store):
        # two clients CAS-appending concurrently must not lose updates
        other = TCPStore(port=store.port, timeout=20)

        def add(client, items):
            for it in items:
                while True:
                    try:
                        raw = client.get_raw("set", timeout=0.05)
                    except (TimeoutError, StoreKeyDeleted):
                        raw = None
                    cur = set(pickle.loads(raw)) if raw else set()
                    if client.compare_and_set("set", raw,
                                              sorted(cur | {it}))[0]:
                        break

        t = threading.Thread(target=add, args=(other, range(0, 10)))
        t.start()
        add(store, range(10, 20))
        t.join(30)
        assert set(store.get("set")) == set(range(20))

    def test_delete_mid_wait_is_typed(self, store):
        res = {}

        def blocked():
            try:
                store2 = TCPStore(port=store.port, timeout=20)
                store2.get("dw", timeout=10)
                res["r"] = "value"
            except StoreKeyDeleted as e:
                res["r"] = ("deleted", e.key)
            except TimeoutError:
                res["r"] = "timeout"

        t = threading.Thread(target=blocked)
        t.start()
        time.sleep(0.3)
        store.delete_key("dw")
        t.join(15)
        assert res.get("r") == ("deleted", "dw")

    def test_absent_key_still_times_out(self, store):
        with pytest.raises(TimeoutError):
            store.get("never", timeout=0.1)
        with pytest.raises(TimeoutError):
            store.get_raw("never", timeout=0.1)


# ------------------------------------------------------- lease lifecycle

class TestMembershipLifecycle:
    def test_join_events_and_members(self, store):
        clock = _Clock()
        svc = _service(store, clock)
        w = svc.watch()
        a = svc.register("a", meta={"port": 1})
        svc.register("b", meta={"port": 2})
        evs = w.poll()
        assert [(e.kind, e.member.name) for e in evs] == [
            (JOIN, "a"), (JOIN, "b")]
        assert a.epoch == 1
        assert set(svc.members()) == {"a", "b"}
        assert svc.members()["a"].meta == {"port": 1}
        assert w.poll() == []                       # steady state is quiet

    def test_renew_extends_and_expiry_reaps(self, store):
        clock = _Clock()
        svc = _service(store, clock, ttl=2.0)
        w = svc.watch()
        a = svc.register("a")
        svc.register("b")
        w.poll()
        clock.t += 1.5
        a.renew()                                    # a now expires at +3.5
        assert w.poll() == []
        clock.t += 1.0                               # b's lease (+2.0) lapsed
        evs = w.poll()
        assert [(e.kind, e.member.name) for e in evs] == [(EXPIRE, "b")]
        assert set(w.members()) == {"a"}
        assert set(svc.members()) == {"a"}           # record reaped

    def test_release_emits_leave_immediately(self, store):
        clock = _Clock()
        svc = _service(store, clock)
        w = svc.watch()
        a = svc.register("a")
        w.poll()
        a.release()
        evs = w.poll()
        assert [(e.kind, e.member.name) for e in evs] == [(LEAVE, "a")]
        a.release()                                  # idempotent

    def test_reregistration_bumps_epoch(self, store):
        clock = _Clock()
        svc = _service(store, clock, ttl=1.0)
        w = svc.watch()
        first = svc.register("a")
        w.poll()
        clock.t += 5                                 # die unrenewed
        assert [e.kind for e in w.poll()] == [EXPIRE]
        second = svc.register("a")
        assert second.epoch == first.epoch + 1
        evs = w.poll()
        assert [(e.kind, e.member.epoch) for e in evs] == [(JOIN, 2)]

    def test_epoch_bump_visible_without_expiry_gap(self, store):
        # watcher that never saw the death still reports the respawn as a
        # join (epoch changed under the same name)
        clock = _Clock()
        svc = _service(store, clock, ttl=10.0)
        w = svc.watch()
        svc.register("a")
        w.poll()
        svc.register("a")                            # new incarnation
        evs = w.poll()
        assert [(e.kind, e.member.epoch) for e in evs] == [(JOIN, 2)]

    def test_fresh_watcher_sees_current_members_as_joins(self, store):
        clock = _Clock()
        svc = _service(store, clock)
        svc.register("a")
        svc.register("b")
        evs = svc.watch().poll()
        assert [(e.kind, e.member.name) for e in evs] == [
            (JOIN, "a"), (JOIN, "b")]


# ------------------------------------------------------ heartbeat + faults

class TestHeartbeatAndFaults:
    def test_register_fault_point(self, store):
        svc = _service(store, _Clock())
        FAULTS.install("membership.register", Always())
        with pytest.raises(Exception):
            svc.register("a")
        FAULTS.reset()
        svc.register("a")                            # recovers once disarmed

    def test_renew_retries_through_transient_fault(self, store):
        svc = _service(store, _Clock(), attempts=3)
        lease = svc.register("a")
        FAULTS.install("membership.heartbeat", FailNth(1))
        lease.renew()                                # attempt 2 succeeds
        assert not lease.lost

    def test_renew_exhaustion_marks_lease_lost(self, store):
        svc = _service(store, _Clock(), attempts=2)
        lease = svc.register("a")
        FAULTS.install("membership.heartbeat", Always())
        with pytest.raises(LeaseLostError):
            lease.renew()
        assert lease.lost

    def test_heartbeat_thread_keeps_lease_alive(self, store):
        # wall-clock service (real renewals) with a tight ttl: the thread
        # must keep the member alive across several ttl windows
        svc = MembershipService(store, group="hb", ttl=0.4)
        w = svc.watch()
        lease = svc.register("a")
        lease.start_heartbeat(interval=0.05)
        try:
            time.sleep(1.0)
            assert [e.kind for e in w.poll()] in ([JOIN], [])
            assert set(w.members() or svc.members()) == {"a"}
            assert not lease.lost
        finally:
            lease.release()

    def test_heartbeat_thread_reports_loss(self, store):
        lost = []
        svc = MembershipService(
            store, group="hb2", ttl=0.4,
            retry_policy=RetryPolicy(max_attempts=2, base_delay=0.0,
                                     max_delay=0.0))
        lease = svc.register("a")
        FAULTS.install("membership.heartbeat", Always())
        lease.start_heartbeat(interval=0.05, on_lost=lost.append)
        deadline = time.monotonic() + 10
        while not lost and time.monotonic() < deadline:
            time.sleep(0.02)
        lease.stop_heartbeat()
        assert lost and isinstance(lost[0], LeaseLostError)
        assert lease.lost


# --------------------------------------------------------------- metrics

class TestMembershipMetrics:
    def test_expiry_and_event_counters_render(self, store):
        import paddle_tpu.observability as obs
        obs.enable()
        try:
            clock = _Clock()
            svc = _service(store, clock, group="mg", ttl=1.0)
            w = svc.watch()
            lease = svc.register("a")
            w.poll()
            lease.renew()                            # histogram sample
            clock.t += 50
            w.poll()                                 # expire
            text = obs.render_prometheus()
            assert 'membership_lease_expiries_total{group="mg"} 1' in text
            assert 'membership_events_total{group="mg",kind="join"} 1' in text
            assert ('membership_events_total{group="mg",kind="expire"} 1'
                    in text)
            assert 'membership_heartbeat_seconds_count{group="mg"} 1' in text
        finally:
            obs.disable()
            obs.reset()
