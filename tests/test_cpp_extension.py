"""Custom C++ op extension: compile, dispatch, autograd, jit capture."""
import numpy as np
import pytest

import paddle_tpu as paddle

SRC = r"""
#include <cstdint>
#include <cmath>

extern "C" void relu6(const float* x, int64_t n, float* out) {
  for (int64_t i = 0; i < n; ++i) {
    float v = x[i] < 0 ? 0 : x[i];
    out[i] = v > 6 ? 6 : v;
  }
}

extern "C" void relu6_grad(const float* x, const float* gout, int64_t n,
                           float* gx) {
  for (int64_t i = 0; i < n; ++i)
    gx[i] = (x[i] > 0 && x[i] < 6) ? gout[i] : 0;
}

extern "C" void cube(const float* x, int64_t n, float* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = x[i] * x[i] * x[i];
}
"""


@pytest.fixture(scope="module")
def ext(tmp_path_factory):
    from paddle_tpu.utils.cpp_extension import load
    src = tmp_path_factory.mktemp("ext") / "my_ops.cc"
    src.write_text(SRC)
    try:
        return load("my_ops", [str(src)])
    except RuntimeError as e:
        pytest.skip(f"no toolchain: {e}")


class TestCppExtension:
    def test_forward_matches_numpy(self, ext):
        x = np.array([-2.0, 0.5, 3.0, 9.0], np.float32)
        out = ext.relu6(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out, np.clip(x, 0, 6))
        out3 = ext.cube(paddle.to_tensor(x)).numpy()
        np.testing.assert_allclose(out3, x ** 3, rtol=1e-6)

    def test_declared_gradient_flows(self, ext):
        x = paddle.to_tensor(np.array([-1.0, 2.0, 7.0], np.float32),
                             stop_gradient=False)
        ext.relu6(x).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), [0, 1, 0])

    def test_works_under_jit_capture(self, ext):
        lin = paddle.nn.Linear(4, 4)

        def step(x):
            return ext.relu6(lin(x)).mean()

        sstep = paddle.jit.to_static(step)
        xv = np.random.RandomState(0).rand(2, 4).astype(np.float32)
        eager = float(step(paddle.to_tensor(xv)))
        sstep(paddle.to_tensor(xv))
        compiled = float(sstep(paddle.to_tensor(xv)))
        np.testing.assert_allclose(compiled, eager, rtol=1e-6)

    def test_op_listing(self, ext):
        assert set(ext.op_names()) == {"relu6", "cube"}
