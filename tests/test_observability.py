"""Unified runtime telemetry (ISSUE 4): metrics registry semantics, span
tracing, profiler scheduler/export edge cases, and the serving-engine
instrumentation — including parity between ``prefix_cache_stats()`` and the
registry after a real cached-serve run.

The registry is process-global; every test that flips the switch uses the
``metrics`` fixture so the suite always leaves telemetry disabled and the
series zeroed (reset keeps bound children valid by design).
"""
import os
import re
import threading

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import observability as obs
from paddle_tpu.observability.registry import REGISTRY


@pytest.fixture
def metrics():
    obs.reset()
    obs.enable()
    yield obs
    obs.disable()
    obs.reset()


# ------------------------------------------------------------------- registry

class TestRegistry:
    def test_disabled_mutations_are_noops(self):
        c = REGISTRY.counter("test_noop_total", "t")
        obs.disable()
        c.inc()
        c.labels().inc(5)
        assert c.labels().value == 0.0

    def test_counter_accumulates_and_rejects_negative(self, metrics):
        c = REGISTRY.counter("test_counter_total", "t")
        c.inc()
        c.inc(2)
        assert c.labels().value == 3.0
        with pytest.raises(ValueError):
            c.labels().inc(-1)

    def test_label_set_isolation(self, metrics):
        c = REGISTRY.counter("test_labels_total", "t", ("op", "kind"))
        c.inc(op="add", kind="a")
        c.inc(3, op="add", kind="b")
        c.inc(op="mul", kind="a")
        assert c.labels(op="add", kind="a").value == 1.0
        assert c.labels(op="add", kind="b").value == 3.0
        assert c.labels(op="mul", kind="a").value == 1.0
        # children are memoized: same label values -> same object
        assert c.labels(op="add", kind="a") is c.labels(op="add", kind="a")
        with pytest.raises(ValueError):
            c.labels(op="add")                      # missing label
        with pytest.raises(ValueError):
            # deliberate type conflict: asserts the registry rejects it
            REGISTRY.gauge("test_labels_total")  # graftlint: disable=contracts

    def test_gauge_set_inc_dec(self, metrics):
        g = REGISTRY.gauge("test_gauge", "t")
        g.set(7)
        g.labels().inc(2)
        g.labels().dec()
        assert g.labels().value == 8.0

    def test_histogram_bucket_boundaries_le_inclusive(self, metrics):
        h = REGISTRY.histogram("test_hist_seconds", "t", buckets=(1.0, 2.0, 5.0))
        child = h.labels()
        for v in (0.5, 1.0, 1.5, 2.0, 2.5, 100.0):
            child.observe(v)
        d = child._data()
        # exact bound values land in their own bucket (le is inclusive)
        assert d["buckets"] == {"1": 2, "2": 2, "5": 1, "+Inf": 1}
        assert d["count"] == 6
        assert d["sum"] == pytest.approx(107.5)

    def test_histogram_rejects_bad_buckets(self):
        with pytest.raises(ValueError):
            REGISTRY.histogram("test_bad_hist", "t", buckets=(1.0, 1.0))
        with pytest.raises(ValueError):
            REGISTRY.histogram("test_empty_hist", "t", buckets=())

    def test_concurrent_increments_from_threads(self, metrics):
        c = REGISTRY.counter("test_threads_total", "t")
        child = c.labels()
        N, M = 8, 2000

        def work():
            for _ in range(M):
                child.inc()

        threads = [threading.Thread(target=work) for _ in range(N)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert child.value == N * M

    def test_reset_keeps_bound_children_valid(self, metrics):
        c = REGISTRY.counter("test_reset_total", "t")
        child = c.labels()
        child.inc(4)
        obs.reset()
        assert child.value == 0.0
        child.inc()                      # the same handle still feeds the family
        assert c.labels().value == 1.0

    def test_snapshot_filters(self, metrics):
        c = REGISTRY.counter("test_snap_total", "t", ("engine",))
        c.inc(engine="0")
        c.inc(engine="1")
        snap = obs.snapshot(prefix="test_snap", labels={"engine": "1"})
        assert list(snap) == ["test_snap_total"]
        assert snap["test_snap_total"]["series"] == [
            {"labels": {"engine": "1"}, "value": 1.0}]
        assert "test_snap_total" not in obs.snapshot(prefix="serving_")


# ------------------------------------------------- Prometheus text exposition

_LABEL_VAL = r'"(?:[^"\\]|\\.)*"'                      # allows \" and \\ escapes
_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"                       # metric name
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=" + _LABEL_VAL +       # first label
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=" + _LABEL_VAL + r")*\})?"  # more labels
    r" (\+Inf|-?[0-9]+(\.[0-9]+)?(e[+-]?[0-9]+)?)$")


def _assert_valid_exposition(text):
    """Minimal 0.0.4 exposition validator: every line is a HELP/TYPE comment
    or a sample; TYPE precedes its samples; histograms are cumulative and end
    at +Inf == _count."""
    typed = {}
    samples = []
    for line in text.splitlines():
        if line.startswith("# HELP "):
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            assert kind in ("counter", "gauge", "histogram"), line
            typed[name] = kind
            continue
        assert _METRIC_LINE.match(line), f"bad exposition line: {line!r}"
        samples.append(line)
    for line in samples:
        name = re.split(r"[{ ]", line, 1)[0]
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        assert name in typed or base in typed, f"sample without TYPE: {line!r}"
    return typed, samples


class TestPrometheus:
    def test_render_parses_as_valid_exposition(self, metrics):
        c = REGISTRY.counter("test_expo_total", "with label", ("op",))
        c.inc(op='weird"val\\ue')        # label escaping exercised
        h = REGISTRY.histogram("test_expo_seconds", "hist", buckets=(0.1, 1.0))
        h.observe(0.05)
        h.observe(0.5)
        h.observe(3.0)
        text = obs.render_prometheus()
        typed, samples = _assert_valid_exposition(text)
        assert typed["test_expo_total"] == "counter"
        assert typed["test_expo_seconds"] == "histogram"
        # histogram buckets are CUMULATIVE and close at +Inf == _count
        buckets = [l for l in samples if l.startswith("test_expo_seconds_bucket")]
        counts = [float(l.rsplit(" ", 1)[1]) for l in buckets]
        assert counts == sorted(counts) and counts[-1] == 3
        assert 'le="+Inf"' in buckets[-1]
        assert any(l.startswith("test_expo_seconds_count") and
                   l.endswith(" 3") for l in samples)

    def test_snapshot_prometheus_round_trip(self, metrics):
        c = REGISTRY.counter("test_round_total", "t", ("k",))
        c.inc(41, k="x")
        c.inc(k="x")
        snap = obs.snapshot(prefix="test_round_total")
        assert snap["test_round_total"]["series"][0]["value"] == 42.0
        assert 'test_round_total{k="x"} 42' in obs.render_prometheus()

    def test_label_value_escaping(self, metrics):
        # backslash, double quote and newline each escape per the 0.0.4 spec:
        # \ -> \\   " -> \"   LF -> \n  (two characters, not a raw newline)
        c = REGISTRY.counter("test_esc_total", "t", ("v",))
        c.inc(v='back\\slash "quote"\nline2')
        text = obs.render_prometheus()
        _assert_valid_exposition(text)
        line = next(l for l in text.splitlines()
                    if l.startswith("test_esc_total{"))
        assert line == 'test_esc_total{v="back\\\\slash \\"quote\\"\\nline2"} 1'

    def test_help_text_escaping(self, metrics):
        # HELP escapes only backslash and newline; a raw newline would split
        # the comment and leave a line the scraper rejects
        REGISTRY.counter("test_help_total", 'path C:\\tmp\nsecond line')
        text = obs.render_prometheus()
        _assert_valid_exposition(text)
        help_line = next(l for l in text.splitlines()
                         if l.startswith("# HELP test_help_total"))
        assert help_line == ("# HELP test_help_total "
                             "path C:\\\\tmp\\nsecond line")

    def test_histogram_inf_sum_count_framing(self, metrics):
        h = REGISTRY.histogram("test_frame_seconds", "h", buckets=(0.1, 1.0),
                               labelnames=("op",))
        h.observe(0.1, op="a")          # boundary lands IN the 0.1 bucket
        h.observe(7.0, op="a")          # beyond the last bound -> +Inf only
        text = obs.render_prometheus()
        typed, samples = _assert_valid_exposition(text)
        assert typed["test_frame_seconds"] == "histogram"
        frame = [l for l in samples if l.startswith("test_frame_seconds")]
        # exactly the spec framing: every bound plus +Inf, then _sum, _count
        assert [l.split(" ")[0] for l in frame] == [
            'test_frame_seconds_bucket{op="a",le="0.1"}',
            'test_frame_seconds_bucket{op="a",le="1"}',
            'test_frame_seconds_bucket{op="a",le="+Inf"}',
            'test_frame_seconds_sum{op="a"}',
            'test_frame_seconds_count{op="a"}',
        ]
        counts = {l.split(" ")[0]: l.rsplit(" ", 1)[1] for l in frame}
        assert counts['test_frame_seconds_bucket{op="a",le="0.1"}'] == "1"
        assert counts['test_frame_seconds_bucket{op="a",le="+Inf"}'] == "2"
        assert counts['test_frame_seconds_count{op="a"}'] == "2"
        assert float(counts['test_frame_seconds_sum{op="a"}']) == 7.1


# ------------------------------------------------- federated snapshot merging

class TestFederation:
    def _remote(self, value=3.0, labels=None, type="counter"):
        return {"test_fed_total": {
            "type": type, "help": "t",
            "series": [{"labels": dict(labels or {"op": "x"}),
                        "value": value}]}}

    def test_merge_relabels_remote_series(self, metrics):
        c = REGISTRY.counter("test_fed_total", "t", ("op",))
        c.inc(op="x")
        merged = obs.merge_snapshots(obs.snapshot(prefix="test_fed"),
                                     {"w0": self._remote(3.0)})
        series = merged["test_fed_total"]["series"]
        # local series untouched, remote series gains replica=<name>
        assert {"labels": {"op": "x"}, "value": 1.0} in series
        assert {"labels": {"op": "x", "replica": "w0"}, "value": 3.0} in series
        text = obs.render_snapshot(merged)
        _assert_valid_exposition(text)
        assert 'test_fed_total{op="x",replica="w0"} 3' in text

    def test_merge_keeps_existing_replica_label(self, metrics):
        # front-door families already attribute a replica; federation must
        # not overwrite the worker's own attribution
        merged = obs.merge_snapshots(
            {}, {"w0": self._remote(2.0, {"op": "x", "replica": "inner"})})
        assert merged["test_fed_total"]["series"] == [
            {"labels": {"op": "x", "replica": "inner"}, "value": 2.0}]

    def test_merge_skips_type_conflicts(self, metrics):
        c = REGISTRY.counter("test_fed_total", "t", ("op",))
        c.inc(op="x")
        merged = obs.merge_snapshots(
            obs.snapshot(prefix="test_fed"),
            {"w0": self._remote(9.0, type="gauge"),
             "w1": self._remote(5.0)})
        # w0's gauge family conflicts with the local counter and is dropped;
        # w1's matching counter merges — and the result still renders clean
        values = {s["labels"].get("replica"): s["value"]
                  for s in merged["test_fed_total"]["series"]}
        assert values == {None: 1.0, "w1": 5.0}
        _assert_valid_exposition(obs.render_snapshot(merged))

    def test_merge_of_disjoint_remote_histogram(self, metrics):
        snap = {"test_fedh_seconds": {
            "type": "histogram", "help": "h",
            "series": [{"labels": {}, "buckets": {"0.1": 1, "+Inf": 1},
                        "sum": 2.5, "count": 2}]}}
        merged = obs.merge_snapshots({}, {"w0": snap})
        text = obs.render_snapshot(merged)
        typed, samples = _assert_valid_exposition(text)
        assert typed["test_fedh_seconds"] == "histogram"
        assert ('test_fedh_seconds_bucket{replica="w0",le="+Inf"} 2'
                in samples)


# ------------------------------------------------------ pull endpoint (HTTP)

class TestMetricsServer:
    def test_scrape_returns_current_exposition(self, metrics):
        import urllib.request
        c = REGISTRY.counter("test_scrape_total", "t")
        c.inc(5)
        with obs.start_metrics_server(port=0) as server:
            assert server.url.endswith(f":{server.port}/metrics")
            with urllib.request.urlopen(server.url, timeout=5) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/plain")
                body = resp.read().decode("utf-8")
            typed, _ = _assert_valid_exposition(body)
            assert typed["test_scrape_total"] == "counter"
            assert "test_scrape_total 5" in body
            # scrapes render live state, not a startup snapshot
            c.inc(2)
            with urllib.request.urlopen(server.url, timeout=5) as resp:
                assert "test_scrape_total 7" in resp.read().decode("utf-8")

    def test_unknown_path_is_404_and_close_releases_port(self, metrics):
        import urllib.error
        import urllib.request
        server = obs.start_metrics_server(port=0)
        url = f"http://{server.addr}:{server.port}/nope"
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(url, timeout=5)
        assert e.value.code == 404
        server.close()
        with pytest.raises(OSError):
            urllib.request.urlopen(server.url, timeout=1)


# ---------------------------------------------------------- dispatch recorder

class TestDispatch:
    def test_disabled_leaves_hot_path_bare(self):
        from paddle_tpu.core import dispatch
        obs.disable()
        assert dispatch.metrics_recorder() is None
        assert dispatch._state.op_recorder is None

    def test_dispatch_counts_and_seconds(self, metrics):
        x = pt.tensor([1.0, 2.0])
        (x * 3).sum()
        snap = obs.snapshot(prefix="dispatch_ops_total")
        ops = {s["labels"]["op"]: s["value"]
               for s in snap["dispatch_ops_total"]["series"]}
        assert ops.get("multiply", 0) >= 1 and ops.get("sum", 0) >= 1
        hist = obs.snapshot(prefix="dispatch_host_seconds")
        assert hist["dispatch_host_seconds"]["series"][0]["count"] >= 2

    def test_taped_dispatches_counted(self, metrics):
        x = pt.tensor([1.0, 2.0], stop_gradient=False)
        (x * x).sum()
        snap = obs.snapshot(prefix="dispatch_taped_total")
        assert snap["dispatch_taped_total"]["series"][0]["value"] >= 2

    def test_profiler_and_metrics_recorders_compose(self, metrics):
        from paddle_tpu.core import dispatch
        from paddle_tpu import profiler
        p = profiler.Profiler(timer_only=True)
        p.start()
        try:
            assert isinstance(dispatch._state.op_recorder,
                              dispatch._FanoutRecorder)
            pt.tensor([1.0]) + 1.0
        finally:
            p.stop()
        # profiler saw the op AND the registry counted it
        assert p._op_recorder.ops
        snap = obs.snapshot(prefix="dispatch_ops_total")
        assert snap["dispatch_ops_total"]["series"]
        # stop() restored the bare metrics recorder, not None
        assert dispatch._state.op_recorder is dispatch.metrics_recorder()


# ----------------------------------------------------------------- trace_span

class TestTraceSpan:
    def test_span_records_host_event_and_histogram(self, metrics):
        from paddle_tpu.profiler import _host_events
        _host_events.pop("test.span", None)
        with obs.trace_span("test.span"):
            pass
        assert len(_host_events["test.span"]) == 1
        snap = obs.snapshot(prefix="span_seconds",
                            labels={"span": "test.span"})
        assert snap["span_seconds"]["series"][0]["count"] == 1

    def test_span_disabled_is_passthrough(self):
        from paddle_tpu.profiler import _host_events
        obs.disable()
        _host_events.pop("test.span.off", None)
        with obs.trace_span("test.span.off"):
            pass
        assert "test.span.off" not in _host_events


# ------------------------------------------------- profiler scheduler/export

class TestScheduler:
    def test_zero_cycle_never_divides(self):
        from paddle_tpu.profiler import ProfilerState, make_scheduler
        s = make_scheduler(closed=0, ready=0, record=0)
        for step in range(4):           # cycle == 0: no ZeroDivisionError
            assert s(step) in (ProfilerState.CLOSED, ProfilerState.RECORD)

    def test_repeat_boundary_exactly_at_cycle_times_repeat(self):
        from paddle_tpu.profiler import ProfilerState, make_scheduler
        s = make_scheduler(closed=1, ready=1, record=2, repeat=2)
        cycle = 4
        assert s(cycle * 2 - 1) == ProfilerState.RECORD_AND_RETURN
        assert s(cycle * 2) == ProfilerState.CLOSED       # exact boundary
        assert s(cycle * 2 + 5) == ProfilerState.CLOSED   # stays closed

    def test_skip_first_shifts_the_whole_schedule(self):
        from paddle_tpu.profiler import ProfilerState, make_scheduler
        s = make_scheduler(closed=1, ready=1, record=1, skip_first=3)
        assert [s(i) for i in range(3)] == [ProfilerState.CLOSED] * 3
        assert s(3) == ProfilerState.CLOSED     # pos 0 of the first cycle
        assert s(4) == ProfilerState.READY
        assert s(5) == ProfilerState.RECORD_AND_RETURN
        # skip_first + repeat: the repeat window starts after the skip
        s2 = make_scheduler(closed=0, ready=0, record=2, repeat=1,
                            skip_first=2)
        assert s2(1) == ProfilerState.CLOSED
        assert s2(2) == ProfilerState.RECORD
        assert s2(3) == ProfilerState.RECORD_AND_RETURN
        assert s2(4) == ProfilerState.CLOSED

    def test_record_and_return_only_on_last_record_step(self):
        from paddle_tpu.profiler import ProfilerState, make_scheduler
        s = make_scheduler(closed=1, ready=1, record=3)
        got = [s(i) for i in range(5)]
        assert got == [ProfilerState.CLOSED, ProfilerState.READY,
                       ProfilerState.RECORD, ProfilerState.RECORD,
                       ProfilerState.RECORD_AND_RETURN]
        assert got.count(ProfilerState.RECORD_AND_RETURN) == 1


class TestExportProtobuf:
    def _fake_xplane(self, root, run, name):
        d = root / "plugins" / "profile" / run
        d.mkdir(parents=True)
        p = d / name
        p.write_bytes(b"\x00fake-xplane")
        return str(p)

    def test_handler_selects_protobuf_format(self):
        from paddle_tpu import profiler
        prof = profiler.Profiler(timer_only=True)
        profiler.export_protobuf("/tmp/ptb")(prof)
        assert prof._export_dir == "/tmp/ptb"
        assert prof._export_format == "protobuf"

    def test_export_resolves_newest_xplane(self, tmp_path):
        from paddle_tpu import profiler
        prof = profiler.Profiler(timer_only=True)
        prof._dir = str(tmp_path)
        self._fake_xplane(tmp_path, "run_a", "host.xplane.pb")
        newest = self._fake_xplane(tmp_path, "run_b", "host.xplane.pb")
        assert prof.export(format="protobuf") == newest

    def test_export_falls_back_to_json_with_warning(self, tmp_path, caplog):
        from paddle_tpu import profiler
        prof = profiler.Profiler(
            timer_only=True,
            on_trace_ready=profiler.export_protobuf(str(tmp_path)))
        prof.start()
        prof.step()
        prof.stop()                       # handler arms protobuf format
        out = str(tmp_path / "trace.json")
        with caplog.at_level("WARNING", logger="paddle_tpu.profiler"):
            path = prof.export(out)
        assert path == out and os.path.exists(out)
        assert any("falling back" in r.message for r in caplog.records)


# --------------------------------------------------------- jit capture events

class TestJitEvents:
    def _events(self, fn_name):
        snap = obs.snapshot(prefix="jit_events_total",
                            labels={"fn": fn_name})
        return {s["labels"]["event"]: s["value"]
                for s in snap.get("jit_events_total", {}).get("series", [])}

    def test_capture_then_cache_hit(self, metrics):
        from paddle_tpu.jit import to_static

        @to_static
        def double_it(x):
            return x * 2.0

        x = pt.tensor([1.0, 2.0])
        double_it(x)
        assert self._events("double_it").get("capture") == 1
        double_it(x)
        ev = self._events("double_it")
        assert ev.get("capture") == 1 and ev.get("cache_hit") == 1


# -------------------------------------------------- serving engine telemetry

@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, **kw):
    from paddle_tpu.inference.serving import LLMEngine
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(model, **kw)


def _serve(eng, prompts, **req_kw):
    req_kw.setdefault("max_new_tokens", 6)
    outs = []
    for p in prompts:
        rid = eng.add_request(p, **req_kw)
        eng.run_until_done()
        outs.append(eng.result(rid))
    return outs


def _prompts(seed=0, n=2, shared=16, tail=5):
    rng = np.random.RandomState(seed)
    prefix = rng.randint(1, 128, (shared,)).astype(np.int32)
    return [np.concatenate([prefix,
                            rng.randint(1, 128, (tail,)).astype(np.int32)])
            for _ in range(n)]


class TestEngineMetrics:
    def test_metrics_view_after_real_served_batch(self, metrics, model):
        eng = _engine(model, prefix_cache=True)
        _serve(eng, _prompts(seed=3))
        m = eng.metrics()
        ttft = m["serving_ttft_seconds"]["series"]
        assert len(ttft) == 1 and ttft[0]["count"] == 2      # one per request
        assert m["serving_token_latency_seconds"]["series"][0]["count"] > 0
        kinds = {s["labels"]["kind"]: s["value"]
                 for s in m["serving_dispatches_total"]["series"]}
        assert kinds["prefill"] >= 2 and kinds["decode"] >= 1
        assert m["serving_generated_tokens_total"]["series"][0]["value"] == 12
        # gauges reflect the drained engine
        assert m["serving_queue_depth"]["series"][0]["value"] == 0
        assert m["serving_active_slots"]["series"][0]["value"] == 0
        assert m["serving_batch_occupancy_ratio"]["series"][0]["value"] == 0
        assert m["serving_free_pages"]["series"][0]["value"] > 0
        # every series carries this engine's label only
        for fam in m.values():
            for s in fam["series"]:
                assert s["labels"]["engine"] == eng._m.label

    def test_prefix_cache_stats_registry_parity(self, metrics, model):
        eng = _engine(model, prefix_cache=True)
        _serve(eng, _prompts(seed=4))
        st = eng.prefix_cache_stats()
        assert st["hits"] >= 2                   # the shared prefix was reused
        events = {s["labels"]["event"]: s["value"]
                  for s in eng.metrics()
                  ["serving_prefix_cache_events_total"]["series"]}
        assert events.get("hit", 0) == st["hits"]
        assert events.get("miss", 0) == st["misses"]
        assert events.get("eviction", 0) == st["evictions"]
        assert events.get("cow_copy", 0) == st["cow_copies"]
        m = eng.metrics()
        assert m["serving_prefix_cached_pages"]["series"][0]["value"] \
            == st["cached_pages"]
        assert m["serving_prefix_reclaimable_pages"]["series"][0]["value"] \
            == st["reclaimable_pages"]

    def test_stats_unchanged_with_metrics_disabled(self, model):
        obs.disable()
        obs.reset()
        eng = _engine(model, prefix_cache=True)
        _serve(eng, _prompts(seed=5))
        st = eng.prefix_cache_stats()
        assert st["hits"] >= 2 and st["prefill_dispatches"] > 0
        # the registry saw nothing: plain-int attrs are the always-on path
        snap = obs.snapshot(prefix="serving_",
                            labels={"engine": eng._m.label})
        for fam in snap.values():
            for s in fam["series"]:
                assert s.get("value", s.get("count", 0)) == 0

    def test_engine_render_prometheus_is_valid(self, metrics, model):
        eng = _engine(model, prefix_cache=True)
        _serve(eng, _prompts(seed=6, n=1))
        typed, samples = _assert_valid_exposition(obs.render_prometheus())
        assert typed["serving_ttft_seconds"] == "histogram"
        assert any(l.startswith("serving_dispatches_total{") for l in samples)
