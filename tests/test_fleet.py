"""Self-healing multi-process fleet: worker RPC, supervision, membership-fed
routing, and crash recovery.

Layers under test, bottom-up:

- the length-prefixed socket RPC (exception transport fidelity, fault
  points, pooled concurrency);
- the worker supervisor (bounded-backoff respawn, crash-loop quarantine) —
  pure units with fake process handles, fake clock, fake sleep;
- the fleet itself: thread-hosted :class:`WorkerServer`\\ s (identical code
  path to the subprocess entry, minus fork cost) behind a
  :class:`FleetReplicaSet` with an injectable clock — "kill -9" is closing
  a worker's RPC listener and step loop WITHOUT releasing its lease, which
  is exactly what the real signal leaves behind.  The deterministic chaos
  test asserts the ISSUE 10 acceptance row: survivors token-exact, the
  zero-token victim requeued once and completed elsewhere, the
  partially-streamed victim failed typed, the respawned worker re-registered
  under a new epoch within one lease TTL, and all three new metric families
  visible in ``render_prometheus()``.

The real-SIGKILL variant (actual subprocess workers, actual ``kill -9``)
is slow-marked and excluded from tier-1."""
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from paddle_tpu.distributed.store import TCPStore
from paddle_tpu.inference.engine.request import RequestStatus
from paddle_tpu.inference.frontend import ShedError
from paddle_tpu.inference.frontend.fleet import FleetReplicaSet, RemoteReplica
from paddle_tpu.inference.frontend.replica import ReplicaDeadError
from paddle_tpu.inference.frontend.router import RouteDecision
from paddle_tpu.inference.frontend.rpc import RpcClient, RpcError, RpcServer
from paddle_tpu.inference.frontend.supervisor import (QUARANTINED, RESPAWNED,
                                                      RUNNING,
                                                      WorkerSupervisor)
from paddle_tpu.inference.frontend.worker import WorkerServer
from paddle_tpu.testing import FAULTS, Always, FailNth, InjectedFault


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# ------------------------------------------------------------------ RPC layer

class TestRpc:
    def _server(self, handler):
        srv = RpcServer(handler)
        srv.start()
        return srv

    def test_roundtrip_and_kwargs(self):
        srv = self._server(lambda op, kw: (op, sorted(kw.items())))
        try:
            c = RpcClient(srv.host, srv.port)
            assert c.call("echo", a=1, b=[2, 3]) == ("echo",
                                                     [("a", 1), ("b", [2, 3])])
            c.close()
        finally:
            srv.close()

    def test_remote_exception_fidelity(self):
        def handler(op, kw):
            if op == "shed":
                raise ShedError("draining", retry_after=7.5)
            if op == "injected":
                raise InjectedFault("some.point", transient=True)
            raise KeyError(kw["k"])

        srv = self._server(handler)
        try:
            c = RpcClient(srv.host, srv.port)
            with pytest.raises(ShedError) as ei:
                c.call("shed")
            assert ei.value.reason == "draining"
            assert ei.value.retry_after == 7.5
            with pytest.raises(InjectedFault) as ei:
                c.call("injected")
            assert ei.value.point == "some.point" and ei.value.transient
            with pytest.raises(KeyError):
                c.call("missing", k="x")
            # the connection survives remote errors
            with pytest.raises(ShedError):
                c.call("shed")
            c.close()
        finally:
            srv.close()

    def test_unpicklable_remote_error_degrades(self):
        class Evil(RuntimeError):
            def __reduce__(self):
                raise TypeError("nope")

        srv = self._server(lambda op, kw: (_ for _ in ()).throw(Evil("boom")))
        try:
            c = RpcClient(srv.host, srv.port)
            with pytest.raises(RuntimeError, match="unpicklable"):
                c.call("x")
            c.close()
        finally:
            srv.close()

    def test_connect_failure_is_rpc_error(self):
        dead = RpcServer(lambda op, kw: None)
        port = dead.port
        dead.close()
        c = RpcClient("127.0.0.1", port, connect_timeout=0.5)
        with pytest.raises(RpcError):
            c.call("ping")

    def test_fault_points(self):
        srv = self._server(lambda op, kw: "pong")
        try:
            c = RpcClient(srv.host, srv.port)
            FAULTS.install("rpc.send", FailNth(1))
            with pytest.raises(InjectedFault):
                c.call("ping")
            assert c.call("ping") == "pong"          # next call recovers
            FAULTS.reset()
            FAULTS.install("rpc.recv", FailNth(1))
            with pytest.raises(InjectedFault):
                c.call("ping")
            FAULTS.reset()
            c.close()
        finally:
            srv.close()

    def test_concurrent_calls_do_not_serialize(self):
        gate = threading.Event()

        def handler(op, kw):
            if op == "slow":
                gate.wait(10)
            return op

        srv = self._server(handler)
        try:
            c = RpcClient(srv.host, srv.port)
            t = threading.Thread(target=c.call, args=("slow",), daemon=True)
            t.start()
            time.sleep(0.1)
            t0 = time.monotonic()
            assert c.call("fast") == "fast"          # separate pooled socket
            assert time.monotonic() - t0 < 5.0
            gate.set()
            t.join(10)
            c.close()
        finally:
            srv.close()


# --------------------------------------------------------------- supervisor

class _FakeProc:
    def __init__(self):
        self.rc = None
        self.terminated = False

    def poll(self):
        return self.rc

    def terminate(self):
        self.terminated = True
        self.rc = 0

    def kill(self):
        self.rc = -9

    def wait(self, timeout=None):
        return self.rc


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = float(t)

    def __call__(self):
        return self.t


class TestWorkerSupervisor:
    def _sup(self, clock=None, **kw):
        procs = []

        def spawn():
            p = _FakeProc()
            procs.append(p)
            return p

        sleeps = []
        sup = WorkerSupervisor(spawn, name="w", clock=clock or _FakeClock(),
                               sleep=sleeps.append, **kw)
        return sup, procs, sleeps

    def test_running_child_is_left_alone(self):
        sup, procs, _ = self._sup()
        sup.start_worker()
        assert sup.tick() == RUNNING
        assert len(procs) == 1

    def test_respawn_with_bounded_backoff(self):
        clock = _FakeClock()
        sup, procs, sleeps = self._sup(clock=clock, base_delay=0.1,
                                       multiplier=2.0, max_delay=0.3,
                                       max_crashes=10, crash_window=100.0)
        sup.start_worker()
        for expected in (0.1, 0.2, 0.3, 0.3):        # capped at max_delay
            procs[-1].rc = 1
            clock.t += 1
            assert sup.tick() == RESPAWNED
            assert sleeps[-1] == pytest.approx(expected)
        assert sup.restarts == 4
        assert len(procs) == 5

    def test_crash_loop_quarantines(self):
        clock = _FakeClock()
        alerts = []
        sup, procs, _ = self._sup(clock=clock, max_crashes=3,
                                  crash_window=10.0,
                                  on_quarantine=alerts.append)
        sup.on_quarantine = alerts.append
        sup.start_worker()
        for _ in range(2):
            procs[-1].rc = 1
            clock.t += 1
            assert sup.tick() == RESPAWNED
        procs[-1].rc = 1
        clock.t += 1
        assert sup.tick() == QUARANTINED
        assert sup.quarantined and alerts == [sup]
        assert sup.tick() == QUARANTINED             # stays down, no respawn
        assert len(procs) == 3

    def test_slow_crashes_outside_window_never_quarantine(self):
        clock = _FakeClock()
        sup, procs, _ = self._sup(clock=clock, max_crashes=3,
                                  crash_window=10.0)
        sup.start_worker()
        for _ in range(6):                            # one crash per 60s
            procs[-1].rc = 1
            clock.t += 60
            assert sup.tick() == RESPAWNED
        assert not sup.quarantined

    def test_reset_clears_quarantine(self):
        clock = _FakeClock()
        sup, procs, _ = self._sup(clock=clock, max_crashes=1)
        sup.start_worker()
        procs[-1].rc = 1
        assert sup.tick() == QUARANTINED
        sup.reset()
        assert sup.tick() == RESPAWNED

    def test_stop_terminates_child(self):
        sup, procs, _ = self._sup()
        sup.start_worker()
        sup.stop()
        assert procs[0].terminated
        assert sup.tick() == "stopped"

    def test_restart_metric_renders(self):
        import paddle_tpu.observability as obs
        obs.enable()
        try:
            clock = _FakeClock()
            sup, procs, _ = self._sup(clock=clock, max_crashes=5)
            sup.start_worker()
            procs[-1].rc = 1
            sup.tick()
            text = obs.render_prometheus()
            assert 'frontend_replica_restarts_total{replica="w"} 1' in text
        finally:
            obs.disable()
            obs.reset()


# ------------------------------------------- fleet end-to-end (tiny engines)

def _tiny_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _engine(model):
    from paddle_tpu.inference.serving import LLMEngine
    return LLMEngine(model, max_batch=3, max_len=64, page_size=8,
                     prefix_cache=True)


class _PinRouter:
    """Deterministic routing for chaos tests: always pick the pinned
    replica when it is in the candidate list, else the first candidate."""

    def __init__(self):
        self.pin = None

    def route(self, prompt_ids, replicas):
        rep = next((r for r in replicas if r.name == self.pin), replicas[0])
        return RouteDecision(rep, "pinned")

    def note_event(self, replica_name, event, key):
        pass

    def forget(self, name):
        pass


class _Fleet:
    """Test harness: a fake-clock store + N thread-hosted WorkerServers +
    one FleetReplicaSet.  kill() is SIGKILL-shaped: the worker's RPC
    listener and step loop vanish, its lease does not."""

    def __init__(self, model, n=2, ttl=5.0, group="fl"):
        self.model = model
        self.group = group
        self.ttl = ttl
        self.clock = _FakeClock(1000.0)
        self.master = TCPStore(is_master=True, timeout=20)
        self.workers = {}
        self.router = _PinRouter()
        self.fleet = FleetReplicaSet(self._store(), group=group, ttl=ttl,
                                     clock=self.clock, router=self.router)
        for i in range(n):
            self.spawn(f"w{i}")
        self.fleet.sync()

    def _store(self):
        return TCPStore(port=self.master.port, timeout=20)

    def spawn(self, name):
        w = WorkerServer(name, _engine(self.model), self._store(),
                         group=self.group, ttl=self.ttl, clock=self.clock)
        w.start(heartbeat=False)                     # tests renew by hand
        self.workers[name] = w
        return w

    def kill(self, name):
        w = self.workers.pop(name)
        w.rpc.close()
        w.replica.close()
        return w

    def renew_all(self):
        for w in self.workers.values():
            w.lease.renew()

    def close(self):
        self.fleet.close()
        for name in list(self.workers):
            self.workers[name].close(drain=False)


@pytest.fixture()
def fleet(model, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
    f = _Fleet(model, n=2)
    yield f
    f.close()


def _reference_tokens(model, prompt, n=6):
    eng = _engine(model)
    rid = eng.add_request(list(prompt), max_new_tokens=n, do_sample=False)
    eng.run_until_done()
    return list(eng.result(rid))


class TestFleetServing:
    def test_members_join_and_stream_token_exact(self, fleet, model):
        assert {r.name for r in fleet.fleet.alive_replicas()} == {"w0", "w1"}
        prompt = list(range(1, 17))
        ref = _reference_tokens(model, prompt)
        h = fleet.fleet.submit(prompt, max_new_tokens=6, do_sample=False)
        assert list(fleet.fleet.stream(h)) == ref
        assert fleet.fleet.status(h).terminal

    def test_worker_drain_sheds_typed_over_rpc(self, fleet):
        w = fleet.workers["w0"]
        w.draining = True
        rep = fleet.fleet.replica("w0")
        with pytest.raises(ShedError) as ei:
            rep.submit(list(range(16)), max_new_tokens=4)
        assert ei.value.reason == "draining"
        assert rep.alive                             # shed is not death

    def test_clean_release_emits_leave_not_expire(self, fleet):
        fleet.workers["w1"].lease.release()
        evs = fleet.fleet.sync()
        assert [(e.kind, e.member.name) for e in evs] == [("leave", "w1")]
        assert {r.name for r in fleet.fleet.alive_replicas()} == {"w0"}

    def test_prefix_keys_warm_router_on_join(self, fleet, model):
        # run one request through w0 so its cache holds prefix pages, then
        # stand up a fresh fleet view: the join must import those keys
        fleet.router.pin = "w0"
        prompt = list(range(1, 25))
        h = fleet.fleet.submit(prompt, max_new_tokens=4, do_sample=False)
        list(fleet.fleet.stream(h))
        from paddle_tpu.inference.frontend.router import PrefixAffinityRouter
        router2 = PrefixAffinityRouter(page_size=8)
        fleet2 = FleetReplicaSet(fleet._store(), group=fleet.group,
                                 ttl=fleet.ttl, clock=fleet.clock,
                                 router=router2)
        try:
            fleet2.sync()
            assert router2.known_keys("w0")          # warmed from snapshot
        finally:
            fleet2.close()


class TestFleetChaos:
    """The deterministic ISSUE 10 acceptance scenario."""

    def test_kill_mid_stream_full_recovery(self, fleet, model):
        import paddle_tpu.observability as obs
        obs.enable()
        try:
            self._scenario(fleet, model)
        finally:
            obs.disable()
            obs.reset()

    def _scenario(self, fleet, model):
        fs = fleet.fleet
        prompt_a = list(range(1, 17))                # partially-streamed victim
        prompt_b = list(range(30, 46))               # zero-token victim
        prompt_c = list(range(60, 76))               # survivor
        # A gets a deep budget on purpose: its paced decode must still be
        # in flight when the kill lands no matter how warm the XLA disk
        # cache is (see the pacing comment below)
        ref_a = _reference_tokens(model, prompt_a, n=24)
        ref_b = _reference_tokens(model, prompt_b)
        ref_c = _reference_tokens(model, prompt_c)

        # stream two tokens of A, none of B, one of C, then kill w0.  The
        # slow_step fault paces every engine's decode so w0 cannot race
        # through A's whole budget between our second next() and the kill —
        # the death must land mid-stream for the resume path to be real.
        # Pacing MUST be armed before the submits: with a warm XLA disk
        # cache the engine otherwise decodes A's whole budget in the gap
        # between submit() and install().  The margin is scale-free:
        # producing A's 24 tokens takes >= 24 paced steps (~6s of pure
        # sleep, immune to compile-cache warmth and host load), while the
        # pre-kill window is two A pulls and one C pull (~1-3s).  Pacing is
        # dropped right after the kill so the recovery drains run fast.
        FAULTS.install("serving.slow_step", Always(), delay=0.25)
        fleet.router.pin = "w0"
        h_a = fs.submit(prompt_a, max_new_tokens=24, do_sample=False)
        h_b = fs.submit(prompt_b, max_new_tokens=6, do_sample=False)
        fleet.router.pin = "w1"
        h_c = fs.submit(prompt_c, max_new_tokens=6, do_sample=False)
        assert (h_a.replica.name, h_b.replica.name,
                h_c.replica.name) == ("w0", "w0", "w1")

        stream_a = fs.stream(h_a)
        got_a = [next(stream_a), next(stream_a)]
        stream_c = fs.stream(h_c)
        got_c = [next(stream_c)]
        fleet.kill("w0")
        FAULTS.reset()
        fleet.router.pin = None

        # zero-token victim: requeued once onto w1 and token-exact
        toks_b = list(fs.stream(h_b))
        assert h_b.requeued and h_b.replica.name == "w1"
        assert toks_b == ref_b
        assert fs.status(h_b).terminal

        # partially-streamed victim: resumed on w1 with its two emitted
        # tokens re-prefilled — the spliced stream is byte-identical to an
        # uninterrupted run
        got_a += list(stream_a)
        assert h_a.resumed and not h_a.requeued
        assert h_a.replica.name == "w1"
        assert got_a == ref_a
        assert fs.status(h_a).terminal
        assert fs.status(h_a) is not RequestStatus.FAILED

        # survivor: token-exact to the single-engine reference
        got_c += list(stream_c)
        assert got_c == ref_c

        # lease expiry: w1 renews, w0 cannot; one TTL later it expires
        fleet.renew_all()
        fleet.clock.t += fleet.ttl + 0.5
        fleet.workers["w1"].lease.renew()
        evs = fs.sync()
        assert [(e.kind, e.member.name) for e in evs] == [("expire", "w0")]
        assert {r.name for r in fs.alive_replicas()} == {"w1"}

        # supervisor respawn: new incarnation registers under epoch 2 and
        # rejoins routing within one lease TTL of the respawn
        sup = WorkerSupervisor(lambda: _RespawnHandle(fleet, "w0"),
                               name="w0", clock=fleet.clock,
                               sleep=lambda s: None, max_crashes=5)
        sup.start_worker()
        assert sup.tick() == RUNNING
        fleet.clock.t += fleet.ttl / 2               # < one TTL
        evs = fs.sync()
        assert [(e.kind, e.member.name, e.member.epoch)
                for e in evs] == [("join", "w0", 2)]
        assert {r.name for r in fs.alive_replicas()} == {"w0", "w1"}

        # the respawned worker serves token-exact streams again
        fleet.router.pin = "w0"
        h = fs.submit(prompt_b, max_new_tokens=6, do_sample=False)
        assert h.replica.name == "w0" and h.replica.epoch == 2
        assert list(fs.stream(h)) == ref_b

        # all three acceptance metric families are visible
        import paddle_tpu.observability as obs
        text = obs.render_prometheus()
        assert ('membership_lease_expiries_total{group="%s"} 1'
                % fleet.group) in text
        assert "frontend_requeued_total 1" in text
        assert "frontend_resumed_total 1" in text
        assert 'frontend_routed_total{replica="w1",reason="resume"} 1' in text
        assert 'frontend_replica_restarts_total' in text

    def test_gateway_keeps_serving_through_kill(self, fleet, model):
        from paddle_tpu.inference.frontend import start_gateway
        prompt = list(range(1, 17))
        ref = _reference_tokens(model, prompt)
        gw = start_gateway(fleet.fleet)
        try:
            fleet.router.pin = "w0"
            body = self._post(gw.url, prompt)
            assert body["tokens"] == ref
            fleet.kill("w0")
            fleet.router.pin = None
            body = self._post(gw.url, prompt)        # routed to the survivor
            assert body["tokens"] == ref and body["replica"] == "w1"
        finally:
            gw.close()

    def _post(self, url, prompt, **extra):
        req = urllib.request.Request(
            url + "/v1/completions",
            data=json.dumps({"prompt": prompt, "max_tokens": 6,
                             "do_sample": False, **extra}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            return json.loads(resp.read())


class _RespawnHandle:
    """Process-handle shim the supervisor drives in the deterministic test:
    'spawning' is standing up a fresh thread-hosted WorkerServer."""

    def __init__(self, harness, name):
        self.worker = harness.spawn(name)

    def poll(self):
        return None if self.worker.replica.alive else 1

    def terminate(self):
        self.worker.close(drain=False)

    def kill(self):
        self.terminate()

    def wait(self, timeout=None):
        return 0


class TestGatewayDeadFleet:
    def test_dead_fleet_503_carries_retry_after(self, model, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
        from paddle_tpu.inference.frontend import start_gateway
        master = TCPStore(is_master=True, timeout=20)
        fleet = FleetReplicaSet(TCPStore(port=master.port, timeout=20),
                                group="empty", clock=_FakeClock())
        gw = start_gateway(fleet)
        try:
            req = urllib.request.Request(
                gw.url + "/v1/completions",
                data=json.dumps({"prompt": [1, 2, 3],
                                 "max_tokens": 2}).encode(),
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=30)
            assert ei.value.code == 503
            assert ei.value.headers.get("Retry-After") is not None
        finally:
            gw.close()
            fleet.close()


# ------------------------------------------------- real processes (slow tier)

@pytest.mark.slow
class TestRealKillNine:
    def test_sigkill_worker_subprocess(self, tmp_path, monkeypatch):
        """Real worker subprocesses, a real SIGKILL, wall-clock leases."""
        monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
        master = TCPStore(is_master=True, timeout=60)
        spec = os.path.join(os.path.dirname(__file__),
                            "_fleet_worker_spec.py")
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "PADDLE_TPU_PURE_PY_STORE": "1"}
        ttl = 3.0
        procs = []

        def spawn(name):
            p = subprocess.Popen(
                [sys.executable, "-m",
                 "paddle_tpu.inference.frontend.worker",
                 "--engine-spec", f"{spec}:make_engine",
                 "--name", name, "--store-port", str(master.port),
                 "--group", "real", "--ttl", str(ttl)],
                env=env, cwd=os.path.dirname(os.path.dirname(spec)))
            procs.append(p)
            return p

        fleet = FleetReplicaSet(TCPStore(port=master.port, timeout=60),
                                group="real", ttl=ttl)
        try:
            spawn("w0")
            spawn("w1")
            deadline = time.monotonic() + 180
            while (len(fleet.alive_replicas()) < 2
                   and time.monotonic() < deadline):
                fleet.sync()
                time.sleep(0.5)
            assert len(fleet.alive_replicas()) == 2, "workers never joined"

            prompt = list(range(1, 17))
            h0 = fleet.submit(prompt, max_new_tokens=6, do_sample=False)
            ref = list(fleet.stream(h0))
            assert len(ref) == 6

            # submit, then SIGKILL the routed worker before polling a token
            h = fleet.submit(prompt, max_new_tokens=6, do_sample=False)
            victim = h.replica.name
            pid = fleet.membership.members()[victim].meta["pid"]
            os.kill(pid, signal.SIGKILL)
            toks = list(fleet.stream(h))
            assert h.requeued and h.replica.name != victim
            assert toks == ref                        # token-exact recovery

            # expiry + respawn: the dead member leaves within ~one TTL,
            # a respawned process rejoins under a new epoch
            deadline = time.monotonic() + ttl * 4
            gone = False
            while time.monotonic() < deadline and not gone:
                gone = any(e.kind == "expire" and e.member.name == victim
                           for e in fleet.sync())
                time.sleep(0.2)
            assert gone, "dead worker's lease never expired"
            spawn(victim)
            deadline = time.monotonic() + 180
            rejoined = None
            while time.monotonic() < deadline and rejoined is None:
                for e in fleet.sync():
                    if e.kind == "join" and e.member.name == victim:
                        rejoined = e.member
                time.sleep(0.5)
            assert rejoined is not None and rejoined.epoch == 2
            h2 = fleet.submit(prompt, max_new_tokens=6, do_sample=False)
            assert list(fleet.stream(h2)) == ref
        finally:
            fleet.close()
            for p in procs:
                if p.poll() is None:
                    p.terminate()
            for p in procs:
                try:
                    p.wait(timeout=30)
                except subprocess.TimeoutExpired:
                    p.kill()
