"""CC101 clean fixture: every access takes the guarding lock, and the
helper is analyzed under the lock its only callers hold."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0

    def inc(self):
        with self._lock:
            self._bump()

    def _bump(self):
        self.value += 1         # caller holds the lock (inherited context)

    def read(self):
        with self._lock:
            return self.value
