"""RL101 clean: every acquire is guarded — closing except, with-block,
daemon thread, joined thread."""
import socket
import threading


def connect(host, port):
    sock = socket.create_connection((host, port), timeout=5)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    except OSError:
        sock.close()
        raise
    return sock


class Server:
    def __init__(self, host, port):
        self._srv = socket.socket()
        try:
            self._srv.bind((host, port))
            self._srv.listen(8)
        except OSError:
            self._srv.close()
            raise

    def close(self):
        self._srv.close()


def snapshot(path):
    with open(path) as f:
        return f.read()


def run_workers(fn):
    threading.Thread(target=fn, daemon=True).start()
    t = threading.Thread(target=fn)
    t.start()
    t.join()
