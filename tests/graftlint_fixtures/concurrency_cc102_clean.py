"""CC102 clean fixture: snapshot under the lock, block outside it."""
import os
import threading
import time


class Checkpointer:
    def __init__(self):
        self._mu = threading.Lock()
        self.dirty = False

    def settle(self):
        time.sleep(0.1)            # not under any lock
        with self._mu:
            self.dirty = False

    def flush(self, fd):
        with self._mu:
            self.dirty = False
        self._sync(fd)             # helper blocks outside the lock
        time.sleep(0.0)

    def _sync(self, fd):
        os.fsync(fd)
