"""CC104 fixture: two locks taken in opposite orders on two paths."""
import threading


class Ledger:
    def __init__(self):
        self._accounts = threading.Lock()
        self._audit = threading.Lock()
        self.balance = 0
        self.trail = []

    def transfer(self, n):
        with self._accounts:
            with self._audit:            # accounts -> audit
                self.balance += n
                self.trail.append(n)

    def reconcile(self):
        with self._audit:
            with self._accounts:         # audit -> accounts: inversion
                self.trail.append(self.balance)
