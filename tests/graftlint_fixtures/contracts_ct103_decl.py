"""Support file for the CT103 bad fixture: the declaring module.  Lint it
TOGETHER with contracts_ct103_bad.py — 'engine.flush' is fired there but
never armed, and 'engine.retire' is never fired at all."""
KNOWN_POINTS = frozenset({
    "engine.step",        # fired and chaos-covered: clean
    "engine.flush",       # CT103 warning: no injected(...) coverage
    "engine.retire",      # CT103 warning: never fired — dead chaos surface
})
