"""Support file for the CT103 clean fixture: every declared point is fired
and chaos-covered in contracts_ct103_clean.py."""
KNOWN_POINTS = frozenset({
    "engine.step",
    "engine.flush",
})
