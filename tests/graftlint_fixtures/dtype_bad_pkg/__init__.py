# graftlint: disable-file=registry-parity  (mini-OpSpec, not a real registry)
"""Lint fixture package: a miniature op registry with dtype-rule violations.

Importable (the dtype-rules runtime half imports it via syspath), but
self-contained — it mimics the real registry's shape (``REGISTRY`` of
``OpSpec``-like entries built by a ``g`` helper) without touching the real
``paddle_tpu.ops.REGISTRY``.
"""
from dataclasses import dataclass, field

import numpy as np


@dataclass
class OpSpec:
    name: str
    category: str
    np_ref: object = None
    sample: object = None
    kwargs: dict = field(default_factory=dict)
    grad: bool = False
    kind: str = "golden"


REGISTRY: dict[str, OpSpec] = {}


def g(name, ref, sample, cat, grad=False, **kw):
    REGISTRY[name] = OpSpec(name, cat, np_ref=ref, sample=sample, grad=grad,
                            **kw)
    return REGISTRY[name]


# DT101: int64 kwargs index array — the tensor layer narrows it to int32
g("bad_index", lambda x: x[[0, 1]], lambda: [np.ones((3, 2), np.float32)],
  "manip", kwargs={"index": np.array([0, 1], np.int64)})

# DT101: float64 sample input
g("bad_sample", lambda x: x * 2, lambda: [np.ones(3, np.float64)], "math")

# DT103: grad=True with integer-only inputs
g("bad_grad", lambda x: x + 1, lambda: [np.arange(4, dtype=np.int32)],
  "math", grad=True)

# DT102 (warning): float64 golden from float32 inputs
g("f64_golden", lambda x: np.vander(x), lambda: [np.ones(3, np.float32)],
  "math")

# clean entry: no findings
g("clean_op", lambda x: x + 1.0, lambda: [np.ones(3, np.float32)], "math",
  grad=True)
