# graftlint: disable-file=trace-safety
"""Lint fixture: shard_map contract violations, one per SS code.

Never imported or executed — the sharding-spec-coverage pass reads it as
source.  Each site below is intentionally wrong; tests assert the exact
finding codes.
"""
import jax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

mesh = Mesh(jax.devices(), ("dp", "mp"))


def body2(a, b):
    return a + b


def bad_in_arity(x):
    # SS101: one spec for a two-argument body
    f = shard_map(body2, mesh=mesh, in_specs=(P("dp"),), out_specs=P("dp"))
    return f(x)


def bad_spec_axis(x, y):
    # SS102: 'ep' is not a mesh axis
    f = shard_map(body2, mesh=mesh, in_specs=(P("dp"), P("ep")),
                  out_specs=P("dp"))
    return f(x, y)


def body_unbound_collective(a):
    # SS103: 'sep' is not bound by the surrounding shard_map's mesh
    return jax.lax.psum(a, "sep")


def bad_collective_axis(x):
    f = shard_map(body_unbound_collective, mesh=mesh, in_specs=(P("dp"),),
                  out_specs=P("dp"))
    return f(x)


def body_divergent(a):
    s = a.sum()
    if s > 0:
        # SS104: collective under a branch on traced data — shards that skip
        # the psum deadlock the ones that reach it
        a = jax.lax.psum(a, "dp")
    return a


def bad_divergence(x):
    f = shard_map(body_divergent, mesh=mesh, in_specs=(P("dp"),),
                  out_specs=P("dp"))
    return f(x)


def body_triple(a, b):
    return a, b, a


def bad_out_arity(x, y):
    # SS105: two out_specs for a three-tuple return
    f = shard_map(body_triple, mesh=mesh, in_specs=(P("dp"), P("dp")),
                  out_specs=(P("dp"), P("dp")))
    return f(x, y)


def bad_named_sharding(x):
    # SS106: 'tp' is not a mesh axis — caught at the NamedSharding site
    # inside with_sharding_constraint, the usual spelling of the bug
    from jax.sharding import NamedSharding
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P("tp", None)))


def bad_jit_shardings(fn, x):
    # SS106 (jit keyword path): bare PartitionSpec in in_shardings resolves
    # against the enclosing `with mesh:` context — 'fsdp' is not an axis
    with mesh:
        g = jax.jit(fn, in_shardings=(P("fsdp"),), out_shardings=P("dp"))
        return g(x)
