"""CT103 bad: fault-point protocol drift (lint together with
contracts_ct103_decl.py, which declares KNOWN_POINTS)."""
from paddle_tpu.testing.faults import FAULTS, FailNth, injected


def step(rid):
    FAULTS.maybe_fire("engine.step", rid=rid)


def flush():
    FAULTS.raise_if("engine.flush")


def rollout(point):
    FAULTS.fire(point)                     # CT103 warning: non-literal name
    FAULTS.maybe_fire("engine.stray")      # CT103 error: not in KNOWN_POINTS


def chaos_test():
    with injected("engine.step", FailNth(1)):
        step(1)
