"""Lint fixture: non-atomic writes the robustness pass must catch (RB105).

Never imported or executed — read as source.  This module "qualifies" as a
persistence code path (it calls ``os.replace`` below), so every
create-truncate ``open`` of a final path is a torn-file hazard its own
idiom already knows how to avoid.
"""
import json
import os


def save_atomic(path, obj):
    # the module's one correct write: this is what makes it "qualify"
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def save_torn(path, obj):
    with open(path, "w") as f:        # RB105: truncates the final path
        json.dump(obj, f)


def save_torn_binary(path, blob):
    f = open(path, "wb")              # RB105: same, binary
    f.write(blob)
    f.close()


def save_torn_kw_mode(path, obj):
    with open(path, mode="w") as f:   # RB105: mode via keyword
        json.dump(obj, f)


def marker_torn(done_dir, rank):
    # a commit marker whose EXISTENCE is the signal readers trust
    with open(os.path.join(done_dir, f"rank_{rank}.done"), "w") as f:  # RB105
        f.write("done")
