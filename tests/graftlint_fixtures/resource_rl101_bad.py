"""RL101 bad: sockets acquired, then calls that can raise before any close
is guaranteed — including the unconditional constructor leak."""
import socket


def connect(host, port):
    sock = socket.create_connection((host, port), timeout=5)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)   # may raise
    return sock


class Server:
    def __init__(self, host, port):
        self._srv = socket.socket()
        self._srv.bind((host, port))    # raises -> caller has nothing to close
        self._srv.listen(8)

    def close(self):
        self._srv.close()
