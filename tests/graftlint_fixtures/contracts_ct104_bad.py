"""CT104 bad: metric-family indiscipline — invalid name, computed name,
and a cross-declaration type conflict."""
from paddle_tpu.observability import REGISTRY


def setup(shard):
    REGISTRY.counter("fleet requests")              # CT104: invalid name
    REGISTRY.counter(f"fleet_{shard}_total")        # CT104: non-literal
    REGISTRY.counter("fleet_steps_total")
    REGISTRY.gauge("fleet_steps_total")             # CT104: type conflict
