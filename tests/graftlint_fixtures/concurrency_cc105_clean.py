"""CC105 clean fixture: the re-entered lock is an RLock, and the plain
Lock is only ever taken once per call chain."""
import threading


class Box:
    def __init__(self):
        self._mu = threading.RLock()     # reentrant: chain re-entry is fine
        self._flat = threading.Lock()
        self.n = 0
        self.m = 0

    def add(self, k):
        with self._mu:
            self._bump(k)

    def _bump(self, k):
        with self._mu:
            self.n += k

    def poke(self):
        with self._flat:
            self.m += 1
