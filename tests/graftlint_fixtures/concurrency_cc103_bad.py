"""CC103 fixture: if-guarded wait, and notify outside the owning with."""
import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()
        self.items = []

    def get(self):
        with self._cv:
            if not self.items:
                self._cv.wait()          # CC103: not re-checked in a while
            return self.items.pop()

    def put(self, item):
        with self._cv:
            self.items.append(item)
        self._cv.notify_all()            # CC103: lock already released
