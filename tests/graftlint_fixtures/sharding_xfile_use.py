# graftlint: disable-file=trace-safety
"""Lint fixture: shard_map over a body imported from another file.  The
in_specs arity is wrong (2 specs, 3 params) — only detectable by resolving
``xbody`` across files."""
import jax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

from sharding_xfile_def import xbody

mesh = Mesh(jax.devices(), ("dp",))


def bad_xfile_arity(x, y):
    # SS101, cross-file: xbody takes three arrays
    f = shard_map(xbody, mesh=mesh, in_specs=(P("dp"), P("dp")),
                  out_specs=P("dp"))
    return f(x, y)
