"""CT103 clean: full fault-point parity (lint together with
contracts_ct103_decl_ok.py) — every fired point is declared, every declared
point is fired and armed by an injected(...) chaos test."""
from paddle_tpu.testing.faults import FAULTS, FailNth, injected


def step(rid):
    FAULTS.maybe_fire("engine.step", rid=rid)


def flush():
    FAULTS.raise_if("engine.flush")


def chaos_test():
    with injected("engine.step", FailNth(1)):
        step(1)
    with injected("engine.flush", FailNth(1)):
        flush()
