"""Lint fixture: swallowed exceptions the robustness pass must catch.

Never imported or executed — read as source.  Each handler below silently
discards every failure; tests assert one RB101 warning per site.  The
tail adds hand-rolled retry loops (RB104): a ``time.sleep`` between
``try``/``except`` attempts, bypassing core.retry's policy.
"""
import time


def bare_swallow(fn):
    try:
        return fn()
    except:  # noqa: E722
        pass


def broad_swallow(fn):
    try:
        return fn()
    except Exception:
        pass


def base_swallow(fn):
    try:
        return fn()
    except BaseException:
        pass


def tuple_swallow(fn):
    try:
        return fn()
    except (ValueError, Exception):
        pass


def ellipsis_swallow(fn):
    try:
        return fn()
    except Exception:
        ...


def loop_swallow(items):
    out = []
    for it in items:
        try:
            out.append(it())
        except Exception:     # RB102: the item's failure AND work vanish
            continue
    return out


def loop_break_swallow(items):
    for it in items:
        try:
            it()
        except Exception:     # RB102: break variant
            break


def return_swallow(fn):
    try:
        return fn()
    except Exception:         # RB102: bare return
        return


def return_none_swallow(fn):
    try:
        return fn()
    except Exception:         # RB102: explicit None is still nothing
        return None


def while_retry_sleep(connect):
    while True:
        try:
            return connect()
        except OSError:       # RB104: flat sleep between attempts
            time.sleep(0.1)


def for_retry_sleep(fn, attempts):
    last = None
    for _ in range(attempts):
        try:
            return fn()
        except ConnectionError as e:
            last = e
        time.sleep(0.5)       # RB104: sleep after the failed attempt
    raise last
