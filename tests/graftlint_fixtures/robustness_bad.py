"""Lint fixture: swallowed exceptions the robustness pass must catch.

Never imported or executed — read as source.  Each handler below silently
discards every failure; tests assert one RB101 warning per site.
"""


def bare_swallow(fn):
    try:
        return fn()
    except:  # noqa: E722
        pass


def broad_swallow(fn):
    try:
        return fn()
    except Exception:
        pass


def base_swallow(fn):
    try:
        return fn()
    except BaseException:
        pass


def tuple_swallow(fn):
    try:
        return fn()
    except (ValueError, Exception):
        pass


def ellipsis_swallow(fn):
    try:
        return fn()
    except Exception:
        ...
