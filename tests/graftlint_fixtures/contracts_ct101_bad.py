"""CT101 bad: op drift on both sides of the worker RPC protocol."""
from paddle_tpu.inference.frontend.rpc import RpcClient, RpcServer


class Worker:
    def serve(self):
        self.srv = RpcServer(self._handle)
        return self.srv

    def _handle(self, op, kw):
        if op == "submit":
            return kw["rid"]
        if op == "audit":                  # CT101 warning: nobody calls it
            return []
        raise ValueError(f"unknown worker op {op!r}")


def gateway(host, port):
    client = RpcClient(host, port)
    client.call("submit", rid=1)
    return client.call("cancel", rid=1)    # CT101 error: no handler arm
