"""Lint fixture: writes the robustness RB105 check must stay silent on.

Never imported or executed — read as source.  Tmp-staged writes, appends,
reads, non-literal modes, and — in ``no_discipline_module`` style — the
whole-module exemption are exercised by the companion module
``persistence_clean_nodisc.py`` (a module with no ``os.replace``/
``os.fsync`` never qualifies, whatever it opens).
"""
import json
import os


def save_atomic(path, obj):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:         # staging file of the idiom itself
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def save_via_tmpname(tmp_path, obj):
    with open(tmp_path, "w") as f:    # identifier says temp: trusted
        json.dump(obj, f)


def save_joined_tmp(d, name, obj):
    with open(os.path.join(d, name + ".tmp"), "w") as f:  # constant says tmp
        json.dump(obj, f)


def append_log(path, line):
    with open(path, "a") as f:        # append never truncates
        f.write(line)


def read_back(path):
    with open(path) as f:             # default mode reads
        return json.load(f)


def read_binary(path):
    with open(path, "rb") as f:
        return f.read()


def dynamic_mode(path, mode):
    with open(path, mode) as f:       # non-literal mode: benefit of doubt
        return f
