"""Lint fixture: exception handling the robustness pass must NOT flag —
narrow swallows, broad handlers that act, and pragma'd deliberate swallows."""
import logging

log = logging.getLogger(__name__)


def narrow_probe(d, k):
    try:
        return d[k]
    except KeyError:          # narrow: idiomatic dict probing
        pass
    return None


def broad_but_logged(fn):
    try:
        return fn()
    except Exception as e:    # broad, but the error is surfaced
        log.warning("fn failed: %s", e)
        return None


def broad_reraise(fn):
    try:
        return fn()
    except Exception:
        raise RuntimeError("fn failed")


def deliberate(fn):
    try:
        return fn()
    except Exception:  # graftlint: disable=robustness — shutdown cleanup
        pass
