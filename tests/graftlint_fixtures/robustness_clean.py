"""Lint fixture: exception handling the robustness pass must NOT flag —
narrow swallows, broad handlers that act, and pragma'd deliberate swallows."""
import logging

log = logging.getLogger(__name__)


def narrow_probe(d, k):
    try:
        return d[k]
    except KeyError:          # narrow: idiomatic dict probing
        pass
    return None


def broad_but_logged(fn):
    try:
        return fn()
    except Exception as e:    # broad, but the error is surfaced
        log.warning("fn failed: %s", e)
        return None


def broad_reraise(fn):
    try:
        return fn()
    except Exception:
        raise RuntimeError("fn failed")


def deliberate(fn):
    try:
        return fn()
    except Exception:  # graftlint: disable=robustness — shutdown cleanup
        pass


def narrow_continue(items):
    out = []
    for it in items:
        try:
            out.append(it())
        except ValueError:    # narrow escape: expected per-item failure
            continue
    return out


def broad_counted_continue(items, stats):
    out = []
    for it in items:
        try:
            out.append(it())
        except Exception as e:  # broad, but the failure is recorded
            stats.append(e)
            continue
    return out


def return_value_after_broad(fn):
    try:
        return fn()
    except Exception:
        return -1             # sentinel communicates the failure
