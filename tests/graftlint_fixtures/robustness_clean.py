"""Lint fixture: exception handling the robustness pass must NOT flag —
narrow swallows, broad handlers that act, pragma'd deliberate swallows, and
sleeping loops that are waiting, not retrying (RB104 stays silent)."""
import logging
import time

log = logging.getLogger(__name__)


def narrow_probe(d, k):
    try:
        return d[k]
    except KeyError:          # narrow: idiomatic dict probing
        pass
    return None


def broad_but_logged(fn):
    try:
        return fn()
    except Exception as e:    # broad, but the error is surfaced
        log.warning("fn failed: %s", e)
        return None


def broad_reraise(fn):
    try:
        return fn()
    except Exception:
        raise RuntimeError("fn failed")


def deliberate(fn):
    try:
        return fn()
    except Exception:  # graftlint: disable=robustness — shutdown cleanup
        pass


def narrow_continue(items):
    out = []
    for it in items:
        try:
            out.append(it())
        except ValueError:    # narrow escape: expected per-item failure
            continue
    return out


def broad_counted_continue(items, stats):
    out = []
    for it in items:
        try:
            out.append(it())
        except Exception as e:  # broad, but the failure is recorded
            stats.append(e)
            continue
    return out


def return_value_after_broad(fn):
    try:
        return fn()
    except Exception:
        return -1             # sentinel communicates the failure


def wait_loop(ready):
    while not ready():        # poll/drain spin: no attempt under try —
        time.sleep(0.05)      # waiting is not retrying


def injected_sleep_retry(fn, sleep):
    while True:               # core.retry's own discipline: the sleep is
        try:                  # an injectable callable, not time.sleep
            return fn()
        except OSError:
            sleep(0.1)


def closure_in_loop(items, out):
    for it in items:
        try:
            out.append(it())
        except ValueError as e:
            out.append(e)

        def later():          # nested def: its sleep is not this loop's
            time.sleep(1.0)   # backoff
        out.append(later)


def deliberate_retry(connect):
    while True:
        try:
            return connect()
        except OSError:
            time.sleep(0.1)   # graftlint: disable=robustness — boot probe
