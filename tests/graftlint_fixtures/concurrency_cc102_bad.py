"""CC102 fixture: blocking calls under a held lock, direct and one
call-hop deep through a same-class helper."""
import os
import threading
import time


class Checkpointer:
    def __init__(self, sleep=time.sleep):
        self._mu = threading.Lock()
        self.sleep = sleep
        self.dirty = False

    def settle(self):
        with self._mu:
            time.sleep(0.1)        # CC102: literal sleep under the lock

    def settle_injected(self):
        with self._mu:
            self.sleep(0.1)        # CC102: injectable sleep attribute

    def flush(self, fd):
        with self._mu:
            self._sync(fd)         # CC102: helper fsyncs, one hop deep
            self.dirty = False

    def _sync(self, fd):
        os.fsync(fd)
