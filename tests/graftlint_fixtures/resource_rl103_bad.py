"""RL103 bad: a membership lease registered in the constructor but no
release()/evict() reachable from any shutdown method — the fleet keeps
routing to the corpse until TTL expiry."""


class Worker:
    def __init__(self, membership, group, name):
        self.lease = membership.register(group, name)
        self.closed = False

    def close(self):
        self.closed = True
