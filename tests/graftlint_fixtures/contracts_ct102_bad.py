"""CT102 bad: an exception raised under the dispatch closure that cannot
travel by pickle — its __init__ mangles the constructor args, so the
default __reduce__ replays cls(*args) with the wrong values."""
from paddle_tpu.inference.frontend.rpc import RpcServer


class QuotaError(RuntimeError):
    def __init__(self, limit, used):
        super().__init__(f"quota exceeded: {used}/{limit}")   # not verbatim
        self.limit = limit
        self.used = used


class Worker:
    def serve(self):
        self.srv = RpcServer(self._handle)
        return self.srv

    def _handle(self, op, kw):
        if op == "reserve":
            raise QuotaError(8, kw["n"])
        raise ValueError(f"unknown worker op {op!r}")
