"""RL103 clean: the lease release is reachable from close() through an
intra-class call."""


class Worker:
    def __init__(self, membership, group, name):
        self.lease = membership.register(group, name)
        self.closed = False

    def close(self):
        self._leave()
        self.closed = True

    def _leave(self):
        self.lease.release()
