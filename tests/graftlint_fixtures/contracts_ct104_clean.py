"""CT104 clean: literal valid names, one type per family, cardinality in
labels instead of the name."""
from paddle_tpu.observability import REGISTRY

REQS = REGISTRY.counter("fleet_requests_total", "requests by op",
                        labelnames=("op",))
INFLIGHT = REGISTRY.gauge("fleet_inflight", "in-flight requests")
STEP_S = REGISTRY.histogram("fleet_step_seconds", "step latency")


def observe(op, dur):
    REQS.inc(op=op)
    STEP_S.observe(dur)
