"""RL102 bad: a PagePool ref separated from its unref by a call that can
raise, with no except/finally rollback — the static shadow of
audit_refcounts."""


class Engine:
    def __init__(self, pool, runner):
        self.pool = pool
        self.runner = runner

    def splice(self, blk, key):
        p = self.pool.alloc_page()
        self.runner.restore_pages([p], [blk])   # raises -> ref strands
        self.pool.register(p, key)
        self.pool.unref_page(p)
