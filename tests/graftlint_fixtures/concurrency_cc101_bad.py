"""CC101 fixture: attribute guarded in one method, naked in another."""
import threading


class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.value = 0          # __init__ writes are exempt

    def inc(self):
        with self._lock:
            self.value += 1     # establishes the guard

    def read(self):
        return self.value       # CC101: no lock held

    def bump_unlocked(self):
        self.value += 2         # CC101: write with no lock held
