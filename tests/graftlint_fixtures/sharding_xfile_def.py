# graftlint: disable-file=trace-safety
"""Lint fixture: the shard_map body lives here; the (broken) call site is in
sharding_xfile_use.py — exercises cross-file body resolution."""


def xbody(a, b, c):
    return a + b + c
