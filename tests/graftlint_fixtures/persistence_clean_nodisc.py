"""Lint fixture: a module with truncating writes but NO atomic-write
discipline anywhere (no ``os.replace``/``os.fsync``) — RB105 is scoped to
modules that already practice the idiom, so this one stays silent.

Never imported or executed — read as source.
"""
import json


def dump_config(path, obj):
    with open(path, "w") as f:        # not a persistence module: silent
        json.dump(obj, f)


def dump_blob(path, blob):
    with open(path, "wb") as f:
        f.write(blob)
