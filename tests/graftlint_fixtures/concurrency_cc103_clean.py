"""CC103 clean fixture: while-predicate waits, notify under the cv, and
wait_for (which embeds its predicate)."""
import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()
        self.items = []

    def get(self):
        with self._cv:
            while not self.items:
                self._cv.wait()
            return self.items.pop()

    def get_eventually(self):
        with self._cv:
            self._cv.wait_for(lambda: self.items)
            return self.items.pop()

    def put(self, item):
        with self._cv:
            self.items.append(item)
            self._cv.notify_all()
