"""CT101 clean: op parity both ways, including a forwarder-resolved site."""
from paddle_tpu.inference.frontend.rpc import RpcClient, RpcServer


class Worker:
    def serve(self):
        self.srv = RpcServer(self._handle)
        return self.srv

    def _handle(self, op, kw):
        if op == "submit":
            return kw["rid"]
        if op == "cancel":
            return True
        raise ValueError(f"unknown worker op {op!r}")


class Remote:
    """The op string flows through a forwarder before hitting the client."""

    def __init__(self, host, port):
        self.client = RpcClient(host, port)

    def _call(self, op, **kw):
        return self.client.call(op, **kw)

    def submit(self, rid):
        return self._call("submit", rid=rid)

    def cancel(self, rid):
        return self._call("cancel", rid=rid)
