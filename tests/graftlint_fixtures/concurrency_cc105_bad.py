"""CC105 fixture: a non-reentrant Lock re-acquired along an intra-class
call chain (and directly, in a nested with)."""
import threading


class Box:
    def __init__(self):
        self._mu = threading.Lock()
        self.n = 0

    def add(self, k):
        with self._mu:
            self._bump(k)                # CC105: _bump retakes _mu

    def add_twice(self, k):
        with self._mu:
            with self._mu:               # CC105: immediate re-acquire
                self.n += 2 * k

    def _bump(self, k):
        with self._mu:
            self.n += k
