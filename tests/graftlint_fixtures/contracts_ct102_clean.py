"""CT102 clean: both pickle-safe shapes — a verbatim-forwarding __init__
and an explicit __reduce__."""
from paddle_tpu.inference.frontend.rpc import RpcServer


class QuotaError(RuntimeError):
    def __init__(self, limit, used):
        super().__init__(limit, used)      # verbatim: default reduce works
        self.limit = limit
        self.used = used


class LeaseGone(RuntimeError):
    def __init__(self, epoch):
        super().__init__(f"lease lost at epoch {epoch}")
        self.epoch = epoch

    def __reduce__(self):
        return (LeaseGone, (self.epoch,))


class Bare(RuntimeError):
    """No __init__ at all: BaseException stores args verbatim."""


class Worker:
    def serve(self):
        self.srv = RpcServer(self._handle)
        return self.srv

    def _handle(self, op, kw):
        if op == "reserve":
            raise QuotaError(8, kw["n"])
        if op == "renew":
            raise LeaseGone(kw["epoch"])
        if op == "probe":
            raise Bare("nope")
        raise ValueError(f"unknown worker op {op!r}")
