"""CC104 clean fixture: one global order, every path honors it."""
import threading


class Ledger:
    def __init__(self):
        self._accounts = threading.Lock()
        self._audit = threading.Lock()
        self.balance = 0
        self.trail = []

    def transfer(self, n):
        with self._accounts:
            with self._audit:            # accounts -> audit everywhere
                self.balance += n
                self.trail.append(n)

    def reconcile(self):
        with self._accounts:
            with self._audit:
                self.trail.append(self.balance)
