# graftlint: disable-file=trace-safety
"""Lint fixture: contract-clean shard_map usage (partial-bound body, axes
that exist, collective on a bound axis, static branch).  Must produce zero
sharding-spec-coverage findings."""
import functools

import jax
from jax.sharding import Mesh, PartitionSpec as P
from jax.experimental.shard_map import shard_map

mesh = Mesh(jax.devices(), ("dp", "mp"))


def _inner(a, b, scale, causal):
    if causal:                       # static flag bound via partial — fine
        a = a * 2
    s = jax.lax.psum(a * scale, "dp")
    return s + b


def clean(x, y):
    body = functools.partial(_inner, scale=2.0, causal=True)
    f = shard_map(body, mesh=mesh, in_specs=(P("dp"), P("mp")),
                  out_specs=P("dp"))
    return f(x, y)


def clean_constraint(x):
    # NamedSharding on axes the mesh defines — no SS106
    from jax.sharding import NamedSharding
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P("dp", "mp")))


def clean_dynamic_sharding(x, mesh2, spec):
    # dynamic mesh/spec: skipped, never guessed
    from jax.sharding import NamedSharding
    return jax.device_put(x, NamedSharding(mesh2, spec))


def clean_jit_shardings(fn, x):
    # bare PartitionSpec on axes the context mesh defines — no SS106
    with mesh:
        g = jax.jit(fn, in_shardings=(P("dp"),), out_shardings=P("dp", "mp"))
        return g(x)


def clean_jit_no_context(fn, x):
    # no statically-known enclosing mesh: skipped, never guessed
    g = jax.jit(fn, in_shardings=(P("anything"),))
    return g(x)


def clean_jit_named_sharding(fn, x):
    # NamedSharding inside jit kwargs carries its OWN mesh — validated at
    # its construction site, not against the context mesh
    from jax.sharding import NamedSharding
    with mesh:
        g = jax.jit(fn, in_shardings=(NamedSharding(mesh, P("mp")),))
        return g(x)
