"""RL102 clean: the risky write is guarded by a rollback try, and a ref
returned to the caller transfers ownership."""


class Engine:
    def __init__(self, pool, runner):
        self.pool = pool
        self.runner = runner

    def splice(self, blk, key):
        p = self.pool.alloc_page()
        try:
            self.runner.restore_pages([p], [blk])
        except Exception:
            self.pool.unref_page(p)     # unwritten page frees cleanly
            raise
        self.pool.register(p, key)
        self.pool.unref_page(p)

    def claim(self):
        return self.pool.alloc_page()   # caller owns the ref
