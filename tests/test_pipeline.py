"""Pipeline-parallel schedule tests: stage partitioning, 1F1B parity vs dense,
interleaved virtual stages, schedule structure (reference semantics:
fleet/meta_parallel/pipeline_parallel.py:575 1F1B, :1179 interleave)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                     LlamaForCausalLMPipe)
from paddle_tpu.parallel.pipeline_layer import (
    PipelineParallel, PipelineParallelWithInterleave, interleave_schedule)


def _cfg(n_layers=4):
    return LlamaConfig.tiny(num_hidden_layers=n_layers)


def _data(cfg, B=4, S=16, seed=0):
    rng = np.random.RandomState(seed)
    ids = rng.randint(0, cfg.vocab_size, (B, S + 1)).astype(np.int32)
    return paddle.to_tensor(ids[:, :-1]), paddle.to_tensor(ids[:, 1:])


def _dense_losses(cfg, steps=3, n_micro=4, lr=1e-2):
    """Dense baseline with the same microbatching (grad accumulation) the
    pipeline uses — MoE routing statistics are batch-dependent, so the
    comparable dense run must see identical microbatches."""
    from paddle_tpu import ops
    paddle.seed(0)
    model = LlamaForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=lr, parameters=model.parameters())
    losses = []
    for step in range(steps):
        x, y = _data(cfg, seed=step)
        total = None
        for xm, ym in zip(ops.split(x, n_micro, axis=0),
                          ops.split(y, n_micro, axis=0)):
            _, loss = model(xm, labels=ym)
            (loss / n_micro).backward()
            d = (loss / n_micro).detach()
            total = d if total is None else total + d
        opt.step()
        opt.clear_grad()
        losses.append(float(total))
    return losses


def _pipe_losses(cfg, pp, steps=3, n_micro=4, lr=1e-2, vpp=None, B=4):
    paddle.seed(0)
    pipe = LlamaForCausalLMPipe(cfg, num_stages=pp,
                                num_virtual_pipeline_stages=vpp)

    class _Strategy:
        pipeline_configs = {"accumulate_steps": n_micro}

    cls = PipelineParallelWithInterleave if vpp else PipelineParallel
    pp_model = cls(pipe, strategy=_Strategy())
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=pp_model.parameters())
    losses = []
    for step in range(steps):
        x, y = _data(cfg, B=B, seed=step)
        loss = pp_model.train_batch((x, y), opt)
        losses.append(float(loss))
    return losses, pp_model


class TestStagePartitioning:
    def test_layer_seg_method(self):
        cfg = _cfg(4)
        pipe = LlamaForCausalLMPipe(cfg, num_stages=4)
        # 6 items: embed + 4 decoders + head; embed joins stage 0, head last
        assert pipe.num_chunks == 4
        assert pipe._chunk_bounds == [(0, 2), (2, 3), (3, 4), (4, 6)]
        assert pipe.get_stage_from_index(0) == 0     # embedding on stage 0
        assert pipe.get_stage_from_index(5) == 3     # head on last stage

    def test_vpp_round_robin_assignment(self):
        cfg = _cfg(4)
        pipe = LlamaForCausalLMPipe(cfg, num_stages=2,
                                    num_virtual_pipeline_stages=2)
        assert pipe.num_chunks == 4
        # chunks 0,2 on stage 0; chunks 1,3 on stage 1
        assert [pipe.stage_of_chunk(c) for c in range(4)] == [0, 1, 0, 1]

    def test_uneven_layer_count_raises(self):
        cfg = _cfg(3)
        with pytest.raises(ValueError):
            LlamaForCausalLMPipe(cfg, num_stages=2)


class TestPP1F1B:
    def test_pp4_loss_parity_vs_dense(self):
        """VERDICT #2 done-criterion: pp=4 tiny-Llama == dense to 1e-5, 3 steps."""
        cfg = _cfg(4)
        dense = _dense_losses(cfg, steps=3, n_micro=4)
        piped, pp_model = _pipe_losses(cfg, pp=4, steps=3, n_micro=4)
        np.testing.assert_allclose(piped, dense, atol=1e-5, rtol=1e-5)

    def test_1f1b_in_flight_bound(self):
        """1F1B keeps at most P microbatches live (GPipe would keep M)."""
        cfg = _cfg(2)
        _, pp_model = _pipe_losses(cfg, pp=2, steps=1, n_micro=8, B=8)
        assert pp_model.max_in_flight == 2

    def test_forward_matches_dense_forward(self):
        cfg = _cfg(4)
        paddle.seed(0)
        dense = LlamaForCausalLM(cfg)
        paddle.seed(0)
        pipe = LlamaForCausalLMPipe(cfg, num_stages=4)
        x, _ = _data(cfg)
        ref = dense(x)
        out = pipe(x)
        np.testing.assert_allclose(np.asarray(out._data), np.asarray(ref._data),
                                   atol=1e-5, rtol=1e-5)


class TestPPComposition:
    def test_moe_pp_parity_vs_dense(self):
        """MoE aux loss rides the boundary stream — each chunk's aux stays in
        its own tape segment (regression: backward crossed detach boundaries)."""
        cfg = LlamaConfig.tiny_moe(num_hidden_layers=4)
        dense = _dense_losses(cfg, steps=2, n_micro=4)
        piped, _ = _pipe_losses(cfg, pp=2, steps=2, n_micro=4)
        np.testing.assert_allclose(piped, dense, atol=1e-5, rtol=1e-5)

    def test_tied_embeddings_pinned_stages(self):
        """Tied embedding weight is shared across stage 0 and the last stage;
        it must stay unpinned and appear once in parameters()."""
        import jax
        cfg = LlamaConfig.tiny(num_hidden_layers=4, tie_word_embeddings=True)
        paddle.seed(0)
        pipe = LlamaForCausalLMPipe(cfg, num_stages=4)
        from paddle_tpu.distributed import ProcessMesh
        mesh = ProcessMesh(np.arange(len(jax.devices())), ["pp"]).jax_mesh()
        pipe.pin_stages(mesh, axis_name="pp")

        class _Strategy:
            pipeline_configs = {"accumulate_steps": 2}

        model = PipelineParallel(pipe, strategy=_Strategy())
        names = [n for n, _ in pipe.named_parameters()]
        assert len(names) == len(set(names))
        opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                     parameters=model.parameters())
        x, y = _data(cfg)
        loss = model.train_batch((x, y), opt)
        assert np.isfinite(float(loss))


class TestZeroBubble:
    def test_zb_loss_parity_vs_dense(self):
        """ZB-H1 reorders dW compute but grads (hence losses over steps) must
        match dense exactly like 1F1B does."""
        from paddle_tpu.parallel.pipeline_layer import ZeroBubblePipelineParallel
        cfg = _cfg(4)
        dense = _dense_losses(cfg, steps=3, n_micro=4)
        paddle.seed(0)
        pipe = LlamaForCausalLMPipe(cfg, num_stages=4)

        class _Strategy:
            pipeline_configs = {"accumulate_steps": 4}

        model = ZeroBubblePipelineParallel(pipe, strategy=_Strategy())
        assert model.schedule_mode == "ZB-H1"
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        losses = []
        for step in range(3):
            x, y = _data(cfg, seed=step)
            losses.append(float(model.train_batch((x, y), opt)))
        # dW work was actually deferred, not inlined
        assert model.w_deferred_total > 0
        np.testing.assert_allclose(losses, dense, atol=1e-5, rtol=1e-5)

    def test_zb_defers_weight_grads(self):
        """Until the W queue runs, parameter .grad stays empty while the
        chunk-boundary activation grads have already propagated."""
        from paddle_tpu.autograd.backward import backward_split
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(8, 8)
        x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype("float32"))
        x.stop_gradient = False
        y = lin(x)
        loss = (y * y).mean()
        param_ids = {id(p) for p in lin.parameters()}
        deferred = backward_split([loss], [None], param_ids)
        assert x.grad is not None                # B: input grad propagated now
        assert all(p.grad is None for p in lin.parameters())
        assert len(deferred) >= 1
        for w in deferred:
            w()
        # W grads match a joint backward
        ref_lin = nn.Linear(8, 8)
        ref_lin.set_state_dict(lin.state_dict())
        x2 = paddle.to_tensor(np.asarray(x._data))
        x2.stop_gradient = False
        loss2 = (ref_lin(x2) * ref_lin(x2)).mean()
        loss2.backward()
        for p, q in zip(lin.parameters(), ref_lin.parameters()):
            np.testing.assert_allclose(np.asarray(p.grad._data),
                                       np.asarray(q.grad._data),
                                       atol=1e-6, rtol=1e-6)
        np.testing.assert_allclose(np.asarray(x.grad._data),
                                   np.asarray(x2.grad._data),
                                   atol=1e-6, rtol=1e-6)


class TestInterleave:
    def test_interleave_parity_vs_dense(self):
        cfg = _cfg(4)
        dense = _dense_losses(cfg, steps=2, n_micro=4)
        piped, pp_model = _pipe_losses(cfg, pp=2, steps=2, n_micro=4, vpp=2)
        assert pp_model.schedule_mode == "interleave"
        np.testing.assert_allclose(piped, dense, atol=1e-5, rtol=1e-5)

    def test_requires_vpp_container(self):
        cfg = _cfg(4)
        pipe = LlamaForCausalLMPipe(cfg, num_stages=2)
        with pytest.raises(ValueError):
            PipelineParallelWithInterleave(pipe)

    def test_schedule_structure(self):
        """Warmup depth and op counts follow the Megatron interleave formula."""
        M, P, V = 4, 2, 2
        for rank in range(P):
            sched = interleave_schedule(M, P, V, rank)
            fwd = [s for s in sched if s[0] == "F"]
            bwd = [s for s in sched if s[0] == "B"]
            assert len(fwd) == M * V and len(bwd) == M * V
            warmup = min((P - rank - 1) * 2 + (V - 1) * P, M * V)
            # the first `warmup` ops are all forwards
            assert all(s[0] == "F" for s in sched[:warmup])
            if warmup < M * V:
                assert sched[warmup + 1][0] == "B"     # steady state alternates
            # every (micro, chunk) forwarded exactly once, backwarded once
            assert len({(m, c) for _, m, c in fwd}) == M * V
            assert len({(m, c) for _, m, c in bwd}) == M * V
