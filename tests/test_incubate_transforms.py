"""incubate.nn fused layers + vision.transforms round-2 additions."""
import numpy as np
import pytest

import paddle_tpu as paddle

rng = np.random.RandomState(0)


class TestFusedLayers:
    def test_fused_linear_matches_linear(self):
        from paddle_tpu.incubate.nn import FusedLinear
        paddle.seed(0)
        fl = FusedLinear(8, 4)
        x = paddle.to_tensor(rng.rand(2, 8).astype(np.float32))
        want = x.numpy() @ fl.weight.numpy() + fl.bias.numpy()
        np.testing.assert_allclose(fl(x).numpy(), want, rtol=1e-5)

    def test_fused_mha_matches_unfused_math(self):
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention
        paddle.seed(1)
        E, H = 16, 4
        mha = FusedMultiHeadAttention(E, H, dropout_rate=0.0,
                                      attn_dropout_rate=0.0,
                                      normalize_before=True)
        mha.eval()
        x = paddle.to_tensor(rng.rand(2, 6, E).astype(np.float32))
        out = mha(x)
        assert out.shape == [2, 6, E]
        # manual recompute
        import paddle_tpu.nn.functional as F
        xn = F.layer_norm(x, [E], mha.pre_ln_scale, mha.pre_ln_bias, 1e-5)
        qkv = np.einsum("bse,thde->bsthd", xn.numpy(), mha.qkv_weight.numpy())
        qkv = qkv + mha.qkv_bias.numpy().reshape(3, H, E // H)[None, None]
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        s = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(E // H)
        p = np.exp(s - s.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        att = np.einsum("bhst,bthd->bshd", p, v).reshape(2, 6, E)
        want = att @ mha.linear_weight.numpy() + mha.linear_bias.numpy() \
            + x.numpy()
        np.testing.assert_allclose(out.numpy(), want, rtol=1e-3, atol=1e-4)

    def test_fused_ffn_trains(self):
        from paddle_tpu.incubate.nn import FusedFeedForward
        paddle.seed(2)
        ffn = FusedFeedForward(8, 16, dropout_rate=0.0,
                               normalize_before=False)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=ffn.parameters())
        x = paddle.to_tensor(rng.rand(4, 5, 8).astype(np.float32))
        losses = []
        for _ in range(10):
            loss = (ffn(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]

    def test_fused_encoder_layer_shape(self):
        from paddle_tpu.incubate.nn import FusedTransformerEncoderLayer
        paddle.seed(3)
        layer = FusedTransformerEncoderLayer(16, 4, 32, dropout_rate=0.0)
        layer.eval()
        x = paddle.to_tensor(rng.rand(2, 7, 16).astype(np.float32))
        assert layer(x).shape == [2, 7, 16]

    def test_mha_guards(self):
        from paddle_tpu.incubate.nn import FusedMultiHeadAttention
        from paddle_tpu.incubate.nn import functional as IF
        with pytest.raises(ValueError, match="must divide embed_dim"):
            FusedMultiHeadAttention(10, 4)
        mha = FusedMultiHeadAttention(16, 4)
        q = paddle.to_tensor(rng.rand(1, 3, 16).astype(np.float32))
        k = paddle.to_tensor(rng.rand(1, 3, 16).astype(np.float32))
        with pytest.raises(NotImplementedError, match="self-attention"):
            mha(q, key=k)
        # 2D qkv weight without num_heads must raise, not guess 8
        with pytest.raises(ValueError, match="num_heads"):
            IF.fused_multi_head_attention(
                q, paddle.to_tensor(rng.rand(16, 48).astype(np.float32)),
                paddle.to_tensor(rng.rand(16, 16).astype(np.float32)))

    def test_functional_fused_ops(self):
        from paddle_tpu.incubate.nn import functional as IF
        x = paddle.to_tensor(rng.rand(3, 8).astype(np.float32),
                             stop_gradient=False)
        y = paddle.to_tensor(rng.rand(3, 8).astype(np.float32))
        out = IF.fused_dropout_add(x, y, p=0.0)
        np.testing.assert_allclose(out.numpy(), x.numpy() + y.numpy())
        b = paddle.to_tensor(np.zeros(8, np.float32))
        act = IF.fused_bias_act(x, b, act_method="relu")
        np.testing.assert_allclose(act.numpy(), np.maximum(x.numpy(), 0))
        # swiglu via fused_bias_act
        g = IF.fused_bias_act(x, None, act_method="swiglu")
        assert g.shape == [3, 4]


class TestTransforms:
    def _img(self):
        return (rng.rand(3, 12, 10) * 255).astype(np.float32)

    def test_center_crop_and_pad(self):
        import paddle_tpu.vision.transforms as T
        img = self._img()
        c = T.CenterCrop(8)(img)
        assert c.shape == (3, 8, 8)
        p = T.Pad(2)(img)
        assert p.shape == (3, 16, 14)
        np.testing.assert_allclose(p[:, 2:-2, 2:-2], img)

    def test_flips(self):
        import paddle_tpu.vision.transforms as T
        img = self._img()
        np.testing.assert_allclose(T.hflip(img), img[:, :, ::-1])
        np.testing.assert_allclose(T.vflip(img), img[:, ::-1, :])
        assert T.RandomVerticalFlip(prob=1.0)(img).shape == img.shape

    def test_grayscale_and_color(self):
        import paddle_tpu.vision.transforms as T
        img = self._img()
        g = T.Grayscale()(img)
        assert g.shape == (1, 12, 10)
        g3 = T.Grayscale(3)(img)
        assert g3.shape == (3, 12, 10)
        # float images use the 0..1 convention; uint8 use 0..255 (by DTYPE)
        f01 = img / 255.0
        np.testing.assert_allclose(T.adjust_brightness(f01, 0.5), f01 * 0.5)
        u8 = img.astype(np.uint8)
        b = T.adjust_brightness(u8, 1.5)
        assert b.dtype == np.uint8 and int(b.max()) > int(u8.max())
        # dark uint8 image is NOT clipped at 1 (regression: dtype not data)
        dark = np.ones((3, 4, 4), np.uint8)
        np.testing.assert_allclose(T.adjust_brightness(dark, 50.0),
                                   np.full((3, 4, 4), 50, np.uint8))
        c = T.adjust_contrast(f01, 1.5)
        assert c.shape == img.shape and np.isfinite(c).all()
        # saturation-0 equals weighted luminance (consistent w/ to_grayscale)
        sat0 = T.adjust_saturation(f01, 0.0)
        np.testing.assert_allclose(sat0, np.broadcast_to(
            T.Grayscale()(f01), sat0.shape), atol=1e-6)
        # 1- and 4-channel grayscale don't crash
        assert T.to_grayscale(np.zeros((1, 8, 8), np.float32)).shape == \
            (1, 8, 8)
        assert T.to_grayscale(np.zeros((8, 8, 4), np.float32)).shape == \
            (8, 8, 1)
        j = T.ColorJitter(0.2, 0.2, 0.2)(f01)
        assert j.shape == img.shape

    def test_rotation(self):
        import paddle_tpu.vision.transforms as T
        img = self._img()
        r = T.rotate(img, 90)
        assert r.shape == img.shape
        rr = T.RandomRotation(30)(img)
        assert rr.shape == img.shape

    def test_random_resized_crop(self):
        import paddle_tpu.vision.transforms as T
        out = T.RandomResizedCrop(8)(self._img())
        assert out.shape == (3, 8, 8)

    def test_compose_pipeline(self):
        import paddle_tpu.vision.transforms as T
        pipe = T.Compose([T.Resize(16), T.CenterCrop(12),
                          T.RandomHorizontalFlip(0.5),
                          T.Normalize(mean=127.5, std=127.5)])
        out = pipe(self._img())
        assert out.shape == (3, 12, 12)
        assert abs(float(out.mean())) < 1.5
