"""Preemption-aware checkpoint-restart (VERDICT r2 #7 done-criterion): a
launched 2-proc job SIGTERM'd mid-train checkpoints, exits restartable, and
the restarted job continues from the checkpointed step with loss continuity
against an uninterrupted reference run."""
import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(workdir, max_restarts, nproc=2):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    env["PREEMPT_DIR"] = str(workdir)
    env["PREEMPT_STEPS"] = "20"
    env["PREEMPT_SLEEP"] = "0.25"
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--master", f"127.0.0.1:{_free_port()}",
           "--log_dir", str(workdir / "log"),
           "--nproc_per_node", str(nproc), "--backend", "cpu",
           "--max_restarts", str(max_restarts),
           os.path.join(ROOT, "tests", "preempt_worker.py")]
    return subprocess.Popen(cmd, env=env, cwd=ROOT,
                            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                            text=True)


def _losses(workdir):
    """step -> loss per rank across all attempts; asserts no step ran twice
    with diverging values."""
    out = {}
    for f in workdir.glob("loss_rank*_pid*.jsonl"):
        rank = int(f.name.split("rank")[1].split("_")[0])
        for line in f.read_text().splitlines():
            d = json.loads(line)
            out.setdefault(rank, {}).setdefault(d["step"], d["loss"])
    return out


@pytest.mark.timeout(300)
def test_sigterm_checkpoint_restart_resumes(tmp_path):
    # reference: uninterrupted run
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    p = _launch(ref_dir, max_restarts=0)
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, err[-2000:]
    ref = _losses(ref_dir)
    assert sorted(ref[0]) == list(range(20))

    # preempted run: SIGTERM both workers a few steps in
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    p = _launch(run_dir, max_restarts=2)
    deadline = time.time() + 120
    pids = []
    while time.time() < deadline and len(pids) < 2:
        pids = [f for f in run_dir.glob("pid_rank*.txt")]
        time.sleep(0.2)
    assert len(pids) == 2, "workers never started"
    # preempt once the train loop is demonstrably RUNNING (>=2 steps logged)
    def steps_logged():
        n = 0
        for f in run_dir.glob("loss_rank0_pid*.jsonl"):
            n = max(n, len(f.read_text().splitlines()))
        return n
    while time.time() < deadline and steps_logged() < 2:
        time.sleep(0.1)
    assert steps_logged() >= 2, "train loop never progressed"
    assert steps_logged() < 20, "loop finished before we could preempt"
    for f in pids:
        try:
            os.kill(int(f.read_text()), signal.SIGTERM)
        except ProcessLookupError:
            pass
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, (out[-1000:], err[-2000:])
    assert "elastic restart" in err or "restart" in err, err[-2000:]

    # a complete checkpoint exists and the combined log covers every step
    # exactly once per rank with values matching the uninterrupted run
    ckpts = list((run_dir / "ckpt").glob("step_*"))
    assert ckpts, "no checkpoint written on SIGTERM"
    got = _losses(run_dir)
    for rank in (0, 1):
        assert sorted(got[rank]) == list(range(20)), \
            f"rank {rank} steps: {sorted(got[rank])}"
        for step in range(20):
            assert abs(got[rank][step] - ref[rank][step]) < 1e-5, \
                (rank, step, got[rank][step], ref[rank][step])


@pytest.mark.timeout(300)
def test_resume_across_world_size_change(tmp_path):
    """VERDICT r3 #6: kill a 4-proc run, restart as 2-proc, loss continuity.
    The worker's DP setup feeds identical data to every rank, so the loss
    sequence is world-size-invariant and directly comparable."""
    # uninterrupted 2-proc reference
    ref_dir = tmp_path / "ref"
    ref_dir.mkdir()
    p = _launch(ref_dir, max_restarts=0)
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, err[-2000:]
    ref = _losses(ref_dir)

    # 4-proc run, SIGTERM'd mid-train (no in-place restart: the "cluster"
    # shrinks instead)
    run_dir = tmp_path / "run"
    run_dir.mkdir()
    p = _launch(run_dir, max_restarts=0, nproc=4)
    deadline = time.time() + 120

    def steps_logged():
        n = 0
        for f in run_dir.glob("loss_rank0_pid*.jsonl"):
            n = max(n, len(f.read_text().splitlines()))
        return n

    pids = []
    while time.time() < deadline and len(pids) < 4:
        pids = list(run_dir.glob("pid_rank*.txt"))
        time.sleep(0.2)
    assert len(pids) == 4, "4-proc workers never started"
    while time.time() < deadline and steps_logged() < 2:
        time.sleep(0.1)
    assert 2 <= steps_logged() < 20, steps_logged()
    for f in pids:
        try:
            os.kill(int(f.read_text()), signal.SIGTERM)
        except ProcessLookupError:
            pass
    p.communicate(timeout=240)     # preempted: nonzero rc expected

    ckpts = list((run_dir / "ckpt").glob("step_*"))
    assert ckpts, "no checkpoint written on SIGTERM"
    for f in pids:                 # restart reuses the pid files
        f.unlink()

    # restart the SAME job dir at HALF the world size
    p = _launch(run_dir, max_restarts=0, nproc=2)
    out, err = p.communicate(timeout=240)
    assert p.returncode == 0, err[-2000:]

    got = _losses(run_dir)
    for rank in (0, 1):
        assert sorted(got[rank]) == list(range(20)), \
            f"rank {rank} steps: {sorted(got[rank])}"
        for step in range(20):
            assert abs(got[rank][step] - ref[rank][step]) < 1e-5, \
                (rank, step, got[rank][step], ref[rank][step])
