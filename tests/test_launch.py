"""Multi-host bring-up tests (VERDICT #4): the launch CLI spawns a real
2-process CPU-backend job; workers rendezvous via jax.distributed + TCPStore
and exercise every explicit collective (reference launch/main.py:23,
parallel.py:978, tcp_store.h:121)."""
import os
import socket
import subprocess
import sys
import threading

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_launch(tmp_path, extra_args, script, timeout=300):
    env = dict(os.environ)
    env["PYTHONPATH"] = ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env.pop("JAX_PLATFORMS", None)
    cmd = [sys.executable, "-m", "paddle_tpu.distributed.launch",
           "--master", f"127.0.0.1:{_free_port()}",
           "--log_dir", str(tmp_path / "log"), *extra_args, script]
    return subprocess.run(cmd, env=env, cwd=ROOT, timeout=timeout,
                          capture_output=True, text=True), tmp_path / "log"


class TestLaunch2Proc:
    def test_collectives_and_dp_step(self, tmp_path):
        res, logdir = _run_launch(
            tmp_path, ["--nproc_per_node", "2", "--backend", "cpu"],
            os.path.join(ROOT, "tests", "launch_worker.py"))
        logs = ""
        for f in sorted(logdir.glob("workerlog.*")):
            logs += f"--- {f.name} ---\n" + f.read_text()
        assert res.returncode == 0, f"launch failed:\n{res.stderr}\n{logs}"
        assert logs.count("LAUNCH_WORKER_OK") == 2, logs

    def test_failure_propagates(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text("import sys; sys.exit(3)\n")
        res, _ = _run_launch(tmp_path, ["--nproc_per_node", "2",
                                        "--backend", "cpu"], str(bad))
        assert res.returncode != 0

    def test_elastic_restart(self, tmp_path):
        """First attempt fails (marker file missing), restart succeeds —
        the fleet/elastic/manager.py:125 restart loop."""
        script = tmp_path / "flaky.py"
        marker = tmp_path / "attempted"
        script.write_text(
            "import os, sys\n"
            f"m = {str(repr(str(marker)))}\n"
            "if not os.path.exists(m):\n"
            "    open(m, 'w').close()\n"
            "    sys.exit(1)\n"
            "print('RECOVERED')\n")
        res, logdir = _run_launch(
            tmp_path, ["--nproc_per_node", "1", "--max_restarts", "2"],
            str(script))
        assert res.returncode == 0, res.stderr
        logs = "".join(f.read_text() for f in logdir.glob("workerlog.*"))
        assert "RECOVERED" in logs


class TestTCPStore:
    def test_kv_roundtrip_and_blocking_wait(self):
        from paddle_tpu.distributed.store import TCPStore
        master = TCPStore("127.0.0.1", 0, is_master=True)
        client = TCPStore("127.0.0.1", master.port)
        master.set("k1", b"v1")
        assert client.get("k1") == b"v1"
        assert client.add("ctr", 5) == 5
        assert master.add("ctr", 2) == 7

        def late_set():
            import time
            time.sleep(0.3)
            master.set("late", b"now")
        threading.Thread(target=late_set).start()
        assert client.get("late", timeout=5) == b"now"   # blocks until set
        with pytest.raises(TimeoutError):
            client.get("never", timeout=0.2)
        assert client.delete_key("k1") is True


class TestCommWatchdog:
    def test_timeout_detection(self):
        from paddle_tpu.distributed.watchdog import CommTaskManager
        mgr = CommTaskManager()          # private instance, not the singleton
        hits = []
        mgr.enable(timeout=0.3, on_timeout=hits.append, poll_interval=0.05)
        seq = mgr.begin("all_reduce_hang", rank=0)
        ok_seq = mgr.begin("all_reduce_fast", rank=0)
        mgr.end(ok_seq)                  # completes in time
        import time
        time.sleep(1.0)
        mgr.disable()
        assert len(hits) == 1 and hits[0].name == "all_reduce_hang"
        assert mgr.timed_out and mgr.timed_out[0].name == "all_reduce_hang"
        assert not mgr.in_flight()

    def test_collectives_register_when_enabled(self):
        import paddle_tpu as paddle
        import paddle_tpu.distributed as dist
        from paddle_tpu.distributed.watchdog import CommTaskManager
        import numpy as np
        mgr = CommTaskManager.instance()
        mgr.enable(timeout=60)
        try:
            t = paddle.to_tensor(np.ones((2,), np.float32))
            dist.all_reduce(t)           # single-process fast path, still tracked
            assert not mgr.in_flight()   # completed and deregistered
        finally:
            mgr.disable()
