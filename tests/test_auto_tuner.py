"""Distributed auto-tuner: candidate generation, pruning, trial loop."""
import time

import numpy as np
import pytest

from paddle_tpu.distributed.auto_tuner import (AutoTuner, Recorder,
                                               candidate_configs)


class TestCandidates:
    def test_factorizations_cover_devices(self):
        for c in candidate_configs(8, micro_batches=(1,)):
            assert (c["dp_degree"] * c["mp_degree"] * c["pp_degree"] *
                    c["sharding_degree"]) == 8

    def test_prune_by_mp_and_pp(self):
        cands = candidate_configs(16, num_layers=6, max_mp=4,
                                  micro_batches=(1,))
        assert all(c["mp_degree"] <= 4 for c in cands)
        # pp must divide layer count: pp in {1, 2} (3 not a divisor of 16...
        # and 6 % 4 != 0 kills pp=4)
        assert all(c["pp_degree"] in (1, 2) for c in cands)

    def test_prune_by_batch_divisibility(self):
        cands = candidate_configs(8, global_batch=16, micro_batches=(1, 2, 3))
        for c in cands:
            dpsh = c["dp_degree"] * c["sharding_degree"]
            assert 16 % dpsh == 0
            assert (16 // dpsh) % c["micro_batch_size"] == 0


class TestTunerLoop:
    def test_search_and_best(self):
        tuner = AutoTuner({"num_devices": 4, "micro_batches": (1,)})
        assert tuner.search_space_size > 0
        n = 0
        while True:
            cfg = tuner.search_once()
            if cfg is None:
                break
            n += 1
            # synthetic objective: favor dp=4 pure-data-parallel
            tuner.add_cfg(cfg, metric=10.0 * cfg["dp_degree"] -
                          cfg["pp_degree"])
        assert n == tuner.search_space_size
        best = tuner.best_cfg()
        assert best["dp_degree"] == 4 and best["pp_degree"] == 1

    def test_run_trials_times_and_skips_failures(self):
        tuner = AutoTuner({"num_devices": 2, "micro_batches": (1,)})

        def make_step(cfg):
            if cfg["mp_degree"] == 2:
                raise RuntimeError("pretend OOM")

            def step():
                time.sleep(0.001 * cfg["pp_degree"])
            return step

        best = tuner.run_trials(make_step, warmup=0, iters=2)
        assert best is not None and best["mp_degree"] != 2
        errs = [h for h in tuner.recorder.history if h["error"]]
        assert errs and "OOM" in errs[0]["error"]

    def test_recorder_roundtrip(self, tmp_path):
        r = Recorder()
        r.add_cfg({"dp_degree": 2, "mp_degree": 1}, metric=5.0)
        r.add_cfg({"dp_degree": 1, "mp_degree": 2}, metric=7.5)
        p = str(tmp_path / "history.csv")
        r.store_history(p)
        r2 = Recorder()
        r2.load_history(p)
        assert r2.sort_metric()[0]["metric"] == 7.5

    def test_real_mesh_trial_on_cpu_devices(self):
        """End-to-end: trial a tiny sharded matmul step per config on the
        8-device CPU mesh."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = np.array(jax.devices()[:8])
        tuner = AutoTuner({"num_devices": 8, "max_mp_degree": 8,
                           "micro_batches": (1,)})
        w = jnp.ones((64, 64))
        x = jnp.ones((32, 64))

        def make_step(cfg):
            dp, mp = cfg["dp_degree"], cfg["mp_degree"]
            if cfg["pp_degree"] != 1 or cfg["sharding_degree"] != 1:
                raise RuntimeError("trial supports dp x mp only")
            mesh = Mesh(devs.reshape(dp, mp), ("dp", "mp"))
            xs = jax.device_put(x, NamedSharding(mesh, P("dp", None)))
            ws = jax.device_put(w, NamedSharding(mesh, P(None, "mp")))
            f = jax.jit(lambda a, b: (a @ b).sum())

            def step():
                jax.block_until_ready(f(xs, ws))
            return step

        best = tuner.run_trials(make_step, warmup=1, iters=2)
        assert best is not None
        assert best["pp_degree"] == 1 and best["sharding_degree"] == 1


class TestAnalyticCostModel:
    """VERDICT r4 missing #4: analytic comp/comm cost estimates so the
    search can rank candidates it never runs (reference
    auto_parallel/static/cost/estimate_cost.py)."""

    def _model(self, **kw):
        from paddle_tpu.distributed.auto_tuner import (AnalyticCostModel,
                                                       ModelDesc)
        desc = dict(num_layers=32, hidden=4096, seq_len=4096, vocab=128256,
                    intermediate=14336, global_batch=64)
        desc.update(kw)
        return AnalyticCostModel(ModelDesc(**desc), hw="v5p")

    def test_memory_infeasible_pruned(self):
        cm = self._model()
        # Llama-8B-ish on ONE chip: weights+AdamW alone bust HBM
        est = cm.estimate({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                           "sharding_degree": 1, "micro_batch_size": 1})
        assert not est["feasible"] and est["step_time_s"] == float("inf")
        # sharded over 64 chips: fits
        est64 = cm.estimate({"dp_degree": 8, "mp_degree": 8, "pp_degree": 1,
                             "sharding_degree": 1, "micro_batch_size": 1})
        assert est64["feasible"]

    def test_tp_comm_grows_with_mp(self):
        cm = self._model()
        base = dict(pp_degree=1, sharding_degree=1, micro_batch_size=1)
        e2 = cm.estimate({**base, "dp_degree": 32, "mp_degree": 2})
        e8 = cm.estimate({**base, "dp_degree": 8, "mp_degree": 8})
        assert e8["tp_comm_s"] > e2["tp_comm_s"]

    def test_pp_bubble_shrinks_with_more_microbatches(self):
        cm = self._model()
        base = dict(dp_degree=2, mp_degree=4, pp_degree=4,
                    sharding_degree=1)
        few = cm.estimate({**base, "micro_batch_size": 16})
        many = cm.estimate({**base, "micro_batch_size": 1})
        assert many["pp_bubble_frac"] < few["pp_bubble_frac"]

    def test_rank_orders_feasible_first_and_by_time(self):
        from paddle_tpu.distributed.auto_tuner import candidate_configs
        cm = self._model()
        cfgs = candidate_configs(64, num_layers=32, global_batch=64)
        ranked = cm.rank(cfgs)
        times = [c["_estimate"]["step_time_s"] for c in ranked]
        assert times == sorted(times)
        assert ranked[0]["_estimate"]["feasible"]

    def test_autotuner_prunes_with_cost_model(self):
        from paddle_tpu.distributed.auto_tuner import AutoTuner
        cm = self._model()
        tuner = AutoTuner({"num_devices": 64, "num_layers": 32,
                           "global_batch_size": 64, "prune_to": 5},
                          cost_model=cm)
        assert tuner.search_space_size == 5
        # every surviving candidate is feasible and carries its estimate
        seen = []
        while True:
            cfg = tuner.search_once()
            if cfg is None:
                break
            assert cfg["_estimate"]["feasible"]
            seen.append(cfg)
        assert len(seen) == 5

    def test_small_model_prefers_pure_dp(self):
        """A small model fitting on one chip: splitting it (mp) only adds
        comm, so pure dp must rank first among 8-chip layouts."""
        cm = self._model(num_layers=12, hidden=768, seq_len=1024,
                         vocab=50257, intermediate=3072, global_batch=64)
        from paddle_tpu.distributed.auto_tuner import candidate_configs
        cfgs = candidate_configs(8, num_layers=12, global_batch=64)
        best = cm.rank(cfgs)[0]
        assert best["mp_degree"] == 1 and best["pp_degree"] == 1
        assert best["dp_degree"] * best["sharding_degree"] == 8
