import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.autograd import PyLayer


def test_simple_backward():
    x = pt.to_tensor([2.0, 3.0], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 6.0])


def test_chain_and_accumulate():
    x = pt.to_tensor(2.0, stop_gradient=False)
    y = x * x + x * 3  # dy/dx = 2x + 3 = 7
    y.backward()
    assert x.grad.item() == pytest.approx(7.0)
    # second backward accumulates into .grad
    z = x * 5
    z.backward()
    assert x.grad.item() == pytest.approx(12.0)


def test_clear_grad():
    x = pt.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    x.clear_grad()
    assert x.grad is None


def test_branching_graph():
    x = pt.to_tensor(3.0, stop_gradient=False)
    a = x * 2
    b = x * 4
    c = a + b  # dc/dx = 6
    c.backward()
    assert x.grad.item() == pytest.approx(6.0)


def test_shared_intermediate():
    x = pt.to_tensor(2.0, stop_gradient=False)
    h = x * x       # used twice
    y = h * 3 + h * 5  # y = 8x^2, dy/dx = 16x = 32
    y.backward()
    assert x.grad.item() == pytest.approx(32.0)


def test_stop_gradient_blocks():
    x = pt.to_tensor(1.0, stop_gradient=False)
    y = pt.to_tensor(2.0)  # stop_gradient=True
    z = x * y
    z.backward()
    assert x.grad.item() == pytest.approx(2.0)
    assert y.grad is None


def test_no_grad_context():
    x = pt.to_tensor(1.0, stop_gradient=False)
    with pt.no_grad():
        y = x * 2
    assert y.stop_gradient and y._grad_node is None


def test_no_grad_decorator():
    @pt.no_grad()
    def f(t):
        return t * 2
    out = f(pt.to_tensor(1.0, stop_gradient=False))
    assert out.stop_gradient


def test_paddle_grad_api():
    x = pt.to_tensor(2.0, stop_gradient=False)
    y = x * x * x
    (g,) = pt.grad(y, x)
    assert g.item() == pytest.approx(12.0)
    assert x.grad is None  # pt.grad must not pollute .grad


def test_grad_allow_unused():
    x = pt.to_tensor(1.0, stop_gradient=False)
    u = pt.to_tensor(1.0, stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        pt.grad(y, [x, u])
    y = x * 2  # rebuild: the failed sweep freed the graph (paddle semantics)
    g = pt.grad(y, [x, u], allow_unused=True)
    assert g[1] is None


def test_backward_nonscalar_default_ones():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * 3
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0, 3.0])


def test_backward_with_grad_tensor():
    x = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    y = x * x
    y.backward(pt.to_tensor([1.0, 0.5]))
    np.testing.assert_allclose(x.grad.numpy(), [2.0, 2.0])


def test_retain_graph():
    x = pt.to_tensor(2.0, stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    assert x.grad.item() == pytest.approx(8.0)
    with pytest.raises(RuntimeError):
        y.backward()  # graph freed now


def test_hooks_modify_grad():
    x = pt.to_tensor(1.0, stop_gradient=False)
    h = x.register_hook(lambda g: g * 10)
    (x * 2).backward()
    assert x.grad.item() == pytest.approx(20.0)
    h.remove()
    x.clear_grad()
    (x * 2).backward()
    assert x.grad.item() == pytest.approx(2.0)


def test_retain_grads_intermediate():
    x = pt.to_tensor(2.0, stop_gradient=False)
    h = x * 3
    h.retain_grads()
    y = h * h
    y.backward()
    assert h.grad.item() == pytest.approx(12.0)


def test_multi_output_op_grad():
    x = pt.to_tensor(np.arange(6, dtype=np.float32), stop_gradient=False)
    a, b, c = pt.split(x, 3)
    (a.sum() * 1 + b.sum() * 2 + c.sum() * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [1, 1, 2, 2, 3, 3])


def test_pylayer():
    class Cube(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * x * x

        @staticmethod
        def backward(ctx, dy):
            (x,) = ctx.saved_tensor
            return dy * 3 * x * x

    x = pt.to_tensor(2.0, stop_gradient=False)
    y = Cube.apply(x)
    assert y.item() == pytest.approx(8.0)
    y.backward()
    assert x.grad.item() == pytest.approx(12.0)


def test_matmul_grad_matches_reference():
    rng = np.random.RandomState(0)
    a = rng.rand(3, 4).astype(np.float32)
    b = rng.rand(4, 2).astype(np.float32)
    x = pt.to_tensor(a, stop_gradient=False)
    w = pt.to_tensor(b, stop_gradient=False)
    (x @ w).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.ones((3, 2)) @ b.T, rtol=1e-5)
    np.testing.assert_allclose(w.grad.numpy(), a.T @ np.ones((3, 2)), rtol=1e-5)
