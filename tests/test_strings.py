"""String tensor + kernels (reference: phi/core/string_tensor.h,
phi/kernels/strings/{empty,copy,lower_upper}_kernel.h)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import strings


def test_construct_shape_reshape_index():
    st = strings.to_string_tensor([["Hello", "WORLD"], ["Ä", "ß"]])
    assert st.shape == [2, 2] and st.numel() == 4 and st.dtype == "pstring"
    assert st[0, 1] == "WORLD"
    r = st.reshape([4])
    assert r.tolist() == ["Hello", "WORLD", "Ä", "ß"]
    b = strings.StringTensor([b"caf\xc3\xa9"])     # bytes decode as UTF-8
    assert b[0] == "café"


def test_empty_and_copy():
    e = strings.empty([2, 3])
    assert e.shape == [2, 3] and all(v == "" for v in e.reshape([6]).tolist())
    src = strings.to_string_tensor(["a", "b"])
    cp = strings.copy(src)
    assert cp.tolist() == ["a", "b"]
    cp._data[0] = "changed"
    assert src[0] == "a"                            # deep copy


def test_lower_upper_ascii_vs_utf8():
    st = strings.to_string_tensor(["HeLLo", "Ärger", "straße", "ÇA"])
    lo_ascii = strings.lower(st)                    # ascii: [A-Z] only
    assert lo_ascii.tolist() == ["hello", "Ärger", "straße", "Ça"]
    lo_utf8 = strings.lower(st, use_utf8_encoding=True)
    assert lo_utf8.tolist() == ["hello", "ärger", "straße", "ça"]
    up_ascii = strings.upper(st)
    assert up_ascii.tolist() == ["HELLO", "ÄRGER", "STRAßE", "ÇA"]
    up_utf8 = strings.upper(st, use_utf8_encoding=True)
    assert up_utf8.tolist() == ["HELLO", "ÄRGER", "STRASSE", "ÇA"]


def test_lazy_namespace():
    assert pt.strings.StringTensor is strings.StringTensor
