"""incubate.asp structured-sparsity tests (reference: python/paddle/incubate/
asp/asp.py decorate:233 prune_model:319, utils.py mask/density helpers)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.incubate import asp


@pytest.fixture(autouse=True)
def _reset():
    asp.reset_excluded_layers()
    asp._masks.clear()
    yield
    asp.reset_excluded_layers()
    asp._masks.clear()


class TestMasks:
    def test_mask_1d_pattern(self):
        w = np.arange(32, dtype=np.float32).reshape(4, 8) - 16
        mask = asp.create_mask(w, n=2, m=4)
        groups = mask.reshape(-1, 4)
        assert (groups.sum(axis=1) == 2).all()
        # largest-magnitude entries survive
        flat = w.reshape(-1, 4)
        for g in range(flat.shape[0]):
            keep = np.argsort(-np.abs(flat[g]))[:2]
            assert set(np.nonzero(groups[g])[0]) == set(keep)

    def test_mask_2d_both_directions_satisfy_nm(self):
        """Greedy 2-D n:m: AT MOST n survivors per m-group in BOTH row and
        column direction (the sparsity invariant; greedy may under-fill a
        group when row/col budgets collide — the reference's mask_2d_best
        exists for that), and density stays near n/m."""
        rng = np.random.RandomState(0)
        w = rng.randn(8, 8).astype(np.float32)
        mask = asp.create_mask(w, n=2, m=4, mask_algo="mask_2d_greedy")
        assert (mask.reshape(-1, 4).sum(axis=1) <= 2).all()
        assert (mask.T.reshape(-1, 4).sum(axis=1) <= 2).all()
        assert mask.mean() >= 0.4
        with pytest.raises(ValueError):   # rows not divisible by m
            asp.create_mask(rng.randn(6, 8).astype(np.float32),
                            mask_algo="mask_2d_greedy")

    def test_mask_2d_best_is_optimal_and_exact(self):
        """Exhaustive best: exactly n per m-group in BOTH directions and
        keeps at least as much |w| as greedy."""
        rng = np.random.RandomState(1)
        w = rng.randn(8, 8).astype(np.float32)
        best = asp.create_mask(w, n=2, m=4, mask_algo="mask_2d_best")
        greedy = asp.create_mask(w, n=2, m=4, mask_algo="mask_2d_greedy")
        assert (best.reshape(-1, 4).sum(1) == 2).all()
        assert (best.T.reshape(-1, 4).sum(1) == 2).all()
        assert (np.abs(w) * best).sum() >= (np.abs(w) * greedy).sum() - 1e-6

    def test_bad_shapes_raise(self):
        with pytest.raises(ValueError):
            asp.create_mask(np.zeros((4, 6), np.float32))   # 6 % 4 != 0
        with pytest.raises(ValueError):
            asp.create_mask(np.zeros(8, np.float32))        # ndim < 2
        with pytest.raises(ValueError):
            asp.create_mask(np.zeros((4, 8), np.float32), mask_algo="nope")

    def test_density_and_check(self):
        w = np.zeros((4, 8), np.float32)
        w[:, :2] = 1.0
        assert asp.calculate_density(w) == 0.25
        assert asp.check_sparsity(w, n=2, m=4)
        assert not asp.check_sparsity(np.ones((4, 8)), n=2, m=4)


class TestPruneAndTrain:
    def _model(self):
        paddle.seed(0)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    def test_prune_model_sets_2_4(self):
        model = self._model()
        pruned = asp.prune_model(model)
        assert len(pruned) == 2
        for _, layer in model.named_sublayers():
            w = getattr(layer, "weight", None)
            if w is not None and w.ndim == 2:
                assert asp.check_sparsity(w)
                assert abs(asp.calculate_density(w) - 0.5) < 1e-6

    def test_excluded_layer_not_pruned(self):
        model = self._model()
        asp.set_excluded_layers(["0"])
        pruned = asp.prune_model(model)
        assert "0" not in pruned and "2" in pruned
        assert asp.calculate_density(model[0].weight) > 0.9

    def test_decorated_optimizer_preserves_sparsity(self):
        model = self._model()
        asp.prune_model(model)
        opt = asp.decorate(paddle.optimizer.Adam(
            learning_rate=1e-2, parameters=model.parameters()))
        rng = np.random.RandomState(0)
        xs = rng.randn(32, 8).astype(np.float32)
        ys = rng.randn(32, 4).astype(np.float32)
        losses = []
        for _ in range(8):
            out = model(paddle.to_tensor(xs))
            loss = ((out - paddle.to_tensor(ys)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]           # trains
        for lyr in (model[0], model[2]):
            assert asp.check_sparsity(lyr.weight)   # sparsity survives steps
        # pass-through attribute access on the wrapper
        assert opt._lr == pytest.approx(1e-2)
