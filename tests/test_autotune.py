"""Autotune subsystem (reference: phi/kernels/autotune)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import autotune as at

rng = np.random.RandomState(0)


class TestAutotuneCore:
    def setup_method(self, _):
        at.clear()
        paddle.set_flags({"FLAGS_use_autotune": True})

    def teardown_method(self, _):
        at.clear()
        paddle.set_flags({"FLAGS_use_autotune": False})

    def test_tune_picks_and_caches(self):
        calls = []

        def build(cfg):
            def run(x):
                calls.append(cfg)
                import time
                if cfg == "slow":
                    time.sleep(0.01)
                return x * 2
            return run

        import jax.numpy as jnp
        args = (jnp.ones(4),)
        key = at.cache_key("op", 4, "float32")
        best = at.tune(key, ["slow", "fast"], build, args, iters=2)
        assert best == "fast"
        # cached: no further timing calls
        n = len(calls)
        again = at.tune(key, ["slow", "fast"], build, args)
        assert again == "fast" and len(calls) == n
        assert at.lookup(key) == "fast"

    def test_disabled_returns_default(self):
        paddle.set_flags({"FLAGS_use_autotune": False})
        import jax.numpy as jnp
        got = at.tune(at.cache_key("op2", 1), ["default", "other"],
                      lambda c: (lambda x: x), (jnp.ones(2),))
        assert got == "default"
        assert at.lookup(at.cache_key("op2", 1)) is None  # nothing cached

    def test_never_tunes_on_tracers(self):
        import jax
        import jax.numpy as jnp
        timed = []

        def build(cfg):
            def run(x):
                timed.append(cfg)
                return x
            return run

        def f(x):
            cfg = at.tune(at.cache_key("op3", 2), ["a", "b"], build, (x,))
            assert cfg == "a"   # default under trace
            return x

        jax.jit(f)(jnp.ones(3))
        assert timed == []

    def test_failing_candidate_skipped(self):
        import jax.numpy as jnp

        def build(cfg):
            if cfg == "bad":
                def boom(x):
                    raise RuntimeError("invalid config")
                return boom
            return lambda x: x + 1
        best = at.tune(at.cache_key("op4", 3), ["bad", "good"], build,
                       (jnp.ones(2),))
        assert best == "good"


class TestFlashBlocks:
    def test_candidates_respect_divisibility(self):
        from paddle_tpu.ops.pallas.flash_attention import _block_candidates
        import jax.numpy as jnp
        c = _block_candidates(256, 256, 128, jnp.float32)
        assert (128, 128) in c and (256, 256) in c
        assert all(256 % bq == 0 and 256 % bk == 0 for bq, bk in c)
        c2 = _block_candidates(128, 128, 128, jnp.float32)
        assert c2 == [(128, 128)]

    def test_flash_matches_reference_with_tuned_blocks(self):
        at.clear()
        paddle.set_flags({"FLAGS_use_autotune": True})
        try:
            from paddle_tpu.ops.pallas.flash_attention import \
                flash_attention_bshd
            from paddle_tpu.nn.functional.attention import _sdpa_ref
            import jax.numpy as jnp
            B, S, H, D = 1, 256, 2, 128
            q = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
            k = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
            v = jnp.asarray(rng.rand(B, S, H, D).astype(np.float32))
            out = flash_attention_bshd(q, k, v, causal=True)
            ref = _sdpa_ref(q, k, v, causal=True)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       rtol=2e-2, atol=2e-3)
            # a tuned entry landed in the cache
            assert any(key.startswith("flash_fwd|") for key in at._cache)
        finally:
            paddle.set_flags({"FLAGS_use_autotune": False})
            at.clear()

    def test_flash_default_blocks_unchanged_when_disabled(self):
        from paddle_tpu.ops.pallas import flash_attention as fa
        import jax.numpy as jnp
        q3 = jnp.zeros((2, 256, 128), jnp.float32)
        assert fa._pick_blocks(q3, q3, q3, True) in fa._block_candidates(
            256, 256, 128, jnp.float32)


class TestWarmAutotune:
    def test_warm_autotune_populates_cache(self):
        # the dispatch wrappers call warm_autotune with concrete arrays on
        # the TPU path (_use_pallas gates it off on CPU, so drive directly);
        # traced kernel calls then hit this cache by static-shape key
        at.clear()
        paddle.set_flags({"FLAGS_use_autotune": True})
        try:
            import jax.numpy as jnp
            from paddle_tpu.ops.pallas.flash_attention import warm_autotune
            q = jnp.asarray(rng.rand(1, 256, 2, 128).astype(np.float32))
            warm_autotune(q, q, q, causal=True)
            assert any(k.startswith("flash_fwd|2|256|256|128")
                       for k in at._cache), list(at._cache)
            # a traced call now uses the cached pick without tuning
            import jax
            from paddle_tpu.ops.pallas import flash_attention as fa
            q3 = jnp.moveaxis(q, 2, 1).reshape(2, 256, 128)
            cached = tuple(at.lookup(at.cache_key(
                "flash_fwd", 2, 256, 256, 128, q3.dtype, True)))
            got = jax.eval_shape(
                lambda a: jnp.asarray(fa._pick_blocks(a, a, a, True)), q3)
            assert cached in fa._block_candidates(256, 256, 128, q3.dtype)
        finally:
            paddle.set_flags({"FLAGS_use_autotune": False})
            at.clear()


class TestGPT2Recompute:
    def test_remat_loss_matches_plain(self):
        from paddle_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM
        xs = rng.randint(0, 256, (2, 33)).astype(np.int32)

        def run(remat):
            paddle.seed(7)
            cfg = GPT2Config.tiny(hidden_dropout_prob=0.0,
                                  attention_dropout_prob=0.0,
                                  use_recompute=remat)
            m = GPT2ForCausalLM(cfg)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=m.parameters())
            x = paddle.to_tensor(xs[:, :-1])
            y = paddle.to_tensor(xs[:, 1:])
            losses = []
            for _ in range(3):
                _, loss = m(x, labels=y)
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            return losses

        np.testing.assert_allclose(run(False), run(True), rtol=1e-5)


class TestChunkedLMLoss:
    def test_parity_with_dense_loss_and_grads(self):
        from paddle_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM
        ids = rng.randint(0, 256, (2, 33)).astype(np.int32)

        def run(chunk):
            paddle.seed(3)
            cfg = GPT2Config.tiny(hidden_dropout_prob=0.0,
                                  attention_dropout_prob=0.0,
                                  loss_chunk_size=chunk)
            m = GPT2ForCausalLM(cfg)
            x = paddle.to_tensor(ids[:, :-1])
            y = paddle.to_tensor(ids[:, 1:])
            _, loss = m(x, labels=y)
            loss.backward()
            return float(loss), float((m.gpt2.wte.weight.grad ** 2).sum())

        l0, g0 = run(0)
        l1, g1 = run(17)   # non-dividing chunk exercises the padding path
        np.testing.assert_allclose(l1, l0, rtol=1e-5)
        np.testing.assert_allclose(g1, g0, rtol=1e-3)

    def test_chunked_loss_respects_ignore_index(self):
        from paddle_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM
        ids = rng.randint(0, 256, (2, 33)).astype(np.int32)
        labels = ids[:, 1:].copy()
        labels[0, :10] = -100   # masked prefix

        def run(chunk):
            paddle.seed(3)
            cfg = GPT2Config.tiny(hidden_dropout_prob=0.0,
                                  attention_dropout_prob=0.0,
                                  loss_chunk_size=chunk)
            m = GPT2ForCausalLM(cfg)
            _, loss = m(paddle.to_tensor(ids[:, :-1]),
                        labels=paddle.to_tensor(labels))
            return float(loss)

        np.testing.assert_allclose(run(17), run(0), rtol=1e-5)
