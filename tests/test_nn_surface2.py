"""Round-2 nn-surface completion tests: losses vs torch goldens, vision
sampling ops, LP/fractional pooling, seq2seq decode."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
rng = np.random.RandomState(0)


def _t(a):
    return paddle.to_tensor(np.asarray(a))


class TestLossGoldens:
    def test_poisson_nll(self):
        x, y = rng.randn(4, 5).astype(np.float32), rng.poisson(2.0, (4, 5)).astype(np.float32)
        ours = float(F.poisson_nll_loss(_t(x), _t(y))._data)
        ref = float(torch.nn.functional.poisson_nll_loss(
            torch.tensor(x), torch.tensor(y)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_gaussian_nll(self):
        x = rng.randn(4, 5).astype(np.float32)
        y = rng.randn(4, 5).astype(np.float32)
        v = rng.rand(4, 5).astype(np.float32) + 0.1
        ours = float(F.gaussian_nll_loss(_t(x), _t(y), _t(v))._data)
        ref = float(torch.nn.functional.gaussian_nll_loss(
            torch.tensor(x), torch.tensor(y), torch.tensor(v)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_soft_margin(self):
        x = rng.randn(6).astype(np.float32)
        y = np.sign(rng.randn(6)).astype(np.float32)
        ours = float(F.soft_margin_loss(_t(x), _t(y))._data)
        ref = float(torch.nn.functional.soft_margin_loss(
            torch.tensor(x), torch.tensor(y)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_multi_label_soft_margin(self):
        x = rng.randn(3, 4).astype(np.float32)
        y = (rng.rand(3, 4) > 0.5).astype(np.float32)
        ours = float(F.multi_label_soft_margin_loss(_t(x), _t(y))._data)
        ref = float(torch.nn.functional.multilabel_soft_margin_loss(
            torch.tensor(x), torch.tensor(y)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_multi_margin(self):
        x = rng.randn(5, 4).astype(np.float32)
        y = rng.randint(0, 4, 5)
        ours = float(F.multi_margin_loss(_t(x), _t(y))._data)
        ref = float(torch.nn.functional.multi_margin_loss(
            torch.tensor(x), torch.tensor(y)))
        np.testing.assert_allclose(ours, ref, rtol=1e-5)

    def test_triplet_with_distance(self):
        a, p, n = (rng.randn(4, 8).astype(np.float32) for _ in range(3))
        ours = float(F.triplet_margin_with_distance_loss(
            _t(a), _t(p), _t(n))._data)
        ref = float(torch.nn.functional.triplet_margin_with_distance_loss(
            torch.tensor(a), torch.tensor(p), torch.tensor(n)))
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_ctc_matches_torch(self):
        T, B, C, L = 8, 3, 5, 3
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = rng.randint(1, C, (B, L)).astype(np.int32)
        in_len = np.array([8, 7, 6], np.int64)
        lab_len = np.array([3, 2, 1], np.int64)
        ours = float(F.ctc_loss(_t(logits), _t(labels), _t(in_len),
                                _t(lab_len))._data)
        ref = float(torch.nn.functional.ctc_loss(
            torch.tensor(logits).log_softmax(-1), torch.tensor(labels.astype(np.int64)),
            torch.tensor(in_len), torch.tensor(lab_len), blank=0,
            reduction="mean"))
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_ctc_empty_label(self):
        """Zero-length targets must not double-count the all-blank path."""
        T, B, C = 6, 2, 5
        logits = rng.randn(T, B, C).astype(np.float32)
        labels = np.array([[1, 2], [0, 0]], np.int32)
        in_len = np.array([6, 6], np.int64)
        lab_len = np.array([2, 0], np.int64)
        ours = float(F.ctc_loss(_t(logits), _t(labels), _t(in_len),
                                _t(lab_len))._data)
        ref = float(torch.nn.functional.ctc_loss(
            torch.tensor(logits).log_softmax(-1),
            torch.tensor(labels.astype(np.int64)),
            torch.tensor(in_len), torch.tensor(lab_len), blank=0,
            reduction="mean", zero_infinity=False))
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_rnnt_matches_bruteforce(self):
        """Tiny grid: enumerate all monotonic paths explicitly."""
        B, T, U, C = 1, 3, 2, 4
        logits = rng.randn(B, T, U + 1, C).astype(np.float32)
        label = np.array([[1, 2]], np.int32)
        ours = float(F.rnnt_loss(_t(logits), _t(label),
                                 _t(np.array([T], np.int64)),
                                 _t(np.array([U], np.int64)),
                                 reduction="mean")._data)
        # brute force over all interleavings of T blanks and U labels
        import itertools
        import scipy.special
        lp = torch.tensor(logits).log_softmax(-1).numpy()[0]
        paths = []
        for positions in itertools.combinations(range(T + U - 1 + 1), U):
            # walk the grid: at each step emit label (u+1) or blank (t+1)
            t = u = 0
            s = 0.0
            ok = True
            seq = ["L" if i in positions else "B" for i in range(T + U)]
            # last move must leave t==T when all emitted; simulate
            t = u = 0
            s = 0.0
            for mv in seq:
                if mv == "L":
                    if u >= U or t >= T:
                        ok = False
                        break
                    s += lp[t, u, label[0, u]]
                    u += 1
                else:
                    if t >= T:
                        ok = False
                        break
                    s += lp[t, u, 0]
                    t += 1
            if ok and t == T and u == U:
                paths.append(s)
        ref = -scipy.special.logsumexp(paths)
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_dice_log_npair_smoke(self):
        probs = torch.softmax(torch.tensor(rng.randn(2, 6, 3).astype(np.float32)), -1).numpy()
        lbl = rng.randint(0, 3, (2, 6, 1))
        d = float(F.dice_loss(_t(probs), _t(lbl))._data)
        assert 0 <= d <= 1
        p = np.clip(rng.rand(4, 1).astype(np.float32), 0.05, 0.95)
        y = (rng.rand(4, 1) > 0.5).astype(np.float32)
        ll = np.asarray(F.log_loss(_t(p), _t(y))._data)
        ref = -(y * np.log(p + 1e-4) + (1 - y) * np.log(1 - p + 1e-4))
        np.testing.assert_allclose(ll, ref, rtol=1e-4)
        a, pos = rng.randn(4, 8).astype(np.float32), rng.randn(4, 8).astype(np.float32)
        npl = float(F.npair_loss(_t(a), _t(pos), _t(np.arange(4)))._data)
        assert np.isfinite(npl)

    def test_hsigmoid_is_normalized(self):
        """Sum over classes of P(c|x) must be 1 under the default tree."""
        C, D = 8, 6
        paddle.seed(0)
        layer = nn.HSigmoidLoss(D, C)
        x = _t(rng.randn(1, D).astype(np.float32))
        total = 0.0
        for c in range(C):
            loss = layer(x, _t(np.array([c], np.int64)))
            total += float(np.exp(-np.asarray(loss._data)).reshape(-1)[0])
        np.testing.assert_allclose(total, 1.0, rtol=1e-4)

    def test_margin_cross_entropy_reduces_to_ce_at_zero_margin(self):
        cos = np.clip(rng.randn(4, 6).astype(np.float32) * 0.3, -1, 1)
        lbl = rng.randint(0, 6, 4)
        ours = float(F.margin_cross_entropy(_t(cos), _t(lbl), margin1=1.0,
                                            margin2=0.0, margin3=0.0,
                                            scale=10.0)._data)
        ref = float(torch.nn.functional.cross_entropy(
            torch.tensor(cos * 10.0), torch.tensor(lbl)))
        np.testing.assert_allclose(ours, ref, rtol=1e-4)

    def test_adaptive_log_softmax(self):
        paddle.seed(0)
        layer = nn.AdaptiveLogSoftmaxWithLoss(16, 20, cutoffs=[4, 10])
        x = _t(rng.randn(8, 16).astype(np.float32))
        full = np.asarray(layer.log_prob(x)._data)
        np.testing.assert_allclose(np.exp(full).sum(-1), np.ones(8), rtol=1e-4)
        lbl = rng.randint(0, 20, 8)
        out, loss = layer(x, _t(lbl))
        np.testing.assert_allclose(np.asarray(out._data),
                                   full[np.arange(8), lbl], rtol=1e-4)
        np.testing.assert_allclose(float(loss._data),
                                   -full[np.arange(8), lbl].mean(), rtol=1e-4)


class TestVisionSampling:
    def test_grid_sample_matches_torch(self):
        x = rng.randn(2, 3, 5, 6).astype(np.float32)
        grid = (rng.rand(2, 4, 4, 2).astype(np.float32) * 2 - 1)
        for align in (True, False):
            ours = np.asarray(F.grid_sample(_t(x), _t(grid),
                                            align_corners=align)._data)
            ref = torch.nn.functional.grid_sample(
                torch.tensor(x), torch.tensor(grid), mode="bilinear",
                padding_mode="zeros", align_corners=align).numpy()
            np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_grid_sample_reflection_and_border(self):
        x = rng.randn(1, 2, 5, 5).astype(np.float32)
        grid = (rng.rand(1, 3, 3, 2).astype(np.float32) * 3 - 1.5)  # OOB too
        for pm in ("reflection", "border"):
            for align in (True, False):
                ours = np.asarray(F.grid_sample(
                    _t(x), _t(grid), padding_mode=pm,
                    align_corners=align)._data)
                ref = torch.nn.functional.grid_sample(
                    torch.tensor(x), torch.tensor(grid), mode="bilinear",
                    padding_mode=pm, align_corners=align).numpy()
                np.testing.assert_allclose(ours, ref, atol=1e-5,
                                           err_msg=f"{pm} align={align}")

    def test_affine_grid_matches_torch(self):
        theta = rng.randn(2, 2, 3).astype(np.float32)
        ours = np.asarray(F.affine_grid(_t(theta), (2, 3, 4, 5))._data)
        ref = torch.nn.functional.affine_grid(
            torch.tensor(theta), (2, 3, 4, 5), align_corners=True).numpy()
        np.testing.assert_allclose(ours, ref, atol=1e-5)

    def test_temporal_shift(self):
        x = rng.randn(4, 8, 2, 2).astype(np.float32)   # N*T with T=2
        out = np.asarray(F.temporal_shift(_t(x), seg_num=2,
                                          shift_ratio=0.25)._data)
        v = x.reshape(2, 2, 8, 2, 2)
        np.testing.assert_allclose(out.reshape(2, 2, 8, 2, 2)[:, 0, :2],
                                   v[:, 1, :2])          # shifted back
        np.testing.assert_allclose(out.reshape(2, 2, 8, 2, 2)[:, 1, 2:4],
                                   v[:, 0, 2:4])         # shifted forward


class TestPoolingVariants:
    def test_lp_pool_matches_torch(self):
        x = np.abs(rng.randn(2, 3, 8).astype(np.float32)) + 0.1
        ours = np.asarray(F.lp_pool1d(_t(x), 2, 2)._data)
        ref = torch.nn.functional.lp_pool1d(torch.tensor(x), 2, 2).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4)
        x2 = np.abs(rng.randn(2, 3, 6, 6).astype(np.float32)) + 0.1
        ours2 = np.asarray(F.lp_pool2d(_t(x2), 3, 2)._data)
        ref2 = torch.nn.functional.lp_pool2d(torch.tensor(x2), 3, 2).numpy()
        np.testing.assert_allclose(ours2, ref2, rtol=1e-4)

    def test_fractional_pool_shapes_and_values(self):
        x = rng.randn(1, 2, 9, 9).astype(np.float32)
        out = F.fractional_max_pool2d(_t(x), 4, random_u=0.5)
        assert out.shape == [1, 2, 4, 4]
        assert np.asarray(out._data).max() <= x.max() + 1e-6
        # kernel_size makes windows overlap: each output >= partition result
        ov = np.asarray(F.fractional_max_pool2d(_t(x), 4, kernel_size=3,
                                                random_u=0.5)._data)
        assert (ov >= np.asarray(out._data) - 1e-6).all()
        assert not np.allclose(ov, np.asarray(out._data))
        out3 = F.fractional_max_pool3d(
            _t(rng.randn(1, 1, 6, 6, 6).astype(np.float32)), 3, random_u=0.4)
        assert out3.shape == [1, 1, 3, 3, 3]

    def test_max_unpool3d_roundtrip_positions(self):
        x = rng.randn(1, 1, 2, 2, 2).astype(np.float32)
        idx = np.array([[[[[0, 9], [18, 27]], [[36, 45], [54, 63]]]]])
        up = F.max_unpool3d(_t(x), _t(idx.astype(np.int32)), 2)
        u = np.asarray(up._data)
        assert u.shape == (1, 1, 4, 4, 4)
        np.testing.assert_allclose(u.reshape(-1)[[0, 9, 18, 27, 36, 45, 54, 63]],
                                   x.reshape(-1))


class TestSeq2Seq:
    def _cell_and_emb(self, V=6, H=8):
        paddle.seed(0)
        cell = nn.GRUCell(H, H)
        emb = nn.Embedding(V, H)
        proj = nn.Linear(H, V)
        return cell, emb, proj

    def test_beam1_equals_greedy(self):
        V = 6
        cell, emb, proj = self._cell_and_emb(V)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=V - 1,
                                   beam_size=1, embedding_fn=emb,
                                   output_fn=proj)
        h0 = paddle.to_tensor(rng.randn(2, 8).astype(np.float32))
        ids, lp = nn.dynamic_decode(dec, h0, max_step_num=5)
        assert ids.shape[0] == 2 and ids.shape[1] == 1
        # greedy reference
        import jax.numpy as jnp
        tok = paddle.to_tensor(np.zeros(2, np.int32))
        state = paddle.to_tensor(np.asarray(h0._data))
        for t in range(ids.shape[2]):
            out, state = cell(emb(tok), state)
            logits = np.asarray(proj(out)._data)
            nxt = logits.argmax(-1)
            np.testing.assert_array_equal(np.asarray(ids._data)[:, 0, t], nxt)
            tok = paddle.to_tensor(nxt.astype(np.int32))
            if (nxt == V - 1).all():
                break

    def test_beam_scores_sorted(self):
        V = 6
        cell, emb, proj = self._cell_and_emb(V)
        dec = nn.BeamSearchDecoder(cell, start_token=0, end_token=V - 1,
                                   beam_size=3, embedding_fn=emb,
                                   output_fn=proj)
        h0 = paddle.to_tensor(rng.randn(2, 8).astype(np.float32))
        ids, lp = nn.dynamic_decode(dec, h0, max_step_num=4)
        scores = np.asarray(lp._data)
        assert ids.shape[:2] == [2, 3]
        assert (np.diff(scores, axis=1) <= 1e-5).all()   # best beam first

    def test_gather_tree(self):
        ids = np.array([[[1, 2]], [[3, 4]]], np.int64)        # [T=2, B=1, K=2]
        parents = np.array([[[0, 0]], [[1, 0]]], np.int64)
        out = np.asarray(F.gather_tree(_t(ids), _t(parents))._data)
        assert out.shape == (2, 1, 2)


class TestMiscLayers:
    def test_softmax2d_unflatten_zeropads(self):
        x = _t(rng.randn(2, 3, 4, 4).astype(np.float32))
        s = np.asarray(nn.Softmax2D()(x)._data)
        np.testing.assert_allclose(s.sum(axis=1), np.ones((2, 4, 4)),
                                   rtol=1e-5)
        u = nn.Unflatten(1, [3, 1])(_t(rng.randn(2, 3).astype(np.float32)))
        assert u.shape == [2, 3, 1]
        z1 = nn.ZeroPad1D([1, 2])(_t(rng.randn(1, 2, 4).astype(np.float32)))
        assert z1.shape == [1, 2, 7]
        z3 = nn.ZeroPad3D([1, 1, 1, 1, 1, 1])(
            _t(rng.randn(1, 1, 2, 2, 2).astype(np.float32)))
        assert z3.shape == [1, 1, 4, 4, 4]

    def test_parameter_dict(self):
        pd = nn.ParameterDict({"a": paddle.create_parameter([2, 2], "float32")})
        pd["b"] = paddle.create_parameter([3], "float32")
        assert "a" in pd and len(pd) == 2
        assert len(list(pd.items())) == 2

    def test_inplace_activations(self):
        t = _t(np.array([-2.0, 2.0], np.float32))
        F.tanh_(t)
        np.testing.assert_allclose(np.asarray(t._data), np.tanh([-2.0, 2.0]),
                                   rtol=1e-6)
        t2 = _t(np.array([-2.0, 2.0], np.float32))
        F.leaky_relu_(t2)
        np.testing.assert_allclose(np.asarray(t2._data), [-0.02, 2.0],
                                   rtol=1e-5)

    def test_pairwise_distance_matches_torch(self):
        a, b = rng.randn(4, 6).astype(np.float32), rng.randn(4, 6).astype(np.float32)
        ours = np.asarray(F.pairwise_distance(_t(a), _t(b))._data)
        ref = torch.nn.functional.pairwise_distance(
            torch.tensor(a), torch.tensor(b)).numpy()
        np.testing.assert_allclose(ours, ref, rtol=1e-4)
