"""OpTest-style golden harness (reference: test/legacy_test/op_test.py:418).

check_output: run the paddle_tpu op, compare against a numpy reference.
check_grad: analytic grads (tape backward) vs central finite differences
(reference: get_numeric_gradient, op_test.py:148).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as pt


def check_output(op, np_ref, inputs, atol=1e-5, rtol=1e-5, kwargs=None):
    kwargs = kwargs or {}
    tensors = [pt.to_tensor(i) for i in inputs]
    out = op(*tensors, **kwargs)
    ref = np_ref(*inputs)
    outs = out if isinstance(out, (list, tuple)) else [out]
    refs = ref if isinstance(ref, (list, tuple)) else [ref]
    for o, r in zip(outs, refs):
        np.testing.assert_allclose(np.asarray(o.numpy(), np.float64),
                                   np.asarray(r, np.float64), atol=atol, rtol=rtol)
    return out


def numeric_grad(op, inputs, idx, out_grad, delta=1e-3, kwargs=None):
    """Central-difference dL/dx[idx] where L = sum(op(x) * out_grad)."""
    kwargs = kwargs or {}
    x = inputs[idx].astype(np.float64)
    grad = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = grad.reshape(-1)

    def run(xv):
        args = [a.copy() for a in inputs]
        args[idx] = xv.astype(inputs[idx].dtype)
        out = op(*[pt.to_tensor(a) for a in args], **kwargs)
        outs = out if isinstance(out, (list, tuple)) else [out]
        ogs = out_grad if isinstance(out_grad, (list, tuple)) else [out_grad]
        return sum(float((o.numpy().astype(np.float64) * g).sum()) for o, g in zip(outs, ogs))

    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + delta
        hi = run(x)
        flat[i] = orig - delta
        lo = run(x)
        flat[i] = orig
        gflat[i] = (hi - lo) / (2 * delta)
    return grad


def check_grad(op, inputs, grad_idx=None, atol=5e-3, rtol=5e-3, delta=1e-3, kwargs=None):
    """Compare tape gradients against finite differences for float64 inputs."""
    kwargs = kwargs or {}
    grad_idx = grad_idx if grad_idx is not None else range(len(inputs))
    tensors = [pt.to_tensor(i, stop_gradient=False) for i in inputs]
    out = op(*tensors, **kwargs)
    outs = out if isinstance(out, (list, tuple)) else [out]
    rng = np.random.RandomState(7)
    out_grads = [rng.uniform(0.1, 1.0, o.shape).astype(np.float32) for o in outs]
    pt.autograd.backward(list(outs), [pt.to_tensor(g) for g in out_grads])
    for i in grad_idx:
        g = tensors[i].grad
        # an input the output provably doesn't depend on (e.g. expand_as's
        # target) legitimately has no tape grad — compare against zeros
        analytic = (g.numpy().astype(np.float64) if g is not None
                    else np.zeros_like(inputs[i], np.float64))
        numeric = numeric_grad(op, inputs, i, out_grads if len(outs) > 1 else out_grads[0],
                               delta=delta, kwargs=kwargs)
        np.testing.assert_allclose(analytic, numeric, atol=atol, rtol=rtol,
                                   err_msg=f"grad mismatch for input {i}")
