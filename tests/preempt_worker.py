"""Worker for test_preemption.py: deterministic 2-rank DP training with a
PreemptionCheckpointer. Writes its PID (so the test can SIGTERM it) and a
per-attempt loss log; on restart resumes from the newest complete checkpoint.
"""
import json
import os
import time


os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.fleet.elastic import PreemptionCheckpointer

WORK = os.environ["PREEMPT_DIR"]
STEPS = int(os.environ.get("PREEMPT_STEPS", "24"))
SLEEP = float(os.environ.get("PREEMPT_SLEEP", "0.1"))

dist.init_parallel_env()
rank = dist.get_rank()

with open(os.path.join(WORK, f"pid_rank{rank}.txt"), "w") as f:
    f.write(str(os.getpid()))

paddle.seed(0)
model = paddle.nn.Linear(8, 1)
opt = paddle.optimizer.Adam(learning_rate=0.05,
                            parameters=model.parameters())
rng = np.random.RandomState(0)
X = paddle.to_tensor(rng.rand(16, 8).astype(np.float32))
Y = paddle.to_tensor(rng.rand(16, 1).astype(np.float32))

# one warmup step materializes the lazy Adam accumulators so the state dict
# is complete, then weights reset to the step-0 values (both runs do this,
# so the loss sequence stays deterministic)
loss = ((model(X) - Y) ** 2).mean()
loss.backward()
opt.step()
opt.clear_grad()
paddle.seed(0)
model.set_state_dict(paddle.nn.Linear(8, 1).state_dict())


def get_state():
    st = {f"model.{k}": v for k, v in model.state_dict().items()}
    for k, v in opt.state_dict().items():
        if hasattr(v, "_data"):
            st[f"opt.{k}"] = v
    return st


pc = PreemptionCheckpointer(
    os.path.join(WORK, "ckpt"),
    get_state=get_state,
    set_state=lambda s: None,       # load_state_dict restores in place
).install()

start = pc.resume()
begin = 0 if start is None else start
log = open(os.path.join(WORK, f"loss_rank{rank}_pid{os.getpid()}.jsonl"), "w")

for step in range(begin, STEPS):
    pc.maybe_checkpoint(step)
    loss = ((model(X) - Y) ** 2).mean()
    loss.backward()
    for p in model.parameters():            # DP grad sync
        if p.grad is not None:
            dist.all_reduce(p.grad)
            p.grad.set_value(p.grad / dist.get_world_size())
    opt.step()
    opt.clear_grad()
    log.write(json.dumps({"step": step, "loss": float(loss)}) + "\n")
    log.flush()
    time.sleep(SLEEP)

log.close()
print("PREEMPT_WORKER_DONE", flush=True)
