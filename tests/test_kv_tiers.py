"""Tiered KV cache (ISSUE 17): host-RAM spill tier + peer-replica page
pulls.  The parity bar everywhere: tokens byte-identical to an engine with
no cache at all — every tier is a pure performance layer, and every fault
path (kv.spill / kv.restore / kv.peer_pull) must degrade to the tier below
(eviction / re-prefill / cold recompute), never to a wrong token."""
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.serving import LLMEngine, prefix_page_keys
from paddle_tpu.testing import FAULTS, Always, FailNth, injected


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(model, **kw)


def _pressure_engine(model, host_bytes=64 << 20, **kw):
    """6-page pool, one 6-page slot: any two distinct 5-page prompts churn
    the pool, so serving A, B, A forces A's chain through the spill tier."""
    kw.setdefault("max_batch", 1)
    kw.setdefault("max_len", 48)
    kw.setdefault("page_pool", 6)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("host_cache_bytes", host_bytes)
    return _engine(model, **kw)


@pytest.fixture(scope="module")
def ref_pressure(model):
    """Cache-off reference at the pressure geometry (module-shared: each
    engine build compiles a prefill program)."""
    return _engine(model, max_batch=1, max_len=48, page_pool=6,
                   prefix_cache=False)


def _churn_prompts(seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 128, (40,)).astype(np.int32) for _ in range(2)]


def _serve_one_by_one(eng, prompts, **req_kw):
    outs, disp = [], []
    for p in prompts:
        rid = eng.add_request(p, **req_kw)
        eng.run_until_done()
        outs.append(eng.result(rid))
        disp.append(eng._finished[rid].prefill_dispatches)
    return outs, disp


class TestHostTier:
    def test_spill_restore_parity_skips_reprefill(self, model, ref_pressure):
        """A fully-evicted 5-page chain comes back from the host tier: the
        re-served prompt pays exactly ONE prefill dispatch (the final
        token) instead of the full prefill — and its tokens are identical
        to the no-cache engine's."""
        a, b = _churn_prompts()
        plan = [a, b, a]
        ref, ref_disp = _serve_one_by_one(ref_pressure, plan,
                                          max_new_tokens=4)
        eng = _pressure_engine(model)
        got, disp = _serve_one_by_one(eng, plan, max_new_tokens=4)
        assert got == ref
        st = eng.kv_tier_stats()
        assert st["host_spills"] >= 5, st      # B's admission evicted A
        assert st["host_restores"] >= 5, st    # A's re-admission restored
        assert st["host_spill_drops"] == 0 and st["host_restore_failures"] == 0
        assert st["hits_host"] >= 5, st
        assert st["host_spill_bytes"] > 0 and st["host_restore_bytes"] > 0
        # the restore made re-admission as cheap as a full HBM hit: only
        # the prompt's final token re-prefills
        assert disp[2] == 1, (disp, st)
        assert disp[2] < ref_disp[2], (disp, ref_disp)
        assert eng.audit_refcounts() == []

    def test_prefix_keys_and_health_advertise_host_tier(self, model):
        """Spilled chains show up in prefix_keys() (fleet join warming and
        peer pulls read it) and health() carries the host-tier gauges."""
        a, b = _churn_prompts(seed=1)
        eng = _pressure_engine(model)
        _serve_one_by_one(eng, [a, b], max_new_tokens=4)
        keys = set(eng.prefix_keys())
        spilled = set(prefix_page_keys(a, eng.page))
        assert spilled <= keys, "host-only chains must be advertised"
        resident = set(eng.pool.key_page)
        assert not (spilled <= resident)       # A really was evicted
        h = eng.health()
        assert h["host_cached_pages"] >= 5
        assert h["host_bytes"] > 0
        assert h["host_headroom_pages"] >= 0

    def test_host_budget_evicts_oldest_chain(self, model, ref_pressure):
        """A host tier sized for 2 pages cannot hold a 5-page chain: old
        entries age out (counted), and a re-serve that misses the host
        tier falls back to plain recompute — still token-exact."""
        a, b = _churn_prompts(seed=2)
        page_bytes = ref_pressure.kv_bytes_per_page()
        eng = _pressure_engine(model, host_bytes=2 * page_bytes)
        plan = [a, b, a]
        ref, ref_disp = _serve_one_by_one(ref_pressure, plan,
                                          max_new_tokens=4)
        got, disp = _serve_one_by_one(eng, plan, max_new_tokens=4)
        assert got == ref
        st = eng.kv_tier_stats()
        assert st["host_evictions"] > 0, st
        assert st["host_cached_pages"] <= 2, st
        assert st["host_bytes"] <= 2 * page_bytes, st
        assert eng.audit_refcounts() == []

    def test_preemption_spills_decoded_pages(self, model):
        """Scheduler preemption demotes the victim's already-decoded pages
        to the host tier (registered under folded prompt+output keys), so
        its resume restores instead of re-prefilling everything."""
        rng = np.random.RandomState(3)
        # two slots, 12-page pool: both requests decoding past their
        # prompts exhausts the pool and preempts the youngest
        eng = _engine(model, max_batch=2, max_len=48, page_pool=9,
                      prefix_cache=True, host_cache_bytes=64 << 20)
        ref = _engine(model, max_batch=2, max_len=48, page_pool=9,
                      prefix_cache=False)
        prompts = [rng.randint(1, 128, (30,)).astype(np.int32)
                   for _ in range(2)]

        def serve(e):
            rids = [e.add_request(p, max_new_tokens=16) for p in prompts]
            e.run_until_done()
            return [e.result(r) for r in rids]

        want = serve(ref)
        got = serve(eng)
        assert got == want
        assert ref.sched.preemptions > 0, "geometry no longer preempts"
        st = eng.kv_tier_stats()
        assert st["host_spills"] > 0, st
        assert eng.audit_refcounts() == []


class TestHostTierChaos:
    def test_transient_spill_and_restore_retry(self, model, ref_pressure):
        """A transient firing at each tier point retries through the seeded
        backoff policy and the tier still functions — no drops, no
        fallbacks, same tokens."""
        a, b = _churn_prompts(seed=4)
        plan = [a, b, a]
        ref, _ = _serve_one_by_one(ref_pressure, plan, max_new_tokens=4)
        eng = _pressure_engine(model)
        with injected("kv.spill", FailNth(1), transient=True), \
                injected("kv.restore", FailNth(1), transient=True):
            got, disp = _serve_one_by_one(eng, plan, max_new_tokens=4)
        assert got == ref
        st = eng.kv_tier_stats()
        assert st["host_spill_drops"] == 0, st
        assert st["host_restore_failures"] == 0, st
        assert st["host_spills"] >= 5 and st["host_restores"] >= 5, st
        assert disp[2] == 1, (disp, st)
        assert eng.audit_refcounts() == []

    def test_poison_spill_degrades_to_eviction(self, model, ref_pressure):
        """Every spill poisoned: the tier degrades to plain LRU eviction —
        the re-serve pays full recompute, tokens stay exact, and no page
        accounting leaks."""
        a, b = _churn_prompts(seed=5)
        plan = [a, b, a]
        ref, ref_disp = _serve_one_by_one(ref_pressure, plan,
                                          max_new_tokens=4)
        eng = _pressure_engine(model)
        with injected("kv.spill", Always()):
            got, disp = _serve_one_by_one(eng, plan, max_new_tokens=4)
        assert got == ref
        st = eng.kv_tier_stats()
        assert st["host_spills"] == 0, st
        assert st["host_spill_drops"] > 0, st
        assert st["host_restores"] == 0, st
        assert disp[2] == ref_disp[2], (disp, ref_disp)  # full recompute
        assert eng.audit_refcounts() == []

    def test_poison_restore_falls_back_to_reprefill(self, model,
                                                    ref_pressure):
        """Spills land but every restore is poisoned: admission re-prefills
        the whole prompt (recompute fallback), token-exact, audit clean."""
        a, b = _churn_prompts(seed=6)
        plan = [a, b, a]
        ref, ref_disp = _serve_one_by_one(ref_pressure, plan,
                                          max_new_tokens=4)
        eng = _pressure_engine(model)
        with injected("kv.restore", Always()):
            got, disp = _serve_one_by_one(eng, plan, max_new_tokens=4)
        assert got == ref
        st = eng.kv_tier_stats()
        assert st["host_spills"] >= 5, st
        assert st["host_restores"] == 0, st
        assert st["host_restore_failures"] > 0, st
        assert disp[2] == ref_disp[2], (disp, ref_disp)
        assert eng.audit_refcounts() == []


def _skewed_pair(model):
    """Two replicas behind a skew-overriding affinity router with peer
    pulls on; returns (rs, engines).  The scenario every peer test drives:
    warm r0 with a prompt, block r0 with a long decode, resubmit the
    prompt — the router skew-routes it to cold r1 naming r0 as holder."""
    from paddle_tpu.inference.frontend import ReplicaSet
    from paddle_tpu.inference.frontend.router import PrefixAffinityRouter
    engines = [_engine(model, prefix_cache=True, host_cache_bytes=32 << 20)
               for _ in range(2)]
    rs = ReplicaSet(engines, peer_pull=True,
                    router=PrefixAffinityRouter(page_size=8,
                                                max_load_skew=0))
    return rs, engines


class TestPeerTier:
    def _run_skew_scenario(self, model):
        """Returns (warm_tokens, pulled_tokens, engines) — the second serve
        of the same prompt, skew-routed onto the replica that never saw
        it."""
        rs, engines = _skewed_pair(model)
        rng = np.random.RandomState(7)
        warm = rng.randint(1, 128, (27,)).astype(np.int32)  # 3 full pages
        blocker = rng.randint(1, 128, (4,)).astype(np.int32)
        try:
            h0 = rs.submit(warm, max_new_tokens=4)          # both cold: r0
            warm_toks, _ = rs.result(h0, timeout=60.0)
            hb = rs.submit(blocker, max_new_tokens=56)      # r0 now busy
            h1 = rs.submit(warm, max_new_tokens=4)          # skew -> r1
            pulled_toks, _ = rs.result(h1, timeout=60.0)
            rs.result(hb, timeout=60.0)
        finally:
            rs.close()
        return list(warm_toks), list(pulled_toks), engines

    def test_peer_pull_warms_cold_replica(self, model):
        """The skew-routed replica pulls the holder's 3-page chain before
        prefill: its admission sees 3 prefix hits it never computed, and
        the tokens match the holder's byte-for-byte."""
        warm_toks, pulled_toks, engines = self._run_skew_scenario(model)
        assert pulled_toks == warm_toks
        e0, e1 = engines
        assert e0.kv_tier_stats()["peer_exports"] >= 1, e0.kv_tier_stats()
        st1 = e1.kv_tier_stats()
        assert st1["peer_imports"] >= 1, st1
        assert st1["peer_import_pages"] >= 3, st1
        assert e1.prefix_cache_stats()["hits"] >= 3
        assert e1.audit_refcounts() == []

    def test_peer_pull_poison_recomputes_cold(self, model):
        """Every pull poisoned: the request is submitted cold and
        recomputes — same tokens, zero imports."""
        with injected("kv.peer_pull", Always()):
            warm_toks, pulled_toks, engines = self._run_skew_scenario(model)
        assert pulled_toks == warm_toks
        assert engines[1].kv_tier_stats()["peer_imports"] == 0
        assert engines[1].audit_refcounts() == []

    def test_peer_pull_transient_retries(self, model):
        """A transient first firing retries and the pull still lands."""
        with injected("kv.peer_pull", FailNth(1), transient=True):
            warm_toks, pulled_toks, engines = self._run_skew_scenario(model)
        assert pulled_toks == warm_toks
        assert engines[1].kv_tier_stats()["peer_import_pages"] >= 3


class TestPeerTierRpc:
    def test_pull_push_over_worker_rpc(self, model):
        """The peer tier's wire path: pull_pages / push_pages ops through a
        real thread-hosted WorkerServer and RemoteReplica — numpy page
        blocks survive the pickle framing and the importer's admission
        serves the spliced chain as ordinary prefix hits."""
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.inference.frontend.fleet import RemoteReplica
        from paddle_tpu.inference.frontend.worker import WorkerServer
        master = TCPStore(is_master=True, timeout=20)
        engines = [_engine(model, prefix_cache=True,
                           host_cache_bytes=32 << 20) for _ in range(2)]
        workers, reps = [], []
        try:
            for i, e in enumerate(engines):
                w = WorkerServer(f"w{i}", e,
                                 TCPStore(port=master.port, timeout=20),
                                 group="kvt", ttl=60.0)
                w.start(heartbeat=False)
                workers.append(w)
                reps.append(RemoteReplica(w.name, w.rpc.host, w.rpc.port))
            rng = np.random.RandomState(8)
            prompt = rng.randint(1, 128, (27,)).astype(np.int32)
            rid = reps[0].submit(list(map(int, prompt)), max_new_tokens=4)
            want, deadline = [], time.monotonic() + 60.0
            while time.monotonic() < deadline:
                toks, st = reps[0].poll(rid, timeout=1.0)
                want.extend(toks)
                if st.terminal:
                    break
            keys = prefix_page_keys(prompt, 8)
            payload = reps[0].export_pages(keys)
            assert payload is not None and len(payload["keys"]) == 3
            assert reps[1].import_pages(payload) == 3
            assert engines[1].kv_tier_stats()["peer_import_pages"] == 3
            assert set(keys) <= set(engines[1].prefix_keys())
            # a second pull of the same chain is a no-op (already cached)
            assert reps[1].import_pages(payload) == 0
            # the spliced pages serve a real request as prefix hits,
            # token-exact with the exporter's serve
            rid2 = reps[1].submit(list(map(int, prompt)), max_new_tokens=4)
            got, deadline = [], time.monotonic() + 60.0
            while time.monotonic() < deadline:
                toks, st = reps[1].poll(rid2, timeout=1.0)
                got.extend(toks)
                if st.terminal:
                    break
            assert got == want
            assert engines[1].prefix_cache_stats()["hits"] >= 3
            assert engines[1].audit_refcounts() == []
        finally:
            for r in reps:
                r.close()
            for w in workers:
                w.close(drain=False)
