"""Round-2 gap fills: max_pool return_mask + MaxUnPool, FeatureAlphaDropout,
matrix_exp, incubate.optimizer LookAhead/ModelAverage."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


class TestMaxPoolMask:
    def test_mask_indices_match_naive(self):
        rng = np.random.RandomState(0)
        x = rng.randn(2, 3, 6, 8).astype(np.float32)
        out, mask = F.max_pool2d(paddle.to_tensor(x), kernel_size=2,
                                 return_mask=True)
        o, m = np.asarray(out._data), np.asarray(mask._data)
        for n in range(2):
            for c in range(3):
                for i in range(3):
                    for j in range(4):
                        win = x[n, c, 2*i:2*i+2, 2*j:2*j+2]
                        assert o[n, c, i, j] == win.max()
                        fi = m[n, c, i, j]
                        assert x[n, c].reshape(-1)[fi] == win.max()

    def test_unpool_roundtrip(self):
        """unpool(pool(x)) reproduces x exactly at the argmax positions and
        zeros elsewhere."""
        rng = np.random.RandomState(1)
        x = rng.randn(2, 2, 4, 4).astype(np.float32)
        t = paddle.to_tensor(x)
        out, mask = F.max_pool2d(t, 2, return_mask=True)
        up = F.max_unpool2d(out, mask, 2)
        u = np.asarray(up._data)
        assert u.shape == x.shape
        assert np.count_nonzero(u) <= 2 * 2 * 2 * 2
        np.testing.assert_allclose(u.reshape(2, 2, -1).max(-1),
                                   np.asarray(out._data).reshape(2, 2, -1).max(-1))

    def test_unpool_layer_and_grad(self):
        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(1, 1, 4, 4).astype(np.float32))
        x.stop_gradient = False
        out, mask = F.max_pool2d(x, 2, return_mask=True)
        up = nn.MaxUnPool2D(2)(out, mask)
        up.sum().backward()
        g = np.asarray(x.grad._data)
        assert np.count_nonzero(g) == 4      # only argmax positions get grad

    def test_padded_pool_mask(self):
        rng = np.random.RandomState(3)
        x = rng.randn(1, 1, 3, 3).astype(np.float32)
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, stride=2, padding=1,
                                 return_mask=True)
        m = np.asarray(mask._data)
        assert m.min() >= 0 and m.max() < 9   # indices always in-bounds

    def test_ceil_mode_mask_shape_matches_plain(self):
        rng = np.random.RandomState(5)
        x = paddle.to_tensor(rng.randn(1, 1, 5, 5).astype(np.float32))
        plain = F.max_pool2d(x, 2, stride=2, ceil_mode=True)
        out, mask = F.max_pool2d(x, 2, stride=2, ceil_mode=True,
                                 return_mask=True)
        assert out.shape == plain.shape == [1, 1, 3, 3]
        np.testing.assert_allclose(np.asarray(out._data),
                                   np.asarray(plain._data))
        m = np.asarray(mask._data)
        assert m.min() >= 0 and m.max() < 25

    def test_mask_rejects_channel_last(self):
        x = paddle.to_tensor(np.zeros((1, 6, 2), np.float32))
        with pytest.raises(ValueError):
            F.max_pool1d(x, 2, data_format="NLC", return_mask=True)

    def test_unpool1d(self):
        rng = np.random.RandomState(4)
        x = paddle.to_tensor(rng.randn(1, 2, 6).astype(np.float32))
        out, mask = F.max_pool1d(x, 2, return_mask=True)
        up = F.max_unpool1d(out, mask, 2)
        assert up.shape == [1, 2, 6]


class TestFeatureAlphaDropout:
    def test_channelwise_mask(self):
        paddle.seed(0)
        layer = nn.FeatureAlphaDropout(p=0.5)
        layer.train()
        x = paddle.to_tensor(np.ones((4, 8, 5, 5), np.float32))
        y = np.asarray(layer(x)._data)
        # each channel is uniformly transformed: per-channel std must be 0
        assert np.allclose(y.std(axis=(2, 3)), 0.0, atol=1e-6)
        layer.eval()
        np.testing.assert_array_equal(np.asarray(layer(x)._data),
                                      np.ones((4, 8, 5, 5), np.float32))


class TestMatrixExp:
    def test_matches_scipy(self):
        import scipy.linalg
        rng = np.random.RandomState(0)
        a = rng.randn(4, 4).astype(np.float32) * 0.3
        out = paddle.to_tensor(a)
        from paddle_tpu.ops import matrix_exp
        np.testing.assert_allclose(np.asarray(matrix_exp(out)._data),
                                   scipy.linalg.expm(a), rtol=1e-4, atol=1e-5)


class TestIncubateOptimizers:
    def _setup(self):
        paddle.seed(0)
        model = nn.Linear(4, 4)
        rng = np.random.RandomState(0)
        xs = rng.randn(16, 4).astype(np.float32)
        ys = rng.randn(16, 4).astype(np.float32)
        return model, xs, ys

    def test_lookahead_syncs_every_k(self):
        from paddle_tpu.incubate import LookAhead
        model, xs, ys = self._setup()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=model.parameters())
        opt = LookAhead(inner, alpha=0.5, k=2)
        w_hist = []
        for i in range(4):
            loss = ((model(paddle.to_tensor(xs)) - paddle.to_tensor(ys)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            w_hist.append(np.asarray(model.weight._buf).copy())
        assert opt._step_num == 4 and len(opt._slow) == 2
        # after a sync step the weights equal the slow weights
        assert not np.allclose(w_hist[0], w_hist[1])
        # slow weights seeded at theta_0: the first sync pulls back toward
        # init, so LookAhead differs from plain SGD already at step k
        paddle.seed(0)
        ref = nn.Linear(4, 4)
        sgd = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=ref.parameters())
        for _ in range(2):
            loss = ((ref(paddle.to_tensor(xs)) - paddle.to_tensor(ys)) ** 2).mean()
            loss.backward()
            sgd.step()
            sgd.clear_grad()
        assert not np.allclose(w_hist[1], np.asarray(ref.weight._buf))

    def test_lookahead_state_roundtrip(self):
        from paddle_tpu.incubate import LookAhead
        model, xs, ys = self._setup()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=model.parameters())
        opt = LookAhead(inner, alpha=0.5, k=3)
        for _ in range(4):
            loss = ((model(paddle.to_tensor(xs)) - paddle.to_tensor(ys)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        sd = opt.state_dict()
        assert any(k.startswith("lookahead_slow_") for k in sd)
        slow_before = {k: np.asarray(v._data if hasattr(v, "_data") else v)
                       for k, v in sd.items() if k.startswith("lookahead_slow_")}
        opt2 = LookAhead(paddle.optimizer.SGD(
            learning_rate=0.1, parameters=model.parameters()), alpha=0.5, k=3)
        opt2.set_state_dict(sd)
        assert opt2._step_num == 4
        for i, p in enumerate(model.parameters()):
            np.testing.assert_array_equal(
                np.asarray(opt2._slow[id(p)][1]),
                slow_before[f"lookahead_slow_{i}"])

    def test_lookahead_validates(self):
        from paddle_tpu.incubate import LookAhead
        model, _, _ = self._setup()
        inner = paddle.optimizer.SGD(learning_rate=0.1,
                                     parameters=model.parameters())
        with pytest.raises(ValueError):
            LookAhead(inner, alpha=2.0)
        with pytest.raises(ValueError):
            LookAhead(inner, k=0)

    def test_model_average_apply_restore(self):
        from paddle_tpu.incubate import ModelAverage
        model, xs, ys = self._setup()
        opt = paddle.optimizer.SGD(learning_rate=0.5,
                                   parameters=model.parameters())
        ma = ModelAverage(parameters=model.parameters())
        snaps = []
        for i in range(3):
            loss = ((model(paddle.to_tensor(xs)) - paddle.to_tensor(ys)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            ma.step()
            snaps.append(np.asarray(model.weight._buf).copy())
        cur = np.asarray(model.weight._buf).copy()
        with ma:
            avg = np.asarray(model.weight._buf)
            np.testing.assert_allclose(avg, np.mean(snaps, axis=0), rtol=1e-5)
        np.testing.assert_array_equal(np.asarray(model.weight._buf), cur)
