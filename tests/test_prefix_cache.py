"""Automatic prefix caching (ISSUE 3 tentpole): shared KV pages across
requests via chain-hash lookup, refcounted page tables, copy-on-write on
shared-page writes, LRU eviction of cached-but-unreferenced pages.
Correctness bar everywhere: byte-identical tokens vs a prefix_cache=False
engine at the same seeds.

One cache-on/cache-off engine pair is module-shared (each LLMEngine build
compiles its prefill program — per-test engines would dominate suite wall
time); tests that need special pool geometry build their own."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.serving import LLMEngine


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, pc, **kw):
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(model, prefix_cache=pc, **kw)


@pytest.fixture(scope="module")
def eng_off(model):
    return _engine(model, False)


@pytest.fixture(scope="module")
def eng_on(model):
    return _engine(model, True)


def _serve_one_by_one(eng, prompts, **req_kw):
    """Admit + finish each request before the next (keeps the cache warm
    between requests). Returns (results, prefill dispatch counts)."""
    outs, disp = [], []
    for p in prompts:
        rid = eng.add_request(p, **req_kw)
        eng.run_until_done()
        outs.append(eng.result(rid))
        disp.append(eng._finished[rid].prefill_dispatches)
    return outs, disp


class TestPrefixCache:
    def test_shared_prefix_fewer_dispatches_and_parity(self, eng_on, eng_off):
        rng = np.random.RandomState(0)
        prefix = rng.randint(1, 128, (16,)).astype(np.int32)  # 2 full pages
        prompts = [np.concatenate([prefix,
                                   rng.randint(1, 128, (5,)).astype(np.int32)])
                   for _ in range(2)]
        ref, ref_disp = _serve_one_by_one(eng_off, prompts, max_new_tokens=6)
        got, disp = _serve_one_by_one(eng_on, prompts, max_new_tokens=6)
        assert got == ref                      # byte-identical tokens
        # the second request's 2-page shared prefix is served from cache:
        # strictly fewer prefill dispatches than the first request
        assert disp[1] < disp[0], (disp, ref_disp)
        st = eng_on.prefix_cache_stats()
        assert st["hits"] >= 2 and st["cached_pages"] >= 2, st
        # the cache-off engine must pay full prefill both times
        assert ref_disp[0] == ref_disp[1]

    def test_seeded_sampling_parity(self, eng_on, eng_off):
        rng = np.random.RandomState(1)
        prefix = rng.randint(1, 128, (16,)).astype(np.int32)
        prompts = [np.concatenate([prefix,
                                   rng.randint(1, 128, (3,)).astype(np.int32)])
                   for _ in range(2)]
        kw = dict(max_new_tokens=5, do_sample=True, temperature=0.8,
                  top_p=0.9, seed=1234)
        ref, _ = _serve_one_by_one(eng_off, prompts, **kw)
        got, _ = _serve_one_by_one(eng_on, prompts, **kw)
        assert got == ref

    def test_cow_on_shared_page(self, eng_on, eng_off):
        """A fully-cached prompt re-prefills its final token into the LAST
        shared page while the original owner still maps it — the write must
        copy, not clobber the sharer's prefix."""
        rng = np.random.RandomState(2)
        p = rng.randint(1, 128, (16,)).astype(np.int32)  # exactly 2 pages

        def serve(eng):
            r1 = eng.add_request(p, max_new_tokens=8)
            eng.step()                       # admit + first prefill chunk
            while eng._slots[0] is not None and eng._slots[0].pos < len(p):
                eng.step()                   # r1 prefilled, still decoding
            r2 = eng.add_request(p, max_new_tokens=8)
            eng.run_until_done()
            return eng.result(r1), eng.result(r2)

        ref = serve(eng_off)
        cow0 = eng_on.cache_cow_copies
        got = serve(eng_on)
        assert got == ref
        assert eng_on.cache_cow_copies > cow0, eng_on.prefix_cache_stats()

    def test_eviction_under_pool_pressure(self, model):
        """Pool far smaller than the distinct-prompt working set: cached
        pages must be reclaimed LRU (not starve admission) and every
        request must still match the cache-off engine."""
        rng = np.random.RandomState(3)
        prompts = [rng.randint(1, 128, (24,)).astype(np.int32)
                   for _ in range(4)]
        kw = dict(max_batch=1, max_len=48)
        ref, _ = _serve_one_by_one(_engine(model, False, **kw), prompts,
                                   max_new_tokens=4)
        eng = _engine(model, True, **kw)
        got, _ = _serve_one_by_one(eng, prompts, max_new_tokens=4)
        assert got == ref
        assert eng.cache_evictions >= 1, eng.prefix_cache_stats()

    def test_preemption_oversubscription_parity(self, model):
        """Concurrent slots + a pool too small for everyone's decode growth:
        preemption (recompute) must interoperate with shared/cached pages
        and still produce identical tokens."""
        rng = np.random.RandomState(4)
        prefix = rng.randint(1, 128, (16,)).astype(np.int32)
        prompts = [np.concatenate([prefix,
                                   rng.randint(1, 128, (4,)).astype(np.int32)])
                   for _ in range(3)]
        # worst case 2 slots x ceil(40/8)=10 pages; a 7-page pool runs dry
        # once both slots outgrow their prompts mid-decode
        kw = dict(max_batch=2, max_len=40, page_pool=7)

        def serve(eng):
            rids = [eng.add_request(p, max_new_tokens=12) for p in prompts]
            eng.run_until_done()
            return [eng.result(r) for r in rids]

        ref_eng = _engine(model, False, **kw)
        ref = serve(ref_eng)
        eng = _engine(model, True, **kw)
        got = serve(eng)
        assert got == ref
        # the configuration must actually exercise the oversubscribed path
        assert eng.preemptions + ref_eng.preemptions > 0

    def test_knob_off_is_legacy_engine(self, eng_off):
        assert len(eng_off._finished) > 0      # served earlier tests
        st = eng_off.prefix_cache_stats()
        assert st["hits"] == st["misses"] == st["evictions"] == 0
        assert st["cached_pages"] == 0 and st["reclaimable_pages"] == 0
        # every page back on the free list, exactly as before the feature
        assert len(eng_off._free_pages) == eng_off.n_pages - 1

    def test_stats_and_full_recycle_with_cache_on(self, eng_on):
        st = eng_on.prefix_cache_stats()
        assert st["hits"] > 0 and st["cached_pages"] > 0
        assert st["prefill_dispatches"] > 0
        # all pages accounted for: free + reclaimable == whole pool
        assert (len(eng_on._free_pages) + len(eng_on._lru)) \
            == eng_on.n_pages - 1
