"""Op coverage tests via the OpTest-style golden harness (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from op_test import check_output, check_grad

rng = np.random.RandomState(42)


class TestUnaryOps:
    x = rng.uniform(0.1, 0.9, (3, 4)).astype(np.float32)

    @pytest.mark.parametrize("name,ref", [
        ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt), ("tanh", np.tanh),
        ("sin", np.sin), ("cos", np.cos), ("abs", np.abs), ("square", np.square),
        ("floor", np.floor), ("ceil", np.ceil), ("sigmoid", lambda a: 1 / (1 + np.exp(-a))),
        ("rsqrt", lambda a: 1 / np.sqrt(a)), ("log1p", np.log1p),
        ("reciprocal", lambda a: 1 / a), ("erf", None),
    ])
    def test_forward(self, name, ref):
        if ref is None:
            from scipy.special import erf as ref
        check_output(getattr(pt, name), ref, [self.x])

    @pytest.mark.parametrize("name", ["exp", "log", "sqrt", "tanh", "sigmoid", "square"])
    def test_grad(self, name):
        check_grad(getattr(pt, name), [self.x])


class TestBinaryOps:
    a = rng.uniform(0.5, 1.5, (3, 4)).astype(np.float32)
    b = rng.uniform(0.5, 1.5, (3, 4)).astype(np.float32)

    @pytest.mark.parametrize("name,ref", [
        ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
        ("divide", np.divide), ("maximum", np.maximum), ("minimum", np.minimum),
        ("pow", np.power), ("atan2", np.arctan2),
    ])
    def test_forward(self, name, ref):
        check_output(getattr(pt, name), ref, [self.a, self.b])

    @pytest.mark.parametrize("name", ["add", "multiply", "divide"])
    def test_grad(self, name):
        check_grad(getattr(pt, name), [self.a, self.b])

    def test_broadcast(self):
        a = rng.rand(3, 1, 4).astype(np.float32)
        b = rng.rand(5, 1).astype(np.float32)
        check_output(pt.add, np.add, [a, b])
        check_grad(pt.add, [a, b])


class TestReductions:
    x = rng.rand(2, 3, 4).astype(np.float32)

    def test_sum(self):
        check_output(pt.sum, lambda a: a.sum(), [self.x])
        check_output(pt.sum, lambda a: a.sum(1), [self.x], kwargs={"axis": 1})
        check_output(pt.sum, lambda a: a.sum((0, 2), keepdims=True), [self.x],
                     kwargs={"axis": [0, 2], "keepdim": True})

    def test_mean_grad(self):
        check_grad(pt.mean, [self.x], kwargs={"axis": 1})

    def test_max_min(self):
        check_output(pt.max, lambda a: a.max(2), [self.x], kwargs={"axis": 2})
        check_output(pt.min, lambda a: a.min(), [self.x])

    def test_prod_logsumexp(self):
        check_output(pt.prod, lambda a: a.prod(1), [self.x], kwargs={"axis": 1})
        from scipy.special import logsumexp as np_lse
        check_output(pt.logsumexp, lambda a: np_lse(a, axis=1), [self.x], kwargs={"axis": 1})

    def test_cumsum(self):
        check_output(pt.cumsum, lambda a: a.cumsum(1), [self.x], kwargs={"axis": 1})

    def test_var_std(self):
        check_output(pt.var, lambda a: a.var(ddof=1), [self.x])
        check_output(pt.std, lambda a: a.std(axis=1, ddof=1), [self.x], kwargs={"axis": 1})


class TestManipulation:
    x = rng.rand(2, 3, 4).astype(np.float32)

    def test_reshape_transpose(self):
        check_output(pt.reshape, lambda a: a.reshape(6, 4), [self.x], kwargs={"shape": [6, 4]})
        check_output(pt.reshape, lambda a: a.reshape(2, -1), [self.x], kwargs={"shape": [2, -1]})
        check_output(pt.transpose, lambda a: a.transpose(2, 0, 1), [self.x],
                     kwargs={"perm": [2, 0, 1]})
        check_grad(pt.transpose, [self.x], kwargs={"perm": [2, 0, 1]})

    def test_squeeze_unsqueeze(self):
        y = rng.rand(2, 1, 3).astype(np.float32)
        check_output(pt.squeeze, lambda a: a.squeeze(1), [y], kwargs={"axis": 1})
        check_output(pt.unsqueeze, lambda a: a[:, None], [self.x], kwargs={"axis": 1})

    def test_concat_stack_split(self):
        a = rng.rand(2, 3).astype(np.float32)
        b = rng.rand(2, 3).astype(np.float32)
        out = pt.concat([pt.to_tensor(a), pt.to_tensor(b)], axis=0)
        np.testing.assert_allclose(out.numpy(), np.concatenate([a, b], 0))
        out = pt.stack([pt.to_tensor(a), pt.to_tensor(b)], axis=1)
        np.testing.assert_allclose(out.numpy(), np.stack([a, b], 1))
        parts = pt.split(pt.to_tensor(a), [1, 2], axis=1)
        assert [p.shape for p in parts] == [[2, 1], [2, 2]]

    def test_gather_ops(self):
        x = pt.to_tensor(self.x)
        idx = pt.to_tensor(np.array([0, 1], np.int64))
        assert pt.gather(x, idx, axis=2).shape == [2, 3, 2]
        nd_idx = pt.to_tensor(np.array([[0, 1], [1, 2]], np.int64))
        assert pt.gather_nd(x, nd_idx).shape == [2, 4]

    def test_tile_expand(self):
        a = rng.rand(1, 3).astype(np.float32)
        check_output(pt.tile, lambda v: np.tile(v, (2, 2)), [a], kwargs={"repeat_times": [2, 2]})
        check_output(pt.expand, lambda v: np.broadcast_to(v, (4, 3)), [a], kwargs={"shape": [4, 3]})

    def test_flatten_flip_roll(self):
        check_output(pt.flatten, lambda a: a.reshape(2, 12), [self.x],
                     kwargs={"start_axis": 1, "stop_axis": 2})
        check_output(pt.flip, lambda a: np.flip(a, 1), [self.x], kwargs={"axis": [1]})
        check_output(pt.roll, lambda a: np.roll(a, 2, 1), [self.x],
                     kwargs={"shifts": 2, "axis": 1})

    def test_scatter(self):
        x = np.zeros((4, 2), np.float32)
        idx = np.array([1, 3], np.int64)
        upd = np.ones((2, 2), np.float32)
        out = pt.scatter(pt.to_tensor(x), pt.to_tensor(idx), pt.to_tensor(upd))
        ref = x.copy(); ref[idx] = upd
        np.testing.assert_allclose(out.numpy(), ref)

    def test_take_along_put_along(self):
        a = rng.rand(3, 4).astype(np.float32)
        i = rng.randint(0, 4, (3, 2)).astype(np.int64)
        out = pt.take_along_axis(pt.to_tensor(a), pt.to_tensor(i), axis=1)
        np.testing.assert_allclose(out.numpy(), np.take_along_axis(a, i, 1))


class TestLinalg:
    def test_matmul_variants(self):
        a = rng.rand(3, 4).astype(np.float32)
        b = rng.rand(4, 5).astype(np.float32)
        check_output(pt.matmul, np.matmul, [a, b])
        check_grad(pt.matmul, [a, b])
        check_output(pt.matmul, lambda x, y: x.T @ y, [a.T.copy(), b],
                     kwargs={"transpose_x": True})
        c = rng.rand(2, 3, 4).astype(np.float32)
        d = rng.rand(2, 4, 5).astype(np.float32)
        check_output(pt.bmm, np.matmul, [c, d])

    def test_einsum(self):
        a = rng.rand(3, 4).astype(np.float32)
        b = rng.rand(4, 5).astype(np.float32)
        out = pt.einsum("ij,jk->ik", pt.to_tensor(a), pt.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), a @ b, rtol=1e-5)

    def test_norm(self):
        a = rng.rand(3, 4).astype(np.float32)
        check_output(pt.norm, lambda x: np.linalg.norm(x), [a], rtol=1e-4)
        check_output(pt.norm, lambda x: np.linalg.norm(x, axis=1), [a],
                     kwargs={"p": 2, "axis": 1}, rtol=1e-4)

    def test_solve_inverse(self):
        a = (rng.rand(3, 3) + 3 * np.eye(3)).astype(np.float32)
        b = rng.rand(3, 2).astype(np.float32)
        check_output(pt.solve, lambda x, y: np.linalg.solve(x, y), [a, b], rtol=1e-3, atol=1e-4)
        check_output(pt.inverse, np.linalg.inv, [a], rtol=1e-3, atol=1e-4)

    def test_svd_qr_cholesky(self):
        a = rng.rand(4, 3).astype(np.float32)
        u, s, v = pt.svd(pt.to_tensor(a))
        np.testing.assert_allclose((u.numpy() * s.numpy()) @ v.numpy().T, a, atol=1e-4)
        q, r = pt.qr(pt.to_tensor(a))
        np.testing.assert_allclose(q.numpy() @ r.numpy(), a, atol=1e-4)
        spd = (a.T @ a + 3 * np.eye(3)).astype(np.float32)
        L = pt.cholesky(pt.to_tensor(spd))
        np.testing.assert_allclose(L.numpy() @ L.numpy().T, spd, atol=1e-3)


class TestSearchSort:
    def test_argmax_sort_topk(self):
        a = rng.rand(3, 5).astype(np.float32)
        assert pt.argmax(pt.to_tensor(a)).item() == a.argmax()
        check_output(pt.sort, lambda x: np.sort(x, 1), [a], kwargs={"axis": 1})
        v, i = pt.topk(pt.to_tensor(a), 2, axis=1)
        ref = np.sort(a, 1)[:, ::-1][:, :2]
        np.testing.assert_allclose(v.numpy(), ref)
        np.testing.assert_array_equal(
            np.take_along_axis(a, i.numpy(), 1), ref)

    def test_nonzero_masked_select_unique(self):
        a = np.array([[0, 1], [2, 0]], np.float32)
        nz = pt.nonzero(pt.to_tensor(a))
        np.testing.assert_array_equal(nz.numpy(), [[0, 1], [1, 0]])
        ms = pt.masked_select(pt.to_tensor(a), pt.to_tensor(a > 0))
        np.testing.assert_allclose(np.sort(ms.numpy()), [1, 2])
        u = pt.unique(pt.to_tensor(np.array([3, 1, 1, 2])))
        np.testing.assert_array_equal(u.numpy(), [1, 2, 3])

    def test_where(self):
        c = np.array([True, False, True])
        a = np.array([1.0, 2.0, 3.0], np.float32)
        b = np.array([9.0, 8.0, 7.0], np.float32)
        out = pt.where(pt.to_tensor(c), pt.to_tensor(a), pt.to_tensor(b))
        np.testing.assert_allclose(out.numpy(), [1, 8, 3])


class TestCreation:
    def test_basics(self):
        assert pt.zeros([2, 3]).shape == [2, 3]
        assert pt.ones([2]).numpy().tolist() == [1, 1]
        assert pt.full([2], 7).numpy().tolist() == [7, 7]
        np.testing.assert_array_equal(pt.arange(5).numpy(), np.arange(5))
        np.testing.assert_array_equal(pt.eye(3).numpy(), np.eye(3, dtype=np.float32))
        a = pt.to_tensor(np.ones((2, 2), np.float32))
        assert pt.zeros_like(a).numpy().sum() == 0
        np.testing.assert_array_equal(pt.linspace(0, 1, 5).numpy(),
                                      np.linspace(0, 1, 5, dtype=np.float32))

    def test_tril_triu(self):
        a = rng.rand(3, 3).astype(np.float32)
        check_output(pt.tril, np.tril, [a])
        check_output(pt.triu, np.triu, [a])


class TestRandom:
    def test_seed_reproducible(self):
        pt.seed(123)
        a = pt.randn([4, 4]).numpy()
        pt.seed(123)
        b = pt.randn([4, 4]).numpy()
        np.testing.assert_array_equal(a, b)
        c = pt.randn([4, 4]).numpy()
        assert not np.array_equal(b, c)

    def test_distributions(self):
        pt.seed(0)
        u = pt.uniform([1000], min=0.0, max=1.0).numpy()
        assert 0 <= u.min() and u.max() <= 1 and abs(u.mean() - 0.5) < 0.05
        n = pt.normal(0.0, 1.0, [2000]).numpy()
        assert abs(n.mean()) < 0.1 and abs(n.std() - 1.0) < 0.1
        r = pt.randint(0, 10, [100]).numpy()
        assert r.min() >= 0 and r.max() < 10
        p = pt.randperm(10).numpy()
        np.testing.assert_array_equal(np.sort(p), np.arange(10))

    def test_multinomial_bernoulli(self):
        pt.seed(0)
        probs = pt.to_tensor(np.array([0.0, 0.0, 1.0], np.float32))
        s = pt.multinomial(probs, 5, replacement=True)
        assert (s.numpy() == 2).all()
        b = pt.bernoulli(pt.to_tensor(np.full((100,), 0.99, np.float32)))
        assert b.numpy().mean() > 0.9


class TestLogic:
    def test_logical(self):
        a = pt.to_tensor([True, False])
        b = pt.to_tensor([True, True])
        assert pt.logical_and(a, b).numpy().tolist() == [True, False]
        assert pt.logical_or(a, b).numpy().tolist() == [True, True]
        assert pt.logical_not(a).numpy().tolist() == [False, True]
        assert pt.all(b).item() and pt.any(a).item()

    def test_close(self):
        a = pt.to_tensor([1.0, 2.0])
        b = pt.to_tensor([1.0 + 1e-7, 2.0])
        assert pt.allclose(a, b).item()
        assert pt.equal_all(a, a).item()
