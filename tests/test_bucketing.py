"""Bucketed variable-seqlen training (VERDICT r3 #5): mixed-length data must
train through a compiled step with <= #buckets traces, at loss parity with
padding everything to one fixed shape."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.io import BucketCollate


def _mixed_length_data(n=12, lo=5, hi=60, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, (int(rng.randint(lo, hi)),)).astype(np.int64)
            for _ in range(n)]


def _make_model():
    from paddle_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM
    pt.seed(0)
    cfg = GPT2Config.tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                          max_position_embeddings=64)
    model = GPT2ForCausalLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3,
                             parameters=model.parameters())

    def step(x, y):
        _, loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    return model, step


def test_bucket_lengths_are_pow2_capped():
    c = BucketCollate(floor=16, max_len=48)
    assert c.bucket_length(3) == 16
    assert c.bucket_length(16) == 16
    assert c.bucket_length(17) == 32
    assert c.bucket_length(40) == 48          # capped at max_len
    assert BucketCollate(floor=8).bucket_length(100) == 128


def test_mixed_lengths_compile_once_per_bucket():
    data = _mixed_length_data()
    collate = BucketCollate(floor=16, max_len=64)
    model, step = _make_model()
    static = pt.jit.to_static(step)
    batches = [data[i:i + 4] for i in range(0, len(data), 4)]
    buckets = set()
    for b in batches * 2:                      # two epochs
        ids, labels = collate(b)
        buckets.add(ids.shape[1])
        static(ids, labels)
    # one traced signature per bucket, not per distinct raw length
    assert len(static._cache) <= len(buckets)
    assert all(not g.eager_only for g in static._cache.values())


def test_bucketed_loss_parity_with_fixed_padding():
    """Right-padding to a SMALLER bucket must give the same loss as padding
    the same samples to the global fixed shape (causal attention + ignored
    pad labels make trailing pads inert)."""
    data = _mixed_length_data(n=4, lo=6, hi=30, seed=3)
    small = BucketCollate(floor=16, max_len=64)
    big = BucketCollate(floor=64, max_len=64)   # fixed-shape padding

    model, step = _make_model()
    ids_s, lab_s = small(data)
    ids_b, lab_b = big(data)
    assert ids_s.shape[1] < ids_b.shape[1]
    _, loss_small = model(ids_s, labels=lab_s)
    _, loss_big = model(ids_b, labels=lab_b)
    np.testing.assert_allclose(float(np.asarray(loss_small._data)),
                               float(np.asarray(loss_big._data)),
                               rtol=2e-5)


def test_bucketed_training_through_dataloader():
    """End-to-end: DataLoader(collate_fn=BucketCollate) + to_static step
    trains (loss drops) over mixed-length data."""
    from paddle_tpu.io import DataLoader

    class _ListDataset:
        def __init__(self, items):
            self.items = items

        def __getitem__(self, i):
            return self.items[i]

        def __len__(self):
            return len(self.items)

    data = _ListDataset(_mixed_length_data(n=16, seed=5))
    collate = BucketCollate(floor=32, max_len=64)
    loader = DataLoader(data, batch_size=4, shuffle=False,
                        collate_fn=collate)
    model, step = _make_model()
    static = pt.jit.to_static(step)
    losses = []
    for _ in range(6):
        for ids, labels in loader:
            losses.append(float(np.asarray(static(ids, labels)._data,
                                           np.float32)))
    assert losses[-1] < losses[0], (losses[0], losses[-1])
