"""Registry-driven OpTest suite (reference: test/legacy_test/op_test.py:418 —
golden outputs + analytic-vs-finite-difference gradients, driven here by the
declarative op registry instead of per-op test classes)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.ops.registry import REGISTRY, coverage_report

GOLDEN = sorted(n for n, s in REGISTRY.items() if s.kind == "golden")
SMOKE = sorted(n for n, s in REGISTRY.items() if s.kind == "smoke")
ALIAS = sorted(n for n, s in REGISTRY.items() if s.kind == "alias")
INPLACE = sorted(n for n, s in REGISTRY.items() if s.kind == "inplace")
GRAD = sorted(n for n, s in REGISTRY.items() if s.grad)


def _wrap(x):
    if isinstance(x, list):
        return [pt.to_tensor(v) for v in x]
    return pt.to_tensor(x)


def _kwargs(spec):
    return {k: (pt.to_tensor(v) if isinstance(v, np.ndarray) else v)
            for k, v in spec.kwargs.items()}


def _run(spec):
    op = spec.resolve()
    raw = spec.sample() if spec.sample else []
    ins = [_wrap(x) for x in raw]
    return raw, op(*ins, **_kwargs(spec))


def _flat_outs(out):
    if isinstance(out, (list, tuple)):
        res = []
        for o in out:
            res.extend(_flat_outs(o))
        return res
    return [out] if isinstance(out, Tensor) else []


@pytest.mark.parametrize("name", GOLDEN)
def test_golden(name):
    spec = REGISTRY[name]
    raw, out = _run(spec)
    if spec.check is not None:
        # golden-by-property (decompositions with sign/order ambiguity):
        # the check asserts reconstruction + structural invariants
        spec.check(raw, out)
        return
    ref = spec.np_ref(*raw)
    outs = out if isinstance(out, (list, tuple)) else [out]
    refs = ref if isinstance(ref, (list, tuple)) else [ref]
    for o, r in zip(outs, refs):
        o_np = np.asarray(o.numpy()) if isinstance(o, Tensor) else np.asarray(o)
        r_np = np.asarray(r)
        if np.iscomplexobj(r_np) or np.iscomplexobj(o_np):
            np.testing.assert_allclose(o_np.astype(np.complex128),
                                       r_np.astype(np.complex128),
                                       atol=spec.atol, rtol=spec.rtol)
        elif r_np.dtype == np.bool_ or o_np.dtype == np.bool_:
            np.testing.assert_array_equal(o_np.astype(bool), r_np.astype(bool))
        elif np.issubdtype(r_np.dtype, np.integer):
            np.testing.assert_array_equal(o_np.astype(np.int64),
                                          r_np.astype(np.int64))
        else:
            np.testing.assert_allclose(o_np.astype(np.float64),
                                       r_np.astype(np.float64),
                                       atol=spec.atol, rtol=spec.rtol)


@pytest.mark.parametrize("name", SMOKE)
def test_smoke(name):
    spec = REGISTRY[name]
    _, out = _run(spec)
    for o in _flat_outs(out):
        a = np.asarray(o.numpy())
        if np.issubdtype(a.dtype, np.floating):
            assert np.isfinite(a).all(), f"{name} produced non-finite output"


@pytest.mark.parametrize("name", ALIAS)
def test_alias(name):
    import paddle_tpu.ops as O
    spec = REGISTRY[name]
    assert callable(getattr(O, name))
    assert callable(getattr(O, spec.alias_of))


@pytest.mark.parametrize("name", INPLACE)
def test_inplace_installed(name):
    assert hasattr(Tensor, name), f"Tensor.{name} missing"


@pytest.mark.parametrize("name", GRAD)
def test_grad(name):
    from op_test import check_grad
    spec = REGISTRY[name]
    raw = spec.sample() if spec.sample else []
    if not raw or any(isinstance(x, list) for x in raw):
        pytest.skip("grad check needs plain tensor inputs")
    idx = [i for i, x in enumerate(raw)
           if np.issubdtype(np.asarray(x).dtype, np.floating)]
    check_grad(spec.resolve(), raw, grad_idx=idx, kwargs=_kwargs(spec),
               atol=8e-3, rtol=8e-3)


def test_inplace_semantics():
    x = pt.to_tensor(np.array([1.0, 4.0, 9.0], np.float32))
    y = x.clone()
    y.sqrt_()
    np.testing.assert_allclose(y.numpy(), np.sqrt(x.numpy()), rtol=1e-6)
    z = pt.to_tensor(np.array([1.0, 2.0], np.float32))
    z.set_(pt.to_tensor(np.array([5.0], np.float32)))
    assert z.shape == [1] and float(z.numpy()[0]) == 5.0


def test_coverage_floor():
    """VERDICT r4 #7 done-criterion: golden >= 330, remaining smokes < 30
    and every one carries a documented reason (RNG-valued output etc.)."""
    rep = coverage_report()
    assert rep["registered_ops"] >= 470, rep
    assert rep["golden_tested"] >= 330, rep
    assert rep["grad_checked"] >= 60, rep
    smokes = rep["smoke_reasons"]
    assert len(smokes) < 30, smokes
    assert all(smokes.values()), smokes
