"""LLM decode/serving path tests (VERDICT #5): paged KV-cache Pallas kernel,
top-p sampling, cached generate(), predictor surface (reference:
block_multi_head_attention, top_p_sampling_kernel.h, analysis_predictor.h)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM, KVCache


class TestPagedAttention:
    def _mk(self, B=3, H=8, KVH=2, D=128, page=16, S=4, P=32, seed=0):
        import jax.numpy as jnp
        rng = np.random.RandomState(seed)
        q = jnp.asarray(rng.randn(B, H, D).astype(np.float32))
        kp = jnp.asarray(rng.randn(P, page, KVH, D).astype(np.float32))
        vp = jnp.asarray(rng.randn(P, page, KVH, D).astype(np.float32))
        bt = jnp.asarray(rng.choice(P, (B, S), replace=False).astype(np.int32))
        return q, kp, vp, bt

    def test_kernel_matches_reference_gqa(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.paged_attention import (paged_attention,
                                                           paged_attention_ref)
        q, kp, vp, bt = self._mk()
        cl = jnp.asarray(np.array([5, 33, 64], np.int32))
        out = paged_attention(q, kp, vp, bt, cl)
        ref = paged_attention_ref(q, kp, vp, bt, cl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    def test_page_boundary_lengths(self):
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.paged_attention import (paged_attention,
                                                           paged_attention_ref)
        q, kp, vp, bt = self._mk()
        for lens in ([1, 16, 17], [15, 32, 48], [64, 64, 64]):
            cl = jnp.asarray(np.array(lens, np.int32))
            out = paged_attention(q, kp, vp, bt, cl)
            ref = paged_attention_ref(q, kp, vp, bt, cl)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, err_msg=str(lens))

    def test_functional_wrapper(self):
        import paddle_tpu.nn.functional as F
        q, kp, vp, bt = self._mk(B=2, S=2, P=8)
        import jax.numpy as jnp
        cl = jnp.asarray(np.array([7, 20], np.int32))
        out = F.paged_attention(paddle.to_tensor(np.asarray(q)),
                                paddle.to_tensor(np.asarray(kp)),
                                paddle.to_tensor(np.asarray(vp)),
                                paddle.to_tensor(np.asarray(bt)),
                                paddle.to_tensor(np.asarray(cl)))
        assert out.shape == [2, 8, 128]
        assert np.isfinite(out.numpy()).all()


class TestKVCache:
    def test_update_and_prefix(self):
        # fixed-shape contract (jit decode): update returns the FULL cache
        # and a traced scalar offset tracks the valid prefix
        cache = KVCache(2, 16, 4, 8)
        k1 = paddle.to_tensor(np.ones((2, 3, 4, 8), np.float32))
        v1 = paddle.to_tensor(np.full((2, 3, 4, 8), 2.0, np.float32))
        kk, vv = cache.update(k1, v1)
        assert int(np.asarray(cache.offset._data)) == 3
        assert kk.shape == [2, 16, 4, 8]
        k2 = paddle.to_tensor(np.full((2, 1, 4, 8), 5.0, np.float32))
        kk, vv = cache.update(k2, k2)
        assert int(np.asarray(cache.offset._data)) == 4
        np.testing.assert_allclose(kk.numpy()[:, :3], 1.0)
        np.testing.assert_allclose(kk.numpy()[:, 3], 5.0)
        np.testing.assert_allclose(kk.numpy()[:, 4:], 0.0)  # untouched tail


class TestGenerate:
    def setup_method(self, _):
        paddle.seed(0)
        self.cfg = LlamaConfig.tiny()
        self.model = LlamaForCausalLM(self.cfg)
        self.model.eval()
        rng = np.random.RandomState(0)
        self.x = paddle.to_tensor(
            rng.randint(0, self.cfg.vocab_size, (2, 8)).astype(np.int32))

    def test_greedy_cache_matches_full_recompute(self):
        """VERDICT #5 done-criterion: cached greedy decode == full-context."""
        a = self.model.generate(self.x, max_new_tokens=6, use_cache=True)
        b = self.model.generate(self.x, max_new_tokens=6, use_cache=False)
        np.testing.assert_array_equal(np.asarray(a._data), np.asarray(b._data))

    def test_gen_state_reuse_and_eviction(self):
        m = self.model
        a1 = m.generate(self.x, max_new_tokens=4)
        states = m._gen_states
        assert len(states) == 1
        key = next(iter(states))
        entry = states[key]
        assert entry["busy"] is False
        # same geometry: reuse (same entry object), identical result
        a2 = m.generate(self.x, max_new_tokens=4)
        assert states[key] is entry
        np.testing.assert_array_equal(np.asarray(a1._data),
                                      np.asarray(a2._data))
        # different batch: second entry
        m.generate(self.x[:1], max_new_tokens=4)
        assert len(m._gen_states) == 2

    def test_generate_reentrant_uses_private_state(self):
        m = self.model
        m.generate(self.x, max_new_tokens=2)
        entry = next(iter(m._gen_states.values()))
        entry["busy"] = True   # simulate an in-flight generate
        try:
            out = m.generate(self.x, max_new_tokens=2)
            assert out.shape == [2, 10]
            # in-flight entry untouched, no overwrite
            assert next(iter(m._gen_states.values())) is entry
        finally:
            entry["busy"] = False

    def test_top_p_and_top_k_decode(self):
        tp = self.model.generate(self.x, max_new_tokens=4, do_sample=True,
                                 top_p=0.8, temperature=0.9)
        tk = self.model.generate(self.x, max_new_tokens=4, do_sample=True,
                                 top_k=5)
        assert tp.shape == [2, 12] and tk.shape == [2, 12]
        v = self.cfg.vocab_size
        assert (np.asarray(tp._data) < v).all() and (np.asarray(tk._data) < v).all()

    def test_eos_early_stop(self):
        # pick eos = the first greedy token → all sequences finish instantly
        first = np.asarray(self.model.generate(
            self.x, max_new_tokens=1)._data)[:, -1]
        eos = int(first[0])
        out = self.model.generate(self.x, max_new_tokens=16, eos_token_id=eos)
        arr = np.asarray(out._data)
        # sequence 0 must have stopped right away (padded with eos if other
        # sequences continued)
        assert arr.shape[1] < 8 + 16 or (arr[0, 9:] == eos).all()


class TestTopPSampling:
    def test_mass_restricted_to_nucleus(self):
        rng = np.random.RandomState(0)
        probs = np.zeros((1, 10), np.float32)
        probs[0, :3] = [0.5, 0.3, 0.15]        # nucleus at p=0.8 = tokens {0,1}
        probs[0, 3:] = 0.05 / 7
        counts = np.zeros(10)
        for seed in range(64):
            _, ids = paddle.ops.top_p_sampling(
                paddle.to_tensor(probs), 0.8, seed=seed + 1)
            counts[int(np.asarray(ids._data)[0, 0])] += 1
        assert counts[:2].sum() == 64, counts    # never leaves the nucleus


class TestPredictor:
    def test_save_load_run(self, tmp_path):
        from paddle_tpu.jit import InputSpec
        import paddle_tpu.inference as infer
        paddle.seed(0)
        net = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.ReLU(),
                                   paddle.nn.Linear(16, 4))
        net.eval()
        x = np.random.RandomState(0).rand(2, 8).astype(np.float32)
        ref = net(paddle.to_tensor(x)).numpy()
        prefix = str(tmp_path / "inference")
        paddle.jit.save(net, prefix, input_spec=[InputSpec([2, 8], "float32")])

        cfg = infer.Config(str(tmp_path))
        pred = infer.create_predictor(cfg)
        outs = pred.run([x])
        np.testing.assert_allclose(outs[0], ref, atol=1e-5)

        # handle-style IO (reference ZeroCopyTensor surface)
        h = pred.get_input_handle(pred.get_input_names()[0])
        h.copy_from_cpu(x)
        pred.run()
        np.testing.assert_allclose(
            pred.get_output_handle("out0").copy_to_cpu(), ref, atol=1e-5)

    def test_predictor_pool(self, tmp_path):
        from paddle_tpu.jit import InputSpec
        import paddle_tpu.inference as infer
        paddle.seed(1)
        net = paddle.nn.Linear(4, 2)
        net.eval()
        prefix = str(tmp_path / "inference")
        paddle.jit.save(net, prefix, input_spec=[InputSpec([1, 4], "float32")])
        pool = infer.PredictorPool(infer.Config(str(tmp_path)), 2)
        x = np.ones((1, 4), np.float32)
        a = pool.retrieve(0).run([x])[0]
        b = pool.retrieve(1).run([x])[0]
        np.testing.assert_allclose(a, b)


class TestLLMEngine:
    """Serving runtime (VERDICT r2 #9): continuous batching over a paged KV
    cache; parity with model.generate; runs sharded on a pp=2 x mp=2 mesh."""

    def _model(self):
        import paddle_tpu as pt
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        pt.seed(0)
        cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                          intermediate_size=176, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=128)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m

    def test_engine_matches_model_generate(self):
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(0)
        prompts = [rng.randint(1, 128, (n,)).astype(np.int32)
                   for n in (5, 9, 3)]
        ref = []
        for p in prompts:
            out = m.generate(pt.to_tensor(p[None, :]), max_new_tokens=6)
            ref.append(np.asarray(out.numpy())[0, len(p):].tolist())
        eng = LLMEngine(m, max_batch=2, max_len=64, page_size=8)
        rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
        eng.run_until_done()
        for rid, r in zip(rids, ref):
            assert eng.result(rid) == r, (rid, eng.result(rid), r)

    def test_continuous_batching_interleaves(self):
        import numpy as np
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(1)
        eng = LLMEngine(m, max_batch=2, max_len=32, page_size=8)
        # 4 requests through 2 slots: pages must recycle, results per-request
        rids = [eng.add_request(rng.randint(1, 128, (4 + i,)),
                                max_new_tokens=4) for i in range(4)]
        steps = eng.run_until_done()
        assert steps > 0 and len(eng._finished) == 4
        assert all(len(eng.result(r)) == 4 for r in rids)
        assert len(eng._free_pages) == eng.n_pages - 1  # all pages recycled

    def test_streaming_accessor_parity(self):
        """new_tokens(rid) is incremental and lossless: concatenating every
        increment reproduces result(rid) exactly, across continuous
        batching with slot churn (the public surface the gateway streams
        from — it never reads slot state)."""
        import numpy as np
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(3)
        eng = LLMEngine(m, max_batch=2, max_len=32, page_size=8)
        rids = [eng.add_request(rng.randint(1, 128, (4 + i,)),
                                max_new_tokens=5) for i in range(4)]
        seen = {r: [] for r in rids}
        while eng._waiting or any(s is not None for s in eng._slots):
            eng.step()
            for r in rids:
                inc = eng.new_tokens(r)
                assert all(type(t) is int for t in inc)
                seen[r].extend(inc)
        for r in rids:
            seen[r].extend(eng.new_tokens(r))      # final drain
            assert seen[r] == list(eng.result(r))
            assert eng.new_tokens(r) == []         # cursor fully consumed

    def test_stream_generator_parity(self):
        """stream(rid) drives the engine itself and yields exactly the
        batch-path result, ending on the terminal status."""
        import numpy as np
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(4)
        prompt = rng.randint(1, 128, (6,))
        ref_eng = LLMEngine(m, max_batch=1, max_len=32, page_size=8)
        rid0 = ref_eng.add_request(prompt, max_new_tokens=5)
        ref_eng.run_until_done()
        eng = LLMEngine(m, max_batch=1, max_len=32, page_size=8)
        rid = eng.add_request(prompt, max_new_tokens=5)
        toks = list(eng.stream(rid))
        assert toks == list(ref_eng.result(rid0))
        assert eng.status(rid).terminal

    def test_engine_on_pp_mp_mesh(self):
        import numpy as np
        import jax
        from jax.sharding import Mesh
        from paddle_tpu.inference.serving import LLMEngine
        if len(jax.devices()) < 4:
            import pytest
            pytest.skip("needs 4 virtual devices")
        m = self._model()
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "mp"))
        rng = np.random.RandomState(2)
        prompt = rng.randint(1, 128, (6,)).astype(np.int32)
        # unsharded reference
        ref_eng = LLMEngine(m, max_batch=2, max_len=32, page_size=8)
        r0 = ref_eng.add_request(prompt, max_new_tokens=5)
        ref_eng.run_until_done()
        # sharded engine: same tokens through a pp=2,mp=2 placement
        eng = LLMEngine(m, mesh=mesh, max_batch=2, max_len=32, page_size=8)
        r1 = eng.add_request(prompt, max_new_tokens=5)
        eng.run_until_done()
        assert eng.result(r1) == ref_eng.result(r0)


def test_generate_tokens_per_dispatch_parity():
    """K decode steps per dispatched program must produce identical tokens
    to per-token dispatch (cache state threads through the K-step capture)."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    m = LlamaForCausalLM(LlamaConfig.tiny())
    m.eval()
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randint(1, 256, (2, 7)).astype(np.int32))
    m._gen_states = {}
    a = np.asarray(m.generate(x, max_new_tokens=10,
                              tokens_per_dispatch=1).numpy())
    m._gen_states = {}
    b = np.asarray(m.generate(x, max_new_tokens=10,
                              tokens_per_dispatch=4).numpy())
    np.testing.assert_array_equal(a, b)
    assert b.shape == (2, 17)


class TestEngineRound4:
    """VERDICT r3 #4: chunked prefill, in-engine sampling, on-demand pages."""

    def _model(self):
        import paddle_tpu as pt
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        pt.seed(0)
        cfg = LlamaConfig(vocab_size=128, hidden_size=64,
                          intermediate_size=176, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=128)
        m = LlamaForCausalLM(cfg)
        m.eval()
        return m

    def test_prefill_is_chunked_not_per_token(self):
        """A P-token prompt must reach its first output token in
        ceil(P/chunk) prefill dispatches + 0 decode steps, not P steps."""
        import numpy as np
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(2)
        prompt = rng.randint(1, 128, (30,)).astype(np.int32)
        eng = LLMEngine(m, max_batch=2, max_len=64, page_size=8,
                        prefill_chunk=8)
        rid = eng.add_request(prompt, max_new_tokens=1)
        steps = eng.run_until_done()
        # ceil(30/8)=4 prefill dispatches; the 4th samples the only token
        assert steps == 4, steps
        assert len(eng.result(rid)) == 1
        assert eng.ttft(rid) is not None and eng.ttft(rid) > 0

    def test_chunked_prefill_matches_greedy_generate(self):
        """Prefill chunking must not change numerics: same outputs as
        model.generate for a prompt spanning several chunks AND pages."""
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(3)
        prompt = rng.randint(1, 128, (21,)).astype(np.int32)
        out = m.generate(pt.to_tensor(prompt[None, :]), max_new_tokens=5)
        ref = np.asarray(out.numpy())[0, len(prompt):].tolist()
        eng = LLMEngine(m, max_batch=2, max_len=64, page_size=8,
                        prefill_chunk=4)
        rid = eng.add_request(prompt, max_new_tokens=5)
        eng.run_until_done()
        assert eng.result(rid) == ref

    def test_sampled_decode_matches_model_generate(self):
        """Seeded top-p sampling in-engine reproduces model.generate's
        draws token-for-token (same filter order, same categorical key)."""
        import numpy as np
        import paddle_tpu as pt
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(4)
        prompt = rng.randint(1, 128, (7,)).astype(np.int32)
        out = m.generate(pt.to_tensor(prompt[None, :]), max_new_tokens=8,
                         do_sample=True, top_p=0.8, temperature=0.9,
                         seed=1234)
        ref = np.asarray(out.numpy())[0, len(prompt):].tolist()
        eng = LLMEngine(m, max_batch=2, max_len=64, page_size=8,
                        prefill_chunk=8)
        rid = eng.add_request(prompt, max_new_tokens=8, do_sample=True,
                              top_p=0.8, temperature=0.9, seed=1234)
        eng.run_until_done()
        assert eng.result(rid) == ref, (eng.result(rid), ref)

    def test_on_demand_pages_and_early_release(self):
        """Admit reserves only prompt pages; decode grows page-by-page; a
        request ending early (eos) never claims its worst-case pages."""
        import numpy as np
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(5)
        prompt = rng.randint(1, 128, (8,)).astype(np.int32)
        eng = LLMEngine(m, max_batch=1, max_len=64, page_size=8,
                        prefill_chunk=8)
        rid = eng.add_request(prompt, max_new_tokens=40)
        eng.step()                       # prefill: exactly 1 page in use
        used_after_prefill = eng.n_pages - 1 - len(eng._free_pages)
        assert used_after_prefill == 1   # NOT ceil((8+40)/8)=6
        # force an early finish via eos on the next emitted token
        eng._slots[0].eos = None
        for _ in range(9):               # 9 decode tokens -> 17 total -> 3 pages
            eng.step()
        used = eng.n_pages - 1 - len(eng._free_pages)
        assert used == 3, used
        eng._slots[0].eos = eng._slots[0].out[-1]  # any token; then match it
        # run until the engine emits that token again or request completes
        eng.run_until_done()
        assert len(eng._free_pages) == eng.n_pages - 1   # all freed

    def test_preemption_recovers_and_completes(self):
        """With an OVERSUBSCRIBED page_pool (smaller than worst case) the
        pool runs dry mid-decode, the youngest slot is preempted (recompute)
        and every request still completes with the right token count."""
        import numpy as np
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(6)
        # worst case would be 2*ceil(24/4)=12 pages; give it 7 -> must
        # preempt when both slots outgrow the pool
        eng = LLMEngine(m, max_batch=2, max_len=24, page_size=4,
                        prefill_chunk=8, page_pool=7)
        rids = [eng.add_request(rng.randint(1, 128, (8,)).astype(np.int32),
                                max_new_tokens=16) for _ in range(3)]
        eng.run_until_done()
        assert eng.preemptions > 0          # oversubscription really bit
        assert len(eng._finished) == 3
        for rid in rids:
            assert len(eng.result(rid)) == 16
        assert len(eng._free_pages) == eng.n_pages - 1

    def test_add_request_validation(self):
        import numpy as np
        import pytest
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        eng = LLMEngine(m, max_batch=1, max_len=16, page_size=8)
        with pytest.raises(ValueError):   # ADVICE r3: silent truncation
            eng.add_request(np.arange(1, 9), max_new_tokens=9)
        with pytest.raises(ValueError):
            eng.add_request(np.array([], np.int32), max_new_tokens=1)
        eng.add_request(np.arange(1, 9), max_new_tokens=8)  # exactly fits

    def test_decode_block_matches_single_step(self):
        """decode_block=4 (K decode steps fused per dispatch) must emit the
        same tokens as per-step decode, greedy AND seeded-sampled, and use
        fewer dispatches."""
        import numpy as np
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(7)
        prompt = rng.randint(1, 128, (9,)).astype(np.int32)
        outs = {}
        steps = {}
        for blk in (1, 4):
            eng = LLMEngine(m, max_batch=2, max_len=64, page_size=8,
                            prefill_chunk=8, decode_block=blk)
            rids = [eng.add_request(prompt, max_new_tokens=7),
                    eng.add_request(prompt, max_new_tokens=7,
                                    do_sample=True, top_p=0.8, seed=99)]
            steps[blk] = eng.run_until_done()
            outs[blk] = [eng.result(r) for r in rids]
        assert outs[1] == outs[4], (outs[1], outs[4])
        assert steps[4] < steps[1]

    def test_repeated_preemption_no_prompt_double_fold(self):
        """A request preempted TWICE must re-fold original_prompt + out, not
        compound the earlier fold (which duplicated context and overflowed
        the page table)."""
        import numpy as np
        from paddle_tpu.inference.serving import LLMEngine
        m = self._model()
        rng = np.random.RandomState(8)
        eng = LLMEngine(m, max_batch=2, max_len=24, page_size=4,
                        prefill_chunk=8, page_pool=7, decode_block=4)
        rids = [eng.add_request(rng.randint(1, 128, (8,)).astype(np.int32),
                                max_new_tokens=16) for _ in range(3)]
        eng.run_until_done()
        assert eng.preemptions >= 2
        for rid in rids:
            r = eng._finished[rid]
            assert len(r.out) == 16
            assert r.prompt == r.prompt0 + r.out[:len(r.prompt) - 8] \
                or len(r.prompt) == 8      # never double-folded


class TestAutoDecodeBlock:
    """decode_block='auto' fits t(k) = RTT + k*c from dispatch samples and
    targets the block where RTT costs <= ~25% of device time (VERDICT r4
    weak #7: the knob previously never adapted to measured RTT)."""

    def _engine(self, **kw):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference.serving import LLMEngine
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        m.eval()
        return cfg, LLMEngine(m, max_batch=2, max_len=96, page_size=8,
                              prefill_chunk=8, decode_block="auto", **kw)

    def test_runs_and_adapts(self):
        cfg, eng = self._engine()
        rng = np.random.RandomState(0)
        prompt = rng.randint(1, cfg.vocab_size, (8,)).astype(np.int32)
        rid = eng.add_request(prompt, max_new_tokens=40)
        eng.run_until_done()
        assert len(eng.result(rid)) == 40
        assert eng.auto_decode_block >= 1     # solved, not stuck pre-sample

    def test_block_model_math_high_rtt(self):
        """Feed synthetic timings: RTT 100ms, c 3ms/token -> target 32 (the
        cap), the tunneled-runtime regime."""
        _, eng = self._engine()
        eng._record_block_sample(1, 0.103)
        assert eng._block_target == 2         # second sample size forced
        eng._record_block_sample(2, 0.106)
        assert eng._block_target == 32        # 3*RTT/c = 100 -> pow2 cap

    def test_block_model_math_low_rtt(self):
        """Local runtime: RTT ~0.2ms, c 3ms -> block stays tiny."""
        _, eng = self._engine()
        eng._record_block_sample(1, 0.0032)
        eng._record_block_sample(2, 0.0062)
        assert eng._block_target <= 2

    def test_fixed_block_unchanged(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference.serving import LLMEngine
        paddle.seed(0)
        m = LlamaForCausalLM(LlamaConfig.tiny())
        m.eval()
        eng = LLMEngine(m, max_batch=2, max_len=64, page_size=8,
                        prefill_chunk=8, decode_block=4)
        assert eng.auto_decode_block == 4

    def test_late_samples_correct_the_fit(self):
        """Least-squares over ALL sampled block sizes (ADVICE r5: the old
        two-earliest-medians fit froze the model): a large-k sample that
        contradicts the small-k extrapolation pulls the target back down."""
        _, eng = self._engine()
        eng._record_block_sample(1, 0.103)
        eng._record_block_sample(2, 0.106)
        assert eng._block_target == 32        # small-k fit: huge RTT
        # k=32 runs now produce real timings: the per-token cost is much
        # higher than the k=1->2 delta suggested. The frozen fit would stay
        # at 32 forever; the full least-squares re-solves to a small block.
        for _ in range(8):
            eng._record_block_sample(32, 1.6)
        assert eng._block_target < 32, eng._block_target

    def test_periodic_small_k_resample(self):
        """Every 64th sample the target drops to a small k for one dispatch
        so the RTT intercept keeps getting re-measured."""
        _, eng = self._engine()
        eng._record_block_sample(1, 0.103)
        eng._record_block_sample(2, 0.106)
        assert eng._block_target == 32
        eng._block_n = 63
        eng._record_block_sample(32, 0.196)   # consistent with the fit
        assert eng._block_target == 2         # forced re-sample at small k
        eng._record_block_sample(2, 0.106)
        assert eng._block_target == 32        # model re-solved, back up
