"""TCPStore: native C++ daemon + Python fallback, one binary protocol."""
import os
import subprocess
import sys
import threading
import time

import pytest


def _exercise(master, client):
    master.set("obj", {"a": [1, 2]})
    assert client.get("obj") == {"a": [1, 2]}
    assert client.add("n", 3) == 3
    assert master.add("n", -1) == 2
    assert master.delete_key("obj") is True
    assert master.delete_key("obj") is False
    t = threading.Thread(target=lambda: (time.sleep(0.2),
                                         master.set("late", b"x")))
    t.start()
    client.wait(["late"], timeout=5)
    assert client.get("late") == b"x"
    t.join()
    with pytest.raises(TimeoutError):
        client.get("missing", timeout=0.2)


class TestNativeStore:
    def test_native_daemon(self):
        from paddle_tpu.core.native.build import load
        if load("pt_store", "store.cc") is None:
            pytest.skip("no C++ toolchain")
        # daemon is once-per-process; run in a subprocess for isolation
        code = """
import threading, time
from paddle_tpu.distributed.store import TCPStore
m = TCPStore(is_master=True, timeout=20)
assert m.server_kind == "native", m.server_kind
c = TCPStore(host="127.0.0.1", port=m.port, timeout=20)
m.set("k", 42); assert c.get("k") == 42
assert c.add("cnt", 7) == 7
threading.Thread(target=lambda: (time.sleep(0.2), m.set("w", 1))).start()
c.wait(["w", "cnt"], timeout=5)
print("NATIVE_OK")
"""
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=120,
                           env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert "NATIVE_OK" in r.stdout, r.stderr[-2000:]

    def test_native_cas_and_deleted_miss(self):
        from paddle_tpu.core.native.build import load
        if load("pt_store", "store.cc") is None:
            pytest.skip("no C++ toolchain")
        code = """
import threading, time
from paddle_tpu.distributed.store import TCPStore, StoreKeyDeleted
m = TCPStore(is_master=True, timeout=20)
assert m.server_kind == "native", m.server_kind
c = TCPStore(host="127.0.0.1", port=m.port, timeout=20)
# expect-absent install, then raw-token swap semantics
ok, cur = c.compare_and_set("k", None, ["v1"])
assert ok
raw = c.get_raw("k")
ok, _ = c.compare_and_set("k", b"stale-token", ["v2"])
assert not ok
ok, _ = c.compare_and_set("k", raw, ["v2"])
assert ok and c.get("k") == ["v2"]
ok, _ = c.compare_and_set("k", None, ["v3"])
assert not ok and c.get("k") == ["v2"]
# DELETE observed by a blocked GET -> typed miss, not a timeout stall
# (DELETE bumps the key's generation even when absent, so this is
# deterministic: the blocked reader always sees the bump)
res = {}
def blocked():
    try:
        c.get("dw", timeout=10)
        res["r"] = "value"
    except StoreKeyDeleted:
        res["r"] = "deleted"
    except TimeoutError:
        res["r"] = "timeout"
t = threading.Thread(target=blocked)
t.start()
time.sleep(0.3)
m.delete_key("dw")
t.join(15)
assert res.get("r") == "deleted", res
# a plain absent-key read still times out as before
try:
    c.get("never-set", timeout=0.1)
    raise SystemExit("expected TimeoutError")
except TimeoutError:
    pass
print("NATIVE_CAS_OK")
"""
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=120,
                           env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert "NATIVE_CAS_OK" in r.stdout, r.stderr[-2000:]

    def test_python_fallback(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
        from paddle_tpu.distributed.store import TCPStore
        m = TCPStore(is_master=True, timeout=20)
        assert m.server_kind == "python"
        c = TCPStore(host="127.0.0.1", port=m.port, timeout=20)
        _exercise(m, c)

    def test_get_after_add_returns_int(self, monkeypatch):
        # counters written by add() must be readable via get() (reference
        # TCPStore semantics; regression: pickle.loads crashed on them)
        monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
        from paddle_tpu.distributed.store import TCPStore
        m = TCPStore(is_master=True, timeout=20)
        m.add("counter", 5)
        assert m.get("counter") == 5

    def test_build_cache_reuses_so(self):
        from paddle_tpu.core.native import build
        lib1 = build.load("pt_store", "store.cc")
        lib2 = build.load("pt_store", "store.cc")
        if lib1 is None:
            pytest.skip("no C++ toolchain")
        assert lib1 is lib2
