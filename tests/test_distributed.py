"""Distributed/parallel tests on an 8-virtual-device CPU mesh (SURVEY §4:
multi-device is simulated in-process; numeric parity vs single-device refs)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.distributed import (ProcessMesh, Shard, Replicate, Partial,
                                    shard_tensor, reshard, fleet)
from paddle_tpu.distributed.auto_parallel.api import unshard_dtensor, get_placements
from paddle_tpu.distributed.fleet.topology import (CommunicateTopology,
                                                   HybridCommunicateGroup,
                                                   set_hybrid_communicate_group)

rng = np.random.RandomState(0)


def _mesh_1d(n=8, name="mp"):
    return ProcessMesh(np.arange(n), [name])


def _set_hcg(**dims):
    names = ["dp", "pp", "sharding", "sep", "mp", "ep"]
    d = [dims.get(n, 1) for n in names]
    topo = CommunicateTopology(names, d)
    hcg = HybridCommunicateGroup(topo, rank=0)
    set_hybrid_communicate_group(hcg)
    return hcg


class TestShardTensor:
    def test_shard_and_gather_roundtrip(self):
        mesh = _mesh_1d()
        x = rng.rand(16, 4).astype(np.float32)
        dt = shard_tensor(pt.to_tensor(x), mesh, [Shard(0)])
        assert dt.is_dist()
        np.testing.assert_allclose(np.asarray(dt._data), x)
        full = unshard_dtensor(dt)
        np.testing.assert_allclose(full.numpy(), x)

    def test_placements_roundtrip(self):
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        x = pt.to_tensor(rng.rand(8, 8).astype(np.float32))
        dt = shard_tensor(x, mesh, [Shard(0), Shard(1)])
        pl = get_placements(dt)
        assert pl[0] == Shard(0) and pl[1] == Shard(1)

    def test_reshard_transitions(self):
        # the reference's reshard function library (r_to_s, s_to_r, s_to_s)
        mesh = _mesh_1d()
        x = rng.rand(8, 8).astype(np.float32)
        r = shard_tensor(pt.to_tensor(x), mesh, [Replicate()])
        s0 = reshard(r, mesh, [Shard(0)])                      # r -> s
        np.testing.assert_allclose(np.asarray(s0._data), x)
        s1 = reshard(s0, mesh, [Shard(1)])                     # s -> s (all-to-all)
        np.testing.assert_allclose(np.asarray(s1._data), x)
        back = reshard(s1, mesh, [Replicate()])                # s -> r (all-gather)
        np.testing.assert_allclose(np.asarray(back._data), x)

    def test_sharded_matmul_matches_dense(self):
        mesh = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        a = rng.rand(8, 16).astype(np.float32)
        b = rng.rand(16, 32).astype(np.float32)
        da = shard_tensor(pt.to_tensor(a), mesh, [Shard(0)])
        db = shard_tensor(pt.to_tensor(b), mesh, [Replicate(), Shard(1)])
        out = da @ db
        np.testing.assert_allclose(np.asarray(out._data), a @ b, rtol=1e-5)

    def test_grad_through_sharded_params(self):
        mesh = _mesh_1d()
        w = pt.Parameter(rng.rand(8, 8).astype(np.float32))
        w._data = shard_tensor(w, mesh, [Shard(0)])._data
        x = pt.to_tensor(rng.rand(4, 8).astype(np.float32))
        (x @ w).sum().backward()
        assert w.grad is not None
        np.testing.assert_allclose(w.grad.numpy(),
                                   x.numpy().T @ np.ones((4, 8)), rtol=1e-5)


class TestTopology:
    def test_hybrid_topology_axes(self):
        topo = CommunicateTopology(["dp", "pp", "sharding", "sep", "mp"],
                                   [2, 2, 1, 1, 2])
        assert topo.world_size() == 8
        hcg = HybridCommunicateGroup(topo, rank=0)
        assert hcg.get_data_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        assert hcg.get_model_parallel_world_size() == 2
        mesh = hcg.get_mesh()
        assert mesh.shape == [2, 2, 1, 1, 2]
        assert mesh.dim_names == ["dp", "pp", "sharding", "sep", "mp"]

    def test_rank_coords(self):
        topo = CommunicateTopology(["dp", "mp"], [2, 4])
        assert topo.get_rank(dp=1, mp=2) == 6
        assert topo.get_coord(6) == {"dp": 1, "mp": 2}
        assert topo.get_axis_list("dp", 0) == [0, 1, 2, 3]
        assert topo.get_comm_list("mp") == [[0, 1, 2, 3], [4, 5, 6, 7]]

    def test_fleet_init_builds_mesh(self):
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 4, "pp_degree": 1,
                                   "sharding_degree": 1, "sep_degree": 1}
        f = fleet.Fleet()
        f.init(is_collective=True, strategy=strategy)
        hcg = f.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 4
        assert hcg.get_data_parallel_world_size() == 2


class TestTPLayers:
    def setup_method(self, m):
        _set_hcg(mp=8)

    def teardown_method(self, m):
        _set_hcg()

    def test_column_parallel_matches_dense(self):
        from paddle_tpu.parallel import ColumnParallelLinear
        pt.seed(1)
        col = ColumnParallelLinear(16, 32, gather_output=True)
        x = pt.to_tensor(rng.rand(4, 16).astype(np.float32))
        ref = x.numpy() @ col.weight.numpy() + col.bias.numpy()
        np.testing.assert_allclose(col(x).numpy(), ref, rtol=1e-4, atol=1e-5)
        assert getattr(col.weight._data.sharding, "num_devices", 1) == 8

    def test_row_parallel_matches_dense(self):
        from paddle_tpu.parallel import RowParallelLinear
        pt.seed(2)
        row = RowParallelLinear(32, 16)
        x = pt.to_tensor(rng.rand(4, 32).astype(np.float32))
        ref = x.numpy() @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(row(x).numpy(), ref, rtol=1e-4, atol=1e-5)

    def test_col_row_composition_with_grad(self):
        from paddle_tpu.parallel import ColumnParallelLinear, RowParallelLinear
        pt.seed(3)
        col = ColumnParallelLinear(16, 64, gather_output=False)
        row = RowParallelLinear(64, 16, input_is_parallel=True)
        x = pt.to_tensor(rng.rand(4, 16).astype(np.float32), stop_gradient=False)
        out = row(col(x))
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)
        out.sum().backward()
        assert col.weight.grad is not None and row.weight.grad is not None

    def test_vocab_parallel_embedding(self):
        from paddle_tpu.parallel import VocabParallelEmbedding
        pt.seed(4)
        emb = VocabParallelEmbedding(64, 16)
        ids = pt.to_tensor(np.array([[0, 13, 63]], np.int64))
        out = emb(ids)
        np.testing.assert_allclose(out.numpy(), emb.weight.numpy()[[0, 13, 63]][None],
                                   rtol=1e-6)


class TestSequenceParallel:
    def setup_method(self, m):
        _set_hcg(mp=8)

    def teardown_method(self, m):
        _set_hcg()

    def test_sp_linear_pair(self):
        from paddle_tpu.parallel import (ColumnSequenceParallelLinear,
                                         RowSequenceParallelLinear)
        pt.seed(5)
        col = ColumnSequenceParallelLinear(16, 64)
        row = RowSequenceParallelLinear(64, 16)
        x = pt.to_tensor(rng.rand(2, 8, 16).astype(np.float32))
        from paddle_tpu.parallel.sequence_parallel import scatter, all_gather
        xs = scatter(x)  # seq-sharded
        out = all_gather(row(col(xs)))
        ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy()) \
            @ row.weight.numpy() + row.bias.numpy()
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-5)


class TestMoE:
    def setup_method(self, m):
        _set_hcg(mp=8)

    def teardown_method(self, m):
        _set_hcg()

    def test_top2_gating_capacity(self):
        from paddle_tpu.parallel import top2_gating
        logits = jnp.asarray(rng.rand(16, 4).astype(np.float32))
        combine, dispatch, aux = top2_gating(logits, capacity=8)
        assert combine.shape == (16, 4, 8)
        # each token goes to at most 2 experts
        per_token = np.asarray(dispatch).sum(axis=(1, 2))
        assert (per_token <= 2).all()
        # no expert bucket exceeds capacity
        per_slot = np.asarray(dispatch).sum(axis=0)
        assert (per_slot <= 1 + 1e-6).all()
        assert float(aux) > 0

    def test_moe_layer_forward_backward(self):
        from paddle_tpu.parallel import MoELayer
        pt.seed(6)
        moe = MoELayer(d_model=16, num_experts=8, d_hidden=32, capacity_factor=2.0)
        x = pt.to_tensor(rng.rand(2, 8, 16).astype(np.float32), stop_gradient=False)
        out = moe(x)
        assert out.shape == [2, 8, 16]
        (out.sum() + moe.aux_loss * 0.01).backward()
        assert moe.gate_w.grad is not None
        assert moe.experts.w1.grad is not None

    def test_moe_preserves_token_mixture(self):
        # with capacity ~ all tokens, output = sum of gated expert outputs;
        # identity experts should roughly reconstruct gate-weighted input
        from paddle_tpu.parallel import MoELayer
        pt.seed(7)
        moe = MoELayer(d_model=8, num_experts=4, d_hidden=16, capacity_factor=4.0)
        # make experts identity-ish: w1 @ w2 == I impossible with gelu; just run
        x = pt.to_tensor(rng.rand(1, 4, 8).astype(np.float32))
        out = moe(x)
        assert np.isfinite(out.numpy()).all()


class TestParallelCrossEntropy:
    def teardown_method(self, m):
        _set_hcg()

    def test_matches_dense_cross_entropy(self):
        from paddle_tpu.parallel import ParallelCrossEntropy
        import paddle_tpu.nn.functional as F
        _set_hcg(mp=8)
        logits = rng.rand(2, 6, 64).astype(np.float32) * 4
        labels = rng.randint(0, 64, (2, 6))
        pce = ParallelCrossEntropy()
        got = pce(pt.to_tensor(logits), pt.to_tensor(labels)).numpy()
        want = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels),
                               reduction="none").numpy()
        np.testing.assert_allclose(got, want.reshape(got.shape), rtol=1e-5,
                                   atol=1e-6)
        # ignore_index zeroes those positions
        labels2 = labels.copy()
        labels2[0, 0] = -100
        got2 = pce(pt.to_tensor(logits), pt.to_tensor(labels2)).numpy()
        assert got2[0, 0] == 0.0

    def test_sharded_logits_never_gathered(self):
        """VERDICT r1 weak #5: the vocab-sharded path must not materialize
        replicated [B, S, V] logits — the compiled program may all-reduce
        scalars-per-token but must not all-gather the vocab axis."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        B, S, V = 2, 8, 512
        mesh = Mesh(np.array(jax.devices()[:8]), ("mp",))
        x = jax.device_put(
            jnp.asarray(rng.rand(B, S, V).astype(np.float32)),
            NamedSharding(mesh, P(None, None, "mp")))
        y = jnp.asarray(rng.randint(0, V, (B, S)))

        from paddle_tpu.parallel.mp_layers import _pce_math

        def ce(xa, ya):
            # the PRODUCT math (what ParallelCrossEntropy dispatches), under
            # the same sharding constraint its forward applies
            xa = jax.lax.with_sharding_constraint(
                xa, NamedSharding(mesh, P(None, None, "mp")))
            return _pce_math(xa, ya)

        compiled = jax.jit(ce).lower(x, y).compile()
        hlo = compiled.as_text()
        for line in hlo.splitlines():
            if "all-gather" in line:
                assert str(V) not in line, f"vocab gathered: {line}"


class TestExpertParallelAxis:
    """VERDICT r1 #10: dedicated ep axis; TP x EP compose."""

    def teardown_method(self, m):
        _set_hcg()

    def test_fleet_init_plumbs_ep_degree(self):
        from paddle_tpu.distributed import fleet as fleet_mod
        strategy = fleet_mod.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "ep_degree": 2}
        f = fleet_mod.Fleet()
        f.init(strategy=strategy)
        hcg = f.get_hybrid_communicate_group()
        assert hcg.get_expert_parallel_world_size() == 2
        assert hcg.get_data_parallel_world_size() == 2

    def test_topology_exposes_ep(self):
        hcg = _set_hcg(ep=4, mp=2)
        assert hcg.get_expert_parallel_world_size() == 4
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_expert_parallel_rank() == 0
        # pre-ep 5-dim call sites still work (dims padded with 1)
        topo5 = CommunicateTopology(dims=[1, 1, 1, 1, 1])
        assert HybridCommunicateGroup(topo5, rank=0) \
            .get_expert_parallel_world_size() == 1

    def test_experts_shard_on_ep_and_hidden_on_mp(self):
        from paddle_tpu.parallel import MoELayer
        _set_hcg(ep=4, mp=2)
        pt.seed(8)
        moe = MoELayer(d_model=16, num_experts=8, d_hidden=32)
        s1 = moe.experts.w1._data.sharding.spec  # [E, d_model, d_hidden]
        s2 = moe.experts.w2._data.sharding.spec  # [E, d_hidden, d_model]
        assert s1[0] == "ep" and s1[2] == "mp", s1
        assert s2[0] == "ep" and s2[1] == "mp", s2

    def test_ep_sharded_moe_matches_single_device(self):
        from paddle_tpu.parallel import MoELayer
        x = rng.rand(2, 8, 16).astype(np.float32)

        def run():
            pt.seed(9)
            moe = MoELayer(d_model=16, num_experts=4, d_hidden=32,
                           capacity_factor=2.0)
            return moe(pt.to_tensor(x)).numpy()

        _set_hcg()
        ref = run()
        _set_hcg(dp=2, mp=2, ep=2)
        out = run()
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


class TestRingAttention:
    def test_matches_dense_attention(self):
        _set_hcg(sep=8)
        try:
            from paddle_tpu.parallel import ring_flash_attention
            from paddle_tpu.nn.functional.attention import _sdpa_ref
            B, S, H, D = 1, 32, 2, 8
            q = rng.rand(B, S, H, D).astype(np.float32)
            k = rng.rand(B, S, H, D).astype(np.float32)
            v = rng.rand(B, S, H, D).astype(np.float32)
            for causal in (False, True):
                out = ring_flash_attention(pt.to_tensor(q), pt.to_tensor(k),
                                           pt.to_tensor(v), causal=causal)
                ref = _sdpa_ref(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                                causal=causal)
                np.testing.assert_allclose(np.asarray(out._data), np.asarray(ref),
                                           rtol=2e-4, atol=2e-5)
        finally:
            _set_hcg()

    def test_grad_flows(self):
        _set_hcg(sep=8)
        try:
            from paddle_tpu.parallel import ring_flash_attention
            q = pt.to_tensor(rng.rand(1, 16, 2, 8).astype(np.float32),
                             stop_gradient=False)
            out = ring_flash_attention(q, q, q, causal=True)
            out.sum().backward()
            assert q.grad is not None and np.isfinite(q.grad.numpy()).all()
        finally:
            _set_hcg()


class TestPipeline:
    def test_spmd_pipeline_matches_sequential(self):
        from paddle_tpu.parallel.pipeline import pipeline_forward
        P_ = 4
        mesh = ProcessMesh(np.arange(P_), ["pp"]).jax_mesh()
        D = 8
        Ws = rng.rand(P_, D, D).astype(np.float32) * 0.5

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        M, B = 6, 2
        xs = rng.rand(M, B, D).astype(np.float32)
        out = pipeline_forward(stage_fn, jnp.asarray(Ws), jnp.asarray(xs),
                               mesh=mesh, axis_name="pp")
        ref = xs.copy()
        for s in range(P_):
            ref = np.tanh(ref @ Ws[s])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)

    def test_pipeline_layer_partition_and_forward(self):
        from paddle_tpu.parallel import PipelineLayer, LayerDesc
        pt.seed(8)
        pl = PipelineLayer([LayerDesc(nn.Linear, 8, 8) for _ in range(6)],
                           num_stages=2)
        assert pl.get_stage_from_index(0) == 0
        assert pl.get_stage_from_index(5) == 1
        x = pt.randn([2, 8])
        out = pl(x)
        assert out.shape == [2, 8]

    def test_pipeline_parallel_train_batch(self):
        from paddle_tpu.parallel import PipelineLayer, PipelineParallel, LayerDesc
        from paddle_tpu.distributed.fleet import DistributedStrategy
        pt.seed(9)
        strategy = DistributedStrategy()
        strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
        model = PipelineLayer([LayerDesc(nn.Linear, 4, 8), LayerDesc(nn.ReLU),
                               LayerDesc(nn.Linear, 8, 1)], num_stages=2,
                              loss_fn=nn.MSELoss())
        pp = PipelineParallel(model, None, strategy)
        opt = pt.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
        x = pt.to_tensor(rng.rand(8, 4).astype(np.float32))
        y = pt.to_tensor(rng.rand(8, 1).astype(np.float32))
        l0 = float(pp.train_batch((x, y), opt).item())
        for _ in range(20):
            l = float(pp.train_batch((x, y), opt).item())
        assert l < l0

    def test_shared_layer_desc_ties_weights(self):
        from paddle_tpu.parallel import PipelineLayer, SharedLayerDesc
        pl = PipelineLayer([
            SharedLayerDesc("emb", nn.Linear, None, "weight", 4, 4),
            SharedLayerDesc("emb", nn.Linear, None, "weight", 4, 4),
        ], num_stages=1)
        l0, l1 = pl.run_functions[0][0], pl.run_functions[1][0]
        assert l0.weight is l1.weight


class TestRecompute:
    def test_recompute_matches_plain(self):
        from paddle_tpu.distributed.fleet.recompute import recompute
        pt.seed(10)
        lin1, lin2 = nn.Linear(8, 32), nn.Linear(32, 8)

        def block(x):
            return lin2(pt.tanh(lin1(x)))

        x1 = pt.to_tensor(rng.rand(4, 8).astype(np.float32), stop_gradient=False)
        out = recompute(block, x1)
        out.sum().backward()
        g_rc = (x1.grad.numpy().copy(), lin1.weight.grad.numpy().copy())

        lin1.clear_gradients() if hasattr(lin1, "clear_gradients") else None
        for p in list(lin1.parameters()) + list(lin2.parameters()):
            p.clear_grad()
        x2 = pt.to_tensor(x1.numpy(), stop_gradient=False)
        block(x2).sum().backward()
        np.testing.assert_allclose(g_rc[0], x2.grad.numpy(), rtol=1e-5)
        np.testing.assert_allclose(g_rc[1], lin1.weight.grad.numpy(), rtol=1e-5)

    def test_recompute_preserves_dropout_rng(self):
        from paddle_tpu.distributed.fleet.recompute import recompute
        pt.seed(11)
        drop = nn.Dropout(0.5)

        def block(x):
            return drop(x) * 2

        x = pt.to_tensor(np.ones((64,), np.float32), stop_gradient=False)
        out = recompute(block, x)
        out.backward(pt.ones([64]))
        # grad is 4 where kept (2 * upscale 2), 0 where dropped; fwd out matches
        fwd = out.numpy()
        grad = x.grad.numpy()
        np.testing.assert_allclose((fwd > 0).astype(np.float32) * 4.0, grad)


class TestSharding:
    def test_stage1_shards_accumulators(self):
        _set_hcg(sharding=8)
        try:
            from paddle_tpu.parallel.sharding import shard_accumulators
            w = pt.Parameter(rng.rand(16, 4).astype(np.float32))
            opt = pt.optimizer.Adam(learning_rate=0.1, parameters=[w])
            shard_accumulators(opt)
            (w * w).sum().backward()
            opt.step()
            m1 = opt._accumulators["moment1"][id(w)]
            assert getattr(m1._buf.sharding, "num_devices", 1) == 8
            assert np.isfinite(np.asarray(w._buf)).all()
        finally:
            _set_hcg()

    def test_group_sharded_parallel_stage3(self):
        _set_hcg(sharding=8)
        try:
            from paddle_tpu.distributed.sharding import group_sharded_parallel
            pt.seed(12)
            model = nn.Linear(16, 8)
            opt = pt.optimizer.AdamW(learning_rate=0.01,
                                     parameters=model.parameters())
            model, opt = group_sharded_parallel(model, opt, level="p_g_os")
            assert getattr(model.weight._buf.sharding, "num_devices", 1) == 8
            x = pt.to_tensor(rng.rand(4, 16).astype(np.float32))
            loss = model(x).sum()
            loss.backward()
            opt.step()
            assert np.isfinite(np.asarray(model.weight._buf)).all()
        finally:
            _set_hcg()


class TestDistributedCheckpoint:
    def test_save_load_with_reshard(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import save_state_dict, load_state_dict
        mesh = _mesh_1d()
        w = rng.rand(16, 8).astype(np.float32)
        src = {"w": shard_tensor(pt.to_tensor(w), mesh, [Shard(0)])}
        save_state_dict(src, str(tmp_path / "ckpt"))
        # load into a DIFFERENTLY sharded destination (reshard-on-load)
        dst = {"w": shard_tensor(pt.zeros([16, 8]), mesh, [Shard(1)])}
        load_state_dict(dst, str(tmp_path / "ckpt"))
        np.testing.assert_allclose(np.asarray(dst["w"]._data), w)

    def test_mesh_change_reshard_no_host_gather(self, tmp_path):
        """VERDICT #7 done-criterion: save on mp=8, load on dp=2 x mp=4 —
        orbax restores each destination shard directly; zero full-array
        host materializations on the load path."""
        import jax
        import paddle_tpu.distributed.checkpoint as ckpt
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
        devs = np.array(jax.devices()[:8])
        mesh8 = ProcessMesh(np.arange(8), ["mp"])
        w = rng.rand(32, 16).astype(np.float32)
        src = {"w": shard_tensor(pt.to_tensor(w), mesh8, [Shard(0)])}
        ckpt.save_state_dict(src, str(tmp_path / "ck2"))
        meta = ckpt.load_metadata(str(tmp_path / "ck2"))
        assert meta["w"]["shape"] == [32, 16]
        assert "mp" in str(meta["w"]["sharding"])

        mesh24 = ProcessMesh(np.arange(8).reshape(2, 4), ["dp", "mp"])
        dst = {"w": shard_tensor(pt.zeros([32, 16]), mesh24,
                                 [Replicate(), Shard(1)])}
        before = ckpt._host_gather_count
        ckpt.load_state_dict(dst, str(tmp_path / "ck2"))
        assert ckpt._host_gather_count == before, "load gathered to host"
        out = dst["w"]._data
        # destination sharding took effect: each shard holds a 32x4 slice
        assert out.addressable_shards[0].data.shape == (32, 4)
        np.testing.assert_allclose(np.asarray(out), w)

    def test_async_save_snapshots_before_queueing(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                       load_state_dict,
                                                       wait_async_save)
        w = pt.to_tensor(rng.rand(8, 4).astype(np.float32))
        expect = np.asarray(w._data).copy()
        save_state_dict({"w": w}, str(tmp_path / "ck3"), async_save=True)
        w._data = w._data * 0.0          # mutate immediately after queueing
        wait_async_save()
        dst = {"w": pt.zeros([8, 4])}
        load_state_dict(dst, str(tmp_path / "ck3"))
        np.testing.assert_allclose(np.asarray(dst["w"]._data), expect)

    def test_async_save_inplace_mutation_cannot_corrupt(self, tmp_path):
        """The hard case: a plain np.ndarray param mutated IN PLACE right
        after async_save returns.  Rebinding (above) leaves the old buffer
        alive, so it passes even with reference-queueing; in-place writes
        reach the queued buffer unless the snapshot is a forced copy."""
        from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                       load_state_dict,
                                                       wait_async_save)
        w = rng.rand(8, 4).astype(np.float32)
        expect = w.copy()
        save_state_dict({"w": w}, str(tmp_path / "ck4"), async_save=True)
        w[:] = -1.0                      # in-place clobber, same buffer
        wait_async_save()
        dst = {"w": pt.zeros([8, 4])}
        load_state_dict(dst, str(tmp_path / "ck4"))
        np.testing.assert_allclose(np.asarray(dst["w"]._data), expect)

    def test_async_save_snapshots_sharded_arrays(self, tmp_path):
        """Multi-device arrays used to be queued by live reference (only
        single-device ones were host-copied); the snapshot must rebuild them
        from per-shard host copies, preserving the sharding for the
        shard-wise write, so the checkpoint survives later rebinds."""
        from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                       load_state_dict,
                                                       wait_async_save)
        mesh = _mesh_1d()
        w = rng.rand(16, 8).astype(np.float32)
        t = shard_tensor(pt.to_tensor(w), mesh, [Shard(0)])
        save_state_dict({"w": t}, str(tmp_path / "ck5"), async_save=True)
        t._data = t._data * 0.0
        wait_async_save()
        dst = {"w": shard_tensor(pt.zeros([16, 8]), mesh, [Shard(1)])}
        load_state_dict(dst, str(tmp_path / "ck5"))
        np.testing.assert_allclose(np.asarray(dst["w"]._data), w)


class TestUlyssesAttention:
    def teardown_method(self, m):
        _set_hcg()

    def test_matches_dense_attention(self):
        from paddle_tpu.parallel import ulysses_attention
        from paddle_tpu.nn.functional.attention import _sdpa_ref
        _set_hcg(sep=8)
        B, S, H, D = 1, 64, 8, 16
        q = rng.rand(B, S, H, D).astype(np.float32)
        k = rng.rand(B, S, H, D).astype(np.float32)
        v = rng.rand(B, S, H, D).astype(np.float32)
        for causal in (False, True):
            out = ulysses_attention(pt.to_tensor(q), pt.to_tensor(k),
                                    pt.to_tensor(v), causal=causal)
            ref = _sdpa_ref(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                            causal=causal)
            np.testing.assert_allclose(out.numpy(), np.asarray(ref),
                                       rtol=2e-4, atol=2e-5)

    def test_gradients_flow(self):
        from paddle_tpu.parallel import ulysses_attention
        _set_hcg(sep=8)
        q = pt.to_tensor(rng.rand(1, 32, 8, 8).astype(np.float32),
                         stop_gradient=False)
        k = pt.to_tensor(rng.rand(1, 32, 8, 8).astype(np.float32),
                         stop_gradient=False)
        v = pt.to_tensor(rng.rand(1, 32, 8, 8).astype(np.float32),
                         stop_gradient=False)
        ulysses_attention(q, k, v, causal=True).sum().backward()
        for t in (q, k, v):
            assert t.grad is not None and np.isfinite(t.grad.numpy()).all()

    def test_head_divisibility_enforced(self):
        from paddle_tpu.parallel import ulysses_attention
        _set_hcg(sep=8)
        q = pt.to_tensor(rng.rand(1, 32, 6, 8).astype(np.float32))
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, q, q)

    def test_single_device_fallback(self):
        from paddle_tpu.parallel import ulysses_attention
        _set_hcg()
        q = pt.to_tensor(rng.rand(1, 16, 4, 8).astype(np.float32))
        out = ulysses_attention(q, q, q, causal=True)
        assert out.shape == [1, 16, 4, 8]


class TestLlamaUlyssesBackend:
    def teardown_method(self, m):
        _set_hcg()

    def test_forward_parity_ring_vs_ulysses(self):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        ids = rng.randint(0, 256, (2, 33)).astype(np.int32)  # 32 tokens

        def run(backend):
            _set_hcg(sep=4)
            pt.seed(11)
            cfg = LlamaConfig.tiny(sep_backend=backend)
            m = LlamaForCausalLM(cfg)
            _, loss = m(pt.to_tensor(ids[:, :-1]),
                        labels=pt.to_tensor(ids[:, 1:]))
            return float(loss)

        np.testing.assert_allclose(run("ulysses"), run("ring"), rtol=1e-4)


class TestCommunicationSurface:
    """API-parity wrappers (reference distributed/communication/*): single-
    process semantics here; cross-process paths are covered by test_launch."""

    def test_gather_and_objects(self):
        from paddle_tpu import distributed as dist
        t = pt.to_tensor(np.arange(4.0, dtype=np.float32))
        out = []
        dist.gather(t, out, dst=0)
        np.testing.assert_allclose(out[0].numpy(), t.numpy())
        objs = []
        dist.gather_object({"a": 1}, objs, dst=0)
        assert objs == [{"a": 1}]
        o = []
        dist.scatter_object_list(o, [[42]])
        assert o == [[42]]

    def test_p2p_loopback_and_batch(self):
        from paddle_tpu import distributed as dist
        t = pt.to_tensor(np.arange(4.0, dtype=np.float32))
        r = pt.to_tensor(np.zeros(4, np.float32))
        assert dist.isend(t, dst=0).wait()
        dist.irecv(r, src=0).wait()
        np.testing.assert_allclose(r.numpy(), t.numpy())
        works = dist.batch_isend_irecv([dist.P2POp(dist.isend, t, 0),
                                        dist.P2POp(dist.irecv, r, 0)])
        assert all(w.wait() for w in works)
        dist.wait(t)

    def test_all_to_all_single_one_proc(self):
        from paddle_tpu import distributed as dist
        x = pt.to_tensor(np.arange(8.0, dtype=np.float32).reshape(4, 2))
        out = pt.to_tensor(np.zeros((4, 2), np.float32))
        dist.all_to_all_single(out, x)
        np.testing.assert_allclose(out.numpy(), x.numpy())
        assert dist.alltoall is dist.all_to_all


class TestGroupShardedWrappers:
    """reference group_sharded_stage2.py:47 / stage3.py:85 model-wrapper API
    (round-1 VERDICT flagged these as docstring-only subclasses)."""

    def test_stage2_and_stage3_train(self):
        import numpy as np
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu.parallel import (GroupShardedStage2, GroupShardedStage3,
                                         GroupShardedOptimizerStage2)
        from paddle_tpu.distributed.fleet.topology import (
            CommunicateTopology, HybridCommunicateGroup,
            set_hybrid_communicate_group)
        import jax
        topo = CommunicateTopology(["dp", "pp", "sharding", "sep", "mp"],
                                   [1, 1, 8, 1, 1])
        set_hybrid_communicate_group(HybridCommunicateGroup(topo))
        rng = np.random.RandomState(0)
        xs = rng.randn(16, 8).astype(np.float32)
        ys = rng.randn(16, 8).astype(np.float32)

        for cls in (GroupShardedStage2, GroupShardedStage3):
            paddle.seed(0)
            model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=model.parameters())
            wrapped = cls(model, opt)
            losses = []
            for _ in range(3):
                loss = ((wrapped(paddle.to_tensor(xs))
                         - paddle.to_tensor(ys)) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            assert losses[-1] < losses[0], cls.__name__
            assert len(wrapped.state_dict()) == len(model.state_dict())
            if cls is GroupShardedStage3:
                # FSDP placement realized: first Linear weight sharded dim 0
                sh = model[0].weight._buf.sharding
                assert getattr(sh, "spec", None) is not None and \
                    sh.spec[0] == "sharding"
            # BOTH stages shard the optimizer accumulators
            acc = opt._accumulators["moment1"]
            any_sharded = any(
                getattr(getattr(t._buf, "sharding", None), "spec", (None,))[0]
                == "sharding" for t in acc.values())
            assert any_sharded, cls.__name__


class TestTopKGating:
    def test_topk_reduces_to_top2(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.parallel.moe import topk_gating, top2_gating
        rng = np.random.RandomState(0)
        logits = jnp.asarray(rng.randn(16, 4).astype(np.float32))
        c2, d2, a2 = top2_gating(logits, 8)
        ck, dk, ak = topk_gating(logits, 8, k=2)
        np.testing.assert_allclose(np.asarray(c2), np.asarray(ck), atol=1e-6)
        np.testing.assert_allclose(float(a2), float(ak), atol=1e-6)

    def test_topk_routes_k_experts_and_respects_capacity(self):
        import numpy as np
        import jax.numpy as jnp
        from paddle_tpu.parallel.moe import topk_gating
        rng = np.random.RandomState(1)
        S, E, C, K = 12, 8, 4, 4
        logits = jnp.asarray(rng.randn(S, E).astype(np.float32))
        combine, dispatch, aux = topk_gating(logits, C, k=K)
        d = np.asarray(dispatch)
        per_token = d.any(-1).sum(-1)          # experts hit per token
        assert per_token.max() <= K and per_token.max() >= 2
        # capacity: each (expert, slot) bucket holds at most one token
        assert d.sum(axis=0).max() <= 1 + 1e-6
        # combine weights normalized over selected experts
        w = np.asarray(combine).sum(axis=(1, 2))
        sel = per_token > 0
        np.testing.assert_allclose(w[sel], np.ones(sel.sum()), rtol=1e-5)

    def test_moe_model_with_top6_preset_trains(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        paddle.seed(0)
        cfg = LlamaConfig.deepseek_moe_16b(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=4,
            max_position_embeddings=64, num_experts=8,
            moe_intermediate_size=32)
        assert cfg.num_experts_per_tok == 6
        model = LlamaForCausalLM(cfg)
        assert model.llama.layers[0].mlp.top_k == 6
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=model.parameters())
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (2, 17)).astype(np.int32)
        losses = []
        for _ in range(3):
            _, loss = model(paddle.to_tensor(ids[:, :-1]),
                            labels=paddle.to_tensor(ids[:, 1:]))
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0]


def test_gradient_merge_strategy_wired():
    """VERDICT r2 weak #9: DistributedStrategy.gradient_merge must actually
    merge: k accumulation micro-steps + one averaged update == one update on
    the averaged gradient."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.fleet.hybrid_optimizer import (
        HybridParallelOptimizer,
    )

    def build():
        pt.seed(0)
        lin = pt.nn.Linear(4, 1)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
        return lin, opt

    rng = np.random.RandomState(0)
    xs = [pt.to_tensor(rng.rand(8, 4).astype(np.float32)) for _ in range(3)]
    ys = [pt.to_tensor(rng.rand(8, 1).astype(np.float32)) for _ in range(3)]

    # merged run: 3 micro-steps through the strategy-wrapped optimizer
    lin_m, opt_m = build()
    strat = DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 3, "avg": True}
    hopt = HybridParallelOptimizer(opt_m, strategy=strat)
    for x, y in zip(xs, ys):
        ((lin_m(x) - y) ** 2).mean().backward()
        hopt.step()
        hopt.clear_grad()

    # reference: one step on the mean of the three gradients
    lin_r, opt_r = build()
    for x, y in zip(xs, ys):
        ((lin_r(x) - y) ** 2).mean().backward()
    for p in lin_r.parameters():
        p.grad.set_value(p.grad / 3.0)
    opt_r.step()
    opt_r.clear_grad()

    for pm, pr in zip(lin_m.parameters(), lin_r.parameters()):
        np.testing.assert_allclose(np.asarray(pm._data),
                                   np.asarray(pr._data), rtol=1e-6)


def test_gradient_merge_handles_selected_rows_grads():
    """ADVICE r3: Embedding(sparse=True) produces SelectedRows grads; the
    merge-average on the k-th step must scale their values in place instead
    of raising on Tensor-only ops."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.core.selected_rows import SelectedRows
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.distributed.fleet.hybrid_optimizer import (
        HybridParallelOptimizer,
    )

    pt.seed(0)
    emb = pt.nn.Embedding(16, 4, sparse=True)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=emb.parameters())
    strat = DistributedStrategy()
    strat.gradient_merge = True
    strat.gradient_merge_configs = {"k_steps": 2, "avg": True}
    hopt = HybridParallelOptimizer(opt, strategy=strat)
    ids = pt.to_tensor(np.array([1, 3, 3], np.int64))
    for _ in range(2):
        emb(ids).sum().backward()
        assert isinstance(emb.weight.grad, SelectedRows)
        hopt.step()          # k-th step averages: must not raise
        hopt.clear_grad()
    w = np.asarray(emb.weight._data)
    assert np.isfinite(w).all()
    # grads existed only for looked-up rows; after the merged update the
    # sparse apply must have cleared them
    assert emb.weight.grad is None


def test_role_makers():
    """Cluster role plumbing (VERDICT §2.4 #69): env-derived PaddleCloud
    roles + explicit UserDefined roles."""
    import os
    from paddle_tpu.distributed.fleet import (PaddleCloudRoleMaker,
                                              UserDefinedRoleMaker, Role)
    env = {"TRAINING_ROLE": "PSERVER",
           "PADDLE_PSERVERS_IP_PORT_LIST": "10.0.0.1:6000,10.0.0.2:6000",
           "PADDLE_TRAINER_ENDPOINTS": "10.0.0.3:0,10.0.0.4:0",
           "POD_IP": "10.0.0.2", "PADDLE_PORT": "6000"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        rm = PaddleCloudRoleMaker(is_collective=False)
        assert rm.is_server() and not rm.is_worker()
        assert rm.server_index() == 1 and rm.server_num() == 2
        assert rm.get_pserver_endpoints() == ["10.0.0.1:6000",
                                              "10.0.0.2:6000"]
        assert rm.role_id() == 1
    finally:
        for k, v in old.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
    rm = PaddleCloudRoleMaker(is_collective=True)
    assert rm.is_worker()
    assert rm.is_first_worker() == (rm.worker_index() == 0)
    assert rm.worker_num() >= 1
    u = UserDefinedRoleMaker(current_id=1, role=Role.SERVER, worker_num=2,
                             server_endpoints=["a:1", "b:2"])
    assert u.is_server() and u.server_index() == 1 and u.server_num() == 2
    u2 = UserDefinedRoleMaker(current_id=0, role=Role.WORKER, worker_num=2)
    assert u2.is_worker() and u2.worker_num() == 2
