"""int8 KV-cache pages (VERDICT r4 missing #3; reference capability:
incubate block_multihead_attention cache_k/v_quant_scales, dynamic mode):
pages store int8 values + per-(token, kv-head) f32 scales, dequantized inside
the paged-attention kernel.  Same HBM budget -> ~2x page capacity."""
import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.pallas.paged_attention import (paged_attention,
                                                   paged_attention_ref,
                                                   quantize_kv)


def _paged_setup(seed=0, B=2, P=6, page=8, KVH=2, H=4, D=16, ctx=(13, 20)):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, H, D).astype(np.float32))
    k = jnp.asarray(rng.randn(P, page, KVH, D).astype(np.float32))
    v = jnp.asarray(rng.randn(P, page, KVH, D).astype(np.float32))
    tables = jnp.asarray(rng.randint(0, P, (B, 3)).astype(np.int32))
    ctx = jnp.asarray(np.array(ctx, np.int32))
    return q, k, v, tables, ctx


class TestQuantizedPagedAttention:
    def test_quantize_roundtrip_error_bounded(self):
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(5, 4, 32).astype(np.float32)) * 3.0
        qv, s = quantize_kv(x)
        assert qv.dtype == jnp.int8 and s.shape == (5, 4)
        deq = qv.astype(jnp.float32) * s[..., None]
        err = np.abs(np.asarray(deq - x))
        # symmetric int8: |err| <= scale/2 per element
        assert (err <= np.asarray(s)[..., None] / 2 + 1e-6).all()

    def test_ref_int8_close_to_f32(self):
        q, k, v, tables, ctx = _paged_setup()
        ref = paged_attention_ref(q, k, v, tables, ctx)
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        out = paged_attention_ref(q, kq, vq, tables, ctx,
                                  k_scales=ks, v_scales=vs)
        # documented tolerance: int8 KV quantization noise
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=0.05, rtol=0.05)

    def test_kernel_int8_matches_ref_int8(self):
        """The Pallas kernel (interpret mode on CPU) must agree with the
        dense-gather reference on identical int8 pages."""
        q, k, v, tables, ctx = _paged_setup()
        kq, ks = quantize_kv(k)
        vq, vs = quantize_kv(v)
        ref = paged_attention_ref(q, kq, vq, tables, ctx,
                                  k_scales=ks, v_scales=vs)
        out = paged_attention(q, kq, vq, tables, ctx,
                              k_scales=ks, v_scales=vs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-3, rtol=2e-3)


class TestEngineInt8Pages:
    def _engines(self, **kw):
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference.serving import LLMEngine
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        m.eval()
        base = dict(max_batch=2, max_len=64, page_size=8, prefill_chunk=8)
        base.update(kw)
        return (cfg, LLMEngine(m, **base),
                LLMEngine(m, kv_cache_dtype="int8", **base))

    def test_engine_parity_within_tolerance(self):
        """Greedy decode with int8 pages must track the full-precision
        engine: identical output length and a high token agreement rate
        (exact equality is not guaranteed — int8 KV noise can flip a
        near-tie argmax; that is the documented tolerance)."""
        cfg, eng_fp, eng_q = self._engines()
        rng = np.random.RandomState(1)
        prompt = rng.randint(1, cfg.vocab_size, (12,)).astype(np.int32)
        outs = []
        for eng in (eng_fp, eng_q):
            rid = eng.add_request(prompt, max_new_tokens=12)
            eng.run_until_done()
            outs.append(eng.result(rid))
        assert len(outs[0]) == len(outs[1]) == 12
        agree = np.mean(np.asarray(outs[0]) == np.asarray(outs[1]))
        assert agree >= 0.75, (agree, outs)

    def test_page_capacity_doubles_at_same_bytes(self):
        """The point of int8 pages: per-page bytes drop to ~(D+8)/(2D) of
        bf16, so the same page_pool byte budget holds ~2x the pages."""
        from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
        from paddle_tpu.inference.serving import LLMEngine
        paddle.seed(0)
        cfg = LlamaConfig.tiny()
        m = LlamaForCausalLM(cfg)
        m.eval()
        m.to(dtype="bfloat16")
        base = dict(max_batch=2, max_len=64, page_size=8, prefill_chunk=8)
        eng_fp = LLMEngine(m, **base)
        eng_q = LLMEngine(m, kv_cache_dtype="int8", **base)
        bpp_fp = eng_fp.kv_bytes_per_page()
        bpp_q = eng_q.kv_bytes_per_page()
        D = cfg.hidden_size // cfg.num_attention_heads
        expect = (D + 4) / (2 * D)     # int8 + f32 scale vs bf16
        assert bpp_q / bpp_fp == pytest.approx(expect, rel=0.05)
        # same byte budget -> 1/expect times the pages (tiny config D=16 ->
        # 1.6x; at the production head_dim=128 the same formula gives 1.94x)
        budget = 16 * bpp_fp
        assert budget // bpp_q == int(16 / expect)
        assert budget // bpp_q > 16

    def test_int8_engine_with_preemption_and_paging(self):
        """int8 pages compose with on-demand paging + preemption."""
        cfg, _, eng_q = self._engines(page_pool=10)
        rng = np.random.RandomState(2)
        rids = [eng_q.add_request(
            rng.randint(1, cfg.vocab_size, (10,)).astype(np.int32),
            max_new_tokens=20) for _ in range(3)]
        eng_q.run_until_done()
        for rid in rids:
            assert len(eng_q.result(rid)) == 20
