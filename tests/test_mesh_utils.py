"""DCN-aware mesh construction + incubate.distributed.models.moe surface."""
import numpy as np
import jax
import pytest

from paddle_tpu.distributed.mesh_utils import create_mesh, create_hybrid_mesh


class TestMeshUtils:
    def test_create_mesh_dict(self):
        m = create_mesh({"dp": 2, "mp": 4})
        assert m.axis_names == ("dp", "mp")
        assert m.devices.shape == (2, 4)
        assert len({d.id for d in m.devices.ravel()}) == 8

    def test_create_mesh_tuple(self):
        m = create_mesh((4, 2), ["a", "b"])
        assert m.devices.shape == (4, 2)

    def test_too_many_devices_raises(self):
        with pytest.raises(ValueError, match="needs"):
            create_mesh({"dp": 64})

    def test_hybrid_mesh_axes(self):
        # per-axis (ICI x DCN) factors: dp grows over DCN (2 slices), mp
        # stays inside a slice (dcn factor 1)
        m = create_hybrid_mesh({"dp": 1, "mp": 4}, {"dp": 2, "mp": 1})
        assert m.axis_names == ("dp", "mp")
        assert m.devices.shape == (2, 4)
        # mp rows stay within one contiguous "slice" of the enumeration
        # (the fallback's dcn-major placement contract)
        ids = np.vectorize(lambda d: d.id)(m.devices)
        assert set(ids[0].tolist()) == {0, 1, 2, 3}
        assert set(ids[1].tolist()) == {4, 5, 6, 7}
        # a sharded matmul over the hybrid mesh executes
        from jax.sharding import NamedSharding, PartitionSpec as P
        x = jax.device_put(np.ones((8, 16), np.float32),
                           NamedSharding(m, P("dp", "mp")))
        out = jax.jit(lambda a: a.sum())(x)
        assert float(out) == 128.0

    def test_hybrid_mesh_mismatched_axes_raise(self):
        with pytest.raises(ValueError, match="align|same keys"):
            create_hybrid_mesh((2, 2), (2,))


class TestIncubateMoeSurface:
    def test_reexports(self):
        from paddle_tpu.incubate.distributed.models.moe import (
            MoELayer, ExpertMLP, top2_gating)
        from paddle_tpu.parallel.moe import MoELayer as Core
        assert MoELayer is Core
