"""Disaggregated prefill/decode (ISSUE 9 tentpole): prefill and decode
engines on separate mesh slices with KV-page handoff between their pools.

Correctness bar everywhere: token-identical output vs the colocated
:class:`LLMEngine` for greedy and fixed-seed sampled requests — the copied
KV pages are bit-identical to what the decode slice would have computed, so
disaggregation may change dispatch structure and latency, never tokens.

The tiny 2-layer model is module-shared (engines build compiled programs);
the cross-slice test shards it over halves of the 8-virtual-device CPU
mesh."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.serving import (DisaggEngine, LLMEngine,
                                          RequestStatus, SpecConfig,
                                          split_mesh)
from paddle_tpu.testing import FAULTS, FailNth, injected
from paddle_tpu.testing.faults import Always


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


_KW = dict(max_batch=3, max_len=64, page_size=8, page_pool=48)


def _prompts(n, seed=0, lo=4, step=5):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 128, (lo + step * i,)).astype(np.int32)
            for i in range(n)]


def _serve(eng, prompts, **req_kw):
    rids = [eng.add_request(p, **req_kw) for p in prompts]
    eng.run_until_done()
    return [eng.result(r) for r in rids]


class TestDisaggParity:
    def test_greedy_token_exact(self, model):
        prompts = _prompts(4)
        ref = _serve(LLMEngine(model, debug_refcount_audit=True, **_KW),
                     prompts, max_new_tokens=7)
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        got = _serve(deng, prompts, max_new_tokens=7)
        assert got == ref
        assert deng.handoff_stats()["handoffs"] == len(prompts)
        assert deng.audit_refcounts() == []

    def test_fixed_seed_sampling_token_exact(self, model):
        prompts = _prompts(3, seed=1)
        kw = dict(max_new_tokens=6, do_sample=True, temperature=0.8,
                  top_p=0.9, top_k=20)
        ref_eng = LLMEngine(model, **_KW)
        ref = [ref_eng.add_request(p, seed=100 + i, **kw)
               for i, p in enumerate(prompts)]
        ref_eng.run_until_done()
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, seed=100 + i, **kw)
                for i, p in enumerate(prompts)]
        deng.run_until_done()
        assert [deng.result(r) for r in rids] == \
            [ref_eng.result(r) for r in ref]

    def test_prefix_cache_on_token_exact(self, model):
        # shared 24-token prefix: the prefill slice's cache serves the
        # later prompts' full pages; tokens must not move
        rng = np.random.RandomState(2)
        base = rng.randint(1, 128, (24,)).astype(np.int32)
        prompts = [np.concatenate([base, rng.randint(1, 128, (k,))
                                   .astype(np.int32)]) for k in (3, 5, 7)]
        ref_eng = LLMEngine(model, prefix_cache=True, **_KW)
        deng = DisaggEngine(model, prefix_cache=True,
                            debug_refcount_audit=True, **_KW)
        # two waves: wave 2 reuses the pages wave 1 registered (wave 1's
        # slots all admit before any key exists, so only wave 2 can hit)
        for wave in range(2):
            ref = _serve(ref_eng, prompts, max_new_tokens=6)
            got = _serve(deng, prompts, max_new_tokens=6)
            assert got == ref, wave
        # cache hits happen on the prefill slice (that is where prompts run)
        assert deng.prefix_cache_stats()["hits"] > 0
        assert deng.audit_refcounts() == []

    def test_spec_decode_on_token_exact(self, model):
        # repetitive prompt so the n-gram proposer actually drafts
        pat = np.tile(np.arange(1, 9, dtype=np.int32), 4)
        prompts = [pat, _prompts(1, seed=3)[0]]
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=8)
        deng = DisaggEngine(model, spec_decode=SpecConfig(max_draft=4),
                            debug_refcount_audit=True, **_KW)
        got = _serve(deng, prompts, max_new_tokens=8)
        assert got == ref
        assert deng.spec_stats()["verify_dispatches"] >= 1
        assert deng.audit_refcounts() == []

    def test_single_token_requests_skip_handoff(self, model):
        # max_new_tokens=1 finishes at the prefill slice's first emit:
        # nothing to decode, nothing to hand off
        prompts = _prompts(2, seed=4)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=1)
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        got = _serve(deng, prompts, max_new_tokens=1)
        assert got == ref
        assert deng.handoff_stats()["handoffs"] == 0
        assert deng.audit_refcounts() == []


class TestDisaggMesh:
    def test_split_mesh_halves(self):
        import jax
        from jax.sharding import Mesh
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "mp"))
        pre, dec = split_mesh(mesh, axis="mp")
        assert pre.axis_names == dec.axis_names == ("pp", "mp")
        assert pre.shape["mp"] == dec.shape["mp"] == 1
        assert not (set(pre.devices.flat) & set(dec.devices.flat))
        with pytest.raises(ValueError):
            split_mesh(Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                            ("pp", "mp")))

    def test_cross_slice_handoff_token_exact(self, model):
        import jax
        from jax.sharding import Mesh
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "mp"))
        pre_mesh, dec_mesh = split_mesh(mesh, axis="mp")
        prompts = _prompts(3, seed=5)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        deng = DisaggEngine(model, prefill_mesh=pre_mesh,
                            decode_mesh=dec_mesh,
                            debug_refcount_audit=True, **_KW)
        assert deng.handoff_stats()["cross_device"]
        got = _serve(deng, prompts, max_new_tokens=6)
        assert got == ref
        assert deng.handoff_stats()["handoffs"] == len(prompts)
        assert deng.audit_refcounts() == []


class TestDisaggChaos:
    def test_transient_handoff_faults_retried(self, model):
        prompts = _prompts(3, seed=6)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, max_new_tokens=6) for p in prompts]
        with injected("serving.kv_handoff", FailNth({1, 3}),
                      transient=True):
            deng.run_until_done()
        assert [deng.result(r) for r in rids] == ref
        stats = deng.handoff_stats()
        assert stats["retries"] >= 2 and stats["failures"] == 0
        assert deng.audit_refcounts() == []

    def test_poisoned_handoff_quarantines_only_that_request(self, model):
        prompts = _prompts(4, seed=7)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, max_new_tokens=6) for p in prompts]
        poison = rids[1]
        FAULTS.install("serving.kv_handoff", Always(),
                       match=lambda ctx: poison in ctx.get("rids", ()))
        try:
            deng.run_until_done()
        finally:
            FAULTS.reset()
        assert deng.status(poison) == RequestStatus.FAILED
        assert "InjectedFault" in deng.error(poison)
        for i in (0, 2, 3):
            assert deng.status(rids[i]) == RequestStatus.FINISHED
            assert deng.result(rids[i]) == ref[i], i
        stats = deng.handoff_stats()
        assert stats["failures"] == 1
        assert stats["handoffs"] == len(prompts) - 1
        # pages released on BOTH slices for the quarantined request
        assert deng.audit_refcounts() == []


class TestDisaggBackpressure:
    def test_handoff_queue_stays_bounded(self, model):
        # depth=1 and a decode side kept full: prefill must pause (no new
        # sink appends) instead of growing the queue without bound
        deng = DisaggEngine(model, handoff_depth=1,
                            debug_refcount_audit=True, **_KW)
        for p in _prompts(6, seed=8, lo=4, step=2):
            deng.add_request(p, max_new_tokens=8)
        steps = 0
        while deng.has_work() and steps < 500:
            deng.step()
            assert len(deng._queue) <= deng.handoff_depth
            steps += 1
        assert not deng.has_work()
        assert deng.handoff_stats()["handoffs"] == 6

    def test_cancel_in_handoff_queue_releases_pages(self, model):
        deng = DisaggEngine(model, handoff_depth=4,
                            debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, max_new_tokens=6)
                for p in _prompts(2, seed=9)]
        # step until something sits in the handoff queue, then cancel it
        steps = 0
        while not deng._queue and steps < 200:
            served = deng.dec.step()
            if len(deng._queue) < deng.handoff_depth:
                served += deng.pre.step()
            steps += 1
        if deng._queue:
            rid = deng._queue[0].r.rid
            assert deng.cancel(rid)
            assert deng.status(rid) == RequestStatus.CANCELLED
        deng.run_until_done()
        assert deng.audit_refcounts() == []

    def test_tpot_reported_after_finish(self, model):
        deng = DisaggEngine(model, **_KW)
        [rid] = [deng.add_request(_prompts(1, seed=10)[0],
                                  max_new_tokens=6)]
        deng.run_until_done()
        assert deng.ttft(rid) is not None
        assert deng.tpot(rid) is not None and deng.tpot(rid) >= 0.0
