"""Disaggregated prefill/decode (ISSUE 9 tentpole): prefill and decode
engines on separate mesh slices with KV-page handoff between their pools.

Correctness bar everywhere: token-identical output vs the colocated
:class:`LLMEngine` for greedy and fixed-seed sampled requests — the copied
KV pages are bit-identical to what the decode slice would have computed, so
disaggregation may change dispatch structure and latency, never tokens.

The tiny 2-layer model is module-shared (engines build compiled programs);
the cross-slice test shards it over halves of the 8-virtual-device CPU
mesh."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.serving import (DisaggEngine, LLMEngine,
                                          RequestStatus, SpecConfig,
                                          split_mesh)
from paddle_tpu.testing import FAULTS, FailNth, injected
from paddle_tpu.testing.faults import Always


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=256)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


_KW = dict(max_batch=3, max_len=64, page_size=8, page_pool=48)


def _prompts(n, seed=0, lo=4, step=5):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 128, (lo + step * i,)).astype(np.int32)
            for i in range(n)]


def _serve(eng, prompts, **req_kw):
    rids = [eng.add_request(p, **req_kw) for p in prompts]
    eng.run_until_done()
    return [eng.result(r) for r in rids]


class TestDisaggParity:
    def test_greedy_token_exact(self, model):
        prompts = _prompts(4)
        ref = _serve(LLMEngine(model, debug_refcount_audit=True, **_KW),
                     prompts, max_new_tokens=7)
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        got = _serve(deng, prompts, max_new_tokens=7)
        assert got == ref
        assert deng.handoff_stats()["handoffs"] == len(prompts)
        assert deng.audit_refcounts() == []

    def test_fixed_seed_sampling_token_exact(self, model):
        prompts = _prompts(3, seed=1)
        kw = dict(max_new_tokens=6, do_sample=True, temperature=0.8,
                  top_p=0.9, top_k=20)
        ref_eng = LLMEngine(model, **_KW)
        ref = [ref_eng.add_request(p, seed=100 + i, **kw)
               for i, p in enumerate(prompts)]
        ref_eng.run_until_done()
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, seed=100 + i, **kw)
                for i, p in enumerate(prompts)]
        deng.run_until_done()
        assert [deng.result(r) for r in rids] == \
            [ref_eng.result(r) for r in ref]

    def test_prefix_cache_on_token_exact(self, model):
        # shared 24-token prefix: the prefill slice's cache serves the
        # later prompts' full pages; tokens must not move
        rng = np.random.RandomState(2)
        base = rng.randint(1, 128, (24,)).astype(np.int32)
        prompts = [np.concatenate([base, rng.randint(1, 128, (k,))
                                   .astype(np.int32)]) for k in (3, 5, 7)]
        ref_eng = LLMEngine(model, prefix_cache=True, **_KW)
        deng = DisaggEngine(model, prefix_cache=True,
                            debug_refcount_audit=True, **_KW)
        # two waves: wave 2 reuses the pages wave 1 registered (wave 1's
        # slots all admit before any key exists, so only wave 2 can hit)
        for wave in range(2):
            ref = _serve(ref_eng, prompts, max_new_tokens=6)
            got = _serve(deng, prompts, max_new_tokens=6)
            assert got == ref, wave
        # cache hits happen on the prefill slice (that is where prompts run)
        assert deng.prefix_cache_stats()["hits"] > 0
        assert deng.audit_refcounts() == []

    def test_spec_decode_on_token_exact(self, model):
        # repetitive prompt so the n-gram proposer actually drafts
        pat = np.tile(np.arange(1, 9, dtype=np.int32), 4)
        prompts = [pat, _prompts(1, seed=3)[0]]
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=8)
        deng = DisaggEngine(model, spec_decode=SpecConfig(max_draft=4),
                            debug_refcount_audit=True, **_KW)
        got = _serve(deng, prompts, max_new_tokens=8)
        assert got == ref
        assert deng.spec_stats()["verify_dispatches"] >= 1
        assert deng.audit_refcounts() == []

    def test_single_token_requests_skip_handoff(self, model):
        # max_new_tokens=1 finishes at the prefill slice's first emit:
        # nothing to decode, nothing to hand off
        prompts = _prompts(2, seed=4)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=1)
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        got = _serve(deng, prompts, max_new_tokens=1)
        assert got == ref
        assert deng.handoff_stats()["handoffs"] == 0
        assert deng.audit_refcounts() == []


class TestDisaggMesh:
    def test_split_mesh_halves(self):
        import jax
        from jax.sharding import Mesh
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "mp"))
        pre, dec = split_mesh(mesh, axis="mp")
        assert pre.axis_names == dec.axis_names == ("pp", "mp")
        assert pre.shape["mp"] == dec.shape["mp"] == 1
        assert not (set(pre.devices.flat) & set(dec.devices.flat))
        with pytest.raises(ValueError):
            split_mesh(Mesh(np.array(jax.devices()[:1]).reshape(1, 1),
                            ("pp", "mp")))

    def test_cross_slice_handoff_token_exact(self, model):
        import jax
        from jax.sharding import Mesh
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("pp", "mp"))
        pre_mesh, dec_mesh = split_mesh(mesh, axis="mp")
        prompts = _prompts(3, seed=5)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        deng = DisaggEngine(model, prefill_mesh=pre_mesh,
                            decode_mesh=dec_mesh,
                            debug_refcount_audit=True, **_KW)
        assert deng.handoff_stats()["cross_device"]
        got = _serve(deng, prompts, max_new_tokens=6)
        assert got == ref
        assert deng.handoff_stats()["handoffs"] == len(prompts)
        assert deng.audit_refcounts() == []


class TestDisaggChaos:
    def test_transient_handoff_faults_retried(self, model):
        prompts = _prompts(3, seed=6)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, max_new_tokens=6) for p in prompts]
        with injected("serving.kv_handoff", FailNth({1, 3}),
                      transient=True):
            deng.run_until_done()
        assert [deng.result(r) for r in rids] == ref
        stats = deng.handoff_stats()
        assert stats["retries"] >= 2 and stats["failures"] == 0
        assert deng.audit_refcounts() == []

    def test_poisoned_handoff_quarantines_only_that_request(self, model):
        prompts = _prompts(4, seed=7)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        deng = DisaggEngine(model, debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, max_new_tokens=6) for p in prompts]
        poison = rids[1]
        FAULTS.install("serving.kv_handoff", Always(),
                       match=lambda ctx: poison in ctx.get("rids", ()))
        try:
            deng.run_until_done()
        finally:
            FAULTS.reset()
        assert deng.status(poison) == RequestStatus.FAILED
        assert "InjectedFault" in deng.error(poison)
        for i in (0, 2, 3):
            assert deng.status(rids[i]) == RequestStatus.FINISHED
            assert deng.result(rids[i]) == ref[i], i
        stats = deng.handoff_stats()
        assert stats["failures"] == 1
        assert stats["handoffs"] == len(prompts) - 1
        # pages released on BOTH slices for the quarantined request
        assert deng.audit_refcounts() == []


class TestDisaggBackpressure:
    def test_handoff_queue_stays_bounded(self, model):
        # depth=1 and a decode side kept full: prefill must pause (no new
        # sink appends) instead of growing the queue without bound
        deng = DisaggEngine(model, handoff_depth=1,
                            debug_refcount_audit=True, **_KW)
        for p in _prompts(6, seed=8, lo=4, step=2):
            deng.add_request(p, max_new_tokens=8)
        steps = 0
        while deng.has_work() and steps < 500:
            deng.step()
            assert len(deng._queue) <= deng.handoff_depth
            steps += 1
        assert not deng.has_work()
        assert deng.handoff_stats()["handoffs"] == 6

    def test_cancel_in_handoff_queue_releases_pages(self, model):
        deng = DisaggEngine(model, handoff_depth=4,
                            debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, max_new_tokens=6)
                for p in _prompts(2, seed=9)]
        # step until something sits in the handoff queue, then cancel it
        steps = 0
        while not deng._queue and steps < 200:
            served = deng.dec.step()
            if len(deng._queue) < deng.handoff_depth:
                served += deng.pre.step()
            steps += 1
        if deng._queue:
            rid = deng._queue[0].r.rid
            assert deng.cancel(rid)
            assert deng.status(rid) == RequestStatus.CANCELLED
        deng.run_until_done()
        assert deng.audit_refcounts() == []

    def test_tpot_reported_after_finish(self, model):
        deng = DisaggEngine(model, **_KW)
        [rid] = [deng.add_request(_prompts(1, seed=10)[0],
                                  max_new_tokens=6)]
        deng.run_until_done()
        assert deng.ttft(rid) is not None
        assert deng.tpot(rid) is not None and deng.tpot(rid) >= 0.0


class TestDisaggMN:
    """M:N pools (ISSUE 18 tentpole): any prefill fan-in, any decode
    fan-out, one shared bounded queue — tokens never move."""

    # 2:1 (prefill fan-in, the shape bursty traffic wants) stays tier-1;
    # the other pool shapes ride the CI disagg step + chaos legs, which
    # run this file unfiltered
    @pytest.mark.parametrize("m,n", [
        (2, 1),
        pytest.param(1, 2, marks=pytest.mark.slow),
        pytest.param(2, 2, marks=pytest.mark.slow)])
    def test_mn_greedy_token_exact(self, model, m, n):
        prompts = _prompts(6, seed=11)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=7)
        deng = DisaggEngine(model, n_prefill=m, n_decode=n,
                            debug_refcount_audit=True, **_KW)
        got = _serve(deng, prompts, max_new_tokens=7)
        assert got == ref
        stats = deng.handoff_stats()
        assert stats["handoffs"] == len(prompts)
        assert stats["n_prefill"] == m and stats["n_decode"] == n
        assert deng.audit_refcounts() == []

    @pytest.mark.slow
    def test_mn_fixed_seed_sampling_token_exact(self, model):
        prompts = _prompts(4, seed=12)
        kw = dict(max_new_tokens=6, do_sample=True, temperature=0.8,
                  top_p=0.9, top_k=20)
        ref_eng = LLMEngine(model, **_KW)
        ref = [ref_eng.add_request(p, seed=200 + i, **kw)
               for i, p in enumerate(prompts)]
        ref_eng.run_until_done()
        deng = DisaggEngine(model, n_prefill=2, n_decode=2,
                            debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, seed=200 + i, **kw)
                for i, p in enumerate(prompts)]
        deng.run_until_done()
        assert [deng.result(r) for r in rids] == \
            [ref_eng.result(r) for r in ref]
        assert deng.audit_refcounts() == []

    @pytest.mark.slow
    def test_mn_prefix_cache_token_exact(self, model):
        # shared prefix across TWO prefill engines: each engine's own LRU
        # serves whatever re-lands on it; tokens must not move either way
        rng = np.random.RandomState(13)
        base = rng.randint(1, 128, (24,)).astype(np.int32)
        prompts = [np.concatenate([base, rng.randint(1, 128, (k,))
                                   .astype(np.int32)]) for k in (3, 5, 7)]
        ref_eng = LLMEngine(model, prefix_cache=True, **_KW)
        deng = DisaggEngine(model, n_prefill=2, n_decode=1,
                            prefix_cache=True,
                            debug_refcount_audit=True, **_KW)
        for wave in range(2):
            ref = _serve(ref_eng, prompts, max_new_tokens=6)
            got = _serve(deng, prompts, max_new_tokens=6)
            assert got == ref, wave
        assert deng.audit_refcounts() == []

    def test_least_loaded_decode_placement_spreads(self, model):
        # 1 prefill feeding 2 decodes: placement is least-loaded, so with
        # six concurrent requests both decode engines must end up serving
        deng = DisaggEngine(model, n_prefill=1, n_decode=2,
                            debug_refcount_audit=True, **_KW)
        _serve(deng, _prompts(6, seed=14), max_new_tokens=6)
        per_engine = [len(de.sched.finished) for de in deng.decodes]
        assert sum(per_engine) == 6
        assert all(c > 0 for c in per_engine), per_engine

    def test_mn_cancel_and_queue_paths(self, model):
        # O(1) cancel: queued handoffs index by rid; cancel mid-flight
        # releases through the one shared path and the audit stays clean
        deng = DisaggEngine(model, n_prefill=2, n_decode=1,
                            handoff_depth=4,
                            debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, max_new_tokens=6)
                for p in _prompts(4, seed=15)]
        steps = 0
        while not deng._queued and steps < 300:
            deng.step()
            steps += 1
        if deng._queued:
            rid = next(iter(deng._queued))
            assert deng.cancel(rid)
            assert deng.status(rid) == RequestStatus.CANCELLED
        deng.run_until_done()
        assert deng.audit_refcounts() == []
        for rid in rids:
            assert deng.status(rid).terminal


class TestSplitMeshSizes:
    """split_mesh beyond even halves: uneven and N-way partitions size the
    slices of an M:N pool; impossible requests fail with pointed errors."""

    def _mesh(self, n, shape, names=("pp", "mp")):
        import jax
        from jax.sharding import Mesh
        if len(jax.devices()) < n:
            pytest.skip(f"needs {n} virtual devices")
        return Mesh(np.array(jax.devices()[:n]).reshape(shape), names)

    def test_uneven_split(self):
        mesh = self._mesh(4, (1, 4))
        big, small = split_mesh(mesh, axis="mp", sizes=(3, 1))
        assert big.shape["mp"] == 3 and small.shape["mp"] == 1
        assert big.axis_names == small.axis_names == ("pp", "mp")
        assert not (set(big.devices.flat) & set(small.devices.flat))

    def test_three_way_split(self):
        mesh = self._mesh(4, (1, 4))
        a, b, c = split_mesh(mesh, axis="mp", sizes=(1, 1, 2))
        assert [s.shape["mp"] for s in (a, b, c)] == [1, 1, 2]
        all_devs = (set(a.devices.flat) | set(b.devices.flat)
                    | set(c.devices.flat))
        assert all_devs == set(mesh.devices.flat)

    def test_sizes_infer_axis(self):
        # no axis given: the unique axis whose size matches sum(sizes)
        mesh = self._mesh(4, (1, 4))
        a, b = split_mesh(mesh, sizes=(2, 2))
        assert a.shape["mp"] == b.shape["mp"] == 2

    def test_pointed_errors(self):
        mesh = self._mesh(4, (1, 4))
        with pytest.raises(ValueError, match="no axis 'xx'"):
            split_mesh(mesh, axis="xx", sizes=(2, 2))
        with pytest.raises(ValueError, match="partition the axis exactly"):
            split_mesh(mesh, axis="mp", sizes=(3, 2))
        with pytest.raises(ValueError, match="positive"):
            split_mesh(mesh, axis="mp", sizes=(5, -1))
        with pytest.raises(ValueError, match="no mesh axis of size 5"):
            split_mesh(mesh, sizes=(4, 1))

    def test_odd_axis_without_sizes_points_at_sizes(self):
        mesh = self._mesh(3, (1, 3))
        with pytest.raises(ValueError, match="sizes="):
            split_mesh(mesh, axis="mp")

    @pytest.mark.slow
    def test_mn_engines_on_split_slices_token_exact(self, model):
        # 2 prefill + 1 decode engines pinned to a 3-way uneven split:
        # every handoff crosses device sets; tokens must not move
        import jax
        if len(jax.devices()) < 4:
            pytest.skip("needs 4 virtual devices")
        mesh = self._mesh(4, (1, 4))
        p0, p1, d0 = split_mesh(mesh, axis="mp", sizes=(1, 1, 2))
        prompts = _prompts(4, seed=16)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        deng = DisaggEngine(model, prefill_meshes=[p0, p1],
                            decode_meshes=[d0],
                            debug_refcount_audit=True, **_KW)
        assert deng.handoff_stats()["cross_device"]
        got = _serve(deng, prompts, max_new_tokens=6)
        assert got == ref
        assert deng.audit_refcounts() == []


class TestAsyncHandoff:
    """The pipelined transfer (dispatch gather/device_put for handoff k+1
    while decode step k runs) must change latency structure only — and
    prove the overlap in handoff_stats()."""

    def test_async_vs_sync_token_exact(self, model):
        prompts = _prompts(5, seed=17)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=7)
        sync_eng = DisaggEngine(model, async_handoff=False,
                                debug_refcount_audit=True, **_KW)
        async_eng = DisaggEngine(model, async_handoff=True,
                                 debug_refcount_audit=True, **_KW)
        assert _serve(sync_eng, prompts, max_new_tokens=7) == ref
        assert _serve(async_eng, prompts, max_new_tokens=7) == ref
        s_sync, s_async = sync_eng.handoff_stats(), async_eng.handoff_stats()
        assert not s_sync["async"] and s_async["async"]
        # sync's blocking hop cannot overlap anything by construction
        assert s_sync["transfer_overlap_s"] == 0.0
        # async staged every handoff before a decode step ran past it, so
        # in-flight time accumulated under decode compute
        assert s_async["transfer_overlap_s"] > 0.0
        assert s_async["handoffs"] == s_sync["handoffs"] == len(prompts)
        for s in (s_sync, s_async):
            assert s["queue_wait_s"] >= 0.0 and s["transfer_s"] > 0.0

    def test_async_registry_series_mirror_stats(self, model):
        from paddle_tpu import observability as obs
        obs.enable()
        try:
            deng = DisaggEngine(model, **_KW)
            _serve(deng, _prompts(3, seed=18), max_new_tokens=6)
            label = deng._pm.label
            snap = obs.REGISTRY.snapshot(
                prefix="serving_handoff", labels={"pool": label})

            def series(name, **extra):
                return next(
                    s for s in snap[name]["series"]
                    if all(s["labels"].get(k) == v
                           for k, v in extra.items()))

            wait = series("serving_handoff_wait_seconds", path="local")
            xfer = series("serving_handoff_transfer_seconds", path="local")
            assert wait["count"] == xfer["count"] == 3
            depth = series("serving_handoff_queue_depth")
            assert depth["value"] == 0  # drained
        finally:
            obs.disable()


class TestCrossHostHandoff:
    """Prefill in another worker process (thread-hosted here, as the fleet
    tests do): the pool pulls serialized page blocks over the worker RPC
    plane and lands them through the same queue → stage → scatter path."""

    @pytest.fixture()
    def worker(self, model):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.inference.frontend.worker import WorkerServer
        master = TCPStore(is_master=True, timeout=20)
        w = WorkerServer("pf0", LLMEngine(model, **_KW),
                         TCPStore(port=master.port, timeout=20),
                         group="disagg-xh", ttl=60.0, role="prefill")
        w.start(heartbeat=False)
        yield w
        w.close(drain=False)

    def _tier(self, w):
        from paddle_tpu.inference.frontend.disagg import RemotePrefillTier
        return RemotePrefillTier(w.rpc.host, w.rpc.port, name=w.name)

    def test_cross_host_greedy_token_exact(self, model, worker):
        prompts = _prompts(4, seed=19)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=7)
        tier = self._tier(worker)
        try:
            deng = DisaggEngine(model, n_prefill=0, remote_prefill=[tier],
                                debug_refcount_audit=True, **_KW)
            got = _serve(deng, prompts, max_new_tokens=7)
            assert got == ref
            stats = deng.handoff_stats()
            assert stats["handoffs"] == len(prompts)
            assert stats["cross_device"]
            # combined dual-pool audit: decode pool here, prefill pool
            # over RPC (remote[0] prefix on any problem)
            assert deng.audit_refcounts() == []
        finally:
            tier.close()

    @pytest.mark.slow
    def test_cross_host_fixed_seed_token_exact(self, model, worker):
        prompts = _prompts(3, seed=20)
        kw = dict(max_new_tokens=6, do_sample=True, temperature=0.8,
                  top_p=0.9, top_k=20)
        ref_eng = LLMEngine(model, **_KW)
        ref = [ref_eng.add_request(p, seed=300 + i, **kw)
               for i, p in enumerate(prompts)]
        ref_eng.run_until_done()
        tier = self._tier(worker)
        try:
            deng = DisaggEngine(model, n_prefill=0, remote_prefill=[tier],
                                debug_refcount_audit=True, **_KW)
            rids = [deng.add_request(p, seed=300 + i, **kw)
                    for i, p in enumerate(prompts)]
            deng.run_until_done()
            assert [deng.result(r) for r in rids] == \
                [ref_eng.result(r) for r in ref]
            assert deng.audit_refcounts() == []
        finally:
            tier.close()

    @pytest.mark.slow
    def test_cross_host_transient_fault_lossless(self, model, worker):
        prompts = _prompts(3, seed=21)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        tier = self._tier(worker)
        try:
            deng = DisaggEngine(model, n_prefill=0, remote_prefill=[tier],
                                debug_refcount_audit=True, **_KW)
            rids = [deng.add_request(p, max_new_tokens=6) for p in prompts]
            with injected("serving.kv_handoff", FailNth({1, 3}),
                          transient=True):
                deng.run_until_done()
            assert [deng.result(r) for r in rids] == ref
            stats = deng.handoff_stats()
            assert stats["retries"] >= 2 and stats["failures"] == 0
            assert deng.audit_refcounts() == []
        finally:
            tier.close()

    @pytest.mark.slow
    def test_cross_host_poison_quarantines_one(self, model, worker):
        prompts = _prompts(4, seed=22)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        tier = self._tier(worker)
        try:
            deng = DisaggEngine(model, n_prefill=0, remote_prefill=[tier],
                                debug_refcount_audit=True, **_KW)
            rids = [deng.add_request(p, max_new_tokens=6) for p in prompts]
            poison = rids[1]
            FAULTS.install(
                "serving.kv_handoff", Always(),
                match=lambda ctx: (poison in ctx.get("rids", ())
                                   and ctx.get("path") == "cross_host"))
            try:
                deng.run_until_done()
            finally:
                FAULTS.reset()
            assert deng.status(poison) == RequestStatus.FAILED
            assert "InjectedFault" in deng.error(poison)
            for i in (0, 2, 3):
                assert deng.status(rids[i]) == RequestStatus.FINISHED
                assert deng.result(rids[i]) == ref[i], i
            stats = deng.handoff_stats()
            assert stats["failures"] == 1
            assert stats["handoffs"] == len(prompts) - 1
            # the worker dropped the poisoned block and released its pages;
            # the pool never allocated destination pages for it
            assert deng.audit_refcounts() == []
        finally:
            tier.close()

    @pytest.mark.slow
    def test_seeded_kv_handoff_chaos_converges(self, model):
        """FailProb kv_handoff chaos under the CI seed matrix: every
        transient hit retries losslessly and every request still matches
        the fault-free tokens, whatever PADDLE_TPU_FAULT_SEED says."""
        import os
        from paddle_tpu.testing.faults import FailProb
        fault_seed = int(os.environ.get("PADDLE_TPU_FAULT_SEED", "11"))
        prompts = _prompts(4, seed=23)
        ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=6)
        deng = DisaggEngine(model, n_prefill=2, n_decode=2,
                            debug_refcount_audit=True, **_KW)
        rids = [deng.add_request(p, max_new_tokens=6) for p in prompts]
        with injected("serving.kv_handoff",
                      FailProb(0.3, seed=fault_seed), transient=True):
            deng.run_until_done()
        assert [deng.result(r) for r in rids] == ref
        assert deng.handoff_stats()["failures"] == 0
        assert deng.audit_refcounts() == []

    @pytest.mark.slow
    def test_fleet_routes_prefill_role_to_tier(self, model, worker):
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.inference.frontend.fleet import FleetReplicaSet
        store_port = worker.membership.store.port
        fleet = FleetReplicaSet(TCPStore(port=store_port, timeout=20),
                                group="disagg-xh", ttl=60.0)
        try:
            fleet.sync()
            # a prefill-role member becomes a tier, never a serving replica
            assert list(fleet.prefill_tiers) == ["pf0"]
            assert fleet.replicas == []
            tier = fleet.prefill_tiers["pf0"]
            prompts = _prompts(2, seed=24)
            ref = _serve(LLMEngine(model, **_KW), prompts, max_new_tokens=5)
            deng = DisaggEngine(model, n_prefill=0, remote_prefill=[tier],
                                debug_refcount_audit=True, **_KW)
            assert _serve(deng, prompts, max_new_tokens=5) == ref
            assert deng.audit_refcounts() == []
        finally:
            fleet.close()


class TestRpcOutOfBand:
    """Protocol-5 out-of-band framing: numpy page blocks ride the wire as
    raw buffers, the in-band pickle stays structural — asserted in bytes,
    and existing small ops are unchanged (zero out-of-band buffers)."""

    def test_page_block_bytes_stay_out_of_band(self):
        import pickle
        from paddle_tpu.inference.frontend.rpc import _encode_frame
        block = tuple(np.random.RandomState(0)
                      .randn(2, 64, 16, 4, 32).astype(np.float32)
                      for _ in range(2))
        payload = {"req": None, "block": block, "n_tokens": 30}
        inband, bufs = _encode_frame(("handoff_pull_reply", payload))
        total = sum(b.nbytes for b in block)
        assert sum(b.nbytes for b in bufs) == total
        # the micro-benchmark: in-band bytes are structure, not data —
        # orders of magnitude below a flat protocol-4-style pickle
        flat = len(pickle.dumps(("handoff_pull_reply", payload), protocol=4))
        assert len(inband) < 2048
        assert len(inband) * 100 < flat

    def test_small_ops_have_no_oob_buffers(self):
        from paddle_tpu.inference.frontend.rpc import _encode_frame
        inband, bufs = _encode_frame(("submit", {
            "prompt_ids": list(range(64)), "max_new_tokens": 8}))
        assert bufs == []

    def test_round_trip_preserves_arrays(self):
        from paddle_tpu.inference.frontend.rpc import RpcClient, RpcServer
        blocks = {}

        def handler(op, kw):
            if op == "put":
                blocks[kw["key"]] = kw["block"]
                return True
            return blocks[kw["key"]]

        srv = RpcServer(handler).start()
        cli = RpcClient(srv.host, srv.port)
        try:
            a = np.arange(1 << 16, dtype=np.float32).reshape(4, -1)
            assert cli.call("put", key="k", block=(a, a * 2))
            b0, b1 = cli.call("get", key="k")
            np.testing.assert_array_equal(b0, a)
            np.testing.assert_array_equal(b1, a * 2)
        finally:
            cli.close()
            srv.close()
