"""graftlint (paddle_tpu.analysis) — per-pass fixture tests + repo self-check.

Each pass gets a known-bad fixture (seeded violations it must catch) and a
known-clean fixture (idioms it must NOT flag).  The repo self-check at the
bottom is the tier-1 CI gate: the analyzer must exit clean on the tree.
"""
import importlib
import json
import pathlib
import textwrap

import pytest

from paddle_tpu.analysis import PASSES, run
from paddle_tpu.analysis import cli
from paddle_tpu.analysis.baseline import Baseline
from paddle_tpu.analysis.cache import FileCache
from paddle_tpu.analysis.framework import Finding, SourceFile

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = pathlib.Path(__file__).resolve().parent / "graftlint_fixtures"


def _lint(tmp_path, source, select=None, name="fixture.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    return run([str(p)], select=select)


def _codes(result):
    return {f.code for f in result.findings}


# ---------------------------------------------------------------- trace-safety

TS_BAD = """
    import jax
    import numpy as np

    _STEP = 0

    @jax.jit
    def bad(x, y):
        global _STEP
        if x > 0:                  # TS101: data-dependent branch
            y = y + 1
        v = float(x)               # TS102: host escape builtin
        h = y.numpy()              # TS103: host escape method
        w = np.tanh(x)             # TS104: numpy on a tracer
        _STEP = _STEP + 1          # TS105: trace-time side effect
        return helper(y) + v + h + w

    def helper(z):
        while z.sum() > 0:         # TS101 via interprocedural taint
            z = z - 1
        return z
"""

TS_CLEAN = """
    import jax
    import numpy as np

    TABLE = np.arange(8)           # numpy on host constants is fine

    @jax.jit
    def clean(x, mask=None):
        if mask is None:           # identity compare is static
            mask = x * 0
        if len(x.shape) == 2:      # shape metadata is host-known
            x = x + 1
        for dim in range(x.ndim):  # ndim is static
            x = x * 1
        vals = [x, x + 1]
        out = 0
        for v, keep in zip(vals, [True, False]):   # static mask: no taint
            if keep:
                out = out + v
        return out
"""


def test_trace_safety_catches_seeded_violations(tmp_path):
    res = _lint(tmp_path, TS_BAD, select=["trace-safety"])
    assert {"TS101", "TS102", "TS103", "TS104", "TS105"} <= _codes(res)
    # the interprocedural edge reaches helper()'s while loop
    lines = {f.line for f in res.findings if f.code == "TS101"}
    assert len(lines) >= 2


def test_trace_safety_clean_idioms_not_flagged(tmp_path):
    res = _lint(tmp_path, TS_CLEAN, select=["trace-safety"])
    assert res.findings == []


def test_trace_safety_respects_static_argnames(tmp_path):
    src = """
        import jax
        from functools import partial

        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode):
            if mode == "train":    # static arg: host branch is fine
                return x * 2
            return x
    """
    res = _lint(tmp_path, src, select=["trace-safety"])
    assert res.findings == []


def test_trace_safety_every_finding_has_hint(tmp_path):
    res = _lint(tmp_path, TS_BAD, select=["trace-safety"])
    assert res.findings and all(f.hint for f in res.findings)


# ------------------------------------------------------------- registry-parity

RP_STATIC_BAD = """
    REGISTRY = {}

    def u(name, ref, cat="math", **kw):
        REGISTRY[name] = (ref, cat, kw)

    u("tanh", None)                 # RP003: golden without np_ref/check
    u("tanh", abs)                  # RP001: duplicate registration
    u("warp", abs, cat="astral")    # RP002: unknown category
"""

RP_RUNTIME_PKG = """
    REGISTRY = {}
    CATEGORIES = frozenset({"math"})
    DUPLICATE_REGISTRATIONS = []

    class OpSpec:
        def __init__(self, name, op, np_ref=None, sample=None, kwargs=(),
                     kind="golden", category="math", check=None,
                     alias_of=None):
            self.name, self.op, self.np_ref = name, op, np_ref
            self.sample, self.kwargs, self.kind = sample, kwargs, kind
            self.category, self.check, self.alias_of = category, check, alias_of

        def resolve(self):
            if self.op is None:
                raise AttributeError(f"no resolver for {self.name}")
            return self.op

    def _one(x):
        return x

    def u(name, ref, cat="math", **kw):
        REGISTRY[name] = OpSpec(name, kw.pop("op", None), np_ref=ref,
                                category=cat, **kw)

    u("good", abs, op=_one, sample=lambda: [1.0])
    u("two_into_one", abs, op=_one, sample=lambda: [1.0, 2.0])  # RP007
    u("ghost", abs, op=None, sample=lambda: [1.0])              # RP006
"""


def test_registry_parity_static_checks(tmp_path):
    res = _lint(tmp_path, RP_STATIC_BAD, select=["registry-parity"])
    assert {"RP001", "RP002", "RP003"} <= _codes(res)


def test_registry_parity_runtime_checks(tmp_path, monkeypatch):
    pkg = tmp_path / "graftlint_fixture_pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "registry.py").write_text(textwrap.dedent(RP_RUNTIME_PKG))
    monkeypatch.syspath_prepend(str(tmp_path))
    importlib.invalidate_caches()
    res = run([str(pkg)], select=["registry-parity"])
    codes = _codes(res)
    assert "RP007" in codes     # resolver arity vs sample builder
    assert "RP006" in codes     # missing resolver
    flagged = {f.message.split("'")[1] for f in res.findings}
    assert "good" not in flagged


def test_registry_parity_clean_on_non_registry_files(tmp_path):
    res = _lint(tmp_path, "def u(x):\n    return x\nu(3)\n",
                select=["registry-parity"])
    assert res.findings == []


# ------------------------------------------------------------ namespace-parity

NS_BAD = """
    __all__ = ["real", "ghost", "real"]    # NS001 ghost, NS002 dup

    def real():
        return 1
"""

NS_CLEAN = """
    import os as _os

    __all__ = ["real", "CONST", "_os"]

    CONST = 3

    def real():
        return 1
"""


def test_namespace_parity_catches_stale_and_duplicate(tmp_path):
    res = _lint(tmp_path, NS_BAD, select=["namespace-parity"])
    assert _codes(res) == {"NS001", "NS002"}
    msgs = " ".join(f.message for f in res.findings)
    assert "ghost" in msgs


def test_namespace_parity_clean(tmp_path):
    res = _lint(tmp_path, NS_CLEAN, select=["namespace-parity"])
    assert res.findings == []


def test_namespace_parity_skips_star_import_files(tmp_path):
    src = """
        from os.path import *

        __all__ = ["join", "whatever"]
    """
    res = _lint(tmp_path, src, select=["namespace-parity"])
    assert not any(f.code == "NS001" for f in res.findings)


# ----------------------------------------------------------- jit-cache-hygiene

JH_BAD = """
    import jax
    import jax.numpy as jnp
    from functools import partial

    @jax.jit
    def f(x, scale=jnp.ones(3), opts=[1, 2]):   # JH002, JH001
        return x * scale

    @partial(jax.jit, static_argnames=("cfg",))
    def g(x, cfg={"a": 1}):                      # JH004
        return x

    def caller(x):
        return g(x, cfg={"b": 2})                # JH003
"""

JH_CLEAN = """
    import jax

    @jax.jit
    def f(x, scale=None, shape=(3, 3)):          # None/tuple defaults hash fine
        return x

    def plain(x, opts=[1]):                      # not a jit entry: no finding
        return x
"""


def test_jit_cache_hygiene_catches_seeded_violations(tmp_path):
    res = _lint(tmp_path, JH_BAD, select=["jit-cache-hygiene"])
    assert _codes(res) == {"JH001", "JH002", "JH003", "JH004"}


def test_jit_cache_hygiene_clean(tmp_path):
    res = _lint(tmp_path, JH_CLEAN, select=["jit-cache-hygiene"])
    assert res.findings == []


# ---------------------------------------------------------- no-adhoc-telemetry

AT_BAD = """
    import time
    from time import time as walltime


    def work():
        t0 = time.time()
        print("starting work")
        elapsed = time.time() - t0
        return elapsed + walltime()
"""

AT_CLEAN = """
    import logging
    import time

    logger = logging.getLogger(__name__)


    def work(timer=None):
        t0 = time.perf_counter()
        logger.info("starting work")
        deadline = time.monotonic() + 5.0
        timer.time()          # method named `time` on another object: fine
        return time.perf_counter() - t0, deadline
"""


def test_no_adhoc_telemetry_catches_seeded_violations(tmp_path):
    res = _lint(tmp_path, AT_BAD, select=["no-adhoc-telemetry"])
    assert _codes(res) == {"AT101", "AT102"}
    # three wall-clock reads: two time.time() plus the renamed from-import
    assert sum(f.code == "AT102" for f in res.findings) == 3
    assert sum(f.code == "AT101" for f in res.findings) == 1


def test_no_adhoc_telemetry_clean_idioms_not_flagged(tmp_path):
    res = _lint(tmp_path, AT_CLEAN, select=["no-adhoc-telemetry"])
    assert res.findings == []


AT103_BAD = """
    class Tier:
        def submit(self, prompt):
            return self.client.call("submit", prompt_ids=prompt)

    def pull(rpc, rid):
        return rpc.call("handoff_pull", rid=rid)

    def scrape(metrics_client, deadline):
        return metrics_client.call("metrics_snapshot", deadline=deadline)
"""

AT103_CLEAN = """
    def traced(self, prompt, ctx):
        return self.client.call("submit", ctx=ctx, prompt_ids=prompt)

    def control_plane(self):
        return self.client.call("ping", ctx=None)   # explicit: untraced

    def not_rpc(self):
        return self._exported.call(self._params)    # jit export, not RPC

    def also_not_rpc(callback):
        return callback.call()                       # no client-ish name
"""


def test_no_adhoc_telemetry_at103_ctx_dropped(tmp_path):
    res = _lint(tmp_path, AT103_BAD, select=["no-adhoc-telemetry"])
    assert _codes(res) == {"AT103"}
    # all three client-like receivers: self.client, bare rpc, *_client
    assert len(res.findings) == 3
    assert all("trace context" in f.message for f in res.findings)


def test_no_adhoc_telemetry_at103_clean_idioms(tmp_path):
    res = _lint(tmp_path, AT103_CLEAN, select=["no-adhoc-telemetry"])
    assert res.findings == []


def test_no_adhoc_telemetry_line_pragma(tmp_path):
    src = """
        import time


        def show():
            print("hi")  # graftlint: disable=no-adhoc-telemetry
            return time.time()  # graftlint: disable=no-adhoc-telemetry
    """
    res = _lint(tmp_path, src, select=["no-adhoc-telemetry"])
    assert res.findings == [] and res.suppressed == 2


# ----------------------------------------------- sharding-spec-coverage

def _sharding(paths):
    return run([str(p) for p in paths], select=["sharding-spec-coverage"])


def test_sharding_spec_catches_seeded_violations():
    res = _sharding([FIXTURES / "sharding_bad.py"])
    assert _codes(res) == {"SS101", "SS102", "SS103", "SS104", "SS105",
                           "SS106"}
    by_code = {}
    for f in res.findings:
        by_code.setdefault(f.code, []).append(f)
    assert "2 positional argument(s)" in by_code["SS101"][0].message
    assert "'ep'" in by_code["SS102"][0].message
    assert "'sep'" in by_code["SS103"][0].message
    assert by_code["SS104"][0].severity == "warning"    # divergence risk
    assert "3-tuple" in by_code["SS105"][0].message
    # SS106 fires at BOTH spec-vs-mesh sites: the NamedSharding ctor and
    # the bare PartitionSpec inside jit's in_shardings keyword
    ss106 = " | ".join(f.message for f in by_code["SS106"])
    assert "'tp'" in ss106 and "'fsdp'" in ss106
    assert any("in_shardings" in f.message for f in by_code["SS106"])
    assert all(f.severity == "error" for f in res.findings
               if f.code != "SS104")
    assert all(f.hint for f in res.findings)


def test_sharding_spec_clean_fixture_not_flagged():
    res = _sharding([FIXTURES / "sharding_clean.py"])
    assert res.findings == []


def test_sharding_spec_resolves_body_across_files():
    res = _sharding([FIXTURES / "sharding_xfile_def.py",
                     FIXTURES / "sharding_xfile_use.py"])
    assert _codes(res) == {"SS101"}
    (f,) = res.findings
    assert f.path.endswith("sharding_xfile_use.py")
    assert "3 positional argument(s)" in f.message


def test_jit_shardings_use_mesh_spelling(tmp_path):
    src = """
        import jax
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(jax.devices(), ("dp",))

        def f(fn, x):
            with jax.sharding.use_mesh(mesh):
                g = jax.jit(fn, out_shardings=P("mp"))
                return g(x)
    """
    res = _lint(tmp_path, src, select=["sharding-spec-coverage"])
    assert _codes(res) == {"SS106"}
    (f,) = res.findings
    assert "'mp'" in f.message and "out_shardings" in f.message


# --------------------------------------------------------------- robustness

def test_robustness_flags_swallowed_exceptions():
    res = run([str(FIXTURES / "robustness_bad.py")], select=["robustness"])
    assert _codes(res) == {"RB101", "RB102", "RB104"}
    by_code = {}
    for f in res.findings:
        by_code.setdefault(f.code, []).append(f)
    assert len(by_code["RB101"]) == 5
    assert len(by_code["RB102"]) == 4        # continue, break, return, None
    assert len(by_code["RB104"]) == 2        # while retry, for retry
    assert all(f.severity == "warning" for f in res.findings)
    msgs = " | ".join(f.message for f in res.findings)
    assert "bare except" in msgs and "except BaseException" in msgs
    rb102 = " | ".join(f.message for f in by_code["RB102"])
    assert "continue" in rb102 and "break" in rb102 and "return" in rb102
    rb104 = " | ".join(f.message for f in by_code["RB104"])
    assert "while retry loop" in rb104 and "for retry loop" in rb104
    assert all("RetryPolicy" in f.message for f in by_code["RB104"])
    assert all(f.hint for f in res.findings)


def test_robustness_clean_fixture_not_flagged():
    res = run([str(FIXTURES / "robustness_clean.py")], select=["robustness"])
    assert res.findings == []
    assert res.suppressed == 2          # pragma'd swallow + pragma'd retry


def test_robustness_rb104_wait_loop_vs_retry_loop(tmp_path):
    # the discriminator is an attempt under try/except in the SAME loop:
    # a sleeping poll loop is waiting, not retrying
    src = """
        import time

        def poll(ready):
            while not ready():
                time.sleep(0.1)

        def reconnect(connect):
            while True:
                try:
                    return connect()
                except OSError:
                    time.sleep(0.1)
    """
    res = _lint(tmp_path, src, select=["robustness"])
    assert _codes(res) == {"RB104"}
    (f,) = res.findings
    assert "time.sleep" in f.message and "core.retry" in f.message


def test_robustness_rb104_ignores_injected_sleep(tmp_path):
    # core.retry's own loop sleeps through an injectable callable — only
    # the literal time.sleep spelling is a policy bypass
    src = """
        def retry(fn, sleep, delays):
            for d in delays:
                try:
                    return fn()
                except OSError:
                    sleep(d)
            return fn()
    """
    res = _lint(tmp_path, src, select=["robustness"])
    assert res.findings == []


def test_robustness_rb105_flags_torn_writes_in_persistence_modules():
    res = run([str(FIXTURES / "persistence_bad.py")], select=["robustness"])
    assert _codes(res) == {"RB105"}
    assert len(res.findings) == 4            # w, wb, mode="w", marker
    assert all(f.severity == "warning" for f in res.findings)
    assert all("os.replace" in f.hint for f in res.findings)
    modes = " | ".join(f.message for f in res.findings)
    assert "'w'" in modes and "'wb'" in modes


def test_robustness_rb105_clean_fixtures_not_flagged():
    # tmp-staged / append / read / dynamic-mode writes inside a qualifying
    # module, and ANY write inside a module with no os.replace/os.fsync
    for name in ("persistence_clean.py", "persistence_clean_nodisc.py"):
        res = run([str(FIXTURES / name)], select=["robustness"])
        assert res.findings == [], name


def test_robustness_rb105_journal_compaction_is_clean():
    # the request journal IS the in-tree model of the idiom RB105 enforces:
    # its own truncating writes are all tmp-staged or append-mode
    res = run([str(REPO / "paddle_tpu" / "inference" / "frontend"
                   / "journal.py")], select=["robustness"])
    assert not [f for f in res.findings if f.code == "RB105"]


def test_sharding_spec_repo_parallel_tree_is_clean():
    res = _sharding([REPO / "paddle_tpu" / "parallel",
                     REPO / "paddle_tpu" / "distributed"])
    assert res.findings == [], "\n" + "\n".join(
        f.render() for f in res.findings)


def test_sharding_spec_skips_dynamic_specs(tmp_path):
    # non-literal specs / meshes must be skipped, never guessed
    src = """
        from jax.experimental.shard_map import shard_map

        def apply(fn, mesh, in_specs, out_specs, x):
            f = shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)
            return f(x)
    """
    res = _lint(tmp_path, src, select=["sharding-spec-coverage"])
    assert res.findings == []


def test_named_sharding_axis_checked_outside_shard_map(tmp_path):
    # SS106 fires at bare NamedSharding construction sites too (device_put,
    # jit sharding args, ...), not only under with_sharding_constraint
    src = """
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(jax.devices(), ("dp",))

        def place(x):
            return jax.device_put(x, NamedSharding(mesh, P("model")))
    """
    res = _lint(tmp_path, src, select=["sharding-spec-coverage"])
    assert _codes(res) == {"SS106"}
    (f,) = res.findings
    assert "'model'" in f.message and "(dp)" in f.message


# --------------------------------------------------------------- dtype-rules

def test_dtype_rules_catches_seeded_violations(monkeypatch):
    monkeypatch.syspath_prepend(str(FIXTURES))
    importlib.invalidate_caches()
    res = run([str(FIXTURES / "dtype_bad_pkg")], select=["dtype-rules"])
    codes = _codes(res)
    assert codes == {"DT101", "DT102", "DT103"}
    flagged = {f.message.split("'")[1] for f in res.findings}
    assert flagged == {"bad_index", "bad_sample", "bad_grad", "f64_golden"}
    by_op = {f.message.split("'")[1]: f for f in res.findings}
    assert by_op["bad_index"].severity == "error"
    assert by_op["f64_golden"].severity == "warning"
    # findings land on the registration line of the offending op
    assert by_op["bad_index"].line != by_op["bad_grad"].line


def test_dtype_rules_warning_not_in_errors(monkeypatch):
    monkeypatch.syspath_prepend(str(FIXTURES))
    importlib.invalidate_caches()
    res = run([str(FIXTURES / "dtype_bad_pkg")], select=["dtype-rules"])
    assert all(f.code != "DT102" for f in res.errors())
    assert any(f.code == "DT102" for f in res.findings)


def test_dtype_rules_skips_non_registry_files(tmp_path):
    res = _lint(tmp_path, "import numpy as np\nx = np.array([1])\n",
                select=["dtype-rules"])
    assert res.findings == []


# ----------------------------------------------------------- concurrency

def _cc(paths):
    return run([str(p) for p in paths], select=["concurrency"])


def test_concurrency_cc101_bad_fixture():
    res = _cc([FIXTURES / "concurrency_cc101_bad.py"])
    assert _codes(res) == {"CC101"}
    # one finding per (attr, method): the naked read AND the naked write
    assert len(res.findings) == 2
    assert all(f.severity == "warning" for f in res.findings)
    assert any("read with no lock held in read()" in f.message
               for f in res.findings)


def test_concurrency_cc101_clean_fixture():
    # the clean fixture routes writes through a caller-holds-the-lock
    # helper: inherited lock context must keep it silent
    res = _cc([FIXTURES / "concurrency_cc101_clean.py"])
    assert res.findings == []


def test_concurrency_cc102_bad_fixture():
    res = _cc([FIXTURES / "concurrency_cc102_bad.py"])
    assert _codes(res) == {"CC102"}
    msgs = "\n".join(f.message for f in res.findings)
    assert "time.sleep()" in msgs
    assert "injectable sleep" in msgs          # self.sleep = sleep param
    assert "which does os.fsync()" in msgs     # one call-hop into _sync()


def test_concurrency_cc102_clean_fixture():
    res = _cc([FIXTURES / "concurrency_cc102_clean.py"])
    assert res.findings == []


def test_concurrency_cc103_bad_fixture():
    res = _cc([FIXTURES / "concurrency_cc103_bad.py"])
    assert _codes(res) == {"CC103"}
    assert all(f.severity == "error" for f in res.findings)
    msgs = "\n".join(f.message for f in res.findings)
    assert "not inside a while loop" in msgs
    assert "notify_all() in put() outside" in msgs


def test_concurrency_cc103_clean_fixture():
    # while-predicate waits, notify under the cv, and a wait_for lambda
    # predicate (which runs WITH the lock held — no CC101 either)
    res = _cc([FIXTURES / "concurrency_cc103_clean.py"])
    assert res.findings == []


def test_concurrency_cc104_bad_fixture():
    res = _cc([FIXTURES / "concurrency_cc104_bad.py"])
    assert _codes(res) == {"CC104"}
    (f,) = res.findings
    assert f.severity == "error"
    # both sites cited by method name (messages stay line-free so the
    # baseline fingerprint survives reformatting)
    assert "transfer()" in f.message and "reconcile()" in f.message
    assert "lock-order inversion" in f.message


def test_concurrency_cc104_clean_fixture():
    res = _cc([FIXTURES / "concurrency_cc104_clean.py"])
    assert res.findings == []


def test_concurrency_cc105_bad_fixture():
    res = _cc([FIXTURES / "concurrency_cc105_bad.py"])
    assert _codes(res) == {"CC105"}
    msgs = "\n".join(f.message for f in res.findings)
    assert "calls self._bump(), which acquires it again" in msgs
    assert "re-acquired in a nested with" in msgs


def test_concurrency_cc105_clean_fixture():
    res = _cc([FIXTURES / "concurrency_cc105_clean.py"])
    assert res.findings == []


def test_concurrency_inherited_lock_context(tmp_path):
    # a helper is only "caller holds the lock" when EVERY non-init call
    # site holds it: one naked call site revokes the inheritance
    res = _lint(tmp_path, """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.Lock()
                self.n = 0

            def locked_path(self):
                with self._mu:
                    self.n += 1
                    self._bump()

            def naked_path(self):
                self._bump()

            def _bump(self):
                self.n += 1
        """, select=["concurrency"])
    assert _codes(res) == {"CC101"}
    assert any("in _bump()" in f.message for f in res.findings)


def test_concurrency_module_level_lock_order(tmp_path):
    res = _lint(tmp_path, """
        import threading

        _a = threading.Lock()
        _b = threading.Lock()

        def forward():
            with _a:
                with _b:
                    pass

        def backward():
            with _b:
                with _a:
                    pass
        """, select=["concurrency"])
    assert _codes(res) == {"CC104"}


def test_concurrency_init_is_exempt(tmp_path):
    # __init__ populates guarded attrs before the object is shared
    res = _lint(tmp_path, """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.Lock()
                self.n = 0
                self.n += 1

            def bump(self):
                with self._mu:
                    self.n += 1
        """, select=["concurrency"])
    assert res.findings == []


def test_concurrency_nested_def_holds_nothing(tmp_path):
    # a closure defined under the lock runs later (possibly on another
    # thread): the sleep inside it is NOT "blocking while holding"
    res = _lint(tmp_path, """
        import threading
        import time

        class Box:
            def __init__(self):
                self._mu = threading.Lock()

            def arm(self):
                with self._mu:
                    def later():
                        time.sleep(1.0)
                    return later
        """, select=["concurrency"])
    assert res.findings == []


def test_concurrency_pragma_and_baseline(tmp_path):
    src = """
        import threading

        class Box:
            def __init__(self):
                self._mu = threading.Lock()
                self.n = 0

            def bump(self):
                with self._mu:
                    self.n += 1

            def peek(self):
                return self.n{pragma}
        """
    flagged = _lint(tmp_path, src.format(pragma=""))
    assert _codes(flagged) == {"CC101"}
    quiet = _lint(tmp_path,
                  src.format(pragma="  # graftlint: disable=concurrency"),
                  name="quiet.py")
    assert quiet.findings == []
    assert quiet.suppressed == 1
    base = Baseline(frozenset(f.fingerprint() for f in flagged.findings))
    absorbed = run([str(tmp_path / "fixture.py")], select=["concurrency"],
                   baseline=base)
    assert absorbed.findings == [] and absorbed.baselined == 1


# ---------------------------------------- contracts (summary-scope) fixtures

def _ct(paths):
    return run([str(p) for p in paths], select=["contracts"])


def _rl(paths):
    return run([str(p) for p in paths], select=["resource_lifecycle"])


def test_contracts_ct101_bad_fixture():
    res = _ct([FIXTURES / "contracts_ct101_bad.py"])
    assert _codes(res) == {"CT101"}
    sev = {f.severity for f in res.findings}
    assert sev == {"error", "warning"}      # unhandled op + dead arm
    msgs = "\n".join(f.message for f in res.findings)
    assert "'cancel' has no registered server handler" in msgs
    assert "'audit' has no call site anywhere" in msgs


def test_contracts_ct101_clean_fixture():
    # parity both ways, with one op site resolved through a forwarder
    # method (Remote._call) and a client bound to a plain local name
    res = _ct([FIXTURES / "contracts_ct101_clean.py"])
    assert res.findings == []


def test_contracts_ct102_bad_fixture():
    res = _ct([FIXTURES / "contracts_ct102_bad.py"])
    assert _codes(res) == {"CT102"}
    assert "QuotaError" in res.findings[0].message
    assert res.findings[0].severity == "warning"


def test_contracts_ct102_clean_fixture():
    # verbatim-forwarding __init__, explicit __reduce__, and no __init__
    # at all are the three pickle-safe shapes
    res = _ct([FIXTURES / "contracts_ct102_clean.py"])
    assert res.findings == []


def test_contracts_ct103_bad_fixture():
    res = _ct([FIXTURES / "contracts_ct103_bad.py",
               FIXTURES / "contracts_ct103_decl.py"])
    assert _codes(res) == {"CT103"}
    msgs = "\n".join(f.message for f in res.findings)
    assert "'engine.stray' is fired but not declared" in msgs
    assert "non-literal point name" in msgs
    assert "'engine.retire' is never fired" in msgs
    assert "'engine.flush' has no injected(...) chaos coverage" in msgs
    errors = [f for f in res.findings if f.severity == "error"]
    assert len(errors) == 1                 # only the undeclared fire


def test_contracts_ct103_clean_fixture():
    res = _ct([FIXTURES / "contracts_ct103_clean.py",
               FIXTURES / "contracts_ct103_decl_ok.py"])
    assert res.findings == []


def test_contracts_ct103_self_armed_adhoc_point_ok(tmp_path):
    # a file that both arms a point (injected/install) and fires it is the
    # injector's own unit test — no parity error even with a KNOWN_POINTS
    # table elsewhere in the project
    adhoc = """
        from paddle_tpu.testing.faults import FAULTS, FailNth, injected

        def test_probe():
            with injected("p", FailNth(1)):
                FAULTS.fire("p", rid=1)
    """
    decl = 'KNOWN_POINTS = frozenset({"engine.step"})\n'
    a = tmp_path / "test_adhoc.py"
    a.write_text(textwrap.dedent(adhoc))
    d = tmp_path / "decl.py"
    d.write_text(decl)
    res = run([str(a), str(d)], select=["contracts"])
    assert not [f for f in res.findings if f.severity == "error"]


def test_contracts_ct104_bad_fixture():
    res = _ct([FIXTURES / "contracts_ct104_bad.py"])
    assert _codes(res) == {"CT104"}
    msgs = "\n".join(f.message for f in res.findings)
    assert "not a valid Prometheus name" in msgs
    assert "non-literal name" in msgs
    assert "redeclared as gauge but first declared as counter" in msgs


def test_contracts_ct104_clean_fixture():
    res = _ct([FIXTURES / "contracts_ct104_clean.py"])
    assert res.findings == []


# ------------------------------------------------- resource_lifecycle fixtures

def test_resource_rl101_bad_fixture():
    res = _rl([FIXTURES / "resource_rl101_bad.py"])
    assert _codes(res) == {"RL101"}
    msgs = "\n".join(f.message for f in res.findings)
    assert "socket 'sock' can leak" in msgs
    assert "constructor raises after acquiring" in msgs


def test_resource_rl101_clean_fixture():
    # closing except, guarded ctor, with-block, daemon thread, joined thread
    res = _rl([FIXTURES / "resource_rl101_clean.py"])
    assert res.findings == []


def test_resource_rl102_bad_fixture():
    res = _rl([FIXTURES / "resource_rl102_bad.py"])
    assert _codes(res) == {"RL102"}
    assert "alloc_page() ref can strand" in res.findings[0].message


def test_resource_rl102_clean_fixture():
    # rollback-guarded risky call and ownership transfer via return
    res = _rl([FIXTURES / "resource_rl102_clean.py"])
    assert res.findings == []


def test_resource_rl103_bad_fixture():
    res = _rl([FIXTURES / "resource_rl103_bad.py"])
    assert _codes(res) == {"RL103"}
    assert "membership lease 'self.lease'" in res.findings[0].message


def test_resource_rl103_clean_fixture():
    # release reachable from close() through an intra-class call
    res = _rl([FIXTURES / "resource_rl103_clean.py"])
    assert res.findings == []


def test_resource_lifecycle_skips_test_files(tmp_path):
    tdir = tmp_path / "tests"
    tdir.mkdir()
    leaky = (FIXTURES / "resource_rl101_bad.py").read_text()
    p = tdir / "test_sockets.py"
    p.write_text(leaky)
    res = run([str(p)], select=["resource_lifecycle"])
    assert res.findings == []


# ------------------------------------- summary cache: cross-file invalidation

CT_CLIENT = """
    from paddle_tpu.inference.frontend.rpc import RpcClient


    def gateway(host, port):
        client = RpcClient(host, port)
        return client.call("resume", rid=1)
"""

CT_WORKER = """
    from paddle_tpu.inference.frontend.rpc import RpcServer


    class Worker:
        def serve(self):
            self.srv = RpcServer(self._handle)
            return self.srv

        def _handle(self, op, kw):
            if op == "submit":
                return kw["rid"]
            raise ValueError(f"unknown worker op {op!r}")
"""


def test_summary_cache_cross_file_invalidation(tmp_path):
    """Editing the dispatcher must re-lint the (unchanged) client file —
    the whole point of the per-domain digest deps, proven WITHOUT
    --no-cache."""
    client = tmp_path / "client.py"
    worker = tmp_path / "worker.py"
    client.write_text(textwrap.dedent(CT_CLIENT))
    worker.write_text(textwrap.dedent(CT_WORKER))
    cpath = str(tmp_path / "cache.json")
    r1 = run([str(client), str(worker)], select=["contracts"],
             cache=FileCache(cpath))
    errs = [f for f in r1.findings if f.severity == "error"]
    assert len(errs) == 1 and "'resume'" in errs[0].message
    assert errs[0].path == str(client)
    # add the missing arm to worker.py ONLY; client.py is byte-identical
    worker.write_text(textwrap.dedent(CT_WORKER).replace(
        'if op == "submit":', 'if op in ("submit", "resume"):'))
    r2 = run([str(client), str(worker)], select=["contracts"],
             cache=FileCache(cpath))
    assert not [f for f in r2.findings if f.severity == "error"]
    assert r2.cache_hits == 0            # rpc digest changed: both re-lint
    # replay: nothing changed, both files served from cache
    r3 = run([str(client), str(worker)], select=["contracts"],
             cache=FileCache(cpath))
    assert r3.cache_hits == 2
    assert [f.to_dict() for f in r3.findings] == \
           [f.to_dict() for f in r2.findings]


def test_summary_cache_unrelated_edit_replays(tmp_path):
    """Editing a file with no rpc/fault/metric facts must NOT re-lint the
    others: only its own entry invalidates."""
    client = tmp_path / "client.py"
    worker = tmp_path / "worker.py"
    other = tmp_path / "mathutil.py"
    client.write_text(textwrap.dedent(CT_CLIENT))
    worker.write_text(textwrap.dedent(CT_WORKER))
    other.write_text("def double(x):\n    return 2 * x\n")
    cpath = str(tmp_path / "cache.json")
    run([str(client), str(worker), str(other)], select=["contracts"],
        cache=FileCache(cpath))
    other.write_text("def double(x):\n    return x + x\n")
    r2 = run([str(client), str(worker), str(other)], select=["contracts"],
             cache=FileCache(cpath))
    assert r2.cache_hits == 2            # client+worker replay, other re-lints


def test_cli_version_lists_rule_ids(capsys):
    assert cli.main(["--version"]) == 0
    out = capsys.readouterr().out
    assert "concurrency" in out
    assert "CC101, CC102, CC103, CC104, CC105" in out


def test_every_pass_declares_rule_codes():
    for name, p in PASSES.items():
        assert p.codes, f"pass {name} declares no rule codes"
        assert all(c.isalnum() for c in p.codes)


# ------------------------------------------------------- baseline workflow

def test_baseline_absorbs_recorded_findings(tmp_path):
    res = _sharding([FIXTURES / "sharding_bad.py"])
    assert res.findings
    bpath = str(tmp_path / "base.json")
    assert Baseline.write(bpath, res.findings) == len(res.findings)
    res2 = run([str(FIXTURES / "sharding_bad.py")],
               select=["sharding-spec-coverage"],
               baseline=Baseline.load(bpath))
    assert res2.findings == [] and res2.baselined == len(res.findings)


def test_baseline_missing_file_is_empty():
    assert len(Baseline.load("/nonexistent/base.json")) == 0


def test_fingerprint_is_path_and_line_independent():
    a = Finding("p", "C1", "/abs/elsewhere/paddle_tpu/ops/x.py", 3, "m")
    b = Finding("p", "C1", "paddle_tpu/ops/x.py", 99, "m")
    c = Finding("p", "C1", "paddle_tpu/ops/x.py", 99, "other message")
    assert a.fingerprint() == b.fingerprint() != c.fingerprint()


def test_cli_baseline_workflow(tmp_path, capsys, monkeypatch):
    monkeypatch.syspath_prepend(str(FIXTURES))
    importlib.invalidate_caches()
    bpath = str(tmp_path / "base.json")
    assert cli.main([str(FIXTURES), "--no-cache",
                     "--write-baseline", bpath]) == 0
    capsys.readouterr()
    assert cli.main([str(FIXTURES), "--no-cache", "--baseline", bpath]) == 0
    assert "baselined" in capsys.readouterr().out


def test_cli_fail_on_warning(tmp_path, capsys, monkeypatch):
    # a registry whose only finding is the DT102 warning
    pkg = tmp_path / "warnonly_pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text(textwrap.dedent("""
        # graftlint: disable-file=registry-parity
        import numpy as np

        class OpSpec:
            def __init__(self, name, np_ref, sample):
                self.name, self.np_ref, self.sample = name, np_ref, sample
                self.kwargs, self.grad, self.kind = {}, False, "golden"
                self.category, self.check, self.alias_of = "math", None, None

            def resolve(self):
                return self.np_ref

        REGISTRY = {}

        def g(name, ref, sample, cat):
            REGISTRY[name] = OpSpec(name, ref, sample)

        g("wide", lambda x: np.vander(x), lambda: [np.ones(3, np.float32)],
          "math")
    """))
    monkeypatch.syspath_prepend(str(tmp_path))
    importlib.invalidate_caches()
    assert cli.main([str(pkg), "--no-cache"]) == 0
    capsys.readouterr()
    assert cli.main([str(pkg), "--no-cache", "--fail-on", "warning"]) == 1


# ----------------------------------------------------- framework: pragmas etc.

def test_line_pragma_suppresses(tmp_path):
    src = """
        import jax

        @jax.jit
        def f(x):
            if x > 0:  # graftlint: disable=trace-safety
                return x
            return -x
    """
    res = _lint(tmp_path, src, select=["trace-safety"])
    assert res.findings == [] and res.suppressed == 1


def test_file_pragma_suppresses_all(tmp_path):
    res = _lint(tmp_path, "# graftlint: disable-file=all\n"
                + textwrap.dedent(TS_BAD), select=["trace-safety"])
    assert res.findings == [] and res.suppressed >= 5


def test_pragma_is_pass_specific(tmp_path):
    src = """
        import jax

        @jax.jit
        def f(x, opts=[1]):  # graftlint: disable=trace-safety
            return x
    """
    res = _lint(tmp_path, src, select=["jit-cache-hygiene"])
    assert _codes(res) == {"JH001"}     # wrong pass name: not suppressed


def test_syntax_error_is_a_finding(tmp_path):
    res = _lint(tmp_path, "def broken(:\n")
    assert _codes(res) == {"GL000"}


def test_cache_replay_matches_fresh_run(tmp_path):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(TS_BAD))
    cpath = str(tmp_path / "cache.json")
    r1 = run([str(p)], select=["trace-safety"], cache=FileCache(cpath))
    r2 = run([str(p)], select=["trace-safety"], cache=FileCache(cpath))
    assert r1.cache_hits == 0 and r2.cache_hits == 1
    assert [f.to_dict() for f in r1.findings] == \
           [f.to_dict() for f in r2.findings]
    # editing the file invalidates the entry
    p.write_text(textwrap.dedent(TS_BAD) + "\n# touched\n")
    r3 = run([str(p)], select=["trace-safety"], cache=FileCache(cpath))
    assert r3.cache_hits == 0


def test_cache_invalidated_on_pass_version_bump(tmp_path, monkeypatch):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(TS_BAD))
    cpath = str(tmp_path / "cache.json")
    run([str(p)], select=["trace-safety"], cache=FileCache(cpath))
    r2 = run([str(p)], select=["trace-safety"], cache=FileCache(cpath))
    assert r2.cache_hits == 1
    ts = PASSES["trace-safety"]
    monkeypatch.setattr(ts, "version", ts.version + 1)
    r3 = run([str(p)], select=["trace-safety"], cache=FileCache(cpath))
    assert r3.cache_hits == 0
    assert [f.to_dict() for f in r3.findings] == \
           [f.to_dict() for f in r2.findings]


def test_finding_dict_round_trip():
    f = Finding("trace-safety", "TS101", "a.py", 3, "msg", "hint", "warning")
    assert Finding.from_dict(f.to_dict()) == f
    # pre-severity cache records default to error
    d = f.to_dict()
    del d["severity"]
    assert Finding.from_dict(d).severity == "error"


def test_builtin_passes_registered():
    assert {"trace-safety", "registry-parity", "namespace-parity",
            "jit-cache-hygiene", "no-adhoc-telemetry",
            "sharding-spec-coverage", "dtype-rules", "robustness",
            "concurrency", "contracts", "resource_lifecycle"} <= set(PASSES)


def test_unknown_pass_rejected(tmp_path):
    with pytest.raises(KeyError):
        _lint(tmp_path, "x = 1\n", select=["no-such-pass"])


# ----------------------------------------------------------------------- CLI

def test_cli_json_schema_and_exit_code(tmp_path, capsys):
    p = tmp_path / "bad.py"
    p.write_text(textwrap.dedent(TS_BAD))
    rc = cli.main([str(p), "--format", "json", "--no-cache"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["graftlint"] == 1
    assert data["files"] == 1
    assert {f["code"] for f in data["findings"]} >= {"TS101", "TS105"}
    assert all({"pass", "code", "path", "line", "message", "hint"}
               <= set(f) for f in data["findings"])


def test_cli_clean_exit_zero(tmp_path, capsys):
    p = tmp_path / "ok.py"
    p.write_text("x = 1\n")
    assert cli.main([str(p), "--no-cache"]) == 0
    assert "OK:" in capsys.readouterr().out


def test_cli_unknown_pass_is_usage_error(tmp_path, capsys):
    p = tmp_path / "ok.py"
    p.write_text("x = 1\n")
    assert cli.main([str(p), "--select", "bogus", "--no-cache"]) == 2


def test_cli_list_passes(capsys):
    assert cli.main(["--list-passes"]) == 0
    out = capsys.readouterr().out
    assert "trace-safety" in out and "registry-parity" in out
    assert "sharding-spec-coverage" in out and "dtype-rules" in out
    assert "contracts" in out and "resource_lifecycle" in out
    assert "[summary]" in out            # summary-scope passes are tagged
    assert "CT101 CT102 CT103 CT104" in out
    assert "RL101 RL102 RL103" in out


def test_cli_explain_rule(capsys):
    assert cli.main(["--explain", "ct101"]) == 0    # case-insensitive
    out = capsys.readouterr().out
    assert "CT101 [contracts v" in out
    assert "severity:" in out
    assert "RPC op parity" in out
    # the committed fixture pair renders as the example
    assert "bad example" in out and "contracts_ct101_bad.py" in out
    assert "clean example" in out and "contracts_ct101_clean.py" in out


def test_cli_explain_every_declared_code(capsys):
    for p in PASSES.values():
        for code in p.codes:
            assert cli.main(["--explain", code]) == 0
    capsys.readouterr()


def test_cli_explain_unknown_code(capsys):
    assert cli.main(["--explain", "XX999"]) == 2
    assert "unknown rule code" in capsys.readouterr().err


def test_cli_sarif_output_valid(capsys, monkeypatch):
    monkeypatch.syspath_prepend(str(FIXTURES))
    importlib.invalidate_caches()
    rc = cli.main([str(FIXTURES), "--no-cache", "--format", "sarif"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert data["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in data["$schema"]
    (sarif_run,) = data["runs"]
    driver = sarif_run["tool"]["driver"]
    assert driver["name"] == "graftlint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert rule_ids == sorted(rule_ids)
    # findings from the newer passes are present
    assert {"SS101", "SS104", "DT101", "DT102"} <= set(rule_ids)
    # every concurrency rule fires on its bad fixture
    assert {"CC101", "CC102", "CC103", "CC104", "CC105"} <= set(rule_ids)
    levels = set()
    for r in sarif_run["results"]:
        assert r["ruleId"] == rule_ids[r["ruleIndex"]]
        levels.add(r["level"])
        (loc,) = r["locations"]
        phys = loc["physicalLocation"]
        assert phys["artifactLocation"]["uri"]
        assert phys["region"]["startLine"] >= 1
        assert r["fingerprints"]["graftlint/v1"]
    assert {"error", "warning"} <= levels


def test_cli_json_reports_severity_and_baseline(capsys):
    rc = cli.main([str(FIXTURES / "sharding_bad.py"), "--no-cache",
                   "--format", "json"])
    data = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert "baselined" in data
    severities = {f["severity"] for f in data["findings"]}
    assert severities == {"error", "warning"}


# ------------------------------------------------------- repo self-check gate

def test_repo_tree_is_clean(tmp_path):
    """The tier-1 CI gate: every pass (the sharding/dtype ones included) must
    exit clean on paddle_tpu/ at error severity; the accepted warnings live
    in the committed baseline."""
    res = run([str(REPO / "paddle_tpu")],
              cache=FileCache(str(tmp_path / "cache.json")),
              baseline=Baseline.load(str(REPO / ".graftlint-baseline.json")))
    assert res.files > 100
    assert {"sharding-spec-coverage", "dtype-rules"} <= set(res.passes)
    assert not res.findings, "\n" + "\n".join(
        f.render() for f in res.findings)


def test_repo_cross_process_contracts_clean(tmp_path):
    """PR-20 gate: the contracts and resource-lifecycle passes must run
    clean — warnings included — over the package AND the top-level test
    files, because CT101/CT103 need both halves of each protocol (op sites
    and dispatcher arms, fault fires and injected(...) coverage) in view.
    Fixture files stay out: tests/*.py does not recurse."""
    paths = [str(REPO / "paddle_tpu")] + sorted(
        str(p) for p in (REPO / "tests").glob("*.py"))
    res = run(paths, select=["contracts", "resource_lifecycle"],
              cache=FileCache(str(tmp_path / "cache.json")))
    assert res.files > 200
    assert not res.findings, "\n" + "\n".join(
        f.render() for f in res.findings)
    # CT103's decl-side checks actually engaged: the declared table is
    # non-empty and chaos coverage exists in the analyzed tree
    from paddle_tpu.analysis.summaries import SummaryIndex
    from paddle_tpu.analysis.framework import (Project, SourceFile,
                                               iter_python_files)
    idx = SummaryIndex(Project(
        [SourceFile(p) for p in iter_python_files(paths)]))
    assert len(idx.declared_points) >= 19
    assert idx.declared_points <= idx.fault_coverage, (
        "declared fault points without injected(...) coverage: "
        f"{sorted(idx.declared_points - idx.fault_coverage)}")


# ------------------------------------------- engine package layering guard

def test_engine_package_has_no_import_cycles():
    """The engine package's layering (request < pages/runner/spec <
    scheduler < core < disagg) must stay acyclic, and ``request`` must stay
    at the bottom importing no siblings — a cycle here means the interface
    split regressed back toward the monolith."""
    import ast

    pkg = REPO / "paddle_tpu" / "inference" / "engine"
    deps = {}
    for path in sorted(pkg.glob("*.py")):
        mod = path.stem
        tree = ast.parse(path.read_text())
        sibs = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.level == 1:
                if node.module:                      # "from .x import y"
                    sibs.add(node.module.split(".")[0])
                else:                                # "from . import x"
                    sibs.update(a.name for a in node.names)
        deps[mod] = sibs - {mod}

    assert deps.get("request") == set(), (
        "engine.request must import no siblings (it is the layering floor)")

    state = {}   # mod -> "visiting" | "done"

    def visit(mod, stack):
        if state.get(mod) == "done" or mod not in deps:
            return
        assert state.get(mod) != "visiting", (
            f"import cycle in inference.engine: {' -> '.join(stack + [mod])}")
        state[mod] = "visiting"
        for dep in sorted(deps[mod]):
            visit(dep, stack + [mod])
        state[mod] = "done"

    for mod in sorted(deps):
        visit(mod, [])
