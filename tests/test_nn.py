"""nn.Layer / layers / functional tests (reference pattern: test/legacy_test API tests)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(0)


class TestLayerBase:
    def test_registration_and_traversal(self):
        class M(nn.Layer):
            def __init__(self):
                super().__init__()
                self.fc = nn.Linear(2, 3)
                self.sub = nn.Sequential(nn.Linear(3, 3), nn.ReLU())
                self.register_buffer("buf", pt.ones([3]))

            def forward(self, x):
                return self.sub(self.fc(x)) + self.buf

        m = M()
        names = [n for n, _ in m.named_parameters()]
        assert "fc.weight" in names and "sub.0.bias" in names
        assert len(m.parameters()) == 4
        assert len(list(m.named_buffers())) == 1
        assert any(isinstance(l, nn.ReLU) for l in m.sublayers())
        out = m(pt.ones([1, 2]))
        assert out.shape == [1, 3]

    def test_train_eval_propagates(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
        m.eval()
        assert not m[1].training
        m.train()
        assert m[1].training

    def test_state_dict_roundtrip(self):
        m1 = nn.Linear(3, 4)
        m2 = nn.Linear(3, 4)
        m2.set_state_dict(m1.state_dict())
        x = pt.randn([2, 3])
        np.testing.assert_allclose(m1(x).numpy(), m2(x).numpy())

    def test_state_dict_shape_mismatch_raises(self):
        m1, m2 = nn.Linear(3, 4), nn.Linear(3, 5)
        with pytest.raises(ValueError):
            m2.set_state_dict(m1.state_dict())

    def test_forward_hooks(self):
        m = nn.Linear(2, 2)
        calls = []
        h1 = m.register_forward_pre_hook(lambda layer, inp: calls.append("pre"))
        h2 = m.register_forward_post_hook(lambda layer, inp, out: calls.append("post"))
        m(pt.ones([1, 2]))
        assert calls == ["pre", "post"]
        h1.remove(); h2.remove()
        m(pt.ones([1, 2]))
        assert calls == ["pre", "post"]

    def test_to_dtype(self):
        m = nn.Linear(2, 2)
        m.to(dtype="bfloat16")
        assert m.weight.dtype == pt.bfloat16

    def test_apply(self):
        m = nn.Sequential(nn.Linear(2, 2), nn.Linear(2, 2))
        seen = []
        m.apply(lambda l: seen.append(type(l).__name__))
        assert seen.count("Linear") == 2


class TestLayers:
    def test_linear_numerics(self):
        m = nn.Linear(3, 4)
        x = rng.rand(5, 3).astype(np.float32)
        ref = x @ m.weight.numpy() + m.bias.numpy()
        np.testing.assert_allclose(m(pt.to_tensor(x)).numpy(), ref, rtol=1e-5)

    def test_embedding(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        idx = pt.to_tensor(np.array([[1, 0, 3]], np.int64))
        out = emb(idx)
        assert out.shape == [1, 3, 4]
        np.testing.assert_allclose(out.numpy()[0, 1], np.zeros(4))
        out.sum().backward()
        assert emb.weight.grad is not None

    def test_layernorm_grad(self):
        ln = nn.LayerNorm(8)
        x = pt.randn([4, 8])
        x.stop_gradient = False
        ln(x).sum().backward()
        assert x.grad is not None and ln.weight.grad is not None

    def test_rmsnorm_matches_ref(self):
        m = nn.RMSNorm(8, epsilon=1e-6)
        x = rng.rand(2, 8).astype(np.float32)
        ref = x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(m(pt.to_tensor(x)).numpy(), ref, atol=1e-5)

    def test_batchnorm_train_eval(self):
        bn = nn.BatchNorm1D(4)
        x = pt.to_tensor(rng.rand(16, 4).astype(np.float32) * 3 + 1)
        bn.train()
        y = bn(x).numpy()
        assert abs(y.mean()) < 1e-4 and abs(y.std() - 1) < 0.1
        # running stats moved toward batch stats
        assert not np.allclose(bn._mean.numpy(), 0)
        bn.eval()
        y2 = bn(x)
        assert y2.shape == [16, 4]

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        x = pt.randn([2, 4, 5, 5])
        assert gn(x).shape == [2, 4, 5, 5]

    def test_conv2d_matches_manual(self):
        conv = nn.Conv2D(1, 1, 3, padding=0, bias_attr=False)
        w = conv.weight.numpy()[0, 0]
        img = rng.rand(1, 1, 5, 5).astype(np.float32)
        out = conv(pt.to_tensor(img)).numpy()[0, 0]
        ref = np.zeros((3, 3), np.float32)
        for i in range(3):
            for j in range(3):
                ref[i, j] = (img[0, 0, i:i+3, j:j+3] * w).sum()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_conv_groups_dilation_stride(self):
        conv = nn.Conv2D(4, 8, 3, stride=2, padding=1, dilation=2, groups=2)
        out = conv(pt.randn([2, 4, 16, 16]))
        assert out.shape[0] == 2 and out.shape[1] == 8

    def test_conv_transpose_shape(self):
        deconv = nn.Conv2DTranspose(4, 3, 4, stride=2, padding=1)
        out = deconv(pt.randn([1, 4, 8, 8]))
        assert out.shape == [1, 3, 16, 16]

    def test_pools(self):
        x = pt.to_tensor(rng.rand(1, 2, 8, 8).astype(np.float32))
        assert nn.MaxPool2D(2)(x).shape == [1, 2, 4, 4]
        assert nn.AvgPool2D(2)(x).shape == [1, 2, 4, 4]
        assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 2, 1, 1]
        np.testing.assert_allclose(nn.AdaptiveAvgPool2D(1)(x).numpy().reshape(1, 2),
                                   x.numpy().mean((2, 3)), rtol=1e-5)

    def test_activations(self):
        x = pt.to_tensor(np.array([-2.0, 0.0, 2.0], np.float32))
        np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 2])
        assert nn.GELU()(x).shape == [3]
        np.testing.assert_allclose(nn.LeakyReLU(0.1)(x).numpy(), [-0.2, 0, 2], rtol=1e-5)
        np.testing.assert_allclose(nn.Softmax()(x).numpy().sum(), 1.0, rtol=1e-5)

    def test_sequential_and_layerlist(self):
        s = nn.Sequential(nn.Linear(2, 3), nn.ReLU(), nn.Linear(3, 1))
        assert len(s) == 3
        assert s(pt.ones([1, 2])).shape == [1, 1]
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        ll.append(nn.Linear(2, 2))
        assert len(ll) == 4 and len(ll.parameters()) == 8

    def test_upsample(self):
        x = pt.to_tensor(rng.rand(1, 1, 4, 4).astype(np.float32))
        up = nn.Upsample(scale_factor=2, mode="nearest")
        assert up(x).shape == [1, 1, 8, 8]
        upb = nn.Upsample(scale_factor=2, mode="bilinear")
        assert upb(x).shape == [1, 1, 8, 8]

    def test_pad_layers(self):
        x = pt.ones([1, 1, 2, 2])
        assert nn.Pad2D([1, 1, 1, 1])(x).shape == [1, 1, 4, 4]


class TestFunctional:
    def test_cross_entropy_matches_ref(self):
        logits = rng.rand(8, 5).astype(np.float32)
        labels = rng.randint(0, 5, (8,)).astype(np.int64)
        out = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels)).item()
        e = np.exp(logits - logits.max(1, keepdims=True))
        p = e / e.sum(1, keepdims=True)
        ref = -np.log(p[np.arange(8), labels]).mean()
        assert abs(out - ref) < 1e-5

    def test_cross_entropy_ignore_index(self):
        logits = rng.rand(4, 3).astype(np.float32)
        labels = np.array([0, -100, 2, -100], np.int64)
        out = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels)).item()
        e = np.exp(logits - logits.max(1, keepdims=True))
        p = e / e.sum(1, keepdims=True)
        ref = -np.log(p[[0, 2], [0, 2]]).mean()
        assert abs(out - ref) < 1e-5

    def test_cross_entropy_soft_label(self):
        logits = rng.rand(4, 3).astype(np.float32)
        soft = np.full((4, 3), 1 / 3, np.float32)
        out = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(soft),
                              soft_label=True).item()
        lse = np.log(np.exp(logits - logits.max(1, keepdims=True)).sum(1)) + logits.max(1)
        ref = (lse - logits.mean(1)).mean()
        assert abs(out - ref) < 1e-4

    def test_mse_l1(self):
        a, b = rng.rand(4).astype(np.float32), rng.rand(4).astype(np.float32)
        assert abs(F.mse_loss(pt.to_tensor(a), pt.to_tensor(b)).item() -
                   ((a - b) ** 2).mean()) < 1e-6
        assert abs(F.l1_loss(pt.to_tensor(a), pt.to_tensor(b)).item() -
                   np.abs(a - b).mean()) < 1e-6

    def test_bce_with_logits(self):
        z = rng.randn(6).astype(np.float32)
        t = (rng.rand(6) > 0.5).astype(np.float32)
        out = F.binary_cross_entropy_with_logits(pt.to_tensor(z), pt.to_tensor(t)).item()
        p = 1 / (1 + np.exp(-z))
        ref = -(t * np.log(p) + (1 - t) * np.log(1 - p)).mean()
        assert abs(out - ref) < 1e-5

    def test_kl_div(self):
        logp = np.log(np.array([[0.3, 0.7]], np.float32))
        t = np.array([[0.5, 0.5]], np.float32)
        out = F.kl_div(pt.to_tensor(logp), pt.to_tensor(t), reduction="sum").item()
        ref = (t * (np.log(t) - logp)).sum()
        assert abs(out - ref) < 1e-5

    def test_dropout_train_scale(self):
        pt.seed(3)
        x = pt.ones([1000])
        y = F.dropout(x, p=0.5, training=True).numpy()
        assert set(np.unique(y)).issubset({0.0, 2.0})
        assert abs(y.mean() - 1.0) < 0.15
        y2 = F.dropout(x, p=0.5, training=False).numpy()
        np.testing.assert_allclose(y2, 1.0)

    def test_sdpa_causal_masks_future(self):
        # value at position 0 must not see position 1
        q = np.zeros((1, 2, 1, 4), np.float32)
        v = np.zeros((1, 2, 1, 4), np.float32)
        v[0, 1] = 100.0
        out = F.scaled_dot_product_attention(
            pt.to_tensor(q), pt.to_tensor(q), pt.to_tensor(v), is_causal=True).numpy()
        np.testing.assert_allclose(out[0, 0], 0.0)

    def test_sdpa_matches_naive(self):
        q = rng.rand(2, 4, 2, 8).astype(np.float32)
        k = rng.rand(2, 4, 2, 8).astype(np.float32)
        v = rng.rand(2, 4, 2, 8).astype(np.float32)
        out = F.scaled_dot_product_attention(pt.to_tensor(q), pt.to_tensor(k),
                                             pt.to_tensor(v)).numpy()
        qh = q.transpose(0, 2, 1, 3)
        kh = k.transpose(0, 2, 1, 3)
        vh = v.transpose(0, 2, 1, 3)
        logits = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(8)
        w = np.exp(logits - logits.max(-1, keepdims=True))
        w = w / w.sum(-1, keepdims=True)
        ref = (w @ vh).transpose(0, 2, 1, 3)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_one_hot_and_label_smooth(self):
        oh = F.one_hot(pt.to_tensor(np.array([0, 2], np.int64)), 3).numpy()
        np.testing.assert_array_equal(oh, [[1, 0, 0], [0, 0, 1]])
        ls = F.label_smooth(pt.to_tensor(oh), epsilon=0.1).numpy()
        np.testing.assert_allclose(ls[0], [0.9 + 0.1 / 3, 0.1 / 3, 0.1 / 3], rtol=1e-5)

    def test_rope_rotation_property(self):
        # RoPE preserves norms
        q = rng.rand(1, 4, 2, 8).astype(np.float32)
        qr, _, _ = F.fused_rotary_position_embedding(pt.to_tensor(q))
        np.testing.assert_allclose(np.linalg.norm(qr.numpy(), axis=-1),
                                   np.linalg.norm(q, axis=-1), rtol=1e-4)

    def test_swiglu(self):
        x = rng.rand(2, 8).astype(np.float32)
        out = F.swiglu(pt.to_tensor(x)).numpy()
        a, b = x[:, :4], x[:, 4:]
        ref = a / (1 + np.exp(-a)) * b
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_sequence_mask(self):
        m = F.sequence_mask(pt.to_tensor(np.array([1, 3], np.int64)), maxlen=4)
        np.testing.assert_array_equal(m.numpy(), [[1, 0, 0, 0], [1, 1, 1, 0]])

    def test_unfold_fold_roundtrip(self):
        x = rng.rand(1, 2, 6, 6).astype(np.float32)
        u = F.unfold(pt.to_tensor(x), 2, strides=2)
        assert u.shape == [1, 8, 9]
        back = F.fold(u, [6, 6], 2, strides=2)
        np.testing.assert_allclose(back.numpy(), x, rtol=1e-5)


def test_chunked_lm_loss_bf16_logits_close_to_f32():
    """loss_logits_dtype='bfloat16' (bench fast path) must match the f32
    chunked loss within bf16 tolerance, forward and backward."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM

    losses, grads = {}, {}
    for dt in ("float32", "bfloat16"):
        pt.seed(0)
        cfg = GPT2Config.tiny(hidden_dropout_prob=0.0,
                              attention_dropout_prob=0.0,
                              loss_chunk_size=64, loss_logits_dtype=dt)
        m = GPT2ForCausalLM(cfg)
        m.to(dtype="bfloat16")   # the bench path; makes the bf16 branch real
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (2, 33)).astype(np.int32)
        x, y = pt.to_tensor(ids[:, :-1]), pt.to_tensor(ids[:, 1:])
        _, loss = m(x, labels=y)
        loss.backward()
        losses[dt] = float(np.asarray(loss._data, np.float32))
        grads[dt] = np.asarray(m.gpt2.wte.weight.grad._data
                               if not hasattr(m.gpt2.wte.weight.grad, "values")
                               else m.gpt2.wte.weight.grad.values,
                               np.float32)
    assert abs(losses["bfloat16"] - losses["float32"]) \
        / max(abs(losses["float32"]), 1e-6) < 2e-2, losses
    num = np.abs(grads["bfloat16"] - grads["float32"]).max()
    den = np.abs(grads["float32"]).max() + 1e-6
    assert num / den < 5e-2, (num, den)
