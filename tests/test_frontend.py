"""Serving front door: prefix-affinity routing, SLO admission, the threaded
ReplicaSet facade, the SSE gateway, replica-death chaos, and the trace-driven
load generator.

The routing/admission tests are pure (stub replicas, no engines, no HTTP).
The end-to-end tests run real tiny-model engines on CPU: concurrent SSE
clients must receive token streams identical to direct single-engine runs,
and a repeated-prefix workload must show affinity routing beating round-robin
on prefix-cache hits (ISSUE 8 acceptance)."""
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from paddle_tpu.inference.frontend.admission import (AdmissionDecision,
                                                     AlwaysAdmit, ShedError,
                                                     SLOAdmission)
from paddle_tpu.inference.frontend.loadgen import (http_completion,
                                                   make_trace, percentile,
                                                   run_closed_loop, summarize)
from paddle_tpu.inference.frontend.router import (PrefixAffinityRouter,
                                                  RoundRobinRouter)
from paddle_tpu.inference.serving import prefix_page_keys
from paddle_tpu.testing import FAULTS, FailNth


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# ------------------------------------------------------------- chain hashing

class TestPrefixPageKeys:
    def test_full_pages_only(self):
        toks = list(range(20))
        assert len(prefix_page_keys(toks, 8)) == 2       # 16 of 20 tokens
        assert prefix_page_keys(toks[:7], 8) == []

    def test_chain_dependence(self):
        a = prefix_page_keys([1] * 16, 8)
        b = prefix_page_keys([1] * 8 + [2] * 8, 8)
        assert a[0] == b[0]                 # shared first page
        assert a[1] != b[1]                 # second page differs -> new chain

    def test_matches_engine_hashing_for_numpy_tokens(self):
        toks = np.arange(32, dtype=np.int32)
        assert prefix_page_keys(toks, 8) == prefix_page_keys(list(toks), 8)


# ------------------------------------------------------- router (pure units)

class _StubReplica:
    def __init__(self, name, load=0):
        self.name = name
        self.alive = True
        self._load = load

    def load(self):
        return self._load


class TestPrefixAffinityRouter:
    def _register_prefix(self, router, name, tokens, page=8):
        for k in prefix_page_keys(tokens, page):
            router.note_event(name, "register", k)

    def test_overlap_scoring_prefers_deepest_prefix(self):
        r = PrefixAffinityRouter(page_size=8)
        prompt = list(range(32))            # 4 full pages
        self._register_prefix(r, "a", prompt[:16])   # 2 pages
        self._register_prefix(r, "b", prompt[:24])   # 3 pages
        reps = [_StubReplica("a"), _StubReplica("b")]
        d = r.route(prompt, reps)
        assert d.replica.name == "b" and d.reason == "affinity"
        assert d.overlap == 3

    def test_overlap_is_contiguous_from_page_zero(self):
        # holding page 2's key without pages 0-1 is worthless (the engine
        # can only reuse a cached prefix from the start)
        r = PrefixAffinityRouter(page_size=8)
        prompt = list(range(32))
        keys = prefix_page_keys(prompt, 8)
        r.note_event("a", "register", keys[2])       # orphan tail page
        self._register_prefix(r, "b", prompt[:8])    # genuine 1-page prefix
        d = r.route(prompt, [_StubReplica("a"), _StubReplica("b")])
        assert d.replica.name == "b" and d.overlap == 1

    def test_evict_event_removes_key(self):
        r = PrefixAffinityRouter(page_size=8)
        prompt = list(range(16))
        self._register_prefix(r, "a", prompt)
        keys = prefix_page_keys(prompt, 8)
        r.note_event("a", "evict", keys[1])
        reps = [_StubReplica("a"), _StubReplica("b")]
        assert r.route(prompt, reps).overlap == 1    # page 0 still cached
        r.note_event("a", "evict", keys[0])
        d = r.route(prompt, reps)
        assert d.reason == "least_loaded"            # index fully drained

    def test_least_loaded_fallback_without_overlap(self):
        r = PrefixAffinityRouter(page_size=8)
        reps = [_StubReplica("a", load=3), _StubReplica("b", load=1)]
        d = r.route(list(range(16)), reps)
        assert d.replica.name == "b" and d.reason == "least_loaded"

    def test_load_breaks_overlap_ties(self):
        r = PrefixAffinityRouter(page_size=8)
        prompt = list(range(16))
        self._register_prefix(r, "a", prompt)
        self._register_prefix(r, "b", prompt)
        reps = [_StubReplica("a", load=2), _StubReplica("b", load=0)]
        d = r.route(prompt, reps)
        assert d.replica.name == "b" and d.reason == "affinity"

    def test_deterministic_name_tiebreak(self):
        r = PrefixAffinityRouter(page_size=8)
        reps = [_StubReplica(n) for n in ("c", "a", "b")]
        for _ in range(3):                  # same state -> same answer
            assert r.route(list(range(16)), reps).replica.name == "a"
        # list order must not matter
        assert r.route(list(range(16)), reps[::-1]).replica.name == "a"

    def test_forget_drops_whole_replica_index(self):
        r = PrefixAffinityRouter(page_size=8)
        prompt = list(range(16))
        self._register_prefix(r, "a", prompt)
        r.forget("a")
        assert r.known_keys("a") == frozenset()
        d = r.route(prompt, [_StubReplica("a"), _StubReplica("b")])
        assert d.reason == "least_loaded"

    def test_route_requires_replicas(self):
        with pytest.raises(ValueError):
            PrefixAffinityRouter(8).route([1, 2], [])

    def test_node_index_shared_across_replicas(self):
        # the radix node index maps each chain key to its holder set: one
        # walk scores every replica, and a node with no holders left is
        # dropped from the index entirely
        r = PrefixAffinityRouter(page_size=8)
        prompt = list(range(24))
        self._register_prefix(r, "a", prompt)
        self._register_prefix(r, "b", prompt[:16])
        keys = prefix_page_keys(prompt, 8)
        assert r._nodes[keys[0]] == {"a", "b"}
        assert r._nodes[keys[2]] == {"a"}
        overlaps = r._overlaps(keys, ["a", "b", "c"])
        assert overlaps == {"a": 3, "b": 2, "c": 0}
        r.note_event("b", "evict", keys[1])
        r.forget("a")
        assert keys[1] not in r._nodes and keys[2] not in r._nodes
        assert r.known_keys("b") == {keys[0]}


class TestRoundRobinRouter:
    def test_cycles_in_order(self):
        r = RoundRobinRouter()
        reps = [_StubReplica("a"), _StubReplica("b")]
        names = [r.route([1], reps).replica.name for _ in range(4)]
        assert names == ["a", "b", "a", "b"]
        assert all(d == "round_robin" for d in
                   (r.route([1], reps).reason,))


# ----------------------------------------------------- admission (pure units)

class _StubHealthReplica:
    def __init__(self, name, waiting=0, free=8, reclaimable=0, total=8):
        self.name = name
        self.alive = True
        self._h = {"waiting": waiting, "free_pages": free,
                   "reclaimable_pages": reclaimable, "total_pages": total}

    def health(self):
        return dict(self._h)


class TestSLOAdmission:
    def test_always_admit_default(self):
        assert AlwaysAdmit().decide([_StubHealthReplica("a")]).admit

    def test_queue_full_requires_every_replica_full(self):
        pol = SLOAdmission(max_queue_per_replica=2)
        full = _StubHealthReplica("a", waiting=2)
        free = _StubHealthReplica("b", waiting=1)
        assert pol.decide([full, free]).admit            # one still has room
        d = pol.decide([full, _StubHealthReplica("c", waiting=5)])
        assert not d.admit and d.reason == "queue_full"
        assert d.retry_after > 0

    def test_page_pressure_needs_backlog(self):
        pol = SLOAdmission(max_queue_per_replica=None, min_free_page_ratio=0.5)
        starved_idle = _StubHealthReplica("a", waiting=0, free=1, total=8)
        assert pol.decide([starved_idle]).admit          # idle always admits
        starved_busy = _StubHealthReplica("a", waiting=3, free=1, total=8)
        d = pol.decide([starved_busy])
        assert not d.admit and d.reason == "page_pressure"

    def test_ttft_slo_uses_observed_window(self):
        pol = SLOAdmission(max_queue_per_replica=None, ttft_slo=0.5)
        rep = _StubHealthReplica("a")
        assert pol.decide([rep]).admit                   # no data -> admit
        for _ in range(4):
            pol.observe_ttft(2.0)
        d = pol.decide([rep])
        assert not d.admit and d.reason == "ttft_slo"
        for _ in range(64):
            pol.observe_ttft(0.01)                       # window recovers
        assert pol.decide([rep]).admit

    def test_tpot_slo_uses_observed_window(self):
        pol = SLOAdmission(max_queue_per_replica=None, tpot_slo=0.05)
        rep = _StubHealthReplica("a")
        assert pol.decide([rep]).admit                   # no data -> admit
        pol.observe_tpot(None)                           # ignored
        for _ in range(4):
            pol.observe_tpot(0.2)                        # decode saturated
        d = pol.decide([rep])
        assert not d.admit and d.reason == "tpot_slo"
        for _ in range(64):
            pol.observe_tpot(0.001)                      # window recovers
        assert pol.decide([rep]).admit

    def test_ttft_slo_checked_before_tpot_slo(self):
        pol = SLOAdmission(max_queue_per_replica=None, ttft_slo=0.5,
                           tpot_slo=0.05)
        pol.observe_ttft(2.0)
        pol.observe_tpot(0.2)
        d = pol.decide([_StubHealthReplica("a")])
        assert not d.admit and d.reason == "ttft_slo"

    def test_decision_repr_and_shed_error(self):
        d = AdmissionDecision(False, "queue_full", 2.0)
        assert "queue_full" in repr(d)
        e = ShedError("queue_full", 2.0)
        assert e.reason == "queue_full" and e.retry_after == 2.0


# ------------------------------------------------------------ loadgen (pure)

class TestLoadgen:
    def test_trace_is_deterministic(self):
        a = make_trace(7, 12, groups=3)
        b = make_trace(7, 12, groups=3)
        assert a == b
        assert a != make_trace(8, 12, groups=3)

    def test_group_major_blocks_adjacent(self):
        t = make_trace(0, 8, groups=4, group_major=True)
        assert [r["group"] for r in t] == [0, 0, 1, 1, 2, 2, 3, 3]
        t = make_trace(0, 8, groups=4, group_major=False)
        assert [r["group"] for r in t] == [0, 1, 2, 3, 0, 1, 2, 3]

    def test_shared_prefix_unique_suffix(self):
        t = make_trace(1, 6, groups=2, prefix_pages=2, page_size=8,
                       suffix_tokens=4, group_major=True)
        g0 = [r["prompt"] for r in t if r["group"] == 0]
        assert all(p[:16] == g0[0][:16] for p in g0)     # shared prefix
        assert len({tuple(p) for p in g0}) == len(g0)    # distinct suffixes

    def test_percentile_nearest_rank(self):
        vals = list(range(1, 101))
        assert percentile(vals, 50) in (50, 51)
        assert percentile(vals, 95) in (95, 96)
        assert percentile([3.0], 95) == 3.0
        with pytest.raises(ValueError):
            percentile([], 50)


# ----------------------------------------------------- end-to-end (tiny CPU)

def _tiny_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _engine(model, **kw):
    from paddle_tpu.inference.serving import LLMEngine
    kw.setdefault("max_batch", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefix_cache", True)
    return LLMEngine(model, **kw)


def _replica_set(model, n=2, **kw):
    from paddle_tpu.inference.frontend import ReplicaSet
    return ReplicaSet([_engine(model) for _ in range(n)], **kw)


def _prompts(n, seed=0, lo=4, step=3):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, 128, (lo + step * i,)).astype(np.int32)
            for i in range(n)]


class TestReplicaSet:
    def test_submit_result_parity_with_direct_engine(self, model):
        prompts = _prompts(3)
        ref = _engine(model)
        rids = [ref.add_request(p, max_new_tokens=6) for p in prompts]
        ref.run_until_done()
        want = [list(ref.result(r)) for r in rids]

        rs = _replica_set(model)
        try:
            handles = [rs.submit(p, max_new_tokens=6) for p in prompts]
            got = [rs.result(h) for h in handles]
        finally:
            rs.close()
        from paddle_tpu.inference.serving import RequestStatus
        assert [list(t) for t, _ in got] == want
        assert all(s is RequestStatus.FINISHED or s is RequestStatus.EOS
                   for _, s in got)

    def test_stream_tokens_incrementally(self, model):
        prompts = _prompts(1, seed=5)
        ref = _engine(model)
        rid = ref.add_request(prompts[0], max_new_tokens=6)
        ref.run_until_done()
        rs = _replica_set(model)
        try:
            h = rs.submit(prompts[0], max_new_tokens=6)
            assert list(rs.stream(h)) == list(ref.result(rid))
        finally:
            rs.close()

    def test_cancel_mid_serve(self, model):
        from paddle_tpu.inference.serving import RequestStatus
        rs = _replica_set(model, n=1)
        try:
            h = rs.submit(_prompts(1)[0], max_new_tokens=40)
            # let it start, then cancel mid-decode
            h.replica.poll(h.rid, timeout=5.0)
            assert rs.cancel(h)
            _, status = rs.result(h, timeout=20.0)
            assert status is RequestStatus.CANCELLED
        finally:
            rs.close()

    def test_engine_level_shed_surfaces_as_shed_error(self, model):
        rs = _replica_set(model, n=1)
        try:
            rs.replicas[0].engine.max_waiting = 0    # engine refuses all
            with pytest.raises(ShedError) as ei:
                rs.submit(_prompts(1)[0], max_new_tokens=4)
            assert ei.value.reason == "engine"
        finally:
            rs.close()

    def test_admission_shed_never_reaches_replicas(self, model):
        class _RefuseAll:
            def decide(self, replicas):
                return AdmissionDecision(False, "queue_full", 3.0)

            def observe_ttft(self, s):
                pass

        rs = _replica_set(model, n=1, admission=_RefuseAll())
        try:
            with pytest.raises(ShedError):
                rs.submit(_prompts(1)[0], max_new_tokens=4)
            assert rs.replicas[0].engine.health()["finished"] == 0
        finally:
            rs.close()

    def test_per_replica_health_and_metrics_labels(self, model):
        rs = _replica_set(model)
        try:
            h = rs.submit(_prompts(1)[0], max_new_tokens=4)
            rs.result(h)
            health = rs.health()
            assert set(health) == {"r0", "r1"}
            assert all(hh["replica"] == name and hh["alive"]
                       for name, hh in health.items())
            metrics = rs.metrics()
            assert set(metrics) == {"r0", "r1"}
        finally:
            rs.close()


class TestStuckStepWatchdog:
    def test_stuck_step_trips_typed_death_and_fails_over(self, model):
        """A step that wedges past ``step_wall_timeout`` is a gray failure:
        the watchdog promotes it to a typed replica death while the step
        still holds the engine condition, pollers fail over immediately,
        and the zero-streamed request requeues onto the survivor with
        byte-identical output."""
        import paddle_tpu.observability as obs
        from paddle_tpu.inference.frontend import (ReplicaSet,
                                                   StuckStepError)
        from paddle_tpu.inference.serving import RequestStatus

        prompt = _prompts(1, seed=3)[0]
        ref = _engine(model)
        rid = ref.add_request(prompt, max_new_tokens=6)
        ref.run_until_done()
        want = list(ref.result(rid))

        engines = [_engine(model) for _ in range(2)]
        for eng in engines:
            # pay each engine's JIT compilation for the exact prompt and
            # decode shapes this test submits, so the watchdog times
            # genuine step wall time, not compilation
            eng.add_request(list(prompt), max_new_tokens=6)
            eng.run_until_done()
        real_step = engines[0].step
        stalled = threading.Event()

        def wedged_step():
            if not stalled.is_set():
                stalled.set()            # wedge the FIRST step only —
                time.sleep(2.0)          #   far past step_wall_timeout
            return real_step()

        engines[0].step = wedged_step
        obs.enable()
        try:
            rs = ReplicaSet(engines, router=RoundRobinRouter(),
                            requeue=True, step_wall_timeout=0.5)
            try:
                h = rs.submit(prompt, max_new_tokens=6)  # round 1 → r0
                toks, status = rs.result(h, timeout=60.0)
                assert status in (RequestStatus.FINISHED, RequestStatus.EOS)
                assert list(toks) == want
                r0 = rs.replicas[0]
                assert not r0.alive
                assert isinstance(r0.error, StuckStepError)
                health = rs.health()
                assert health["r0"]["alive"] is False
                assert health["r1"]["alive"] is True
                text = obs.render_prometheus()
                assert 'frontend_stuck_steps_total{replica="r0"} 1' in text
                assert "frontend_requeued_total 1" in text
            finally:
                rs.close()
        finally:
            obs.disable()
            obs.reset()


class TestAffinityVsRoundRobin:
    def _run(self, model, router, trace):
        rs = _replica_set(model, n=2, router=router)
        try:
            records, wall = run_closed_loop(rs, trace, concurrency=1)
            hits = sum(r.engine.prefix_cache_stats()["hits"]
                       for r in rs.replicas)
            lookups = hits + sum(r.engine.prefix_cache_stats()["misses"]
                                 for r in rs.replicas)
        finally:
            rs.close()
        assert all(r["status"] in ("finished", "eos") for r in records)
        return records, hits, max(1, lookups)

    def test_affinity_beats_round_robin_on_prefix_hits(self, model):
        """ISSUE 8 acceptance: a repeated-prefix workload served
        group-major, closed-loop, over 2 replicas.  Round-robin alternates
        replicas, so a group's repeat lands on the replica WITHOUT its
        prefix (zero hits); affinity routes it back to the cached replica
        (>=1 page hit per repeat) — at least 2x the round-robin hit rate."""
        import paddle_tpu.observability as obs
        trace = make_trace(3, 8, groups=4, prefix_pages=2, page_size=8,
                           suffix_tokens=3, max_new_tokens=4,
                           group_major=True)
        _, rr_hits, rr_lookups = self._run(model, RoundRobinRouter(), trace)

        obs.enable()
        try:
            obs.reset()
            aff_records, aff_hits, aff_lookups = self._run(
                model, PrefixAffinityRouter(page_size=8), trace)
            snap = obs.snapshot(prefix="frontend_affinity")
            events = {s["labels"]["event"]: s["value"] for s in
                      snap["frontend_affinity_events_total"]["series"]}
        finally:
            obs.disable()

        assert aff_hits > 0, "affinity routing produced no prefix-cache hits"
        aff_rate = aff_hits / aff_lookups
        rr_rate = rr_hits / rr_lookups
        assert rr_hits == 0 or aff_rate >= 2 * rr_rate, (
            f"affinity {aff_rate:.3f} not >= 2x round-robin {rr_rate:.3f}")
        # the router's own view agrees: one miss per group's first request,
        # hits for the repeats
        assert events.get("hit", 0) >= 4
        # and every repeat went to the replica that served its group before
        by_group = {}
        for r in aff_records:
            by_group.setdefault(r["group"], set()).add(r["replica"])
        assert all(len(v) == 1 for v in by_group.values())


class TestGatewayHTTP:
    @pytest.fixture()
    def served(self, model):
        from paddle_tpu.inference.frontend import start_gateway
        rs = _replica_set(model)
        gw = start_gateway(rs)
        yield gw, rs
        gw.close()
        rs.close()

    def test_concurrent_sse_streams_byte_identical(self, model, served):
        """ISSUE 8 acceptance: >=3 concurrent streaming clients against a
        2-replica set each receive exactly the token stream a direct
        single-engine run produces."""
        gw, _ = served
        prompts = _prompts(3, seed=9)
        ref = _engine(model)
        rids = [ref.add_request(p, max_new_tokens=6) for p in prompts]
        ref.run_until_done()
        want = [[int(t) for t in ref.result(r)] for r in rids]

        results = [None] * len(prompts)
        errors = []

        def client(i):
            try:
                results[i] = http_completion(gw.url, prompts[i],
                                             max_tokens=6, stream=True,
                                             timeout=120.0)
            except Exception as e:  # surfaced via the errors list
                errors.append((i, repr(e)))

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180.0)
        assert not errors, errors
        for i, want_toks in enumerate(want):
            assert results[i]["tokens"] == want_toks, i
            assert results[i]["status"] in ("finished", "eos")
            # one event per token + final status + [DONE]
            assert results[i]["events"] == len(want_toks) + 2

    def test_non_stream_completion(self, served):
        gw, _ = served
        out = http_completion(gw.url, _prompts(1, seed=11)[0], max_tokens=5)
        assert len(out["tokens"]) == 5
        assert out["status"] in ("finished", "eos")
        assert out["replica"] in ("r0", "r1")

    def test_shed_maps_to_429_with_retry_after(self, model):
        from paddle_tpu.inference.frontend import start_gateway

        class _RefuseAll:
            def decide(self, replicas):
                return AdmissionDecision(False, "queue_full", 7.0)

            def observe_ttft(self, s):
                pass

        rs = _replica_set(model, n=1, admission=_RefuseAll())
        gw = start_gateway(rs)
        try:
            with pytest.raises(urllib.error.HTTPError) as ei:
                http_completion(gw.url, [1, 2, 3], max_tokens=4)
            assert ei.value.code == 429
            assert ei.value.headers["Retry-After"] == "7"
            body = json.loads(ei.value.read().decode())
            assert body["reason"] == "queue_full"
        finally:
            gw.close()
            rs.close()

    def test_unserved_deadline_maps_to_408(self, served):
        gw, _ = served
        with pytest.raises(urllib.error.HTTPError) as ei:
            http_completion(gw.url, _prompts(1)[0], max_tokens=4,
                            deadline=1e-6)
        assert ei.value.code == 408
        # Retry-After parity with 429/503: an unserved deadline is a load
        # symptom, the client should back off before re-asking
        assert ei.value.headers["Retry-After"] == "1"

    def test_bad_request_maps_to_400(self, served):
        gw, _ = served
        req = urllib.request.Request(
            gw.url + "/v1/completions",
            data=json.dumps({"prompt": "not-token-ids"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30.0)
        assert ei.value.code == 400

    def test_unknown_route_404(self, served):
        gw, _ = served
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(gw.url + "/v2/nope", timeout=30.0)
        assert ei.value.code == 404

    def test_healthz_and_metrics_endpoints(self, served):
        gw, _ = served
        with urllib.request.urlopen(gw.url + "/healthz", timeout=30.0) as r:
            health = json.loads(r.read().decode())
        assert set(health) == {"r0", "r1", "fleet"}
        assert health["fleet"]["alive"] == 2
        assert all(h["alive"] for name, h in health.items()
                   if name != "fleet")
        with urllib.request.urlopen(gw.url + "/metrics", timeout=30.0) as r:
            text = r.read().decode()
        assert "frontend_requests_total" in text
        assert "# TYPE frontend_stream_seconds histogram" in text

    def test_client_disconnect_cancels_request(self, model, served):
        import http.client
        import socket
        import struct
        from paddle_tpu.inference.serving import RequestStatus
        gw, rs = served
        # throttle decode (100ms/step via the slow-step fault point) so the
        # stream outlives the disconnect — at full speed the tiny model
        # generates and buffers all 56 tokens before the RST propagates
        from paddle_tpu.testing.faults import Always
        FAULTS.install("serving.slow_step", Always(), delay=0.1)
        body = json.dumps({"prompt": [int(t) for t in _prompts(1)[0]],
                           "max_tokens": 56, "stream": True})
        conn = http.client.HTTPConnection(gw.addr, gw.port, timeout=60.0)
        conn.request("POST", "/v1/completions", body=body,
                     headers={"Content-Type": "application/json"})
        sock = conn.sock                    # getresponse() may detach it
        resp = conn.getresponse()
        resp.read(16)                       # first bytes of the stream
        # RST on close (not a graceful FIN): the kernel would otherwise
        # buffer the server's remaining writes without erroring, and a
        # short stream could complete before the disconnect surfaces
        sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                        struct.pack("ii", 1, 0))
        resp.close()                        # drop makefile()'s fd reference
        sock.close()                        # ...so this really closes + RSTs
        conn.close()                        # walk away mid-stream
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            done = [r.engine._finished for r in rs.replicas]
            statuses = [req.status for fin in done for req in fin.values()]
            if RequestStatus.CANCELLED in statuses:
                break
            time.sleep(0.2)
        else:
            pytest.fail("client disconnect never cancelled the request")


class TestReplicaDeathChaos:
    def test_replica_kill_mid_stream(self, model):
        """ISSUE 8 chaos acceptance: kill one replica mid-stream.  Its
        inflight requests end FAILED (typed, not hung), the router stops
        selecting it, and survivors on the other replica stay token-exact
        with a fault-free run."""
        from paddle_tpu.inference.serving import RequestStatus
        prompts = _prompts(2, seed=21)
        ref = _engine(model)
        ref_rids = [ref.add_request(p, max_new_tokens=8) for p in prompts]
        ref.run_until_done()
        want = [list(ref.result(r)) for r in ref_rids]

        rs = _replica_set(model, n=2)
        try:
            # deterministic placement: empty set routes least-loaded with
            # name tie-break -> first request r0, second (r0 now loaded) r1
            h0 = rs.submit(prompts[0], max_new_tokens=8)
            h1 = rs.submit(prompts[1], max_new_tokens=8)
            assert {h0.replica.name, h1.replica.name} == {"r0", "r1"}
            victim, survivor = h0, h1
            # kill the victim's replica a few steps in (mid-stream)
            FAULTS.install(
                "frontend.step", FailNth(3),
                match=lambda ctx: ctx.get("replica") == victim.replica.name)
            _, vstat = rs.result(victim, timeout=120.0)
            assert vstat is RequestStatus.FAILED
            assert "injected fault" in (rs.request_error(victim) or "")
            assert not victim.replica.alive
            # the dead replica's prefix index is gone from the router
            assert rs.router.known_keys(victim.replica.name) == frozenset()
            # survivor is token-exact with the fault-free run
            toks, sstat = rs.result(survivor, timeout=120.0)
            assert sstat in (RequestStatus.FINISHED, RequestStatus.EOS)
            assert list(toks) == want[1]
            # router only selects live replicas from now on
            for _ in range(3):
                h = rs.submit(prompts[0], max_new_tokens=2)
                assert h.replica.name == survivor.replica.name
                rs.result(h, timeout=120.0)
            # dead-replica health is visible to /healthz consumers
            health = rs.health()
            assert health[victim.replica.name]["alive"] is False
            assert health[victim.replica.name]["error"]
        finally:
            rs.close()

    def test_no_live_replicas_raises(self, model):
        from paddle_tpu.inference.frontend.replica import ReplicaDeadError
        rs = _replica_set(model, n=1)
        try:
            FAULTS.install("frontend.step", FailNth(1))
            h = rs.submit(_prompts(1)[0], max_new_tokens=4)
            _, status = rs.result(h, timeout=120.0)
            assert status.value == "failed"
            with pytest.raises(ReplicaDeadError):
                rs.submit(_prompts(1)[0], max_new_tokens=4)
        finally:
            rs.close()

    def test_submit_fault_point_fires(self, model):
        from paddle_tpu.testing import InjectedFault
        rs = _replica_set(model, n=1)
        try:
            FAULTS.install("frontend.route", FailNth(1))
            with pytest.raises(InjectedFault):
                rs.submit(_prompts(1)[0], max_new_tokens=4)
            FAULTS.reset()
            FAULTS.install("frontend.submit", FailNth(1),
                           match=lambda ctx: ctx.get("replica") == "r0")
            with pytest.raises(InjectedFault):
                rs.submit(_prompts(1)[0], max_new_tokens=4)
            FAULTS.reset()
            h = rs.submit(_prompts(1)[0], max_new_tokens=4)  # healthy again
            _, status = rs.result(h, timeout=120.0)
            assert status.value in ("finished", "eos")
        finally:
            rs.close()


class TestLoadgenEndToEnd:
    def test_closed_loop_summary(self, model):
        trace = make_trace(5, 6, groups=2, prefix_pages=1, page_size=8,
                           suffix_tokens=2, max_new_tokens=3)
        rs = _replica_set(model, n=2)
        try:
            records, wall = run_closed_loop(rs, trace, concurrency=3)
        finally:
            rs.close()
        assert all(r is not None for r in records)
        s = summarize(records, wall)
        assert s["requests"] == 6 and s["shed"] == 0 and s["failed"] == 0
        assert s["total_tokens"] == 18
        assert s["tokens_per_s"] > 0
        assert s["ttft_p50_s"] is not None and s["ttft_p95_s"] is not None
        assert s["ttft_p95_s"] >= s["ttft_p50_s"]
