"""Round-2 partial-row fills: SpectralNorm, static Executor feed/fetch,
Model inference export, profiler result round-trip."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn

rng = np.random.RandomState(0)


class TestSpectralNorm:
    def test_normalizes_leading_singular_value(self):
        paddle.seed(0)
        sn = nn.SpectralNorm([6, 4], dim=0, power_iters=20)
        w = rng.randn(6, 4).astype(np.float32)
        out = sn(paddle.to_tensor(w)).numpy()
        sigma = np.linalg.svd(w, compute_uv=False)[0]
        np.testing.assert_allclose(np.linalg.svd(out, compute_uv=False)[0],
                                   1.0, rtol=1e-3)
        np.testing.assert_allclose(out, w / sigma, rtol=1e-3, atol=1e-4)

    def test_power_iteration_warms_up_buffers(self):
        paddle.seed(1)
        sn = nn.SpectralNorm([5, 3], power_iters=1)
        w = paddle.to_tensor(rng.randn(5, 3).astype(np.float32))
        u0 = sn.weight_u.numpy().copy()
        for _ in range(30):   # u/v persist, so repeated calls converge
            out = sn(w)
        assert not np.allclose(sn.weight_u.numpy(), u0)
        sigma = np.linalg.svd(w.numpy(), compute_uv=False)[0]
        np.testing.assert_allclose(
            np.linalg.svd(out.numpy(), compute_uv=False)[0] * sigma,
            sigma, rtol=1e-3)

    def test_conv_weight_4d(self):
        paddle.seed(2)
        sn = nn.SpectralNorm([8, 3, 3, 3], dim=0, power_iters=15)
        w = rng.randn(8, 3, 3, 3).astype(np.float32)
        out = sn(paddle.to_tensor(w)).numpy()
        m = w.reshape(8, -1)
        sigma = np.linalg.svd(m, compute_uv=False)[0]
        np.testing.assert_allclose(out, w / sigma, rtol=1e-3, atol=1e-4)

    def test_gradient_flows(self):
        sn = nn.SpectralNorm([4, 4], power_iters=5)
        w = paddle.to_tensor(rng.randn(4, 4).astype(np.float32),
                             stop_gradient=False)
        sn(w).sum().backward()
        assert w.grad is not None and np.isfinite(w.grad.numpy()).all()


class TestStaticExecutor:
    def test_feed_fetch_replay(self):
        ps = paddle.static
        main = ps.Program()
        with ps.program_guard(main):
            x = ps.data("x", [None, 4], "float32")
            w = paddle.to_tensor(rng.rand(4, 3).astype(np.float32),
                                 stop_gradient=False)
            y = paddle.matmul(x, w)
            z = paddle.nn.functional.relu(y) * 2.0
        exe = ps.Executor()
        exe.run(ps.default_startup_program())
        xv = rng.rand(5, 4).astype(np.float32)
        out, = exe.run(main, feed={"x": xv}, fetch_list=[z])
        want = np.maximum(xv @ w.numpy(), 0) * 2.0
        np.testing.assert_allclose(out, want, rtol=1e-5)
        # run again with different feed — replay, not cached result
        xv2 = rng.rand(2, 4).astype(np.float32)
        out2, = exe.run(main, feed={"x": xv2}, fetch_list=[z])
        np.testing.assert_allclose(out2, np.maximum(xv2 @ w.numpy(), 0) * 2,
                                   rtol=1e-5)

    def test_fetch_intermediate_and_multiple(self):
        ps = paddle.static
        main = ps.Program()
        with ps.program_guard(main):
            a = ps.data("a", [3], "float32")
            b = a + 1.0
            c = b * b
        exe = ps.Executor()
        av = np.array([1.0, 2.0, 3.0], np.float32)
        bv, cv = exe.run(main, feed={"a": av}, fetch_list=[b, c])
        np.testing.assert_allclose(bv, av + 1)
        np.testing.assert_allclose(cv, (av + 1) ** 2)


class TestStaticExecutorRegressions:
    def test_bool_int_ops_replay(self):
        ps = paddle.static
        main = ps.Program()
        with ps.program_guard(main):
            x = ps.data("x", [4], "float32")
            mask = paddle.cast(x > 0, "float32")
        out, = ps.Executor().run(main, feed={"x": np.array(
            [-1, 2, -3, 4], np.float32)}, fetch_list=[mask])
        np.testing.assert_allclose(out, [0, 1, 0, 1])

    def test_missing_feed_raises(self):
        ps = paddle.static
        main = ps.Program()
        with ps.program_guard(main):
            x = ps.data("x", [2], "float32")
            y = x * 2.0
        with pytest.raises(ValueError, match="missing from feed"):
            ps.Executor().run(main, feed={}, fetch_list=[y])

    def test_deep_graph_no_recursion_error(self):
        ps = paddle.static
        main = ps.Program()
        with ps.program_guard(main):
            z = ps.data("z", [2], "float32")
            out = z
            for _ in range(2000):
                out = out + 1.0
        got, = ps.Executor().run(
            main, feed={"z": np.zeros(2, np.float32)}, fetch_list=[out])
        np.testing.assert_allclose(got, [2000.0, 2000.0])


class TestMultiDynamicExport:
    def test_two_dynamic_inputs_share_scope(self, tmp_path):
        from paddle_tpu.jit import InputSpec
        paddle.seed(4)

        class Net(paddle.nn.Layer):
            def __init__(self):
                super().__init__()
                self.lin = paddle.nn.Linear(4, 4)

            def forward(self, a, b):
                return self.lin(a) + b

        net = Net()
        path = str(tmp_path / "two_dyn")
        paddle.jit.save(net, path, input_spec=[
            InputSpec([None, 4], "float32", "a"),
            InputSpec([None, 4], "float32", "b")])
        loaded = paddle.jit.load(path)
        for batch in (2, 5):
            av = rng.rand(batch, 4).astype(np.float32)
            bv = rng.rand(batch, 4).astype(np.float32)
            got = loaded(paddle.to_tensor(av), paddle.to_tensor(bv)).numpy()
            want = net(paddle.to_tensor(av), paddle.to_tensor(bv)).numpy()
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


class TestModelExport:
    def test_inference_export_roundtrip(self, tmp_path):
        from paddle_tpu.jit import InputSpec
        paddle.seed(3)
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        m = paddle.Model(net, inputs=[InputSpec([None, 4], "float32", "x")])
        path = str(tmp_path / "infer")
        m.save(path, training=False)
        loaded = paddle.jit.load(path)
        xv = rng.rand(3, 4).astype(np.float32)
        got = loaded(paddle.to_tensor(xv)).numpy()
        want = net(paddle.to_tensor(xv)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_export_without_specs_raises(self):
        m = paddle.Model(nn.Linear(2, 2))
        with pytest.raises(ValueError, match="input specs"):
            m.save("/tmp/x", training=False)


class TestProfilerRoundtrip:
    def test_export_and_load(self, tmp_path):
        import paddle_tpu.profiler as prof
        p = prof.Profiler(timer_only=True)
        p.start()
        with prof.RecordEvent("my_region"):
            _ = (paddle.to_tensor(np.ones(4, np.float32)) * 2).numpy()
        p.step()
        p.step()
        p.stop()
        path = str(tmp_path / "trace.json")
        assert p.export(path) == path
        res = prof.load_profiler_result(path)
        summ = res.time_range_summary()
        assert "my_region" in summ
        assert summ["my_region"]["calls"] >= 1
        assert any(e["cat"] == "step" for e in res.events)


class TestHapiCallbacks:
    def test_callbacks_fire_and_early_stop(self, tmp_path):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi.callbacks import (Callback, EarlyStopping,
                                               ModelCheckpoint)
        from paddle_tpu.io import TensorDataset

        seen = []

        class Spy(Callback):
            def on_epoch_begin(self, epoch, logs=None):
                seen.append(("begin", epoch))

            def on_train_batch_end(self, step, logs=None):
                seen.append(("batch", step, logs["loss"]))

            def on_epoch_end(self, epoch, logs=None):
                seen.append(("end", epoch, logs["loss"]))

        paddle.seed(0)
        x = rng.rand(8, 4).astype(np.float32)
        yv = rng.rand(8, 1).astype(np.float32)
        ds = TensorDataset([paddle.to_tensor(x), paddle.to_tensor(yv)])
        m = paddle.Model(nn.Linear(4, 1))
        m.prepare(optimizer=paddle.optimizer.SGD(
            learning_rate=1e9, parameters=m.parameters()),   # diverges
            loss=nn.MSELoss())
        stopper = EarlyStopping(monitor="loss", mode="min", patience=0,
                                verbose=0)
        m.fit(ds, batch_size=4, epochs=10, verbose=0,
              callbacks=[Spy(), stopper,
                         ModelCheckpoint(save_dir=str(tmp_path))])
        assert any(e[0] == "begin" for e in seen)
        assert any(e[0] == "batch" for e in seen)
        epochs_run = max(e[1] for e in seen if e[0] == "end") + 1
        assert epochs_run < 10            # early stopping fired
        assert (tmp_path / "0.pdparams").exists()


class TestLowPrecisionAudit:
    def test_audit_records_low_precision_ops(self):
        import paddle_tpu.amp as amp
        import paddle_tpu.nn as nn
        paddle.set_flags({"FLAGS_low_precision_op_list": 1})
        amp.clear_low_precision_op_list()
        try:
            lin = nn.Linear(4, 4)
            x = paddle.to_tensor(rng.rand(2, 4).astype(np.float32))
            with amp.auto_cast(level="O1"):
                lin(x)
            ops_seen = amp.low_precision_op_list()
            assert any("linear" in k or "matmul" in k for k in ops_seen), \
                ops_seen
        finally:
            paddle.set_flags({"FLAGS_low_precision_op_list": 0})
            amp.clear_low_precision_op_list()
