"""paddle.quantization tests: fake-quant STE numerics, QAT training,
PTQ calibrate+convert, weight-only int8/int4 serving path."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu import quantization as Q

rng = np.random.RandomState(0)


class TestFakeQuant:
    def test_forward_matches_numpy(self):
        x = rng.randn(16).astype(np.float32)
        scale, qmax = 2.0, 127.0
        got = np.asarray(Q.fake_quant(x, scale, qmax))
        want = np.clip(np.round(x / scale * qmax), -qmax, qmax) / qmax * scale
        np.testing.assert_allclose(got, want, rtol=1e-6)

    def test_ste_gradient_clips_out_of_range(self):
        import jax
        import jax.numpy as jnp
        x = jnp.asarray([0.5, 3.0, -0.2, -5.0], jnp.float32)
        g = jax.grad(lambda a: Q.fake_quant(a, 1.0, 127.0).sum())(x)
        np.testing.assert_allclose(np.asarray(g), [1, 0, 1, 0], atol=1e-6)

    def test_quanter_layer_updates_ema_scale(self):
        qt = Q.FakeQuanterWithAbsMaxObserverLayer(moving_rate=0.5)
        x = paddle.to_tensor(np.array([1.0, -4.0], np.float32))
        qt(x)
        s1 = float(qt.scales())
        assert s1 > 0
        qt(paddle.to_tensor(np.array([8.0, 0.0], np.float32)))
        assert float(qt.scales()) > s1
        qt.eval()
        s_frozen = float(qt.scales())
        qt(paddle.to_tensor(np.array([100.0], np.float32)))
        assert float(qt.scales()) == s_frozen


class TestQAT:
    def _model(self):
        paddle.seed(3)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    def test_quantize_replaces_linears(self):
        model = self._model()
        cfg = Q.QuantConfig(activation=Q.FakeQuanterWithAbsMaxObserver(),
                            weight=Q.FakeQuanterWithAbsMaxObserver())
        qat = Q.QAT(cfg)
        qmodel = qat.quantize(model)
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("QuantedLinear") == 2
        # original model untouched (inplace=False)
        assert all(type(l).__name__ != "QuantedLinear"
                   for l in model.sublayers())

    def test_qat_trains_and_tracks_float(self):
        model = self._model()
        cfg = Q.QuantConfig(activation=Q.FakeQuanterWithAbsMaxObserver(),
                            weight=Q.FakeQuanterWithAbsMaxObserver())
        qmodel = Q.QAT(cfg).quantize(model)
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=qmodel.parameters())
        x = rng.rand(32, 8).astype(np.float32)
        w = rng.rand(8, 4).astype(np.float32)
        y = x @ w
        losses = []
        for _ in range(40):
            pred = qmodel(paddle.to_tensor(x))
            loss = ((pred - paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.3, (losses[0], losses[-1])

    def test_convert_produces_int8_close_outputs(self):
        model = self._model()
        cfg = Q.QuantConfig(activation=None,
                            weight=Q.FakeQuanterWithAbsMaxObserver())
        qmodel = Q.QAT(cfg).quantize(model)
        x = paddle.to_tensor(rng.rand(4, 8).astype(np.float32))
        qmodel(x)  # populate scales
        infer = Q.QAT(cfg).convert(qmodel)
        kinds = [type(l).__name__ for l in infer.sublayers()]
        assert kinds.count("QuantizedLinearInfer") == 2
        import jax.numpy as jnp
        for l in infer.sublayers():
            if type(l).__name__ == "QuantizedLinearInfer":
                assert l.qweight._data.dtype == jnp.int8
        ref = model(x).numpy()
        got = infer(x).numpy()
        err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err


class TestPTQ:
    def test_calibrate_then_convert(self):
        paddle.seed(4)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
        cfg = Q.QuantConfig(activation=Q.AbsmaxObserver(),
                            weight=Q.PerChannelAbsmaxObserver(quant_axis=1))
        ptq = Q.PTQ(cfg)
        calib = ptq.quantize(model)
        for _ in range(4):
            calib(paddle.to_tensor(rng.rand(16, 8).astype(np.float32)))
        infer = ptq.convert(calib)
        kinds = [type(l).__name__ for l in infer.sublayers()]
        assert kinds.count("QuantizedLinearInfer") == 2
        x = paddle.to_tensor(rng.rand(4, 8).astype(np.float32))
        ref = model(x).numpy()
        got = infer(x).numpy()
        err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err

    def test_hist_and_kl_observers(self):
        data = rng.randn(4096).astype(np.float32)
        data[0] = 50.0  # outlier the percentile threshold should ignore
        h = Q.HistObserverLayer(percentile=0.999)
        h(paddle.to_tensor(data))
        s = float(h.scales())
        assert 2.0 < s < 10.0, s
        k = Q.KLObserverLayer()
        k(paddle.to_tensor(data))
        sk = float(k.scales())
        assert 1.0 < sk < 51.0, sk


class TestWeightOnly:
    def test_int8_roundtrip_and_linear(self):
        w = rng.randn(32, 16).astype(np.float32)
        qw, s = Q.weight_quantize(paddle.to_tensor(w))
        import jax.numpy as jnp
        assert qw._data.dtype == jnp.int8
        wd = Q.weight_dequantize(qw, s).numpy()
        assert np.abs(wd - w).max() < np.abs(w).max() / 100
        x = rng.randn(4, 32).astype(np.float32)
        y = Q.weight_only_linear(paddle.to_tensor(x), qw,
                                 weight_scale=s).numpy()
        rel = np.abs(y - x @ w).max() / (np.abs(x @ w).max() + 1e-9)
        assert rel < 0.02, rel

    def test_int4_pack_roundtrip(self):
        w = rng.randn(32, 8).astype(np.float32)
        qw, s = Q.weight_quantize(paddle.to_tensor(w),
                                  algo="weight_only_int4")
        assert qw.shape == [16, 8]  # two nibbles per byte
        wd = Q.weight_dequantize(qw, s, algo="weight_only_int4").numpy()
        assert wd.shape == (32, 8)
        rel = np.abs(wd - w).max() / np.abs(w).max()
        assert rel < 0.2, rel
        x = rng.randn(4, 32).astype(np.float32)
        y = Q.weight_only_linear(paddle.to_tensor(x), qw, weight_scale=s,
                                 weight_dtype="int4").numpy()
        # exact vs the dequantized weights (packing correctness) ...
        np.testing.assert_allclose(y, x @ wd, rtol=1e-4, atol=1e-4)
        # ... and loosely tracks the float weights (4-bit quant loss)
        rel = np.abs(y - x @ w).max() / (np.abs(x @ w).max() + 1e-9)
        assert rel < 0.2, rel

    def test_nn_quant_namespace(self):
        from paddle_tpu.nn.quant import weight_only_linear as wol
        assert wol is Q.weight_only_linear


class TestReviewRegressions:
    def test_name_config_selects_layers(self):
        paddle.seed(6)
        model = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 2))
        cfg = Q.QuantConfig()
        cfg.add_name_config("0", weight=Q.FakeQuanterWithAbsMaxObserver())
        qmodel = Q.QAT(cfg).quantize(model)
        kinds = [type(l).__name__ for l in qmodel.sublayers()]
        assert kinds.count("QuantedLinear") == 1, kinds

    def test_channelwise_qat_capture_then_convert(self):
        paddle.seed(7)
        model = nn.Sequential(nn.Linear(8, 4))
        cfg = Q.QuantConfig(activation=None,
                            weight=Q.FakeQuanterChannelWiseAbsMax(
                                quant_axis=1))
        qmodel = Q.QAT(cfg).quantize(model)
        opt = paddle.optimizer.SGD(learning_rate=1e-2,
                                   parameters=qmodel.parameters())

        def step(x, y):
            loss = ((qmodel(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        sstep = paddle.jit.to_static(step)
        x = paddle.to_tensor(rng.rand(4, 8).astype(np.float32))
        y = paddle.to_tensor(rng.rand(4, 4).astype(np.float32))
        sstep(x, y)
        sstep(x, y)  # compiled replay — must not leak tracers into scales
        infer = Q.QAT(cfg).convert(qmodel)
        kinds = [type(l).__name__ for l in infer.sublayers()]
        assert kinds.count("QuantizedLinearInfer") == 1
        ref = qmodel(x).numpy()
        np.testing.assert_allclose(infer(x).numpy(), ref, rtol=1e-2,
                                   atol=1e-2)

    def test_wrong_axis_per_channel_scales_raise(self):
        with pytest.raises(ValueError, match="OUTPUT channel"):
            Q.QuantizedLinearInfer.from_float(
                paddle.to_tensor(rng.rand(4, 8).astype(np.float32)), None,
                paddle.to_tensor(np.ones(4, np.float32)))  # in-axis scales

    def test_conv2d_ptq_converts_to_int8(self):
        paddle.seed(8)
        model = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU())
        cfg = Q.QuantConfig(activation=Q.AbsmaxObserver(),
                            weight=Q.PerChannelAbsmaxObserver(quant_axis=0))
        ptq = Q.PTQ(cfg)
        calib = ptq.quantize(model)
        x = paddle.to_tensor(rng.rand(2, 3, 8, 8).astype(np.float32))
        calib(x)
        infer = ptq.convert(calib)
        kinds = [type(l).__name__ for l in infer.sublayers()]
        assert kinds.count("QuantizedConv2DInfer") == 1, kinds
        import jax.numpy as jnp
        for l in infer.sublayers():
            if type(l).__name__ == "QuantizedConv2DInfer":
                assert l.qweight._data.dtype == jnp.int8
        ref = model(x).numpy()
        got = infer(x).numpy()
        err = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
        assert err < 0.05, err

    def test_hist_observer_memory_is_bounded(self):
        h = Q.HistObserverLayer(bins=64)
        for i in range(5):
            h(paddle.to_tensor((rng.rand(1000) * (i + 1)).astype(np.float32)))
        assert h._hist.shape == (64,)
        assert abs(h._hist.sum() - 5000) < 1.0  # re-binning conserves mass
        s = float(h.scales())
        assert 3.0 < s <= 5.0, s


class TestQATCapture:
    def test_qat_step_captures_to_static(self):
        """The whole QAT train step (fake-quant + EMA scale updates) must
        compile into one program via to_static and keep updating scales."""
        paddle.seed(5)
        model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
        cfg = Q.QuantConfig(activation=Q.FakeQuanterWithAbsMaxObserver(),
                            weight=Q.FakeQuanterWithAbsMaxObserver())
        qmodel = Q.QAT(cfg).quantize(model)
        opt = paddle.optimizer.SGD(learning_rate=1e-2,
                                   parameters=qmodel.parameters())

        def step(x, y):
            loss = ((qmodel(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        sstep = paddle.jit.to_static(step)
        x = paddle.to_tensor(rng.rand(8, 8).astype(np.float32))
        y = paddle.to_tensor(rng.rand(8, 2).astype(np.float32))
        l0 = float(sstep(x, y))
        quanters = [l for l in qmodel.sublayers()
                    if type(l).__name__ == "FakeQuanterWithAbsMaxObserverLayer"]
        assert quanters
        s_before = [float(q.scales()) for q in quanters]
        for _ in range(3):
            l1 = float(sstep(x, y))
        s_after = [float(q.scales()) for q in quanters]
        assert l1 < l0
        assert any(a != b for a, b in zip(s_before, s_after))


class TestQuantMatmulKernel:
    """Pallas weight-only matmul (VERDICT r2 #4): in-kernel tile dequant,
    numerics vs the XLA dequant reference for int8 and packed int4."""

    def _data(self, M=8, K=256, N=256, seed=0):
        rng = np.random.RandomState(seed)
        x = jnp.asarray(rng.randn(M, K).astype(np.float32) * 0.3)
        w = rng.randn(K, N).astype(np.float32) * 0.1
        return x, w

    @pytest.mark.parametrize("algo", ["weight_only_int8", "weight_only_int4"])
    def test_kernel_matches_dequant_reference(self, algo):
        import paddle_tpu as pt
        from paddle_tpu.quantization.weight_only import (weight_quantize,
                                                         weight_dequantize)
        from paddle_tpu.ops.pallas.quant_matmul import quant_matmul
        x, w = self._data()
        qw, s = weight_quantize(pt.to_tensor(w), algo=algo)
        int4 = algo.endswith("int4")
        y = quant_matmul(x, jnp.asarray(np.asarray(qw.numpy())),
                         jnp.asarray(np.asarray(s.numpy())), int4=int4)
        wd = np.asarray(weight_dequantize(qw, s, algo=algo).numpy())
        ref = np.asarray(x) @ wd
        np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-2, atol=2e-2)

    def test_tiny_m_padding(self):
        import paddle_tpu as pt
        from paddle_tpu.quantization.weight_only import weight_quantize
        from paddle_tpu.ops.pallas.quant_matmul import quant_matmul
        x, w = self._data(M=1)
        qw, s = weight_quantize(pt.to_tensor(w))
        y = quant_matmul(x, jnp.asarray(np.asarray(qw.numpy())),
                         jnp.asarray(np.asarray(s.numpy())))
        assert y.shape == (1, 256)
