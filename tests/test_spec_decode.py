"""Speculative decoding (ISSUE 6 tentpole): self-drafting n-gram / draft-model
proposals verified by ONE multi-query target forward, with paged-KV rollback
of rejected drafts. Correctness bar everywhere: token-identical output vs a
spec-off engine for greedy and fixed-seed sampled requests.

The tiny 2-layer model is module-shared (engine builds compile programs);
tests needing special page geometry build their own engines."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.inference.serving import (LLMEngine, SpecConfig,
                                          _NgramProposer)


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


def _engine(model, spec, **kw):
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_len", 96)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(model, spec_decode=spec, **kw)


_RNG = np.random.default_rng(1)
_PAT = _RNG.integers(5, 120, size=6).tolist()
# mixed lengths: repeated structure (n-gram hits), short random, mixed tail
_PROMPTS = [_PAT * 4,
            _RNG.integers(5, 120, size=11).tolist(),
            _PAT * 2 + [7, 9],
            _RNG.integers(5, 120, size=3).tolist()]


def _serve(eng, prompts, **req_kw):
    req_kw.setdefault("max_new_tokens", 20)
    rids = [eng.add_request(p, **req_kw) for p in prompts]
    eng.run_until_done()
    return [eng.result(rid) for rid in rids]


def _check_page_accounting(eng):
    """Pool conservation + per-slot allocation exactly covers each length."""
    alloc = sum(int(eng._n_alloc[s]) for s in range(eng.max_batch))
    assert alloc + len(eng._free_pages) + len(eng._lru) == eng.n_pages - 1
    for s, r in enumerate(eng._slots):
        if r is None:
            continue
        lens = int(eng._lens[s])
        assert int(eng._n_alloc[s]) >= max(1, -(-lens // eng.page))


# ---------------------------------------------------------------- the kernel

class TestMultiQueryKernel:
    def _setup(self, seed=0, B=2, P=9, page=8, KVH=2, H=4, D=16, S=4, Q=3,
               ctx=(13, 22)):
        import jax.numpy as jnp
        rng = np.random.default_rng(seed)
        k_pages = jnp.asarray(rng.standard_normal((P, page, KVH, D)),
                              jnp.float32)
        v_pages = jnp.asarray(rng.standard_normal((P, page, KVH, D)),
                              jnp.float32)
        bt = jnp.asarray(rng.permutation(P - 1)[:B * S].reshape(B, S),
                         jnp.int32)
        cl = jnp.asarray(list(ctx), jnp.int32)
        q = jnp.asarray(rng.standard_normal((B, Q, H, D)), jnp.float32)
        return q, k_pages, v_pages, bt, cl

    def test_kernel_matches_ref(self):
        from paddle_tpu.ops.pallas.paged_attention import (
            paged_attention_multiquery, paged_attention_multiquery_ref)
        args = self._setup()
        out = np.asarray(paged_attention_multiquery(*args))
        ref = np.asarray(paged_attention_multiquery_ref(*args))
        np.testing.assert_allclose(out, ref, atol=2e-3, rtol=2e-3)

    def test_rows_match_single_query_ref(self):
        """Row j of the multi-query ref == the single-query ref at ctx+j —
        the causal-horizon contract verification relies on."""
        from paddle_tpu.ops.pallas.paged_attention import (
            paged_attention_multiquery_ref, paged_attention_ref)
        q, kp, vp, bt, cl = self._setup()
        out = np.asarray(paged_attention_multiquery_ref(q, kp, vp, bt, cl))
        for j in range(q.shape[1]):
            single = np.asarray(
                paged_attention_ref(q[:, j], kp, vp, bt, cl + j))
            np.testing.assert_allclose(out[:, j], single, atol=1e-5,
                                       rtol=1e-5)

    def test_int8_path(self):
        from paddle_tpu.ops.pallas.paged_attention import (
            paged_attention_multiquery, paged_attention_multiquery_ref,
            quantize_kv)
        q, kp, vp, bt, cl = self._setup()
        kq, ks = quantize_kv(kp)
        vq, vs = quantize_kv(vp)
        out = np.asarray(paged_attention_multiquery(
            q, kq, vq, bt, cl, k_scales=ks, v_scales=vs))
        ref = np.asarray(paged_attention_multiquery_ref(q, kp, vp, bt, cl))
        assert np.max(np.abs(out - ref)) < 0.05


# -------------------------------------------------------------- the proposer

class TestNgramProposer:
    def test_suffix_match_proposes_continuation(self):
        p = _NgramProposer(SpecConfig(max_draft=4, ngram_max=3))
        #          match <1,2> at idx 1 -> propose what followed: 3, 4, 5
        toks = [9, 1, 2, 3, 4, 5, 1, 2]
        assert p.propose(toks, 3) == [3, 4, 5]

    def test_longest_ngram_wins(self):
        p = _NgramProposer(SpecConfig(max_draft=4, ngram_max=3))
        # suffix <1,2,3> matches at 0 (-> 7), suffix <3> alone also at 5
        toks = [1, 2, 3, 7, 8, 3, 9, 1, 2, 3]
        assert p.propose(toks, 2) == [7, 8]

    def test_no_match_returns_empty(self):
        p = _NgramProposer(SpecConfig())
        assert p.propose([1, 2, 3, 4], 4) == []
        assert p.propose([5], 4) == []


# ----------------------------------------------------------------- parity

class TestSpecParity:
    def test_greedy_parity_mixed_prompts(self, model):
        base = _serve(_engine(model, None), _PROMPTS)
        eng = _engine(model, SpecConfig(max_draft=4))
        out = _serve(eng, _PROMPTS)
        assert out == base
        # the repeated-structure workload must actually speculate
        st = eng.spec_stats()
        assert st["proposed"] > 0 and st["accepted"] > 0
        assert st["tokens_per_step"] > 1.0
        assert st["verify_dispatches"] > 0
        _check_page_accounting(eng)

    def test_greedy_parity_one_by_one(self, model):
        for p in _PROMPTS[:2]:
            base = _serve(_engine(model, None, max_batch=1), [p])
            out = _serve(_engine(model, SpecConfig(max_draft=3),
                                 max_batch=1), [p])
            assert out == base

    def test_fixed_seed_sampling_parity(self, model):
        kw = dict(do_sample=True, temperature=0.9, top_p=0.8, seed=17,
                  max_new_tokens=16)
        base = _serve(_engine(model, None), _PROMPTS[:3], **kw)
        out = _serve(_engine(model, SpecConfig(max_draft=4)), _PROMPTS[:3],
                     **kw)
        assert out == base

    def test_seedless_sampling_smoke(self, model):
        """Seedless draws consume the global seed counter per dispatch, so
        exact parity is impossible by construction (same caveat as prefix
        caching) — assert the distribution machinery stays sound: correct
        lengths, in-vocab tokens, and drafts actually verified."""
        eng = _engine(model, SpecConfig(max_draft=4))
        out = _serve(eng, _PROMPTS[:2], do_sample=True, temperature=0.8,
                     max_new_tokens=18)
        for o in out:
            assert len(o) == 18
            assert all(0 <= t < model.config.vocab_size for t in o)
        assert eng.spec_stats()["verify_dispatches"] > 0

    def test_eos_mid_verify(self, model):
        """eos landing inside an accepted run stops the request exactly
        where the spec-off engine stops it (later accepted tokens are
        discarded on release)."""
        base = _serve(_engine(model, None, max_batch=1), [_PROMPTS[0]])[0]
        # an eos whose FIRST occurrence is deep enough to sit inside a
        # multi-token accepted run
        eos = next(t for i, t in enumerate(base) if base.index(t) == i >= 4)
        stop = base.index(eos) + 1
        a = _serve(_engine(model, None, max_batch=1), [_PROMPTS[0]],
                   eos_token_id=eos)
        b = _serve(_engine(model, SpecConfig(max_draft=4), max_batch=1),
                   [_PROMPTS[0]], eos_token_id=eos)
        assert a == b
        assert a[0][-1] == eos and len(a[0]) == stop


# ----------------------------------------------------------------- rollback

class TestRollback:
    def test_rollback_across_page_boundaries(self, model):
        """max_draft > page_size forces verify steps whose provisional rows
        span page boundaries; every rejection must hand those pages back."""
        eng = _engine(model, SpecConfig(max_draft=6), page_size=4,
                      max_len=64, max_batch=2)
        rids = [eng.add_request(p[:12], max_new_tokens=24)
                for p in _PROMPTS[:2]]
        while eng._waiting or any(s is not None for s in eng._slots):
            eng.step()
            # after every step: allocation exactly covers the committed
            # length (truncation freed everything past it) and the pool sums
            for s, r in enumerate(eng._slots):
                # mid-prefill slots hold the whole prompt's reservation;
                # the tight bound applies once decode/verify is running
                if r is None or r.pos < len(r.prompt):
                    continue
                lens = int(eng._lens[s])
                assert int(eng._n_alloc[s]) == max(1, -(-lens // 4))
            _check_page_accounting(eng)
        base = _serve(_engine(model, None, page_size=4, max_len=64,
                              max_batch=2),
                      [p[:12] for p in _PROMPTS[:2]], max_new_tokens=24)
        assert [eng.result(r) for r in rids] == base
        assert eng.spec_stats()["proposed"] > 0

    def test_pool_drains_clean_after_spec_serve(self, model):
        eng = _engine(model, SpecConfig(max_draft=4))
        _serve(eng, _PROMPTS)
        assert sum(int(eng._n_alloc[s]) for s in range(eng.max_batch)) == 0
        assert len(eng._free_pages) + len(eng._lru) == eng.n_pages - 1


# ------------------------------------------------------------- prefix cache

class TestSpecWithPrefixCache:
    def test_parity_and_shared_pages_survive_drafts(self, model):
        """Rejected drafts write provisional KV beyond a slot's length; with
        the prefix cache on, those writes must never land in a SHARED page.
        If one did, the third request's cached-prefix serve would return
        corrupted tokens — so exact parity here is the mutation check."""
        prompts = [_PAT * 4, _PAT * 4, (_PAT * 4)[:20]]

        def serve_fresh(spec):
            eng = _engine(model, spec, prefix_cache=True, max_batch=2)
            outs = []
            for p in prompts:      # sequential: later ones hit the cache
                rid = eng.add_request(p, max_new_tokens=16)
                eng.run_until_done()
                outs.append(eng.result(rid))
            return outs, eng

        base, _ = serve_fresh(None)
        out, eng = serve_fresh(SpecConfig(max_draft=4))
        assert out == base
        assert eng.prefix_cache_stats()["hits"] > 0
        assert eng.spec_stats()["accepted"] > 0
        _check_page_accounting(eng)


# -------------------------------------------------------------- draft model

class TestDraftModel:
    def test_self_draft_is_always_accepted(self, model):
        """Using the TARGET model as its own draft model makes every
        proposal the greedy continuation — acceptance must be 100% and the
        output identical to spec-off (generate()/engine parity)."""
        eng = _engine(model, SpecConfig(max_draft=3, draft_model=model),
                      max_batch=1)
        out = _serve(eng, [_PROMPTS[1]], max_new_tokens=12)
        base = _serve(_engine(model, None, max_batch=1), [_PROMPTS[1]],
                      max_new_tokens=12)
        assert out == base
        st = eng.spec_stats()
        assert st["acceptance_rate"] == 1.0
        assert st["proposed"] > 0
        # every verify step lands its full draft+1 run
        assert st["tokens_per_step"] > 2.0


# ------------------------------------------------------------ config/metrics

class TestSpecConfigAndMetrics:
    def test_config_validation(self):
        with pytest.raises(ValueError):
            SpecConfig(max_draft=0)
        with pytest.raises(ValueError):
            SpecConfig(ngram_min=0)
        with pytest.raises(ValueError):
            SpecConfig(ngram_max=1, ngram_min=2)

    def test_spec_off_stats_are_zero(self, model):
        eng = _engine(model, None)
        _serve(eng, _PROMPTS[:1])
        st = eng.spec_stats()
        assert st["proposed"] == st["accepted"] == st["emitted"] == 0
        assert st["verify_dispatches"] == 0 and st["draft_target"] == 0

    def test_registry_mirrors_spec_counters(self, model):
        from paddle_tpu import observability as obs
        obs.reset()
        obs.enable()
        try:
            eng = _engine(model, SpecConfig(max_draft=4))
            _serve(eng, _PROMPTS[:2])
            st = eng.spec_stats()
            m = eng.metrics()
            assert (m["serving_spec_proposed_total"]["series"][0]["value"]
                    == st["proposed"])
            assert (m["serving_spec_accepted_total"]["series"][0]["value"]
                    == st["accepted"])
            hist = m["serving_spec_acceptance_ratio"]["series"][0]
            assert hist["count"] == st["verify_dispatches"]
            kinds = {s["labels"]["kind"]: s["value"]
                     for s in m["serving_dispatches_total"]["series"]}
            assert kinds.get("verify", 0) == st["verify_dispatches"]
        finally:
            obs.disable()
            obs.reset()

    def test_adaptive_cost_model_separate_from_decode_fit(self, model):
        """The verify cost curve must be learned in _spec_samples, never
        leaking into the decode-block auto-fit's samples."""
        eng = _engine(model, SpecConfig(max_draft=4), decode_block="auto")
        n_decode_dispatch = 0
        rids = [eng.add_request(p, max_new_tokens=20) for p in _PROMPTS]
        while eng._waiting or any(s is not None for s in eng._slots):
            before = eng.spec_dispatches
            eng.step()
            if eng.spec_dispatches == before:
                n_decode_dispatch += 1   # prefill or plain decode step
        assert eng._spec_samples            # verify steps were sampled
        # decode-block fit only ever saw plain decode dispatches: with every
        # decode step recorded at most once, sample counts can't exceed them
        assert sum(len(v) for v in eng._block_samples.values()) \
            <= n_decode_dispatch
        # spec stats expose the adapted target
        assert 1 <= eng.spec_stats()["draft_target"] <= 4
        assert all(eng.result(r) for r in rids)
