"""SOT-style stitched graph breaks (VERDICT r4 missing #1): float()/.numpy()
inside a captured step must NOT de-compile the signature — the step stays one
fused program, and the python around the break observes true per-call values
via the echo pass (reference analog: sot/translate.py:31 subgraph stitching).
"""
import logging

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def _model_and_opt(seed=0, lr=0.05):
    paddle.seed(seed)
    m = nn.Linear(8, 4)
    opt = paddle.optimizer.AdamW(learning_rate=lr, parameters=m.parameters())
    return m, opt


def _data(seed=0, n=6):
    rng = np.random.RandomState(seed)
    return [(paddle.to_tensor(rng.rand(16, 8).astype(np.float32)),
             paddle.to_tensor(rng.rand(16, 4).astype(np.float32)))
            for _ in range(n)]


class TestFloatBreakStitching:
    def test_float_loss_metric_hook_stays_compiled(self):
        """The exact idiom from the VERDICT: float(loss) in a metric callback.
        Losses must match eager, the metric list must hold TRUE per-call
        values in steady state, and the compiled program must run every call.
        (Capture passes — spy/trace — re-run the python with capture-time
        values, like any trace-based capture; steady state is one echo per
        call with the true value.)"""
        metrics = []
        m, opt = _model_and_opt()

        def train_step(x, y):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            metrics.append(float(loss))      # the break
            return loss

        step = paddle.jit.to_static(train_step)
        data = _data()
        losses = [float(np.asarray(step(x, y)._data)) for x, y in data]

        # eager twin for parity
        m2, opt2 = _model_and_opt()
        ref = []
        for x, y in data:
            loss = ((m2(x) - y) ** 2).mean()
            loss.backward()
            opt2.step()
            opt2.clear_grad()
            ref.append(float(loss))
        np.testing.assert_allclose(losses, ref, rtol=2e-5, atol=2e-5)
        # steady state (after the capture calls): the metric hook observed
        # the true value of every call, not the spy-time constant
        np.testing.assert_allclose(metrics[-4:], ref[-4:],
                                   rtol=2e-5, atol=2e-5)
        assert len(set(np.round(metrics, 6))) > 1   # values actually change

        group = next(iter(step._cache.values()))
        assert not group.eager_only
        entry = group.variants[0]
        assert entry.compiled is not None
        assert entry.break_kinds == ("float",)
        assert len(entry.op_tape) > 0
        # steady-state: exactly one append per call
        n = len(metrics)
        step(*data[0])
        assert len(metrics) == n + 1

    def test_compiled_program_runs_every_call(self):
        m, opt = _model_and_opt()
        seen = []

        def train_step(x, y):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            seen.append(float(loss))
            return loss

        step = paddle.jit.to_static(train_step)
        data = _data()
        step(*data[0])                       # spy
        step(*data[1])                       # first compiled call
        group = next(iter(step._cache.values()))
        entry = group.variants[0]
        calls = []
        orig = entry.compiled
        entry.compiled = lambda *a: (calls.append(1), orig(*a))[1]
        step(*data[2])
        step(*data[3])
        assert len(calls) == 2               # compile-count hook: both calls
        assert not group.eager_only          # ...ran the compiled program

    def test_numpy_break(self):
        m, opt = _model_and_opt()
        grabbed = []

        def train_step(x, y):
            pred = m(x)
            loss = ((pred - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            grabbed.append(pred.numpy().copy())   # full-array break
            return loss

        step = paddle.jit.to_static(train_step)
        data = _data()
        for x, y in data[:2]:                 # capture warmup
            step(x, y)
        grabbed.clear()
        for x, y in data[2:5]:                # steady state
            step(x, y)
        group = next(iter(step._cache.values()))
        assert not group.eager_only
        assert group.variants[0].break_kinds == ("numpy",)
        assert len(grabbed) == 3 and grabbed[0].shape == (16, 4)
        # weights move every step, so consecutive grabbed preds must differ
        assert not np.allclose(grabbed[1], grabbed[2])

    def test_fstring_logging_break(self):
        m, opt = _model_and_opt()
        lines = []

        def train_step(x, y):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            lines.append(f"loss={loss:.6f}")     # __format__ break
            return loss

        step = paddle.jit.to_static(train_step)
        data = _data()
        step(*data[0])                        # capture warmup
        step(*data[1])
        lines.clear()
        losses = [float(np.asarray(step(x, y)._data)) for x, y in data[2:5]]
        group = next(iter(step._cache.values()))
        assert not group.eager_only
        assert lines == [f"loss={v:.6f}" for v in np.float32(losses)]

    def test_break_plus_guard_coexist(self):
        m, opt = _model_and_opt()
        metrics = []

        def train_step(x, y, flag):
            loss = ((m(x) - y) ** 2).mean()
            if bool(flag):                        # int/bool value guard
                loss = loss * 2.0
            loss.backward()
            opt.step()
            opt.clear_grad()
            metrics.append(float(loss))          # stitched break
            return loss

        step = paddle.jit.to_static(train_step)
        data = _data()
        t = paddle.to_tensor(np.array(1, np.int32))
        f = paddle.to_tensor(np.array(0, np.int32))
        step(data[0][0], data[0][1], t)          # capture warmup
        step(data[1][0], data[1][1], t)
        metrics.clear()
        l2 = float(np.asarray(step(data[2][0], data[2][1], t)._data))
        assert metrics[-1] == pytest.approx(l2, rel=1e-6)
        l3 = float(np.asarray(step(data[3][0], data[3][1], f)._data))  # guard
        group = next(iter(step._cache.values()))
        assert not group.eager_only
        assert len(group.variants) == 2          # one per guard branch
        assert metrics[-1] == pytest.approx(l3, rel=1e-6)

    def test_op_divergence_on_break_value_falls_back_loudly(self, caplog):
        """Tensor ops conditioned on a float() break value cannot be stitched:
        the echo pass detects the tape divergence BEFORE committing state,
        the call runs eagerly (correct numbers), and the signature pins
        eager-only with a warning — never silently wrong."""
        m, opt = _model_and_opt()

        losses = []

        def train_step(x, y, thresh):
            loss = ((m(x) - y) ** 2).mean()
            if float(loss) > thresh:             # break value drives op flow
                loss = loss * 2.0                # extra op on one path only
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
            return loss

        step = paddle.jit.to_static(train_step)
        data = _data()
        # ...train until the loss crosses the threshold: the echo pass must
        # catch the branch flip and fall back
        with caplog.at_level(logging.WARNING, logger="paddle_tpu.jit"):
            vals = []
            for i in range(30):
                x, y = data[i % len(data)]
                vals.append(float(np.asarray(step(x, y, 0.5)._data)))
        group = next(iter(step._cache.values()))
        assert group.eager_only          # pinned, not silently wrong
        assert any("eager" in r.message for r in caplog.records)
        # eager twin parity across the WHOLE trajectory (incl. the fallback
        # call): state was never corrupted by a half-committed step
        m2, opt2 = _model_and_opt()
        ref = []
        for i in range(30):
            x, y = data[i % len(data)]
            loss = ((m2(x) - y) ** 2).mean()
            if float(loss) > 0.5:
                loss = loss * 2.0
            loss.backward()
            opt2.step()
            opt2.clear_grad()
            ref.append(float(loss))
        np.testing.assert_allclose(vals, ref, rtol=2e-4, atol=2e-5)

    def test_scan_steps_rejects_breaks_eagerly(self):
        m, opt = _model_and_opt()
        metrics = []

        def train_step(x, y):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            metrics.append(float(loss))
            return loss

        step = paddle.jit.scan_steps(train_step)
        rng = np.random.RandomState(0)
        xs = paddle.to_tensor(rng.rand(3, 16, 8).astype(np.float32))
        ys = paddle.to_tensor(rng.rand(3, 16, 4).astype(np.float32))
        out = step(xs, ys)                      # falls back to eager loop
        out2 = step(xs, ys)
        group = next(iter(step._cache.values()))
        assert group.eager_only                  # documented restriction
        assert len(metrics) == 6                 # but all steps really ran


class TestMultipleBreaks:
    def test_two_floats_and_numpy_in_order(self):
        """Several breaks per step: values arrive in program order, every
        call, with the step still compiled."""
        m, opt = _model_and_opt()
        seen = []

        def train_step(x, y):
            pred = m(x)
            loss = ((pred - y) ** 2).mean()
            pre = float(loss)                  # break 1 (pre-update loss)
            loss.backward()
            opt.step()
            opt.clear_grad()
            seen.append((pre, pred.numpy().mean(), float(loss)))  # 2, 3
            return loss

        step = paddle.jit.to_static(train_step)
        data = _data()
        step(*data[0])                         # capture warmup
        step(*data[1])
        seen.clear()
        vals = [float(np.asarray(step(x, y)._data)) for x, y in data[2:5]]
        group = next(iter(step._cache.values()))
        assert not group.eager_only
        assert group.variants[0].break_kinds == ("float", "numpy", "float")
        assert len(seen) == 3
        for (pre, pmean, post), v in zip(seen, vals):
            assert pre == pytest.approx(v, rel=1e-5)   # same tensor read 2x
            assert post == pytest.approx(v, rel=1e-5)
            assert np.isfinite(pmean)
        # distinct calls observed distinct values
        assert seen[0][0] != seen[1][0]


class TestEchoPlaceholders:
    def test_smuggled_tensor_raises_clearly_post_echo(self):
        """A Tensor appended to a list inside the step and read AFTER the
        call is an echo-pass placeholder (its buffer is a ShapeDtypeStruct,
        not data). The host read must raise a pointed error, not an opaque
        numpy failure (ADVICE r5)."""
        m, opt = _model_and_opt()
        kept = []

        def train_step(x, y):
            loss = ((m(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            float(loss)                       # stitched break: fine
            kept.append(loss)                 # placeholder smuggled out
            return loss

        step = paddle.jit.to_static(train_step)
        data = _data()
        for x, y in data[:3]:
            step(x, y)
        group = next(iter(step._cache.values()))
        assert not group.eager_only           # the smuggle alone can't pin
        for fn in (lambda t: float(t), lambda t: t.numpy(),
                   lambda t: t.item(), lambda t: int(t)):
            with pytest.raises(RuntimeError, match="placeholder"):
                fn(kept[-1])
        # the error points the user at the stitching scheme docs
        with pytest.raises(RuntimeError, match="to_static"):
            kept[-1].numpy()

    def test_float_break_keeps_traced_dtype(self):
        """Break values ride out of the compiled program in their traced
        dtype — an f32 round-trip would be observable for f64 inputs under
        jax_enable_x64 and for large int64 counters (ADVICE r5)."""
        from paddle_tpu.jit.to_static import _ReplayContext
        import jax
        import jax.numpy as jnp

        entry = _ReplayContext({}, plan=[("float", 2.0)])
        t = paddle.to_tensor(np.array(2.0, np.float32))

        def probe(buf):
            entry.values[id(t)] = buf
            entry.plan_idx = 0
            entry.break_outs.clear()
            entry.on_scalar(t, "float", float)
            return entry.break_outs[0]

        out = jax.eval_shape(probe, jax.ShapeDtypeStruct((), jnp.int32))
        assert out.dtype == jnp.int32         # not silently cast to f32
        out = jax.eval_shape(probe, jax.ShapeDtypeStruct((), jnp.float32))
        assert out.dtype == jnp.float32
