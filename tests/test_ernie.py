"""ERNIE encoder family (BASELINE ERNIE-style config; PaddleNLP ErnieModel
parity surface): embeddings incl. token/task types, post-LN encoder, pooler,
MLM + classification heads, mask semantics, to_static capture."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.models.ernie import (ErnieConfig, ErnieModel,
                                     ErnieForMaskedLM,
                                     ErnieForSequenceClassification)


def _ids(b=2, s=12, vocab=256, seed=0):
    rng = np.random.RandomState(seed)
    return pt.to_tensor(rng.randint(1, vocab, (b, s)).astype(np.int64))


def test_forward_shapes_and_pooler():
    pt.seed(0)
    cfg = ErnieConfig.tiny(task_type_vocab_size=3)
    m = ErnieModel(cfg)
    m.eval()
    seq, pooled = m(_ids())
    assert seq.shape == [2, 12, 64] and pooled.shape == [2, 64]
    assert np.isfinite(seq.numpy()).all()
    # tanh pooler is bounded
    assert (np.abs(pooled.numpy()) <= 1.0 + 1e-6).all()


def test_padding_mask_blocks_pad_influence():
    """Changing PAD-position token ids must not change unpadded outputs."""
    pt.seed(0)
    cfg = ErnieConfig.tiny()
    m = ErnieModel(cfg)
    m.eval()
    rng = np.random.RandomState(1)
    ids = rng.randint(1, 256, (1, 10)).astype(np.int64)
    mask = np.ones((1, 10), np.float32)
    mask[0, 7:] = 0.0
    a = m(pt.to_tensor(ids), attention_mask=pt.to_tensor(mask))[0].numpy()
    ids2 = ids.copy()
    ids2[0, 7:] = rng.randint(1, 256, (3,))
    b = m(pt.to_tensor(ids2), attention_mask=pt.to_tensor(mask))[0].numpy()
    np.testing.assert_allclose(a[0, :7], b[0, :7], atol=1e-5)


def test_mlm_head_tied_and_trains():
    pt.seed(0)
    cfg = ErnieConfig.tiny()
    m = ErnieForMaskedLM(cfg)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=m.parameters())
    rng = np.random.RandomState(2)
    ids = rng.randint(1, 256, (2, 16)).astype(np.int64)
    labels = np.full((2, 16), -100, np.int64)
    labels[:, 3:8] = rng.randint(1, 256, (2, 5))
    x, y = pt.to_tensor(ids), pt.to_tensor(labels)
    losses = []
    for _ in range(8):
        _, loss = m(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._data, np.float32)))
    assert losses[-1] < losses[0]
    # decoder is tied to the word embeddings (no separate [V,H] matrix)
    n_vh = sum(1 for _, p in m.named_parameters()
               if list(p.shape) == [cfg.vocab_size, cfg.hidden_size])
    assert n_vh == 1


def test_classifier_trains_under_to_static():
    pt.seed(0)
    cfg = ErnieConfig.tiny(hidden_dropout_prob=0.0,
                           attention_probs_dropout_prob=0.0)
    m = ErnieForSequenceClassification(cfg, num_classes=3)
    opt = pt.optimizer.AdamW(learning_rate=2e-3, parameters=m.parameters())
    rng = np.random.RandomState(3)
    x = pt.to_tensor(rng.randint(1, 256, (8, 10)).astype(np.int64))
    y = pt.to_tensor(rng.randint(0, 3, (8,)).astype(np.int64))

    def step(x, y):
        _, loss = m(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    static = pt.jit.to_static(step)
    losses = [float(np.asarray(static(x, y)._data, np.float32))
              for _ in range(10)]
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])
    assert all(v.compiled is not None and not g.eager_only
               for g in static._cache.values() for v in g.variants)
