"""The bench artifact must be parseable even when the chip environment
misbehaves (VERDICT r4 weak #1: BENCH_r04.json was a raw traceback after
backend-init UNAVAILABLE).  bench.py's supervisor entry re-rolls failures in
fresh children and, on final failure, still emits the one-line JSON with an
``error`` field and exits 0."""
import json
import os
import signal
import subprocess
import sys
import time

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(ROOT, "bench.py")


def _last_metric_line(stdout):
    for line in reversed(stdout.strip().splitlines()):
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            return obj
    return None


class TestBenchSupervisor:
    def test_attempt_timeout_yields_structured_error(self):
        """A hung/slow child (simulated with a tiny attempt timeout) must
        produce the structured-error JSON, not a traceback, and rc 0."""
        env = {**os.environ, "JAX_PLATFORMS": "cpu",
               "BENCH_MAX_ATTEMPTS": "1", "BENCH_ATTEMPT_TIMEOUT": "3"}
        r = subprocess.run([sys.executable, BENCH], env=env,
                           capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr[-2000:]
        obj = _last_metric_line(r.stdout)
        assert obj is not None, r.stdout[-2000:]
        assert obj["value"] is None and obj["vs_baseline"] is None
        assert "error" in obj and "hung past" in obj["error"]
        assert obj["extra"]["attempts"][0]["attempt"] == 1

    def test_dead_tunnel_pool_ip_yields_structured_error(self):
        """VERDICT r4 'Done' criterion: a forced backend failure (pool IP
        pointing at an unreachable address) still produces JSON output."""
        env = {**os.environ, "JAX_PLATFORMS": "axon",
               "PALLAS_AXON_POOL_IPS": "10.255.255.1",
               "BENCH_MAX_ATTEMPTS": "2", "BENCH_ATTEMPT_TIMEOUT": "45",
               "BENCH_PROBE_TIMEOUT": "10"}
        r = subprocess.run([sys.executable, BENCH], env=env,
                           capture_output=True, text=True, timeout=300)
        assert r.returncode == 0, r.stderr[-2000:]
        obj = _last_metric_line(r.stdout)
        assert obj is not None, r.stdout[-2000:]
        assert obj["value"] is None
        assert "error" in obj
        # the fail-fast probe turns the attempt-long hang into a quick rc=2
        # with the probe's diagnosis in the child stderr tail
        assert "probe" in obj["error"]
        assert len(obj["extra"]["attempts"]) == 2

    def test_crashing_child_yields_structured_error(self):
        """A child whose backend init raises outright (unknown platform name
        — the same failure class as r4's UNAVAILABLE) is reported with the
        child's stderr tail in the reason."""
        env = {**os.environ, "JAX_PLATFORMS": "bogusplatform",
               "BENCH_MAX_ATTEMPTS": "1", "BENCH_ATTEMPT_TIMEOUT": "120"}
        r = subprocess.run([sys.executable, BENCH], env=env,
                           capture_output=True, text=True, timeout=240)
        assert r.returncode == 0
        obj = _last_metric_line(r.stdout)
        assert obj is not None and obj["value"] is None
        assert "rc=" in obj["error"]

    def test_sigterm_mid_run_emits_partial_artifact(self, tmp_path):
        """ISSUE acceptance criterion: an EXTERNAL wall timeout (SIGTERM to
        the supervisor) arriving mid-run must still leave a parseable JSON
        artifact — the newest PARTIAL section line the child flushed,
        annotated as truncated — and exit 0."""
        ready = tmp_path / "ready"
        env = {**os.environ, "BENCH_SMOKE": "1",
               "BENCH_SMOKE_READY": str(ready),
               "BENCH_MAX_ATTEMPTS": "1"}
        proc = subprocess.Popen([sys.executable, BENCH], env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.PIPE, text=True)
        try:
            deadline = time.time() + 60
            while not ready.exists() and time.time() < deadline:
                time.sleep(0.1)
            assert ready.exists(), "smoke child never signalled readiness"
            proc.send_signal(signal.SIGTERM)
            out, err = proc.communicate(timeout=60)
        finally:
            if proc.poll() is None:
                proc.kill()
        assert proc.returncode == 0, err[-2000:]
        obj = _last_metric_line(out)
        assert obj is not None, out[-2000:]
        assert obj.get("partial") is True
        assert "truncated" in obj["extra"]
        assert obj["extra"]["attempts"], "attempt log missing"
