"""The dryrun's parity assertions must be able to catch a wrong-but-finite
sharding bug (VERDICT r4 weak #3: finite-only checks can't).  The positive
path (all parts parity OK) is exercised by the driver on every round; here we
prove the negative: a deliberately desynced shard fails part A fast."""
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_injected_shard_desync_fails_parity():
    code = ("from __graft_entry__ import dryrun_multichip; "
            "dryrun_multichip(8)")
    env = {**os.environ, "GRAFT_DRYRUN_INJECT_FAULT": "1",
           "JAX_PLATFORMS": "cpu"}
    r = subprocess.run([sys.executable, "-c", code], cwd=ROOT, env=env,
                       capture_output=True, text=True, timeout=500)
    assert r.returncode != 0, "fault-injected dryrun unexpectedly passed"
    assert "parity FAIL" in (r.stdout + r.stderr)
