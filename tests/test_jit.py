"""to_static capture tests (reference test analog: test/dygraph_to_static/ —
run eager vs captured, compare outputs)."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn


def _linear_problem(seed=0):
    rng = np.random.RandomState(seed)
    x = pt.to_tensor(rng.rand(8, 4).astype(np.float32))
    y = pt.to_tensor(rng.rand(8, 2).astype(np.float32))
    return x, y


def test_static_matches_eager_train_loop():
    losses = {}
    for mode in ("eager", "static"):
        pt.seed(0)
        lin = nn.Linear(4, 2)
        opt = pt.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())

        def step(x, y):
            loss = ((lin(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        fn = pt.jit.to_static(step) if mode == "static" else step
        x, y = _linear_problem()
        out = [float(np.asarray(fn(x, y)._buf, np.float32)) for _ in range(4)]
        losses[mode] = out
    np.testing.assert_allclose(losses["eager"], losses["static"], rtol=1e-5)


def test_grad_accumulation_lifts_grads_as_inputs():
    """ADVICE r1 #4: with clear_grad OUTSIDE the captured fn, pre-existing
    grads must be program inputs, not trace-time constants."""
    pt.seed(0)
    lin = nn.Linear(4, 2)

    def accum_step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()          # accumulates into existing grads
        return loss

    static = pt.jit.to_static(accum_step)
    x, y = _linear_problem()

    # eager reference
    pt.seed(0)
    ref = nn.Linear(4, 2)

    def ref_step(x, y):
        loss = ((ref(x) - y) ** 2).mean()
        loss.backward()
        return loss

    for i in range(4):
        static(x, y)
        ref_step(x, y)
        w_g = np.asarray(lin.weight.grad._buf, np.float32)
        w_gr = np.asarray(ref.weight.grad._buf, np.float32)
        np.testing.assert_allclose(w_g, w_gr, rtol=1e-5,
                                   err_msg=f"accumulated grads diverge at step {i}")
    # grads really accumulated (≈4x one step's grad), not frozen at spy value
    static_once = np.asarray(lin.weight.grad._buf, np.float32)
    lin.weight.clear_grad()
    static(x, y)
    one = np.asarray(lin.weight.grad._buf, np.float32)
    np.testing.assert_allclose(static_once, 4 * one, rtol=1e-4)


def test_grad_accumulation_then_clear_retraces():
    """Clearing grads after capture must re-trace (grad-state signature
    changed), not crash or reuse stale inputs."""
    pt.seed(0)
    lin = nn.Linear(4, 2)

    def accum_step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        return loss

    static = pt.jit.to_static(accum_step)
    x, y = _linear_problem()
    static(x, y)
    static(x, y)
    lin.weight.clear_grad()
    lin.bias.clear_grad()
    static(x, y)  # grads now None → MissedCapture → re-trace, no stale reuse
    one = np.asarray(lin.weight.grad._buf, np.float32)
    lin.weight.clear_grad()
    lin.bias.clear_grad()
    static(x, y)
    np.testing.assert_allclose(np.asarray(lin.weight.grad._buf, np.float32),
                               one, rtol=1e-6)


def test_full_step_capture_with_clear_inside():
    """The canonical fused step (backward+opt+clear inside) still works and
    matches eager across lr-schedule changes."""
    pt.seed(0)
    lin = nn.Linear(4, 2)
    sched = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    opt = pt.optimizer.Adam(learning_rate=sched, parameters=lin.parameters())

    def step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    static = pt.jit.to_static(step)
    x, y = _linear_problem()
    prev = float("inf")
    for _ in range(6):
        loss = float(np.asarray(static(x, y)._buf, np.float32))
        sched.step()
    assert loss < 0.5  # converging
    # the capture must actually COMPILE (round-1 regression: lazy accumulator
    # creation during the spy made every optimizer step silently eager-only)
    assert all(e.compiled is not None and not e.eager_only
               for e in static._cache.values())


def test_adamw_with_clip_capture_compiles():
    """AdamW + global-norm clip (the bench configuration) must compile, not
    silently fall back to eager."""
    pt.seed(0)
    lin = nn.Linear(4, 2)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=lin.parameters(),
                             grad_clip=nn.ClipGradByGlobalNorm(1.0))

    def step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    static = pt.jit.to_static(step)
    x, y = _linear_problem()
    eager_losses = []
    for _ in range(4):
        eager_losses.append(float(np.asarray(static(x, y)._buf, np.float32)))
    assert all(e.compiled is not None and not e.eager_only
               for e in static._cache.values())
    # parity with a pure-eager twin
    pt.seed(0)
    lin2 = nn.Linear(4, 2)
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3, parameters=lin2.parameters(),
                              grad_clip=nn.ClipGradByGlobalNorm(1.0))
    ref = []
    for _ in range(4):
        loss = ((lin2(x) - y) ** 2).mean()
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        ref.append(float(np.asarray(loss._buf, np.float32)))
    np.testing.assert_allclose(eager_losses, ref, rtol=1e-5)
