"""to_static capture tests (reference test analog: test/dygraph_to_static/ —
run eager vs captured, compare outputs)."""
import numpy as np

import paddle_tpu as pt
import paddle_tpu.nn as nn


def _linear_problem(seed=0):
    rng = np.random.RandomState(seed)
    x = pt.to_tensor(rng.rand(8, 4).astype(np.float32))
    y = pt.to_tensor(rng.rand(8, 2).astype(np.float32))
    return x, y


def test_static_matches_eager_train_loop():
    losses = {}
    for mode in ("eager", "static"):
        pt.seed(0)
        lin = nn.Linear(4, 2)
        opt = pt.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())

        def step(x, y):
            loss = ((lin(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        fn = pt.jit.to_static(step) if mode == "static" else step
        x, y = _linear_problem()
        out = [float(np.asarray(fn(x, y)._buf, np.float32)) for _ in range(4)]
        losses[mode] = out
    np.testing.assert_allclose(losses["eager"], losses["static"], rtol=1e-5)


def test_grad_accumulation_lifts_grads_as_inputs():
    """ADVICE r1 #4: with clear_grad OUTSIDE the captured fn, pre-existing
    grads must be program inputs, not trace-time constants."""
    pt.seed(0)
    lin = nn.Linear(4, 2)

    def accum_step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()          # accumulates into existing grads
        return loss

    static = pt.jit.to_static(accum_step)
    x, y = _linear_problem()

    # eager reference
    pt.seed(0)
    ref = nn.Linear(4, 2)

    def ref_step(x, y):
        loss = ((ref(x) - y) ** 2).mean()
        loss.backward()
        return loss

    for i in range(4):
        static(x, y)
        ref_step(x, y)
        w_g = np.asarray(lin.weight.grad._buf, np.float32)
        w_gr = np.asarray(ref.weight.grad._buf, np.float32)
        np.testing.assert_allclose(w_g, w_gr, rtol=1e-5,
                                   err_msg=f"accumulated grads diverge at step {i}")
    # grads really accumulated (≈4x one step's grad), not frozen at spy value
    static_once = np.asarray(lin.weight.grad._buf, np.float32)
    lin.weight.clear_grad()
    static(x, y)
    one = np.asarray(lin.weight.grad._buf, np.float32)
    np.testing.assert_allclose(static_once, 4 * one, rtol=1e-4)


def test_grad_accumulation_then_clear_retraces():
    """Clearing grads after capture must re-trace (grad-state signature
    changed), not crash or reuse stale inputs."""
    pt.seed(0)
    lin = nn.Linear(4, 2)

    def accum_step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        return loss

    static = pt.jit.to_static(accum_step)
    x, y = _linear_problem()
    static(x, y)
    static(x, y)
    lin.weight.clear_grad()
    lin.bias.clear_grad()
    static(x, y)  # grads now None → MissedCapture → re-trace, no stale reuse
    one = np.asarray(lin.weight.grad._buf, np.float32)
    lin.weight.clear_grad()
    lin.bias.clear_grad()
    static(x, y)
    np.testing.assert_allclose(np.asarray(lin.weight.grad._buf, np.float32),
                               one, rtol=1e-6)


def test_full_step_capture_with_clear_inside():
    """The canonical fused step (backward+opt+clear inside) still works and
    matches eager across lr-schedule changes."""
    pt.seed(0)
    lin = nn.Linear(4, 2)
    sched = pt.optimizer.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.5)
    opt = pt.optimizer.Adam(learning_rate=sched, parameters=lin.parameters())

    def step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    static = pt.jit.to_static(step)
    x, y = _linear_problem()
    prev = float("inf")
    for _ in range(6):
        loss = float(np.asarray(static(x, y)._buf, np.float32))
        sched.step()
    assert loss < 0.5  # converging
    # the capture must actually COMPILE (round-1 regression: lazy accumulator
    # creation during the spy made every optimizer step silently eager-only)
    assert all(v.compiled is not None and not g.eager_only
               for g in static._cache.values() for v in g.variants)


def test_adamw_with_clip_capture_compiles():
    """AdamW + global-norm clip (the bench configuration) must compile, not
    silently fall back to eager."""
    pt.seed(0)
    lin = nn.Linear(4, 2)
    opt = pt.optimizer.AdamW(learning_rate=1e-3, parameters=lin.parameters(),
                             grad_clip=nn.ClipGradByGlobalNorm(1.0))

    def step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    static = pt.jit.to_static(step)
    x, y = _linear_problem()
    eager_losses = []
    for _ in range(4):
        eager_losses.append(float(np.asarray(static(x, y)._buf, np.float32)))
    assert all(v.compiled is not None and not g.eager_only
               for g in static._cache.values() for v in g.variants)
    # parity with a pure-eager twin
    pt.seed(0)
    lin2 = nn.Linear(4, 2)
    opt2 = pt.optimizer.AdamW(learning_rate=1e-3, parameters=lin2.parameters(),
                              grad_clip=nn.ClipGradByGlobalNorm(1.0))
    ref = []
    for _ in range(4):
        loss = ((lin2(x) - y) ** 2).mean()
        loss.backward()
        opt2.step()
        opt2.clear_grad()
        ref.append(float(np.asarray(loss._buf, np.float32)))
    np.testing.assert_allclose(eager_losses, ref, rtol=1e-5)


def test_guard_specialization_compiles_both_branches():
    """VERDICT r2 #3: a data-dependent Python branch must NOT make the
    signature eager. Each branch gets its own compiled variant; divergence is
    detected via guard outputs and re-runs the right variant."""
    calls = {"n": 0}

    def f(x):
        calls["n"] += 1
        s = (x * 2).sum()
        if s > 10:                    # bool() guard point
            return s * 3
        return s - 1

    static = pt.jit.to_static(f)
    lo = pt.to_tensor(np.ones(4, np.float32))          # s=8  -> else
    hi = pt.to_tensor(np.full(4, 10.0, np.float32))    # s=80 -> if
    assert abs(float(static(lo)) - 7.0) < 1e-5
    assert abs(float(static(hi)) - 240.0) < 1e-5       # diverge -> new variant
    assert abs(float(static(lo)) - 7.0) < 1e-5
    assert abs(float(static(hi)) - 240.0) < 1e-5
    n = calls["n"]
    for _ in range(3):                                  # steady state: no python
        static(lo), static(hi)
    assert calls["n"] == n
    (group,) = static._cache.values()
    assert len(group.variants) == 2 and not group.eager_only
    assert all(v.compiled is not None for v in group.variants)


def test_guard_divergence_does_not_corrupt_state():
    """A diverged run must commit NO state writes: optimizer state after a
    branch flip matches an eager twin exactly."""
    def build():
        pt.seed(0)
        lin = nn.Linear(4, 2)
        opt = pt.optimizer.Adam(learning_rate=0.05, parameters=lin.parameters())
        return lin, opt

    def make_step(lin, opt):
        def step(x, y, scale):
            loss = ((lin(x) - y) ** 2).mean() * scale
            if loss > 0.5:            # guard: branch depends on loss value
                loss = loss * 2.0
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss
        return step

    x, y = _linear_problem()
    lin_s, opt_s = build()
    static = pt.jit.to_static(make_step(lin_s, opt_s))
    lin_e, opt_e = build()
    eager = make_step(lin_e, opt_e)
    # scale schedule drives the branch both ways, incl. flips after compile
    for scale in [2.0, 2.0, 0.01, 0.01, 2.0, 0.01, 2.0]:
        ls = static(x, y, scale)
        le = eager(x, y, scale)
        np.testing.assert_allclose(np.asarray(ls._buf, np.float32),
                                   np.asarray(le._buf, np.float32), rtol=1e-5)
    for ps, pe in zip(lin_s.parameters(), lin_e.parameters()):
        np.testing.assert_allclose(np.asarray(ps._buf, np.float32),
                                   np.asarray(pe._buf, np.float32), rtol=1e-5)


def test_gpt2_train_step_with_branch_stays_compiled():
    """VERDICT r2 done-criterion: a GPT-2 train step containing a
    data-dependent Python branch runs with the step compiled (python body does
    not execute in steady state) and matches eager output."""
    from paddle_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM

    def build():
        pt.seed(0)
        cfg = GPT2Config.tiny(hidden_dropout_prob=0.0,
                              attention_dropout_prob=0.0,
                              max_position_embeddings=64)
        m = GPT2ForCausalLM(cfg)
        opt = pt.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=m.parameters())
        return cfg, m, opt

    def make_step(m, opt, counter):
        def step(x, y):
            counter["n"] += 1
            _, loss = m(x, labels=y)
            loss.backward()
            # data-dependent branch: halve the lr effect on high-loss steps
            if loss > 1e6:
                opt.clear_grad()       # skip step on loss explosion
            else:
                opt.step()
                opt.clear_grad()
            return loss
        return step

    rng = np.random.RandomState(0)
    cfg, m, opt = build()
    ids = rng.randint(0, cfg.vocab_size, (2, 17)).astype(np.int32)
    x = pt.to_tensor(ids[:, :-1])
    y = pt.to_tensor(ids[:, 1:])
    cnt = {"n": 0}
    static = pt.jit.to_static(make_step(m, opt, cnt))
    losses = [float(np.asarray(static(x, y)._buf, np.float32)) for _ in range(5)]
    (group,) = static._cache.values()
    assert not group.eager_only and group.variants, "step fell back to eager"
    n = cnt["n"]
    static(x, y)
    assert cnt["n"] == n, "python body ran in steady state (not compiled)"
    # parity with eager twin
    cfg2, m2, opt2 = build()
    eager = make_step(m2, opt2, {"n": 0})
    ref = [float(np.asarray(eager(x, y)._buf, np.float32)) for _ in range(5)]
    np.testing.assert_allclose(losses, ref, rtol=2e-3)


# ---- scan_steps: K steps per dispatch via one fused lax.scan ----------------

def _scan_problem(k=5, seed=0):
    rng = np.random.RandomState(seed)
    xs = pt.to_tensor(rng.rand(k, 8, 4).astype(np.float32))
    ys = pt.to_tensor(rng.rand(k, 8, 2).astype(np.float32))
    return xs, ys


def test_scan_steps_matches_eager_train_loop():
    """scan_steps(step)(stacked) == running step eagerly per slice: identical
    per-step losses AND identical final weights, with K optimizer updates."""
    K = 5
    xs, ys = _scan_problem(K)

    def make():
        pt.seed(0)
        lin = nn.Linear(4, 2)
        opt = pt.optimizer.AdamW(learning_rate=1e-2,
                                 parameters=lin.parameters(),
                                 grad_clip=nn.ClipGradByGlobalNorm(1.0))

        def step(x, y):
            loss = ((lin(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return lin, step

    lin_e, step_e = make()
    ref = []
    for i in range(2 * K):
        loss = step_e(pt.to_tensor(np.asarray(xs._buf)[i % K]),
                      pt.to_tensor(np.asarray(ys._buf)[i % K]))
        ref.append(float(np.asarray(loss._buf, np.float32)))

    lin_s, step_s = make()
    scan = pt.jit.scan_steps(step_s)
    out1 = scan(xs, ys)          # capture call: eager per-slice
    out2 = scan(xs, ys)          # compiled: ONE fused scan dispatch
    got = list(np.asarray(out1._buf, np.float32)) + \
        list(np.asarray(out2._buf, np.float32))
    assert out2._buf.shape == (K,)
    np.testing.assert_allclose(got, ref, rtol=2e-4)
    np.testing.assert_allclose(np.asarray(lin_s.weight._buf, np.float32),
                               np.asarray(lin_e.weight._buf, np.float32),
                               rtol=2e-4)
    assert all(v.compiled is not None and not g.eager_only
               for g in scan._cache.values() for v in g.variants)


def test_scan_steps_threads_rng_state():
    """Dropout inside a scanned step must draw a fresh mask per slice (the
    RNG key threads through the scan carry), matching the eager loop."""
    K = 4

    def make():
        pt.seed(7)
        lin = nn.Linear(4, 4)
        drop = nn.Dropout(0.5)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())

        def step(x, y):
            loss = ((drop(lin(x)) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            return loss

        return lin, step

    rng = np.random.RandomState(3)
    xs = pt.to_tensor(rng.rand(K, 8, 4).astype(np.float32))
    ys = pt.to_tensor(rng.rand(K, 8, 4).astype(np.float32))

    lin_e, step_e = make()
    ref = [float(np.asarray(step_e(pt.to_tensor(np.asarray(xs._buf)[i]),
                                   pt.to_tensor(np.asarray(ys._buf)[i]))._buf,
                            np.float32)) for i in range(K)]
    lin_s, step_s = make()
    scan = pt.jit.scan_steps(step_s)
    got = list(np.asarray(scan(xs, ys)._buf, np.float32))
    np.testing.assert_allclose(got, ref, rtol=2e-4)
    # per-slice masks must differ: with a stuck key all K losses would match
    assert len({round(v, 6) for v in got}) > 1


def test_scan_steps_guarded_fn_falls_back_eager():
    """Value guards can't specialize inside a scan: the signature must fall
    back to the per-slice eager loop with correct results, not crash."""
    K = 3
    pt.seed(0)
    lin = nn.Linear(4, 2)
    opt = pt.optimizer.SGD(learning_rate=0.05, parameters=lin.parameters())

    def step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        if float(np.asarray(loss._buf)) > 0:  # true graph break
            loss = loss * 1.0
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    xs, ys = _scan_problem(K, seed=1)
    scan = pt.jit.scan_steps(step)
    for _ in range(4):
        out = scan(xs, ys)
    assert out._buf.shape == (K,)
    assert all(g.eager_only for g in scan._cache.values())


def test_scan_steps_rejects_ragged_leading_dim():
    import pytest
    scan = pt.jit.scan_steps(lambda a, b: a + b)
    with pytest.raises(ValueError):
        scan(pt.to_tensor(np.zeros((3, 2), np.float32)),
             pt.to_tensor(np.zeros((4, 2), np.float32)))


def test_guarded_signature_warns_once(caplog):
    """VERDICT r3 #7: a value-guarded signature must loudly disclose its
    per-call device->host sync cost — once, not per call."""
    import logging
    pt.seed(0)
    lin = nn.Linear(4, 2)

    def step(x, y):
        loss = ((lin(x) - y) ** 2).mean()
        if int(loss * 0) == 0:        # value guard (int conversion)
            loss = loss * 1.0
        return loss

    static = pt.jit.to_static(step)
    x, y = _linear_problem()
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.jit"):
        for _ in range(3):
            static(x, y)
    warns = [r for r in caplog.records if "value guard" in r.message]
    assert len(warns) == 1, [r.message for r in caplog.records]
