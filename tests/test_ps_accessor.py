"""PS accessor policy + wire auth (VERDICT r4 missing #2): CtrAccessor-style
feature admission / score decay / threshold shrink (reference
paddle/fluid/distributed/ps/table/ctr_accessor.h:30) and HMAC-authenticated
pickle frames."""
import os
import socket

import numpy as np
import pytest

from paddle_tpu.distributed.ps_sparse import (SparseShard, SparsePsClient,
                                              start_server_process)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestAccessorPolicy:
    def test_admission_threshold_gates_row_creation(self, tmp_path):
        sh = SparseShard("t", dim=4, capacity_rows=16, data_dir=str(tmp_path),
                         lr=1.0, initializer="zeros", admit_threshold=3)
        ids = np.array([7])
        g = np.ones((1, 4), np.float32)
        sh.push(ids, g)                      # 1st show: candidate only
        st = sh.stats()
        assert st["resident"] == 0 and st["spilled"] == 0
        assert st["candidates"] == 1
        # pull of an unadmitted id returns the initializer, creates nothing
        np.testing.assert_allclose(sh.pull(ids), 0.0)
        assert sh.stats()["resident"] == 0
        sh.push(ids, g)                      # 2nd show
        assert sh.stats()["resident"] == 0
        sh.push(ids, g)                      # 3rd show: admitted + trained
        st = sh.stats()
        assert st["resident"] == 1 and st["candidates"] == 0
        # only the post-admission push applied (earlier grads dropped, like
        # the reference drops updates to uncreated embedx)
        np.testing.assert_allclose(sh.pull(ids), -1.0)

    def test_skewed_one_shot_stream_stays_bounded(self, tmp_path):
        """A stream of one-shot features + a few hot features: the hot ones
        train, the one-shots never occupy a row, and the candidate set stays
        within its budget."""
        cap = 32
        sh = SparseShard("t", dim=4, capacity_rows=cap,
                         data_dir=str(tmp_path), lr=0.5, initializer="zeros",
                         admit_threshold=2)
        hot = np.arange(8, dtype=np.int64)
        rng = np.random.RandomState(0)
        for step in range(200):
            one_shots = rng.randint(10_000, 10_000_000, size=16)
            batch = np.concatenate([hot, one_shots])
            sh.push(batch, np.ones((len(batch), 4), np.float32))
        st = sh.stats()
        assert st["resident"] + st["spilled"] == 8        # hot features only
        assert st["candidates"] <= sh._cand_budget
        # hot features actually trained
        assert (sh.pull(hot) < 0).all()

    def test_decay_and_threshold_shrink(self, tmp_path):
        sh = SparseShard("t", dim=4, capacity_rows=16, data_dir=str(tmp_path),
                         lr=0.1, initializer="zeros")
        hot, stale = np.array([1, 2]), np.array([50, 60])
        for _ in range(10):
            sh.push(hot, np.ones((2, 4), np.float32))
        sh.push(stale, np.ones((2, 4), np.float32))       # score 1 each
        assert sh.stats()["resident"] == 4
        # two decay epochs, then shrink below threshold: stale rows (score
        # ~0.25) die, hot rows (score ~2.5+) survive
        sh.shrink(decay_rate=0.5)
        deleted = sh.shrink(decay_rate=0.5, delete_threshold=1.0)
        assert deleted == 2
        st = sh.stats()
        assert st["resident"] == 2
        ids_left = sorted(rid for rid in sh.slot_of)
        assert ids_left == [1, 2]

    def test_score_survives_spill_and_save_load(self, tmp_path):
        sh = SparseShard("t", dim=2, capacity_rows=4, data_dir=str(tmp_path),
                         lr=0.1, initializer="zeros")
        ids = np.arange(12, dtype=np.int64)   # 3x capacity: forces spill
        for _ in range(3):
            sh.push(ids, np.ones((12, 2), np.float32))
        ck = str(tmp_path / "ck.sqlite")
        sh.save(ck)
        sh2 = SparseShard("t2", dim=2, capacity_rows=4,
                          data_dir=str(tmp_path), lr=0.1, initializer="zeros")
        sh2.load(ck)
        # all scores (resident-at-save and spilled-at-save) restored: a
        # shrink below 3 deletes nothing, above 3 deletes everything
        assert sh2.shrink(decay_rate=1.0, delete_threshold=2.9) == 0
        assert sh2.shrink(decay_rate=1.0, delete_threshold=3.1) == 12


class TestWireAuth:
    def _serve_with_key(self, tmp_path, key):
        port = _free_port()
        old = os.environ.get("PADDLE_PS_AUTH_KEY")
        os.environ["PADDLE_PS_AUTH_KEY"] = key
        try:
            proc = start_server_process(port, str(tmp_path))
        finally:
            if old is None:
                os.environ.pop("PADDLE_PS_AUTH_KEY", None)
            else:
                os.environ["PADDLE_PS_AUTH_KEY"] = old
        return port, proc

    def test_authenticated_roundtrip_and_unauthenticated_refused(self, tmp_path):
        port, proc = self._serve_with_key(tmp_path, "sekrit")
        try:
            # correct key: works
            os.environ["PADDLE_PS_AUTH_KEY"] = "sekrit"
            c = SparsePsClient([f"127.0.0.1:{port}"], retry=5.0)
            c.create_table("t", dim=4, capacity_rows_per_server=8,
                           lr=1.0, initializer="zeros")
            out = c.pull("t", np.array([1]))
            assert out.shape == (1, 4)
            c.close()
            # no key: server must drop the connection without answering
            os.environ.pop("PADDLE_PS_AUTH_KEY", None)
            c2 = SparsePsClient([f"127.0.0.1:{port}"], retry=2.0)
            with pytest.raises((ConnectionError, OSError, RuntimeError)):
                c2.pull("t", np.array([1]))
            c2.close()
            # wrong key: same refusal
            os.environ["PADDLE_PS_AUTH_KEY"] = "wrong"
            c3 = SparsePsClient([f"127.0.0.1:{port}"], retry=2.0)
            with pytest.raises((ConnectionError, OSError, RuntimeError)):
                c3.pull("t", np.array([1]))
            c3.close()
            # cleanly shut the server down with the right key
            os.environ["PADDLE_PS_AUTH_KEY"] = "sekrit"
            c4 = SparsePsClient([f"127.0.0.1:{port}"], retry=5.0)
            c4.shutdown()
            proc.wait(timeout=10)
        finally:
            os.environ.pop("PADDLE_PS_AUTH_KEY", None)
            if proc.poll() is None:
                proc.kill()

    def test_client_side_shrink_over_wire(self, tmp_path):
        port = _free_port()
        proc = start_server_process(port, str(tmp_path))
        try:
            c = SparsePsClient([f"127.0.0.1:{port}"])
            c.create_table("t", dim=4, capacity_rows_per_server=16,
                           lr=0.1, initializer="zeros")
            c.push("t", np.array([1, 2]), np.ones((2, 4), np.float32))
            assert c.shrink(decay_rate=1.0, delete_threshold=0.5) == 0
            assert c.shrink(decay_rate=0.1, delete_threshold=0.5) == 2
            c.shutdown()
            proc.wait(timeout=10)
        finally:
            if proc.poll() is None:
                proc.kill()


class TestMigrationAndDeterminism:
    def test_legacy_3col_spill_db_migrates(self, tmp_path):
        """Spill DBs written before the score column must load and serve."""
        import sqlite3
        con = sqlite3.connect(tmp_path / "t.spill.sqlite")
        con.execute("CREATE TABLE rows (id INTEGER PRIMARY KEY, "
                    "row BLOB, accum REAL)")
        con.execute("INSERT INTO rows VALUES (?, ?, ?)",
                    (5, np.full((4,), 2.0, np.float32).tobytes(), 0.0))
        con.commit()
        con.close()
        sh = SparseShard("t", dim=4, capacity_rows=2, data_dir=str(tmp_path),
                         lr=1.0, initializer="zeros")
        np.testing.assert_allclose(sh.pull(np.array([5])), 2.0)
        # eviction path writes 4 columns into the migrated table
        sh.push(np.arange(10, dtype=np.int64), np.ones((10, 4), np.float32))
        assert sh.stats()["spilled"] >= 8

    def test_unadmitted_pull_is_deterministic(self, tmp_path):
        """Read-only pulls of unadmitted ids return ONE fixed default row
        and never perturb the init RNG stream."""
        sh = SparseShard("t", dim=8, capacity_rows=8, data_dir=str(tmp_path),
                         admit_threshold=2, initializer="uniform")
        a = sh.pull(np.array([1]))
        b = sh.pull(np.array([1, 999]))
        np.testing.assert_array_equal(a[0], b[0])
        np.testing.assert_array_equal(b[0], b[1])
