import numpy as np
import pytest

import paddle_tpu as pt


def test_to_tensor_basics():
    t = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    assert t.shape == [2, 2]
    assert t.dtype == np.float32
    assert t.stop_gradient
    assert t.ndim == 2
    assert t.size == 4
    np.testing.assert_array_equal(t.numpy(), [[1, 2], [3, 4]])


def test_dtypes():
    assert pt.to_tensor([1, 2]).dtype == np.int64 or pt.to_tensor([1, 2]).dtype == np.int32
    t = pt.to_tensor([1.0], dtype="bfloat16")
    assert t.dtype == pt.bfloat16
    t32 = t.astype("float32")
    assert t32.dtype == np.float32


def test_arithmetic_overloads():
    a = pt.to_tensor([1.0, 2.0, 3.0])
    b = pt.to_tensor([4.0, 5.0, 6.0])
    np.testing.assert_allclose((a + b).numpy(), [5, 7, 9])
    np.testing.assert_allclose((a - b).numpy(), [-3, -3, -3])
    np.testing.assert_allclose((a * 2).numpy(), [2, 4, 6])
    np.testing.assert_allclose((2 * a).numpy(), [2, 4, 6])
    np.testing.assert_allclose((a / 2).numpy(), [0.5, 1.0, 1.5])
    np.testing.assert_allclose((2 ** a).numpy(), [2, 4, 8])
    np.testing.assert_allclose((1 - a).numpy(), [0, -1, -2])
    np.testing.assert_allclose((-a).numpy(), [-1, -2, -3])
    np.testing.assert_allclose(abs(pt.to_tensor([-1.0, 2.0])).numpy(), [1, 2])


def test_matmul_overload():
    a = pt.to_tensor(np.eye(3, dtype=np.float32))
    b = pt.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
    np.testing.assert_allclose((a @ b).numpy(), b.numpy())


def test_comparisons():
    a = pt.to_tensor([1.0, 2.0, 3.0])
    assert (a > 1.5).numpy().tolist() == [False, True, True]
    assert (a == 2.0).numpy().tolist() == [False, True, False]


def test_indexing():
    a = pt.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert a[0, 0].item() == 0.0
    assert a[-1].shape == [4]
    assert a[:, 1:3].shape == [3, 2]
    assert a[pt.to_tensor([0, 2])].shape == [2, 4]
    b = a[a > 5.0]  # boolean mask (eager host path)
    assert b.shape == [6]


def test_setitem():
    a = pt.to_tensor(np.zeros((3, 3), np.float32))
    a[1, 1] = 5.0
    assert a[1, 1].item() == 5.0
    a[0] = np.ones(3, np.float32)
    np.testing.assert_allclose(a[0].numpy(), [1, 1, 1])


def test_inplace_methods():
    a = pt.to_tensor([1.0, 2.0])
    a.add_(pt.to_tensor([1.0, 1.0]))
    np.testing.assert_allclose(a.numpy(), [2, 3])
    a.scale_(scale=2.0)
    np.testing.assert_allclose(a.numpy(), [4, 6])
    a.zero_()
    np.testing.assert_allclose(a.numpy(), [0, 0])
    a.fill_(7.0)
    np.testing.assert_allclose(a.numpy(), [7, 7])


def test_detach_and_clone():
    a = pt.to_tensor([1.0], stop_gradient=False)
    b = a * 2
    c = b.detach()
    assert c.stop_gradient and b._grad_node is not None and c._grad_node is None
    d = a.clone()
    assert not d.stop_gradient


def test_item_and_scalar():
    t = pt.to_tensor(3.5)
    assert t.item() == pytest.approx(3.5)
    assert float(t) == pytest.approx(3.5)
    assert t.ndim == 0


def test_parameter():
    p = pt.Parameter(np.ones((2, 2), np.float32))
    assert not p.stop_gradient
    assert p.trainable
    assert p.persistable


def test_cast_preserves_grad():
    a = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    b = a.astype("bfloat16")
    assert not b.stop_gradient
    b.sum().backward()
    assert a.grad is not None
