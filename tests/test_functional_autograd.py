"""paddle.autograd.jacobian / hessian (reference: python/paddle/autograd/
autograd.py, exported at autograd/__init__.py:26)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import jacobian, hessian


def _t(a, stop_gradient=False):
    t = paddle.to_tensor(np.asarray(a, np.float32))
    t.stop_gradient = stop_gradient
    return t


class TestJacobian:
    def test_matches_analytic_linear(self):
        A = np.array([[1., 2., 3.], [4., 5., 6.]], np.float32)
        x = _t([1., -1., 2.])
        y = paddle.to_tensor(A) @ x
        J = jacobian(y, x)
        np.testing.assert_allclose(np.asarray(J._data), A, atol=1e-6)

    def test_elementwise_nonlinear(self):
        x = _t([0.5, 1.0, 2.0])
        y = x * x * x
        J = jacobian(y, x)
        np.testing.assert_allclose(np.asarray(J._data),
                                   np.diag(3 * np.array([0.25, 1.0, 4.0])),
                                   rtol=1e-5)

    def test_multiple_xs_and_ys(self):
        x1, x2 = _t([1.0, 2.0]), _t([3.0])
        y1 = (x1 * 2).sum() + x2[0]
        y2 = x1[0] * x2[0]
        out = jacobian([y1, y2], [x1, x2])
        np.testing.assert_allclose(np.asarray(out[0][0]._data), [[2., 2.]])
        np.testing.assert_allclose(np.asarray(out[0][1]._data), [[1.]])
        np.testing.assert_allclose(np.asarray(out[1][0]._data), [[3., 0.]])
        np.testing.assert_allclose(np.asarray(out[1][1]._data), [[1.]])

    def test_batched(self):
        rng = np.random.RandomState(0)
        xb = _t(rng.randn(4, 3))
        yb = xb * xb          # independent per batch element
        J = jacobian(yb, xb, batch_axis=0)
        assert J.shape == [4, 3, 3]
        for b in range(4):
            np.testing.assert_allclose(
                np.asarray(J._data)[b],
                np.diag(2 * np.asarray(xb._data)[b]), rtol=1e-5)

    def test_unused_input_gives_zeros(self):
        x1, x2 = _t([1.0, 2.0]), _t([3.0, 4.0])
        y = (x1 * x1).sum()
        out = jacobian(y, [x1, x2])
        np.testing.assert_allclose(np.asarray(out[1]._data), [[0., 0.]])


class TestHessian:
    def test_quadratic_form(self):
        Q = np.array([[2., 1.], [1., 4.]], np.float32)
        x = _t([1.0, -2.0])
        y = 0.5 * (x @ paddle.to_tensor(Q) @ x)
        H = hessian(y, x)
        np.testing.assert_allclose(np.asarray(H._data), Q, atol=1e-5)

    def test_matches_finite_difference(self):
        def f(v):
            t = _t(v)
            return ((t * t * t).sum() + (t[0] * t[1])), t

        x0 = np.array([0.7, -1.3], np.float32)
        y, x = f(x0)
        H = np.asarray(hessian(y, x)._data)
        eps = 1e-3
        num = np.zeros((2, 2))
        for i in range(2):
            for j in range(2):
                xpp = x0.copy(); xpp[i] += eps; xpp[j] += eps
                xpm = x0.copy(); xpm[i] += eps; xpm[j] -= eps
                xmp = x0.copy(); xmp[i] -= eps; xmp[j] += eps
                xmm = x0.copy(); xmm[i] -= eps; xmm[j] -= eps
                def val(v):   # float64 reference (f32 FD noise swamps eps^2)
                    v = v.astype(np.float64)
                    return (v ** 3).sum() + v[0] * v[1]
                num[i, j] = (val(xpp) - val(xpm) - val(xmp) + val(xmm)) / (4 * eps * eps)
        np.testing.assert_allclose(H, num, atol=1e-2)

    def test_batched_hessian(self):
        rng = np.random.RandomState(0)
        xb = _t(rng.randn(3, 2))
        y = (xb * xb).sum(axis=1)     # per-batch scalar
        H = hessian(y, xb, batch_axis=0)
        assert H.shape == [3, 2, 2]
        for b in range(3):
            np.testing.assert_allclose(np.asarray(H._data)[b], 2 * np.eye(2),
                                       atol=1e-5)

    def test_non_scalar_raises(self):
        x = _t([1.0, 2.0])
        with pytest.raises(ValueError):
            hessian(x * x, x)


class TestJvpVjp:
    def test_vjp_matches_manual(self):
        from paddle_tpu.incubate.autograd import vjp
        x = _t([1.0, 2.0, 3.0])
        v = paddle.to_tensor(np.array([1.0, 0.5, 2.0], np.float32))
        y, g = vjp(lambda t: t * t, x, v)
        np.testing.assert_allclose(np.asarray(y._data), [1., 4., 9.])
        np.testing.assert_allclose(np.asarray(g._data),
                                   2 * np.array([1., 2., 3.]) *
                                   np.array([1., 0.5, 2.]))

    def test_jvp_forward_mode(self):
        from paddle_tpu.incubate.autograd import jvp
        x = _t([1.0, 2.0])
        v = paddle.to_tensor(np.array([1.0, -1.0], np.float32))
        y, t = jvp(lambda a: (a * a * a).sum(), x, v)
        np.testing.assert_allclose(float(y._data), 9.0)
        # d/deps sum((x+eps*v)^3) = 3x^2 . v = 3*1 - 3*4 = -9
        np.testing.assert_allclose(float(t._data), -9.0, rtol=1e-6)

    def test_vjp_leaves_other_grads_alone(self):
        """vjp must not pollute unrelated leaves' .grad nor flip the input's
        stop_gradient (regression: it used backward() over the whole graph)."""
        from paddle_tpu.incubate.autograd import vjp
        import paddle_tpu.nn as nn
        paddle.seed(0)
        lin = nn.Linear(3, 3)
        x = paddle.to_tensor(np.ones(3, np.float32))
        assert x.stop_gradient
        _, g = vjp(lambda t: lin(t), x)
        assert g is not None
        assert x.stop_gradient                    # restored
        assert all(p.grad is None for p in lin.parameters())

    def test_callable_jacobian_hessian_wrappers(self):
        from paddle_tpu.incubate.autograd import Jacobian, Hessian
        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        J = Jacobian(lambda t: t * t, x)
        np.testing.assert_allclose(np.asarray(J._data), np.diag([2., 4.]))
        H = Hessian(lambda t: (t * t).sum(), x)
        np.testing.assert_allclose(np.asarray(H._data), 2 * np.eye(2))

    def test_mask_2d_best_refuses_large_m(self):
        from paddle_tpu.incubate import asp
        with pytest.raises(ValueError):
            asp.create_mask(np.random.randn(8, 8).astype(np.float32),
                            n=4, m=8, mask_algo="mask_2d_best")

    def test_jvp_vjp_transpose_identity(self):
        """<v, J u> == <J^T v, u> — forward and reverse mode agree."""
        from paddle_tpu.incubate.autograd import jvp, vjp
        rng = np.random.RandomState(0)
        u = rng.randn(4).astype(np.float32)
        vv = rng.randn(4).astype(np.float32)
        W = rng.randn(4, 4).astype(np.float32)
        f = lambda t: paddle.to_tensor(W) @ (t * t)
        x0 = rng.randn(4).astype(np.float32)
        _, jv = jvp(f, _t(x0.copy()), paddle.to_tensor(u))
        _, vj = vjp(f, _t(x0.copy()), paddle.to_tensor(vv))
        lhs = float(np.dot(vv, np.asarray(jv._data)))
        rhs = float(np.dot(np.asarray(vj._data), u))
        np.testing.assert_allclose(lhs, rhs, rtol=1e-4)
