"""Package-fill tests (VERDICT #9): paddle.distribution vs scipy goldens,
paddle.sparse on BCOO (no densifying), RNN/LSTM/GRU vs torch goldens."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
import paddle_tpu.distribution as D


class TestDistributions:
    def test_normal_log_prob_entropy_kl(self):
        n = D.Normal(1.0, 2.0)
        v = np.array([0.5, 1.0, 3.0], np.float32)
        np.testing.assert_allclose(n.log_prob(paddle.to_tensor(v)).numpy(),
                                   st.norm.logpdf(v, 1.0, 2.0), rtol=1e-5)
        np.testing.assert_allclose(float(n.entropy()),
                                   st.norm.entropy(1.0, 2.0), rtol=1e-5)
        q = D.Normal(0.0, 1.0)
        expect = 0.5 * (4 + 1 - 1 - np.log(4))
        np.testing.assert_allclose(float(D.kl_divergence(n, q)), expect,
                                   rtol=1e-5)

    def test_normal_rsample_is_differentiable(self):
        loc = paddle.to_tensor(np.array([0.0], np.float32),
                               stop_gradient=False)
        d = D.Normal(loc, 1.0)
        s = d.rsample([64])
        s.sum().backward()
        np.testing.assert_allclose(loc.grad.numpy(), [64.0], rtol=1e-5)

    @pytest.mark.parametrize("dist,ref,val", [
        (lambda: D.Uniform(0.0, 2.0), lambda v: st.uniform.logpdf(v, 0, 2),
         np.array([0.5, 1.5], np.float32)),
        (lambda: D.Beta(2.0, 3.0), lambda v: st.beta.logpdf(v, 2, 3),
         np.array([0.2, 0.7], np.float32)),
        (lambda: D.Gamma(2.0, 3.0),
         lambda v: st.gamma.logpdf(v, 2, scale=1 / 3),
         np.array([0.5, 1.5], np.float32)),
        (lambda: D.Exponential(1.5), lambda v: st.expon.logpdf(v, scale=1/1.5),
         np.array([0.3, 2.0], np.float32)),
        (lambda: D.Laplace(0.0, 1.0), lambda v: st.laplace.logpdf(v),
         np.array([-1.0, 0.5], np.float32)),
        (lambda: D.Cauchy(0.0, 1.0), lambda v: st.cauchy.logpdf(v),
         np.array([-1.0, 2.0], np.float32)),
        (lambda: D.Gumbel(0.0, 1.0), lambda v: st.gumbel_r.logpdf(v),
         np.array([-0.5, 1.0], np.float32)),
        (lambda: D.StudentT(4.0), lambda v: st.t.logpdf(v, 4),
         np.array([-1.0, 0.8], np.float32)),
        (lambda: D.Poisson(3.0), lambda v: st.poisson.logpmf(v, 3.0),
         np.array([1.0, 4.0], np.float32)),
        (lambda: D.Geometric(0.3),
         lambda v: st.geom.logpmf(v + 1, 0.3),
         np.array([0.0, 3.0], np.float32)),
        (lambda: D.LogNormal(0.0, 1.0), lambda v: st.lognorm.logpdf(v, 1.0),
         np.array([0.5, 2.0], np.float32)),
        (lambda: D.Binomial(paddle.to_tensor(10.0), 0.4),
         lambda v: st.binom.logpmf(v, 10, 0.4),
         np.array([3.0, 7.0], np.float32)),
    ])
    def test_log_prob_vs_scipy(self, dist, ref, val):
        d = dist()
        np.testing.assert_allclose(d.log_prob(paddle.to_tensor(val)).numpy(),
                                   ref(val), rtol=1e-4, atol=1e-5)

    def test_categorical_and_bernoulli(self):
        c = D.Categorical(probs=paddle.to_tensor(
            np.array([0.2, 0.3, 0.5], np.float32)))
        np.testing.assert_allclose(
            c.log_prob(paddle.to_tensor(np.array([2], np.int32))).numpy(),
            [np.log(0.5)], rtol=1e-5)
        np.testing.assert_allclose(float(c.entropy()),
                                   st.entropy([0.2, 0.3, 0.5]), rtol=1e-5)
        b = D.Bernoulli(probs=0.3)
        np.testing.assert_allclose(
            float(b.log_prob(paddle.to_tensor(1.0))), np.log(0.3), rtol=1e-4)

    def test_dirichlet_multinomial_mvn(self):
        a = np.array([2.0, 3.0, 4.0], np.float32)
        d = D.Dirichlet(paddle.to_tensor(a))
        v = np.array([0.2, 0.3, 0.5], np.float32)
        np.testing.assert_allclose(
            float(d.log_prob(paddle.to_tensor(v))),
            st.dirichlet.logpdf(v, a), rtol=1e-4)
        m = D.Multinomial(5, paddle.to_tensor(
            np.array([0.2, 0.3, 0.5], np.float32)))
        cnt = np.array([1.0, 2.0, 2.0], np.float32)
        np.testing.assert_allclose(
            float(m.log_prob(paddle.to_tensor(cnt))),
            st.multinomial.logpmf(cnt, 5, [0.2, 0.3, 0.5]), rtol=1e-4)
        cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
        mvn = D.MultivariateNormal(paddle.to_tensor(
            np.zeros(2, np.float32)), covariance_matrix=paddle.to_tensor(cov))
        pt = np.array([0.3, -0.7], np.float32)
        np.testing.assert_allclose(
            float(mvn.log_prob(paddle.to_tensor(pt))),
            st.multivariate_normal.logpdf(pt, np.zeros(2), cov), rtol=1e-4)

    def test_sampling_moments(self):
        paddle.seed(0)
        s = D.Normal(2.0, 0.5).sample([4000]).numpy()
        assert abs(s.mean() - 2.0) < 0.05 and abs(s.std() - 0.5) < 0.05
        u = D.Uniform(-1.0, 1.0).sample([4000]).numpy()
        assert abs(u.mean()) < 0.06 and u.min() >= -1 and u.max() < 1

    def test_kl_pairs(self):
        pairs = [
            (D.Beta(2.0, 3.0), D.Beta(3.0, 2.0)),
            (D.Gamma(2.0, 1.0), D.Gamma(3.0, 2.0)),
            (D.Exponential(1.0), D.Exponential(2.0)),
            (D.Laplace(0.0, 1.0), D.Laplace(1.0, 2.0)),
        ]
        for p, q in pairs:
            kl = float(D.kl_divergence(p, q))
            assert np.isfinite(kl) and kl >= 0, (type(p).__name__, kl)
        # monte-carlo check one of them
        paddle.seed(0)
        p, q = D.Gamma(2.0, 1.0), D.Gamma(3.0, 2.0)
        x = p.sample([200000])
        mc = float((p.log_prob(x) - q.log_prob(x)).numpy().mean())
        np.testing.assert_allclose(float(D.kl_divergence(p, q)), mc, rtol=0.05)

    def test_independent_and_transformed(self):
        base = D.Normal(paddle.to_tensor(np.zeros((3, 4), np.float32)),
                        paddle.to_tensor(np.ones((3, 4), np.float32)))
        ind = D.Independent(base, 1)
        assert ind.batch_shape == (3,) and ind.event_shape == (4,)
        v = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        np.testing.assert_allclose(
            ind.log_prob(paddle.to_tensor(v)).numpy(),
            st.norm.logpdf(v).sum(-1), rtol=1e-4)


class TestDistributionRegressions:
    """Round-2 review findings: detached rsample, NaN entropy, KL dispatch."""

    def test_rsample_differentiable_across_families(self):
        paddle.seed(11)
        loc = paddle.to_tensor(0.5, stop_gradient=False)
        D.Laplace(loc, 1.0).rsample([4]).sum().backward()
        np.testing.assert_allclose(loc.grad.numpy(), 4.0, rtol=1e-5)
        for dist, param in [
            (lambda p: D.Gamma(p, 1.0), 2.0),
            (lambda p: D.Beta(p, 3.0), 2.0),
            (lambda p: D.Exponential(p), 2.0),
            (lambda p: D.Gumbel(p, 1.0), 0.0),
            (lambda p: D.Cauchy(p, 1.0), 0.0),
            (lambda p: D.StudentT(p), 5.0),
            (lambda p: D.Uniform(p, 4.0), 1.0),
        ]:
            t = paddle.to_tensor(param, stop_gradient=False)
            dist(t).rsample([4]).sum().backward()
            assert t.grad is not None and np.isfinite(t.grad.numpy()).all(), \
                dist(t)
        # sample() stays detached
        loc2 = paddle.to_tensor(0.5, stop_gradient=False)
        assert D.Laplace(loc2, 1.0).sample([4]).stop_gradient

    def test_mvn_scale_tril_gradients(self):
        lt = paddle.to_tensor(np.array([[1.0, 0], [0.3, 1.0]], np.float32),
                              stop_gradient=False)
        mv = D.MultivariateNormal(paddle.to_tensor([0.0, 0.0]), scale_tril=lt)
        mv.log_prob(paddle.to_tensor([0.5, 0.5])).backward()
        assert lt.grad is not None and np.isfinite(lt.grad.numpy()).all()

    def test_derived_params_keep_gradients(self):
        # Categorical / Bernoulli(probs=...) / Chi2 normalize their params;
        # the derivation must stay on the tape (round-2 review finding)
        logits = paddle.to_tensor(np.array([0.1, 0.2, 0.7], np.float32),
                                  stop_gradient=False)
        D.Categorical(logits=logits).log_prob(
            paddle.to_tensor([2])).sum().backward()
        assert logits.grad is not None and \
            np.isfinite(logits.grad.numpy()).all()

        probs = paddle.to_tensor(np.array([0.3, 0.6], np.float32),
                                 stop_gradient=False)
        D.Categorical(probs=probs).entropy().sum().backward()
        assert probs.grad is not None

        bp = paddle.to_tensor(0.3, stop_gradient=False)
        D.Bernoulli(probs=bp).log_prob(paddle.to_tensor(1.0)).backward()
        np.testing.assert_allclose(bp.grad.numpy(), 1 / 0.3, rtol=1e-4)

        df = paddle.to_tensor(4.0, stop_gradient=False)
        D.Chi2(df).log_prob(paddle.to_tensor(2.0)).backward()
        assert df.grad is not None and np.isfinite(float(df.grad))

    def test_bernoulli_entropy_saturated_probs(self):
        assert abs(float(D.Bernoulli(logits=20.0).entropy())) < 1e-6
        assert abs(float(D.Bernoulli(probs=1.0).entropy())) < 1e-6
        assert abs(float(D.Bernoulli(probs=0.0).entropy())) < 1e-6

    def test_continuous_bernoulli_sample_and_kl(self):
        paddle.seed(12)
        p = D.ContinuousBernoulli(probs=0.2)
        q = D.ContinuousBernoulli(probs=0.8)
        x = p.sample([100000])
        xv = x.numpy()
        # continuous samples in (0,1), not discrete {0,1}
        assert ((xv > 0) & (xv < 1)).mean() > 0.99
        np.testing.assert_allclose(float(p.mean), xv.mean(), atol=0.01)
        # subclass KL dispatches to the CB formula (with log-normalizer),
        # not the base Bernoulli one; cross-check by Monte Carlo
        kl = float(D.kl_divergence(p, q))
        mc = float((p.log_prob(x).numpy() - q.log_prob(x).numpy()).mean())
        np.testing.assert_allclose(kl, mc, atol=0.02)
        bern = float(D.kl_divergence(D.Bernoulli(probs=0.2),
                                     D.Bernoulli(probs=0.8)))
        assert abs(kl - bern) > 0.05


class TestSparse:
    def _coo(self, seed=0):
        rng = np.random.RandomState(seed)
        dense = rng.rand(4, 5).astype(np.float32)
        dense[dense < 0.6] = 0
        idx = np.nonzero(dense)
        vals = dense[idx]
        t = paddle.sparse.sparse_coo_tensor(np.stack(idx), vals,
                                            shape=[4, 5])
        return t, dense

    def test_coo_roundtrip_no_densify(self):
        t, dense = self._coo()
        assert t.is_sparse() and t.is_sparse_coo()
        assert t.nnz() == int((dense != 0).sum())
        # values() holds exactly nnz entries — storage stayed sparse
        assert t.values().shape == [t.nnz()]
        np.testing.assert_allclose(t.to_dense().numpy(), dense)

    def test_csr_roundtrip(self):
        t, dense = self._coo()
        csr = t.to_sparse_csr()
        assert csr.is_sparse_csr()
        np.testing.assert_allclose(csr.to_dense().numpy(), dense)
        back = csr.to_sparse_coo()
        np.testing.assert_allclose(back.to_dense().numpy(), dense)

    def test_add_multiply_matmul(self):
        a, da = self._coo(0)
        b, db = self._coo(1)
        np.testing.assert_allclose((a + b).to_dense().numpy(), da + db,
                                   rtol=1e-6)
        np.testing.assert_allclose((a - b).to_dense().numpy(), da - db,
                                   rtol=1e-6, atol=1e-6)
        out = paddle.sparse.multiply(a, 2.5)
        np.testing.assert_allclose(out.to_dense().numpy(), da * 2.5)
        dense_rhs = np.random.RandomState(2).rand(5, 3).astype(np.float32)
        mm = paddle.sparse.matmul(a, paddle.to_tensor(dense_rhs))
        np.testing.assert_allclose(mm.numpy(), da @ dense_rhs, rtol=1e-5)

    def test_masked_matmul_sddmm(self):
        a, _ = self._coo(0)
        x = np.random.RandomState(3).rand(4, 6).astype(np.float32)
        y = np.random.RandomState(4).rand(6, 5).astype(np.float32)
        out = paddle.sparse.masked_matmul(paddle.to_tensor(x),
                                          paddle.to_tensor(y), a)
        full = x @ y
        mask = a.to_dense().numpy() != 0
        np.testing.assert_allclose(out.to_dense().numpy(), full * mask,
                                   rtol=1e-5)

    def test_unary_value_ops(self):
        t, dense = self._coo()
        np.testing.assert_allclose(paddle.sparse.relu(t).to_dense().numpy(),
                                   np.maximum(dense, 0), rtol=1e-6)
        np.testing.assert_allclose(paddle.sparse.tanh(t).to_dense().numpy(),
                                   np.tanh(dense), rtol=1e-6)
        sq = paddle.sparse.square(t)
        assert sq.nnz() == t.nnz()       # still sparse

    def test_transpose_sum(self):
        t, dense = self._coo()
        tr = paddle.sparse.transpose(t, [1, 0])
        np.testing.assert_allclose(tr.to_dense().numpy(), dense.T)
        np.testing.assert_allclose(float(paddle.sparse.sum(t)), dense.sum(),
                                   rtol=1e-6)

    def test_sum_negative_axis_keepdim(self):
        t, dense = self._coo()
        out = paddle.sparse.sum(t, axis=-1, keepdim=True)
        assert out.shape == [4, 1]
        np.testing.assert_allclose(out.to_dense().numpy(),
                                   dense.sum(-1, keepdims=True), rtol=1e-6)

    def test_pow_on_csr(self):
        t, dense = self._coo()
        out = paddle.sparse.pow(t.to_sparse_csr(), 2.0)
        np.testing.assert_allclose(out.to_dense().numpy(), dense ** 2,
                                   rtol=1e-6)

    def test_add_shape_mismatch_raises(self):
        t, _ = self._coo()
        other = paddle.sparse.sparse_coo_tensor([[0], [0]], [1.0], [7, 7])
        with pytest.raises(ValueError):
            paddle.sparse.add(t, other)

    def test_softmax_counts_stored_zeros(self):
        csr = paddle.sparse.sparse_csr_tensor(
            [0, 2, 3], [0, 1, 1], [0.0, 2.0, 1.0], [2, 2])
        v = paddle.sparse.nn.Softmax()(csr).values().numpy()
        row0 = np.exp([0.0, 2.0]) / np.exp([0.0, 2.0]).sum()
        np.testing.assert_allclose(v, [row0[0], row0[1], 1.0], atol=1e-6)
        with pytest.raises(ValueError):
            paddle.sparse.nn.Softmax(axis=0)(csr)


class TestRNN:
    def setup_method(self, _):
        import torch
        self.torch = torch
        paddle.seed(0)
        rng = np.random.RandomState(0)
        self.x = rng.randn(3, 7, 5).astype(np.float32)

    def _sync(self, pl, tl, layers, bidirectional):
        with self.torch.no_grad():
            for layer in range(layers):
                for sfx in ["", "_reverse"] if bidirectional else [""]:
                    for nm in ["weight_ih", "weight_hh", "bias_ih", "bias_hh"]:
                        getattr(tl, f"{nm}_l{layer}{sfx}").copy_(
                            self.torch.from_numpy(
                                getattr(pl, f"{nm}_l{layer}{sfx}").numpy().copy()))

    def test_lstm_bidirectional_2layer_vs_torch(self):
        pl = paddle.nn.LSTM(5, 6, num_layers=2, direction="bidirect")
        tl = self.torch.nn.LSTM(5, 6, num_layers=2, bidirectional=True,
                                batch_first=True)
        self._sync(pl, tl, 2, True)
        out_p, (h_p, c_p) = pl(paddle.to_tensor(self.x))
        out_t, (h_t, c_t) = tl(self.torch.from_numpy(self.x))
        np.testing.assert_allclose(out_p.numpy(), out_t.detach().numpy(),
                                   atol=1e-5)
        np.testing.assert_allclose(h_p.numpy(), h_t.detach().numpy(),
                                   atol=1e-5)
        np.testing.assert_allclose(c_p.numpy(), c_t.detach().numpy(),
                                   atol=1e-5)

    def test_gru_vs_torch(self):
        pl = paddle.nn.GRU(5, 6)
        tl = self.torch.nn.GRU(5, 6, batch_first=True)
        self._sync(pl, tl, 1, False)
        out_p, h_p = pl(paddle.to_tensor(self.x))
        out_t, h_t = tl(self.torch.from_numpy(self.x))
        np.testing.assert_allclose(out_p.numpy(), out_t.detach().numpy(),
                                   atol=1e-5)

    def test_simple_rnn_vs_torch(self):
        pl = paddle.nn.SimpleRNN(5, 6)
        tl = self.torch.nn.RNN(5, 6, batch_first=True)
        self._sync(pl, tl, 1, False)
        out_p, _ = pl(paddle.to_tensor(self.x))
        out_t, _ = tl(self.torch.from_numpy(self.x))
        np.testing.assert_allclose(out_p.numpy(), out_t.detach().numpy(),
                                   atol=1e-5)

    def test_lstm_trains(self):
        paddle.seed(1)
        lstm = paddle.nn.LSTM(5, 8)
        head = paddle.nn.Linear(8, 1)
        opt = paddle.optimizer.Adam(
            learning_rate=1e-2,
            parameters=lstm.parameters() + head.parameters())
        rng = np.random.RandomState(0)
        xv = rng.randn(8, 7, 5).astype(np.float32)
        yv = xv.sum(axis=(1, 2), keepdims=False)[:, None].astype(np.float32)
        losses = []
        for _ in range(60):
            out, (h, c) = lstm(paddle.to_tensor(xv))
            pred = head(out[:, -1])
            loss = ((pred - paddle.to_tensor(yv)) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        # torch on the identical task/seed reaches 0.23x at step 60; we match.
        assert losses[-1] < losses[0] * 0.35, (losses[0], losses[-1])

    def test_cells_and_generic_rnn_wrapper(self):
        from paddle_tpu.nn import LSTMCell, GRUCell, SimpleRNNCell, RNN, BiRNN
        cell = LSTMCell(5, 6)
        out, (h, c) = cell(paddle.to_tensor(self.x[:, 0]))
        assert out.shape == [3, 6] and c.shape == [3, 6]
        runner = RNN(LSTMCell(5, 6))
        y, state = runner(paddle.to_tensor(self.x))
        assert y.shape == [3, 7, 6]
        bi = BiRNN(GRUCell(5, 6), GRUCell(5, 6))
        y, _ = bi(paddle.to_tensor(self.x))
        assert y.shape == [3, 7, 12]
