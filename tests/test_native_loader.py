"""Native C++ DataLoader engine tests (core/native/dataloader.cc +
io/native_loader.py). Reference analog: the C++ data plane of
fluid/framework/data_feed.cc / DataLoader worker pool."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, TensorDataset, BatchSampler
from paddle_tpu.io.native_loader import (NativeArrayLoader, available)

pytestmark = pytest.mark.skipif(not available(),
                                reason="no C++ toolchain for native engine")


def _data(n=64, l=8, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randint(0, 1000, (n, l)).astype(np.int32),
            rng.randn(n, l).astype(np.float32))


class TestEngine:
    def test_order_and_values(self):
        xs, ys = _data()
        batches = [list(range(i, i + 16)) for i in range(0, 64, 16)]
        out = list(NativeArrayLoader([xs, ys], batches, num_threads=4))
        assert len(out) == 4
        for k, (bx, by) in enumerate(out):
            np.testing.assert_array_equal(bx, xs[batches[k]])
            np.testing.assert_array_equal(by, ys[batches[k]])

    def test_shuffled_and_ragged_tail(self):
        xs, _ = _data(n=50)
        rng = np.random.RandomState(3)
        perm = rng.permutation(50)
        batches = [perm[i:i + 16].tolist() for i in range(0, 50, 16)]
        out = [b[0] for b in NativeArrayLoader([xs], batches, num_threads=3)]
        assert [len(b) for b in out] == [16, 16, 16, 2]
        for k, b in enumerate(out):
            np.testing.assert_array_equal(b, xs[batches[k]])

    def test_bad_index_raises(self):
        xs, _ = _data(n=8)
        with pytest.raises(RuntimeError):
            list(NativeArrayLoader([xs], [[0, 99]], num_threads=1))

    def test_many_batches_soak(self):
        """Deep prefetch + many small batches: exercises the depth window,
        in-order delivery, and thread handoff under churn."""
        xs, _ = _data(n=256, l=4)
        batches = [np.random.RandomState(i).randint(0, 256, 8).tolist()
                   for i in range(200)]
        out = [b[0] for b in NativeArrayLoader([xs], batches,
                                               num_threads=8, depth=4)]
        assert len(out) == 200
        for k in (0, 57, 123, 199):
            np.testing.assert_array_equal(out[k], xs[batches[k]])

    def test_owned_copies_survive(self):
        """Yielded arrays are owned copies — holding them across iterations
        must not alias the recycled engine slot."""
        xs, _ = _data(n=32)
        batches = [list(range(0, 8)), list(range(8, 16)), list(range(16, 24))]
        held = list(NativeArrayLoader([xs], batches, num_threads=2, depth=1))
        np.testing.assert_array_equal(held[0][0], xs[:8])
        np.testing.assert_array_equal(held[2][0], xs[16:24])


class TestDataLoaderIntegration:
    def test_auto_engine_matches_sync(self):
        xs, ys = _data()
        ds = TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
        sync = [(np.asarray(a._data), np.asarray(b._data))
                for a, b in DataLoader(ds, batch_size=16)]
        nat = [(np.asarray(a._data), np.asarray(b._data))
               for a, b in DataLoader(ds, batch_size=16, num_workers=4,
                                      engine="native")]
        assert len(sync) == len(nat)
        for (sa, sb), (na, nb) in zip(sync, nat):
            np.testing.assert_array_equal(sa, na)
            np.testing.assert_array_equal(sb, nb)

    def test_native_requires_tensor_dataset(self):
        from paddle_tpu.io import Dataset

        class LD(Dataset):
            def __getitem__(self, i): return np.zeros(3, np.float32)
            def __len__(self): return 8

        with pytest.raises(RuntimeError):
            iter(DataLoader(LD(), batch_size=2, num_workers=2,
                            engine="native")).__next__()

    def test_native_engine_with_zero_workers(self):
        """engine='native' is honored even at the default num_workers=0."""
        xs, ys = _data(n=32)
        ds = TensorDataset([paddle.to_tensor(xs), paddle.to_tensor(ys)])
        out = list(DataLoader(ds, batch_size=8, engine="native"))
        assert len(out) == 4
        np.testing.assert_array_equal(np.asarray(out[0][0]._data), xs[:8])

    def test_python_engine_still_works(self):
        """mp-worker fallback path. The dataset returns plain numpy — forked
        children must not touch jax arrays (fork-unsafe XLA runtime; the
        native engine exists precisely to avoid this)."""
        from paddle_tpu.io import Dataset

        class NpDataset(Dataset):
            def __init__(self): self.xs, self.ys = _data(n=32)
            def __getitem__(self, i): return self.xs[i], self.ys[i]
            def __len__(self): return 32

        out = list(DataLoader(NpDataset(), batch_size=8, num_workers=2,
                              engine="python"))
        assert len(out) == 4
        np.testing.assert_array_equal(np.asarray(out[0][0]._data),
                                      _data(n=32)[0][:8])
