"""Test env: CPU XLA with 8 virtual devices (SURVEY §4 — the reference simulates
multi-node as multi-process on one host; we simulate a TPU mesh as 8 CPU devices).

This environment's TPU plugin ignores the ``JAX_PLATFORMS`` env var, so the env
var alone is NOT enough: we must also force the platform through ``jax.config``
and, if a TPU backend already initialized, clear it.  Tests hard-assert the
8-device CPU mesh up front so a mis-forced platform fails loudly instead of
silently testing less (round-1 failure mode).

Hermeticity (VERDICT r4 #2): the plugin registers from sitecustomize in every
descendant interpreter that inherits its discovery env vars — and then dials
the tunnel, hanging each subprocess-spawning test when the tunnel is down.  So
the vars are scrubbed from THIS process's environ up front (children inherit
the cleaned environ), and an autouse fixture reaps any child process a test
leaks (timeouts in ``communicate()`` kill nothing).
"""
import importlib.util
import os
import signal
import tempfile
import time

# Scrub accelerator-plugin discovery vars BEFORE anything imports jax and
# before any test spawns a child.  Loaded by file path: importing the package
# would pull in jax ahead of the platform forcing below.
_spec = importlib.util.spec_from_file_location(
    "_paddle_tpu_hermetic",
    os.path.join(os.path.dirname(__file__), os.pardir,
                 "paddle_tpu", "core", "hermetic.py"))
_hermetic = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_hermetic)
_hermetic.scrub_plugin_vars()

# hermetic autotune cache: don't read/write the user's on-disk cache
os.environ["PADDLE_TPU_AUTOTUNE_CACHE"] = os.path.join(
    tempfile.gettempdir(), f"paddle_tpu_autotune_test_{os.getpid()}.json")

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

# XLA executable cache, keyed by HLO hash: serving/spec tests build many
# LLMEngine instances whose per-instance jit closures lower to identical
# programs — the on-disk cache dedups those compiles within a run (and
# across runs / subprocess children, which inherit the env var).  Unlike
# the autotune cache this never changes behavior, only compile latency.
os.environ.setdefault(
    "JAX_COMPILATION_CACHE_DIR",
    os.path.join(tempfile.gettempdir(), "paddle_tpu_xla_cache"))
os.environ.setdefault("JAX_ENABLE_X64", "0")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
    import jax.extend.backend

    jax.extend.backend.clear_backends()

assert jax.devices()[0].platform == "cpu", (
    f"test suite requires the CPU platform, got {jax.devices()[0].platform}"
)
assert len(jax.devices()) == 8, (
    f"test suite requires 8 virtual CPU devices, got {len(jax.devices())}"
)

import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running tests excluded from the tier-1 gate (-m 'not slow')")


# ------------------------------------------------- chaos post-mortem capture

def _dump_chaos_artifacts(nodeid):
    """When ``PADDLE_TPU_CHAOS_ARTIFACTS`` names a directory, drop a metrics
    registry snapshot plus every pinned flight-recorder trace there — the
    evidence a red chaos-matrix leg needs for a post-mortem without a rerun.
    CI uploads the directory on failure; unset (the default) this is free."""
    d = os.environ.get("PADDLE_TPU_CHAOS_ARTIFACTS")
    if not d:
        return
    import json

    from paddle_tpu import observability as obs
    try:
        os.makedirs(d, exist_ok=True)
        safe = "".join(c if c.isalnum() or c in "._-" else "_"
                       for c in nodeid)[-120:]
        with open(os.path.join(d, f"metrics-{safe}.json"), "w",
                  encoding="utf-8") as f:
            json.dump(obs.snapshot(), f, indent=1, sort_keys=True)
    except Exception:
        pass                    # capture must never mask the real failure
    for tid, reason in obs.flight.pinned().items():
        try:
            obs.flight.dump_trace(tid, obs.flight.events_for(tid),
                                  reason=reason, out_dir=d)
        except OSError:
            pass


def pytest_runtest_logreport(report):
    if report.failed:
        _dump_chaos_artifacts(report.nodeid)


def _live_children():
    """pid -> state for direct children of this process (via /proc)."""
    me = os.getpid()
    out = {}
    for d in os.listdir("/proc"):
        if not d.isdigit():
            continue
        try:
            with open(f"/proc/{d}/stat") as f:
                stat = f.read()
            rest = stat[stat.rindex(")") + 2:].split()
            if int(rest[1]) == me:
                out[int(d)] = rest[0]
        except (OSError, ValueError):
            continue
    return out


def _cmdline(pid):
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\0", b" ").decode(errors="replace")
    except OSError:
        return ""


# multiprocessing helper daemons legitimately persist across tests
_KEEP_CHILDREN = ("multiprocessing.resource_tracker",
                  "multiprocessing.forkserver")


@pytest.fixture(autouse=True)
def _reap_leaked_children():
    """A child process that outlives its test is a leak (RPC pairs and PS
    servers survive ``communicate(timeout=...)`` expiry, which kills nothing):
    terminate it and reap the zombie so later tests don't inherit port
    collisions or CPU contention."""
    before = set(_live_children())
    yield
    after = _live_children()
    leaked = {p: st for p, st in after.items() if p not in before}
    live = [p for p, st in leaked.items()
            if st != "Z" and not any(k in _cmdline(p) for k in _KEEP_CHILDREN)]
    for p in live:
        try:
            os.kill(p, signal.SIGTERM)
        except OSError:
            pass
    deadline = time.time() + 5.0
    while live and time.time() < deadline:
        live = [p for p, st in _live_children().items()
                if p in live and st != "Z"]
        if live:
            time.sleep(0.05)
    for p in live:
        try:
            os.kill(p, signal.SIGKILL)
        except OSError:
            pass
    # reap every zombie child (leaked or pre-existing) without blocking
    for p, st in _live_children().items():
        if st == "Z":
            try:
                os.waitpid(p, os.WNOHANG)
            except (OSError, ChildProcessError):
                pass
