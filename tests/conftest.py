"""Test env: CPU XLA with 8 virtual devices (SURVEY §4 — the reference simulates
multi-node as multi-process on one host; we simulate a TPU mesh as 8 CPU devices)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")
