"""Test env: CPU XLA with 8 virtual devices (SURVEY §4 — the reference simulates
multi-node as multi-process on one host; we simulate a TPU mesh as 8 CPU devices).

This environment's TPU plugin ignores the ``JAX_PLATFORMS`` env var, so the env
var alone is NOT enough: we must also force the platform through ``jax.config``
and, if a TPU backend already initialized, clear it.  Tests hard-assert the
8-device CPU mesh up front so a mis-forced platform fails loudly instead of
silently testing less (round-1 failure mode).
"""
import os
import tempfile

# hermetic autotune cache: don't read/write the user's on-disk cache
os.environ["PADDLE_TPU_AUTOTUNE_CACHE"] = os.path.join(
    tempfile.gettempdir(), f"paddle_tpu_autotune_test_{os.getpid()}.json")

flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"
os.environ.setdefault("JAX_ENABLE_X64", "0")
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
if jax.devices()[0].platform != "cpu" or len(jax.devices()) < 8:
    import jax.extend.backend

    jax.extend.backend.clear_backends()

assert jax.devices()[0].platform == "cpu", (
    f"test suite requires the CPU platform, got {jax.devices()[0].platform}"
)
assert len(jax.devices()) == 8, (
    f"test suite requires 8 virtual CPU devices, got {len(jax.devices())}"
)
