"""Engine factory for fleet worker subprocesses (--engine-spec target).

Kept as a plain module (not a test file) so
``python -m paddle_tpu.inference.frontend.worker
--engine-spec tests/_fleet_worker_spec.py:make_engine`` can load it by path
in the slow kill-9 chaos test without importing the pytest machinery."""


def make_engine():
    import paddle_tpu as pt
    from paddle_tpu.inference.serving import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM

    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    model.eval()
    return LLMEngine(model, max_batch=3, max_len=64, page_size=8,
                     prefix_cache=True)
