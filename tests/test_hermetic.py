"""Hermeticity against a dead/unreachable accelerator tunnel (VERDICT r4 #2).

The platform plugin registers from sitecustomize in every interpreter whose
env carries its discovery vars, ignores ``JAX_PLATFORMS=cpu``, and hangs on a
dead tunnel.  Every CPU-bound spawn path must therefore ship children a
scrubbed environment (reference pattern: the CPU-simulation contract of
test/legacy_test/test_dist_base.py:957).
"""
import os
import subprocess
import sys

from paddle_tpu.core.hermetic import (ACCEL_PLUGIN_VARS, cpu_child_env,
                                      scrub_plugin_vars)

UNREACHABLE = "10.255.255.1"   # RFC-1918, nothing listens; a dial would hang


class TestCpuChildEnv:
    def test_strips_plugin_vars_and_forces_cpu(self):
        base = {var: "x" for var in ACCEL_PLUGIN_VARS}
        base.update({"PATH": "/bin", "JAX_PLATFORMS": "axon"})
        env = cpu_child_env(base)
        for var in ACCEL_PLUGIN_VARS:
            assert var not in env
        assert env["JAX_PLATFORMS"] == "cpu"
        assert env["PATH"] == "/bin"

    def test_extra_overrides_win(self):
        env = cpu_child_env({}, PADDLE_TRAINER_ID="3")
        assert env["PADDLE_TRAINER_ID"] == "3"

    def test_scrub_returns_removed_for_restore(self):
        os.environ["PALLAS_AXON_POOL_IPS"] = UNREACHABLE
        try:
            removed = scrub_plugin_vars()
            assert removed["PALLAS_AXON_POOL_IPS"] == UNREACHABLE
            assert "PALLAS_AXON_POOL_IPS" not in os.environ
        finally:
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)


class TestSpawnPathsAreHermetic:
    def test_launch_worker_env_cpu_backend(self):
        from paddle_tpu.distributed.launch.main import _parse, _worker_env
        os.environ["PALLAS_AXON_POOL_IPS"] = UNREACHABLE
        try:
            args = _parse(["--nproc_per_node=2", "--backend=cpu", "x.py"])
            env = _worker_env(args, 0)
            assert "PALLAS_AXON_POOL_IPS" not in env
            assert env["JAX_PLATFORMS"] == "cpu"
            # non-cpu backends keep the parent env untouched
            args = _parse(["--nproc_per_node=2", "x.py"])
            env = _worker_env(args, 0)
            assert env["PALLAS_AXON_POOL_IPS"] == UNREACHABLE
        finally:
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)

    def test_child_with_dead_tunnel_env_runs_cpu(self):
        """End-to-end: parent env points the plugin at an unreachable address;
        a child launched through cpu_child_env must come up on CPU fast
        instead of hanging on the tunnel."""
        base = {**os.environ, "PALLAS_AXON_POOL_IPS": UNREACHABLE,
                "JAX_PLATFORMS": "axon"}
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('BACKEND', jax.default_backend())"],
            env=cpu_child_env(base), capture_output=True, text=True,
            timeout=120)
        assert "BACKEND cpu" in r.stdout, r.stderr[-2000:]

    def test_ps_server_child_is_hermetic(self, tmp_path):
        """start_server_process ships a scrubbed env even when the parent's
        environ points at a dead tunnel."""
        import socket
        import numpy as np
        from paddle_tpu.distributed.ps_sparse import (start_server_process,
                                                      SparsePsClient)
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        os.environ["PALLAS_AXON_POOL_IPS"] = UNREACHABLE
        try:
            p = start_server_process(port, str(tmp_path), ready_timeout=60)
            client = SparsePsClient([f"127.0.0.1:{port}"])
            client.create_table("t", dim=4, capacity_rows_per_server=8,
                                lr=1.0, initializer="zeros")
            out = client.pull("t", np.array([1, 2]))
            assert out.shape == (2, 4)
            client.shutdown()
            p.wait(timeout=10)
        finally:
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)


class TestLaunchBackendProbe:
    def test_dead_tunnel_fails_fast_with_clear_error(self, tmp_path, capsys):
        """An accelerator launch against a dead tunnel must fail in ONE probe
        child with one clear message, not N workers hanging to timeouts."""
        from paddle_tpu.distributed.launch.main import launch
        script = tmp_path / "t.py"
        script.write_text("print('ran')\n")
        os.environ["PALLAS_AXON_POOL_IPS"] = UNREACHABLE
        try:
            rc = launch(["--nproc_per_node=2", "--backend_probe_timeout=20",
                         f"--log_dir={tmp_path}/log", str(script)])
        finally:
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        assert rc == 3
        assert not (tmp_path / "log" / "workerlog.0").exists()

    def test_cpu_backend_skips_probe(self, tmp_path):
        from paddle_tpu.distributed.launch.main import launch
        script = tmp_path / "t.py"
        script.write_text("print('ran')\n")
        os.environ["PALLAS_AXON_POOL_IPS"] = UNREACHABLE
        try:
            rc = launch(["--nproc_per_node=1", "--backend=cpu",
                         f"--log_dir={tmp_path}/log", str(script)])
        finally:
            os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        assert rc == 0
        assert "ran" in (tmp_path / "log" / "workerlog.0").read_text()
