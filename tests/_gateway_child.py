"""Child process for the real-SIGKILL durable-gateway test
(``tests/test_journal.py::TestRealKillNine``).

Builds the deterministic tiny Llama (``pt.seed(0)`` pins the weights, so
token streams match across processes), starts a durable gateway on an
OS-picked port over the journal dir given in argv, and prints
``READY <port>`` once it can serve.  The parent kills this process with
SIGKILL mid-stream, spawns a fresh one on the SAME journal dir, and
expects the spliced stream to be byte-identical.

Usage::

    python tests/_gateway_child.py <journal_dir> [--slow-step SECONDS]

``--slow-step`` paces every engine step through the ``serving.slow_step``
fault point so the parent can reliably kill mid-stream.
"""
import argparse
import sys
import time


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("journal_dir")
    ap.add_argument("--slow-step", type=float, default=0.0)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu.inference.frontend import ReplicaSet, start_gateway
    from paddle_tpu.inference.serving import LLMEngine
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    from paddle_tpu.testing import FAULTS, Always

    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    model = LlamaForCausalLM(cfg)
    model.eval()
    if args.slow_step > 0:
        FAULTS.install("serving.slow_step", Always(), delay=args.slow_step)

    rs = ReplicaSet(
        [LLMEngine(model, max_batch=3, max_len=64, page_size=8,
                   prefix_cache=True) for _ in range(2)],
        requeue=True)
    gw = start_gateway(rs, journal_dir=args.journal_dir,
                       journal_fsync="critical")
    print(f"READY {gw.port}", flush=True)
    try:
        while True:          # serve until the parent kills us
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        gw.close()
        rs.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
