"""Sub-namespace parity sweep + behavior tests for the round-2 fills
(reference __all__ of static/sparse/distribution/vision/transforms/text/io/
jit — all names must resolve)."""
import os
import re

import numpy as np
import pytest

import paddle_tpu as paddle

REF = "/root/reference/python/paddle"


def _ref_all(rel):
    path = f"{REF}/{rel}/__init__.py"
    if not os.path.exists(path):
        pytest.skip("reference checkout not present")
    src = open(path).read()
    m = re.search(r"__all__ = \[(.*?)\]", src, re.S)
    return re.findall(r"'([A-Za-z0-9_]+)'", m.group(1)) if m else []


@pytest.mark.parametrize("rel,mod", [
    ("incubate", "paddle_tpu.incubate"),
    ("utils", "paddle_tpu.utils"),
    ("device", "paddle_tpu.device"),
    ("geometric", "paddle_tpu.geometric"),
    ("profiler", "paddle_tpu.profiler"),
    ("inference", "paddle_tpu.inference"),
    ("static", "paddle_tpu.static"),
    ("sparse", "paddle_tpu.sparse"),
    ("distribution", "paddle_tpu.distribution"),
    ("vision", "paddle_tpu.vision"),
    ("vision/transforms", "paddle_tpu.vision.transforms"),
    ("text", "paddle_tpu.text"),
    ("io", "paddle_tpu.io"),
    ("jit", "paddle_tpu.jit"),
    ("nn", "paddle_tpu.nn"),
    ("nn/functional", "paddle_tpu.nn.functional"),
    ("amp", "paddle_tpu.amp"),
    ("metric", "paddle_tpu.metric"),
    ("optimizer", "paddle_tpu.optimizer"),
])
def test_namespace_covers_reference(rel, mod):
    import importlib
    m = importlib.import_module(mod)
    missing = [n for n in _ref_all(rel) if not hasattr(m, n)]
    assert not missing, f"{rel} missing: {missing}"


class TestStaticCompat:
    def test_append_backward_and_scope(self):
        import paddle_tpu.static as st
        p = st.create_parameter([3], "float32")
        p.stop_gradient = False
        loss = (paddle.to_tensor(np.ones(3, np.float32)) * p).sum()
        pairs = st.append_backward(loss)
        assert pairs and pairs[0][1] is not None
        np.testing.assert_allclose(np.asarray(pairs[0][1]._data), np.ones(3))
        sc = st.Scope()
        with st.scope_guard(sc):
            assert st.global_scope() is sc
        assert st.global_scope() is not sc

    def test_ema_apply_restore(self):
        import paddle_tpu.static as st
        p = paddle.to_tensor(np.ones(2, np.float32))
        ema = st.ExponentialMovingAverage(decay=0.5)
        ema.update([p])
        p._data = p._data * 3
        ema.update()
        cur = np.asarray(p._data).copy()
        with ema.apply():
            avg = np.asarray(p._data)
            np.testing.assert_allclose(avg, [2.0, 2.0])  # 0.5*1 + 0.5*3
        np.testing.assert_array_equal(np.asarray(p._data), cur)

    def test_accuracy_and_auc(self):
        import paddle_tpu.static as st
        probs = paddle.to_tensor(np.array(
            [[0.9, 0.1], [0.2, 0.8], [0.7, 0.3]], np.float32))
        lbl = paddle.to_tensor(np.array([0, 1, 1]))
        acc = float(st.accuracy(probs, lbl)._data)
        np.testing.assert_allclose(acc, 2 / 3, rtol=1e-5)
        auc_t, _, _ = st.auc(probs, lbl)
        assert 0.0 <= float(auc_t._data) <= 1.0

    def test_program_state_roundtrip(self, tmp_path):
        import paddle_tpu.static as st
        st.global_scope()._vars["w"] = paddle.to_tensor(
            np.arange(4, dtype=np.float32))
        st.save(None, str(tmp_path / "prog"))
        st.global_scope()._vars["w"] = paddle.to_tensor(np.zeros(4, np.float32))
        st.load(None, str(tmp_path / "prog"))
        np.testing.assert_array_equal(
            np.asarray(st.global_scope()._vars["w"]._data),
            np.arange(4, dtype=np.float32))


class TestSparseAdditions:
    def _x(self):
        import paddle_tpu.sparse as sp
        return sp.sparse_coo_tensor(np.array([[0, 1], [1, 0]]),
                                    np.array([2.0, 3.0], np.float32), (2, 2))

    def test_mv_addmm_mask_slice(self):
        import paddle_tpu.sparse as sp
        x = self._x()
        v = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        np.testing.assert_allclose(np.asarray(sp.mv(x, v)._data), [4.0, 3.0])
        out = sp.addmm(paddle.to_tensor(np.ones((2, 2), np.float32)), x,
                       paddle.to_tensor(np.eye(2, dtype=np.float32)))
        np.testing.assert_allclose(np.asarray(out._data), [[1, 3], [4, 1]])
        m = sp.mask_as(paddle.to_tensor(
            np.arange(4).reshape(2, 2).astype(np.float32)), x)
        assert m.nnz() == 2
        s = sp.slice(x, [0], [0], [1])
        assert s.shape == [1, 2]
        assert sp.isnan(x).nnz() == 2          # values are False but present


class TestDistributionAdditions:
    def test_lkj_matches_torch(self):
        torch = pytest.importorskip("torch")
        from paddle_tpu.distribution import LKJCholesky
        paddle.seed(0)
        for d, eta in [(3, 1.0), (4, 2.5)]:
            dist = LKJCholesky(d, eta)
            L = np.asarray(dist.sample()._data)
            C = L @ L.T
            np.testing.assert_allclose(np.diag(C), np.ones(d), atol=1e-5)
            ours = float(dist.log_prob(paddle.to_tensor(L))._data)
            ref = float(torch.distributions.LKJCholesky(d, eta).log_prob(
                torch.tensor(L)))
            np.testing.assert_allclose(ours, ref, atol=1e-3)

    def test_exponential_family_entropy_bregman(self):
        """Gaussian in natural form: Bregman entropy equals the closed form."""
        import jax.numpy as jnp
        from paddle_tpu.distribution import ExponentialFamily

        class NatNormal(ExponentialFamily):
            def __init__(self, mu, sigma):
                self.mu, self.sigma = mu, sigma
                super().__init__()

            @property
            def _natural_parameters(self):
                return (jnp.asarray(self.mu / self.sigma ** 2),
                        jnp.asarray(-0.5 / self.sigma ** 2))

            def _log_normalizer(self, n1, n2):
                return -n1 ** 2 / (4 * n2) - 0.5 * jnp.log(-2 * n2)

            @property
            def _mean_carrier_measure(self):
                # E[log h(x)] with h = 1/sqrt(2*pi) (the 2*pi term lives in
                # the carrier, not in this A)
                return -0.5 * np.log(2 * np.pi)

        mu, sigma = 1.3, 0.7
        ent = float(np.asarray(NatNormal(mu, sigma).entropy()._data))
        closed = 0.5 * np.log(2 * np.pi * np.e * sigma ** 2)
        np.testing.assert_allclose(ent, closed, rtol=1e-5)


class TestTransformsAdditions:
    def test_hue_affine_perspective_erase(self):
        import colorsys
        import paddle_tpu.vision.transforms as T
        img = np.random.RandomState(0).randint(0, 255, (8, 8, 3)).astype(np.uint8)
        np.testing.assert_array_equal(T.adjust_hue(img, 0.0), img)
        ref = np.zeros_like(img)
        for y in range(8):
            for x in range(8):
                r, g, b = img[y, x] / 255.0
                h, s, v = colorsys.rgb_to_hsv(r, g, b)
                ref[y, x] = np.round(np.array(
                    colorsys.hsv_to_rgb((h + 0.25) % 1.0, s, v)) * 255)
        assert np.abs(T.adjust_hue(img, 0.25).astype(int)
                      - ref.astype(int)).max() <= 1
        np.testing.assert_array_equal(T.affine(img, angle=0.0), img)
        pts = [(0, 0), (7, 0), (7, 7), (0, 7)]
        np.testing.assert_array_equal(T.perspective(img, pts, pts), img)
        chw = img.transpose(2, 0, 1)          # erase contract is [..., H, W]
        e = T.erase(chw, 1, 2, 3, 4, 0)
        assert (e[:, 1:4, 2:6] == 0).all()
        np.random.seed(0)
        assert T.RandomAffine(15)(img).shape == img.shape
        assert T.RandomPerspective(prob=1.0)(img).shape == img.shape
        assert T.RandomErasing(prob=1.0)(img).shape == img.shape
        assert T.Transpose()(img).shape == (3, 8, 8)

    def test_image_backend(self):
        import paddle_tpu.vision as V
        V.set_image_backend("pil")
        assert V.get_image_backend() == "pil"
        with pytest.raises(ValueError):
            V.set_image_backend("bogus")


class TestIncubateSurface:
    def test_softmax_mask_fuse(self):
        from paddle_tpu.incubate import (softmax_mask_fuse,
                                         softmax_mask_fuse_upper_triangle)
        x = paddle.to_tensor(np.random.RandomState(0).randn(2, 4, 4)
                             .astype(np.float32))
        m = paddle.to_tensor(np.zeros((2, 4, 4), np.float32))
        out = np.asarray(softmax_mask_fuse(x, m)._data)
        np.testing.assert_allclose(out.sum(-1), np.ones((2, 4)), rtol=1e-5)
        tri = np.asarray(softmax_mask_fuse_upper_triangle(x)._data)
        assert np.allclose(np.triu(tri[0], k=1), 0, atol=1e-6)
        np.testing.assert_allclose(tri.sum(-1), np.ones((2, 4)), rtol=1e-5)

    def test_graph_khop_and_weighted_sampling(self):
        import paddle_tpu.geometric as G
        from paddle_tpu.incubate import graph_khop_sampler
        # chain graph 0->1->2->3 in CSC
        row = paddle.to_tensor(np.array([1, 2, 3, 0], np.int64))
        colptr = paddle.to_tensor(np.array([0, 1, 2, 3, 4], np.int64))
        paddle.seed(0)
        src, dst, sample_index = graph_khop_sampler(
            row, colptr, paddle.to_tensor(np.array([0])), [1, 1])
        # 2 hops from node 0 along the chain: edges (1<-0), (2<-1),
        # reindexed so node 0 is index 0, first-seen neighbors follow
        assert np.asarray(src._data).size == 2
        assert np.asarray(dst._data).tolist()[0] == 0
        assert np.asarray(sample_index._data).tolist()[0] == 0
        with pytest.raises(NotImplementedError):
            graph_khop_sampler(row, colptr, paddle.to_tensor(np.array([0])),
                               [1], return_eids=True)
        w = paddle.to_tensor(np.array([1.0, 1.0, 1.0, 1.0], np.float32))
        n, c = G.weighted_sample_neighbors(row, colptr, w,
                                           paddle.to_tensor(np.array([0, 1])),
                                           sample_size=1)
        assert np.asarray(c._data).tolist() == [1, 1]

    def test_require_version_and_device_shims(self):
        import paddle_tpu.utils as U
        import paddle_tpu.device as D
        U.require_version("0.0.0")
        with pytest.raises(Exception):
            U.require_version("999.0.0")
        assert D.get_cudnn_version() is None
        assert D.is_compiled_with_distribute() is True
        assert D.get_all_custom_device_type() == []

    def test_inference_enums(self):
        import paddle_tpu.inference as I
        assert I.get_num_bytes_of_data_type(I.DataType.BFLOAT16) == 2
        assert "paddle_tpu" in I.get_version()


class TestIoJitAdditions:
    def test_subset_random_sampler(self):
        from paddle_tpu.io import SubsetRandomSampler
        s = SubsetRandomSampler([3, 5, 9])
        out = list(iter(s))
        assert sorted(out) == [3, 5, 9] and len(s) == 3

    def test_jit_verbosity_knobs(self):
        import paddle_tpu.jit as jit
        jit.set_verbosity(1)
        jit.set_code_level(50)
        jit.set_verbosity(0)


class TestTimerHelper:
    def test_timer_group_throughput(self):
        from paddle_tpu.distributed.fleet.utils import get_timers
        import time as _time
        timers = get_timers()
        t = timers("step")
        for _ in range(3):
            t.start()
            _time.sleep(0.01)
            t.stop()
        thr = timers.throughput("step", items=300, reset=False)
        assert 300 / 0.2 < thr < 300 / 0.02
        msg = timers.log(["step"])
        assert "step" in msg
        with pytest.raises(RuntimeError):
            t.stop()          # not started


def test_static_release_tape_frees_graph():
    """r2 weak #7: a finished static program's op tape can be dropped."""
    import gc
    import paddle_tpu.static as st

    main = st.Program()
    with st.program_guard(main):
        x = st.data("x", [4])
        h = x * 2.0
        loss = (h + 1.0).sum()
    exe = st.Executor()
    (out,) = exe.run(main, feed={"x": np.ones(4, np.float32)},
                     fetch_list=[loss])
    np.testing.assert_allclose(np.asarray(out), 12.0)
    node = loss._replay_node[0]
    st.release_tape(loss, h)
    main.drop()
    del h
    gc.collect()
    assert loss._replay_node is None
    assert node.in_arrays is None and node.raw_fn is None
    assert all(i is None for i in node.inputs)
