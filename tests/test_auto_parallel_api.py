"""parallelize()/to_distributed()/Engine tests (reference:
auto_parallel/intermediate/parallelize.py:51, high_level_api.py:253,
static/engine.py:99). Runs on the 8-device CPU mesh from conftest."""
import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.distributed as dist
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM


def _mesh(shape=(2, 4), names=("dp", "mp")):
    n = int(np.prod(shape))
    return dist.ProcessMesh(np.arange(n).reshape(shape), list(names))


def _param_spec(p):
    sh = getattr(p._buf, "sharding", None)
    spec = tuple(getattr(sh, "spec", ()) or ())
    while spec and spec[-1] is None:    # normalize trailing Nones
        spec = spec[:-1]
    return spec


def _llama_plan():
    from paddle_tpu.distributed import ColWiseParallel, RowWiseParallel
    return {
        "llama.embed_tokens": ColWiseParallel(),
        "llama.layers.*.self_attn.q_proj": ColWiseParallel(),
        "llama.layers.*.self_attn.k_proj": ColWiseParallel(),
        "llama.layers.*.self_attn.v_proj": ColWiseParallel(),
        "llama.layers.*.self_attn.o_proj": RowWiseParallel(),
        "llama.layers.*.mlp.gate_proj": ColWiseParallel(),
        "llama.layers.*.mlp.up_proj": ColWiseParallel(),
        "llama.layers.*.mlp.down_proj": RowWiseParallel(),
    }


class TestParallelize:
    def test_plan_shards_params(self):
        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=64,
                               intermediate_size=128, vocab_size=128)
        model = LlamaForCausalLM(cfg)
        mesh = _mesh()
        model, _ = dist.parallelize(model, mesh=mesh, config={
            "mp_config": {"parallelize_plan": _llama_plan()},
            "dp_config": {"sharding_level": 3},
        })
        layer = model.llama.layers[0]
        # colwise: out-dim on mp; rowwise: in-dim on mp; ZeRO-3 composes dp on
        # the free dim (the shard_llama P(dp, mp) pattern)
        assert _param_spec(layer.self_attn.q_proj.weight) == ("dp", "mp")
        assert _param_spec(layer.self_attn.o_proj.weight) == ("mp", "dp")
        assert _param_spec(model.llama.embed_tokens.weight) == ("mp", "dp")
        # FSDP catch-all: norm weights sharded on dp when divisible
        ln = layer.input_layernorm.weight
        assert _param_spec(ln) == ("dp",)

    def test_parallelized_model_trains_and_matches_dense(self):
        cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=64,
                               intermediate_size=128, vocab_size=128)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (4, 17)).astype(np.int32)
        x, y = ids[:, :-1], ids[:, 1:]

        def run(parallel):
            paddle.seed(0)
            model = LlamaForCausalLM(cfg)
            if parallel:
                model, _ = dist.parallelize(model, mesh=_mesh(), config={
                    "mp_config": {"parallelize_plan": _llama_plan()},
                    "dp_config": {"sharding_level": 3}})
            opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                         parameters=model.parameters())
            losses = []
            for _ in range(3):
                _, loss = model(paddle.to_tensor(x), labels=paddle.to_tensor(y))
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            return losses

        np.testing.assert_allclose(run(True), run(False), rtol=2e-4, atol=2e-4)


class TestToDistributed:
    def test_auto_plan_detects_projections(self):
        paddle.seed(0)
        cfg = LlamaConfig.tiny(num_hidden_layers=2, hidden_size=64,
                               intermediate_size=128, vocab_size=128)
        model = LlamaForCausalLM(cfg)
        model, _, plan = dist.to_distributed(model, mesh=_mesh())
        tp = plan["tp"]
        assert any(k.endswith("q_proj") and v == "ColWiseParallel"
                   for k, v in tp.items())
        assert any(k.endswith("o_proj") and v == "RowWiseParallel"
                   for k, v in tp.items())
        assert any("embed" in k for k in tp)
        layer = model.llama.layers[0]
        assert _param_spec(layer.self_attn.q_proj.weight) == ("dp", "mp")
        assert _param_spec(layer.mlp.down_proj.weight) == ("mp", "dp")


class TestEngine:
    def _data(self, n=64):
        rng = np.random.RandomState(0)
        xs = rng.randn(n, 8).astype(np.float32)
        w = rng.randn(8, 1).astype(np.float32)
        ys = xs @ w + 0.01 * rng.randn(n, 1).astype(np.float32)
        return xs, ys

    def test_fit_converges(self):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        engine = dist.Engine(model=model, loss=nn.MSELoss(), optimizer=opt,
                             mesh=_mesh((8,), ("dp",)))
        xs, ys = self._data()
        hist = engine.fit((xs, ys), epochs=8, batch_size=16)
        assert hist["loss"][-1] < hist["loss"][0] * 0.5

    def test_evaluate_and_predict(self):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 1))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        engine = dist.Engine(model=model, loss=nn.MSELoss(), optimizer=opt,
                             mesh=_mesh((8,), ("dp",)))
        xs, ys = self._data(32)
        out = engine.evaluate((xs, ys), batch_size=16)
        assert np.isfinite(out["loss"])
        preds = engine.predict((xs, ys), batch_size=16)
        assert len(preds) == 2 and preds[0].shape == (16, 1)

    def test_save_load_roundtrip(self, tmp_path):
        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 1))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        engine = dist.Engine(model=model, loss=nn.MSELoss(), optimizer=opt)
        xs, ys = self._data(32)
        engine.fit((xs, ys), epochs=1, batch_size=16)
        path = str(tmp_path / "engine_ckpt")
        engine.save(path)
        w0 = np.asarray(model[0].weight._buf)
        engine.fit((xs, ys), epochs=1, batch_size=16)
        engine.load(path)
        np.testing.assert_allclose(np.asarray(model[0].weight._buf), w0)

    def test_prepare_is_side_effect_free(self):
        """prepare() warms the compile cache without touching weights or
        optimizer state (the reference Engine.prepare only builds programs)."""
        class _Spec:
            def __init__(self, shape, dtype): self.shape, self.dtype = shape, dtype

        paddle.seed(0)
        model = nn.Sequential(nn.Linear(8, 1))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        engine = dist.Engine(model=model, loss=nn.MSELoss(), optimizer=opt)
        w0 = np.asarray(model[0].weight._buf).copy()
        engine.prepare(_Spec((16, 8), "float32"), _Spec((16, 1), "float32"))
        np.testing.assert_array_equal(np.asarray(model[0].weight._buf), w0)
        # lazily-created Adam moments from the warm-up step were dropped,
        # and the step counter rolled back
        assert all(not store for store in opt._accumulators.values())
        assert int(np.asarray(opt._global_step._data)) == 0
        # and the compiled step is live: fit reuses it and trains normally
        xs, ys = self._data(32)
        hist = engine.fit((xs, ys), epochs=1, batch_size=16)
        assert np.isfinite(hist["loss"][-1])

    def test_accepts_raw_jax_mesh(self):
        import jax
        from jax.sharding import Mesh
        paddle.seed(0)
        devs = np.asarray(jax.devices()[:8], dtype=object)[::-1]  # permuted
        jmesh = Mesh(devs.reshape(8), axis_names=("dp",))
        model = nn.Sequential(nn.Linear(8, 1))
        opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                    parameters=model.parameters())
        engine = dist.Engine(model=model, loss=nn.MSELoss(), optimizer=opt,
                             mesh=jmesh)
        assert engine._mesh.jax_mesh() is jmesh   # device order preserved
        xs, ys = self._data(32)
        out = engine.evaluate((xs, ys), batch_size=16)
        assert np.isfinite(out["loss"])

    def test_strategy_fields(self):
        s = dist.Strategy({"pipeline": {"enable": True, "accumulate_steps": 4},
                           "sharding": {"enable": True, "stage": 2}})
        assert s.pipeline.enable and s.pipeline.accumulate_steps == 4
        assert s.sharding.stage == 2 and s.amp.enable is False
