"""Worker driven by test_launch.py through paddle_tpu.distributed.launch.
Exercises the multi-process bring-up + every explicit collective + a DP train
step whose gradients allreduce across processes. Prints LAUNCH_WORKER_OK on
success; any assert kills the job (the launcher propagates rc)."""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
rank, world = dist.get_rank(), dist.get_world_size()
assert jax.process_count() == world, (jax.process_count(), world)

# ---- explicit collectives ----------------------------------------------------
t = paddle.to_tensor(np.full((4,), float(rank + 1), np.float32))
dist.all_reduce(t)
expect = sum(range(1, world + 1))
np.testing.assert_allclose(t.numpy(), np.full((4,), expect, np.float32))

tl = []
dist.all_gather(tl, paddle.to_tensor(np.full((2,), float(rank), np.float32)))
assert len(tl) == world
for r in range(world):
    np.testing.assert_allclose(tl[r].numpy(), np.full((2,), float(r)))

b = paddle.to_tensor(np.full((3,), float(rank * 10 + 7), np.float32))
dist.broadcast(b, src=0)
np.testing.assert_allclose(b.numpy(), np.full((3,), 7.0))

# scatter: rank 0 hands rank r the value r+100
st = paddle.to_tensor(np.zeros((2,), np.float32))
parts = [paddle.to_tensor(np.full((2,), float(r + 100), np.float32))
         for r in range(world)] if rank == 0 else None
dist.scatter(st, parts, src=0)
np.testing.assert_allclose(st.numpy(), np.full((2,), float(rank + 100)))

# all_to_all: rank r sends value r*10+dst to dst
outs = []
ins = [paddle.to_tensor(np.full((2,), float(rank * 10 + d), np.float32))
       for d in range(world)]
dist.all_to_all(outs, ins)
for srcr in range(world):
    np.testing.assert_allclose(outs[srcr].numpy(),
                               np.full((2,), float(srcr * 10 + rank)))

# reduce_scatter: each dst gets sum_r (r + dst)
rs = paddle.to_tensor(np.zeros((2,), np.float32))
dist.reduce_scatter(rs, [paddle.to_tensor(
    np.full((2,), float(rank + d), np.float32)) for d in range(world)])
np.testing.assert_allclose(rs.numpy(),
                           np.full((2,), float(sum(r + rank for r in range(world)))))

# p2p over the control-plane store
if world >= 2:
    if rank == 0:
        dist.send(paddle.to_tensor(np.arange(3, dtype=np.float32)), dst=1)
    elif rank == 1:
        rv = paddle.to_tensor(np.zeros((3,), np.float32))
        dist.recv(rv, src=0)
        np.testing.assert_allclose(rv.numpy(), np.arange(3, dtype=np.float32))

dist.barrier()

# ---- DP training step: grads must be identical across processes --------------
paddle.seed(0)  # same init on every rank
model = paddle.nn.Linear(8, 4)
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
rng = np.random.RandomState(rank)          # different data per rank
x = paddle.to_tensor(rng.rand(4, 8).astype(np.float32))
y = paddle.to_tensor(rng.rand(4, 4).astype(np.float32))
loss = ((model(x) - y) ** 2).mean()
loss.backward()
for p in model.parameters():               # DP allreduce-mean of grads
    dist.all_reduce(p.grad)
    p.grad.scale_(1.0 / world)
opt.step()
# weights must now be bit-identical everywhere: allgather and compare
wl = []
dist.all_gather(wl, model.weight)
for r in range(world):
    np.testing.assert_allclose(wl[r].numpy(), wl[0].numpy(), atol=0)

print(f"LAUNCH_WORKER_OK rank={rank}/{world}", flush=True)
