"""End-to-end request durability: token-exact resume of partially-streamed
requests across replica death (ISSUE 14).

Layers under test, bottom-up:

- engine resume admission: ``add_request(..., resume_tokens=...)`` folds
  the already-emitted history into the prefill context, so the continued
  decode is byte-identical to the uninterrupted run — greedy AND
  fixed-seed sampling, prefix cache on AND off, at several kill offsets
  including one landing exactly on a page boundary (the parity sweep is
  driven at the engine level, where offsets are exact by construction);
- frontend recovery: a replica killed mid-stream hands its request to a
  survivor with the emitted history re-prefilled; the client's spliced
  stream is byte-identical, ``frontend_resumed_total`` ticks, and the
  survivor's page refcounts audit clean.  The single resume attempt is
  the only line of defence: poisoning it (the ``frontend.resume`` fault
  point) is the one way a partially-streamed request ends FAILED;
- supervisor quarantine (satellite S1): crash-looping into quarantine
  proactively evicts the worker's membership lease — watchers observe
  ``leave`` on their next poll, with the fake clock never advancing past
  the TTL;
- gateway keep-alive (satellite S2): an idle stream carries ``: ping``
  SSE comments, and a client that disconnects before the first token is
  detected by the failing ping write and cancelled on the replica.
"""
import http.client
import json
import socket
import struct
import time

import pytest

import paddle_tpu.observability as obs
from paddle_tpu.inference.engine.request import RequestStatus
from paddle_tpu.testing import FAULTS, Always


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


def _tiny_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def model():
    return _tiny_model()


def _engine(model, **kw):
    from paddle_tpu.inference.serving import LLMEngine
    kw.setdefault("max_batch", 3)
    kw.setdefault("max_len", 64)
    kw.setdefault("page_size", 8)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("debug_refcount_audit", True)
    return LLMEngine(model, **kw)


def _replica_set(model, n=2, **kw):
    from paddle_tpu.inference.frontend import ReplicaSet
    kw.setdefault("requeue", True)
    return ReplicaSet([_engine(model) for _ in range(n)], **kw)


def _run(model, prompt, max_new, seed=None, cache=True, resume=None):
    """One fresh engine, one request, all tokens out."""
    eng = _engine(model, prefix_cache=cache)
    kw = {"max_new_tokens": max_new}
    if seed is None:
        kw["do_sample"] = False
    else:
        kw["do_sample"] = True
        kw["seed"] = seed
    if resume is not None:
        kw["resume_tokens"] = resume
    rid = eng.add_request(list(prompt), **kw)
    eng.run_until_done()
    toks = list(eng.result(rid))
    assert eng.audit_refcounts() == []
    return toks, eng


PROMPT = list(range(1, 17))                  # 16 tokens = 2 full pages


# ----------------------------------------------- engine resume admission (S4)

class TestEngineResumeParity:
    """The seeded-sampling resume parity sweep: token at position p is a
    pure function of (sampling config, context), so re-prefilling
    ``prompt + emitted`` and decoding the remainder must be byte-identical
    to the uninterrupted run — at every offset, with and without the
    prefix cache, greedy and fixed-seed alike."""

    # offset 8 puts prompt(16) + emitted(8) = 24 exactly on a page
    # boundary (page_size=8): the resumed prefill ends flush with a page
    OFFSETS = (1, 8, 11)
    SEEDS = (None, 7, 1234)                  # None = greedy

    @pytest.mark.parametrize("cache", [True, False],
                             ids=["prefix-cache", "no-cache"])
    def test_resume_parity_sweep(self, model, cache):
        n = 12
        for seed in self.SEEDS:
            ref, _ = _run(model, PROMPT, n, seed=seed, cache=cache)
            assert len(ref) == n
            for k in self.OFFSETS:
                got, eng = _run(model, PROMPT, n - k, seed=seed, cache=cache,
                                resume=ref[:k])
                assert ref[:k] + got == ref, (
                    f"seed={seed} offset={k} cache={cache}: resumed tail "
                    f"diverged")
                assert eng.health()["resume_admissions"] == 1

    def test_resume_budget_accounting_respects_max_len(self, model):
        # prompt + resumed history + budget must fit max_len exactly like
        # an uninterrupted request would
        eng = _engine(model, max_len=32)
        with pytest.raises(ValueError):
            eng.add_request(PROMPT, max_new_tokens=8,
                            resume_tokens=list(range(10)), do_sample=False)

    def test_resumed_request_streams_only_new_tokens(self, model):
        # new_tokens() must never replay the resumed history — the client
        # already holds it; the splice depends on this
        ref, _ = _run(model, PROMPT, 8)
        eng = _engine(model)
        rid = eng.add_request(PROMPT, max_new_tokens=5, resume_tokens=ref[:3],
                              do_sample=False)
        out = []
        while not eng.status(rid).terminal or eng.new_tokens(rid):
            eng.step()
            out.extend(eng.new_tokens(rid))
        assert out == ref[3:]


# ------------------------------------------------- frontend resume recovery

class TestFrontendResumeChaos:
    def _kill_at(self, model, offset, seed=None, max_new=16):
        """Kill the serving replica after ``offset`` client-streamed
        tokens; returns (full client stream, handle, replica set)."""
        kw = ({"do_sample": False} if seed is None
              else {"do_sample": True, "seed": seed})
        ref, _ = _run(model, PROMPT, max_new, seed=seed)
        rs = _replica_set(model)
        try:
            # pace decode so the victim cannot finish its whole budget
            # between client pulls — the kill must land mid-request
            FAULTS.install("serving.slow_step", Always(), delay=0.05)
            h = rs.submit(PROMPT, max_new_tokens=max_new, **kw)
            victim = h.replica.name
            s = rs.stream(h)
            got = [next(s) for _ in range(offset)]
            FAULTS.install("frontend.step", Always(),
                           match=lambda ctx: ctx.get("replica") == victim)
            got += [t for t in s]
            FAULTS.reset()
            return ref, got, h, victim, rs
        except BaseException:
            rs.close()
            raise

    @pytest.mark.parametrize("offset", [1, 2, 5])
    def test_kill_mid_decode_greedy_stream_byte_identical(self, model,
                                                          offset):
        obs.enable()
        try:
            ref, got, h, victim, rs = self._kill_at(model, offset)
            try:
                assert h.resumed and not h.requeued
                assert h.replica.name != victim
                assert got == ref
                assert rs.status(h) in (RequestStatus.FINISHED,
                                        RequestStatus.EOS)
                # survivor holds no leaked pages once the request is done
                assert rs.replica(h.replica.name).engine.audit_refcounts() \
                    == []
                text = obs.render_prometheus()
                assert "frontend_resumed_total 1" in text
                assert 'reason="resume"' in text
                assert "frontend_resume_splice_seconds_count 1" in text
            finally:
                rs.close()
        finally:
            obs.disable()
            obs.reset()

    def test_kill_mid_decode_fixed_seed_stream_byte_identical(self, model):
        ref, got, h, victim, rs = self._kill_at(model, 2, seed=77)
        try:
            assert h.resumed and got == ref
            assert rs.status(h) is not RequestStatus.FAILED
        finally:
            rs.close()

    def test_resume_attempt_failure_is_the_only_failed_path(self, model):
        # the acceptance clause: a partially-streamed request only ends
        # FAILED when its single resume attempt ALSO dies
        rs = _replica_set(model)
        try:
            FAULTS.install("serving.slow_step", Always(), delay=0.05)
            h = rs.submit(PROMPT, max_new_tokens=16, do_sample=False)
            victim = h.replica.name
            s = rs.stream(h)
            got = [next(s), next(s)]
            FAULTS.install("frontend.step", Always(),
                           match=lambda ctx: ctx.get("replica") == victim)
            FAULTS.install("frontend.resume", Always())
            got += list(s)
            assert h.resumed
            assert rs.status(h) is RequestStatus.FAILED
            assert "died mid-request" in (rs.request_error(h) or "")
            # FAILED hands back no tokens (the client's stream already
            # holds the partial prefix; result() must not invent a tail)
            toks, status = rs.result(h)
            assert status is RequestStatus.FAILED and toks == []
        finally:
            FAULTS.reset()
            rs.close()

    def test_fully_buffered_victim_finishes_without_reroute(self, model):
        # death after the whole budget already streamed (an RPC batch can
        # deliver the final tokens and then the replica dies before the
        # terminal status round-trip): the dead replica owed nothing but
        # the status, which recovery pins locally — no second decode
        rs = _replica_set(model)
        try:
            ref, _ = _run(model, PROMPT, 4)
            h = rs.submit(PROMPT, max_new_tokens=4, do_sample=False)
            victim = h.replica.name
            s = rs.stream(h)
            got = [next(s) for _ in range(4)]       # full budget client-side
            status = rs._resume(h)                  # recovery path, directly
            assert status is RequestStatus.FINISHED
            assert h.resumed and h.replica.name == victim   # never re-routed
            assert got == ref
            assert rs.result(h) == (ref, RequestStatus.FINISHED)
        finally:
            rs.close()

    def test_result_after_resume_returns_full_stream(self, model):
        # result() on a resumed handle must splice too, not just stream()
        ref, _ = _run(model, PROMPT, 12)
        rs = _replica_set(model)
        try:
            FAULTS.install("serving.slow_step", Always(), delay=0.05)
            h = rs.submit(PROMPT, max_new_tokens=12, do_sample=False)
            victim = h.replica.name
            s = rs.stream(h)
            next(s), next(s)
            FAULTS.install("frontend.step", Always(),
                           match=lambda ctx: ctx.get("replica") == victim)
            list(s)
            FAULTS.reset()
            toks, status = rs.result(h)
            assert toks == ref and status.terminal
        finally:
            rs.close()


# ------------------------------------- supervisor quarantine eviction (S1)

class _CrashedHandle:
    """A process handle that is already dead."""

    def poll(self):
        return 1

    def terminate(self):
        pass

    def kill(self):
        pass

    def wait(self, timeout=None):
        return 1


class _Clock:
    def __init__(self, t=1000.0):
        self.t = float(t)

    def __call__(self):
        return self.t


class TestQuarantineEvictsLease:
    def test_quarantine_evicts_lease_within_one_poll(self, monkeypatch):
        from paddle_tpu.distributed.membership import MembershipService
        from paddle_tpu.distributed.store import TCPStore
        from paddle_tpu.inference.frontend.supervisor import (QUARANTINED,
                                                              WorkerSupervisor)
        monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
        store = TCPStore(is_master=True, timeout=20)
        clock = _Clock()
        svc = MembershipService(store, group="q", ttl=1000.0, clock=clock)
        watcher = svc.watch()
        svc.register("w0", meta={"port": 1})
        assert [(e.kind, e.member.name)
                for e in watcher.poll()] == [("join", "w0")]

        sup = WorkerSupervisor(lambda: _CrashedHandle(), name="w0",
                               clock=clock, sleep=lambda s: None,
                               max_crashes=1, membership=svc)
        sup.start_worker()
        assert sup.tick() == QUARANTINED
        # ONE watcher poll — the fake clock never moved, so this leave can
        # only come from the supervisor's proactive evict, not TTL expiry
        assert [(e.kind, e.member.name)
                for e in watcher.poll()] == [("leave", "w0")]
        assert "w0" not in svc.members()

    def test_quarantine_without_membership_handle_still_quarantines(self):
        from paddle_tpu.inference.frontend.supervisor import (QUARANTINED,
                                                              WorkerSupervisor)
        sup = WorkerSupervisor(lambda: _CrashedHandle(), name="w1",
                               clock=_Clock(), sleep=lambda s: None,
                               max_crashes=1)
        sup.start_worker()
        assert sup.tick() == QUARANTINED


# -------------------------------------- gateway keep-alive + disconnect (S2)

class TestGatewayKeepAlive:
    def _gateway(self, model, ping_interval):
        from paddle_tpu.inference.frontend import start_gateway
        rs = _replica_set(model, n=1)
        gw = start_gateway(rs, ping_interval=ping_interval)
        return gw, rs

    def test_idle_stream_carries_ping_comments(self, model):
        gw, rs = self._gateway(model, ping_interval=0.15)
        try:
            # stall decode so the stream is silent long enough to need pings
            FAULTS.install("serving.slow_step", Always(), delay=0.4)
            body = json.dumps({"prompt": PROMPT, "max_tokens": 2,
                               "stream": True})
            conn = http.client.HTTPConnection(gw.addr, gw.port, timeout=60.0)
            conn.request("POST", "/v1/completions", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            raw = b""
            while b"[DONE]" not in raw:
                chunk = resp.read(64)
                if not chunk:
                    break
                raw += chunk
                if b": ping" in raw and b"data:" not in raw:
                    FAULTS.reset()           # seen a pre-token ping; speed up
            conn.close()
            assert b": ping\n\n" in raw       # keep-alive comment frames
            assert raw.index(b": ping") < raw.index(b"data:")  # before tok 1
            assert b"[DONE]" in raw           # and the stream still completed
        finally:
            FAULTS.reset()
            gw.close()
            rs.close()

    def test_pre_first_token_disconnect_cancels_on_replica(self, model):
        gw, rs = self._gateway(model, ping_interval=0.1)
        try:
            # decode stalled: no token will be ready before the client bails
            FAULTS.install("serving.slow_step", Always(), delay=0.3)
            body = json.dumps({"prompt": PROMPT, "max_tokens": 48,
                               "stream": True})
            conn = http.client.HTTPConnection(gw.addr, gw.port, timeout=60.0)
            conn.request("POST", "/v1/completions", body=body,
                         headers={"Content-Type": "application/json"})
            sock = conn.sock
            resp = conn.getresponse()        # headers arrive before tokens
            # RST on close so the server's next ping write errors instead
            # of filling the kernel buffer
            sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                            struct.pack("ii", 1, 0))
            resp.close()
            sock.close()
            conn.close()                     # gone before the first token
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                statuses = [req.status
                            for r in rs.replicas
                            for req in r.engine._finished.values()]
                if RequestStatus.CANCELLED in statuses:
                    break
                time.sleep(0.1)
            else:
                pytest.fail("pre-first-token disconnect never cancelled "
                            "the request")
        finally:
            FAULTS.reset()
            gw.close()
            rs.close()
