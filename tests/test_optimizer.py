"""Optimizer / LR scheduler / grad clip tests."""
import math

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn as nn
from paddle_tpu.optimizer import SGD, Momentum, Adam, AdamW, RMSProp, Lamb, Adagrad
from paddle_tpu.optimizer import lr as lr_mod


def _quadratic_problem():
    # minimize ||Wx - y||^2 over W
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.rand(16, 4).astype(np.float32))
    y = pt.to_tensor(rng.rand(16, 2).astype(np.float32))
    w = pt.Parameter(np.zeros((4, 2), np.float32))
    return x, y, w


@pytest.mark.parametrize("opt_cls,kw", [
    (SGD, dict(learning_rate=0.3)),
    (Momentum, dict(learning_rate=0.1, momentum=0.9)),
    (Adam, dict(learning_rate=0.1)),
    (AdamW, dict(learning_rate=0.1, weight_decay=0.0)),
    (RMSProp, dict(learning_rate=0.05)),
    (Adagrad, dict(learning_rate=0.5)),
    (Lamb, dict(learning_rate=0.05, lamb_weight_decay=0.0)),
])
def test_optimizers_converge(opt_cls, kw):
    x, y, w = _quadratic_problem()
    opt = opt_cls(parameters=[w], **kw)
    first = last = None
    for i in range(60):
        loss = ((x @ w - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        if i == 0:
            first = loss.item()
        last = loss.item()
    # the problem has ~0.27x irreducible least-squares floor
    assert last < first * 0.35, f"{opt_cls.__name__}: {first} -> {last}"


def test_sgd_exact_update():
    w = pt.Parameter(np.array([1.0, 2.0], np.float32))
    opt = SGD(learning_rate=0.1, parameters=[w])
    (w * w).sum().backward()  # grad = 2w
    opt.step()
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.2, 2.0 - 0.4], rtol=1e-5)


def test_adamw_decoupled_decay():
    w = pt.Parameter(np.array([10.0], np.float32))
    opt = AdamW(learning_rate=0.1, weight_decay=0.5, parameters=[w])
    w.sum().backward()
    opt.step()
    # decoupled: w ← w*(1 - lr*wd) - lr * update(≈1 at t=0)
    expected = 10.0 * (1 - 0.1 * 0.5) - 0.1
    np.testing.assert_allclose(w.numpy(), [expected], rtol=1e-3)


def test_optimizer_state_dict_roundtrip():
    x, y, w = _quadratic_problem()
    opt = Adam(learning_rate=0.1, parameters=[w])
    ((x @ w - y) ** 2).mean().backward()
    opt.step(); opt.clear_grad()
    sd = opt.state_dict()
    w2 = pt.Parameter(np.zeros((4, 2), np.float32))
    opt2 = Adam(learning_rate=0.1, parameters=[w2])
    ((x @ w2 - y) ** 2).mean().backward()
    opt2.step(); opt2.clear_grad()
    opt2.set_state_dict(sd)
    np.testing.assert_allclose(opt2._acc("moment1", w2).numpy(),
                               opt._acc("moment1", w).numpy(), rtol=1e-6)


def test_param_groups_with_different_lr():
    w1 = pt.Parameter(np.array([1.0], np.float32))
    w2 = pt.Parameter(np.array([1.0], np.float32))
    opt = SGD(learning_rate=0.1,
              parameters=[{"params": [w1]},
                          {"params": [w2], "learning_rate": 0.5}])
    (w1 + w2).backward()
    opt.step()
    np.testing.assert_allclose(w1.numpy(), [0.9], rtol=1e-5)
    np.testing.assert_allclose(w2.numpy(), [0.95], rtol=1e-5)


def test_grad_clip_global_norm():
    w = pt.Parameter(np.array([3.0, 4.0], np.float32))  # |g|=10 after *2
    clip = nn.ClipGradByGlobalNorm(1.0)
    opt = SGD(learning_rate=1.0, parameters=[w], grad_clip=clip)
    (w * w).sum().backward()  # grad [6, 8], norm 10
    opt.step()
    # clipped grad = [0.6, 0.8]
    np.testing.assert_allclose(w.numpy(), [3.0 - 0.6, 4.0 - 0.8], rtol=1e-4)


def test_grad_clip_by_value():
    w = pt.Parameter(np.array([3.0], np.float32))
    opt = SGD(learning_rate=1.0, parameters=[w], grad_clip=nn.ClipGradByValue(1.0))
    (w * 5).sum().backward()  # grad 5 -> clip to 1
    opt.step()
    np.testing.assert_allclose(w.numpy(), [2.0], rtol=1e-5)


class TestLRSchedulers:
    def test_step_decay(self):
        s = lr_mod.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(s())
            s.step()
        np.testing.assert_allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025], rtol=1e-6)

    def test_cosine(self):
        s = lr_mod.CosineAnnealingDecay(1.0, T_max=10)
        assert abs(s() - 1.0) < 1e-6
        for _ in range(10):
            s.step()
        assert s() < 1e-6

    def test_warmup_then_constant(self):
        s = lr_mod.LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
        vals = []
        for _ in range(6):
            vals.append(s())
            s.step()
        np.testing.assert_allclose(vals[:4], [0.0, 0.025, 0.05, 0.075], rtol=1e-5)
        assert vals[5] == pytest.approx(0.1)

    def test_scheduler_with_optimizer(self):
        w = pt.Parameter(np.array([1.0], np.float32))
        sched = lr_mod.StepDecay(0.1, step_size=1, gamma=0.1)
        opt = SGD(learning_rate=sched, parameters=[w])
        w.sum().backward()
        opt.step()  # lr 0.1
        np.testing.assert_allclose(w.numpy(), [0.9], rtol=1e-5)
        sched.step()
        w.clear_grad(); w.sum().backward()
        opt.step()  # lr 0.01
        np.testing.assert_allclose(w.numpy(), [0.89], rtol=1e-4)

    def test_cosine_warmup_decay_nlp(self):
        s = lr_mod.CosineAnnealingWithWarmupDecay(max_lr=1.0, min_lr=0.1,
                                                  warmup_step=2, decay_step=10)
        s.step(1)
        assert s() == pytest.approx(0.5)
        s.step(10)
        assert s() == pytest.approx(0.1, abs=1e-6)

    def test_reduce_on_plateau(self):
        s = lr_mod.ReduceOnPlateau(1.0, patience=1, factor=0.5)
        for m in [1.0, 1.0, 1.0, 1.0]:
            s.step(m)
        assert s() < 1.0


class TestMasterWeights:
    """multi_precision keeps a persistent f32 master copy (ADVICE r1 #2)."""

    def test_sub_ulp_updates_accumulate(self):
        # bf16 ulp near 1.0 is ~0.0078; 200 updates of 1e-4 only land if the
        # master f32 copy persists between steps
        p = pt.Parameter(np.ones((8,), np.float32))
        p._buf = p._buf.astype("bfloat16")
        opt = SGD(learning_rate=1e-4, parameters=[p], multi_precision=True)
        for _ in range(200):
            p.grad = pt.to_tensor(np.ones((8,), np.float32))
            opt.step()
            opt.clear_grad()
        mw = opt._accumulators["master_weight"][id(p)]
        np.testing.assert_allclose(np.asarray(mw._buf), 1.0 - 200 * 1e-4,
                                   rtol=1e-5)
        # model copy tracks the master, cast down
        assert np.asarray(p._buf, np.float32)[0] < 1.0

    def test_without_multi_precision_bf16_loses_small_updates(self):
        p = pt.Parameter(np.ones((8,), np.float32))
        p._buf = p._buf.astype("bfloat16")
        opt = SGD(learning_rate=1e-4, parameters=[p], multi_precision=False)
        for _ in range(5):
            p.grad = pt.to_tensor(np.ones((8,), np.float32))
            opt.step()
            opt.clear_grad()
        # documents the bf16 rounding behavior the master path avoids
        assert np.asarray(p._buf, np.float32)[0] == 1.0

    def test_master_weight_in_state_dict_roundtrip(self):
        p = pt.Parameter(np.ones((4,), np.float32))
        p._buf = p._buf.astype("bfloat16")
        opt = AdamW(learning_rate=1e-3, parameters=[p], multi_precision=True)
        p.grad = pt.to_tensor(np.full((4,), 0.5, np.float32))
        opt.step()
        sd = opt.state_dict()
        assert any(k.startswith("master_weight") for k in sd)

        p2 = pt.Parameter(np.ones((4,), np.float32))
        p2._buf = p2._buf.astype("bfloat16")
        opt2 = AdamW(learning_rate=1e-3, parameters=[p2], multi_precision=True)
        opt2.set_state_dict(sd)
        mw2 = opt2._accumulators["master_weight"][id(p2)]
        assert mw2._buf.dtype == np.float32


def test_set_state_dict_preserves_f32_moments_on_bf16_params():
    """Restoring f32 Adam moments into a fresh optimizer over bf16 params must
    NOT downcast them to bf16 (ADVICE r1 #3)."""
    p = pt.Parameter(np.ones((4,), np.float32))
    opt = Adam(learning_rate=1e-3, parameters=[p])
    p.grad = pt.to_tensor(np.full((4,), 0.25, np.float32))
    opt.step()
    sd = opt.state_dict()
    assert sd["moment1_0"]._buf.dtype == np.float32

    p2 = pt.Parameter(np.ones((4,), np.float32))
    p2._buf = p2._buf.astype("bfloat16")
    opt2 = Adam(learning_rate=1e-3, parameters=[p2])
    opt2.set_state_dict(sd)
    m1 = opt2._accumulators["moment1"][id(p2)]
    assert m1._buf.dtype == np.float32
    np.testing.assert_allclose(np.asarray(m1._buf),
                               np.asarray(sd["moment1_0"]._buf))


class TestNewOptimizersVsTorch:
    """NAdam/RAdam/Rprop update math vs torch.optim on identical streams."""

    def _run_pair(self, make_ours, make_torch, steps=5, rtol=2e-4):
        import torch
        rng2 = np.random.RandomState(3)
        w0 = rng2.rand(6, 4).astype(np.float32)
        grads = [rng2.randn(6, 4).astype(np.float32) for _ in range(steps)]
        p = pt.to_tensor(w0.copy(), stop_gradient=False)
        opt = make_ours([p])
        tp = torch.nn.Parameter(torch.from_numpy(w0.copy()))
        topt = make_torch([tp])
        for g in grads:
            p._grad_buf = pt.to_tensor(g)
            opt.step()
            opt.clear_grad()
            tp.grad = torch.from_numpy(g)
            topt.step()
            topt.zero_grad()
        np.testing.assert_allclose(p.numpy(), tp.detach().numpy(),
                                   rtol=rtol, atol=1e-5)

    def test_nadam_matches_torch(self):
        import torch
        self._run_pair(
            lambda ps: pt.optimizer.NAdam(learning_rate=0.01,
                                              parameters=ps),
            lambda ps: torch.optim.NAdam(ps, lr=0.01))

    def test_radam_matches_torch(self):
        import torch
        self._run_pair(
            lambda ps: pt.optimizer.RAdam(learning_rate=0.01,
                                              parameters=ps),
            lambda ps: torch.optim.RAdam(ps, lr=0.01), steps=8)

    def test_rprop_matches_torch(self):
        import torch
        self._run_pair(
            lambda ps: pt.optimizer.Rprop(learning_rate=0.01,
                                              parameters=ps),
            lambda ps: torch.optim.Rprop(ps, lr=0.01), steps=6)

    def test_asgd_sag_semantics(self):
        # constant grads: d/min(m+1,n) == 1 every step -> x -= lr each step
        p = pt.to_tensor(np.ones((4,), np.float32), stop_gradient=False)
        opt = pt.optimizer.ASGD(learning_rate=0.1, batch_num=2,
                                parameters=[p])
        for _ in range(5):
            p._grad_buf = pt.to_tensor(np.ones(4, np.float32))
            opt.step()
            opt.clear_grad()
        np.testing.assert_allclose(p.numpy(), 0.5, rtol=1e-5)
        # alternating batch grads: d averages the two slots
        q = pt.to_tensor(np.zeros((2,), np.float32), stop_gradient=False)
        opt2 = pt.optimizer.ASGD(learning_rate=1.0, batch_num=2,
                                 parameters=[q])
        for g in (2.0, 4.0):
            q._grad_buf = pt.to_tensor(np.full(2, g, np.float32))
            opt2.step()
            opt2.clear_grad()
        # step1: -1*2/1 = -2 ; step2: -(2+4)/2 = -3 -> total -5
        np.testing.assert_allclose(q.numpy(), -5.0, rtol=1e-5)


def test_selected_rows_sparse_embedding_grad():
    """VERDICT r2 §2.1 #12: Embedding(sparse=True) produces a SelectedRows
    row-sparse gradient; SGD applies it as a scatter; result matches the
    dense path exactly."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.core.selected_rows import SelectedRows

    def run(sparse):
        pt.seed(0)
        emb = pt.nn.Embedding(50, 4, sparse=sparse)
        opt = pt.optimizer.SGD(learning_rate=0.1,
                               parameters=emb.parameters())
        ids = pt.to_tensor(np.array([[1, 3, 3], [7, 1, 9]], np.int64))
        for _ in range(3):
            loss = (emb(ids) ** 2).sum()
            loss.backward()
            if sparse:
                assert isinstance(emb.weight.grad, SelectedRows)
                assert emb.weight.grad.shape == [50, 4]
            opt.step()
            opt.clear_grad()
        return np.asarray(emb.weight._data)

    w_sparse = run(True)
    w_dense = run(False)
    np.testing.assert_allclose(w_sparse, w_dense, rtol=1e-6)
    # untouched rows must be bit-identical to init (no dense write happened)
    pt.seed(0)
    w0 = np.asarray(pt.nn.Embedding(50, 4).weight._data)
    touched = {1, 3, 7, 9}
    for r in range(50):
        if r not in touched:
            np.testing.assert_array_equal(w_sparse[r], w0[r])
