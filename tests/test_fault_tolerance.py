"""Fault tolerance: deterministic chaos over the serving engine, the shared
retry helper, the fault-injection harness itself, and the control-plane
store/watchdog robustness paths.

The chaos suite's contract: under injected page-allocation failures, a
poison request, deadline expiries, and cancellations, the engine (a) never
dies, (b) gives every request exactly one typed terminal status, (c) leaks
zero pages (refcount audit runs after every step), and (d) keeps every
surviving greedy request token-exact with a fault-free run."""
import threading
import time

import numpy as np
import pytest

from paddle_tpu.core.retry import RetryError, RetryPolicy, retry_call
from paddle_tpu.testing import FAULTS, FailNth, FailProb, InjectedFault, injected
from paddle_tpu.testing.faults import Always, Never


@pytest.fixture(autouse=True)
def _no_leftover_faults():
    FAULTS.reset()
    yield
    FAULTS.reset()


# ------------------------------------------------------------ fault harness

class TestFaultHarness:
    def test_fail_nth_schedules(self):
        s = FailNth(3)
        assert [s.should_fire(n) for n in (1, 2, 3, 4)] == [
            False, False, True, False]
        s = FailNth({1, 4})
        assert [s.should_fire(n) for n in (1, 2, 3, 4)] == [
            True, False, False, True]
        s = FailNth(2, every=True)
        assert [s.should_fire(n) for n in (1, 2, 3, 9)] == [
            False, True, True, True]

    def test_fail_prob_is_seed_reproducible(self):
        sa, sb = FailProb(0.5, seed=7), FailProb(0.5, seed=7)
        a = [sa.should_fire(n) for n in range(40)]
        b = [sb.should_fire(n) for n in range(40)]
        assert a == b and True in a and False in a
        with pytest.raises(ValueError):
            FailProb(1.5)

    def test_match_does_not_consume_schedule(self):
        # a poison-request matcher must not burn FailNth counts on calls
        # for OTHER requests: calls increments only on matching contexts
        with injected("p", FailNth(1), match=lambda c: c.get("rid") == 9) as pt:
            assert FAULTS.fire("p", rid=1) is None
            assert FAULTS.fire("p", rid=2) is None
            assert pt.calls == 0
            assert FAULTS.fire("p", rid=9) is pt
            assert pt.calls == 1 and pt.fires == 1
        assert not FAULTS.active

    def test_raise_if_and_transient_flag(self):
        with injected("q", Always(), transient=True):
            with pytest.raises(InjectedFault) as ei:
                FAULTS.raise_if("q")
            assert ei.value.transient and ei.value.point == "q"
        with injected("q", Never()):
            FAULTS.raise_if("q")            # never fires

    def test_maybe_fire_is_raise_if_behind_idle_check(self):
        # the one-line production probe: inert with nothing installed,
        # raises when its point fires, and keeps ctx matching intact
        FAULTS.maybe_fire("p", rid=1)       # nothing armed: no-op
        with injected("p", Always(), transient=True):
            with pytest.raises(InjectedFault) as ei:
                FAULTS.maybe_fire("p", rid=1)
            assert ei.value.transient and ei.value.point == "p"
        with injected("p", Always(), match=lambda c: c.get("rid") == 9):
            FAULTS.maybe_fire("p", rid=1)   # context mismatch: no fire
            with pytest.raises(InjectedFault):
                FAULTS.maybe_fire("p", rid=9)
        assert not FAULTS.active

    def test_injected_removes_only_its_point(self):
        FAULTS.install("keep", Always())
        with injected("scoped", Always()):
            assert FAULTS.point("scoped") is not None
        assert FAULTS.point("scoped") is None
        assert FAULTS.point("keep") is not None


# ------------------------------------------------------------- retry helper

class TestRetry:
    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("boom")
            return "ok"

        slept = []
        out = retry_call(flaky, policy=RetryPolicy(max_attempts=5, seed=0),
                         retry_on=(OSError,), sleep=slept.append)
        assert out == "ok" and len(calls) == 3 and len(slept) == 2

    def test_exhaustion_raises_retry_error_with_cause(self):
        def dead():
            raise OSError("down")

        with pytest.raises(RetryError) as ei:
            retry_call(dead, policy=RetryPolicy(max_attempts=3, seed=0),
                       retry_on=(OSError,), op="x", sleep=lambda d: None)
        assert ei.value.attempts == 3
        assert isinstance(ei.value.__cause__, OSError)
        assert "x failed after 3 attempt(s)" in str(ei.value)

    def test_non_matching_error_propagates_immediately(self):
        def bad():
            raise ValueError("logic bug")

        with pytest.raises(ValueError):
            retry_call(bad, retry_on=(OSError,), sleep=lambda d: None)

    def test_backoff_curve_capped_and_jittered_in_range(self):
        p = RetryPolicy(max_attempts=6, base_delay=0.1, max_delay=0.4,
                        multiplier=2.0, seed=3)
        ds = list(p.delays())
        caps = [0.1, 0.2, 0.4, 0.4, 0.4]
        assert len(ds) == 5
        for d, cap in zip(ds, caps):
            assert cap / 2 <= d <= cap          # equal jitter: [cap/2, cap]
        assert ds == list(RetryPolicy(max_attempts=6, base_delay=0.1,
                                      max_delay=0.4, seed=3).delays())

    def test_deadline_stops_before_overrunning_sleep(self):
        now = [0.0]

        def clock():
            return now[0]

        def sleep(d):
            now[0] += d

        def dead():
            raise OSError("down")

        with pytest.raises(RetryError) as ei:
            retry_call(dead, policy=RetryPolicy(
                max_attempts=50, base_delay=1.0, multiplier=1.0,
                jitter=False, deadline=3.5), retry_on=(OSError,),
                sleep=sleep, clock=clock)
        # 1s per sleep: attempts at t=0,1,2,3; the sleep to t=4 would
        # overrun the 3.5s deadline, so exactly 4 attempts happen
        assert ei.value.attempts == 4


# ----------------------------------------------------------- serving chaos

def _tiny_model():
    import paddle_tpu as pt
    from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
    pt.seed(0)
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_hidden_layers=2, num_attention_heads=4,
                      num_key_value_heads=2, max_position_embeddings=128)
    m = LlamaForCausalLM(cfg)
    m.eval()
    return m


class TestServingChaos:
    @pytest.fixture(scope="class")
    def model(self):
        return _tiny_model()

    def _engine(self, model, **kw):
        from paddle_tpu.inference.serving import LLMEngine
        kw.setdefault("max_batch", 3)
        kw.setdefault("max_len", 64)
        kw.setdefault("page_size", 8)
        kw.setdefault("debug_refcount_audit", True)   # audit EVERY step
        return LLMEngine(model, **kw)

    def _prompts(self, n, seed=0):
        rng = np.random.RandomState(seed)
        return [rng.randint(1, 128, (4 + 3 * i,)).astype(np.int32)
                for i in range(n)]

    def test_chaos_survivors_token_exact(self, model):
        """The acceptance chaos run: page-alloc failures + a poison request
        + a deadline expiry during a multi-request serve.  Survivors match
        the fault-free run token for token; every request ends in exactly
        one typed terminal status; the per-step refcount audit stays
        clean."""
        from paddle_tpu.inference.serving import RequestStatus
        prompts = self._prompts(5)

        ref_eng = self._engine(model)
        ref_rids = [ref_eng.add_request(p, max_new_tokens=6) for p in prompts]
        ref_eng.run_until_done()
        ref = {i: ref_eng.result(r) for i, r in enumerate(ref_rids)}

        eng = self._engine(model)
        rids = [eng.add_request(p, max_new_tokens=6) for p in prompts]
        # request #2 is expired before it can finish; #3 is poison (its
        # batched decode dispatches always fail; probes pin the blame)
        eng._waiting[2].deadline = time.perf_counter() - 1.0
        eng._any_deadline = True
        poison = rids[3]
        FAULTS.install("serving.page_alloc", FailNth({2, 5, 9}))
        FAULTS.install(
            "serving.step", Always(),
            match=lambda ctx: (ctx.get("phase") == "decode"
                               and poison in ctx.get("rids", ())))
        eng.run_until_done()
        FAULTS.reset()

        statuses = {i: eng.status(r) for i, r in enumerate(rids)}
        assert statuses[2] == RequestStatus.TIMEOUT
        assert statuses[3] == RequestStatus.FAILED
        assert "InjectedFault" in eng.error(poison)
        for i in (0, 1, 4):                      # the survivors
            assert statuses[i] == RequestStatus.FINISHED
            assert eng.result(rids[i]) == ref[i], i
        assert eng.quarantined == 1 and eng.timeouts == 1
        assert eng.step_failures >= 1
        assert eng.audit_refcounts() == []       # zero leaked pages
        h = eng.health()
        assert h["active_slots"] == 0 and h["waiting"] == 0
        assert h["finished"] == len(rids)

    def test_seeded_probability_chaos_converges(self, model):
        """FailProb page-alloc chaos: allocation randomly (but seed-
        reproducibly) runs dry; every request still finishes and matches
        the fault-free tokens.  ``PADDLE_TPU_FAULT_SEED`` picks the seed —
        CI runs the chaos suites across a fixed seed matrix, and any seed
        must converge (the log artifact names the one that didn't)."""
        import os
        from paddle_tpu.inference.serving import RequestStatus
        fault_seed = int(os.environ.get("PADDLE_TPU_FAULT_SEED", "11"))
        prompts = self._prompts(4, seed=1)
        ref_eng = self._engine(model)
        ref = [ref_eng.add_request(p, max_new_tokens=5) for p in prompts]
        ref_eng.run_until_done()
        eng = self._engine(model)
        rids = [eng.add_request(p, max_new_tokens=5) for p in prompts]
        with injected("serving.page_alloc", FailProb(0.3, seed=fault_seed)):
            eng.run_until_done()
        for rr, r in zip(ref, rids):
            assert eng.status(r) == RequestStatus.FINISHED
            assert eng.result(r) == ref_eng.result(rr)
        assert eng.audit_refcounts() == []

    def test_transient_step_errors_are_retried(self, model):
        from paddle_tpu.inference.serving import RequestStatus
        prompts = self._prompts(3, seed=2)
        ref_eng = self._engine(model)
        ref = [ref_eng.add_request(p, max_new_tokens=5) for p in prompts]
        ref_eng.run_until_done()
        eng = self._engine(model)
        rids = [eng.add_request(p, max_new_tokens=5) for p in prompts]
        with injected("serving.step", FailNth({2, 7}), transient=True):
            eng.run_until_done()
        assert eng.step_retries >= 1 and eng.quarantined == 0
        for rr, r in zip(ref, rids):
            assert eng.status(r) == RequestStatus.FINISHED
            assert eng.result(r) == ref_eng.result(rr)

    def test_poison_prefill_quarantined_without_probes(self, model):
        # prefill is single-slot: attribution is direct, no probe sweep
        from paddle_tpu.inference.serving import RequestStatus
        prompts = self._prompts(3, seed=3)
        eng = self._engine(model)
        rids = [eng.add_request(p, max_new_tokens=4) for p in prompts]
        poison = rids[1]
        FAULTS.install(
            "serving.step", Always(),
            match=lambda ctx: (ctx.get("phase") == "prefill"
                               and poison in ctx.get("rids", ())))
        eng.run_until_done()
        FAULTS.reset()
        assert eng.status(poison) == RequestStatus.FAILED
        assert eng.quarantine_probes == 0
        assert [eng.status(r) for r in rids if r != poison] == [
            RequestStatus.FINISHED] * 2
        assert eng.audit_refcounts() == []

    def test_slow_step_fault_stalls_but_serves(self, model):
        from paddle_tpu.inference.serving import RequestStatus
        eng = self._engine(model)
        rid = eng.add_request([1, 2, 3, 4], max_new_tokens=3)
        t0 = time.perf_counter()
        with injected("serving.slow_step", FailNth(1), delay=0.2):
            eng.run_until_done()
        assert time.perf_counter() - t0 >= 0.2
        assert eng.status(rid) == RequestStatus.FINISHED

    def test_deadline_mid_decode_keeps_partial_output(self, model):
        from paddle_tpu.inference.serving import RequestStatus
        eng = self._engine(model)
        rid = eng.add_request([1, 2, 3, 4], max_new_tokens=50, deadline=30.0)
        for _ in range(4):                       # prefill + a few tokens
            eng.step()
        r = eng._slots[[s is not None for s in eng._slots].index(True)]
        n_before = len(r.out)
        assert n_before >= 1
        r.deadline = time.perf_counter() - 1.0   # force expiry
        eng.step()
        assert eng.status(rid) == RequestStatus.TIMEOUT
        assert len(eng.result(rid)) == n_before  # partial output kept
        assert eng.audit_refcounts() == []

    def test_cancel_during_prefill(self, model):
        from paddle_tpu.inference.serving import RequestStatus
        # prompt spans several prefill chunks; cancel after the first
        eng = self._engine(model, prefill_chunk=8)
        rng = np.random.RandomState(4)
        rid = eng.add_request(rng.randint(1, 128, (30,)), max_new_tokens=4)
        other = eng.add_request(rng.randint(1, 128, (5,)), max_new_tokens=4)
        eng.step()                               # first prefill chunk only
        r = next(s for s in eng._slots if s is not None and s.rid == rid)
        assert r.pos < len(r.prompt)             # genuinely mid-prefill
        assert eng.cancel(rid) is True
        eng.run_until_done()
        assert eng.status(rid) == RequestStatus.CANCELLED
        assert eng.result(rid) == []
        assert eng.status(other) == RequestStatus.FINISHED
        assert eng.audit_refcounts() == []

    def test_cancel_request_sharing_prefix_pages(self, model):
        """Cancelling a request whose pages the prefix cache shares with a
        live request must not free the shared pages out from under it."""
        from paddle_tpu.inference.serving import RequestStatus
        eng = self._engine(model, prefix_cache=True, max_batch=2)
        prompt = list(range(1, 25))              # three full 8-token pages
        a = eng.add_request(prompt, max_new_tokens=8)
        while eng._waiting:                      # admit + let pages register
            eng.step()
        for _ in range(3):
            eng.step()
        b = eng.add_request(prompt, max_new_tokens=8)  # shares a's pages
        while eng._waiting:
            eng.step()
        assert eng.cache_hits > 0                # b really did share pages
        assert eng.cancel(a) is True             # free sharer mid-flight
        eng.step()
        assert eng.audit_refcounts() == []       # shared pages survived
        eng.run_until_done()
        assert eng.status(a) == RequestStatus.CANCELLED
        assert eng.status(b) == RequestStatus.FINISHED
        assert len(eng.result(b)) == 8
        assert eng.audit_refcounts() == []

    def test_cancel_waiting_and_unknown(self, model):
        from paddle_tpu.inference.serving import RequestStatus
        eng = self._engine(model, max_batch=1)
        busy = eng.add_request([1, 2, 3], max_new_tokens=4)
        queued = eng.add_request([4, 5, 6], max_new_tokens=4)
        eng.step()
        assert eng.cancel(queued) is True        # still waiting: dequeued
        assert eng.cancel(queued) is False       # already terminal
        assert eng.cancel(10_000) is False       # unknown rid
        eng.run_until_done()
        assert eng.status(queued) == RequestStatus.CANCELLED
        assert eng.status(busy) == RequestStatus.FINISHED

    def test_admission_control_sheds_on_queue_bound(self, model):
        from paddle_tpu.inference.serving import RequestStatus
        eng = self._engine(model, max_batch=1, max_waiting=2)
        rids = [eng.add_request([1, 2, 3], max_new_tokens=3)
                for _ in range(5)]
        # nothing has been admitted to a slot yet, so all five queue:
        # the bound of 2 sheds the last three
        shed = [r for r in rids if eng.status(r) == RequestStatus.SHED]
        assert len(shed) == 3 and eng.shed_requests == 3
        eng.run_until_done()
        for r in rids:
            if r not in shed:
                assert eng.status(r) == RequestStatus.FINISHED
        # terminal statuses also reached the metrics registry mirror
        assert eng.health()["shed_requests"] == 3

    def test_shed_terminal_counters_in_registry(self, model):
        from paddle_tpu import observability as obs
        obs.reset()
        obs.enable()
        try:
            eng = self._engine(model, max_batch=1, max_waiting=1)
            rids = [eng.add_request([1, 2], max_new_tokens=2)
                    for _ in range(4)]
            eng.run_until_done()
            snap = obs.snapshot(prefix="serving_terminal_requests_total")
            series = snap["serving_terminal_requests_total"]["series"]
            mine = {s["labels"]["status"]: s["value"] for s in series
                    if s["labels"]["engine"] == eng._m.label}
            assert mine.get("shed") == 3
            assert mine.get("finished") == 1
            assert rids
        finally:
            obs.disable()
            obs.reset()


# ------------------------------------------------------ store + watchdog

class TestControlPlaneFaults:
    def test_store_reconnect_with_injected_drops(self, monkeypatch):
        from paddle_tpu.distributed.store import TCPStore
        monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
        master = TCPStore(is_master=True, timeout=20)
        # first two connect attempts fail; backoff retries land the third
        with injected("store.connect", FailNth({1, 2})) as point:
            client = TCPStore(host="127.0.0.1", port=master.port, timeout=20)
        assert point.fires == 2 and point.calls == 3
        master.set("k", {"v": 1})
        assert client.get("k") == {"v": 1}

    def test_store_connect_exhaustion_times_out(self, monkeypatch):
        from paddle_tpu.distributed.store import TCPStore
        monkeypatch.setenv("PADDLE_TPU_PURE_PY_STORE", "1")
        master = TCPStore(is_master=True, timeout=20)
        with injected("store.connect", Always()):
            with pytest.raises(TimeoutError, match="could not reach"):
                TCPStore(host="127.0.0.1", port=master.port, timeout=0.3)

    def test_watchdog_timeout_counter(self):
        from paddle_tpu import observability as obs
        from paddle_tpu.distributed.watchdog import CommTaskManager
        mgr = CommTaskManager()                  # private, not the singleton
        obs.reset()
        obs.enable()
        try:
            fired = threading.Event()
            mgr.enable(timeout=0.05, poll_interval=0.01,
                       on_timeout=lambda t: fired.set())
            seq = mgr.begin("all_reduce", rank=0)
            assert seq > 0
            assert fired.wait(5.0)
            mgr.disable()
            child = obs.COMM_WATCHDOG_TIMEOUTS.labels(op="all_reduce")
            assert child.value >= 1.0
        finally:
            mgr.disable()
            obs.disable()
            obs.reset()
