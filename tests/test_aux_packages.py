"""geometric / audio / text / vision.datasets / onnx package tests."""
import gzip
import os
import pickle
import struct
import tarfile
import wave

import numpy as np
import pytest

import paddle_tpu as paddle

rng = np.random.RandomState(0)


class TestGeometric:
    def test_segment_ops(self):
        from paddle_tpu import geometric as G
        data = paddle.to_tensor(np.array([[1., 2.], [3., 4.], [5., 6.]],
                                         np.float32))
        ids = paddle.to_tensor(np.array([0, 0, 1]))
        np.testing.assert_allclose(G.segment_sum(data, ids).numpy(),
                                   [[4, 6], [5, 6]])
        np.testing.assert_allclose(G.segment_mean(data, ids).numpy(),
                                   [[2, 3], [5, 6]])
        np.testing.assert_allclose(G.segment_max(data, ids).numpy(),
                                   [[3, 4], [5, 6]])
        np.testing.assert_allclose(G.segment_min(data, ids).numpy(),
                                   [[1, 2], [5, 6]])

    def test_send_u_recv_matches_manual(self):
        from paddle_tpu import geometric as G
        x = rng.rand(5, 3).astype(np.float32)
        src = np.array([0, 1, 2, 3])
        dst = np.array([1, 2, 1, 0])
        out = G.send_u_recv(paddle.to_tensor(x), paddle.to_tensor(src),
                            paddle.to_tensor(dst), reduce_op="sum").numpy()
        want = np.zeros((5, 3), np.float32)
        for s, d in zip(src, dst):
            want[d] += x[s]
        np.testing.assert_allclose(out, want, rtol=1e-6)
        # max on empty segments must be 0, not -inf
        outm = G.send_u_recv(paddle.to_tensor(x), paddle.to_tensor(src),
                             paddle.to_tensor(dst), reduce_op="max").numpy()
        assert np.isfinite(outm).all()
        assert (outm[4] == 0).all()

    def test_send_ue_recv_and_uv(self):
        from paddle_tpu import geometric as G
        x = rng.rand(4, 2).astype(np.float32)
        e = rng.rand(3, 2).astype(np.float32)
        src = np.array([0, 1, 2])
        dst = np.array([1, 0, 3])
        out = G.send_ue_recv(paddle.to_tensor(x), paddle.to_tensor(e),
                             paddle.to_tensor(src), paddle.to_tensor(dst),
                             message_op="mul", reduce_op="sum").numpy()
        want = np.zeros((4, 2), np.float32)
        for i, (s, d) in enumerate(zip(src, dst)):
            want[d] += x[s] * e[i]
        np.testing.assert_allclose(out, want, rtol=1e-5)
        uv = G.send_uv(paddle.to_tensor(x), paddle.to_tensor(x),
                       paddle.to_tensor(src), paddle.to_tensor(dst),
                       message_op="add").numpy()
        np.testing.assert_allclose(uv, x[src] + x[dst], rtol=1e-6)

    def test_segment_ops_differentiable(self):
        from paddle_tpu import geometric as G
        x = paddle.to_tensor(rng.rand(4, 2).astype(np.float32),
                             stop_gradient=False)
        ids = paddle.to_tensor(np.array([0, 1, 0, 1]))
        G.segment_sum(x, ids).sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones((4, 2)))


class TestAuxRegressions:
    def test_segment_minmax_empty_segments_zero(self):
        from paddle_tpu import geometric as G
        data = paddle.to_tensor(np.array([[1., 2.], [3., 4.]], np.float32))
        ids = paddle.to_tensor(np.array([0, 2]))  # segment 1 empty
        mx = G.segment_max(data, ids).numpy()
        mn = G.segment_min(data, ids).numpy()
        assert np.isfinite(mx).all() and np.isfinite(mn).all()
        assert (mx[1] == 0).all() and (mn[1] == 0).all()

    def test_sample_neighbors_varies_across_calls(self):
        from paddle_tpu import geometric as G
        # star graph: node 0 has 20 neighbors
        row = paddle.to_tensor(np.arange(1, 21))
        colptr = paddle.to_tensor(np.array([0, 20] + [20] * 20))
        nodes = paddle.to_tensor(np.array([0]))
        draws = {tuple(sorted(G.sample_neighbors(row, colptr, nodes,
                                                 sample_size=5)[0]
                             .numpy().tolist())) for _ in range(6)}
        assert len(draws) > 1  # not the same sample every call

    def test_audio_dataset_split_covers_all_classes(self, tmp_path):
        import paddle_tpu.audio as A
        from paddle_tpu.audio.datasets import TESS
        for c in ("angry", "happy"):
            os.makedirs(tmp_path / c)
            for i in range(5):
                sig = rng.rand(1, 160).astype(np.float32) * 0.1
                A.save(str(tmp_path / c / f"{i}.wav"),
                       paddle.to_tensor(sig), 16000)
        tr = TESS(mode="train", data_dir=str(tmp_path))
        te = TESS(mode="dev", data_dir=str(tmp_path))
        assert sorted(set(tr._labels)) == [0, 1]
        assert sorted(set(te._labels)) == [0, 1]
        # spectrogram feat_type works (sr-independent feature)
        sp = TESS(mode="train", data_dir=str(tmp_path),
                  feat_type="spectrogram", n_fft=64, hop_length=32)
        x, y = sp[0]
        assert x.shape[1] == 33
        assert sp._feature(16000) is sp._feature(16000)  # built once

    def test_wav_8_and_32_bit_roundtrip(self, tmp_path):
        import paddle_tpu.audio as A
        sig = (0.25 * np.sin(2 * np.pi * 440 * np.arange(800) / 16000)
               ).astype(np.float32)[None, :]
        for bits, atol in ((8, 2e-2), (32, 1e-6)):
            p = str(tmp_path / f"t{bits}.wav")
            A.save(p, paddle.to_tensor(sig), 16000, bits_per_sample=bits)
            assert A.info(p).bits_per_sample == bits
            assert A.info(p).num_samples == 800
            back, sr = A.load(p)
            np.testing.assert_allclose(back.numpy(), sig, atol=atol)

    def test_imdb_shared_vocab_across_modes(self, tmp_path):
        from paddle_tpu.text.datasets import Imdb
        import io as _io
        tarp = str(tmp_path / "aclImdb.tar.gz")
        reviews = {
            "aclImdb/train/pos/0.txt": b"great movie wonderful " * 60,
            "aclImdb/train/neg/0.txt": b"bad movie terrible " * 60,
            "aclImdb/test/pos/0.txt": b"wonderful film great " * 60,
            "aclImdb/test/neg/0.txt": b"terrible film bad " * 60,
        }
        with tarfile.open(tarp, "w:gz") as tf:
            for name, data in reviews.items():
                ti = tarfile.TarInfo(name)
                ti.size = len(data)
                tf.addfile(ti, _io.BytesIO(data))
        tr = Imdb(data_file=tarp, mode="train", cutoff=50)
        te = Imdb(data_file=tarp, mode="test", cutoff=50)
        assert tr.word_idx == te.word_idx  # one shared vocabulary
        assert len(tr) == 2 and len(te) == 2

    def test_imikolov_missing_member_raises(self, tmp_path):
        from paddle_tpu.text.datasets import Imikolov
        import io as _io
        tarp = str(tmp_path / "wrong.tgz")
        with tarfile.open(tarp, "w:gz") as tf:
            data = b"hello world\n"
            ti = tarfile.TarInfo("./other/path.txt")
            ti.size = len(data)
            tf.addfile(ti, _io.BytesIO(data))
        with pytest.raises(ValueError, match="no member"):
            Imikolov(data_file=tarp, mode="train")


class TestAudioFunctional:
    def test_mel_hz_roundtrip(self):
        from paddle_tpu.audio import functional as F
        for htk in (False, True):
            f = 440.0
            assert abs(F.mel_to_hz(F.hz_to_mel(f, htk), htk) - f) < 1e-2

    def test_fbank_matrix_rows_cover_band(self):
        from paddle_tpu.audio import functional as F
        fb = F.compute_fbank_matrix(16000, 512, n_mels=40).numpy()
        assert fb.shape == (40, 257)
        assert (fb >= 0).all()
        assert (fb.sum(1) > 0).all()   # every filter has support

    def test_windows_match_scipy(self):
        import scipy.signal.windows as sw
        from paddle_tpu.audio import functional as F
        for name, sfn in [("hann", sw.hann), ("hamming", sw.hamming),
                          ("blackman", sw.blackman),
                          ("bartlett", sw.bartlett),
                          ("nuttall", sw.nuttall), ("triang", sw.triang),
                          ("bohman", sw.bohman)]:
            got = F.get_window(name, 32, fftbins=True).numpy()
            want = sfn(32, sym=False)
            np.testing.assert_allclose(got, want, atol=1e-6, err_msg=name)
        got = F.get_window(("kaiser", 12.0), 32, fftbins=True).numpy()
        np.testing.assert_allclose(got, sw.kaiser(32, 12.0, sym=False),
                                   atol=1e-6)
        got = F.get_window(("gaussian", 7.0), 32, fftbins=True).numpy()
        np.testing.assert_allclose(got, sw.gaussian(32, 7.0, sym=False),
                                   atol=1e-6)

    def test_power_to_db(self):
        from paddle_tpu.audio import functional as F
        s = paddle.to_tensor(np.array([1.0, 0.1, 1e-12], np.float32))
        db = F.power_to_db(s, top_db=80.0).numpy()
        assert abs(db[0]) < 1e-5 and abs(db[1] + 10) < 1e-4
        assert db[2] >= db[0] - 80 - 1e-4

    def test_create_dct_orthonormal(self):
        from paddle_tpu.audio import functional as F
        d = F.create_dct(8, 8).numpy()
        np.testing.assert_allclose(d.T @ d, np.eye(8), atol=1e-5)


class TestAudioFeatures:
    def test_mel_pipeline_shapes_and_finite(self):
        from paddle_tpu.audio.features import (Spectrogram, MelSpectrogram,
                                               LogMelSpectrogram, MFCC)
        wav = paddle.to_tensor(
            np.sin(2 * np.pi * 440 * np.arange(8000) / 16000)
            .astype(np.float32)[None, :])
        spec = Spectrogram(n_fft=512, hop_length=160)(wav)
        assert spec.shape[1] == 257
        mel = MelSpectrogram(sr=16000, n_fft=512, hop_length=160,
                             n_mels=40)(wav)
        assert mel.shape[1] == 40
        logmel = LogMelSpectrogram(sr=16000, n_fft=512, hop_length=160,
                                   n_mels=40)(wav)
        assert np.isfinite(logmel.numpy()).all()
        mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=512, hop_length=160,
                    n_mels=40)(wav)
        assert mfcc.shape[1] == 13

    def test_spectrogram_peak_at_tone_bin(self):
        from paddle_tpu.audio.features import Spectrogram
        sr, f0 = 16000, 1000.0
        wav = paddle.to_tensor(
            np.sin(2 * np.pi * f0 * np.arange(sr) / sr)
            .astype(np.float32)[None, :])
        spec = Spectrogram(n_fft=512, hop_length=256)(wav).numpy()[0]
        peak_bin = spec.mean(-1).argmax()
        assert abs(peak_bin - round(f0 * 512 / sr)) <= 1


class TestAudioIO:
    def test_wav_save_load_roundtrip(self, tmp_path):
        import paddle_tpu.audio as A
        path = str(tmp_path / "t.wav")
        sig = (0.5 * np.sin(2 * np.pi * 440 * np.arange(1600) / 16000)
               ).astype(np.float32)[None, :]
        A.save(path, paddle.to_tensor(sig), 16000)
        back, sr = A.load(path)
        assert sr == 16000
        np.testing.assert_allclose(back.numpy(), sig, atol=1e-3)
        meta = A.info(path)
        assert meta.sample_rate == 16000 and meta.num_samples == 1600


class TestViterbi:
    def _brute(self, emit, trans, length):
        T, N = emit.shape
        best, path = -np.inf, None
        import itertools
        for seq in itertools.product(range(N), repeat=length):
            s = emit[0, seq[0]] + sum(
                trans[seq[i - 1], seq[i]] + emit[i, seq[i]]
                for i in range(1, length))
            if s > best:
                best, path = s, seq
        return best, list(path)

    def test_matches_brute_force(self):
        from paddle_tpu.text import viterbi_decode
        B, T, N = 3, 5, 4
        emit = rng.rand(B, T, N).astype(np.float32)
        trans = rng.rand(N, N).astype(np.float32)
        lens = np.array([5, 3, 4])
        scores, paths = viterbi_decode(
            paddle.to_tensor(emit), paddle.to_tensor(trans),
            paddle.to_tensor(lens), include_bos_eos_tag=False)
        scores, paths = scores.numpy(), paths.numpy()
        for b in range(B):
            want_s, want_p = self._brute(emit[b], trans, lens[b])
            np.testing.assert_allclose(scores[b], want_s, rtol=1e-5)
            assert paths[b, :lens[b]].tolist() == want_p
            assert (paths[b, lens[b]:] == 0).all()

    def test_decoder_layer(self):
        from paddle_tpu.text import ViterbiDecoder
        N = 3
        dec = ViterbiDecoder(rng.rand(N + 2, N + 2).astype(np.float32),
                             include_bos_eos_tag=True)
        emit = paddle.to_tensor(rng.rand(2, 4, N + 2).astype(np.float32))
        scores, paths = dec(emit, paddle.to_tensor(np.array([4, 2])))
        assert scores.shape == [2] and paths.shape == [2, 4]


class TestVisionDatasets:
    def _write_idx(self, tmp, images, labels):
        ip = os.path.join(tmp, "img.idx.gz")
        lp = os.path.join(tmp, "lbl.idx.gz")
        with gzip.open(ip, "wb") as f:
            f.write(struct.pack(">IIII", 2051, *images.shape))
            f.write(images.tobytes())
        with gzip.open(lp, "wb") as f:
            f.write(struct.pack(">II", 2049, len(labels)))
            f.write(labels.tobytes())
        return ip, lp

    def test_mnist_idx_parsing(self, tmp_path):
        from paddle_tpu.vision.datasets import MNIST
        imgs = rng.randint(0, 255, (10, 28, 28)).astype(np.uint8)
        lbls = rng.randint(0, 10, 10).astype(np.uint8)
        ip, lp = self._write_idx(str(tmp_path), imgs, lbls)
        ds = MNIST(image_path=ip, label_path=lp)
        assert len(ds) == 10
        x, y = ds[3]
        np.testing.assert_allclose(x, imgs[3].astype(np.float32))
        assert y == int(lbls[3])

    def test_cifar10_tar_parsing(self, tmp_path):
        from paddle_tpu.vision.datasets import Cifar10
        data = rng.randint(0, 255, (8, 3072)).astype(np.uint8)
        labels = rng.randint(0, 10, 8).tolist()
        tarp = str(tmp_path / "cifar-10.tar.gz")
        batch = {b"data": data, b"labels": labels}
        import io as _io
        with tarfile.open(tarp, "w:gz") as tf:
            payload = pickle.dumps(batch)
            ti = tarfile.TarInfo("cifar-10-batches-py/data_batch_1")
            ti.size = len(payload)
            tf.addfile(ti, _io.BytesIO(payload))
        ds = Cifar10(data_file=tarp, mode="train")
        assert len(ds) == 8
        x, y = ds[0]
        assert x.shape == (3, 32, 32)
        assert y == labels[0]

    def test_missing_file_raises_clearly(self):
        from paddle_tpu.vision.datasets import MNIST
        with pytest.raises(RuntimeError, match="cannot download"):
            MNIST(image_path="/nonexistent", label_path="/nonexistent")

    def test_dataset_folder(self, tmp_path):
        from paddle_tpu.vision.datasets import DatasetFolder
        for c in ("cat", "dog"):
            os.makedirs(tmp_path / c)
            for i in range(3):
                np.save(tmp_path / c / f"{i}.npy",
                        rng.rand(4, 4).astype(np.float32))
        ds = DatasetFolder(str(tmp_path))
        assert len(ds) == 6 and ds.classes == ["cat", "dog"]
        x, y = ds[0]
        assert x.shape == (4, 4) and y == 0


class TestTextDatasets:
    def test_uci_housing(self, tmp_path):
        from paddle_tpu.text.datasets import UCIHousing
        raw = rng.rand(50, 14).astype(np.float32)
        p = str(tmp_path / "housing.data")
        np.savetxt(p, raw)
        tr = UCIHousing(data_file=p, mode="train")
        te = UCIHousing(data_file=p, mode="test")
        assert len(tr) == 40 and len(te) == 10
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)

    def test_imikolov_ngrams(self, tmp_path):
        from paddle_tpu.text.datasets import Imikolov
        tarp = str(tmp_path / "simple-examples.tgz")
        text = "the cat sat on the mat\nthe dog sat on the log\n" * 30
        import io as _io
        with tarfile.open(tarp, "w:gz") as tf:
            data = text.encode()
            ti = tarfile.TarInfo("./simple-examples/data/ptb.train.txt")
            ti.size = len(data)
            tf.addfile(ti, _io.BytesIO(data))
        ds = Imikolov(data_file=tarp, window_size=3, mode="train",
                      min_word_freq=10)
        assert len(ds) > 0
        assert ds[0].shape == (3,)


class TestOnnxGate:
    def test_export_requires_input_spec(self):
        """export() is real now (tests/test_onnx_export.py); the remaining
        gate is the input_spec requirement."""
        import paddle_tpu.onnx as onnx_mod
        with pytest.raises(ValueError, match="input_spec"):
            onnx_mod.export(None, "/tmp/x.onnx")


def test_profiler_summary_statistics():
    """VERDICT r2 #8: Profiler.summary() prints aggregated per-op tables with
    times for a profiled train step (reference profiler_statistic.py)."""
    import numpy as np
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.profiler as profiler

    pt.seed(0)
    lin = nn.Linear(8, 4)
    opt = pt.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
    x = pt.to_tensor(np.random.RandomState(0).rand(16, 8).astype(np.float32))
    y = pt.to_tensor(np.random.RandomState(1).rand(16, 4).astype(np.float32))
    prof = profiler.Profiler(timer_only=True)
    prof.start()
    for _ in range(3):
        with profiler.RecordEvent("train_step"):
            loss = ((lin(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        prof.step()
    prof.stop()
    out = prof.summary(sorted_by=profiler.SortedKeys.CPUTotal)
    assert "Overview" in out and "avg=" in out
    assert "Operator (host dispatch" in out
    # top-k op rows with call counts and times: the step's ops ran 3x each
    assert "linear" in out and "calls" in out.lower()
    assert prof._op_recorder.ops["linear"][0] == 3
    assert "train_step" in out            # user RecordEvent table
    # dispatch hook uninstalled after stop
    from paddle_tpu.core.dispatch import _state
    assert _state.op_recorder is None
