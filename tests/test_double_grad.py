"""Higher-order autograd tests (VERDICT #8): create_graph=True double grad via
tape-recorded vjps (reference: fluid/eager double-grad + python/paddle/autograd
grad(create_graph=True))."""
import numpy as np
import pytest

import paddle_tpu as paddle


def test_second_order_polynomial():
    x = paddle.to_tensor(np.array([1.5, -2.0, 3.0], np.float32),
                         stop_gradient=False)
    y = (x ** 3).sum()
    (g1,) = paddle.grad(y, [x], create_graph=True)
    np.testing.assert_allclose(g1.numpy(), 3 * x.numpy() ** 2, rtol=1e-5)
    (g2,) = paddle.grad(g1.sum(), [x])
    np.testing.assert_allclose(g2.numpy(), 6 * x.numpy(), rtol=1e-5)


def test_third_order():
    x = paddle.to_tensor(np.array([1.2], np.float32), stop_gradient=False)
    (g1,) = paddle.grad((x ** 4).sum(), [x], create_graph=True)
    (g2,) = paddle.grad(g1.sum(), [x], create_graph=True)
    (g3,) = paddle.grad(g2.sum(), [x])
    np.testing.assert_allclose(g3.numpy(), [24 * 1.2], rtol=1e-4)


def test_mixed_partials_through_network():
    """d/dw of ||dL/dx|| through a small MLP (the double-backward shape WGAN-GP
    uses); verified against finite differences."""
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.Tanh(),
                               paddle.nn.Linear(8, 1))
    rng = np.random.RandomState(0)
    xv = rng.rand(3, 4).astype(np.float32)

    def penalty():
        x = paddle.to_tensor(xv, stop_gradient=False)
        out = net(x).sum()
        (gx,) = paddle.grad(out, [x], create_graph=True)
        return ((gx ** 2).sum(axis=1).sqrt() - 1.0).pow(2).mean()

    gp = penalty()
    gp.backward()
    w = net[0].weight
    analytic = w.grad.numpy().copy()

    # central finite differences on two scattered weight entries
    for (i, j) in [(0, 0), (2, 5)]:
        eps = 1e-3
        orig = float(w.numpy()[i, j])
        for sgn, store in ((1, "hi"), (-1, "lo")):
            wm = w.numpy().copy()
            wm[i, j] = orig + sgn * eps
            w.set_value(paddle.to_tensor(wm))
            val = float(penalty())
            if store == "hi":
                hi = val
            else:
                lo = val
        wm = w.numpy().copy()
        wm[i, j] = orig
        w.set_value(paddle.to_tensor(wm))
        fd = (hi - lo) / (2 * eps)
        np.testing.assert_allclose(analytic[i, j], fd, atol=5e-3, rtol=5e-2,
                                   err_msg=f"weight[{i},{j}]")


def test_gradient_penalty_training_step():
    """VERDICT #8 done-criterion: a WGAN-GP-style step with a gradient penalty
    optimizes without error and the penalty decreases."""
    paddle.seed(0)
    disc = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
                                paddle.nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=disc.parameters())
    rng = np.random.RandomState(0)
    data = rng.rand(16, 8).astype(np.float32)
    vals = []
    for step in range(25):
        x = paddle.to_tensor(data, stop_gradient=False)
        out = (disc(x) * 5.0).sum()        # scale so ||grad_x|| starts far from 1
        (gx,) = paddle.grad(out, [x], create_graph=True)
        gp = ((gx ** 2).sum(axis=1).sqrt() - 1.0).pow(2).mean()
        gp.backward()
        opt.step()
        opt.clear_grad()
        vals.append(float(gp))
    assert vals[-1] < vals[0] * 0.2, (vals[0], vals[-1])


def test_create_graph_grads_have_graph():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    (g,) = paddle.grad((x ** 2).sum(), [x], create_graph=True)
    assert g._grad_node is not None          # differentiable
    (g_plain,) = paddle.grad((x ** 2).sum(), [x])
    assert g_plain._grad_node is None        # first-order: detached


def test_hessian_vector_product():
    """HVP via grad-of-(grad·v) — the canonical double-grad composition."""
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32),
                         stop_gradient=False)
    v = paddle.to_tensor(np.array([0.5, -1.0], np.float32))
    # f = x0^2 * x1 ; H = [[2*x1, 2*x0], [2*x0, 0]]
    f = (x[0] ** 2) * x[1]
    (g,) = paddle.grad(f, [x], create_graph=True)
    (hv,) = paddle.grad((g * v).sum(), [x])
    H = np.array([[2 * 2.0, 2 * 1.0], [2 * 1.0, 0.0]], np.float32)
    np.testing.assert_allclose(hv.numpy(), H @ v.numpy(), rtol=1e-5)
