"""Hybrid-parallel training on a device mesh (8 virtual CPU devices here;
the same code runs on a real TPU pod slice — GSPMD inserts the collectives).

Run:
  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu \
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/sharded_train.py
"""
import os
import sys

# runnable from any cwd: the repo root (one level up) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.distributed import Shard, shard_tensor
from paddle_tpu.distributed.fleet.topology import (
    CommunicateTopology, HybridCommunicateGroup,
    set_hybrid_communicate_group)
from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                     shard_llama)


def main(steps=3):
    topo = CommunicateTopology(["dp", "pp", "sharding", "sep", "mp"],
                               [2, 1, 1, 1, 4])        # dp=2 x mp=4
    hcg = HybridCommunicateGroup(topo, rank=0)
    set_hybrid_communicate_group(hcg)
    mesh = hcg.get_mesh()

    paddle.seed(0)
    cfg = LlamaConfig.tiny(num_key_value_heads=4)
    model = LlamaForCausalLM(cfg)
    shard_llama(model, mesh, fsdp_axis="dp", mp_axis="mp")
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())

    def train_step(x, y):
        xs = shard_tensor(x, mesh, [Shard(0)])          # batch on dp
        _, loss = model(xs, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss

    step = paddle.jit.to_static(train_step)
    rng = np.random.RandomState(0)
    for i in range(steps):
        ids = rng.randint(0, cfg.vocab_size, (4, 33)).astype(np.int32)
        loss = step(paddle.to_tensor(ids[:, :-1]),
                    paddle.to_tensor(ids[:, 1:]))
        print(f"step {i}: loss {float(loss.numpy()):.4f} (dp=2 x mp=4 mesh)")


if __name__ == "__main__":
    main()
