"""Pretrain a (tiny) GPT-2 with the compiled train step.

The pattern scales to the real chip unchanged: `jit.scan_steps` fuses K
optimizer steps into one dispatch (one tunnel round trip buys K updates).
Losses come back STACKED on the leading [K] axis and are read on the host
after the dispatch — scan_steps raises a permanent MissedCapture on any
in-step scalar event, so a `float(loss)` inside the step would silently
pin the whole example eager (stitched breaks are a `to_static` feature).

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python examples/train_gpt2.py
"""
import os
import sys

# runnable from any cwd: the repo root (one level up) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM


def main(steps=4, k=2, batch=2, seqlen=64):
    paddle.seed(0)
    cfg = GPT2Config.tiny(hidden_dropout_prob=0.0,
                          attention_dropout_prob=0.0,
                          max_position_embeddings=seqlen)
    model = GPT2ForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4, weight_decay=0.01,
                                 parameters=model.parameters(),
                                 grad_clip=nn.ClipGradByGlobalNorm(1.0))
    losses = []

    def train_step(x, y):
        _, loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        return loss                     # host read happens AFTER dispatch

    step = paddle.jit.scan_steps(train_step) if k > 1 \
        else paddle.jit.to_static(train_step)
    rng = np.random.RandomState(0)
    # one fixed batch, revisited every step: loss must fall as the model
    # memorizes it (fresh random ids each step would just bounce around)
    ids = rng.randint(0, cfg.vocab_size,
                      (k, batch, seqlen + 1)).astype(np.int32)
    x = paddle.to_tensor(ids[:, :, :-1] if k > 1 else ids[0, :, :-1])
    y = paddle.to_tensor(ids[:, :, 1:] if k > 1 else ids[0, :, 1:])
    for i in range(steps):
        loss = step(x, y)               # [k] stacked under scan_steps
        losses.extend(np.asarray(loss.numpy()).reshape(-1).tolist())
    print(f"losses (k={k} updates/dispatch): "
          f"{[round(v, 3) for v in losses]}")
    assert losses[-1] < losses[0]
    return losses


if __name__ == "__main__":
    main()
