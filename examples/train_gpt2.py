"""Pretrain a (tiny) GPT-2 with the compiled train step.

The pattern scales to the real chip unchanged: `jit.scan_steps` fuses K
optimizer steps into one dispatch (one tunnel round trip buys K updates),
and `float(loss)` inside the step is a stitched break — the step stays one
fused XLA program while your logging sees true per-call values.

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python examples/train_gpt2.py
"""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.models.gpt2 import GPT2Config, GPT2ForCausalLM


def main(steps=4, k=2, batch=2, seqlen=64):
    paddle.seed(0)
    cfg = GPT2Config.tiny(hidden_dropout_prob=0.0,
                          attention_dropout_prob=0.0,
                          max_position_embeddings=seqlen)
    model = GPT2ForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=3e-4, weight_decay=0.01,
                                 parameters=model.parameters(),
                                 grad_clip=nn.ClipGradByGlobalNorm(1.0))
    losses = []

    def train_step(x, y):
        _, loss = model(x, labels=y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))      # stitched break: stays compiled
        return loss

    step = paddle.jit.scan_steps(train_step) if k > 1 \
        else paddle.jit.to_static(train_step)
    rng = np.random.RandomState(0)
    for i in range(steps):
        ids = rng.randint(0, cfg.vocab_size,
                          (k, batch, seqlen + 1)).astype(np.int32)
        x = paddle.to_tensor(ids[:, :, :-1] if k > 1 else ids[0, :, :-1])
        y = paddle.to_tensor(ids[:, :, 1:] if k > 1 else ids[0, :, 1:])
        step(x, y)
    print(f"losses (k={k} updates/dispatch): "
          f"{[round(v, 3) for v in losses]}")
    assert losses[-1] < losses[0]
    return losses


if __name__ == "__main__":
    main()
