"""Serve a (tiny) Llama with the continuous-batching paged-KV engine.

Features on display: chunked prefill, in-graph per-request sampling,
on-demand paging with preemption, RTT-adaptive decode blocks, and int8
KV-cache pages (~2x slots at the same HBM budget).

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python examples/serve_llama.py

Set METRICS_PORT to also expose engine telemetry on a Prometheus pull
endpoint for the duration of the run (e.g. METRICS_PORT=9400 -> scrape
http://127.0.0.1:9400/metrics; 0 lets the OS pick a port).
"""
import os
import sys

# runnable from any cwd: the repo root (one level up) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.inference.serving import LLMEngine


def main():
    paddle.seed(0)
    metrics = None
    if os.environ.get("METRICS_PORT") is not None:
        obs.enable()
        metrics = obs.start_metrics_server(
            port=int(os.environ["METRICS_PORT"]))
        print(f"metrics endpoint: {metrics.url}")
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()
    eng = LLMEngine(model, max_batch=2, max_len=96, page_size=8,
                    prefill_chunk=16, decode_block="auto",
                    kv_cache_dtype="int8")
    rng = np.random.RandomState(0)
    rids = [eng.add_request(
        rng.randint(1, model.config.vocab_size, (12,)).astype(np.int32),
        max_new_tokens=16, do_sample=bool(i), temperature=0.8, top_p=0.9,
        seed=7) for i in range(3)]
    steps = eng.run_until_done()
    for rid in rids:
        toks = eng.result(rid)
        print(f"request {rid}: {len(toks)} tokens, "
              f"TTFT {eng.ttft(rid) * 1e3:.1f} ms -> {toks[:8]}...")
    print(f"engine dispatches: {steps}, "
          f"auto decode block: {eng.auto_decode_block}, "
          f"KV bytes/page: {eng.kv_bytes_per_page()}")
    if metrics is not None:
        ttft = [ln for ln in obs.render_prometheus().splitlines()
                if ln.startswith("serving_ttft_seconds_count")]
        print("scraped:", *ttft, sep="\n  ")
        metrics.close()
        obs.disable()


if __name__ == "__main__":
    main()
