"""Serve a (tiny) Llama behind the streaming serving front door.

Features on display: a 2-replica :class:`ReplicaSet` of continuous-batching
paged-KV engines (chunked prefill, int8 KV pages, RTT-adaptive decode
blocks), prefix-affinity routing, SLO-aware admission, and the stdlib SSE
gateway -- the script starts the HTTP front door, drives it with a few
clients (streaming and non-streaming), and prints what came back.

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python examples/serve_llama.py

Set METRICS_PORT to also expose engine + frontend telemetry on a
Prometheus pull endpoint for the duration of the run (e.g.
METRICS_PORT=9400 -> scrape http://127.0.0.1:9400/metrics; 0 lets the OS
pick a port).  The gateway itself always serves /metrics too.

Set JOURNAL_DIR to turn on the durable request plane: requests journal to
that directory before acknowledgment, submits become idempotent
(Idempotency-Key header), SSE streams resumable (Last-Event-ID), and a
restarted gateway pointed at the same directory recovers unfinished
requests -- the script demonstrates an idempotent replay when the knob is
set.
"""
import os
import sys

# runnable from any cwd: the repo root (one level up) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from paddle_tpu.inference.serving import LLMEngine
from paddle_tpu.inference.frontend import (
    ReplicaSet, SLOAdmission, start_gateway, http_completion)


def main():
    paddle.seed(0)
    metrics = None
    if os.environ.get("METRICS_PORT") is not None:
        obs.enable()
        metrics = obs.start_metrics_server(
            port=int(os.environ["METRICS_PORT"]))
        print(f"metrics endpoint: {metrics.url}")
    model = LlamaForCausalLM(LlamaConfig.tiny())
    model.eval()

    def _engine():
        return LLMEngine(model, max_batch=2, max_len=96, page_size=8,
                         prefill_chunk=16, decode_block="auto",
                         kv_cache_dtype="int8", prefix_cache=True)

    rng = np.random.RandomState(0)
    with ReplicaSet([_engine(), _engine()],
                    admission=SLOAdmission(max_queue_per_replica=32)) as rs:
        journal_dir = os.environ.get("JOURNAL_DIR")
        gw = start_gateway(rs, port=int(os.environ.get("PORT", 0)),
                           journal_dir=journal_dir)
        print(f"front door: {gw.url}/v1/completions"
              + (f" (journal: {journal_dir})" if journal_dir else ""))
        try:
            shared = rng.randint(
                1, model.config.vocab_size, (12,)).tolist()
            # one streaming client: tokens arrive as SSE events
            out = http_completion(gw.url, shared, max_tokens=16,
                                  stream=True)
            print(f"stream: {len(out['tokens'])} tokens over "
                  f"{out['events']} SSE events ({out['status']}) "
                  f"-> {out['tokens'][:8]}...")
            # a few non-streaming clients sharing the same prompt prefix,
            # so the router can exploit the replicas' prefix caches
            for i in range(3):
                prompt = shared + rng.randint(
                    1, model.config.vocab_size, (4,)).tolist()
                out = http_completion(
                    gw.url, prompt, max_tokens=16, do_sample=bool(i),
                    temperature=0.8, top_p=0.9, seed=7)
                print(f"request {i}: {len(out['tokens'])} tokens on "
                      f"{out.get('replica', 'durable')} ({out['status']}) "
                      f"-> {out['tokens'][:8]}...")
            if journal_dir is not None:
                # idempotent replay: same key, same tokens, nothing re-runs
                first = http_completion(
                    gw.url, shared, max_tokens=16,
                    headers={"Idempotency-Key": "demo"})
                again = http_completion(
                    gw.url, shared, max_tokens=16,
                    headers={"Idempotency-Key": "demo"})
                print(f"idempotent replay: "
                      f"{'match' if again['tokens'] == first['tokens'] else 'MISMATCH'}"
                      f" ({len(again['tokens'])} tokens, key="
                      f"{again['idempotency_key']})")
                print(f"journal: {gw.plane.health()}")
            for name, h in rs.health().items():
                print(f"replica {name}: finished={h['finished']} "
                      f"free_pages={h['free_pages']} alive={h['alive']}")
        finally:
            gw.close()
    if metrics is not None:
        lines = [ln for ln in obs.render_prometheus().splitlines()
                 if ln.startswith(("serving_ttft_seconds_count",
                                   "frontend_requests_total",
                                   "frontend_routed_total"))]
        print("scraped:", *lines, sep="\n  ")
        metrics.close()
        obs.disable()


if __name__ == "__main__":
    main()
