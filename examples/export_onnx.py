"""Export a model to ONNX and verify it with the in-tree numpy runner.

No external onnx package needed: the exporter serializes the captured jaxpr
directly against the public onnx.proto schema.

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python examples/export_onnx.py
"""
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.onnx import export, _runner


def main():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    x = paddle.to_tensor(np.random.RandomState(0).rand(
        3, 16).astype(np.float32))
    path = export(model, tempfile.mkdtemp() + "/mlp", input_spec=[x])
    got = _runner.run(open(path, "rb").read(),
                      {"x0": np.asarray(x._data)})["y0"]
    ref = np.asarray(model(x)._data)
    np.testing.assert_allclose(got, ref, atol=1e-4, rtol=1e-4)
    print(f"exported {path} and verified: max|Δ| = "
          f"{np.abs(got - ref).max():.2e}")


if __name__ == "__main__":
    main()
