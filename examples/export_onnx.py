"""Export a model to ONNX and verify it with the in-tree numpy runner.

No external onnx package needed: the exporter serializes the captured jaxpr
directly against the public onnx.proto schema, and `load_and_run` re-executes
the exported graph for verification.

Run:  PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu python examples/export_onnx.py
"""
import os
import sys

# runnable from any cwd: the repo root (one level up) on sys.path
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
from paddle_tpu.onnx import export, load_and_run


def main():
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    x = paddle.to_tensor(np.random.RandomState(0).rand(
        3, 16).astype(np.float32))
    with tempfile.TemporaryDirectory() as d:
        path = export(model, d + "/mlp", input_spec=[x])
        got = load_and_run(path, {"x0": x.numpy()})["y0"]
    ref = model(x).numpy()
    np.testing.assert_allclose(got, ref, atol=1e-4, rtol=1e-4)
    print(f"exported and verified: max|Δ| = {np.abs(got - ref).max():.2e}")


if __name__ == "__main__":
    main()
