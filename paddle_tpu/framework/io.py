"""paddle.save / paddle.load (reference: python/paddle/framework/io.py:773,1020).

Format: pickle with Tensors materialized as numpy arrays + a dtype tag so
bfloat16 round-trips. Compatible surface: state_dicts, nested containers,
plain Tensors, optimizer state.
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax.numpy as jnp
import ml_dtypes  # noqa: F401 — registers bfloat16 with numpy

from ..core.tensor import Tensor, Parameter

_PROTO = 4
_MAGIC = b"PTPU1\n"


class _TensorPayload:
    __slots__ = ("array", "is_param", "name")

    def __init__(self, array, is_param, name):
        self.array = array
        self.is_param = is_param
        self.name = name


def _pack(obj):
    if isinstance(obj, Tensor):
        return _TensorPayload(np.asarray(obj._data), isinstance(obj, Parameter), obj.name)
    if isinstance(obj, dict):
        return {k: _pack(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_pack(v) for v in obj)
    return obj


def _unpack(obj, return_numpy=False):
    if isinstance(obj, _TensorPayload):
        if return_numpy:
            return obj.array
        t = Parameter(jnp.asarray(obj.array), name=obj.name) if obj.is_param \
            else Tensor(jnp.asarray(obj.array), name=obj.name)
        if obj.is_param:
            t.persistable = True
        return t
    if isinstance(obj, dict):
        return {k: _unpack(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_unpack(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=_PROTO, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        f.write(_MAGIC)
        pickle.dump(_pack(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") as f:
        head = f.read(len(_MAGIC))
        if head != _MAGIC:
            f.seek(0)  # tolerate plain-pickle files
        obj = pickle.load(f)
    return _unpack(obj, return_numpy)
