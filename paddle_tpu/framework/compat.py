"""Top-level API compat pieces (reference: python/paddle/__init__.py exports —
iinfo/finfo/dtype, dlpack interop, printoptions, CUDA place/rng shims, the
legacy `batch` reader decorator, LazyGuard).
"""
from __future__ import annotations

import numpy as np
import jax

from ..core.tensor import Tensor
from ..core import dtype as dtypes
from ..core.device import Place


class dtype:
    """paddle.dtype — wraps a numpy/jax dtype with paddle naming
    (reference: the pybind DataType enum exposed as paddle.dtype)."""

    def __init__(self, d):
        self.np = np.dtype(dtypes.convert_dtype(d) or d)

    @property
    def name(self):
        return dtypes.paddle_name(self.np) if hasattr(dtypes, "paddle_name") \
            else str(self.np)

    def __eq__(self, other):
        if isinstance(other, dtype):
            return self.np == other.np
        try:
            return self.np == np.dtype(dtypes.convert_dtype(other) or other)
        except TypeError:
            return NotImplemented

    def __hash__(self):
        return hash(self.np)

    def __repr__(self):
        return f"paddle.{self.np.name}"


class iinfo:
    """reference paddle.iinfo (pybind iinfo): integer type limits."""

    def __init__(self, d):
        d = dtypes.convert_dtype(d) or d
        info = np.iinfo(np.dtype(d))
        self.min, self.max, self.bits = int(info.min), int(info.max), info.bits
        self.dtype = str(np.dtype(d))

    def __repr__(self):
        return f"iinfo(min={self.min}, max={self.max}, bits={self.bits})"


class finfo:
    """reference paddle.finfo: floating type limits (bfloat16 aware)."""

    def __init__(self, d):
        d = dtypes.convert_dtype(d) or d
        import ml_dtypes
        info = ml_dtypes.finfo(d) if str(d) in ("bfloat16", "float8_e4m3fn",
                                                "float8_e5m2") else \
            np.finfo(np.dtype(d))
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = float(getattr(info, "tiny", getattr(info, "smallest_normal", 0.0)))
        self.smallest_normal = self.tiny
        self.bits = info.bits
        self.dtype = str(d)

    def __repr__(self):
        return (f"finfo(min={self.min}, max={self.max}, eps={self.eps}, "
                f"bits={self.bits})")


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference paddle.set_printoptions — numpy drives Tensor repr here."""
    kw = {}
    if precision is not None:
        kw["precision"] = precision
    if threshold is not None:
        kw["threshold"] = threshold
    if edgeitems is not None:
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        kw["suppress"] = not sci_mode
    np.set_printoptions(**kw)


# ---- CUDA-compat shims (TPU build: map to the default accelerator) ----------
class CUDAPlace(Place):
    """Compat: the reference's GPU place. On the TPU build it resolves to the
    n-th available accelerator device (API-compatible, device is TPU/CPU)."""

    def __init__(self, device_id=0):
        devs = jax.devices()
        super().__init__(devs[min(device_id, len(devs) - 1)])


class CUDAPinnedPlace(Place):
    """Compat: pinned-host place — host memory is already the staging area
    for PJRT transfers, so this is the CPU device."""

    def __init__(self):
        try:
            cpu = jax.local_devices(backend="cpu")
        except Exception:
            cpu = jax.devices()
        super().__init__(cpu[0])


def get_cuda_rng_state():
    """Compat alias of the framework RNG state (one device RNG on TPU)."""
    from ..core.rng import get_rng_state
    return get_rng_state()


def set_cuda_rng_state(state):
    from ..core.rng import set_rng_state
    return set_rng_state(state)


# ---- dlpack ------------------------------------------------------------------
def to_dlpack(x):
    """reference paddle.utils.dlpack.to_dlpack / paddle.to_dlpack."""
    arr = x._data if isinstance(x, Tensor) else x
    return arr.__dlpack__()


def from_dlpack(capsule):
    """Accepts any __dlpack__-capable object (numpy, torch cpu, jax arrays,
    paddle Tensors) or a legacy raw capsule (host-resident)."""
    if isinstance(capsule, Tensor):
        capsule = capsule._data
    if not hasattr(capsule, "__dlpack__"):
        class _LegacyCapsule:
            """jax>=0.5 dropped raw-capsule intake; present the capsule
            through the protocol (host device — legacy capsules carry no
            device info)."""

            def __init__(self, c):
                self._c = c

            def __dlpack__(self, **kw):
                return self._c

            def __dlpack_device__(self):
                return (1, 0)    # kDLCPU
        capsule = _LegacyCapsule(capsule)
    return Tensor(jax.numpy.from_dlpack(capsule))


# ---- misc --------------------------------------------------------------------
class LazyGuard:
    """reference paddle.LazyGuard defers parameter materialization during
    Layer construction. XLA arrays are lazily materialized by the runtime
    already (construction traces an init computation; buffers appear on first
    use), so the guard is a compat context manager with no extra effect."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def batch(reader, batch_size, drop_last=False):
    """Legacy reader decorator (reference python/paddle/reader): turns a
    sample generator fn into a batch generator fn."""
    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


def check_shape(shape):
    """reference paddle.static check_shape: validate a shape spec (ints, -1
    for inferred, None for dynamic)."""
    if isinstance(shape, (list, tuple)):
        for v in shape:
            if v is None:
                continue
            if not isinstance(v, (int, np.integer)):
                raise TypeError(f"shape entries must be int/None, got {v!r}")
            if v < -1:
                raise ValueError(f"shape entries must be >= -1, got {v}")
    elif not isinstance(shape, (int, np.integer)):
        raise TypeError(f"shape must be int or list/tuple, got {type(shape)}")
    return shape


class _UnsupportedDType:
    """Placeholder for the reference's prototype string dtypes (pstring/raw);
    using them raises instead of silently mis-typing."""

    def __init__(self, name):
        self._name = name

    def __repr__(self):
        return f"paddle.{self._name} (unsupported on the TPU build)"

    def __call__(self, *a, **k):
        raise TypeError(f"dtype {self._name!r} is not supported on TPU")


pstring = _UnsupportedDType("pstring")
raw = _UnsupportedDType("raw")
