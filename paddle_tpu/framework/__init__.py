"""paddle.framework compat namespace."""
from .io import save, load  # noqa: F401
from ..core.rng import seed  # noqa: F401
from ..core.dtype import set_default_dtype, get_default_dtype  # noqa: F401


def in_dynamic_mode():
    from ..core.dispatch import _state
    return _state.trace_ctx is None
