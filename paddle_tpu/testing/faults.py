"""Deterministic fault injection — named fault points with seeded schedules.

Production code declares *fault points* — ``FAULTS.fire("serving.page_alloc")``
at the spot where an allocation could fail, ``FAULTS.raise_if("serving.step",
rids=[...])`` where a dispatch could blow up — and pays one dict-emptiness
check while nothing is installed.  Tests arm a point with a schedule:

    from paddle_tpu.testing import FAULTS, FailNth, FailProb

    FAULTS.install("serving.page_alloc", FailNth(3))          # 3rd call fails
    FAULTS.install("serving.step", FailProb(0.2, seed=7))     # seeded coin
    FAULTS.install("serving.step", FailNth(1), transient=True,
                   match=lambda ctx: 42 in ctx.get("rids", ()))
    ...
    FAULTS.reset()

or scoped with the context manager::

    with injected("store.connect", FailNth({1, 2})):
        ...

Schedules are pure functions of their own call counter (plus a seeded RNG for
:class:`FailProb`), so a chaos test replays the exact same failure sequence
every run.  Known points today: ``serving.page_alloc`` (allocation returns
dry), ``serving.step`` (dispatch raises :class:`InjectedFault`),
``serving.slow_step`` (dispatch stalls ``delay`` seconds),
``serving.kv_handoff`` (disaggregated prefill→decode page transfer raises
before any page is copied, so a transient retry is idempotent; ctx has
``rids`` and ``path`` — ``local`` for the in-process gather→device_put hop,
``cross_host`` when the pool pulls a serialized block off a remote prefill
worker, where the fault fires pool-side BEFORE the pull RPC so a retry
re-pulls a block the worker still holds), ``store.connect``
(client connect raises); in the serving front door, ``frontend.route``
(gateway submit fails before routing), ``frontend.submit`` (fails after a
replica is chosen; ctx has ``replica``), ``frontend.step`` (a replica's
step loop dies — the chaos tests kill a replica mid-stream with this; ctx
has ``replica``), and ``frontend.resume`` (the durable-resume attempt for a
partially-streamed request fails — the only path on which such a request
may end FAILED; ctx has the dead ``replica``).  The durable request plane
adds ``journal.append`` (a write-ahead journal record fails to append; ctx
has ``kind``), ``journal.fsync`` (the fsync after a critical append raises
— a full-disk / dying-device stand-in), and ``gateway.recover`` (the
re-drive of one journaled non-terminal request during gateway crash
recovery fails; ctx has ``key``).  The self-healing fleet adds
``membership.register`` /
``membership.heartbeat`` (lease registration / renewal attempts raise; ctx
has ``group`` and ``member`` — arm ``Always`` to starve a lease to death)
and ``rpc.send`` / ``rpc.recv`` (the worker RPC channel fails client-side
around the request/response halves; ctx has ``op``).  The KV-cache
hierarchy adds ``kv.spill`` (the device→host page copy behind an LRU
reclaim raises; transient firings retry, poison degrades to a plain
eviction — recompute on the next hit; ctx has ``page``), ``kv.restore``
(the host→device restore of a spilled chain raises before any page is
written; poison falls back to re-prefill; ctx has ``keys``), and
``kv.peer_pull`` (the gateway-driven peer page pull fails before the
export RPC; poison submits the request cold — recompute; ctx has
``replica`` and ``holder``).  The registry itself stays name-keyed and
open, but every point production code fires must be listed in
:data:`KNOWN_POINTS` — graftlint's ``contracts`` pass (CT103) checks that
each fired string is declared here and that each declared string has
chaos coverage, so the table is the single source of truth for the
fault-point protocol.
"""
from __future__ import annotations

import random
import threading
from contextlib import contextmanager

__all__ = ["InjectedFault", "FailNth", "FailProb", "Always", "Never",
           "FaultPoint", "FaultInjector", "FAULTS", "KNOWN_POINTS",
           "injected"]

# the declared fault-point protocol: every name production code fires.
# graftlint CT103 enforces parity both ways (fired => declared here,
# declared => fired somewhere and armed by an injected(...) chaos test).
KNOWN_POINTS = frozenset({
    "serving.page_alloc",
    "serving.step",
    "serving.slow_step",
    "serving.kv_handoff",
    "store.connect",
    "frontend.route",
    "frontend.submit",
    "frontend.step",
    "frontend.resume",
    "journal.append",
    "journal.fsync",
    "gateway.recover",
    "membership.register",
    "membership.heartbeat",
    "rpc.send",
    "rpc.recv",
    "kv.spill",
    "kv.restore",
    "kv.peer_pull",
})


class InjectedFault(RuntimeError):
    """Raised by an armed fault point. ``transient`` marks errors the
    consuming subsystem should treat as retryable (the serving engine routes
    those through its backoff path instead of quarantining a request)."""

    def __init__(self, point, transient=False):
        super().__init__(f"injected fault at {point!r}"
                         + (" (transient)" if transient else ""))
        self.point = point
        self.transient = transient

    def __reduce__(self):
        # survive the worker RPC's pickle round trip with point/transient
        # intact (chaos tests assert on them gateway-side)
        return (InjectedFault, (self.point, self.transient))


# ---- schedules ---------------------------------------------------------------
class FailNth:
    """Fire on specific 1-based call numbers: ``FailNth(3)`` fails the third
    call only; ``FailNth({1, 2, 5})`` each listed call; ``FailNth(2, every=
    True)`` call 2 and every call after it."""

    def __init__(self, nth, every=False):
        self.nth = {int(nth)} if isinstance(nth, int) else {int(n) for n in nth}
        self.every = every
        self._floor = min(self.nth)

    def should_fire(self, n_call):
        if self.every:
            return n_call >= self._floor
        return n_call in self.nth


class FailProb:
    """Fire with probability ``p`` per call from a private seeded stream —
    chaotic in shape, bit-reproducible across runs."""

    def __init__(self, p, seed=0):
        if not 0.0 <= p <= 1.0:
            raise ValueError("p must be in [0, 1]")
        self.p = float(p)
        self._rng = random.Random(seed)

    def should_fire(self, n_call):
        return self._rng.random() < self.p


class Always:
    def should_fire(self, n_call):
        return True


class Never:
    def should_fire(self, n_call):
        return False


# ---- registry ----------------------------------------------------------------
class FaultPoint:
    """One armed point: a schedule, an optional context predicate, and the
    knobs consumers read off a firing (``transient``, ``delay``)."""

    def __init__(self, name, schedule, match=None, transient=False,
                 delay=0.0):
        self.name = name
        self.schedule = schedule
        self.match = match
        self.transient = transient
        self.delay = float(delay)
        self.calls = 0          # times the point was evaluated
        self.fires = 0          # times it actually fired

    def evaluate(self, ctx):
        if self.match is not None and not self.match(ctx):
            return False
        self.calls += 1
        if self.schedule.should_fire(self.calls):
            self.fires += 1
            return True
        return False


class FaultInjector:
    """Process-wide fault-point registry (usually the :data:`FAULTS`
    singleton).  ``fire`` is the hot-path probe: with nothing installed it is
    a single attribute read returning None."""

    def __init__(self):
        self._points: dict[str, FaultPoint] = {}
        self._mu = threading.Lock()

    # _points is read lock-free on the hot path BY DESIGN (see the class
    # docstring): production probes pay one dict read, installs/removes are
    # test-time and rare, and dict get/bool are atomic under the GIL.
    @property
    def active(self):
        return bool(self._points)  # graftlint: disable=concurrency

    def install(self, name, schedule, match=None, transient=False,
                delay=0.0) -> FaultPoint:
        point = FaultPoint(name, schedule, match=match, transient=transient,
                           delay=delay)
        with self._mu:
            self._points[name] = point
        return point

    def remove(self, name):
        with self._mu:
            self._points.pop(name, None)

    def reset(self):
        with self._mu:
            self._points.clear()

    def point(self, name) -> FaultPoint | None:
        return self._points.get(name)  # graftlint: disable=concurrency

    def fire(self, name, **ctx) -> FaultPoint | None:
        """Evaluate point ``name``; returns the :class:`FaultPoint` when it
        fires (so the caller can read ``delay``/``transient``), else None."""
        if not self._points:  # graftlint: disable=concurrency
            return None
        point = self._points.get(name)
        if point is None or not point.evaluate(ctx):
            return None
        return point

    def raise_if(self, name, **ctx):
        """Raise :class:`InjectedFault` when point ``name`` fires."""
        point = self.fire(name, **ctx)
        if point is not None:
            raise InjectedFault(name, transient=point.transient)

    def maybe_fire(self, name, **ctx):
        """The one-line production probe: :meth:`raise_if` behind the
        idle-path emptiness check, replacing the
        ``if FAULTS.active: FAULTS.raise_if(...)`` boilerplate at every
        fault point.  With nothing installed this is a single dict-emptiness
        read; armed, it raises :class:`InjectedFault` when ``name`` fires.
        One call shape also gives graftlint CT103 a single pattern to
        match for fault-point parity."""
        if not self._points:  # graftlint: disable=concurrency
            return
        self.raise_if(name, **ctx)


FAULTS = FaultInjector()


@contextmanager
def injected(name, schedule, match=None, transient=False, delay=0.0):
    """Arm ``name`` on the process singleton for the enclosed block; the
    point is removed (not reset-all) on exit so nested injections compose."""
    point = FAULTS.install(name, schedule, match=match, transient=transient,
                           delay=delay)
    try:
        yield point
    finally:
        FAULTS.remove(name)
