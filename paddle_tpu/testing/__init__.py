"""paddle_tpu.testing — deterministic chaos/fault tooling for tier-1 tests.

The reference repo validates its fault paths with live multi-node kill tests;
on a single CPU host the equivalent is *injected* failure: named fault points
threaded through the serving engine and the control-plane store, driven by
seeded schedules so every failure path is exercised deterministically (see
:mod:`.faults`).
"""
from .faults import (FAULTS, KNOWN_POINTS, Always, FailNth,  # noqa: F401
                     FailProb, FaultInjector, InjectedFault, Never, injected)

__all__ = ["FAULTS", "KNOWN_POINTS", "FaultInjector", "InjectedFault",
           "FailNth", "FailProb", "Always", "Never", "injected"]
