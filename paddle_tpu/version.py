__version__ = "0.5.0"
full_version = __version__
major, minor, patch = 0, 5, 0


def show():
    print(f"paddle_tpu {__version__}")  # graftlint: disable=no-adhoc-telemetry
