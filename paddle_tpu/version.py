__version__ = "0.3.0"
full_version = __version__
major, minor, patch = 0, 3, 0


def show():
    print(f"paddle_tpu {__version__}")
