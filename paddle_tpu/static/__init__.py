"""paddle.static compat surface (reference: python/paddle/static/).

The reference's Program/Executor static graph collapses into to_static capture
(jaxpr/StableHLO is the program IR). These shims keep static-style user code
importable; InputSpec is the real, shared spec type.
"""
from __future__ import annotations

import contextlib

from ..jit import InputSpec  # noqa: F401
from ..jit.to_static import StaticFunction  # noqa: F401


class Program:
    """Placeholder Program: captured programs are jaxprs inside StaticFunction."""

    def __init__(self):
        self._sf = None

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


def default_main_program():
    return Program()


def default_startup_program():
    return Program()


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    yield


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class Executor:
    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        raise NotImplementedError(
            "static Executor.run: use paddle_tpu.jit.to_static capture instead "
            "(the PIR/StandaloneExecutor path is subsumed by XLA)")


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad
    return grad(targets, inputs, grad_outputs=target_gradients, allow_unused=True)
