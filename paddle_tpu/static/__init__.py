"""paddle.static compat surface (reference: python/paddle/static/).

The reference's Program/Executor static graph collapses into to_static capture
(jaxpr/StableHLO is the program IR). Here the static feed/fetch pattern is
REAL: `data()` makes named placeholder Tensors, eager user code builds the op
tape (dispatch records raw_fn per node), and `Executor.run` replays the tape
from fetch targets with feed values substituted — a mini interpreter over the
same graph autograd uses (reference: StandaloneExecutor over PIR).
"""
from __future__ import annotations

import contextlib
import weakref

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import _state as _dispatch_state
from ..jit import InputSpec  # noqa: F401
from ..jit.to_static import StaticFunction  # noqa: F401

# id(tensor) -> weakref of every placeholder ever made by data()
_placeholder_regs: "weakref.WeakValueDictionary[int, Tensor]" = \
    weakref.WeakValueDictionary()


def _is_placeholder(t):
    return _placeholder_regs.get(id(t)) is t


def enable_static():
    """Record replay linkage for every dispatched op (reference:
    paddle.enable_static). program_guard enables this automatically."""
    _dispatch_state.static_record = True


def disable_static():
    _dispatch_state.static_record = False


class Program:
    """Holds the named placeholders created under its guard; ops live on the
    dispatch tape (jaxpr analog), not in a separate block structure."""

    def __init__(self):
        # weak: a placeholder the user dropped shouldn't be pinned forever
        # by the module-global default program
        self._placeholders = weakref.WeakValueDictionary()

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self


_default_main = Program()
_default_startup = Program()
_current: list[Program] = [_default_main]


def default_main_program():
    return _current[-1]


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _current.append(main_program)
    prev = _dispatch_state.static_record
    _dispatch_state.static_record = True
    try:
        yield
    finally:
        _dispatch_state.static_record = prev
        _current.pop()


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def data(name, shape, dtype="float32", lod_level=0):
    """Named placeholder; stop_gradient=False so every downstream op records
    on the tape for Executor replay (reference: static/input.py data)."""
    shp = [1 if (s is None or (isinstance(s, int) and s < 0)) else int(s)
           for s in shape]
    t = Tensor(jnp.zeros(shp, dtype), stop_gradient=False, name=name)
    _current[-1]._placeholders[name] = t
    _placeholder_regs[id(t)] = t
    return t


class Executor:
    """Replays the op tape under fetch targets, substituting feed arrays for
    placeholders (reference: executor.py Executor over StandaloneExecutor)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        feed = feed or {}
        if not fetch_list:
            return []   # startup program: params already eagerly initialized
        cache = {}      # id(replay node) -> tuple of output arrays

        def entry(t):
            """(node, slot) to replay t, or None if t is a leaf."""
            if t._replay_node is not None:
                return t._replay_node
            n = t._grad_node
            if n is not None and n.raw_fn is not None:
                return (n, t._out_slot)
            return None

        def leaf_value(t):
            if _is_placeholder(t):
                if t.name not in feed:
                    raise ValueError(
                        f"static placeholder '{t.name}' reached by fetch "
                        f"but missing from feed={sorted(feed)}")
                return jnp.asarray(np.asarray(feed[t.name]), t._buf.dtype)
            return t._buf   # parameter / constant: current live value

        def ev(root):
            if not isinstance(root, Tensor):
                return jnp.asarray(root)
            # iterative post-order (graphs can be 1000s of ops deep)
            stack = [(root, False)]
            while stack:
                t, expanded = stack.pop()
                e = None if _is_placeholder(t) else entry(t)
                if e is None or id(e[0]) in cache:
                    continue
                node = e[0]
                if expanded:
                    args = []
                    for inp, arr in zip(node.inputs, node.in_arrays):
                        if inp is None:
                            args.append(arr)
                        else:
                            e2 = None if _is_placeholder(inp) else entry(inp)
                            args.append(leaf_value(inp) if e2 is None
                                        else cache[id(e2[0])][e2[1]])
                    out = node.raw_fn(*args)
                    cache[id(node)] = out if isinstance(out, (tuple, list)) \
                        else (out,)
                else:
                    stack.append((t, True))
                    for inp in node.inputs:
                        if inp is not None:
                            stack.append((inp, False))
            e = None if _is_placeholder(root) else entry(root)
            if e is None:
                return leaf_value(root)
            return cache[id(e[0])][e[1]]

        return [np.asarray(ev(t)) for t in fetch_list]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad
    return grad(targets, inputs, grad_outputs=target_gradients, allow_unused=True)
