"""paddle.static compat surface (reference: python/paddle/static/).

The reference's Program/Executor static graph collapses into to_static capture
(jaxpr/StableHLO is the program IR). Here the static feed/fetch pattern is
REAL: `data()` makes named placeholder Tensors, eager user code builds the op
tape (dispatch records raw_fn per node), and `Executor.run` replays the tape
from fetch targets with feed values substituted — a mini interpreter over the
same graph autograd uses (reference: StandaloneExecutor over PIR).
"""
from __future__ import annotations

import contextlib
import weakref

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import _state as _dispatch_state
from ..jit import InputSpec  # noqa: F401
from ..jit.to_static import StaticFunction  # noqa: F401

# id(tensor) -> weakref of every placeholder ever made by data()
_placeholder_regs: "weakref.WeakValueDictionary[int, Tensor]" = \
    weakref.WeakValueDictionary()


def _is_placeholder(t):
    return _placeholder_regs.get(id(t)) is t


def enable_static():
    """Record replay linkage for every dispatched op (reference:
    paddle.enable_static). program_guard enables this automatically."""
    _dispatch_state.static_record = True


def disable_static():
    _dispatch_state.static_record = False


class Program:
    """Holds the named placeholders created under its guard; ops live on the
    dispatch tape (jaxpr analog), not in a separate block structure."""

    def __init__(self):
        # weak: a placeholder the user dropped shouldn't be pinned forever
        # by the module-global default program
        self._placeholders = weakref.WeakValueDictionary()

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return self

    def drop(self):
        """Release this program's placeholders from the module registry so a
        finished program's tape can be garbage collected (use release_tape on
        the fetch targets to free the op graph eagerly)."""
        for t in list(self._placeholders.values()):
            _placeholder_regs.pop(id(t), None)
        self._placeholders = weakref.WeakValueDictionary()


def release_tape(*tensors):
    """Eagerly free the replay op-graph reachable from `tensors` (r2 weak #7:
    a long static program retains every op's inputs via _replay_node until
    the last fetch target dies). After this, Executor.run on these targets
    raises instead of replaying stale state."""
    stack = []
    for t in tensors:
        for n in (t._replay_node[0] if t._replay_node else None,
                  t._grad_node):
            if n is not None:
                stack.append(n)
        t._replay_node = None
        t._grad_node = None
    seen = set()
    while stack:
        node = stack.pop()
        if id(node) in seen:
            continue
        seen.add(id(node))
        for inp in node.inputs:
            if inp is None:
                continue
            for n in (inp._replay_node[0] if inp._replay_node else None,
                      inp._grad_node):
                if n is not None:
                    stack.append(n)
            inp._replay_node = None
            inp._grad_node = None
        node.keep_arrays = False
        node.release()
        node.inputs = (None,) * len(node.inputs)


_default_main = Program()
_default_startup = Program()
_current: list[Program] = [_default_main]


def default_main_program():
    return _current[-1]


def default_startup_program():
    return _default_startup


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _current.append(main_program)
    prev = _dispatch_state.static_record
    _dispatch_state.static_record = True
    try:
        yield
    finally:
        _dispatch_state.static_record = prev
        _current.pop()


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


def data(name, shape, dtype="float32", lod_level=0):
    """Named placeholder; stop_gradient=False so every downstream op records
    on the tape for Executor replay (reference: static/input.py data)."""
    shp = [1 if (s is None or (isinstance(s, int) and s < 0)) else int(s)
           for s in shape]
    t = Tensor(jnp.zeros(shp, dtype), stop_gradient=False, name=name)
    _current[-1]._placeholders[name] = t
    _placeholder_regs[id(t)] = t
    return t


class Executor:
    """Replays the op tape under fetch targets, substituting feed arrays for
    placeholders (reference: executor.py Executor over StandaloneExecutor)."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kwargs):
        feed = feed or {}
        if not fetch_list:
            return []   # startup program: params already eagerly initialized
        cache = {}      # id(replay node) -> tuple of output arrays

        def entry(t):
            """(node, slot) to replay t, or None if t is a leaf."""
            if t._replay_node is not None:
                return t._replay_node
            n = t._grad_node
            if n is not None and n.raw_fn is not None:
                return (n, t._out_slot)
            return None

        def leaf_value(t):
            if _is_placeholder(t):
                if t.name not in feed:
                    raise ValueError(
                        f"static placeholder '{t.name}' reached by fetch "
                        f"but missing from feed={sorted(feed)}")
                return jnp.asarray(np.asarray(feed[t.name]), t._buf.dtype)
            return t._buf   # parameter / constant: current live value

        def ev(root):
            if not isinstance(root, Tensor):
                return jnp.asarray(root)
            # iterative post-order (graphs can be 1000s of ops deep)
            stack = [(root, False)]
            while stack:
                t, expanded = stack.pop()
                e = None if _is_placeholder(t) else entry(t)
                if e is None or id(e[0]) in cache:
                    continue
                node = e[0]
                if expanded:
                    args = []
                    for inp, arr in zip(node.inputs, node.in_arrays):
                        if inp is None:
                            args.append(arr)
                        else:
                            e2 = None if _is_placeholder(inp) else entry(inp)
                            args.append(leaf_value(inp) if e2 is None
                                        else cache[id(e2[0])][e2[1]])
                    out = node.raw_fn(*args)
                    cache[id(node)] = out if isinstance(out, (tuple, list)) \
                        else (out,)
                else:
                    stack.append((t, True))
                    for inp in node.inputs:
                        if inp is not None:
                            stack.append((inp, False))
            e = None if _is_placeholder(root) else entry(root)
            if e is None:
                return leaf_value(root)
            return cache[id(e[0])][e[1]]

        return [np.asarray(ev(t)) for t in fetch_list]


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..autograd import grad
    return grad(targets, inputs, grad_outputs=target_gradients, allow_unused=True)


# ---- round-2 compat surface (reference python/paddle/static/__init__.py) ----
Variable = Tensor            # the static Variable IS the capture-aware Tensor


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """reference static append_backward: run the tape backward over the
    recorded program and return (param, grad) pairs."""
    from ..autograd import backward as _bw
    # walk the tape BEFORE the sweep: backward() releases node inputs
    # progressively to free activations as it goes
    params = parameter_list or [
        t for t in _iter_recorded_params(loss) if not t.stop_gradient]
    _bw([loss])
    return [(p, p.grad) for p in params if p.grad is not None]


def _iter_recorded_params(root):
    seen, out, stack = set(), [], [root._grad_node]
    while stack:
        node = stack.pop()
        if node is None or id(node) in seen:
            continue
        seen.add(id(node))
        for inp in node.inputs:
            if inp is None:
                continue
            if inp._grad_node is None:
                out.append(inp)
            else:
                stack.append(inp._grad_node)
    return out


class Scope:
    """reference global_scope(): name -> variable store."""

    def __init__(self):
        self._vars = {}

    def var(self, name):
        return self._vars.setdefault(name, Tensor(jnp.zeros(())))

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


@contextlib.contextmanager
def scope_guard(scope):
    _scope_stack.append(scope)
    try:
        yield
    finally:
        _scope_stack.pop()


class BuildStrategy:
    """reference BuildStrategy: fusion/memory knobs. XLA owns these choices;
    the attributes are accepted and recorded."""

    def __init__(self):
        self.enable_inplace = True
        self.memory_optimize = True
        self.fuse_elewise_add_act_ops = True
        self.build_cinn_pass = False


class CompiledProgram:
    """reference CompiledProgram: wraps a Program for execution — here the
    Program's replay graph is already the compiled artifact."""

    def __init__(self, program, build_strategy=None):
        self.program = program
        self.build_strategy = build_strategy or BuildStrategy()


class ExponentialMovingAverage:
    """reference static ExponentialMovingAverage: EMA shadow weights with
    apply/restore (dygraph-friendly realization)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self._decay = decay
        self._shadow = {}
        self._backup = {}
        self._params = []

    def update(self, parameters=None):
        from .. import ops
        params = parameters if parameters is not None else self._params
        if parameters is not None:
            self._params = list(parameters)
        for p in params:
            prev = self._shadow.get(id(p))
            cur = p._data if hasattr(p, "_data") else p
            self._shadow[id(p)] = cur if prev is None else \
                self._decay * prev + (1 - self._decay) * cur

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        self._backup = {id(p): p._data for p in self._params}
        for p in self._params:
            if id(p) in self._shadow:
                p._data = self._shadow[id(p)]
        try:
            yield self
        finally:
            if need_restore:
                for p in self._params:
                    p._data = self._backup.get(id(p), p._data)
                self._backup = {}

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup[id(p)]
        self._backup = {}


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    t = Tensor(jnp.full(tuple(shape), value, dtype))
    t.name = name
    if name:
        global_scope()._vars[name] = t
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..nn.initializer import _resolve, XavierUniform, Constant
    default = default_initializer or (Constant(0.0) if is_bias
                                      else XavierUniform())
    pattr, init = _resolve(attr, default)
    from ..core.tensor import Parameter
    data = init(list(shape), dtype)
    return Parameter(data, name=name or (pattr.name if pattr else None))


def cpu_places(device_count=None):
    import jax
    from ..core.device import Place
    n = device_count or len([d for d in jax.devices() if d.platform == "cpu"]) or 1
    devs = [d for d in jax.devices() if d.platform == "cpu"] or jax.devices()
    return [Place(devs[i % len(devs)]) for i in range(n)]


def cuda_places(device_ids=None):
    """Compat: resolves to the available accelerator devices on this build."""
    import jax
    from ..core.device import Place
    devs = jax.devices()
    ids = device_ids if device_ids is not None else range(len(devs))
    return [Place(devs[i % len(devs)]) for i in ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


@contextlib.contextmanager
def device_guard(device=None):
    """reference device_guard: op placement hint. XLA places ops; accepted
    for compatibility."""
    yield


@contextlib.contextmanager
def ipu_shard_guard(index=-1, stage=-1):
    yield


def set_ipu_shard(layer, index=-1, stage=-1):
    return layer


class IpuStrategy:
    def __init__(self):
        raise NotImplementedError("IPU support is not part of the TPU build")


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        raise NotImplementedError("IPU support is not part of the TPU build")


class WeightNormParamAttr:
    """reference WeightNormParamAttr: ParamAttr requesting weight-norm
    reparameterization (dim recorded; use nn.utils.weight_norm for layers)."""

    def __init__(self, dim=None, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=False,
                 need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip


def Print(input, first_n=-1, message=None, summarize=20, print_tensor_name=True,
          print_tensor_type=True, print_tensor_shape=True,
          print_tensor_layout=True, print_tensor_lod=True,
          print_phase="both"):
    """reference static.Print: debug-print a tensor (eager-executed here)."""
    import numpy as _np
    arr = _np.asarray(input._data) if hasattr(input, "_data") else _np.asarray(input)
    prefix = (message + " ") if message else ""
    print(  # graftlint: disable=no-adhoc-telemetry (static.Print IS a print op)
        f"{prefix}{'Tensor' if print_tensor_name else ''} "
          f"shape={list(arr.shape) if print_tensor_shape else '...'} "
          f"values={arr.reshape(-1)[:summarize]}")
    return input


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """reference static.py_func: call a python function on tensors."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    return res


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """reference static.accuracy."""
    from .. import ops
    import numpy as _np
    lg = _np.asarray(input._data)
    lb = _np.asarray(label._data).reshape(-1)
    topk = _np.argsort(-lg, axis=-1)[:, :k]
    acc = float((topk == lb[:, None]).any(axis=1).mean())
    return Tensor(jnp.asarray(acc, jnp.float32))


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """reference static.auc (binary ROC-AUC over probability column 1)."""
    import numpy as _np
    probs = _np.asarray(input._data)
    p1 = probs[:, 1] if probs.ndim == 2 and probs.shape[1] == 2 else probs.reshape(-1)
    y = _np.asarray(label._data).reshape(-1)
    order = _np.argsort(-p1)
    y_sorted = y[order]
    tps = _np.cumsum(y_sorted)
    fps = _np.cumsum(1 - y_sorted)
    tpr = tps / max(tps[-1], 1)
    fpr = fps / max(fps[-1], 1)
    a = float(_np.trapezoid(tpr, fpr)) if hasattr(_np, "trapezoid") else \
        float(_np.trapz(tpr, fpr))
    t = Tensor(jnp.asarray(a, jnp.float32))
    return t, t, [t]


def ctr_metric_bundle(input, label, ins_tag_weight=None):
    raise NotImplementedError(
        "ctr_metric_bundle is parameter-server-era; use static.auc / "
        "paddle.metric instead")


# ---- save/load (reference static/io.py) --------------------------------------
def save(program, model_path, protocol=4, **configs):
    """Persist the parameters recorded in the program scope."""
    from ..framework.io import save as _save
    state = {name: t for name, t in global_scope()._vars.items()}
    _save(state, model_path + ".pdparams" if not str(model_path).endswith(
        ".pdparams") else model_path)


def load(program, model_path, executor=None, var_list=None):
    from ..framework.io import load as _load
    path = model_path if str(model_path).endswith(".pdparams") else \
        model_path + ".pdparams"
    state = _load(path)
    for k, v in state.items():
        global_scope()._vars[k] = v if isinstance(v, Tensor) else Tensor(v)


def load_program_state(model_path, var_list=None):
    from ..framework.io import load as _load
    path = model_path if str(model_path).endswith(".pdparams") else \
        model_path + ".pdparams"
    state = _load(path)
    import numpy as _np
    return {k: _np.asarray(v._data if isinstance(v, Tensor) else v)
            for k, v in state.items()}


def set_program_state(program, state_dict):
    for k, v in state_dict.items():
        global_scope()._vars[k] = Tensor(jnp.asarray(v))


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         **kwargs):
    """reference save_inference_model -> jit.save of the traced function."""
    raise NotImplementedError(
        "static save_inference_model: export with paddle.jit.save (StableHLO) "
        "— the static Program here is a replay tape, not a serializable graph")


def load_inference_model(path_prefix, executor=None, **kwargs):
    raise NotImplementedError(
        "static load_inference_model: use paddle.jit.load / paddle.inference")


def serialize_program(feed_vars, fetch_vars, **kwargs):
    raise NotImplementedError("serialize_program: use paddle.jit.save")


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kwargs):
    raise NotImplementedError("serialize_persistables: use paddle.save")


def deserialize_program(data):
    raise NotImplementedError("deserialize_program: use paddle.jit.load")


def deserialize_persistables(program, data, executor=None):
    raise NotImplementedError("deserialize_persistables: use paddle.load")


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content if isinstance(content, bytes) else bytes(content))


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program
