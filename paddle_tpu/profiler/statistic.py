"""Profiler statistics (reference: python/paddle/profiler/profiler_statistic.py
— aggregated per-op/kernel time tables and the sorted summary report).

Two data planes:
- DEVICE: the XPlane protobuf jax.profiler wrote is parsed (via the xprof
  tooling when installed) into per-HLO-op rows: self time, occurrences,
  category, bound-by. This is the kernel table the reference builds from
  CUPTI records.
- HOST: the op-dispatch chokepoint (core/dispatch.py apply_op) records
  per-op dispatch wall time while a Profiler is active — the eager "CPU"
  column of the reference's operator table. XLA dispatch is asynchronous, so
  host time is dispatch cost, not device latency (stated in the header).
"""
from __future__ import annotations

import glob
import os
from collections import defaultdict


def collect_device_ops(xplane_dir):
    """Parse the xplane dump into rows:
    (op_name, category, occurrences, total_self_us, avg_self_us, bound_by).
    Returns [] when no dump or no parser is available."""
    if not xplane_dir:
        return []
    files = sorted(glob.glob(os.path.join(
        xplane_dir, "plugins", "profile", "*", "*.xplane.pb")))
    if not files:
        return []
    try:
        from xprof.convert import raw_to_tool_data as rtd
        import json
        data, _ = rtd.xspace_to_tool_data([files[-1]], "hlo_stats", {})
        d = json.loads(data if isinstance(data, str) else data.decode())
        cols = [c["id"] for c in d["cols"]]
        ix = {k: cols.index(k) for k in
              ("category", "hlo_op_name", "total_self_time", "avg_self_time",
               "occurrences", "bound_by")}
        rows = []
        for r in d["rows"]:
            c = r["c"]
            rows.append((
                str(c[ix["hlo_op_name"]]["v"]),
                str(c[ix["category"]]["v"]),
                float(c[ix["occurrences"]]["v"] or 0),
                float(c[ix["total_self_time"]]["v"] or 0),
                float(c[ix["avg_self_time"]]["v"] or 0),
                str(c[ix["bound_by"]]["v"]),
            ))
        return rows
    except Exception:       # parser optional; statistics degrade gracefully
        return []


def device_summary(xplane_dir, top=25):
    rows = collect_device_ops(xplane_dir)
    if not rows:
        return None
    total = sum(r[3] for r in rows) or 1.0
    by_cat = defaultdict(float)
    for r in rows:
        by_cat[r[1]] += r[3]
    lines = ["", "-------- Device (XLA HLO self-time) by category --------",
             f"{'category':32s} {'total_ms':>12s} {'%':>7s}"]
    for cat, t in sorted(by_cat.items(), key=lambda kv: -kv[1]):
        lines.append(f"{cat:32s} {t/1e3:12.3f} {100*t/total:6.1f}%")
    lines += ["", f"-------- Device top {top} HLO ops --------",
              f"{'op':44s} {'calls':>7s} {'total_ms':>10s} {'avg_us':>9s} "
              f"{'%':>6s} {'bound':>8s}"]
    for name, cat, occ, tot, avg, bound in sorted(
            rows, key=lambda r: -r[3])[:top]:
        lines.append(f"{name[:44]:44s} {int(occ):7d} {tot/1e3:10.3f} "
                     f"{avg:9.1f} {100*tot/total:5.1f}% {bound[:8]:>8s}")
    return "\n".join(lines)


class HostOpRecorder:
    """Per-op dispatch timing, installed by Profiler via dispatch hooks."""

    def __init__(self):
        self.ops: dict[str, list] = defaultdict(lambda: [0, 0.0, 0.0, 1e30])

    def record(self, name, dt, **_):
        # extra dispatch facts (amp/taped/lifted) belong to the metrics
        # recorder; this table only aggregates host wall time
        e = self.ops[name]
        e[0] += 1
        e[1] += dt
        e[2] = max(e[2], dt)
        e[3] = min(e[3], dt)

    def table(self, sorted_by=None, top=30):
        from . import SortedKeys
        key = {
            SortedKeys.CPUTotal: lambda kv: -kv[1][1],
            SortedKeys.CPUAvg: lambda kv: -(kv[1][1] / kv[1][0]),
            SortedKeys.CPUMax: lambda kv: -kv[1][2],
            SortedKeys.CPUMin: lambda kv: kv[1][3],
        }.get(sorted_by, lambda kv: -kv[1][1])
        lines = ["", "-------- Operator (host dispatch; async — dispatch "
                     "cost, not device latency) --------",
                 f"{'op':36s} {'calls':>7s} {'total_ms':>10s} {'avg_us':>9s} "
                 f"{'max_us':>9s} {'min_us':>9s}"]
        for name, (n, tot, mx, mn) in sorted(self.ops.items(), key=key)[:top]:
            lines.append(f"{name[:36]:36s} {n:7d} {tot*1e3:10.3f} "
                         f"{tot/n*1e6:9.1f} {mx*1e6:9.1f} {mn*1e6:9.1f}")
        return "\n".join(lines)
