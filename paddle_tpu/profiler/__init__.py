"""paddle.profiler (reference: python/paddle/profiler/profiler.py:358).

TPU-native: wraps the JAX/XLA profiler (XPlane protocol → TensorBoard /
Perfetto; the reference's chrome-trace export maps to jax.profiler traces).
RecordEvent maps to jax.profiler.TraceAnnotation; host-side timeline events are
collected in-process for summary() tables.
"""
from __future__ import annotations

import contextlib
import glob
import logging
import os
import time
from collections import defaultdict
from enum import Enum

import jax

logger = logging.getLogger("paddle_tpu.profiler")


class ProfilerTarget(Enum):
    CPU = 0
    GPU = 1
    XPU = 2
    CUSTOM_DEVICE = 3
    TPU = 4


class ProfilerState(Enum):
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class SummaryView(Enum):
    DeviceView = 0
    OverView = 1
    ModelView = 2
    DistributedView = 3
    KernelView = 4
    OperatorView = 5
    MemoryView = 6


def make_scheduler(*, closed=0, ready=0, record=1, repeat=0, skip_first=0):
    def scheduler(step):
        s = step - skip_first
        if s < 0:
            return ProfilerState.CLOSED
        cycle = closed + ready + record
        if repeat and s >= cycle * repeat:
            return ProfilerState.CLOSED
        pos = s % cycle if cycle else 0
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    return scheduler


def export_chrome_tracing(dir_name, worker_name=None):
    def handler(prof):
        prof._export_dir = dir_name
    return handler


def export_protobuf(dir_name, worker_name=None):
    """on_trace_ready handler selecting the XPlane/protobuf export path.

    jax.profiler natively writes its device trace as an XPlane protobuf
    (``plugins/profile/<run>/*.xplane.pb`` under the trace dir) while
    recording, so protobuf export means: resolve the newest ``.xplane.pb``
    from the trace dir in :meth:`Profiler.export` instead of writing the
    chrome-trace JSON. Like ``export_chrome_tracing``, ``dir_name`` becomes
    the trace dir of the NEXT ``start()`` (the current trace already picked
    its dir at start time).

    Documented fallback (previously this silently aliased
    ``export_chrome_tracing``): with ``timer_only=True``, or when the
    backend wrote no xplane dump, there is no protobuf to resolve —
    ``export()`` logs the downgrade and falls back to chrome-trace JSON.
    """
    def handler(prof):
        prof._export_dir = dir_name
        prof._export_format = "protobuf"
    return handler


class RecordEvent:
    """reference: profiler/utils.py:47 — user-level trace annotation."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._ann = None
        self._begin = None

    def begin(self):
        self._ann = jax.profiler.TraceAnnotation(self.name)
        self._ann.__enter__()
        self._begin = time.perf_counter()
        _host_events[self.name].append(0.0)

    def end(self):
        if self._ann is not None:
            self._ann.__exit__(None, None, None)
            _host_events[self.name][-1] = time.perf_counter() - self._begin
            self._ann = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *exc):
        self.end()
        return False


_host_events: dict = defaultdict(list)


class Profiler:
    """reference: profiler/profiler.py:358."""

    def __init__(self, *, targets=None, scheduler=None, on_trace_ready=None,
                 record_shapes=False, profile_memory=False, timer_only=False,
                 emit_nvtx=False, custom_device_types=None, with_flops=False):
        self._scheduler = scheduler if callable(scheduler) else (
            make_scheduler(record=scheduler[1] - scheduler[0], skip_first=scheduler[0])
            if isinstance(scheduler, (tuple, list)) else None)
        self._on_trace_ready = on_trace_ready
        self._timer_only = timer_only
        self._step = 0
        self._dir = None
        self._export_dir = None
        self._active = False
        self._step_times = []
        self._last_step_t = None
        self._op_recorder = None
        self._export_format = "json"

    def start(self):
        self._dir = self._export_dir or os.path.join("/tmp", "paddle_tpu_profile")
        if not self._timer_only:
            jax.profiler.start_trace(self._dir)
            self._active = True
        from .statistic import HostOpRecorder
        from ..core.dispatch import _state, compose_recorders, metrics_recorder
        self._op_recorder = HostOpRecorder()
        # stack onto the observability recorder (if metrics are enabled) so
        # dispatch keeps its single instrumentation branch
        _state.op_recorder = compose_recorders(self._op_recorder,
                                               metrics_recorder())
        self._last_step_t = time.perf_counter()
        return self

    def stop(self):
        from ..core.dispatch import _state, metrics_recorder
        _state.op_recorder = metrics_recorder()
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        if self._on_trace_ready is not None:
            self._on_trace_ready(self)

    def step(self, num_samples=None):
        now = time.perf_counter()
        if self._last_step_t is not None:
            self._step_times.append(now - self._last_step_t)
        self._last_step_t = now
        self._step += 1

    def step_info(self, unit=None):
        if not self._step_times:
            return ""
        import numpy as np
        t = np.asarray(self._step_times[-10:])
        return (f"avg step {t.mean()*1000:.2f} ms (last {len(t)}), "
                f"ips {1.0/t.mean():.2f} steps/s")

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit="ms", views=None):
        """Reference-style sorted report (profiler_statistic.py): overview
        (step-time breakdown), device HLO table (when the xplane parsed),
        host operator table, user RecordEvent table."""
        import numpy as np
        lines = []
        if self._step_times:
            arr = np.asarray(self._step_times)
            lines += ["-------- Overview (step-time breakdown) --------",
                      f"steps={len(arr)} total={arr.sum()*1e3:.3f}ms "
                      f"avg={arr.mean()*1e3:.3f}ms "
                      f"min={arr.min()*1e3:.3f}ms max={arr.max()*1e3:.3f}ms"]
        if op_detail and getattr(self, "_op_recorder", None) is not None \
                and self._op_recorder.ops:
            lines.append(self._op_recorder.table(sorted_by=sorted_by))
        from .statistic import device_summary
        dev = device_summary(self._dir) if not self._timer_only else None
        if dev:
            lines.append(dev)
        if _host_events:
            lines += ["", "-------- User events (RecordEvent) --------"]
            for name, times in sorted(_host_events.items(),
                                      key=lambda kv: -sum(kv[1])):
                arr = np.asarray(times)
                lines.append(
                    f"{name:40s} calls={len(arr):6d} "
                    f"total={arr.sum()*1000:10.3f}ms "
                    f"avg={arr.mean()*1000:8.3f}ms")
        out = "\n".join(lines)
        print(out)  # graftlint: disable=no-adhoc-telemetry
        return out

    def _latest_xplane(self):
        """Newest .xplane.pb the jax profiler wrote under the trace dir."""
        if not self._dir:
            return None
        files = sorted(glob.glob(os.path.join(
            self._dir, "plugins", "profile", "*", "*.xplane.pb")))
        return files[-1] if files else None

    def export(self, path=None, format=None):
        """Write host events + step times as a chrome-trace JSON; the XLA
        XPlane dump (TensorBoard/Perfetto) lives in self._dir. Returns the
        written path (reference: profiler.py export).

        format="protobuf" (or an ``export_protobuf`` on_trace_ready handler)
        resolves the XPlane protobuf jax wrote instead; when none exists
        (timer_only, or the backend produced no dump) the documented
        fallback is this chrome-trace JSON path."""
        fmt = format or self._export_format
        if fmt == "protobuf":
            pb = self._latest_xplane()
            if pb is not None:
                return pb
            logger.warning(
                "export_protobuf: no .xplane.pb under %r (timer_only run, "
                "or the backend wrote no device trace); falling back to "
                "chrome-trace JSON", self._dir)
        if path is None:
            return self._dir
        import json
        events = []
        t0 = 0.0
        for name, times in _host_events.items():
            for dur in times:
                events.append({"name": name, "ph": "X", "pid": 0, "tid": 0,
                               "ts": t0 * 1e6, "dur": dur * 1e6,
                               "cat": "host"})
                t0 += dur
        t1 = 0.0
        for i, dur in enumerate(self._step_times):
            events.append({"name": f"step {i}", "ph": "X", "pid": 0,
                           "tid": 1, "ts": t1 * 1e6, "dur": dur * 1e6,
                           "cat": "step"})
            t1 += dur
        with open(path, "w") as f:
            json.dump({"traceEvents": events,
                       "xplane_dir": self._dir or ""}, f)
        return path

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


class ProfilerResult:
    """Parsed chrome-trace (reference: profiler.py ProfilerResult)."""

    def __init__(self, events, xplane_dir=""):
        self.events = events
        self.xplane_dir = xplane_dir

    def time_range_summary(self):
        agg = defaultdict(lambda: [0, 0.0])
        for e in self.events:
            agg[e["name"]][0] += 1
            agg[e["name"]][1] += e.get("dur", 0.0) / 1e6
        return {k: {"calls": v[0], "total_s": v[1]} for k, v in agg.items()}

    def summary(self):
        lines = ["--------- loaded profile ---------"]
        for name, s in sorted(self.time_range_summary().items(),
                              key=lambda kv: -kv[1]["total_s"]):
            lines.append(f"{name:40s} calls={s['calls']:6d} "
                         f"total={s['total_s']*1000:10.3f}ms")
        out = "\n".join(lines)
        print(out)  # graftlint: disable=no-adhoc-telemetry
        return out


def load_profiler_result(filename):
    """Load a Profiler.export JSON back (reference: profiler.py
    load_profiler_result)."""
    import json
    with open(filename) as f:
        d = json.load(f)
    return ProfilerResult(d.get("traceEvents", []), d.get("xplane_dir", ""))


class SortedKeys:
    """reference profiler/profiler_statistic.py SortedKeys enum: summary-table
    sort orders."""
    CPUTotal = 0
    CPUAvg = 1
    CPUMax = 2
    CPUMin = 3
    GPUTotal = 4
    GPUAvg = 5
    GPUMax = 6
    GPUMin = 7
