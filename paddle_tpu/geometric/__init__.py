"""paddle.geometric analog (reference: python/paddle/geometric — math.py
segment ops, message_passing/send_recv.py, reindex.py, sampling/neighbors.py).

TPU-native: segment reductions map to jax.ops.segment_* (XLA scatter-reduce,
which TPU lowers to sorted segmented reductions); message passing is
gather -> elementwise -> segment-reduce, all fusable under jit. Neighbor
sampling is host-side numpy (data-prep, never in the compiled path)."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap

__all__ = [
    "segment_sum", "segment_mean", "segment_min", "segment_max",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
    "sample_neighbors", "reindex_heter_graph", "weighted_sample_neighbors",
]


def _num_segments(ids, count=None):
    if count is not None:
        return int(count)
    return int(np.asarray(jnp.max(unwrap(ids)))) + 1


def _segment(op_name, jfn, data, segment_ids, num=None, zero_empty=False):
    n = _num_segments(segment_ids, num)

    def f(d, ids):
        ids = ids.astype(jnp.int32)
        out = jfn(d, ids, num_segments=n)
        if zero_empty:
            # min/max of an empty segment is +-inf in XLA; reference fills 0
            has = jax.ops.segment_sum(jnp.ones((d.shape[0],)), ids,
                                      num_segments=n) > 0
            out = jnp.where(has[(...,) + (None,) * (d.ndim - 1)], out, 0)
        return out
    return apply_op(op_name, f, data, segment_ids)


def segment_sum(data, segment_ids, name=None):
    """reference: geometric/math.py segment_sum."""
    return _segment("segment_sum", jax.ops.segment_sum, data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids)

    def f(d, ids):
        ids = ids.astype(jnp.int32)
        tot = jax.ops.segment_sum(d, ids, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), ids,
                                  num_segments=n)
        return tot / jnp.maximum(cnt, 1)[(...,) + (None,) * (d.ndim - 1)]
    return apply_op("segment_mean", f, data, segment_ids)


def segment_min(data, segment_ids, name=None):
    return _segment("segment_min", jax.ops.segment_min, data, segment_ids,
                    zero_empty=True)


def segment_max(data, segment_ids, name=None):
    return _segment("segment_max", jax.ops.segment_max, data, segment_ids,
                    zero_empty=True)


def _reduce(msg, dst, n, reduce_op):
    ops = {"sum": jax.ops.segment_sum, "mean": None,
           "max": jax.ops.segment_max, "min": jax.ops.segment_min}
    if reduce_op == "mean":
        tot = jax.ops.segment_sum(msg, dst, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((msg.shape[0],), msg.dtype), dst,
                                  num_segments=n)
        return tot / jnp.maximum(cnt, 1)[(...,) + (None,) * (msg.ndim - 1)]
    out = ops[reduce_op](msg, dst, num_segments=n)
    if reduce_op in ("max", "min"):
        # empty segments: match reference (zeros, not +-inf)
        has = jax.ops.segment_sum(jnp.ones((msg.shape[0],)), dst,
                                  num_segments=n) > 0
        out = jnp.where(has[(...,) + (None,) * (msg.ndim - 1)], out, 0)
    return out


def send_u_recv(x, src_index, dst_index, reduce_op="sum", out_size=None,
                name=None):
    """Gather x[src], reduce onto dst (reference: send_recv.py:55)."""
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"reduce_op must be sum/mean/max/min, got {reduce_op}")
    n = out_size or x.shape[0]

    def f(a, s, d):
        return _reduce(a[s.astype(jnp.int32)], d.astype(jnp.int32), int(n),
                       reduce_op)
    return apply_op("send_u_recv", f, x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op="add",
                 reduce_op="sum", out_size=None, name=None):
    """Gather x[src], combine with edge feature y, reduce onto dst
    (reference: send_recv.py send_ue_recv)."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]
    if reduce_op not in ("sum", "mean", "max", "min"):
        raise ValueError(f"bad reduce_op {reduce_op}")
    n = out_size or x.shape[0]

    def f(a, e, s, d):
        msg = combine(a[s.astype(jnp.int32)], e.astype(a.dtype))
        return _reduce(msg, d.astype(jnp.int32), int(n), reduce_op)
    return apply_op("send_ue_recv", f, x, y, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op="add", name=None):
    """Per-edge message combining x[src] and y[dst] (reference: send_uv)."""
    combine = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
               "div": jnp.divide}[message_op]

    def f(a, b, s, d):
        return combine(a[s.astype(jnp.int32)], b[d.astype(jnp.int32)])
    return apply_op("send_uv", f, x, y, src_index, dst_index)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact global ids to local ids (reference: reindex.py:23). Host-side
    (sampling/data-prep path)."""
    xs = np.asarray(unwrap(x))
    nb = np.asarray(unwrap(neighbors))
    uniq, inv = np.unique(np.concatenate([xs, nb]), return_inverse=True)
    # order: x's nodes first, then new neighbor nodes (reference contract)
    order = {}
    for v in xs.tolist():
        order.setdefault(v, len(order))
    for v in nb.tolist():
        order.setdefault(v, len(order))
    remap = np.array([order[v] for v in uniq.tolist()])
    out_nodes = np.array(sorted(order, key=order.get))
    reindexed = remap[inv[len(xs):]]
    return (Tensor(jnp.asarray(reindexed.astype(np.int64))),
            Tensor(jnp.asarray(out_nodes.astype(np.int64))),
            count)


def sample_neighbors(row, colptr, input_nodes, sample_size=-1, eids=None,
                     return_eids=False, perm_buffer=None, name=None):
    """Uniform neighbor sampling over CSC graph (reference:
    sampling/neighbors.py:25). Host-side numpy."""
    r = np.asarray(unwrap(row))
    cp = np.asarray(unwrap(colptr))
    nodes = np.asarray(unwrap(input_nodes))
    out_nb, out_cnt = [], []
    rng = np.random.RandomState(_rng_seed())
    for v in nodes.tolist():
        beg, end = int(cp[v]), int(cp[v + 1])
        nbrs = r[beg:end]
        if sample_size >= 0 and len(nbrs) > sample_size:
            nbrs = rng.choice(nbrs, size=sample_size, replace=False)
        out_nb.append(nbrs)
        out_cnt.append(len(nbrs))
    neighbors = np.concatenate(out_nb) if out_nb else np.zeros(0, r.dtype)
    return (Tensor(jnp.asarray(neighbors.astype(np.int64))),
            Tensor(jnp.asarray(np.array(out_cnt, np.int32))))


def _first_seen_remap(arrays):
    """Shared node remapping: order = xs first, then first-seen neighbors
    (same contract as reindex_graph)."""
    import numpy as _np
    order = {}
    for arr in arrays:
        for v in arr.tolist():
            if v not in order:
                order[v] = len(order)

    def remap(arr):
        if arr.size == 0:
            return _np.zeros(0, _np.int64)
        return _np.asarray([order[v] for v in arr.tolist()], _np.int64)
    nodes = _np.asarray(sorted(order, key=order.__getitem__))
    return remap, nodes


def _rng_seed():
    """Host RNG seed drawn from the framework generator (follows paddle.seed;
    shared by the neighbor samplers)."""
    from ..core.rng import next_key
    return int(np.asarray(jax.random.key_data(next_key())).ravel()[-1]
               & 0x7FFFFFFF)


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """reference geometric/reindex.py reindex_heter_graph -> (reindex_src,
    reindex_dst, out_nodes): neighbors/count are per-edge-type lists; src is
    every neighbor remapped into the shared numbering (x first, then
    first-seen), dst repeats each x position by its per-type neighbor count."""
    from ..core.dispatch import unwrap as _u
    import numpy as _np
    xs = _np.asarray(_u(x)).reshape(-1)
    neigh = [_np.asarray(_u(n)).reshape(-1) for n in neighbors]
    cnts = [_np.asarray(_u(c)).reshape(-1) for c in count]
    remap, nodes = _first_seen_remap([xs] + neigh)
    src = _np.concatenate([remap(n) for n in neigh]) if neigh else         _np.zeros(0, _np.int64)
    dst_parts = [_np.repeat(_np.arange(len(xs), dtype=_np.int64), c)
                 for c in cnts]
    dst = _np.concatenate(dst_parts) if dst_parts else _np.zeros(0, _np.int64)
    return (Tensor(jnp.asarray(src)), Tensor(jnp.asarray(dst)),
            Tensor(jnp.asarray(nodes)))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None, return_eids=False,
                              name=None):
    """reference geometric/sampling/neighbors.py weighted_sample_neighbors:
    weight-proportional sampling without replacement (CSC graph). Zero-weight
    edges are excluded from sampling; all-zero rows fall back to uniform."""
    from ..core.dispatch import unwrap as _u
    import numpy as _np
    r = _np.asarray(_u(row)).reshape(-1)
    cp = _np.asarray(_u(colptr)).reshape(-1)
    w = _np.asarray(_u(edge_weight)).reshape(-1).astype(_np.float64)
    nodes = _np.asarray(_u(input_nodes)).reshape(-1)
    ev = _np.asarray(_u(eids)).reshape(-1) if eids is not None else None
    rng = _np.random.RandomState(_rng_seed())
    out_n, out_cnt, out_e = [], [], []
    for v in nodes:
        lo, hi = int(cp[v]), int(cp[v + 1])
        pos_all = _np.arange(lo, hi)
        cw = w[lo:hi]
        if cw.sum() > 0:
            pos_all = pos_all[cw > 0]
            cw = cw[cw > 0]
        if sample_size < 0 or len(pos_all) <= sample_size:
            picked = pos_all
        else:
            p = cw / cw.sum() if cw.sum() > 0 else None
            picked = rng.choice(pos_all, size=sample_size, replace=False, p=p)
        out_n.append(r[picked])
        out_cnt.append(len(picked))
        if return_eids:
            out_e.append(ev[picked] if ev is not None else picked)
    flat = _np.concatenate(out_n) if out_n else _np.zeros(0, r.dtype)
    res = (Tensor(jnp.asarray(flat)),
           Tensor(jnp.asarray(_np.asarray(out_cnt, _np.int32))))
    if return_eids:
        fe = _np.concatenate(out_e) if out_e else _np.zeros(0, _np.int64)
        return res + (Tensor(jnp.asarray(fe)),)
    return res
