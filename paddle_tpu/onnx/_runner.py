"""Dependency-free numpy evaluator for the ONNX op subset export() emits.

Exists so exported models can be VERIFIED in-tree (decode the protobuf,
re-execute the graph, compare against the framework's own forward) without
an onnx runtime in the image — and doubles as executable documentation of
the op subset's semantics."""
from __future__ import annotations

import numpy as np

from . import _proto as P


def _get_attrs(node_fields):
    attrs = {}
    for raw in node_fields.get(5, []):
        f = P.decode(raw)
        name = f[1][0].decode()
        atype = int(f.get(20, [0])[0])
        if atype == P.ATTR_INT:
            v = int(f[3][0])
            if v >= 1 << 63:
                v -= 1 << 64
            attrs[name] = v
        elif atype == P.ATTR_FLOAT:
            attrs[name] = float(f[2][0])
        elif atype == P.ATTR_STRING:
            attrs[name] = f[4][0].decode()
        elif atype == P.ATTR_INTS:
            vals, i = [], 0
            buf = f[8][0]
            while i < len(buf):
                v, i = P._read_varint(buf, i)
                if v >= 1 << 63:
                    v -= 1 << 64
                vals.append(v)
            attrs[name] = vals
        elif atype == P.ATTR_TENSOR:
            attrs[name] = P.decode_tensor(f[5][0])[1]
    return attrs


def run(model_bytes: bytes, inputs: dict[str, np.ndarray]):
    """Execute a serialized ModelProto; returns {output_name: array}."""
    mf = P.decode(model_bytes)
    gf = P.decode(mf[7][0])
    env = dict(inputs)
    for raw in gf.get(5, []):                       # initializers
        name, arr = P.decode_tensor(raw)
        env[name] = arr
    out_names = []
    for raw in gf.get(12, []):                      # declared outputs
        out_names.append(P.decode(raw)[1][0].decode())
    for raw in gf.get(1, []):                       # nodes, topological
        f = P.decode(raw)
        ins = [env[b.decode()] for b in f.get(1, [])]
        outs = [b.decode() for b in f.get(2, [])]
        op = f[4][0].decode()
        attrs = _get_attrs(f)
        env[outs[0]] = _OPS[op](ins, attrs)
    return {n: env[n] for n in out_names}


def _reduce(fn, ins, attrs, axes_from_input):
    x = ins[0]
    axes = tuple(int(a) for a in (ins[1] if axes_from_input
                                  else attrs.get("axes", [])))
    return fn(x, axis=axes or None, keepdims=bool(attrs.get("keepdims", 1)))


_OPS = {
    "Add": lambda i, a: i[0] + i[1],
    "Sub": lambda i, a: i[0] - i[1],
    "Mul": lambda i, a: i[0] * i[1],
    "Div": lambda i, a: i[0] / i[1],
    "Max": lambda i, a: np.maximum(i[0], i[1]),
    "Min": lambda i, a: np.minimum(i[0], i[1]),
    "Pow": lambda i, a: np.power(i[0], i[1]),
    "Neg": lambda i, a: -i[0],
    "Exp": lambda i, a: np.exp(i[0]),
    "Log": lambda i, a: np.log(i[0]),
    "Tanh": lambda i, a: np.tanh(i[0]),
    "Sigmoid": lambda i, a: 1.0 / (1.0 + np.exp(-i[0])),
    "Sqrt": lambda i, a: np.sqrt(i[0]),
    "Erf": lambda i, a: __import__("scipy.special",
                                   fromlist=["erf"]).erf(i[0]),
    "Abs": lambda i, a: np.abs(i[0]),
    "Sign": lambda i, a: np.sign(i[0]),
    "Floor": lambda i, a: np.floor(i[0]),
    "Ceil": lambda i, a: np.ceil(i[0]),
    "Reciprocal": lambda i, a: 1.0 / i[0],
    "MatMul": lambda i, a: i[0] @ i[1],
    "Transpose": lambda i, a: np.transpose(i[0], a["perm"]),
    "Reshape": lambda i, a: i[0].reshape([int(d) for d in i[1]]),
    "Expand": lambda i, a: np.broadcast_to(
        i[0], [int(d) for d in i[1]]).copy(),
    "Concat": lambda i, a: np.concatenate(i, axis=a["axis"]),
    "Cast": lambda i, a: i[0].astype(P._ONNX2NP[a["to"]]),
    "Where": lambda i, a: np.where(i[0], i[1], i[2]),
    "Identity": lambda i, a: i[0],
    "Greater": lambda i, a: i[0] > i[1],
    "Less": lambda i, a: i[0] < i[1],
    "GreaterOrEqual": lambda i, a: i[0] >= i[1],
    "LessOrEqual": lambda i, a: i[0] <= i[1],
    "Equal": lambda i, a: i[0] == i[1],
    "And": lambda i, a: np.logical_and(i[0], i[1]),
    "Or": lambda i, a: np.logical_or(i[0], i[1]),
    "Not": lambda i, a: np.logical_not(i[0]),
    "ReduceSum": lambda i, a: _reduce(np.sum, i, a, True),
    "ReduceMax": lambda i, a: _reduce(np.max, i, a, False),
    "ReduceMin": lambda i, a: _reduce(np.min, i, a, False),
    "Slice": lambda i, a: i[0][tuple(
        slice(int(s), int(e), int(st))
        for s, e, st in zip(i[1], i[2], i[4]))],
}
