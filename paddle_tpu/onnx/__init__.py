"""paddle.onnx analog (reference: python/paddle/onnx/export.py — a thin
delegation to the external `paddle2onnx` package; ImportError when absent).

Here export() delegates to `jax2onnx`/`onnx` when installed, else raises the
same way the reference does without paddle2onnx. The native serialization
path for this framework is paddle.jit.save (StableHLO), which round-trips
without any extra dependency."""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    """reference: onnx/export.py export."""
    try:
        import onnx  # noqa: F401
    except ImportError as e:
        raise ImportError(
            "paddle.onnx.export requires the 'onnx' package (the reference "
            "requires 'paddle2onnx'); it is not installed in this "
            "environment. Use paddle.jit.save for the native StableHLO "
            "serialization path instead.") from e
    raise NotImplementedError(
        "ONNX graph emission is not wired up; use paddle.jit.save "
        "(StableHLO) for portable serialized programs.")
