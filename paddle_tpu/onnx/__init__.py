"""paddle.onnx analog (reference: python/paddle/onnx/export.py).

The reference delegates to the external `paddle2onnx` package and raises
ImportError without it.  This environment bakes in no ONNX tooling, so the
export path is SELF-CONTAINED: the layer's forward is captured as a jaxpr
(the framework's program IR) and serialized directly against the public
onnx.proto schema (_proto.py hand-encodes the protobuf; _export.py maps jax
primitives onto ONNX ops; _runner.py re-executes exported graphs in numpy so
tests verify numerics without an ONNX runtime).

Supported op subset: MLP-class inference graphs — Linear stacks, norms,
standard activations, elementwise math, reshape/transpose/concat/slice.
Unsupported primitives raise NotImplementedError naming the primitive.  The
native serialization path for full models remains paddle.jit.save
(StableHLO), which round-trips any program.
"""
from __future__ import annotations

import numpy as np

__all__ = ["export", "load_and_run"]


def load_and_run(path, inputs):
    """Execute an exported .onnx file with the in-tree numpy evaluator
    (covers exactly the op subset export() emits).  ``inputs`` maps input
    names ("x0", "x1", ...) to numpy arrays; returns {output_name: array}.
    The public verification entry point — no external ONNX runtime needed."""
    from . import _runner
    with open(path, "rb") as f:
        return _runner.run(f.read(), inputs)


def export(layer, path, input_spec=None, opset_version=17, **configs):
    """Export `layer`'s forward as an ONNX model to ``path`` + '.onnx'.

    input_spec: list of example Tensors/arrays, or InputSpec-like objects
    with .shape and .dtype (reference: static.InputSpec).  Returns the path
    written.
    """
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    from . import _export

    if input_spec is None:
        raise ValueError("paddle.onnx.export requires input_spec (example "
                         "tensors or InputSpec)")

    examples = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(jnp.asarray(spec._data))
        elif hasattr(spec, "shape") and hasattr(spec, "dtype") and \
                not isinstance(spec, np.ndarray):
            shape = [1 if d in (None, -1) else int(d) for d in spec.shape]
            examples.append(jnp.zeros(shape, np.dtype(spec.dtype)))
        else:
            examples.append(jnp.asarray(spec))

    fn = layer.forward if hasattr(layer, "forward") else layer

    def array_fn(*arrays):
        outs = fn(*[Tensor(a) for a in arrays])
        flat = outs if isinstance(outs, (tuple, list)) else [outs]
        return [o._data if isinstance(o, Tensor) else o for o in flat]

    closed = _export.trace_callable(array_fn, examples)
    in_names = [f"x{i}" for i in range(len(examples))]
    out_names = [f"y{i}" for i in range(len(closed.jaxpr.outvars))]
    blob = _export.jaxpr_to_model(closed, in_names, out_names,
                                  graph_name=type(layer).__name__,
                                  opset=opset_version)
    out_path = path if path.endswith(".onnx") else path + ".onnx"
    with open(out_path, "wb") as f:
        f.write(blob)
    return out_path
