"""jaxpr -> ONNX GraphProto conversion (reference capability:
python/paddle/onnx/export.py, which delegates to paddle2onnx's
program->ONNX converter; here the captured program IS a jaxpr, so the
converter maps jax primitives onto ONNX ops directly).

Supported primitive subset (enough for MLP/attention-free inference graphs —
Linear stacks, norms, standard activations):
  dot_general (matmul form), add/sub/mul/div/max/min/pow, neg, exp, log,
  tanh, logistic, sqrt, rsqrt, erf, abs, sign, floor, ceil, integer_pow,
  reduce_sum/max/min, broadcast_in_dim, reshape, transpose, concatenate,
  convert_element_type, select_n, slice, custom_jvp_call/pjit (inlined).
Anything else raises NotImplementedError with the primitive name.
"""
from __future__ import annotations

import numpy as np
import jax

from . import _proto as P


class _Converter:
    def __init__(self):
        self.nodes: list[bytes] = []
        self.initializers: list[bytes] = []
        self.names: dict[int, str] = {}     # id(jax var) -> onnx name
        self.counter = 0

    def fresh(self, hint="t"):
        self.counter += 1
        return f"{hint}_{self.counter}"

    def name_of(self, v):
        from jax._src.core import Literal
        if isinstance(v, Literal):
            return self.add_const(np.asarray(v.val))
        return self.names[id(v)]

    def add_const(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers.append(P.tensor_proto(name, np.asarray(arr)))
        return name

    def emit(self, op, ins, n_out=1, **attrs):
        outs = [self.fresh(op.lower()) for _ in range(n_out)]
        self.nodes.append(P.node(op, ins, outs, name=self.fresh(op), **attrs))
        return outs if n_out > 1 else outs[0]

    def set_name(self, var, name):
        self.names[id(var)] = name

    # ------------------------------ primitives -------------------------------
    def convert_eqn(self, eqn):
        prim = eqn.primitive.name
        handler = getattr(self, f"_p_{prim}", None)
        if handler is None:
            handler = _SIMPLE.get(prim)
            if handler is None:
                raise NotImplementedError(
                    f"onnx export: unsupported primitive '{prim}' — the "
                    "supported subset is documented in paddle_tpu/onnx")
            ins = [self.name_of(v) for v in eqn.invars]
            self.set_name(eqn.outvars[0], self.emit(handler, ins))
            return
        handler(eqn)

    def _p_dot_general(self, eqn):
        ((lc, rc), (lb, rb)) = eqn.params["dimension_numbers"]
        a, b = eqn.invars
        an, bn = self.name_of(a), self.name_of(b)
        if lb or rb:
            raise NotImplementedError("onnx export: batched dot_general")
        if len(lc) != 1 or len(rc) != 1:
            raise NotImplementedError("onnx export: multi-dim contraction")
        # canonical MatMul contracts lhs last dim with rhs first dim
        if lc[0] != a.aval.ndim - 1:
            perm = [d for d in range(a.aval.ndim) if d != lc[0]] + [lc[0]]
            an = self.emit("Transpose", [an], perm=perm)
        if rc[0] != 0:
            perm = [rc[0]] + [d for d in range(b.aval.ndim) if d != rc[0]]
            bn = self.emit("Transpose", [bn], perm=perm)
        self.set_name(eqn.outvars[0], self.emit("MatMul", [an, bn]))

    def _p_reshape(self, eqn):
        shape = self.add_const(np.asarray(eqn.params["new_sizes"], np.int64),
                               "shape")
        self.set_name(eqn.outvars[0], self.emit(
            "Reshape", [self.name_of(eqn.invars[0]), shape]))

    def _p_transpose(self, eqn):
        self.set_name(eqn.outvars[0], self.emit(
            "Transpose", [self.name_of(eqn.invars[0])],
            perm=list(eqn.params["permutation"])))

    def _p_broadcast_in_dim(self, eqn):
        x = eqn.invars[0]
        tgt = eqn.params["shape"]
        bdims = eqn.params["broadcast_dimensions"]
        xn = self.name_of(x)
        # place the operand's dims at bdims, 1 elsewhere, then Expand
        inter = [1] * len(tgt)
        for i, d in enumerate(bdims):
            inter[d] = x.aval.shape[i] if x.aval.ndim else 1
        if tuple(inter) != tuple(x.aval.shape):
            shape = self.add_const(np.asarray(inter, np.int64), "shape")
            xn = self.emit("Reshape", [xn, shape])
        shape = self.add_const(np.asarray(tgt, np.int64), "shape")
        self.set_name(eqn.outvars[0], self.emit("Expand", [xn, shape]))

    def _p_concatenate(self, eqn):
        self.set_name(eqn.outvars[0], self.emit(
            "Concat", [self.name_of(v) for v in eqn.invars],
            axis=int(eqn.params["dimension"])))

    def _p_convert_element_type(self, eqn):
        to = P.np_to_onnx_dtype(eqn.params["new_dtype"])
        self.set_name(eqn.outvars[0], self.emit(
            "Cast", [self.name_of(eqn.invars[0])], to=int(to)))

    def _p_select_n(self, eqn):
        c, x0, x1 = (self.name_of(v) for v in eqn.invars)
        # select_n picks cases[c]: False -> x0, True -> x1; Where picks its
        # SECOND operand where the condition is true
        self.set_name(eqn.outvars[0], self.emit("Where", [c, x1, x0]))

    def _p_integer_pow(self, eqn):
        y = eqn.params["y"]
        xn = self.name_of(eqn.invars[0])
        if y == 2:
            out = self.emit("Mul", [xn, xn])
        elif y == -1:
            out = self.emit("Reciprocal", [xn])
        else:
            e = self.add_const(np.asarray(float(y), np.float32), "exp")
            out = self.emit("Pow", [xn, e])
        self.set_name(eqn.outvars[0], out)

    def _p_square(self, eqn):
        xn = self.name_of(eqn.invars[0])
        self.set_name(eqn.outvars[0], self.emit("Mul", [xn, xn]))

    def _p_erfc(self, eqn):
        one = self.add_const(np.asarray(1.0, np.float32), "one")
        e = self.emit("Erf", [self.name_of(eqn.invars[0])])
        self.set_name(eqn.outvars[0], self.emit("Sub", [one, e]))

    def _p_rsqrt(self, eqn):
        s = self.emit("Sqrt", [self.name_of(eqn.invars[0])])
        self.set_name(eqn.outvars[0], self.emit("Reciprocal", [s]))

    def _reduce(self, eqn, op, axes_as_input):
        xn = self.name_of(eqn.invars[0])
        axes = [int(a) for a in eqn.params["axes"]]
        if axes_as_input:    # ReduceSum carries axes as an input since opset 13
            an = self.add_const(np.asarray(axes, np.int64), "axes")
            out = self.emit(op, [xn, an], keepdims=0)
        else:                # ReduceMax/Min keep attribute axes through opset 17
            out = self.emit(op, [xn], axes=axes, keepdims=0)
        self.set_name(eqn.outvars[0], out)

    def _p_reduce_sum(self, eqn):
        self._reduce(eqn, "ReduceSum", True)

    def _p_reduce_max(self, eqn):
        self._reduce(eqn, "ReduceMax", False)

    def _p_reduce_min(self, eqn):
        self._reduce(eqn, "ReduceMin", False)

    def _p_slice(self, eqn):
        xn = self.name_of(eqn.invars[0])
        starts = eqn.params["start_indices"]
        ends = eqn.params["limit_indices"]
        strides = eqn.params["strides"] or [1] * len(starts)
        axes = list(range(len(starts)))
        ins = [xn,
               self.add_const(np.asarray(starts, np.int64), "starts"),
               self.add_const(np.asarray(ends, np.int64), "ends"),
               self.add_const(np.asarray(axes, np.int64), "axes"),
               self.add_const(np.asarray(strides, np.int64), "steps")]
        self.set_name(eqn.outvars[0], self.emit("Slice", ins))

    # nested jaxprs (jit regions, custom_jvp wrappers like relu/gelu): inline
    def _inline(self, eqn, inner, invals):
        for iv, outer in zip(inner.jaxpr.invars, invals):
            self.set_name(iv, outer)
        for cv, cval in zip(inner.jaxpr.constvars, inner.consts):
            self.set_name(cv, self.add_const(np.asarray(cval)))
        for sub in inner.jaxpr.eqns:
            self.convert_eqn(sub)
        for ov, outer in zip(inner.jaxpr.outvars, eqn.outvars):
            self.set_name(outer, self.name_of(ov))

    def _p_pjit(self, eqn):
        self._inline(eqn, eqn.params["jaxpr"],
                     [self.name_of(v) for v in eqn.invars])

    _p_jit = _p_pjit          # this jax names the inner-jit primitive 'jit'

    def _p_closed_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"],
                     [self.name_of(v) for v in eqn.invars])

    def _p_custom_jvp_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"],
                     [self.name_of(v) for v in eqn.invars])

    def _p_custom_vjp_call(self, eqn):
        self._inline(eqn, eqn.params["call_jaxpr"],
                     [self.name_of(v) for v in eqn.invars])

    def _p_stop_gradient(self, eqn):
        self.set_name(eqn.outvars[0], self.name_of(eqn.invars[0]))

    def _p_copy(self, eqn):
        self.set_name(eqn.outvars[0], self.name_of(eqn.invars[0]))


_SIMPLE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "neg": "Neg",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "erf": "Erf", "abs": "Abs", "sign": "Sign",
    "floor": "Floor", "ceil": "Ceil",
    "gt": "Greater", "lt": "Less", "ge": "GreaterOrEqual",
    "le": "LessOrEqual", "eq": "Equal", "and": "And", "or": "Or",
    "not": "Not",
}


def jaxpr_to_model(closed, in_names, out_names, graph_name="paddle_tpu",
                   opset=17):
    """ClosedJaxpr -> serialized ONNX ModelProto bytes."""
    cv = _Converter()
    jaxpr = closed.jaxpr
    inputs = []
    for v, nm in zip(jaxpr.invars, in_names):
        cv.set_name(v, nm)
        inputs.append(P.value_info(nm, np.dtype(v.aval.dtype), v.aval.shape))
    for v, cval in zip(jaxpr.constvars, closed.consts):
        cv.set_name(v, cv.add_const(np.asarray(cval), "param"))
    for eqn in jaxpr.eqns:
        cv.convert_eqn(eqn)
    outputs = []
    for v, nm in zip(jaxpr.outvars, out_names):
        # alias the final value to the declared output name
        cv.nodes.append(P.node("Identity", [cv.name_of(v)], [nm],
                               name=cv.fresh("out")))
        outputs.append(P.value_info(nm, np.dtype(v.aval.dtype), v.aval.shape))
    g = P.graph(cv.nodes, graph_name, cv.initializers, inputs, outputs)
    return P.model(g, opset=opset)


def trace_callable(fn, example_arrays):
    return jax.make_jaxpr(fn)(*example_arrays)
