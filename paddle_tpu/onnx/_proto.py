"""Minimal self-contained ONNX protobuf encoder/decoder.

The reference delegates ONNX emission to an external package
(python/paddle/onnx/export.py -> paddle2onnx); this environment has no onnx
package baked in, so the serializer is implemented directly against the
public, stable onnx.proto schema (targets IR version 8 / default opset 17).
Only the message subset export() emits is implemented: ModelProto,
GraphProto, NodeProto, AttributeProto, TensorProto, ValueInfoProto.

The decoder is generic protobuf (field -> wire values) and exists so tests
can round-trip and a numpy evaluator can re-execute exported graphs without
any external dependency.
"""
from __future__ import annotations

import struct

import numpy as np

# onnx.TensorProto.DataType (public enum values)
FLOAT, INT32, INT64, BOOL, FLOAT16, DOUBLE, BFLOAT16 = 1, 6, 7, 9, 10, 11, 16

_NP2ONNX = {
    np.dtype(np.float32): FLOAT,
    np.dtype(np.float64): DOUBLE,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.bool_): BOOL,
    np.dtype(np.float16): FLOAT16,
}

_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}
try:                                   # bf16 graphs decode via ml_dtypes
    import ml_dtypes as _mld
    _ONNX2NP[BFLOAT16] = np.dtype(_mld.bfloat16)
except ImportError:                    # pragma: no cover
    pass


def np_to_onnx_dtype(dt) -> int:
    dt = np.dtype(dt)
    if dt.name == "bfloat16":
        return BFLOAT16
    if dt not in _NP2ONNX:
        raise NotImplementedError(f"onnx export: unsupported dtype {dt}")
    return _NP2ONNX[dt]


# ------------------------------ wire encoding --------------------------------
def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(num: int, value: int) -> bytes:
    return _varint(num << 3 | 0) + _varint(int(value))


def field_bytes(num: int, payload: bytes) -> bytes:
    return _varint(num << 3 | 2) + _varint(len(payload)) + payload


def field_string(num: int, s: str) -> bytes:
    return field_bytes(num, s.encode())


def packed_int64(num: int, values) -> bytes:
    body = b"".join(_varint(int(v)) for v in values)
    return field_bytes(num, body)


# ------------------------------ message builders -----------------------------
def tensor_proto(name: str, arr: np.ndarray) -> bytes:
    """TensorProto: dims=1, data_type=2, name=8, raw_data=9 (little-endian)."""
    arr = np.ascontiguousarray(arr)
    dt = np_to_onnx_dtype(arr.dtype)
    raw = arr.tobytes()
    msg = b"".join(field_varint(1, d) for d in arr.shape)
    msg += field_varint(2, dt)
    msg += field_string(8, name)
    msg += field_bytes(9, raw)
    return msg


def value_info(name: str, dtype, shape) -> bytes:
    """ValueInfoProto{name=1, type=2} / TypeProto{tensor_type=1} /
    Tensor{elem_type=1, shape=2} / TensorShapeProto{dim=1{dim_value=1}}."""
    dims = b"".join(
        field_bytes(1, field_varint(1, int(d))) for d in shape)
    tshape = dims
    ttensor = field_varint(1, np_to_onnx_dtype(dtype)) + field_bytes(2, tshape)
    ttype = field_bytes(1, ttensor)
    return field_string(1, name) + field_bytes(2, ttype)


ATTR_FLOAT, ATTR_INT, ATTR_STRING, ATTR_TENSOR = 1, 2, 3, 4
ATTR_FLOATS, ATTR_INTS = 6, 7


def attribute(name: str, value) -> bytes:
    """AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8, type=20."""
    msg = field_string(1, name)
    if isinstance(value, bool) or isinstance(value, (int, np.integer)):
        msg += _varint(3 << 3 | 0) + _varint(int(value) & ((1 << 64) - 1))
        msg += field_varint(20, ATTR_INT)
    elif isinstance(value, float):
        msg += _varint(2 << 3 | 5) + struct.pack("<f", value)
        msg += field_varint(20, ATTR_FLOAT)
    elif isinstance(value, str):
        msg += field_bytes(4, value.encode())
        msg += field_varint(20, ATTR_STRING)
    elif isinstance(value, np.ndarray):
        msg += field_bytes(5, tensor_proto(name + "_t", value))
        msg += field_varint(20, ATTR_TENSOR)
    elif isinstance(value, (list, tuple)) and all(
            isinstance(v, (int, np.integer)) for v in value):
        msg += packed_int64(8, value)
        msg += field_varint(20, ATTR_INTS)
    elif isinstance(value, (list, tuple)):
        msg += field_bytes(7, b"".join(struct.pack("<f", float(v))
                                       for v in value))
        msg += field_varint(20, ATTR_FLOATS)
    else:
        raise NotImplementedError(f"attribute {name}: {type(value)}")
    return msg


def node(op_type: str, inputs, outputs, name="", **attrs) -> bytes:
    """NodeProto: input=1, output=2, name=3, op_type=4, attribute=5."""
    msg = b"".join(field_string(1, i) for i in inputs)
    msg += b"".join(field_string(2, o) for o in outputs)
    if name:
        msg += field_string(3, name)
    msg += field_string(4, op_type)
    for k, v in attrs.items():
        msg += field_bytes(5, attribute(k, v))
    return msg


def graph(nodes, name, initializers, inputs, outputs) -> bytes:
    """GraphProto: node=1, name=2, initializer=5, input=11, output=12."""
    msg = b"".join(field_bytes(1, n) for n in nodes)
    msg += field_string(2, name)
    msg += b"".join(field_bytes(5, t) for t in initializers)
    msg += b"".join(field_bytes(11, v) for v in inputs)
    msg += b"".join(field_bytes(12, v) for v in outputs)
    return msg


def model(graph_msg: bytes, opset: int = 17, producer="paddle_tpu") -> bytes:
    """ModelProto: ir_version=1, producer_name=2, graph=7, opset_import=8
    (OperatorSetIdProto{domain=1, version=2})."""
    msg = field_varint(1, 8)                   # IR version 8
    msg += field_string(2, producer)
    msg += field_bytes(7, graph_msg)
    msg += field_bytes(8, field_string(1, "") + field_varint(2, opset))
    return msg


# ------------------------------ generic decoder ------------------------------
def decode(buf: bytes):
    """Parse a protobuf message into {field_number: [values]}; length-
    delimited fields come back as raw bytes (decode nested messages by
    calling decode again)."""
    out: dict[int, list] = {}
    i, n = 0, len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        num, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _read_varint(buf, i)
        elif wt == 2:
            ln, i = _read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == 5:
            v = struct.unpack("<f", buf[i:i + 4])[0]
            i += 4
        elif wt == 1:
            v = struct.unpack("<d", buf[i:i + 8])[0]
            i += 8
        else:
            raise ValueError(f"unsupported wire type {wt}")
        out.setdefault(num, []).append(v)
    return out


def _read_varint(buf, i):
    shift = v = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def decode_tensor(buf: bytes) -> tuple[str, np.ndarray]:
    f = decode(buf)
    dims = [int(d) for d in f.get(1, [])]
    dt = _ONNX2NP[int(f[2][0])]
    name = f.get(8, [b""])[0].decode()
    arr = np.frombuffer(f[9][0], dt).reshape(dims)
    return name, arr
