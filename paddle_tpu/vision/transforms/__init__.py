"""paddle.vision.transforms (reference: python/paddle/vision/transforms) — numpy-based."""
from __future__ import annotations

import numpy as np

from ...core.tensor import Tensor


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        new_shape = list(arr.shape)
        new_shape[h_ax], new_shape[w_ax] = self.size
        return np.asarray(jax.image.resize(jnp.asarray(arr), new_shape, "linear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            return arr[..., ::-1].copy() if arr.ndim == 3 and arr.shape[0] in (1, 3) \
                else arr[:, ::-1].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if self.padding:
            pads = [(0, 0), (self.padding, self.padding), (self.padding, self.padding)] \
                if chw else [(self.padding, self.padding), (self.padding, self.padding)] + \
                ([(0, 0)] if arr.ndim == 3 else [])
            arr = np.pad(arr, pads, mode="constant")
        h_ax = 1 if chw else 0
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


# ---- round-2 additions: the rest of the reference transform set -------------
def _axes(arr):
    """(h_axis, w_axis, chw?) for a 2D/3D image array."""
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    return (1, 2, True) if chw else (0, 1, False)


def hflip(img):
    arr = np.asarray(img)
    h, w, chw = _axes(arr)
    return np.flip(arr, axis=w).copy()


def vflip(img):
    arr = np.asarray(img)
    h, w, chw = _axes(arr)
    return np.flip(arr, axis=h).copy()


def crop(img, top, left, height, width):
    arr = np.asarray(img)
    h, w, chw = _axes(arr)
    sl = [slice(None)] * arr.ndim
    sl[h] = slice(top, top + height)
    sl[w] = slice(left, left + width)
    return arr[tuple(sl)]


def center_crop(img, output_size):
    size = output_size if isinstance(output_size, (list, tuple)) else \
        (output_size, output_size)
    arr = np.asarray(img)
    h, w, chw = _axes(arr)
    th, tw = size
    top = max(0, (arr.shape[h] - th) // 2)
    left = max(0, (arr.shape[w] - tw) // 2)
    return crop(arr, top, left, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:         # (left/right, top/bottom)
        pl = pr = padding[0]
        pt = pb = padding[1]
    else:
        pl, pt, pr, pb = padding
    h, w, chw = _axes(arr)
    pads = [(0, 0)] * arr.ndim
    pads[h] = (pt, pb)
    pads[w] = (pl, pr)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, pads, mode=mode, **kw)


def _value_range(img):
    """Max representable value from DTYPE (not data): integers use their
    type's range, floats are 0..1 by convention (PIL/reference)."""
    dt = np.asarray(img).dtype
    return float(np.iinfo(dt).max) if np.issubdtype(dt, np.integer) else 1.0


def _restore_dtype(out, like):
    dt = np.asarray(like).dtype
    return out.astype(dt) if np.issubdtype(dt, np.integer) else out


def adjust_brightness(img, brightness_factor):
    hi = _value_range(img)
    arr = np.asarray(img, np.float32)
    return _restore_dtype(np.clip(arr * brightness_factor, 0, hi), img)


def adjust_contrast(img, contrast_factor):
    hi = _value_range(img)
    arr = np.asarray(img, np.float32)
    mean = arr.mean()
    return _restore_dtype(
        np.clip((arr - mean) * contrast_factor + mean, 0, hi), img)


_GRAY_WGT = np.array([0.299, 0.587, 0.114], np.float32)


def _luminance(arr, chw):
    """Weighted gray over the channel axis; 1-chan passes through, RGBA uses
    the RGB channels."""
    c_ax = 0 if chw else -1
    nc = arr.shape[c_ax]
    if nc == 1:
        return np.take(arr, 0, axis=c_ax)
    rgb = np.take(arr, [0, 1, 2], axis=c_ax) if nc == 4 else arr
    if chw:
        return np.tensordot(_GRAY_WGT, rgb, axes=([0], [0]))
    return rgb @ _GRAY_WGT


def adjust_saturation(img, saturation_factor):
    hi = _value_range(img)
    arr = np.asarray(img, np.float32)
    if arr.ndim == 2:
        return _restore_dtype(arr, img)
    h, w, chw = _axes(arr)
    gray = np.expand_dims(_luminance(arr, chw), 0 if chw else -1)
    return _restore_dtype(
        np.clip(gray + (arr - gray) * saturation_factor, 0, hi), img)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img, np.float32)
    h, w, chw = _axes(arr)
    g = arr if arr.ndim == 2 else _luminance(arr, chw)
    g = _restore_dtype(g, img)
    if num_output_channels == 1:
        return g[None] if chw or arr.ndim == 2 else g[..., None]
    rep = [g] * num_output_channels
    return np.stack(rep, axis=0 if (chw or arr.ndim == 2) else -1)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    from scipy import ndimage
    arr = np.asarray(img, np.float32)
    h, w, chw = _axes(arr)
    order = {"nearest": 0, "bilinear": 1}[interpolation]
    return ndimage.rotate(arr, -angle, axes=(w, h), reshape=expand,
                          order=order, cval=fill)


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.args = (padding, fill, padding_mode)

    def __call__(self, img):
        return pad(img, *self.args)


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.n)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_brightness(img, f)


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_contrast(img, f)


class SaturationTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_saturation(img, f)


class ColorJitter:
    """brightness/contrast/saturation jitter (reference transforms.ColorJitter;
    hue omitted: needs HSV round-trip the reference does via PIL)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation)]

    def __call__(self, img):
        for t in np.random.permutation(self.ts):
            img = t(img)
        return img


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, **self.kw)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img)
        h, w, chw = _axes(arr)
        H, W = arr.shape[h], arr.shape[w]
        area = H * W
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= W and ch <= H:
                top = np.random.randint(0, H - ch + 1)
                left = np.random.randint(0, W - cw + 1)
                patch = crop(arr, top, left, ch, cw)
                return Resize(self.size, self.interpolation)(patch)
        return Resize(self.size, self.interpolation)(center_crop(
            arr, (min(H, W), min(H, W))))


# ---- round-2 completion (reference vision/transforms/transforms.py) ----------
class BaseTransform:
    """reference transforms.py BaseTransform: keys-aware callable base. The
    functional core here applies `_apply_image` to array inputs."""

    def __init__(self, keys=None):
        self.keys = keys or ("image",)

    def _apply_image(self, img):
        raise NotImplementedError

    def __call__(self, inputs):
        if isinstance(inputs, (list, tuple)):
            keys = list(self.keys) + ["__passthrough__"] * (
                len(inputs) - len(self.keys))   # extras pass through untouched
            return type(inputs)(
                self._apply_image(v) if k == "image" else v
                for k, v in zip(keys, inputs))
        return self._apply_image(inputs)


class Transpose(BaseTransform):
    """reference Transpose: HWC -> CHW (or a custom order)."""

    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = tuple(order)

    def _apply_image(self, img):
        arr = np.asarray(img)
        if arr.ndim == 2:
            arr = arr[..., None]
        return arr.transpose(self.order)


def adjust_hue(img, hue_factor):
    """reference functional adjust_hue: shift hue by hue_factor in [-0.5, 0.5]
    via RGB->HSV->RGB (vectorized numpy)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3)
    a = arr if not chw else arr.transpose(1, 2, 0)
    maxv = 255.0 if a.dtype == np.uint8 else 1.0
    rgb = a.astype(np.float32) / maxv
    import colorsys  # noqa: F401 (documented algorithm; vectorized below)
    mx = rgb.max(-1)
    mn = rgb.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4)) / 6
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    h = (h + hue_factor) % 1.0
    i = np.floor(h * 6)
    f = h * 6 - i
    p, q, t = v * (1 - s), v * (1 - f * s), v * (1 - (1 - f) * s)
    i = (i.astype(np.int32) % 6)[..., None]
    out = np.select(
        [i == 0, i == 1, i == 2, i == 3, i == 4, i == 5],
        [np.stack([v, t, p], -1), np.stack([q, v, p], -1),
         np.stack([p, v, t], -1), np.stack([p, q, v], -1),
         np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    out = out * maxv
    if arr.dtype == np.uint8:
        out = np.round(out)        # truncation would bias the roundtrip -1
    out = out.astype(arr.dtype)
    return out.transpose(2, 0, 1) if chw else out


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        u = np.random.uniform(-self.value, self.value)
        return adjust_hue(img, u)


def _affine_matrix(angle, translate, scale, shear, center):
    import math as _m
    rot = _m.radians(angle)
    sx, sy = (_m.radians(s) for s in shear)
    cx, cy = center
    # torch convention: M = T(center) R S Sh T(-center) + translate
    a = _m.cos(rot - sy) / _m.cos(sy)
    b = -_m.cos(rot - sy) * _m.tan(sx) / _m.cos(sy) - _m.sin(rot)
    c = _m.sin(rot - sy) / _m.cos(sy)
    d = -_m.sin(rot - sy) * _m.tan(sx) / _m.cos(sy) + _m.cos(rot)
    mat = np.array([[a, b, 0.0], [c, d, 0.0]]) * scale
    mat[0, 2] = translate[0] + cx - mat[0, 0] * cx - mat[0, 1] * cy
    mat[1, 2] = translate[1] + cy - mat[1, 0] * cx - mat[1, 1] * cy
    return mat


def affine(img, angle=0.0, translate=(0, 0), scale=1.0, shear=(0.0, 0.0),
           interpolation="nearest", fill=0, center=None):
    """reference functional affine: inverse-warp sampling with the affine
    matrix (nearest/bilinear)."""
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3)
    a = arr if not chw else arr.transpose(1, 2, 0)
    if a.ndim == 2:
        a = a[..., None]
    H, W = a.shape[:2]
    if isinstance(shear, (int, float)):
        shear = (float(shear), 0.0)
    ctr = center if center is not None else ((W - 1) / 2, (H - 1) / 2)
    M = _affine_matrix(angle, translate, scale, shear, ctr)
    Mi = np.linalg.inv(np.vstack([M, [0, 0, 1]]))[:2]
    ys, xs = np.mgrid[0:H, 0:W]
    src = Mi @ np.stack([xs.ravel(), ys.ravel(), np.ones(H * W)])
    sx, sy = src[0].reshape(H, W), src[1].reshape(H, W)
    out = _warp_sample(a, sx, sy, interpolation, fill)
    if arr.ndim == 2:
        out = out[..., 0]
    return out.transpose(2, 0, 1) if chw else out


def _warp_sample(a, sx, sy, interpolation, fill):
    """Inverse-warp gather shared by affine/perspective (HWC array in)."""
    H, W = a.shape[:2]
    if interpolation == "bilinear":
        x0, y0 = np.floor(sx), np.floor(sy)
        out = np.zeros_like(a, np.float32)
        for dx, dy in ((0, 0), (1, 0), (0, 1), (1, 1)):
            xi, yi = x0 + dx, y0 + dy
            wgt = (1 - np.abs(sx - xi)) * (1 - np.abs(sy - yi))
            ok = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
            xi_c = np.clip(xi, 0, W - 1).astype(int)
            yi_c = np.clip(yi, 0, H - 1).astype(int)
            pix = np.where(ok[..., None], a[yi_c, xi_c].astype(np.float32),
                           float(fill))
            out = out + wgt[..., None] * pix
        return out.astype(a.dtype)
    xi = np.round(sx).astype(int)
    yi = np.round(sy).astype(int)
    ok = (xi >= 0) & (xi < W) & (yi >= 0) & (yi < H)
    return np.where(ok[..., None],
                    a[np.clip(yi, 0, H - 1), np.clip(xi, 0, W - 1)],
                    np.asarray(fill, a.dtype))


class RandomAffine(BaseTransform):
    """reference RandomAffine."""

    def __init__(self, degrees, translate=None, scale=None, shear=None,
                 interpolation="nearest", fill=0, center=None, keys=None):
        super().__init__(keys)
        self.degrees = (-degrees, degrees) if isinstance(degrees, (int, float)) \
            else tuple(degrees)
        self.translate, self.scale_rng = translate, scale
        self.shear = shear
        self.interpolation, self.fill, self.center = interpolation, fill, center

    def _apply_image(self, img):
        arr = np.asarray(img)
        H, W = (arr.shape[-2:] if arr.shape[0] in (1, 3) and arr.ndim == 3
                else arr.shape[:2])
        angle = np.random.uniform(*self.degrees)
        tr = (0, 0)
        if self.translate:
            tr = (np.random.uniform(-self.translate[0], self.translate[0]) * W,
                  np.random.uniform(-self.translate[1], self.translate[1]) * H)
        sc = np.random.uniform(*self.scale_rng) if self.scale_rng else 1.0
        sh = (0.0, 0.0)
        if self.shear is not None:
            s = self.shear if isinstance(self.shear, (list, tuple)) \
                else (-self.shear, self.shear)
            sy = np.random.uniform(s[2], s[3]) if len(s) == 4 else 0.0
            sh = (np.random.uniform(s[0], s[1]), sy)
        return affine(img, angle, tr, sc, sh, self.interpolation, self.fill,
                      self.center)


def perspective(img, startpoints, endpoints, interpolation="nearest", fill=0):
    """reference functional perspective: 4-point homography warp."""
    arr = np.asarray(img)
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and arr.shape[-1] not in (1, 3)
    a = arr if not chw else arr.transpose(1, 2, 0)
    if a.ndim == 2:
        a = a[..., None]
    H, W = a.shape[:2]
    # solve homography endpoints -> startpoints (inverse warp)
    A, bvec = [], []
    for (ex, ey), (sx_, sy_) in zip(endpoints, startpoints):
        A.append([ex, ey, 1, 0, 0, 0, -sx_ * ex, -sx_ * ey]); bvec.append(sx_)
        A.append([0, 0, 0, ex, ey, 1, -sy_ * ex, -sy_ * ey]); bvec.append(sy_)
    h = np.linalg.solve(np.asarray(A, np.float64), np.asarray(bvec, np.float64))
    Hm = np.append(h, 1.0).reshape(3, 3)
    ys, xs = np.mgrid[0:H, 0:W]
    pts = Hm @ np.stack([xs.ravel(), ys.ravel(), np.ones(H * W)])
    sx = (pts[0] / pts[2]).reshape(H, W)
    sy = (pts[1] / pts[2]).reshape(H, W)
    out = _warp_sample(a, sx, sy, interpolation, fill)
    if arr.ndim == 2:
        out = out[..., 0]
    return out.transpose(2, 0, 1) if chw else out


class RandomPerspective(BaseTransform):
    def __init__(self, prob=0.5, distortion_scale=0.5,
                 interpolation="nearest", fill=0, keys=None):
        super().__init__(keys)
        self.prob, self.scale = prob, distortion_scale
        self.interpolation, self.fill = interpolation, fill

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img)
        H, W = (arr.shape[-2:] if arr.shape[0] in (1, 3) and arr.ndim == 3
                else arr.shape[:2])
        d = self.scale
        dx = lambda: int(np.random.uniform(0, d * W / 2))
        dy = lambda: int(np.random.uniform(0, d * H / 2))
        start = [(0, 0), (W - 1, 0), (W - 1, H - 1), (0, H - 1)]
        end = [(dx(), dy()), (W - 1 - dx(), dy()),
               (W - 1 - dx(), H - 1 - dy()), (dx(), H - 1 - dy())]
        return perspective(img, start, end, self.interpolation, self.fill)


def _spatial_axes(arr):
    """(h_axis, w_axis) honoring the reference layout contract: np arrays
    are HWC (or HW), Tensors/CHW arrays are [..., H, W]."""
    if arr.ndim == 3 and arr.shape[-1] in (1, 3, 4) and \
            arr.shape[0] not in (1, 3, 4):
        return 0, 1                                    # HWC
    if arr.ndim == 2:
        return 0, 1
    return arr.ndim - 2, arr.ndim - 1                  # CHW / batched CHW


def erase(img, i, j, h, w, v, inplace=False):
    """reference functional erase (Tensor: CHW; np.array: HWC)."""
    if isinstance(img, Tensor):
        out = img.clone() if not inplace else img
        out[..., i:i + h, j:j + w] = v
        return out
    arr = np.asarray(img)
    out = arr if inplace else arr.copy()
    ha, wa = _spatial_axes(out)
    sl = [slice(None)] * out.ndim
    sl[ha] = slice(i, i + h)
    sl[wa] = slice(j, j + w)
    out[tuple(sl)] = v
    return out


class RandomErasing(BaseTransform):
    """reference RandomErasing (CHW arrays/Tensors)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False, keys=None):
        super().__init__(keys)
        self.prob, self.scale, self.ratio = prob, scale, ratio
        self.value, self.inplace = value, inplace

    def _apply_image(self, img):
        if np.random.rand() >= self.prob:
            return img
        arr = np.asarray(img._data) if isinstance(img, Tensor) else np.asarray(img)
        if isinstance(img, Tensor):
            H, W = arr.shape[-2:]
        else:
            ha, wa = _spatial_axes(arr)
            H, W = arr.shape[ha], arr.shape[wa]
        area = H * W
        for _ in range(10):
            a = np.random.uniform(*self.scale) * area
            r = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                         np.log(self.ratio[1])))
            h, w = int(round(np.sqrt(a * r))), int(round(np.sqrt(a / r)))
            if h < H and w < W:
                i = np.random.randint(0, H - h)
                j = np.random.randint(0, W - w)
                return erase(img, i, j, h, w, self.value, self.inplace)
        return img
