"""paddle.vision.transforms (reference: python/paddle/vision/transforms) — numpy-based."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        new_shape = list(arr.shape)
        new_shape[h_ax], new_shape[w_ax] = self.size
        return np.asarray(jax.image.resize(jnp.asarray(arr), new_shape, "linear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            return arr[..., ::-1].copy() if arr.ndim == 3 and arr.shape[0] in (1, 3) \
                else arr[:, ::-1].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if self.padding:
            pads = [(0, 0), (self.padding, self.padding), (self.padding, self.padding)] \
                if chw else [(self.padding, self.padding), (self.padding, self.padding)] + \
                ([(0, 0)] if arr.ndim == 3 else [])
            arr = np.pad(arr, pads, mode="constant")
        h_ax = 1 if chw else 0
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


# ---- round-2 additions: the rest of the reference transform set -------------
def _axes(arr):
    """(h_axis, w_axis, chw?) for a 2D/3D image array."""
    chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
    return (1, 2, True) if chw else (0, 1, False)


def hflip(img):
    arr = np.asarray(img)
    h, w, chw = _axes(arr)
    return np.flip(arr, axis=w).copy()


def vflip(img):
    arr = np.asarray(img)
    h, w, chw = _axes(arr)
    return np.flip(arr, axis=h).copy()


def crop(img, top, left, height, width):
    arr = np.asarray(img)
    h, w, chw = _axes(arr)
    sl = [slice(None)] * arr.ndim
    sl[h] = slice(top, top + height)
    sl[w] = slice(left, left + width)
    return arr[tuple(sl)]


def center_crop(img, output_size):
    size = output_size if isinstance(output_size, (list, tuple)) else \
        (output_size, output_size)
    arr = np.asarray(img)
    h, w, chw = _axes(arr)
    th, tw = size
    top = max(0, (arr.shape[h] - th) // 2)
    left = max(0, (arr.shape[w] - tw) // 2)
    return crop(arr, top, left, th, tw)


def pad(img, padding, fill=0, padding_mode="constant"):
    arr = np.asarray(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:         # (left/right, top/bottom)
        pl = pr = padding[0]
        pt = pb = padding[1]
    else:
        pl, pt, pr, pb = padding
    h, w, chw = _axes(arr)
    pads = [(0, 0)] * arr.ndim
    pads[h] = (pt, pb)
    pads[w] = (pl, pr)
    mode = {"constant": "constant", "edge": "edge", "reflect": "reflect",
            "symmetric": "symmetric"}[padding_mode]
    kw = {"constant_values": fill} if mode == "constant" else {}
    return np.pad(arr, pads, mode=mode, **kw)


def _value_range(img):
    """Max representable value from DTYPE (not data): integers use their
    type's range, floats are 0..1 by convention (PIL/reference)."""
    dt = np.asarray(img).dtype
    return float(np.iinfo(dt).max) if np.issubdtype(dt, np.integer) else 1.0


def _restore_dtype(out, like):
    dt = np.asarray(like).dtype
    return out.astype(dt) if np.issubdtype(dt, np.integer) else out


def adjust_brightness(img, brightness_factor):
    hi = _value_range(img)
    arr = np.asarray(img, np.float32)
    return _restore_dtype(np.clip(arr * brightness_factor, 0, hi), img)


def adjust_contrast(img, contrast_factor):
    hi = _value_range(img)
    arr = np.asarray(img, np.float32)
    mean = arr.mean()
    return _restore_dtype(
        np.clip((arr - mean) * contrast_factor + mean, 0, hi), img)


_GRAY_WGT = np.array([0.299, 0.587, 0.114], np.float32)


def _luminance(arr, chw):
    """Weighted gray over the channel axis; 1-chan passes through, RGBA uses
    the RGB channels."""
    c_ax = 0 if chw else -1
    nc = arr.shape[c_ax]
    if nc == 1:
        return np.take(arr, 0, axis=c_ax)
    rgb = np.take(arr, [0, 1, 2], axis=c_ax) if nc == 4 else arr
    if chw:
        return np.tensordot(_GRAY_WGT, rgb, axes=([0], [0]))
    return rgb @ _GRAY_WGT


def adjust_saturation(img, saturation_factor):
    hi = _value_range(img)
    arr = np.asarray(img, np.float32)
    if arr.ndim == 2:
        return _restore_dtype(arr, img)
    h, w, chw = _axes(arr)
    gray = np.expand_dims(_luminance(arr, chw), 0 if chw else -1)
    return _restore_dtype(
        np.clip(gray + (arr - gray) * saturation_factor, 0, hi), img)


def to_grayscale(img, num_output_channels=1):
    arr = np.asarray(img, np.float32)
    h, w, chw = _axes(arr)
    g = arr if arr.ndim == 2 else _luminance(arr, chw)
    g = _restore_dtype(g, img)
    if num_output_channels == 1:
        return g[None] if chw or arr.ndim == 2 else g[..., None]
    rep = [g] * num_output_channels
    return np.stack(rep, axis=0 if (chw or arr.ndim == 2) else -1)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    from scipy import ndimage
    arr = np.asarray(img, np.float32)
    h, w, chw = _axes(arr)
    order = {"nearest": 0, "bilinear": 1}[interpolation]
    return ndimage.rotate(arr, -angle, axes=(w, h), reshape=expand,
                          order=order, cval=fill)


class CenterCrop:
    def __init__(self, size):
        self.size = size

    def __call__(self, img):
        return center_crop(img, self.size)


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        return vflip(img) if np.random.rand() < self.prob else img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.args = (padding, fill, padding_mode)

    def __call__(self, img):
        return pad(img, *self.args)


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.n)


class BrightnessTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_brightness(img, f)


class ContrastTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_contrast(img, f)


class SaturationTransform:
    def __init__(self, value):
        self.value = value

    def __call__(self, img):
        if self.value == 0:
            return img
        f = 1 + np.random.uniform(-self.value, self.value)
        return adjust_saturation(img, f)


class ColorJitter:
    """brightness/contrast/saturation jitter (reference transforms.ColorJitter;
    hue omitted: needs HSV round-trip the reference does via PIL)."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation)]

    def __call__(self, img):
        for t in np.random.permutation(self.ts):
            img = t(img)
        return img


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        self.degrees = (-degrees, degrees) if np.isscalar(degrees) \
            else tuple(degrees)
        self.kw = dict(interpolation=interpolation, expand=expand,
                       center=center, fill=fill)

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, **self.kw)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.scale, self.ratio = scale, ratio
        self.interpolation = interpolation

    def __call__(self, img):
        arr = np.asarray(img)
        h, w, chw = _axes(arr)
        H, W = arr.shape[h], arr.shape[w]
        area = H * W
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if cw <= W and ch <= H:
                top = np.random.randint(0, H - ch + 1)
                left = np.random.randint(0, W - cw + 1)
                patch = crop(arr, top, left, ch, cw)
                return Resize(self.size, self.interpolation)(patch)
        return Resize(self.size, self.interpolation)(center_crop(
            arr, (min(H, W), min(H, W))))
