"""paddle.vision.transforms (reference: python/paddle/vision/transforms) — numpy-based."""
from __future__ import annotations

import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.data_format = data_format

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        return (img - self.mean.reshape(shape)) / self.std.reshape(shape)


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.max() > 1.5:
            arr = arr / 255.0
        if arr.ndim == 2:
            arr = arr[None] if self.data_format == "CHW" else arr[..., None]
        elif self.data_format == "CHW" and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        return arr


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        h_ax, w_ax = (1, 2) if chw else (0, 1)
        new_shape = list(arr.shape)
        new_shape[h_ax], new_shape[w_ax] = self.size
        return np.asarray(jax.image.resize(jnp.asarray(arr), new_shape, "linear"))


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            arr = np.asarray(img)
            return arr[..., ::-1].copy() if arr.ndim == 3 and arr.shape[0] in (1, 3) \
                else arr[:, ::-1].copy()
        return img


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = size if isinstance(size, (list, tuple)) else (size, size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if self.padding:
            pads = [(0, 0), (self.padding, self.padding), (self.padding, self.padding)] \
                if chw else [(self.padding, self.padding), (self.padding, self.padding)] + \
                ([(0, 0)] if arr.ndim == 3 else [])
            arr = np.pad(arr, pads, mode="constant")
        h_ax = 1 if chw else 0
        h, w = arr.shape[h_ax], arr.shape[h_ax + 1]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        if chw:
            return arr[:, i:i + th, j:j + tw]
        return arr[i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
