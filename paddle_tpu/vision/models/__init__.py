"""Vision model zoo (reference: python/paddle/vision/models) — LeNet + ResNet
family (the conv-heavy benchmark path, BASELINE config #4)."""
from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear, Flatten, Dropout
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D
from ...nn.layer.pooling import MaxPool2D, AdaptiveAvgPool2D
from ...nn.layer.activation import ReLU
from ...nn.layer.container import Sequential
from ... import nn


class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(), MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1), ReLU(), MaxPool2D(2, 2))
        self.fc = Sequential(Linear(400, 120), Linear(120, 84),
                             Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = x.flatten(1)
        return self.fc(x)


class BasicBlock(Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None):
        super().__init__()
        self.conv1 = Conv2D(inplanes, planes, 3, stride=stride, padding=1,
                            bias_attr=False)
        self.bn1 = BatchNorm2D(planes)
        self.relu = ReLU()
        self.conv2 = Conv2D(planes, planes, 3, padding=1, bias_attr=False)
        self.bn2 = BatchNorm2D(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64):
        super().__init__()
        width = int(planes * (base_width / 64.0)) * groups
        self.conv1 = Conv2D(inplanes, width, 1, bias_attr=False)
        self.bn1 = BatchNorm2D(width)
        self.conv2 = Conv2D(width, width, 3, stride=stride, padding=1,
                            groups=groups, bias_attr=False)
        self.bn2 = BatchNorm2D(width)
        self.conv3 = Conv2D(width, planes * 4, 1, bias_attr=False)
        self.bn3 = BatchNorm2D(planes * 4)
        self.relu = ReLU()
        self.downsample = downsample

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(Layer):
    """groups/width follow the torchvision convention: ResNeXt sets
    (groups=32, width=4), wide ResNet sets width=128 (reference resnet.py
    resnext50_32x4d / wide_resnet50_2 factories)."""

    def __init__(self, block, depth=50, width=64, num_classes=1000,
                 with_pool=True, groups=1):
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        if block is BasicBlock and (groups != 1 or width != 64):
            raise ValueError(
                "BasicBlock only supports groups=1 and width=64 "
                "(ResNeXt/wide variants need the bottleneck block)")
        self.groups = groups
        self.base_width = width
        self.inplanes = 64
        self.conv1 = Conv2D(3, 64, 7, stride=2, padding=3, bias_attr=False)
        self.bn1 = BatchNorm2D(64)
        self.relu = ReLU()
        self.maxpool = MaxPool2D(3, stride=2, padding=1)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        self.avgpool = AdaptiveAvgPool2D((1, 1))
        self.fc = Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1):
        downsample = None
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = Sequential(
                Conv2D(self.inplanes, planes * block.expansion, 1, stride=stride,
                       bias_attr=False),
                BatchNorm2D(planes * block.expansion))
        kw = {}
        if block is BottleneckBlock and (self.groups > 1 or
                                         self.base_width != 64):
            kw = {"groups": self.groups, "base_width": self.base_width}
        layers = [block(self.inplanes, planes, stride, downsample, **kw)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes, **kw))
        return Sequential(*layers)

    def forward(self, x):
        x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
        x = self.layer4(self.layer3(self.layer2(self.layer1(x))))
        x = self.avgpool(x).flatten(1)
        return self.fc(x)


def resnet18(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 18, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return ResNet(BasicBlock, 34, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 152, **kwargs)


def resnext50_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=4, groups=32, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=4, groups=32, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=4, groups=64, **kwargs)


def wide_resnet50_2(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 50, width=128, **kwargs)


def wide_resnet101_2(pretrained=False, **kwargs):
    return ResNet(BottleneckBlock, 101, width=128, **kwargs)


from .extra import (VGG, vgg16, vgg19, MobileNetV2, mobilenet_v2,
                    AlexNet, alexnet)  # noqa: F401,E402
from .extra2 import (DenseNet, densenet121, densenet161, densenet169,  # noqa: F401,E402
                     densenet201, SqueezeNet, squeezenet1_0, squeezenet1_1,
                     ShuffleNetV2, shufflenet_v2_x0_25, shufflenet_v2_x0_5,
                     shufflenet_v2_x1_0, shufflenet_v2_x1_5,
                     shufflenet_v2_x2_0, shufflenet_v2_swish,
                     MobileNetV1, mobilenet_v1, MobileNetV3,
                     mobilenet_v3_large, mobilenet_v3_small,
                     GoogLeNet, googlenet, InceptionV3, inception_v3)
