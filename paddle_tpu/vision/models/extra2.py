"""Vision zoo, part 3 (reference: python/paddle/vision/models/{densenet,
squeezenet,shufflenetv2,mobilenetv1,mobilenetv3,googlenet,inceptionv3}.py).

Standard published architectures, written against paddle_tpu.nn. NCHW.
"""
from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear, Dropout
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D
from ...nn.layer.pooling import AdaptiveAvgPool2D
from ...nn import Sequential, ReLU, MaxPool2D, AvgPool2D, Hardswish
from ... import ops
from ...nn import functional as F
from .extra import _make_divisible


class ConvBNLayer(Layer):
    """conv -> BN -> optional activation (the zoo's shared stem block)."""

    def __init__(self, cin, cout, k, stride=1, padding=0, groups=1, act="relu"):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=padding,
                           groups=groups, bias_attr=False)
        self.bn = BatchNorm2D(cout)
        self.act = act

    def forward(self, x):
        x = self.bn(self.conv(x))
        if self.act is not None:
            x = getattr(F, self.act)(x)   # relu / hardswish / swish / ...
        return x


# ---- DenseNet (densenet.py) --------------------------------------------------
class _DenseLayer(Layer):
    def __init__(self, cin, growth, bn_size=4, drop=0.0):
        super().__init__()
        self.bn1 = BatchNorm2D(cin)
        self.conv1 = Conv2D(cin, bn_size * growth, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth)
        self.conv2 = Conv2D(bn_size * growth, growth, 3, padding=1,
                            bias_attr=False)
        self.drop = drop

    def forward(self, x):
        y = self.conv1(F.relu(self.bn1(x)))
        y = self.conv2(F.relu(self.bn2(y)))
        if self.drop:
            y = F.dropout(y, p=self.drop, training=self.training)
        return ops.concat([x, y], axis=1)


class _Transition(Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.bn = BatchNorm2D(cin)
        self.conv = Conv2D(cin, cout, 1, bias_attr=False)
        self.pool = AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(F.relu(self.bn(x))))


class DenseNet(Layer):
    """reference densenet.py; canonical growth-rate dense blocks."""

    def __init__(self, layers=121, growth_rate=None, num_init_features=None,
                 bn_size=4, dropout=0.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfgs = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
                169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
                264: (6, 12, 64, 48)}
        # 161 is the wide variant; None means "canonical for this depth" so
        # explicit caller overrides are honored
        if growth_rate is None:
            growth_rate = 48 if layers == 161 else 32
        if num_init_features is None:
            num_init_features = 96 if layers == 161 else 64
        block_cfg = cfgs[layers]
        self.stem = Sequential(
            Conv2D(3, num_init_features, 7, stride=2, padding=3,
                   bias_attr=False),
            BatchNorm2D(num_init_features), ReLU(),
            MaxPool2D(3, stride=2, padding=1))
        c = num_init_features
        blocks = []
        for i, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(c, growth_rate, bn_size, dropout))
                c += growth_rate
            if i != len(block_cfg) - 1:
                blocks.append(_Transition(c, c // 2))
                c //= 2
        self.blocks = Sequential(*blocks)
        self.bn_final = BatchNorm2D(c)
        self.with_pool = with_pool
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = F.relu(self.bn_final(self.blocks(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(1))
        return x


def densenet121(pretrained=False, **kw):
    return DenseNet(121, **kw)


def densenet161(pretrained=False, **kw):
    return DenseNet(161, **kw)


def densenet169(pretrained=False, **kw):
    return DenseNet(169, **kw)


def densenet201(pretrained=False, **kw):
    return DenseNet(201, **kw)


# ---- SqueezeNet (squeezenet.py) ---------------------------------------------
class _Fire(Layer):
    def __init__(self, cin, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Conv2D(cin, squeeze, 1)
        self.e1 = Conv2D(squeeze, e1, 1)
        self.e3 = Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = F.relu(self.squeeze(x))
        return ops.concat([F.relu(self.e1(s)), F.relu(self.e3(s))], axis=1)


class SqueezeNet(Layer):
    """reference squeezenet.py (versions '1.0' / '1.1')."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        v = str(version)
        if v == "1.0":
            self.features = Sequential(
                Conv2D(3, 96, 7, stride=2), ReLU(),
                MaxPool2D(3, stride=2),
                _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                _Fire(128, 32, 128, 128), MaxPool2D(3, stride=2),
                _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256))
        elif v == "1.1":
            self.features = Sequential(
                Conv2D(3, 64, 3, stride=2), ReLU(),
                MaxPool2D(3, stride=2),
                _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                MaxPool2D(3, stride=2),
                _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                MaxPool2D(3, stride=2),
                _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256))
        else:
            raise ValueError(f"unsupported SqueezeNet version {version!r}")
        self.drop = Dropout(0.5)
        self.final_conv = Conv2D(512, num_classes, 1)
        self.with_pool = with_pool
        self.pool = AdaptiveAvgPool2D(1)

    def forward(self, x):
        x = F.relu(self.final_conv(self.drop(self.features(x))))
        if self.with_pool:
            x = self.pool(x)
        return x.flatten(1)


def squeezenet1_0(pretrained=False, **kw):
    return SqueezeNet("1.0", **kw)


def squeezenet1_1(pretrained=False, **kw):
    return SqueezeNet("1.1", **kw)


# ---- ShuffleNetV2 (shufflenetv2.py) -----------------------------------------
def _channel_shuffle(x, groups):
    b, c, h, w = x.shape
    x = x.reshape([b, groups, c // groups, h, w])
    x = x.transpose([0, 2, 1, 3, 4])
    return x.reshape([b, c, h, w])


class _ShuffleUnit(Layer):
    def __init__(self, cin, cout, stride, act="relu"):
        super().__init__()
        self.stride = stride
        branch = cout // 2
        if stride == 2:
            self.b1 = Sequential(
                ConvBNLayer(cin, cin, 3, stride=2, padding=1, groups=cin,
                            act=None),
                ConvBNLayer(cin, branch, 1, act=act))
            c2_in = cin
        else:
            self.b1 = None
            c2_in = cin // 2
        self.b2 = Sequential(
            ConvBNLayer(c2_in, branch, 1, act=act),
            ConvBNLayer(branch, branch, 3, stride=stride, padding=1,
                        groups=branch, act=None),
            ConvBNLayer(branch, branch, 1, act=act))

    def forward(self, x):
        if self.stride == 2:
            out = ops.concat([self.b1(x), self.b2(x)], axis=1)
        else:
            half = x.shape[1] // 2
            x1, x2 = x[:, :half], x[:, half:]
            out = ops.concat([x1, self.b2(x2)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(Layer):
    """reference shufflenetv2.py (scale 0.25-2.0 + swish variant)."""

    _CHANNELS = {0.25: (24, 24, 48, 96, 512), 0.33: (24, 32, 64, 128, 512),
                 0.5: (24, 48, 96, 192, 1024), 1.0: (24, 116, 232, 464, 1024),
                 1.5: (24, 176, 352, 704, 1024), 2.0: (24, 244, 488, 976, 2048)}

    def __init__(self, scale=1.0, act="relu", num_classes=1000, with_pool=True):
        super().__init__()
        chans = self._CHANNELS[float(scale)]
        repeats = (4, 8, 4)
        self.stem = Sequential(
            ConvBNLayer(3, chans[0], 3, stride=2, padding=1, act=act),
            MaxPool2D(3, stride=2, padding=1))
        units = []
        cin = chans[0]
        for stage, n in enumerate(repeats):
            cout = chans[stage + 1]
            units.append(_ShuffleUnit(cin, cout, stride=2, act=act))
            for _ in range(n - 1):
                units.append(_ShuffleUnit(cout, cout, stride=1, act=act))
            cin = cout
        self.units = Sequential(*units)
        self.conv_last = ConvBNLayer(cin, chans[4], 1, act=act)
        self.with_pool = with_pool
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(chans[4], num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.conv_last(self.units(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(1))
        return x


def shufflenet_v2_x0_25(pretrained=False, **kw):
    return ShuffleNetV2(0.25, **kw)


def shufflenet_v2_x0_5(pretrained=False, **kw):
    return ShuffleNetV2(0.5, **kw)


def shufflenet_v2_x1_0(pretrained=False, **kw):
    return ShuffleNetV2(1.0, **kw)


def shufflenet_v2_x1_5(pretrained=False, **kw):
    return ShuffleNetV2(1.5, **kw)


def shufflenet_v2_x2_0(pretrained=False, **kw):
    return ShuffleNetV2(2.0, **kw)


def shufflenet_v2_swish(pretrained=False, **kw):
    return ShuffleNetV2(1.0, act="swish", **kw)


# ---- MobileNetV1 (mobilenetv1.py) -------------------------------------------
class _DepthwiseSeparable(Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.dw = ConvBNLayer(cin, cin, 3, stride=stride, padding=1,
                              groups=cin)
        self.pw = ConvBNLayer(cin, cout, 1)

    def forward(self, x):
        return self.pw(self.dw(x))


class MobileNetV1(Layer):
    """reference mobilenetv1.py."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        def c(v):
            return max(8, int(v * scale))
        self.stem = ConvBNLayer(3, c(32), 3, stride=2, padding=1)
        cfg = [(32, 64, 1), (64, 128, 2), (128, 128, 1), (128, 256, 2),
               (256, 256, 1), (256, 512, 2)] + [(512, 512, 1)] * 5 + \
              [(512, 1024, 2), (1024, 1024, 1)]
        self.blocks = Sequential(*[
            _DepthwiseSeparable(c(i), c(o), s) for i, o, s in cfg])
        self.with_pool = with_pool
        self.pool = AdaptiveAvgPool2D(1)
        self.fc = Linear(c(1024), num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(x.flatten(1))
        return x


def mobilenet_v1(pretrained=False, scale=1.0, **kw):
    return MobileNetV1(scale=scale, **kw)


# ---- MobileNetV3 (mobilenetv3.py) -------------------------------------------
class _SqueezeExcite(Layer):
    def __init__(self, c, r=4):
        super().__init__()
        mid = _make_divisible(c // r)
        self.pool = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(c, mid, 1)
        self.fc2 = Conv2D(mid, c, 1)

    def forward(self, x):
        s = F.relu(self.fc1(self.pool(x)))
        s = F.hardsigmoid(self.fc2(s))
        return x * s


class _MBV3Block(Layer):
    def __init__(self, cin, exp, cout, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if exp != cin:
            layers.append(ConvBNLayer(cin, exp, 1, act=act))
        layers.append(ConvBNLayer(exp, exp, k, stride=stride,
                                  padding=k // 2, groups=exp, act=act))
        if use_se:
            layers.append(_SqueezeExcite(exp))
        layers.append(ConvBNLayer(exp, cout, 1, act=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        y = self.block(x)
        return x + y if self.use_res else y


# (k, exp, out, SE, act, stride) per published config
_V3_LARGE = [
    (3, 16, 16, False, "relu", 1), (3, 64, 24, False, "relu", 2),
    (3, 72, 24, False, "relu", 1), (5, 72, 40, True, "relu", 2),
    (5, 120, 40, True, "relu", 1), (5, 120, 40, True, "relu", 1),
    (3, 240, 80, False, "hardswish", 2), (3, 200, 80, False, "hardswish", 1),
    (3, 184, 80, False, "hardswish", 1), (3, 184, 80, False, "hardswish", 1),
    (3, 480, 112, True, "hardswish", 1), (3, 672, 112, True, "hardswish", 1),
    (5, 672, 160, True, "hardswish", 2), (5, 960, 160, True, "hardswish", 1),
    (5, 960, 160, True, "hardswish", 1)]
_V3_SMALL = [
    (3, 16, 16, True, "relu", 2), (3, 72, 24, False, "relu", 2),
    (3, 88, 24, False, "relu", 1), (5, 96, 40, True, "hardswish", 2),
    (5, 240, 40, True, "hardswish", 1), (5, 240, 40, True, "hardswish", 1),
    (5, 120, 48, True, "hardswish", 1), (5, 144, 48, True, "hardswish", 1),
    (5, 288, 96, True, "hardswish", 2), (5, 576, 96, True, "hardswish", 1),
    (5, 576, 96, True, "hardswish", 1)]


class MobileNetV3(Layer):
    """reference mobilenetv3.py (small/large)."""

    def __init__(self, config="large", scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        cfg = _V3_LARGE if config == "large" else _V3_SMALL
        last_exp = 960 if config == "large" else 576
        last_c = 1280 if config == "large" else 1024

        def c(v):
            return _make_divisible(v * scale)
        self.stem = ConvBNLayer(3, c(16), 3, stride=2, padding=1,
                                act="hardswish")
        blocks, cin = [], c(16)
        for k, exp, cout, se, act, s in cfg:
            blocks.append(_MBV3Block(cin, c(exp), c(cout), k, s, se, act))
            cin = c(cout)
        self.blocks = Sequential(*blocks)
        self.conv_last = ConvBNLayer(cin, c(last_exp), 1, act="hardswish")
        self.with_pool = with_pool
        self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(c(last_exp), last_c), Hardswish(), Dropout(0.2),
                Linear(last_c, num_classes))
        else:
            self.classifier = None

    def forward(self, x):
        x = self.conv_last(self.blocks(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.classifier is not None:
            x = self.classifier(x.flatten(1))
        return x


def mobilenet_v3_large(pretrained=False, scale=1.0, **kw):
    return MobileNetV3("large", scale=scale, **kw)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kw):
    return MobileNetV3("small", scale=scale, **kw)


# ---- GoogLeNet / Inception v1 (googlenet.py) --------------------------------
class _Inception(Layer):
    def __init__(self, cin, c1, c3r, c3, c5r, c5, proj):
        super().__init__()
        self.b1 = ConvBNLayer(cin, c1, 1)
        self.b2 = Sequential(ConvBNLayer(cin, c3r, 1),
                             ConvBNLayer(c3r, c3, 3, padding=1))
        self.b3 = Sequential(ConvBNLayer(cin, c5r, 1),
                             ConvBNLayer(c5r, c5, 3, padding=1))
        self.b4 = Sequential(MaxPool2D(3, stride=1, padding=1),
                             ConvBNLayer(cin, proj, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b2(x), self.b3(x), self.b4(x)],
                          axis=1)


class _AuxHead(Layer):
    def __init__(self, cin, num_classes):
        super().__init__()
        self.pool = AdaptiveAvgPool2D(4)
        self.conv = ConvBNLayer(cin, 128, 1)
        self.fc1 = Linear(128 * 16, 1024)
        self.drop = Dropout(0.7)
        self.fc2 = Linear(1024, num_classes)

    def forward(self, x):
        x = self.conv(self.pool(x)).flatten(1)
        x = self.drop(F.relu(self.fc1(x)))
        return self.fc2(x)


class GoogLeNet(Layer):
    """reference googlenet.py — returns (main, aux1, aux2) like the
    reference (aux heads train-time only in typical recipes)."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            ConvBNLayer(3, 64, 7, stride=2, padding=3),
            MaxPool2D(3, stride=2, padding=1),
            ConvBNLayer(64, 64, 1),
            ConvBNLayer(64, 192, 3, padding=1),
            MaxPool2D(3, stride=2, padding=1))
        self.i3a = _Inception(192, 64, 96, 128, 16, 32, 32)
        self.i3b = _Inception(256, 128, 128, 192, 32, 96, 64)
        self.pool3 = MaxPool2D(3, stride=2, padding=1)
        self.i4a = _Inception(480, 192, 96, 208, 16, 48, 64)
        self.i4b = _Inception(512, 160, 112, 224, 24, 64, 64)
        self.i4c = _Inception(512, 128, 128, 256, 24, 64, 64)
        self.i4d = _Inception(512, 112, 144, 288, 32, 64, 64)
        self.i4e = _Inception(528, 256, 160, 320, 32, 128, 128)
        self.pool4 = MaxPool2D(3, stride=2, padding=1)
        self.i5a = _Inception(832, 256, 160, 320, 32, 128, 128)
        self.i5b = _Inception(832, 384, 192, 384, 48, 128, 128)
        self.aux1 = _AuxHead(512, num_classes)
        self.aux2 = _AuxHead(528, num_classes)
        self.with_pool = with_pool
        self.pool = AdaptiveAvgPool2D(1)
        self.drop = Dropout(0.4)
        self.fc = Linear(1024, num_classes)

    def forward(self, x):
        x = self.pool3(self.i3b(self.i3a(self.stem(x))))
        x = self.i4a(x)
        a1 = self.aux1(x) if self.training else None
        x = self.i4d(self.i4c(self.i4b(x)))
        a2 = self.aux2(x) if self.training else None
        x = self.i5b(self.i5a(self.pool4(self.i4e(x))))
        if self.with_pool:
            x = self.pool(x)
        out = self.fc(self.drop(x.flatten(1)))
        if self.training:
            return out, a1, a2
        return out


def googlenet(pretrained=False, **kw):
    return GoogLeNet(**kw)


# ---- InceptionV3 (inceptionv3.py) -------------------------------------------
class _IncA(Layer):
    def __init__(self, cin, pool_feat):
        super().__init__()
        self.b1 = ConvBNLayer(cin, 64, 1)
        self.b5 = Sequential(ConvBNLayer(cin, 48, 1),
                             ConvBNLayer(48, 64, 5, padding=2))
        self.b3 = Sequential(ConvBNLayer(cin, 64, 1),
                             ConvBNLayer(64, 96, 3, padding=1),
                             ConvBNLayer(96, 96, 3, padding=1))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             ConvBNLayer(cin, pool_feat, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b5(x), self.b3(x), self.bp(x)],
                          axis=1)


class _RedA(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = ConvBNLayer(cin, 384, 3, stride=2)
        self.b3d = Sequential(ConvBNLayer(cin, 64, 1),
                              ConvBNLayer(64, 96, 3, padding=1),
                              ConvBNLayer(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _IncB(Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = ConvBNLayer(cin, 192, 1)
        self.b7 = Sequential(
            ConvBNLayer(cin, c7, 1),
            ConvBNLayer(c7, c7, (1, 7), padding=(0, 3)),
            ConvBNLayer(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(
            ConvBNLayer(cin, c7, 1),
            ConvBNLayer(c7, c7, (7, 1), padding=(3, 0)),
            ConvBNLayer(c7, c7, (1, 7), padding=(0, 3)),
            ConvBNLayer(c7, c7, (7, 1), padding=(3, 0)),
            ConvBNLayer(c7, 192, (1, 7), padding=(0, 3)))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             ConvBNLayer(cin, 192, 1))

    def forward(self, x):
        return ops.concat([self.b1(x), self.b7(x), self.b7d(x), self.bp(x)],
                          axis=1)


class _RedB(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = Sequential(ConvBNLayer(cin, 192, 1),
                             ConvBNLayer(192, 320, 3, stride=2))
        self.b7 = Sequential(
            ConvBNLayer(cin, 192, 1),
            ConvBNLayer(192, 192, (1, 7), padding=(0, 3)),
            ConvBNLayer(192, 192, (7, 1), padding=(3, 0)),
            ConvBNLayer(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return ops.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncC(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = ConvBNLayer(cin, 320, 1)
        self.b3r = ConvBNLayer(cin, 384, 1)
        self.b3a = ConvBNLayer(384, 384, (1, 3), padding=(0, 1))
        self.b3b = ConvBNLayer(384, 384, (3, 1), padding=(1, 0))
        self.bdr = Sequential(ConvBNLayer(cin, 448, 1),
                              ConvBNLayer(448, 384, 3, padding=1))
        self.bda = ConvBNLayer(384, 384, (1, 3), padding=(0, 1))
        self.bdb = ConvBNLayer(384, 384, (3, 1), padding=(1, 0))
        self.bp = Sequential(AvgPool2D(3, stride=1, padding=1),
                             ConvBNLayer(cin, 192, 1))

    def forward(self, x):
        b3 = self.b3r(x)
        bd = self.bdr(x)
        return ops.concat(
            [self.b1(x), self.b3a(b3), self.b3b(b3),
             self.bda(bd), self.bdb(bd), self.bp(x)], axis=1)


class InceptionV3(Layer):
    """reference inceptionv3.py. The auxiliary classifier is NOT implemented
    (canonical aux-free variant; param count 23,834,568 @ 1000 classes) —
    training recipes that rely on the aux loss need to add their own head."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.stem = Sequential(
            ConvBNLayer(3, 32, 3, stride=2),
            ConvBNLayer(32, 32, 3),
            ConvBNLayer(32, 64, 3, padding=1),
            MaxPool2D(3, stride=2),
            ConvBNLayer(64, 80, 1),
            ConvBNLayer(80, 192, 3),
            MaxPool2D(3, stride=2))
        self.blocks = Sequential(
            _IncA(192, 32), _IncA(256, 64), _IncA(288, 64),
            _RedA(288),
            _IncB(768, 128), _IncB(768, 160), _IncB(768, 160), _IncB(768, 192),
            _RedB(768),
            _IncC(1280), _IncC(2048))
        self.with_pool = with_pool
        self.pool = AdaptiveAvgPool2D(1)
        self.drop = Dropout(0.5)
        self.fc = Linear(2048, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.blocks(self.stem(x))
        if self.with_pool:
            x = self.pool(x)
        if self.fc is not None:
            x = self.fc(self.drop(x.flatten(1)))
        return x


def inception_v3(pretrained=False, **kw):
    return InceptionV3(**kw)
