"""VGG / MobileNetV2 / AlexNet (reference: python/paddle/vision/models/
vgg.py, mobilenetv2.py, alexnet.py — same topologies on the paddle_tpu.nn
stack; conv stacks fuse under jit and land on the MXU as implicit GEMMs)."""
from __future__ import annotations

from ...nn.layer.layers import Layer
from ...nn.layer.common import Linear, Dropout
from ...nn.layer.conv import Conv2D
from ...nn.layer.norm import BatchNorm2D
from ...nn.layer.pooling import MaxPool2D, AdaptiveAvgPool2D
from ...nn.layer.container import Sequential
from ...nn.layer.activation import ReLU, ReLU6
from ... import ops

__all__ = ["VGG", "vgg16", "vgg19", "MobileNetV2", "mobilenet_v2",
           "AlexNet", "alexnet"]

_VGG_CFGS = {
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
          512, 512, 512, "M", 512, 512, 512, "M"],            # vgg16
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],  # vgg19
}


def _vgg_features(cfg, batch_norm=False):
    layers, c_in = [], 3
    for v in cfg:
        if v == "M":
            layers.append(MaxPool2D(kernel_size=2, stride=2))
        else:
            layers.append(Conv2D(c_in, v, 3, padding=1))
            if batch_norm:
                layers.append(BatchNorm2D(v))
            layers.append(ReLU())
            c_in = v
    return Sequential(*layers)


class VGG(Layer):
    """reference: vision/models/vgg.py VGG."""

    def __init__(self, features, num_classes=1000, with_pool=True):
        super().__init__()
        self.features = features
        self.with_pool = with_pool
        if with_pool:
            self.avgpool = AdaptiveAvgPool2D((7, 7))
        if num_classes > 0:
            self.classifier = Sequential(
                Linear(512 * 7 * 7, 4096), ReLU(), Dropout(),
                Linear(4096, 4096), ReLU(), Dropout(),
                Linear(4096, num_classes))
        self.num_classes = num_classes

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = ops.flatten(x, 1)
            x = self.classifier(x)
        return x


def vgg16(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_vgg_features(_VGG_CFGS["D"], batch_norm), **kwargs)


def vgg19(pretrained=False, batch_norm=False, **kwargs):
    return VGG(_vgg_features(_VGG_CFGS["E"], batch_norm), **kwargs)


class _InvertedResidual(Layer):
    def __init__(self, inp, oup, stride, expand_ratio):
        super().__init__()
        self.stride = stride
        hidden = int(round(inp * expand_ratio))
        self.use_res = stride == 1 and inp == oup
        layers = []
        if expand_ratio != 1:
            layers += [Conv2D(inp, hidden, 1, bias_attr=False),
                       BatchNorm2D(hidden), ReLU6()]
        layers += [
            Conv2D(hidden, hidden, 3, stride=stride, padding=1,
                   groups=hidden, bias_attr=False),
            BatchNorm2D(hidden), ReLU6(),
            Conv2D(hidden, oup, 1, bias_attr=False), BatchNorm2D(oup),
        ]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


def _make_divisible(v, divisor=8, min_value=None):
    """reference: mobilenetv2.py _make_divisible — round channel counts to
    multiples of 8, never dropping more than 10%."""
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class MobileNetV2(Layer):
    """reference: vision/models/mobilenetv2.py MobileNetV2."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        inp = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        feats = [Conv2D(3, inp, 3, stride=2, padding=1, bias_attr=False),
                 BatchNorm2D(inp), ReLU6()]
        for t, c, n, s in cfg:
            out_c = _make_divisible(c * scale)
            for i in range(n):
                feats.append(_InvertedResidual(inp, out_c,
                                               s if i == 0 else 1, t))
                inp = out_c
        feats += [Conv2D(inp, last, 1, bias_attr=False), BatchNorm2D(last),
                  ReLU6()]
        self.features = Sequential(*feats)
        self.with_pool = with_pool
        self.num_classes = num_classes
        if with_pool:
            self.pool2d_avg = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2), Linear(last,
                                                              num_classes))
        self._last = last

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool2d_avg(x)
        if self.num_classes > 0:
            x = ops.reshape(x, [x.shape[0], self._last])
            x = self.classifier(x)
        return x


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    return MobileNetV2(scale=scale, **kwargs)


class AlexNet(Layer):
    """reference: vision/models/alexnet.py."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(kernel_size=3, stride=2),
            Conv2D(64, 192, 5, padding=2), ReLU(),
            MaxPool2D(kernel_size=3, stride=2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(),
            MaxPool2D(kernel_size=3, stride=2))
        self.avgpool = AdaptiveAvgPool2D((6, 6))
        self.classifier = Sequential(
            Dropout(), Linear(256 * 6 * 6, 4096), ReLU(),
            Dropout(), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.features(x)
        x = self.avgpool(x)
        x = ops.flatten(x, 1)
        return self.classifier(x)


def alexnet(pretrained=False, **kwargs):
    return AlexNet(**kwargs)
