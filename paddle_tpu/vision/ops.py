"""paddle.vision.ops (reference: python/paddle/vision/ops.py — nms:1934,
roi_align:1705, roi_pool:1610, box coders etc.).

TPU-native: roi_align/roi_pool are dense gather+interpolate jnp math (jit
fusable); nms's data-dependent loop runs as a lax.while_loop over a fixed
[N] mask — static shapes, no host sync."""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import apply_op, unwrap

__all__ = ["nms", "roi_align", "roi_pool", "box_area", "box_iou"]


def box_area(boxes):
    def f(b):
        return (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return apply_op("box_area", f, boxes)


def _iou_matrix(a, b):
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def box_iou(boxes1, boxes2):
    return apply_op("box_iou", _iou_matrix, boxes1, boxes2)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """reference: vision/ops.py nms:1934 — returns kept indices sorted by
    score. Greedy suppression as a lax.while_loop over a static [N] mask."""
    b = unwrap(boxes)
    n = b.shape[0]
    s = unwrap(scores) if scores is not None else jnp.arange(
        n, 0, -1, dtype=jnp.float32)

    def f(bx, sc, *cat):
        iou = _iou_matrix(bx, bx)
        if cat:  # category-aware: only same-category boxes suppress
            same = cat[0][:, None] == cat[0][None, :]
            iou = jnp.where(same, iou, 0.0)
        order = jnp.argsort(-sc)

        def body(state):
            i, alive, keep = state
            idx = order[i]
            is_alive = alive[idx]
            keep = keep.at[idx].set(is_alive)
            sup = (iou[idx] > iou_threshold) & is_alive
            alive = alive & ~sup
            alive = alive.at[idx].set(False)
            return i + 1, alive, keep

        def cond(state):
            return state[0] < n

        _, _, keep = jax.lax.while_loop(
            cond, body, (0, jnp.ones((n,), bool), jnp.zeros((n,), bool)))
        kept_sorted = order[keep[order]]
        return kept_sorted

    args = (Tensor(b), Tensor(s))
    if category_idxs is not None:
        args += (Tensor(jnp.asarray(unwrap(category_idxs))),)
    out = apply_op("nms", f, *args)
    if top_k is not None:
        out = out[:top_k]
    return out


def _bilinear(feat, y, x):
    """feat [C, H, W]; y/x [...] float coords -> [C, ...]. Coordinates are
    CLAMPED into the image before weights are computed (reference roi_align
    border behavior) — unclamped coords would extrapolate with negative
    weights at the borders."""
    H, W = feat.shape[-2:]
    y = jnp.clip(y, 0.0, H - 1.0)
    x = jnp.clip(x, 0.0, W - 1.0)
    y0 = jnp.clip(jnp.floor(y), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    ly, lx = y - y0, x - x0
    y0i, y1i, x0i, x1i = (v.astype(jnp.int32) for v in (y0, y1, x0, x1))
    v00 = feat[:, y0i, x0i]
    v01 = feat[:, y0i, x1i]
    v10 = feat[:, y1i, x0i]
    v11 = feat[:, y1i, x1i]
    return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
            + v10 * ly * (1 - lx) + v11 * ly * lx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """reference: vision/ops.py roi_align:1705. x [N, C, H, W]; boxes
    [R, 4] in (x1, y1, x2, y2); boxes_num [N] rois per image."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size
    ratio = 2 if sampling_ratio <= 0 else sampling_ratio

    def f(xa, ba, bn):
        # roi r belongs to the image whose cumulative count first exceeds r
        img_of = jnp.searchsorted(jnp.cumsum(bn),
                                  jnp.arange(ba.shape[0]), side="right")
        off = 0.5 if aligned else 0.0
        sb = ba * spatial_scale - off

        def one(roi, img):
            x1, y1, x2, y2 = roi
            rw = jnp.maximum(x2 - x1, 1e-3)
            rh = jnp.maximum(y2 - y1, 1e-3)
            bin_h, bin_w = rh / ph, rw / pw
            gy = (jnp.arange(ph)[:, None] * bin_h + y1 +
                  (jnp.arange(ratio)[None, :] + 0.5) * bin_h / ratio)
            gx = (jnp.arange(pw)[:, None] * bin_w + x1 +
                  (jnp.arange(ratio)[None, :] + 0.5) * bin_w / ratio)
            yy = gy.reshape(-1)                       # [ph*ratio]
            xx = gx.reshape(-1)                       # [pw*ratio]
            feat = xa[img]
            vals = _bilinear(feat, yy[:, None], xx[None, :])  # [C,phr,pwr]
            C = feat.shape[0]
            vals = vals.reshape(C, ph, ratio, pw, ratio)
            return vals.mean(axis=(2, 4))

        return jax.vmap(one)(sb, img_of)

    return apply_op("roi_align", f, x, boxes,
                    Tensor(jnp.asarray(unwrap(boxes_num)).astype(jnp.int32)))


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """reference: vision/ops.py roi_pool:1610 — max pooling per bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    def f(xa, ba, bn):
        H, W = xa.shape[-2:]
        img_of = jnp.searchsorted(jnp.cumsum(bn), jnp.arange(ba.shape[0]),
                                  side="right")
        sb = jnp.round(ba * spatial_scale)

        def one(roi, img):
            # exact integer-cell membership per bin (matches the quantized
            # reference kernel): cell (h, w) belongs to bin
            # (floor((h-y1)/bin_h), floor((w-x1)/bin_w)) when inside the roi
            x1, y1, x2, y2 = roi
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            bin_h, bin_w = rh / ph, rw / pw
            hs = jnp.arange(H, dtype=jnp.float32)
            ws = jnp.arange(W, dtype=jnp.float32)
            bin_h_of = jnp.floor((hs - y1) / bin_h)
            bin_w_of = jnp.floor((ws - x1) / bin_w)
            in_h = (hs >= y1) & (hs <= y2)
            in_w = (ws >= x1) & (ws <= x2)
            mh = (bin_h_of[None, :] == jnp.arange(ph)[:, None]) & in_h
            mw = (bin_w_of[None, :] == jnp.arange(pw)[:, None]) & in_w
            mask = mh[:, None, :, None] & mw[None, :, None, :]  # [ph,pw,H,W]
            feat = xa[img]                                      # [C, H, W]
            vals = jnp.where(mask[None], feat[:, None, None], -jnp.inf)
            out = vals.max(axis=(-2, -1))
            return jnp.where(jnp.isfinite(out), out, 0.0)       # empty bins

        return jax.vmap(one)(sb, img_of)

    return apply_op("roi_pool", f, x, boxes,
                    Tensor(jnp.asarray(unwrap(boxes_num)).astype(jnp.int32)))
