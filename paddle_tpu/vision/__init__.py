"""paddle.vision — models/transforms/datasets (reference: python/paddle/vision).
Model zoo (ResNet/LeNet) lands with the conv-heavy benchmark config."""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .models import LeNet, ResNet, resnet18, resnet50  # noqa: F401


# ---- image backend (reference vision/image.py) -------------------------------
_image_backend = "pil"


def set_image_backend(backend):
    """reference vision/image.py set_image_backend: 'pil' or 'cv2'."""
    global _image_backend
    if backend not in ("pil", "cv2"):
        raise ValueError(f"backend must be 'pil' or 'cv2', got {backend!r}")
    _image_backend = backend


def get_image_backend():
    return _image_backend


def image_load(path, backend=None):
    """reference vision/image.py image_load."""
    be = backend or _image_backend
    if be == "cv2":
        try:
            import cv2
        except ImportError as e:
            raise ImportError("cv2 backend requested but OpenCV is not "
                              "installed; use the 'pil' backend") from e
        return cv2.imread(str(path))
    from PIL import Image
    return Image.open(path)
