"""paddle.vision — models/transforms/datasets (reference: python/paddle/vision).
Model zoo (ResNet/LeNet) lands with the conv-heavy benchmark config."""
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .models import LeNet, ResNet, resnet18, resnet50  # noqa: F401
